"""Benchmark entry: ResNet-50 ImageNet-shape training throughput + MFU on
the available accelerator (one TPU chip under the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

Never exits with a raw traceback: backend init is retried with backoff
(the chip may be transiently held), and any failure still emits a
machine-readable diagnostic JSON line.

Baseline for vs_baseline: the reference's published ResNet-50 recipe —
BigDL trains ResNet-50 at global batch 8192 on 2048 Xeon cores
(models/resnet/README.md:85-150); whitepaper-era Broadwell measurements
imply ~35 img/s per 32-core executor.  vs_baseline = our img/s on ONE
chip / 35 (chip-for-executor speedup).

MFU: model FLOPs per optimizer step (XLA cost analysis of the compiled
step when available, else the analytic ResNet-50 count 3x2x4.09 GFLOP
per image) / step time / chip peak bf16 FLOPs (device_kind lookup).
North star: >=45% MFU (BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _emit(obj):
    print(json.dumps(obj), flush=True)


def _emit_failure(reason: str):
    _emit({"metric": "resnet50_train_img_per_sec", "value": 0.0,
           "unit": "images/sec/chip", "vs_baseline": 0.0, "error": reason})


# Dense bf16 peak FLOP/s per chip by device_kind substring (public specs).
_PEAK_BF16 = [
    ("v6", 918e12), ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v5litepod", 197e12), ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
]


def _peak_flops(device_kind: str):
    kind = (device_kind or "").lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def _init_backend(attempts: int = 3, deadline_s: float = 150.0):
    """jax.devices() with retry/backoff under an overall deadline — one
    transient backend hiccup must not erase the round's perf evidence
    (round-1 failure mode), but a slow-failing init must not eat the
    whole driver budget either."""
    import jax
    t0 = time.time()
    delay = 5.0
    last = None
    for i in range(attempts):
        try:
            devs = jax.devices()
            return jax, devs[0]
        except Exception as e:  # backend UNAVAILABLE, chip held, ...
            last = e
            sys.stderr.write(
                f"[bench] backend init attempt {i + 1}/{attempts} failed: "
                f"{type(e).__name__}: {e}\n")
            if i + 1 == attempts or time.time() - t0 + delay > deadline_s:
                break
            try:
                import jax.extend.backend
                jax.extend.backend.clear_backends()
            except Exception:
                pass
            time.sleep(delay)
            delay *= 2
    raise RuntimeError(
        f"backend init failed after {time.time() - t0:.0f}s "
        f"(is another process holding the chip?): {last}") from last


def _start_watchdog(budget_s: float = 540.0):
    """If the bench hasn't finished within budget (e.g. backend init or
    compile blocked indefinitely), emit the diagnostic JSON line and
    hard-exit — the driver must always receive parseable output."""
    import threading

    def fire():
        _emit_failure(f"watchdog: bench exceeded {budget_s:.0f}s "
                      f"(blocked backend init or compile)")
        import os
        os._exit(2)

    t = threading.Timer(budget_s, fire)
    t.daemon = True
    t.start()
    return t


def main():
    watchdog = _start_watchdog()
    try:
        jax, dev = _init_backend()
    except Exception as e:
        _emit_failure(f"backend_init: {e}")
        watchdog.cancel()
        return
    try:
        _bench(jax, dev)
    except Exception as e:
        import traceback
        sys.stderr.write(traceback.format_exc())
        _emit_failure(f"{type(e).__name__}: {e}")
    finally:
        watchdog.cancel()


def _bench(jax, dev):
    import jax.numpy as jnp

    from bigdl_tpu.core.module import partition, combine, cast_floating
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim.methods import SGD
    from bigdl_tpu.utils import set_seed

    set_seed(0)
    on_tpu = dev.platform != "cpu"
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 64

    model = resnet50(class_num=1000)
    criterion = nn.CrossEntropyCriterion()
    method = SGD(0.1, momentum=0.9, dampening=0.0)

    params_tree, rest = partition(model)
    opt_state = method.init_state(params_tree)

    def step(params, rest, opt_state, x, y):
        def loss_fn(p):
            m = cast_floating(combine(p, rest), jnp.bfloat16)
            out = m.forward(x.astype(jnp.bfloat16)).astype(jnp.float32)
            return criterion(out, y), m

        (loss, m2), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, opt_state2 = method.update(grads, params, opt_state)
        _, rest2 = partition(m2)
        rest2 = cast_floating(rest2, jnp.float32)
        return params, rest2, opt_state2, loss

    jitted = jax.jit(step)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, size, size, 3)),
                    dtype=jnp.float32)
    y = jnp.asarray(rng.integers(1, 1001, size=(batch,)))

    # AOT compile ONCE; the same executable serves cost analysis and the
    # timed loop (a second trace/compile would double the startup cost).
    t_c = time.perf_counter()
    compiled = jitted.lower(params_tree, rest, opt_state, x, y).compile()
    sys.stderr.write(
        f"[bench] compiled in {time.perf_counter() - t_c:.1f}s\n")

    # FLOPs per step, preferring XLA's own cost analysis of the program
    # we actually execute (fwd+bwd+update); analytic ResNet-50 fallback.
    flops_per_step = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", -1.0)) if cost else -1.0
        if f > 0:
            flops_per_step = f
    except Exception:
        pass
    if flops_per_step is None:
        # 4.089e9 MACs fwd per 224px image; x2 FLOP/MAC; train ~ 3x fwd
        flops_per_step = 3 * 2 * 4.089e9 * batch * (size / 224.0) ** 2

    # warmup
    params_tree, rest, opt_state, loss = compiled(
        params_tree, rest, opt_state, x, y)
    jax.block_until_ready(loss)

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        params_tree, rest, opt_state, loss = compiled(
            params_tree, rest, opt_state, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    step_time = dt / iters
    img_per_sec = batch / step_time
    peak = _peak_flops(getattr(dev, "device_kind", ""))
    mfu = (flops_per_step / step_time / peak) if (peak and on_tpu) else None
    out = {
        "metric": f"resnet50_train_img_per_sec_bs{batch}_{size}px_"
                  f"{dev.platform}",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        # reference: ~35 img/s per 32-core executor (module docstring)
        "vs_baseline": round(img_per_sec / 35.0, 2),
        "step_time_ms": round(step_time * 1e3, 2),
        "flops_per_step": flops_per_step,
        "device_kind": getattr(dev, "device_kind", dev.platform),
    }
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
    _emit(out)


if __name__ == "__main__":
    main()
