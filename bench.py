"""Benchmark entry: ResNet-50 ImageNet-shape training throughput + MFU on
the available accelerator (one TPU chip under the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The headline number drives the FRAMEWORK loop (``Optimizer.optimize()``
with mesh + bf16 compute + async loss readback), not a hand-rolled
bypass; the raw jitted-step number is reported alongside so a gap
between the two reads as framework overhead to fix.

MFU is reported against two rooflines:
  * ``mfu_vs_spec``     — public peak bf16 FLOP/s for the device kind;
    flagged ``mfu_vs_spec_suspect`` when > 1 (a virtualized chip can
    out-run its nominal spec, which makes the spec denominator wrong).
  * ``mfu_vs_measured`` — an empirically calibrated roofline: a chained
    big-matmul microbench run on the same chip right before the model
    bench.  This is the honest utilization number.

Baseline for vs_baseline: the reference's published ResNet-50 recipe —
BigDL trains ResNet-50 at global batch 8192 on 2048 Xeon cores
(models/resnet/README.md:85-150); whitepaper-era Broadwell measurements
imply ~35 img/s per 32-core executor.  vs_baseline = our img/s on ONE
chip / 35 (chip-for-executor speedup).

Never exits with a raw traceback: backend init is retried with backoff,
and any failure still emits a machine-readable diagnostic JSON line.
"""

from __future__ import annotations

import json
import logging
import sys
import time

import numpy as np


def _emit(obj):
    print(json.dumps(obj), flush=True)


def _emit_failure(reason: str):
    _emit({"metric": "resnet50_train_img_per_sec", "value": 0.0,
           "unit": "images/sec/chip", "vs_baseline": 0.0, "error": reason})


# Dense bf16 peak FLOP/s per chip by device_kind substring (public specs).
_PEAK_BF16 = [
    ("v6", 918e12), ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v5litepod", 197e12), ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
]


def _peak_flops(device_kind: str):
    kind = (device_kind or "").lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def _init_backend(attempts: int = 3, deadline_s: float = 150.0):
    """jax.devices() with retry/backoff under an overall deadline — one
    transient backend hiccup must not erase the round's perf evidence,
    but a slow-failing init must not eat the whole driver budget either."""
    import jax
    t0 = time.time()
    delay = 5.0
    last = None
    for i in range(attempts):
        try:
            devs = jax.devices()
            return jax, devs[0]
        except Exception as e:  # backend UNAVAILABLE, chip held, ...
            last = e
            sys.stderr.write(
                f"[bench] backend init attempt {i + 1}/{attempts} failed: "
                f"{type(e).__name__}: {e}\n")
            if i + 1 == attempts or time.time() - t0 + delay > deadline_s:
                break
            try:
                import jax.extend.backend
                jax.extend.backend.clear_backends()
            except Exception:
                pass
            time.sleep(delay)
            delay *= 2
    raise RuntimeError(
        f"backend init failed after {time.time() - t0:.0f}s "
        f"(is another process holding the chip?): {last}") from last


def _start_watchdog(budget_s: float = 540.0):
    """If the bench hasn't finished within budget (e.g. backend init or
    compile blocked indefinitely), emit the diagnostic JSON line and
    hard-exit — the driver must always receive parseable output."""
    import threading

    def fire():
        _emit_failure(f"watchdog: bench exceeded {budget_s:.0f}s "
                      f"(blocked backend init or compile)")
        import os
        os._exit(2)

    t = threading.Timer(budget_s, fire)
    t.daemon = True
    t.start()
    return t


def main():
    watchdog = _start_watchdog()
    try:
        jax, dev = _init_backend()
    except Exception as e:
        _emit_failure(f"backend_init: {e}")
        watchdog.cancel()
        return
    try:
        _bench(jax, dev)
    except Exception as e:
        import traceback
        sys.stderr.write(traceback.format_exc())
        _emit_failure(f"{type(e).__name__}: {e}")
    finally:
        watchdog.cancel()


def _measure_peak(jax, on_tpu: bool) -> float:
    """Empirical bf16 matmul roofline of this chip: chained square
    matmuls (each output feeds the next, so XLA cannot elide any) timed
    after warmup.  Returns achieved FLOP/s.

    Timing forces completion with a scalar readback — on the tunneled
    bench backend ``block_until_ready`` returns before the work is done,
    which is how round 2 shipped a 204%-of-spec MFU."""
    import jax.numpy as jnp

    n = 8192 if on_tpu else 512
    chain_len = 8

    @jax.jit
    def chain(a, b):
        for _ in range(chain_len):
            a = jnp.matmul(a, b, preferred_element_type=jnp.bfloat16)
        return a

    a = jnp.full((n, n), 0.5, jnp.bfloat16)
    b = jnp.full((n, n), 1e-4, jnp.bfloat16)

    def run(reps):
        out = a
        for _ in range(reps):
            out = chain(out, b)
        return float(jnp.sum(out, dtype=jnp.float32))

    run(1)  # compile chain + the readback reduction
    reps = 16 if on_tpu else 2
    t0 = time.perf_counter()
    run(reps)
    dt = time.perf_counter() - t0
    flops = 2.0 * n * n * n * chain_len * reps
    peak = flops / dt
    sys.stderr.write(f"[bench] measured matmul roofline: "
                     f"{peak / 1e12:.1f} TFLOP/s bf16 ({n}^3 x{chain_len}, "
                     f"{dt:.2f}s)\n")
    return peak


class _TimedData:
    """Wraps a dataset with per-epoch iterator timestamps, so the bench
    can time steady-state epochs of the real Optimizer loop."""

    def __init__(self, inner):
        self.inner = inner
        self.epoch_starts = []

    def data(self, train=True):
        self.epoch_starts.append(time.perf_counter())
        return self.inner.data(train)

    def size(self) -> int:
        return self.inner.size()


def _bench(jax, dev):
    import jax.numpy as jnp

    from bigdl_tpu.core.module import partition, combine, cast_floating
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim.methods import SGD
    from bigdl_tpu.utils import set_seed

    logging.getLogger("bigdl_tpu.optim").setLevel(logging.WARNING)
    set_seed(0)
    on_tpu = dev.platform != "cpu"
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 64

    peak_measured = _measure_peak(jax, on_tpu)
    peak_spec = _peak_flops(getattr(dev, "device_kind", ""))

    model = resnet50(class_num=1000)
    criterion = nn.CrossEntropyCriterion()
    method = SGD(0.1, momentum=0.9, dampening=0.0)

    params_tree, rest = partition(model)
    opt_state = method.init_state(params_tree)

    def step(params, rest, opt_state, x, y):
        def loss_fn(p):
            m = cast_floating(combine(p, rest), jnp.bfloat16)
            out = m.forward(x.astype(jnp.bfloat16)).astype(jnp.float32)
            return criterion(out, y), m

        (loss, m2), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, opt_state2 = method.update(grads, params, opt_state)
        _, rest2 = partition(m2)
        rest2 = cast_floating(rest2, jnp.float32)
        return params, rest2, opt_state2, loss

    jitted = jax.jit(step, donate_argnums=(0, 1, 2))

    rng = np.random.default_rng(0)
    x_np = rng.normal(size=(batch, size, size, 3)).astype(np.float32)
    y_np = rng.integers(1, 1001, size=(batch,))
    x = jnp.asarray(x_np)
    y = jnp.asarray(y_np)

    # AOT compile ONCE; the same executable serves cost analysis and the
    # timed loop (a second trace/compile would double the startup cost).
    t_c = time.perf_counter()
    compiled = jitted.lower(params_tree, rest, opt_state, x, y).compile()
    sys.stderr.write(
        f"[bench] raw step compiled in {time.perf_counter() - t_c:.1f}s\n")

    # FLOPs per step, preferring XLA's own cost analysis of the program
    # we actually execute (fwd+bwd+update); analytic ResNet-50 fallback.
    flops_per_step = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", -1.0)) if cost else -1.0
        if f > 0:
            flops_per_step = f
    except Exception:
        pass
    if flops_per_step is None:
        # 4.089e9 MACs fwd per 224px image; x2 FLOP/MAC; train ~ 3x fwd
        flops_per_step = 3 * 2 * 4.089e9 * batch * (size / 224.0) ** 2

    # warmup (float() forces real completion; see _measure_peak)
    params_tree, rest, opt_state, loss = compiled(
        params_tree, rest, opt_state, x, y)
    float(loss)

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        params_tree, rest, opt_state, loss = compiled(
            params_tree, rest, opt_state, x, y)
    float(loss)
    dt = time.perf_counter() - t0
    raw_step_time = dt / iters
    raw_img_per_sec = batch / raw_step_time

    # ---- the framework loop: Optimizer.optimize() on a 1-chip mesh ------
    opt_step_time = opt_img_per_sec = None
    opt_error = None
    try:
        from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
        from bigdl_tpu.optim import Optimizer, Trigger

        iters_per_epoch = 20 if on_tpu else 3
        epochs = 4
        # The batches share one host buffer, so the HBM cache holds it
        # once; epochs after the first pay zero host->device transfer
        # (cache_on_device ≙ the reference's CachedDistriDataSet).
        data = _TimedData(
            DataSet.array([MiniBatch(x_np, y_np)
                           for _ in range(iters_per_epoch)], shuffle=False)
            .cache_on_device())
        model2 = resnet50(class_num=1000)
        opt = (Optimizer(model2, data, nn.CrossEntropyCriterion())
               .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
               .set_end_when(Trigger.max_epoch(epochs))
               .set_compute_dtype(jnp.bfloat16)
               .set_log_interval(iters_per_epoch))
        t_c = time.perf_counter()
        opt.optimize()
        sys.stderr.write(f"[bench] optimizer loop ({epochs} epochs) in "
                         f"{time.perf_counter() - t_c:.1f}s\n")
        # epoch 1 pays trace+compile; steady state = best later epoch
        starts = data.epoch_starts
        epoch_times = [b - a for a, b in zip(starts[1:], starts[2:])]
        opt_step_time = min(epoch_times) / iters_per_epoch
        opt_img_per_sec = batch / opt_step_time
    except Exception as e:
        import traceback
        sys.stderr.write(traceback.format_exc())
        opt_error = f"{type(e).__name__}: {e}"

    def mfu(per_step_flops, step_time, peak):
        if not (peak and on_tpu and step_time):
            return None
        return round(per_step_flops / step_time / peak, 4)

    headline = opt_img_per_sec if opt_img_per_sec else raw_img_per_sec
    out = {
        "metric": f"resnet50_train_img_per_sec_bs{batch}_{size}px_"
                  f"{dev.platform}",
        "value": round(headline, 2),
        "unit": "images/sec/chip",
        # reference: ~35 img/s per 32-core executor (module docstring)
        "vs_baseline": round(headline / 35.0, 2),
        "raw_step_img_per_sec": round(raw_img_per_sec, 2),
        "raw_step_time_ms": round(raw_step_time * 1e3, 2),
        "flops_per_step": flops_per_step,
        "peak_measured_flops": peak_measured,
        "device_kind": getattr(dev, "device_kind", dev.platform),
    }
    if opt_img_per_sec:
        out["optimizer_img_per_sec"] = round(opt_img_per_sec, 2)
        out["optimizer_step_time_ms"] = round(opt_step_time * 1e3, 2)
        overhead = 1.0 - opt_img_per_sec / raw_img_per_sec
        out["optimizer_overhead_pct"] = round(100.0 * overhead, 1)
    if opt_error:
        out["optimizer_error"] = opt_error
    m_spec = mfu(flops_per_step, opt_step_time or raw_step_time, peak_spec)
    m_meas = mfu(flops_per_step, opt_step_time or raw_step_time,
                 peak_measured)
    if m_spec is not None:
        out["mfu_vs_spec"] = m_spec
        if m_spec > 1.0:
            # >100% of nominal spec: the spec denominator is wrong for
            # this (virtualized) part — trust mfu_vs_measured instead
            out["mfu_vs_spec_suspect"] = True
    if m_meas is not None:
        out["mfu_vs_measured"] = m_meas
    _emit(out)


if __name__ == "__main__":
    main()
