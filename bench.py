"""Benchmark entry: ResNet-50 ImageNet-shape training throughput + MFU on
the available accelerator (one TPU chip under the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Phase-resilient design (round-4 rework).  Rounds 1-3 produced zero valid
perf evidence: r01 died in backend init, r02 shipped a physically
impossible number (async dispatch never forced to completion), r03 hung
inside a single monolithic 540 s watchdog and emitted ``value: 0.0``,
discarding everything measured before the hang.  This rewrite makes that
failure mode impossible:

* Every phase runs in a daemon worker thread with its OWN deadline; a
  hung XLA dispatch (the tunneled backend stalls sometimes) abandons
  that phase and moves on instead of wedging the run.
* A shared RESULT dict is updated the moment each sub-measurement lands;
  the global watchdog emits the BEST-SO-FAR partial result — never 0.0.
* Phase order puts the headline first: backend init -> model step
  (compile + timed loop) -> optimizer loop -> roofline.  A roofline
  stall (what killed r03) can no longer erase the step time.
* Timing forces real completion with a scalar readback (``float()``) —
  ``block_until_ready`` returned early on the tunneled backend, which is
  how r02 shipped a 204%-of-spec MFU.

The headline number drives the FRAMEWORK loop (``Optimizer.optimize()``
with mesh + bf16 compute + async loss readback), not a hand-rolled
bypass; the raw jitted-step number is reported alongside so a gap
between the two reads as framework overhead to fix.

MFU is reported against two rooflines:
  * ``mfu_vs_spec``     — public peak bf16 FLOP/s for the device kind;
    flagged ``mfu_vs_spec_suspect`` when > 1.
  * ``mfu_vs_measured`` — an empirically calibrated roofline: a chained
    big-matmul microbench run on the same chip (escalating sizes, each
    under its own deadline).

Baseline for vs_baseline: the reference's published ResNet-50 recipe —
BigDL trains ResNet-50 at global batch 8192 on 2048 Xeon cores
(reference: models/resnet/README.md:85-150); whitepaper-era Broadwell
measurements imply ~35 img/s per 32-core executor.  vs_baseline = our
img/s on ONE chip / 35 (chip-for-executor speedup).  Per-iteration
throughput telemetry matches optim/DistriOptimizer.scala:425-431.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Optional

import numpy as np

# ---------------------------------------------------------------------------
# Budget + emission plumbing
# ---------------------------------------------------------------------------

T_START = time.monotonic()
TOTAL_BUDGET_S = float(os.environ.get("BIGDL_TPU_BENCH_BUDGET_S", "500"))

RESULT = {
    "metric": "resnet50_train_img_per_sec",
    "value": 0.0,
    "unit": "images/sec/chip",
    "vs_baseline": 0.0,
    "phases": {},
}
_LOCK = threading.Lock()
_EMITTED = threading.Event()


def _elapsed() -> float:
    return time.monotonic() - T_START


def _remaining() -> float:
    return TOTAL_BUDGET_S - _elapsed()


def _log(msg: str) -> None:
    sys.stderr.write(f"[bench +{_elapsed():6.1f}s] {msg}\n")
    sys.stderr.flush()


def _update(**kv) -> None:
    """Record measurements the moment they land, so a later hang cannot
    erase them; keeps the headline `value` in sync with the best number
    measured so far (optimizer loop preferred over raw step)."""
    with _LOCK:
        RESULT.update(kv)
        head = RESULT.get("optimizer_img_per_sec") or RESULT.get(
            "raw_step_img_per_sec")
        if head:
            RESULT["value"] = round(head, 2)
            RESULT["vs_baseline"] = round(head / 35.0, 2)
        flops = RESULT.get("flops_per_step")
        step = RESULT.get("optimizer_step_time_ms") or RESULT.get(
            "raw_step_time_ms")
        if flops and step:
            sec = step / 1e3
            peak_m = RESULT.get("peak_measured_flops")
            peak_s = RESULT.get("peak_spec_flops")
            if peak_m:
                RESULT["mfu_vs_measured"] = round(flops / sec / peak_m, 4)
            if peak_s:
                m = round(flops / sec / peak_s, 4)
                RESULT["mfu_vs_spec"] = m
                if m > 1.0:
                    RESULT["mfu_vs_spec_suspect"] = True


def _emit_final(tag: str) -> None:
    """Print the single JSON result line exactly once (watchdog and the
    normal path race; atomic test-and-set under the lock)."""
    with _LOCK:
        if _EMITTED.is_set():
            return
        _EMITTED.set()
        if tag != "done":
            RESULT["partial"] = tag
        line = json.dumps(RESULT)
    print(line, flush=True)


# ---------------------------------------------------------------------------
# Phase runner: per-phase deadline in a daemon thread
# ---------------------------------------------------------------------------

def run_phase(name: str, fn, deadline_s: float):
    """Run fn() on a daemon thread, waiting at most deadline_s.  Returns
    the value or None.  A timed-out phase is abandoned (the thread may
    stay wedged in a native call; daemon threads don't block exit)."""
    deadline_s = min(deadline_s, max(_remaining() - 15.0, 5.0))
    _log(f"phase {name}: start (deadline {deadline_s:.0f}s)")
    box = {}

    def target():
        try:
            box["value"] = fn()
        except Exception:
            box["error"] = traceback.format_exc()

    t = threading.Thread(target=target, daemon=True, name=f"bench-{name}")
    t0 = time.monotonic()
    t.start()
    t.join(deadline_s)
    dt = time.monotonic() - t0
    if t.is_alive():
        _log(f"phase {name}: TIMED OUT after {dt:.1f}s (abandoned)")
        with _LOCK:
            RESULT["phases"][name] = f"timeout {dt:.0f}s"
        return None
    if "error" in box:
        sys.stderr.write(box["error"])
        with _LOCK:
            RESULT["phases"][name] = "error: " + box["error"].strip(
            ).splitlines()[-1][:200]
        return None
    with _LOCK:
        RESULT["phases"][name] = f"ok {dt:.1f}s"
    _log(f"phase {name}: done in {dt:.1f}s")
    return box.get("value")


def _start_watchdog():
    def fire():
        _log(f"watchdog: total budget {TOTAL_BUDGET_S:.0f}s exceeded; "
             f"emitting best-so-far partial result")
        _emit_final("watchdog")
        os._exit(3)

    t = threading.Timer(max(TOTAL_BUDGET_S - _elapsed(), 1.0), fire)
    t.daemon = True
    t.start()
    return t


# ---------------------------------------------------------------------------
# Phases
# ---------------------------------------------------------------------------

def _peak_flops(device_kind: str):
    """Public dense bf16 peak FLOP/s — the table lives in
    telemetry.perf now (one declaration for bench, chip-session, and
    the attribution layer)."""
    from bigdl_tpu.telemetry.perf import device_peak_flops
    return device_peak_flops(device_kind)


def _probe_backend_subprocess(wait_s: float) -> Optional[bool]:
    """Probe the tunneled chip from a THROWAWAY subprocess.  Round 4's
    zero: jax.devices() in the bench process hung for the full 260 s
    phase deadline and the wedged thread poisoned the rest of the run.
    A subprocess probe keeps this process clean — only after a probe
    comes back healthy does the main process touch the backend (by then
    the tunnel is warm and init is fast).

    CRITICAL: a child that outlives ``wait_s`` is ABANDONED, never
    killed — killing a client mid-init is precisely what wedges the
    tunnel for hours (observed r04).  An abandoned child that finally
    connects just prints and exits; it occupies no chip state
    in the meantime because its init never completed.

    Returns True (healthy), False (child exited unhealthy — safe to
    retry), or None (still hanging — wedged; do NOT start another
    client)."""
    import subprocess
    # the child tolerates a closed read end: after the abandon path
    # below closes our pipe fd, its final print must not turn the clean
    # "connects, prints, exits" teardown into a BrokenPipeError crash
    code = ("import jax, os, sys\n"
            "d = jax.devices()\n"
            "try:\n"
            "    print(d[0].platform, flush=True)\n"
            "except BrokenPipeError:\n"
            "    os.dup2(os.open(os.devnull, os.O_WRONLY),\n"
            "            sys.stdout.fileno())\n")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        rc = proc.poll()
        if rc is not None:
            out = (proc.stdout.read() or "").strip()
            _log(f"backend probe: rc={rc} out={out!r}")
            return rc == 0 and bool(out)
        time.sleep(1.0)
    # one final poll: the 1s poll cadence leaves a window where the
    # child EXITED just after the deadline — that child answered
    # (healthy or not), so the tunnel is not wedged; classify it like
    # any other exit (False → retryable) instead of abandoning (None →
    # terminal, no more clients this run)
    rc = proc.poll()
    if rc is not None:
        out = (proc.stdout.read() or "").strip()
        _log(f"backend probe: exited just past the {wait_s:.0f}s "
             f"deadline (rc={rc} out={out!r}) — slow, not wedged; "
             "retry is safe")
        return rc == 0 and bool(out)
    _log(f"backend probe: still hanging after {wait_s:.0f}s — "
         f"abandoning the child UNKILLED (pid {proc.pid}; a kill "
         "mid-init is what wedges the tunnel). If that child turns out "
         "to exit on its own after this run, the tunnel was merely "
         "slow, not wedged — retrying on the NEXT run is safe")
    try:
        # fd hygiene only: the abandoned Popen (and its pipe fd) would
        # otherwise leak for the life of the bench process.  The child
        # handles the resulting BrokenPipeError on its single print (see
        # the probe code above), so its teardown stays clean.
        proc.stdout.close()
    except OSError:
        pass
    return None


def phase_backend():
    """Backend init with wedge recovery: a subprocess probe (so a hung
    init cannot wedge THIS process), one crash-retry, then the real
    in-process init.  A HANGING probe is terminal for this run — more
    clients would pile onto a wedged tunnel — but a probe that exits
    unhealthy (crash, transient error) gets one retry."""
    if os.environ.get("BIGDL_TPU_BENCH_FORCE_PROBE_FAIL"):
        # CI seam (scripts/perf_smoke.sh): simulate the wedged tunnel
        # so the carried-forward publication path is exercised on CPU
        raise RuntimeError(
            "backend probe failure forced "
            "(BIGDL_TPU_BENCH_FORCE_PROBE_FAIL=1)")
    import jax
    if os.environ.get("BIGDL_TPU_BENCH_FORCE_CPU"):
        # the axon sitecustomize overrides JAX_PLATFORMS; win the
        # override war the same way tests/conftest.py does
        jax.config.update("jax_platforms", "cpu")
    else:
        # wait sized to keep the HALF-wedged recovery window (r04 note:
        # a tunnel that comes up in 3-4 minutes must not be forfeited;
        # compile + the raw-step headline still fit the remainder), with
        # a floor that tolerates a routine ~60s cold init even when the
        # budget is already thin
        wait = max(min(260.0, _remaining() - 140.0), 75.0)
        for attempt in (0, 1):
            ok = _probe_backend_subprocess(wait)
            if ok:
                break
            if ok is None:
                raise RuntimeError(
                    "tunneled backend is wedged (probe hung; child "
                    "abandoned unkilled); not starting more clients")
            if attempt == 0:
                _log("probe child exited unhealthy; resting 20s then "
                     "retrying once")
                time.sleep(20.0)
                wait = max(min(90.0, _remaining() - 150.0), 30.0)
        else:
            raise RuntimeError(
                "tunneled backend unreachable (probe child kept "
                "exiting unhealthy)")
    last = None
    for i in range(3):
        try:
            dev = jax.devices()[0]
            _log(f"backend up: {dev.platform} / "
                 f"{getattr(dev, 'device_kind', '?')}")
            _update(device_kind=getattr(dev, "device_kind", dev.platform),
                    platform=dev.platform)
            peak = _peak_flops(getattr(dev, "device_kind", ""))
            if peak:
                _update(peak_spec_flops=peak)
            return dev
        except Exception as e:
            last = e
            _log(f"backend init attempt {i + 1}/3 failed: "
                 f"{type(e).__name__}: {e}")
            try:
                import jax.extend.backend
                jax.extend.backend.clear_backends()
            except Exception:
                pass
            time.sleep(5.0 * (i + 1))
    raise RuntimeError(f"backend init failed: {last}") from last


def _build_step(on_tpu: bool, batch: int, size: int, fused: bool = False):
    """Build the jitted fwd+bwd+update for ResNet-50 and AOT-compile it."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.core.module import partition, combine, cast_floating
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim.methods import SGD
    from bigdl_tpu.utils import set_seed

    logging.getLogger("bigdl_tpu.optim").setLevel(logging.WARNING)
    set_seed(0)

    model = resnet50(class_num=1000, fused=fused)
    criterion = nn.CrossEntropyCriterion()
    method = SGD(0.1, momentum=0.9, dampening=0.0)
    params_tree, rest = partition(model)
    opt_state = method.init_state(params_tree)

    def step(params, rest, opt_state, x, y):
        def loss_fn(p):
            m = cast_floating(combine(p, rest), jnp.bfloat16)
            out = m.forward(x.astype(jnp.bfloat16)).astype(jnp.float32)
            return criterion(out, y), m

        (loss, m2), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, opt_state2 = method.update(grads, params, opt_state)
        _, rest2 = partition(m2)
        rest2 = cast_floating(rest2, jnp.float32)
        return params, rest2, opt_state2, loss

    jitted = jax.jit(step, donate_argnums=(0, 1, 2))

    rng = np.random.default_rng(0)
    x_np = rng.normal(size=(batch, size, size, 3)).astype(np.float32)
    y_np = rng.integers(1, 1001, size=(batch,))
    x = jnp.asarray(x_np)
    y = jnp.asarray(y_np)

    t_c = time.monotonic()
    compiled = jitted.lower(params_tree, rest, opt_state, x, y).compile()
    pfx = "fused_" if fused else ""
    _update(**{pfx + "compile_s": round(time.monotonic() - t_c, 1)})
    _log(f"{'fused ' if fused else ''}raw step compiled in "
         f"{time.monotonic() - t_c:.1f}s")

    # FLOPs per step, preferring XLA's own cost analysis of the program
    # we actually execute (fwd+bwd+update); analytic ResNet-50 fallback.
    from bigdl_tpu.utils.xla_cost import compiled_bytes, compiled_flops
    flops_per_step = compiled_flops(compiled)
    if flops_per_step is None:
        # 4.089e9 MACs fwd per 224px image; x2 FLOP/MAC; train ~ 3x fwd
        flops_per_step = 3 * 2 * 4.089e9 * batch * (size / 224.0) ** 2
    _update(**{pfx + "flops_per_step": flops_per_step})
    # XLA's own HBM traffic estimate: the fused-kernel tranche exists to
    # cut bytes/step, so record the compiler's number for both variants
    # (custom-call kernels self-report via pallas cost estimates; the
    # comparison is still apples-to-apples on the XLA-visible traffic)
    by = compiled_bytes(compiled)
    if by:
        _update(**{pfx + "bytes_per_step": by})
    # inter-chip payload of the compiled step (the HLO's collective
    # outputs; 0.0 on a single-device program) — the comm budget the
    # mesh-observability layer cross-checks and the comm-bound roofline
    # verdict consumes
    from bigdl_tpu.utils.xla_cost import collective_hlo_bytes
    comm = collective_hlo_bytes(compiled)
    if comm is not None:
        _update(**{pfx + "comm_bytes_per_step": comm["total"]})
    return compiled, (params_tree, rest, opt_state, x, y), (x_np, y_np)


def phase_raw_step(on_tpu: bool, batch: int, size: int):
    compiled, state, host_batch = _build_step(on_tpu, batch, size)
    params_tree, rest, opt_state, x, y = state

    # warmup (float() forces real completion on the tunneled backend)
    params_tree, rest, opt_state, loss = compiled(
        params_tree, rest, opt_state, x, y)
    _update(raw_warmup_loss=round(float(loss), 4))
    _log(f"warmup step done, loss={float(loss):.3f}")

    # Timed loops in escalating rep counts: land a coarse number fast,
    # refine while budget remains.
    for iters in ((5, 20) if on_tpu else (2, 3)):
        t0 = time.perf_counter()
        for _ in range(iters):
            params_tree, rest, opt_state, loss = compiled(
                params_tree, rest, opt_state, x, y)
        float(loss)
        dt = time.perf_counter() - t0
        _update(raw_step_time_ms=round(dt / iters * 1e3, 2),
                raw_step_img_per_sec=round(batch / (dt / iters), 2))
        _log(f"raw step: {dt / iters * 1e3:.2f} ms/step over {iters} iters "
             f"({batch / (dt / iters):.1f} img/s)")
    return host_batch


def phase_fused_step(on_tpu: bool, batch: int, size: int):
    """The round-5 kernel tranche: ResNet-50 with the fused conv+BN+ReLU
    Pallas bottleneck path (ops/conv_bn_kernels.py).  Measured head to
    head against the XLA step from phase_raw_step; the winner carries
    the optimizer-loop headline.  Also records XLA's bytes-accessed for
    both programs — the tranche's purpose is structurally fewer bytes on
    an HBM-bound step (docs/performance.md)."""
    compiled, state, _ = _build_step(on_tpu, batch, size, fused=True)
    params_tree, rest, opt_state, x, y = state
    params_tree, rest, opt_state, loss = compiled(
        params_tree, rest, opt_state, x, y)
    fused_loss = float(loss)
    _log(f"fused warmup step done, loss={fused_loss:.3f}")
    # numerics cross-check: same seed + same batch, so the first-step
    # loss must match the XLA variant to bf16 tolerance — the kernels
    # are interpret-tested; a compiled-mode divergence (Mosaic bug, a
    # layout assumption) must never promote a broken-but-fast variant
    raw_loss = RESULT.get("raw_warmup_loss")
    suspect = (raw_loss is not None
               and abs(fused_loss - raw_loss)
               > 0.05 * max(abs(raw_loss), 1.0))
    if suspect:
        _update(fused_numerics_suspect=True,
                fused_warmup_loss=round(fused_loss, 4))
        _log(f"fused warmup loss {fused_loss:.4f} diverges from raw "
             f"{raw_loss:.4f}; fused will NOT be promoted")
    for iters in ((5, 20) if on_tpu else (2,)):
        t0 = time.perf_counter()
        for _ in range(iters):
            params_tree, rest, opt_state, loss = compiled(
                params_tree, rest, opt_state, x, y)
        float(loss)
        dt = time.perf_counter() - t0
        _update(fused_step_time_ms=round(dt / iters * 1e3, 2),
                fused_step_img_per_sec=round(batch / (dt / iters), 2))
        _log(f"fused step: {dt / iters * 1e3:.2f} ms/step over {iters} "
             f"iters ({batch / (dt / iters):.1f} img/s)")
    raw_ms = RESULT.get("raw_step_time_ms")
    fused_ms = RESULT.get("fused_step_time_ms")
    if raw_ms and fused_ms:
        win = fused_ms < raw_ms * 0.995 and not suspect
        _update(fused_wins=bool(win),
                fused_speedup_vs_xla=round(raw_ms / fused_ms, 4))
        b0, b1 = RESULT.get("bytes_per_step"), RESULT.get(
            "fused_bytes_per_step")
        if b0 and b1:
            _update(fused_bytes_reduction_pct=round(
                100.0 * (1.0 - b1 / b0), 2))


def phase_optimizer_loop(on_tpu: bool, batch: int, size: int, host_batch):
    """The framework loop: Optimizer.optimize() on a 1-chip mesh.  This
    is the headline path (matches the reference's Throughput telemetry,
    optim/DistriOptimizer.scala:425-431)."""
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.optim.methods import SGD

    x_np, y_np = host_batch
    # unified telemetry rides along: the step-phase histograms
    # (data-wait vs device step) land in a JSON snapshot next to the
    # BENCH artifact, so a future perf round can attribute a regression
    # without re-running a TPU profile
    try:
        from bigdl_tpu import telemetry
        telemetry.enable()
    except Exception:
        telemetry = None
    iters_per_epoch = 10 if on_tpu else 3
    # 10 epochs -> 9 steady windows on the chip (marginal cost <1s per
    # extra window): the aggregate-span estimator gets enough windows
    # that any residual one-time cost is visible as a leading outlier
    # rather than dominating the mean, and the windowed number — the
    # headline — carries real averaging depth
    epochs = 10 if on_tpu else 4
    # The batches share one host buffer, so the HBM cache holds it once;
    # epochs after the first pay zero host->device transfer
    # (cache_on_device ≙ the reference's CachedDistriDataSet), and the
    # dispatch windows are staged once and reused across epochs.
    data = (DataSet.array([MiniBatch(x_np, y_np)
                           for _ in range(iters_per_epoch)], shuffle=False)
            .cache_on_device())
    use_fused = bool(RESULT.get("fused_wins"))
    if use_fused:
        _update(optimizer_loop_variant="fused")
    model2 = resnet50(class_num=1000, fused=use_fused)
    # gradient-sync mode for this round: flat (default) or hierarchical
    # with an optional wire codec (BIGDL_TPU_BENCH_SYNC=hierarchical,
    # BIGDL_TPU_BENCH_WIRE_DTYPE=bf16|int8).  Recorded either way —
    # comm_wire_dtype + the compression ratio land in the attribution
    # table and BENCH_telemetry.json so a round artifact always states
    # which sync mode produced its number.
    sync_mode = os.environ.get("BIGDL_TPU_BENCH_SYNC", "flat")
    if sync_mode not in ("flat", "hierarchical"):
        # a typo must not silently run flat while the artifact records
        # the typo string as the sync mode that produced the number
        _log(f"BIGDL_TPU_BENCH_SYNC={sync_mode!r} unknown (expected "
             f"'flat' or 'hierarchical'); using flat sync")
        sync_mode = "flat"
    wire = os.environ.get("BIGDL_TPU_BENCH_WIRE_DTYPE") or None
    if wire is not None:
        try:
            from bigdl_tpu.parallel.compression import get_codec
            if get_codec(wire) is None:
                # uncompressed spellings ("fp32"/"none") are a valid
                # explicit no-op under EITHER sync mode — normalize
                # silently, don't warn below as if compression were
                # requested and dropped
                wire = None
        except ValueError as e:
            # same soft-fail as the SYNC typo above: a bad wire dtype
            # must not abort the whole bench round
            _log(f"BIGDL_TPU_BENCH_WIRE_DTYPE rejected ({e}); syncing "
                 f"uncompressed")
            wire = None
    if sync_mode != "hierarchical" and wire is not None:
        # a flat-sync run has no compressed wire — recording the
        # requested dtype anyway would produce a self-contradictory
        # artifact (bf16 label on fp32 bytes)
        _log(f"BIGDL_TPU_BENCH_WIRE_DTYPE={wire} ignored: sync mode is "
             f"{sync_mode!r} (set BIGDL_TPU_BENCH_SYNC=hierarchical)")
        wire = None
    opt = (Optimizer(model2, data, nn.CrossEntropyCriterion())
           .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
           .set_end_when(Trigger.max_epoch(epochs))
           .set_compute_dtype(jnp.bfloat16)
           .set_log_interval(iters_per_epoch)
           # k steps per compiled dispatch: hides the tunnel's per-call
           # launch latency (≙ the reference's 1-task-per-node fix for
           # Spark scheduling overhead, whitepaper fig 8).  XLA:CPU runs
           # scan bodies slower than unrolled steps, so windowing is
           # only a win on the accelerator
           .set_iterations_per_dispatch(iters_per_epoch if on_tpu else 1))
    # one mesh build shared by plan resolution and the byte estimate
    # (optimize() builds its own): make_mesh re-emits its truncation
    # warning on every call, and the operator should read it once.
    # Non-fatal: optimize() raises the same build error fatally below
    try:
        bench_mesh = opt.mesh_config.build()
    except Exception:
        bench_mesh = None
    if sync_mode == "hierarchical":
        opt.set_gradient_sync(hierarchical=True, wire_dtype=wire)
        # record what the run RESOLVES to, not what was requested:
        # on a mesh without a dcn axis the wire codec is dropped,
        # and without batch parallelism the sync degrades to the
        # flat step — the artifact must describe the bytes it
        # actually produced
        try:
            plan = opt._grad_sync_plan(bench_mesh)
            if plan is None:
                sync_mode, wire = "flat", None
            else:
                wire = plan["wire_dtype"]
        except Exception:
            # optimize() below raises the same error fatally; stamp
            # the requested mode so even a crashing round's partial
            # artifact names its sync config
            pass
    # stamped before (and independent of) the byte estimate: the
    # artifact must state which sync mode produced its number even
    # when the estimator fails
    _update(comm_sync_mode=sync_mode, comm_wire_dtype=(wire or "fp32"))
    try:
        from bigdl_tpu.parallel.sharding import grad_allreduce_bytes
        if bench_mesh is None:
            # the shared build above already failed; optimize() below
            # raises the same error fatally — nothing to estimate
            raise RuntimeError("mesh build failed; skipping estimate")
        est = grad_allreduce_bytes(
            model2, bench_mesh,
            hierarchical=(sync_mode == "hierarchical"), wire_dtype=wire)
        _update(comm_compression_ratio=round(
                    float(est.get("compression_ratio", 1.0)), 4),
                grad_sync_bytes_per_step=est["bytes_per_step"])
        if est.get("dcn_bytes_per_step"):
            _update(dcn_bytes_per_step=est["dcn_bytes_per_step"])
    except Exception:
        _log("grad-sync byte estimate failed (non-fatal):\n"
             + traceback.format_exc())
    t_c = time.monotonic()
    opt.optimize()
    _log(f"optimizer loop ({epochs} epochs) in {time.monotonic() - t_c:.1f}s")
    # Completion-to-completion window timings from the loss-drain worker
    # (loop dispatches are fully async — wall-clock epoch gaps would
    # measure dispatch rate, the r02 lie).  Window 1 bears the compile;
    # steady state = the AGGREGATE span over the later windows (a
    # min() over per-window rates reads impossibly fast whenever the
    # drain lags one window and the next completions bunch together).
    steady = opt.window_timings[1:]
    if steady:
        step_t = sum(dt for _, dt, _ in steady) / sum(
            n for n, _, _ in steady)
        upd = dict(optimizer_step_time_ms=round(step_t * 1e3, 2),
                   optimizer_img_per_sec=round(batch / step_t, 2))
        raw = RESULT.get("raw_step_img_per_sec")
        if raw:
            upd["optimizer_overhead_pct"] = round(
                100.0 * (1.0 - (batch / step_t) / raw), 1)
        _update(**upd)
    # step-time attribution: phases + residual summing to the measured
    # wall step (telemetry.perf); recomputed after the roofline phase
    # so mfu_vs_measured joins the table
    attribution = None
    try:
        if opt.compiled_flops_per_iteration:
            _update(optimizer_flops_per_step=(
                opt.compiled_flops_per_iteration))
        _OPT_WINDOW_RECORDS[:] = list(opt.window_records)
        attribution = _build_attribution()
        if attribution:
            _update(attribution=attribution)
            ph = attribution["phases_s"]
            _log("attribution (s/step): "
                 + " ".join(f"{k}={v:.6f}" for k, v in ph.items())
                 + f" residual={attribution['residual_s']:.6f}"
                 + f" wall={attribution['wall_step_s']:.6f}"
                 + f" dominant={attribution['dominant_phase']}")
    except Exception:
        _log("perf attribution failed (non-fatal):\n"
             + traceback.format_exc())
    if telemetry is not None:
        try:
            from bigdl_tpu.telemetry.export import json_snapshot
            from bigdl_tpu.telemetry.runtime import sample_runtime
            sample_runtime()
            snap_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_telemetry.json")
            snap = json_snapshot()
            if attribution:
                # the attribution table rides in the artifact so a
                # future perf round reads where the time went without
                # re-running a TPU profile
                snap["perf_attribution"] = attribution
            with open(snap_path, "w", encoding="utf-8") as f:
                json.dump(snap, f, default=str)
            _update(telemetry_snapshot=os.path.basename(snap_path))
            _log(f"telemetry snapshot written to {snap_path}")
            # flight-recorder summary: the snapshot embeds the event
            # ring, so a bench run's retries/faults/commits are
            # attributable after the fact
            ev = snap.get("events", {})
            _log(f"flight recorder: {ev.get('buffered', 0)} events "
                 f"{ev.get('by_kind', {})} ({ev.get('dropped', 0)} "
                 f"dropped)")
        except Exception:
            _log("telemetry snapshot failed (non-fatal):\n"
                 + traceback.format_exc())


def phase_transformer(on_tpu: bool):
    """Secondary metric: decoder-only transformer LM training through
    the same Optimizer loop (L6 H512 T2048 b8, bf16, flash attention).
    The reference trains its Transformer stack too (nn/Transformer.
    scala:749); long-context throughput is where the Pallas flash
    kernels earn their keep."""
    from bigdl_tpu.examples.perf import main as perf_main

    seq, batch = (2048, 8) if on_tpu else (128, 2)
    # emit=False: bench's stdout contract is exactly ONE result line
    # (and a process-global redirect from this abandonable worker
    # thread could leave stdout hijacked after a phase timeout)
    out = perf_main(["--model", "transformer-lm", "--seq-len",
                     str(seq), "-b", str(batch), "--hidden-size",
                     "512", "--num-layers", "6", "--num-heads", "8",
                     "--vocab-size", "32000", "--bf16",
                     "--iterations", "10", "--epochs", "4"],
                    emit=False)
    if out.get("windows_timed"):
        step_ms = out["ms_per_iteration"]
        upd = dict(transformer_lm_ms_per_step=step_ms,
                   transformer_lm_tokens_per_sec=round(
                       batch * seq / (step_ms / 1e3), 1),
                   transformer_lm_config=f"L6-H512-T{seq}-b{batch}-bf16")
        # One defensible MFU number: the roofline phase co-measured the
        # chip's attainable matmul peak minutes before this phase, in
        # THIS run — not a same-day figure from a different session
        # (the virtualized part's throughput swings 78-157 TF/s between
        # sessions; docs/performance.md "Measuring honestly")
        tf = out.get("model_tflops_per_sec")
        peak = RESULT.get("peak_measured_flops")
        if tf and peak:
            upd["transformer_lm_tflops_per_sec"] = tf
            upd["transformer_lm_mfu_vs_measured"] = round(
                tf * 1e12 / peak, 4)
        _update(**upd)


def phase_int8(on_tpu: bool):
    """int8-vs-fp32 inference latency ratio on ResNet-50 shapes — the
    missing TPU datapoint for the reference's 'up to 2x' int8 claim
    (reference docs/docs/whitepaper.md int8 section; fidelity is already
    test-locked, tests/test_quantized.py)."""
    from bigdl_tpu.examples.perf import main as perf_main

    batch = 32 if on_tpu else 4
    size = 224 if on_tpu else 64
    out = perf_main(["--model", "resnet50", "-b", str(batch),
                     "--image-size", str(size), "--int8-infer"],
                    emit=False)
    if out.get("int8_speedup"):
        base = out.get("baseline_dtype", "fp32")
        _update(int8_speedup_vs_fp32=out["int8_speedup"],
                int8_infer_ms=out.get("int8_ms"),
                fp32_infer_ms=out.get(f"{base}_ms"),
                int8_config=f"resnet50-b{batch}-{size}px")


def phase_generate_serving(on_tpu: bool):
    """Continuous-batching decode throughput (serving.generation): the
    ISSUE-10 acceptance workload — mixed-length prompts through the
    fixed-shape KV slot pool vs the sequential ``generate()`` baseline —
    plus the ISSUE-13 prefill-wall probes: a shared-system-prompt
    workload measuring the prefix KV cache's TTFT win, and a mixed
    long/short arrival cadence probe measuring how chunked prefill
    bounds the inter-token tail (both run against a larger
    prefill-dominant model config).  Fully measurable on the CPU
    backend (unlike the MFU campaign), and recorded as its own
    versioned RoundArtifact so the serving perf trajectory is durable
    evidence like the training one."""
    from bigdl_tpu.models import transformer_lm
    from bigdl_tpu.serving.generation import (
        run_cadence_probe, run_mixed_workload,
        run_shared_prefix_workload,
    )
    from bigdl_tpu.utils import set_seed

    set_seed(7)
    if on_tpu:
        model = transformer_lm(vocab_size=32000, hidden_size=512,
                               num_layers=6, num_heads=8,
                               filter_size=1024, max_len=512)
        n_req, slots, seq_sample = 32, 16, 8
    else:
        model = transformer_lm(vocab_size=128, hidden_size=64,
                               num_layers=2, num_heads=4,
                               filter_size=128, max_len=256)
        n_req, slots, seq_sample = 32, 8, 6
    rng = np.random.default_rng(10)
    prompts = [rng.integers(1, 129, rng.integers(8, 65)).astype(np.int32)
               for _ in range(n_req)]
    max_news = [int(rng.integers(16, 129)) for _ in range(n_req)]
    # the UNSHARED workload runs with the defaults (prefix cache off):
    # the no-regression bar vs GENSERVE_r01 is judged on this number
    out = run_mixed_workload(model.eval_mode(), prompts, max_news,
                             slots=slots, sequential_sample=seq_sample)

    # prefill-wall probes: a model where prefill compute dominates a
    # decode step (the regime the prefix cache and chunk budget exist
    # for — at tiny-model scale prefill is all dispatch overhead and
    # the probes measure nothing)
    set_seed(7)
    probe_model = transformer_lm(
        vocab_size=32000 if on_tpu else 512, hidden_size=256,
        num_layers=4, num_heads=8, filter_size=512,
        max_len=512).eval_mode()
    try:
        shared = run_shared_prefix_workload(
            probe_model, n_requests=32, prefix_len=448, tail=(8, 49),
            max_new=8, slots=8, prefix_granularity=64, prefill_chunk=64)
        out["shared_prefix"] = shared
    except Exception:
        _log("shared-prefix probe failed (non-fatal):\n"
             + traceback.format_exc())
    try:
        out["cadence"] = {
            "bounded": run_cadence_probe(probe_model, bounded=True),
            "unbounded": run_cadence_probe(probe_model, bounded=False),
        }
    except Exception:
        _log("cadence probe failed (non-fatal):\n"
             + traceback.format_exc())
    _update(gen_serving_tokens_per_sec=out["continuous_tokens_per_sec"],
            gen_serving_speedup_vs_sequential=out.get(
                "speedup_vs_sequential"),
            gen_serving_greedy_equal_checked=out.get(
                "greedy_equal_checked"),
            gen_serving_greedy_checked_requests=out.get(
                "greedy_checked_requests"),
            gen_serving_slot_occupancy=out["slot_occupancy_mean"],
            gen_serving_prefix_ttft_p50_speedup=out.get(
                "shared_prefix", {}).get("ttft_p50_speedup"),
            gen_serving_cadence_p99_over_steady=out.get(
                "cadence", {}).get("bounded", {}).get(
                    "p99_over_steady_p50"),
            gen_serving_config=f"slots{slots}-req{n_req}-prompts8to64-"
                               f"new16to128")
    # durable evidence: its own artifact series (GENSERVE_r<N>.json),
    # same envelope as the training rounds; latest_confirmed() keys on
    # the BENCH_* pattern so this series never masquerades as one
    try:
        from bigdl_tpu.telemetry import perf
        here = os.path.dirname(os.path.abspath(__file__))
        tag = os.environ.get("BIGDL_TPU_ROUND", "latest")
        payload = dict(out)
        payload["metric"] = "generate_serving_tokens_per_sec"
        payload["value"] = out["continuous_tokens_per_sec"]
        payload["unit"] = "new_tokens/sec"
        payload["platform"] = "tpu" if on_tpu else "cpu"
        art = perf.make_round_artifact(
            payload, kind="generate_serving", timestamp=time.time(),
            device_kind=RESULT.get("device_kind"),
            confirmed_on_device=bool(on_tpu),
            git_rev=perf.git_revision(here))
        path = perf.write_round_artifact(
            os.path.join(here, f"GENSERVE_r{tag}.json"), art)
        _log(f"generate_serving artifact: {os.path.basename(path)} "
             f"({out['continuous_tokens_per_sec']} tok/s, "
             f"{out.get('speedup_vs_sequential')}x vs sequential)")
    except Exception:
        _log("generate_serving artifact write failed (non-fatal):\n"
             + traceback.format_exc())
    return out


def phase_fleet(on_tpu: bool):
    """The self-driving-fleet closed loop, measured: chaos kill ->
    controller replacement, spike -> scale-up, new checkpoint
    generation -> rolling zero-drop hot-deploy.  Headline metric is
    train-to-serve freshness (commit timestamp -> last replica
    serving the new generation)."""
    import tempfile

    from bigdl_tpu.fleet.harness import run_fleet_scenario

    work = tempfile.mkdtemp(prefix="bench-fleet-")
    r = run_fleet_scenario(work, load_s=2.0, spike_requests=14,
                           wait_scale_down=True)
    out = {
        "freshness_s": r["freshness_s"],
        "deployed_generation": r["deployed_generation"],
        "deploy_swapped_replicas": r["deploy_swapped"],
        "killed_replica": r["killed_replica"],
        "live_after_spike": r["live_after_spike"],
        "live_final": r["live_final"],
        "requests": {"submitted": r["submitted"], "ok": r["ok"],
                     "shed": r["shed"], "dropped": r["dropped"]},
        "greedy_rows_equal": r["greedy_rows_equal"],
        "admitted_outstanding_at_end": r["admitted_outstanding"],
        "events": r["events"],
        "loop_duration_s": r["duration_s"],
    }
    _update(fleet_deploy_freshness_s=r["freshness_s"],
            fleet_zero_drop=(r["dropped"] == 0
                             and r["admitted_outstanding"] == 0),
            fleet_scale_up_events=r["events"]["scale_up"],
            fleet_config="1to3replicas-kill+spike+hotdeploy")
    # durable evidence: its own artifact series (FLEET_r<N>.json),
    # same envelope as the training rounds
    try:
        from bigdl_tpu.telemetry import perf
        here = os.path.dirname(os.path.abspath(__file__))
        tag = os.environ.get("BIGDL_TPU_ROUND", "latest")
        payload = dict(out)
        payload["metric"] = "fleet_deploy_freshness_seconds"
        payload["value"] = r["freshness_s"]
        payload["unit"] = "seconds"
        payload["platform"] = "tpu" if on_tpu else "cpu"
        art = perf.make_round_artifact(
            payload, kind="fleet", timestamp=time.time(),
            device_kind=RESULT.get("device_kind"),
            confirmed_on_device=bool(on_tpu),
            git_rev=perf.git_revision(here))
        path = perf.write_round_artifact(
            os.path.join(here, f"FLEET_r{tag}.json"), art)
        _log(f"fleet artifact: {os.path.basename(path)} "
             f"(freshness {r['freshness_s']}s, "
             f"{r['deploy_swapped']} replicas hot-deployed, "
             f"dropped={r['dropped']})")
    except Exception:
        _log("fleet artifact write failed (non-fatal):\n"
             + traceback.format_exc())
    return out


def phase_roofline(on_tpu: bool):
    """Empirical bf16 matmul roofline: chained square matmuls (each
    output feeds the next so XLA cannot elide any), timed after warmup
    with a scalar readback.  Escalating sizes, each its own sub-deadline:
    the r03 hang at 8192 can cost at most one slice of budget now, and a
    smaller measured roofline is kept as a lower bound."""
    import jax
    import jax.numpy as jnp

    chain_len = 8

    def measure(n, reps):
        @jax.jit
        def chain(a, b):
            for _ in range(chain_len):
                a = jnp.matmul(a, b, preferred_element_type=jnp.bfloat16)
            return a

        a = jnp.full((n, n), 0.5, jnp.bfloat16)
        b = jnp.full((n, n), 1e-4, jnp.bfloat16)

        def run(r):
            out = a
            for _ in range(r):
                out = chain(out, b)
            return float(jnp.sum(out, dtype=jnp.float32))

        run(1)  # compile chain + the readback reduction
        t0 = time.perf_counter()
        run(reps)
        dt = time.perf_counter() - t0
        peak = 2.0 * n * n * n * chain_len * reps / dt
        _log(f"roofline n={n}: {peak / 1e12:.1f} TFLOP/s bf16 ({dt:.2f}s)")
        return peak

    sizes = ((2048, 16), (4096, 16), (8192, 8)) if on_tpu else ((512, 2),)
    best = None
    for n, reps in sizes:
        if _remaining() < 45.0:
            _log(f"roofline: skipping n>={n} (budget)")
            break
        # each size gets its own abandonment deadline via a nested phase
        val = run_phase(f"roofline_{n}", lambda n=n, r=reps: measure(n, r),
                        deadline_s=40.0)
        if val is None:
            break  # a wedged dispatch rarely recovers; keep lower bound
        best = max(best or 0.0, val)
        _update(peak_measured_flops=best)
    return best


# ---------------------------------------------------------------------------
# Perf attribution + durable-evidence plumbing (telemetry.perf)
# ---------------------------------------------------------------------------

# the optimizer loop's per-window phase records, kept so the
# attribution table can be re-derived AFTER the roofline phase measures
# this run's peak (phase order puts the headline loop first)
_OPT_WINDOW_RECORDS: list = []


def _build_attribution():
    """Attribution report (phases + residual + MFU + boundedness) from
    the optimizer loop's window records and whatever cost/roofline
    numbers have landed in RESULT so far."""
    from bigdl_tpu.telemetry import perf
    if not _OPT_WINDOW_RECORDS:
        return None
    pfx = ("fused_" if RESULT.get("optimizer_loop_variant") == "fused"
           else "")
    rep = perf.attribution_report(
        _OPT_WINDOW_RECORDS,
        # prefer the optimizer loop's own execution-weighted FLOP
        # count (the program the windows actually ran); fall back to
        # the raw-step program's
        flops_per_step=(RESULT.get("optimizer_flops_per_step")
                        or RESULT.get(pfx + "flops_per_step")
                        or RESULT.get("flops_per_step")),
        bytes_per_step=(RESULT.get(pfx + "bytes_per_step")
                        or RESULT.get("bytes_per_step")),
        peak_spec_flops=RESULT.get("peak_spec_flops"),
        peak_measured_flops=RESULT.get("peak_measured_flops"),
        device_kind=RESULT.get("device_kind"),
        comm_bytes_per_step=(RESULT.get(pfx + "comm_bytes_per_step")
                             or RESULT.get("comm_bytes_per_step")),
        dcn_bytes_per_step=RESULT.get("dcn_bytes_per_step"))
    if rep is not None:
        # which sync mode produced this number rides IN the table (and
        # through it the BENCH_telemetry.json snapshot), so a round
        # artifact is self-describing about its gradient wire
        for key in ("comm_sync_mode", "comm_wire_dtype",
                    "comm_compression_ratio"):
            if RESULT.get(key) is not None:
                rep[key] = RESULT[key]
    return rep


def _refresh_attribution():
    """Re-derive the attribution table once the same-run roofline has
    landed (mfu_vs_measured becomes computable), and rewrite the
    telemetry snapshot's embedded copy so artifact and result line
    agree."""
    try:
        att = _build_attribution()
        if not att:
            return
        _update(attribution=att)
        snap_name = RESULT.get("telemetry_snapshot")
        if snap_name:
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), snap_name)
            with open(path, "r", encoding="utf-8") as f:
                snap = json.load(f)
            snap["perf_attribution"] = att
            with open(path, "w", encoding="utf-8") as f:
                json.dump(snap, f, default=str)
    except Exception:
        _log("attribution refresh failed (non-fatal):\n"
             + traceback.format_exc())


def _publish_carried_forward():
    """Emit the newest confirmed on-device artifact as this round's
    result, marked ``carried_forward: true`` with the ORIGINAL
    measurement timestamp — the wedged-tunnel degradation VERDICT items
    1 and 6 asked for.  Falls back to the old 0.0 partial only when no
    confirmed evidence exists on disk."""
    try:
        from bigdl_tpu.telemetry import perf
        here = os.path.dirname(os.path.abspath(__file__))
        found = perf.latest_confirmed(here)
        if found is None:
            raise FileNotFoundError(
                "no confirmed on-device BENCH artifact on disk")
        path, doc = found
        out = perf.carried_forward_result(
            doc, path,
            note="backend unreachable at bench time; republishing the "
                 "latest confirmed on-device evidence")
        out["probe_failure"] = RESULT["phases"].get("backend_init")
        perf.record_carried_forward_round()
        with _LOCK:
            if _EMITTED.is_set():
                return
            _EMITTED.set()
            line = json.dumps(out)
        print(line, flush=True)
        _log(f"published carried-forward round from "
             f"{os.path.basename(path)} (value {out.get('value')}, "
             f"original_timestamp {out.get('original_timestamp')})")
    except Exception:
        _log("carried-forward publication failed; emitting the "
             "explicitly-partial result:\n" + traceback.format_exc())
        _emit_final("backend_init_failed")


def main():
    _start_watchdog()
    # generous init runway: the tunneled chip was unreachable for all
    # of round 4 with init hanging indefinitely — but a HALF-wedged
    # tunnel that comes up in 3-4 minutes must not be forfeited; the
    # remaining budget still fits compile + the raw-step measurement
    dev = run_phase("backend_init", phase_backend, deadline_s=340.0)
    if dev is None:
        # The tunneled chip comes and goes (r04: unreachable for a
        # whole session, then back).  A wedged backend must never again
        # publish a 0.0 round: re-emit the newest CONFIRMED on-device
        # artifact, clearly marked carried_forward with its original
        # timestamp.  Only with no confirmed evidence anywhere on disk
        # does the explicitly-partial 0.0 line go out.
        _publish_carried_forward()
        return

    on_tpu = dev.platform != "cpu"
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 64
    _update(metric=f"resnet50_train_img_per_sec_bs{batch}_{size}px_"
                   f"{dev.platform}")

    host_batch = run_phase(
        "raw_step", lambda: phase_raw_step(on_tpu, batch, size),
        deadline_s=240.0)
    if host_batch is None:
        rng = np.random.default_rng(0)
        host_batch = (rng.normal(size=(batch, size, size, 3)).astype(
            np.float32), rng.integers(1, 1001, size=(batch,)))

    # Fused Pallas tranche head-to-head (TPU only: off-accelerator the
    # model falls back to the plain path, so there is nothing to race).
    # The gate and the deadline both reserve the optimizer loop's
    # budget (~130s): the HEADLINE phase must never be starved by the
    # secondary comparison.
    if on_tpu and os.environ.get("BIGDL_TPU_BENCH_NO_FUSED"):
        RESULT["phases"]["fused_step"] = "skipped (BIGDL_TPU_BENCH_NO_FUSED)"
    elif on_tpu and _remaining() > 280.0:
        run_phase("fused_step",
                  lambda: phase_fused_step(on_tpu, batch, size),
                  deadline_s=min(150.0, _remaining() - 130.0))
    elif on_tpu:
        RESULT["phases"]["fused_step"] = "skipped (budget)"

    if _remaining() > 90.0:
        run_phase("optimizer_loop",
                  lambda: phase_optimizer_loop(on_tpu, batch, size,
                                               host_batch),
                  deadline_s=180.0)
    else:
        RESULT["phases"]["optimizer_loop"] = "skipped (budget)"
    if _remaining() > 60.0:
        run_phase("roofline", lambda: phase_roofline(on_tpu),
                  deadline_s=150.0)
        # the roofline landed after the optimizer loop: fold the
        # measured peak into the attribution table + snapshot copy
        _refresh_attribution()
    else:
        RESULT["phases"]["roofline"] = "skipped (budget)"
    if _remaining() > 75.0:
        run_phase("transformer", lambda: phase_transformer(on_tpu),
                  deadline_s=110.0)
    else:
        RESULT["phases"]["transformer"] = "skipped (budget)"
    if _remaining() > 50.0:
        run_phase("int8_infer", lambda: phase_int8(on_tpu),
                  deadline_s=100.0)
    else:
        RESULT["phases"]["int8_infer"] = "skipped (budget)"
    if _remaining() > 60.0:
        run_phase("generate_serving",
                  lambda: phase_generate_serving(on_tpu),
                  deadline_s=120.0)
    else:
        RESULT["phases"]["generate_serving"] = "skipped (budget)"
    if _remaining() > 60.0:
        run_phase("fleet", lambda: phase_fleet(on_tpu),
                  deadline_s=120.0)
    else:
        RESULT["phases"]["fleet"] = "skipped (budget)"

    # RoundArtifact provenance on the result line itself: schema
    # version, run timestamp, git rev, and the confirmed-on-device flag
    # latest_confirmed() keys on when a later wedged round degrades to
    # carrying this one forward
    try:
        from bigdl_tpu.telemetry import perf
        _update(schema_version=perf.ROUND_ARTIFACT_VERSION,
                timestamp=time.time(),
                git_rev=perf.git_revision(
                    os.path.dirname(os.path.abspath(__file__))),
                confirmed_on_device=bool(on_tpu and RESULT.get("value")))
    except Exception:
        _log("provenance stamping failed (non-fatal):\n"
             + traceback.format_exc())

    _emit_final("done")
    # hard-exit: abandoned phase threads may be wedged inside native XLA
    # calls; normal interpreter teardown can SIGABRT after our JSON is
    # already out — exit 0 deliberately once the result line is printed
    os._exit(0)


if __name__ == "__main__":
    main()
