"""Benchmark entry: ResNet-50 ImageNet-shape training throughput on the
available accelerator (one TPU chip under the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline for vs_baseline: the reference's published ResNet-50 recipe
throughput per CPU core — BigDL trains ResNet-50 at global batch 8192 on
2048 Xeon cores (models/resnet/README.md); sustained ~1.1 img/s/core
(whitepaper-era Broadwell measurements ⇒ ~2250 img/s cluster-wide).
vs_baseline reports our img/s on ONE chip divided by the reference's
img/s on one 32-core executor (~35 img/s) — i.e. chip-for-executor
speedup.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.core.module import partition, combine, forward_context
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim.methods import SGD
    from bigdl_tpu.utils import set_seed

    set_seed(0)
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch = 64 if on_tpu else 8
    size = 224 if on_tpu else 64

    model = resnet50(class_num=1000)
    criterion = nn.CrossEntropyCriterion()
    method = SGD(0.1, momentum=0.9, dampening=0.0)

    params_tree, rest = partition(model)
    opt_state = method.init_state(params_tree)

    from bigdl_tpu.core.module import cast_floating

    @jax.jit
    def step(params, rest, opt_state, x, y):
        def loss_fn(p):
            m = cast_floating(combine(p, rest), jnp.bfloat16)
            out = m.forward(x.astype(jnp.bfloat16)).astype(jnp.float32)
            return criterion(out, y), m

        (loss, m2), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, opt_state2 = method.update(grads, params, opt_state)
        _, rest2 = partition(m2)
        rest2 = cast_floating(rest2, jnp.float32)
        return params, rest2, opt_state2, loss

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, size, size, 3)),
                    dtype=jnp.float32)
    y = jnp.asarray(rng.integers(1, 1001, size=(batch,)))

    # warmup/compile
    params_tree, rest, opt_state, loss = step(
        params_tree, rest, opt_state, x, y)
    jax.block_until_ready(loss)

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        params_tree, rest, opt_state, loss = step(
            params_tree, rest, opt_state, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_per_sec = batch * iters / dt
    # reference: ~35 img/s per 32-core executor (see module docstring)
    vs_baseline = img_per_sec / 35.0
    print(json.dumps({
        "metric": f"resnet50_train_img_per_sec_bs{batch}_{size}px_"
                  f"{dev.platform}",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs_baseline, 2),
    }))


if __name__ == "__main__":
    main()
