"""Criterion oracle tests vs torch CPU + structural tests.

Targets use the reference's 1-based class convention; torch's are
0-based, adjusted at the boundary.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn

RTOL, ATOL = 1e-4, 1e-5


def rnd(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_classnll_matches_torch():
    logits = rnd(5, 7)
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits)))
    target = np.array([1, 3, 7, 2, 5])
    ours = nn.ClassNLLCriterion()(jnp.asarray(logp), jnp.asarray(target))
    ref = F.nll_loss(torch.tensor(logp), torch.tensor(target - 1))
    np.testing.assert_allclose(float(ours), float(ref), rtol=RTOL)


def test_classnll_with_weights_matches_torch():
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(rnd(5, 4))))
    target = np.array([1, 2, 3, 4, 2])
    w = np.array([0.2, 0.5, 1.0, 2.0], dtype=np.float32)
    ours = nn.ClassNLLCriterion(weights=w)(jnp.asarray(logp),
                                           jnp.asarray(target))
    ref = F.nll_loss(torch.tensor(logp), torch.tensor(target - 1),
                     weight=torch.tensor(w))
    np.testing.assert_allclose(float(ours), float(ref), rtol=RTOL)


def test_crossentropy_matches_torch():
    logits = rnd(6, 9)
    target = np.array([1, 2, 3, 4, 5, 9])
    ours = nn.CrossEntropyCriterion()(jnp.asarray(logits),
                                      jnp.asarray(target))
    ref = F.cross_entropy(torch.tensor(logits), torch.tensor(target - 1))
    np.testing.assert_allclose(float(ours), float(ref), rtol=RTOL)


def test_mse_and_abs_match_torch():
    a, b = rnd(4, 5), rnd(4, 5, seed=1)
    np.testing.assert_allclose(
        float(nn.MSECriterion()(jnp.asarray(a), jnp.asarray(b))),
        float(F.mse_loss(torch.tensor(a), torch.tensor(b))), rtol=RTOL)
    np.testing.assert_allclose(
        float(nn.AbsCriterion()(jnp.asarray(a), jnp.asarray(b))),
        float(F.l1_loss(torch.tensor(a), torch.tensor(b))), rtol=RTOL)


def test_bce_matches_torch():
    p = 1 / (1 + np.exp(-rnd(6, 3)))
    t = (rnd(6, 3, seed=2) > 0).astype(np.float32)
    np.testing.assert_allclose(
        float(nn.BCECriterion()(jnp.asarray(p), jnp.asarray(t))),
        float(F.binary_cross_entropy(torch.tensor(p), torch.tensor(t))),
        rtol=1e-3)


def test_smoothl1_matches_torch():
    a, b = rnd(4, 5), rnd(4, 5, seed=1) * 3
    np.testing.assert_allclose(
        float(nn.SmoothL1Criterion()(jnp.asarray(a), jnp.asarray(b))),
        float(F.smooth_l1_loss(torch.tensor(a), torch.tensor(b))), rtol=RTOL)


def test_distkldiv_matches_torch():
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(rnd(4, 6))))
    t = np.asarray(jax.nn.softmax(jnp.asarray(rnd(4, 6, seed=1))))
    np.testing.assert_allclose(
        float(nn.DistKLDivCriterion()(jnp.asarray(logp), jnp.asarray(t))),
        float(F.kl_div(torch.tensor(logp), torch.tensor(t),
                       reduction="mean")), rtol=1e-3)


def test_margin_ranking_matches_torch():
    x1, x2 = rnd(8), rnd(8, seed=1)
    y = np.sign(rnd(8, seed=2)).astype(np.float32)
    ours = nn.MarginRankingCriterion(margin=0.5)(
        (jnp.asarray(x1), jnp.asarray(x2)), jnp.asarray(y))
    ref = F.margin_ranking_loss(torch.tensor(x1), torch.tensor(x2),
                                torch.tensor(y), margin=0.5)
    np.testing.assert_allclose(float(ours), float(ref), rtol=RTOL)


def test_multimargin_matches_torch():
    x = rnd(5, 6)
    t = np.array([1, 4, 2, 6, 3])
    ours = nn.MultiMarginCriterion()(jnp.asarray(x), jnp.asarray(t))
    ref = F.multi_margin_loss(torch.tensor(x), torch.tensor(t - 1))
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-3)


def test_soft_margin_matches_torch():
    x = rnd(6, 4)
    y = np.sign(rnd(6, 4, seed=1)).astype(np.float32)
    ours = nn.SoftMarginCriterion()(jnp.asarray(x), jnp.asarray(y))
    ref = F.soft_margin_loss(torch.tensor(x), torch.tensor(y))
    np.testing.assert_allclose(float(ours), float(ref), rtol=RTOL)


def test_cosine_embedding_matches_torch():
    x1, x2 = rnd(4, 8), rnd(4, 8, seed=1)
    y = np.array([1, -1, 1, -1], dtype=np.float32)
    ours = nn.CosineEmbeddingCriterion(margin=0.3)(
        (jnp.asarray(x1), jnp.asarray(x2)), jnp.asarray(y))
    ref = F.cosine_embedding_loss(torch.tensor(x1), torch.tensor(x2),
                                  torch.tensor(y), margin=0.3)
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-3)


def test_hinge_embedding_matches_torch():
    x = rnd(10)
    y = np.sign(rnd(10, seed=1)).astype(np.float32)
    ours = nn.HingeEmbeddingCriterion(margin=1.0)(
        jnp.asarray(x), jnp.asarray(y))
    ref = F.hinge_embedding_loss(torch.tensor(x), torch.tensor(y))
    np.testing.assert_allclose(float(ours), float(ref), rtol=RTOL)


def test_multilabel_soft_margin_matches_torch():
    x = rnd(4, 5)
    t = (rnd(4, 5, seed=3) > 0).astype(np.float32)
    ours = nn.MultiLabelSoftMarginCriterion()(jnp.asarray(x), jnp.asarray(t))
    ref = F.multilabel_soft_margin_loss(torch.tensor(x), torch.tensor(t))
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-3)


def test_multilabel_margin_matches_torch():
    x = rnd(3, 6)
    t = np.array([[2, 4, 0, 0, 0, 0], [1, 0, 0, 0, 0, 0],
                  [3, 5, 6, 0, 0, 0]])
    ours = nn.MultiLabelMarginCriterion()(jnp.asarray(x), jnp.asarray(t))
    tt = torch.tensor(t - 1)
    tt[t == 0] = -1
    ref = F.multilabel_margin_loss(torch.tensor(x), tt)
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-3)


def test_criterion_backward_matches_torch():
    logits = rnd(4, 5)
    target = np.array([1, 2, 3, 4])
    crit = nn.CrossEntropyCriterion()
    gi = crit.backward(jnp.asarray(logits), jnp.asarray(target))
    xt = torch.tensor(logits, requires_grad=True)
    F.cross_entropy(xt, torch.tensor(target - 1)).backward()
    np.testing.assert_allclose(np.asarray(gi), xt.grad.numpy(),
                               rtol=RTOL, atol=ATOL)


def test_parallel_and_multi_criterion():
    a, b = jnp.asarray(rnd(3, 4)), jnp.asarray(rnd(3, 4, seed=1))
    pc = nn.ParallelCriterion().add(nn.MSECriterion(), 0.5) \
                               .add(nn.AbsCriterion(), 2.0)
    loss = pc((a, a * 0), (b, b))
    expect = 0.5 * float(nn.MSECriterion()(a, b)) \
        + 2.0 * float(nn.AbsCriterion()(a * 0, b))
    np.testing.assert_allclose(float(loss), expect, rtol=RTOL)
    mc = nn.MultiCriterion().add(nn.MSECriterion()).add(nn.AbsCriterion())
    loss2 = mc(a, b)
    expect2 = float(nn.MSECriterion()(a, b)) + float(nn.AbsCriterion()(a, b))
    np.testing.assert_allclose(float(loss2), expect2, rtol=RTOL)


def test_kld_vae_criterion():
    mean = jnp.zeros((2, 4))
    log_var = jnp.zeros((2, 4))
    assert float(nn.KLDCriterion()((mean, log_var))) == pytest.approx(0.0)


def test_timedistributed_criterion():
    x = rnd(2, 3, 5)  # batch, time, classes
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(x)))
    t = np.array([[1, 2, 3], [4, 5, 1]])
    # reference semantics: sum of per-timestep criterion losses, divided
    # by nstep when size_average (TimeDistributedCriterion.scala)
    ours_sa = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), True, 2)(
        jnp.asarray(logp), jnp.asarray(t))
    ours_sum = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), False, 2)(
        jnp.asarray(logp), jnp.asarray(t))
    per_step = [F.nll_loss(torch.tensor(logp[:, i]),
                           torch.tensor(t[:, i] - 1)) for i in range(3)]
    expect_sum = float(sum(per_step))
    np.testing.assert_allclose(float(ours_sum), expect_sum, rtol=1e-3)
    np.testing.assert_allclose(float(ours_sa), expect_sum / 3, rtol=1e-3)


def test_multimargin_weights_applied():
    x = rnd(5, 6)
    t = np.array([1, 4, 2, 6, 3])
    w = np.array([0.1, 0.5, 1.0, 2.0, 0.3, 1.5], dtype=np.float32)
    ours = nn.MultiMarginCriterion(weights=w)(jnp.asarray(x), jnp.asarray(t))
    ref = F.multi_margin_loss(torch.tensor(x), torch.tensor(t - 1),
                              weight=torch.tensor(w))
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-3)


def test_multilabel_margin_stops_at_first_zero():
    x = rnd(1, 6)
    t = np.array([[2, 0, 4, 0, 0, 0]])  # only class 2 is a target
    ours = nn.MultiLabelMarginCriterion()(jnp.asarray(x), jnp.asarray(t))
    tt = torch.tensor(t - 1)
    tt[t == 0] = -1
    ref = F.multilabel_margin_loss(torch.tensor(x), tt)
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-3)


def test_distkldiv_divides_by_nelement():
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(rnd(4, 6))))
    t = np.asarray(jax.nn.softmax(jnp.asarray(rnd(4, 6, seed=1))))
    ours = nn.DistKLDivCriterion(size_average=True)(
        jnp.asarray(logp), jnp.asarray(t))
    ref = F.kl_div(torch.tensor(logp), torch.tensor(t), reduction="mean")
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-3)


# -------------------------------------------------------------------------
# Parametrized gradient sweep: every torch-comparable criterion's
# input-gradient must match torch (the reference's per-criterion specs
# check backward too).  Cases: (name, ours, torch_fn, make_(input,target)).
# -------------------------------------------------------------------------

def _r(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def _sig01(*shape, seed=0):
    return (1 / (1 + np.exp(-_r(*shape, seed=seed)))).astype(np.float32)


GRAD_CASES = [
    ("MSE", lambda: nn.MSECriterion(),
     lambda a, t: F.mse_loss(a, t), lambda: (_r(4, 5, seed=1), _r(4, 5, seed=2))),
    ("Abs", lambda: nn.AbsCriterion(),
     lambda a, t: F.l1_loss(a, t), lambda: (_r(4, 5, seed=3), _r(4, 5, seed=4))),
    ("BCE", lambda: nn.BCECriterion(),
     lambda a, t: F.binary_cross_entropy(a, t),
     lambda: (_sig01(4, 5, seed=5), (_r(4, 5, seed=6) > 0).astype(np.float32))),
    ("SmoothL1", lambda: nn.SmoothL1Criterion(),
     lambda a, t: F.smooth_l1_loss(a, t),
     lambda: (_r(4, 5, seed=7), _r(4, 5, seed=8))),
    ("SoftMargin", lambda: nn.SoftMarginCriterion(),
     lambda a, t: F.soft_margin_loss(a, t),
     lambda: (_r(4, 5, seed=9),
              np.sign(_r(4, 5, seed=10)).astype(np.float32))),
    ("ClassNLL", lambda: nn.ClassNLLCriterion(),
     lambda a, t: F.nll_loss(a, t),
     lambda: (np.log(_sig01(6, 4, seed=11) + 0.1),
              np.random.RandomState(12).randint(1, 5, size=(6,)))),
    ("CrossEntropy", lambda: nn.CrossEntropyCriterion(),
     lambda a, t: F.cross_entropy(a, t),
     lambda: (_r(6, 4, seed=13),
              np.random.RandomState(14).randint(1, 5, size=(6,)))),
    ("DistKLDiv", lambda: nn.DistKLDivCriterion(),
     lambda a, t: F.kl_div(a, t, reduction="batchmean") * t.shape[0]
     / t.numel(),
     lambda: (np.log(_sig01(4, 5, seed=15) + 0.05),
              _sig01(4, 5, seed=16))),
    ("Poisson", lambda: nn.PoissonCriterion(),
     lambda a, t: F.poisson_nll_loss(torch.log(a), t, log_input=True,
                                     full=False),
     lambda: (_sig01(4, 5, seed=17) + 0.5, _sig01(4, 5, seed=18))),
    ("MultiMargin", lambda: nn.MultiMarginCriterion(),
     lambda a, t: F.multi_margin_loss(a, t),
     lambda: (_r(6, 4, seed=19),
              np.random.RandomState(20).randint(1, 5, size=(6,)))),
    ("HingeEmbedding", lambda: nn.HingeEmbeddingCriterion(margin=1.0),
     lambda a, t: F.hinge_embedding_loss(a, t, margin=1.0),
     lambda: (np.abs(_r(4, 5, seed=21)),
              np.sign(_r(4, 5, seed=22)).astype(np.float32))),
    ("MultiLabelSoftMargin", lambda: nn.MultiLabelSoftMarginCriterion(),
     lambda a, t: F.multilabel_soft_margin_loss(a, t),
     lambda: (_r(4, 5, seed=23), (_r(4, 5, seed=24) > 0).astype(np.float32))),
]


@pytest.mark.parametrize("case", GRAD_CASES, ids=lambda c: c[0])
def test_criterion_grad_sweep(case):
    name, make_ours, torch_fn, make_io = case
    crit = make_ours()
    a_np, t_np = make_io()
    one_based = name in ("ClassNLL", "CrossEntropy", "MultiMargin")

    g_ours = jax.grad(
        lambda a: crit(a, jnp.asarray(t_np)))(jnp.asarray(a_np))

    ta = torch.tensor(a_np, requires_grad=True)
    tt = torch.tensor(t_np - 1) if one_based else torch.tensor(t_np)
    loss = torch_fn(ta, tt)
    loss.backward()
    np.testing.assert_allclose(np.asarray(g_ours), ta.grad.numpy(),
                               rtol=1e-4, atol=1e-5, err_msg=name)
