"""Sharded-embedding subsystem tests (bigdl_tpu/embedding/).

The load-bearing assertions: (a) the a2a lookup and its gradient are
bit-compatible with the dense single-device gather; (b) Optimizer
training of the hybrid (sharded tables + replicated tower) matches the
unsharded baseline at fixed seed to fp32 tolerance; (c) the compiled
training step contains NO dense (rows x dim) table all-reduce — the
gradient path is provably sparse at the HLO level (and the dp baseline
proves the check has teeth); (d) interrupted-and-resumed streaming
eval equals the one-shot sweep, including over a MixedDataSet source;
(e) sessions keyed by embedding shard ride the router to one home
replica, and a request scores end-to-end through Router -> Replica ->
RecommenderScorer.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import combine, partition
from bigdl_tpu.embedding import (
    HybridPlanError, RecommenderScorer, ShardedEmbeddingTable,
    StreamingRecEval, configure_hybrid, hybrid_optim_methods,
    resolve_hybrid, shard_affinity_key,
)
from bigdl_tpu.embedding.sharded_table import LAST_LOOKUP_SHAPES
from bigdl_tpu.models import WideAndDeep, wide_and_deep, zoo
from bigdl_tpu.utils import set_seed


def _mesh(n=8):
    from bigdl_tpu.parallel.mesh import MeshConfig
    return MeshConfig(data=n).build()


def _dup_heavy_ids(n_index, shape, seed=0):
    """Ids with guaranteed duplicates (drawn from a quarter of the
    space), 1-based."""
    rng = np.random.default_rng(seed)
    return rng.integers(1, max(n_index // 4, 2),
                        size=shape).astype(np.int32)


# ---------------------------------------------------------------------------
# lookup: a2a path == dense gather, forward and backward
# ---------------------------------------------------------------------------

def test_sharded_lookup_matches_dense():
    set_seed(3)
    t = ShardedEmbeddingTable(64, 8)
    ids = _dup_heavy_ids(64, (16, 3), seed=1)
    dense = np.asarray(t.forward(ids))
    LAST_LOOKUP_SHAPES.clear()
    t.set_mesh(_mesh())
    a2a = np.asarray(t.forward(ids))
    np.testing.assert_allclose(a2a, dense, atol=1e-6)
    assert a2a.shape == (16, 3, 8)
    # per-device buffers: 48 flat ids over 8 devices = S=6 local ids,
    # exact capacity S per destination (nothing ever dropped)
    assert LAST_LOOKUP_SHAPES["send"] == (8, 6)
    assert LAST_LOOKUP_SHAPES["vecs"] == (8, 6, 8)


def test_sharded_lookup_gradient_matches_dense_and_stays_sparse():
    set_seed(3)
    t = ShardedEmbeddingTable(64, 8)
    ids = _dup_heavy_ids(64, (24,), seed=2)

    def loss_of(table):
        params, rest = partition(table)

        def loss(p):
            out = combine(p, rest).forward(ids)
            return jnp.sum(out * out)

        return jax.grad(loss)(params)

    g_dense = loss_of(t)
    t.set_mesh(_mesh())
    g_a2a = loss_of(t)
    gd = np.asarray(jax.tree_util.tree_leaves(g_dense)[0])
    ga = np.asarray(jax.tree_util.tree_leaves(g_a2a)[0])
    np.testing.assert_allclose(ga, gd, rtol=1e-5, atol=1e-6)
    # sparse: rows never looked up get exactly zero gradient
    touched = np.zeros(64, bool)
    touched[np.unique(ids) - 1] = True
    assert np.all(ga[~touched] == 0.0)
    assert np.any(ga[touched] != 0.0)


def test_lookup_rejects_unhonorable_layouts():
    t = ShardedEmbeddingTable(60, 4)  # 60 % 8 != 0
    with pytest.raises(ValueError, match="do not divide over 8 shards"):
        t.set_mesh(_mesh())
    t2 = ShardedEmbeddingTable(64, 4)
    with pytest.raises(ValueError, match="not on the mesh"):
        t2.set_mesh(_mesh(), axis="expert")
    t2.set_mesh(_mesh())
    with pytest.raises(ValueError, match="do not shard over the 8-way"):
        t2.forward(np.ones((3,), np.int32))  # 3 ids over 8 devices


def test_owner_of_matches_affinity_key():
    t = ShardedEmbeddingTable(64, 4).set_mesh(_mesh())
    for uid in (1, 8, 9, 33, 64, 200):
        shard = int(t.owner_of(uid))
        assert shard_affinity_key(uid, 64, 8) == f"emb-default-user-s{shard}"


# ---------------------------------------------------------------------------
# nn/sparse dedup: same gradient values, fewer scatter rows
# ---------------------------------------------------------------------------

def test_dedup_backward_same_values_fewer_scatter_rows():
    from bigdl_tpu.nn.sparse import dedup_gather, dedup_scatter_updates
    set_seed(11)
    w = jnp.asarray(np.random.default_rng(0).normal(size=(32, 4)),
                    jnp.float32)
    # duplicate-heavy: 48 lookups into 8 distinct rows
    idx = jnp.asarray(np.random.default_rng(1).integers(0, 8, size=48))
    cot = jnp.asarray(np.random.default_rng(2).normal(size=(48, 4)),
                      jnp.float32)

    g_dedup = jax.vjp(lambda w: dedup_gather(w, idx), w)[1](cot)[0]
    g_naive = jax.vjp(lambda w: w[idx], w)[1](cot)[0]
    np.testing.assert_allclose(np.asarray(g_dedup), np.asarray(g_naive),
                               rtol=1e-6, atol=1e-6)
    # the pin: duplicates collapse BEFORE the scatter — one combined
    # contribution row per unique id, zeros elsewhere
    rows, contrib = dedup_scatter_updates(idx, cot)
    nonzero = int(np.sum(np.any(np.asarray(contrib) != 0.0, axis=1)))
    n_unique = int(np.unique(np.asarray(idx)).size)
    assert nonzero == n_unique < idx.shape[0]


def test_lookup_table_sparse_duplicate_batch_gradient():
    from bigdl_tpu.nn.sparse import LookupTableSparse, SparseTensor
    set_seed(12)
    mod = LookupTableSparse(16, 4)
    # duplicate-heavy batch: row 0 looks up id 3 three times + id 7,
    # row 1 looks up id 7 twice + id 1 twice
    dense_ids = jnp.asarray([[3, 3, 3, 7], [7, 7, 1, 1]], jnp.int32)
    ids = SparseTensor.from_dense(dense_ids)
    params, rest = partition(mod)

    def loss(p):
        return jnp.sum(combine(p, rest).forward(ids) ** 2)

    g = np.asarray(jax.tree_util.tree_leaves(jax.grad(loss)(params))[0])
    # oracle: the same sum-combined math on the plain dense gather
    w0 = jnp.asarray(np.asarray(mod.weight))

    def ref_loss(w):
        emb = w[jnp.clip(dense_ids - 1, 0, 15)]
        return jnp.sum(jnp.sum(emb, axis=1) ** 2)

    g_ref = np.asarray(jax.grad(ref_loss)(w0))
    np.testing.assert_allclose(g, g_ref, rtol=1e-5, atol=1e-6)
    touched = np.zeros(16, bool)
    touched[[0, 2, 6]] = True  # ids 1, 3, 7 -> rows 0, 2, 6
    assert np.all(g[~touched] == 0.0)
    assert np.all(np.any(g[touched] != 0.0, axis=1))


# ---------------------------------------------------------------------------
# hybrid training: loss equivalence + provable HLO sparsity
# ---------------------------------------------------------------------------

def _wd_dataset(n=32, bs=16):
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import DataSet, Sample
    from bigdl_tpu.dataset.movielens import synthetic_id_stream
    samples = []
    for pairs, labels in synthetic_id_stream(n_users=64, n_items=32,
                                             batch_size=n, batches=1,
                                             seed=6):
        samples = [Sample(pairs[i], labels[i]) for i in range(n)]
    return (DataSet.array(samples, shuffle=False)
            .transform(SampleToMiniBatch(bs)))


def _train_wd(sharded, n_iter=4):
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.parallel.mesh import MeshConfig
    from bigdl_tpu.parallel.sharding import ShardingRules
    set_seed(42)
    model = WideAndDeep(64, 32, embed_dim=8, mlp_dims=(16,))
    opt = (Optimizer(model, _wd_dataset(), nn.BCECriterion())
           .set_optim_method(SGD(0.05))
           .set_end_when(Trigger.max_iteration(n_iter)))
    if sharded:
        plan = configure_hybrid(opt, axes={"data": 8})
        assert plan["n_shards"] == 8 and len(plan["tables"]) == 4
    else:
        opt.set_mesh(MeshConfig(data=1), ShardingRules())
    opt.optimize()
    leaves = [np.asarray(l) for l in
              jax.tree_util.tree_leaves(model.parameters())]
    return opt.state["loss"], leaves


@pytest.mark.slow
def test_hybrid_training_matches_single_device_baseline():
    loss_base, params_base = _train_wd(sharded=False)
    loss_shard, params_shard = _train_wd(sharded=True)
    assert abs(loss_base - loss_shard) <= 1e-6, \
        f"sharded loss {loss_shard} != baseline {loss_base}"
    for a, b in zip(params_base, params_shard):
        np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_hybrid_step_hlo_has_no_dense_table_allreduce():
    """The acceptance gate, at the artifact level: the compiled hybrid
    step moves table data ONLY through all_to_all; a dense
    (rows x dim) table all-reduce in the HLO is the sparsity
    regression this test exists to catch.  The dp baseline DOES
    contain those all-reduces — proving the pattern would fire."""
    from bigdl_tpu.dataset.dataset import MiniBatch
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.parallel.mesh import MeshConfig
    from bigdl_tpu.parallel.sharding import ShardingRules

    table_shapes = [(64, 8), (32, 8), (64, 1), (32, 1)]

    def compile_step(sharded):
        set_seed(42)
        model = WideAndDeep(64, 32, embed_dim=8, mlp_dims=(16,))
        opt = (Optimizer(model, _wd_dataset(), nn.BCECriterion())
               .set_optim_method(SGD(0.05)))
        if sharded:
            configure_hybrid(opt, axes={"data": 8})
        else:
            opt.set_mesh(MeshConfig(data=8), ShardingRules())
        rng = np.random.default_rng(3)
        pairs = np.stack([rng.integers(1, 65, size=16),
                          rng.integers(1, 33, size=16)],
                         axis=1).astype(np.int32)
        labels = rng.integers(0, 2, size=(16, 1)).astype(np.float32)
        return opt.compile_step(MiniBatch(pairs, labels)).as_text()

    def table_allreduce_lines(text):
        return [l for l in text.splitlines()
                if "all-reduce" in l
                and any(f"f32[{r},{d}]" in l for r, d in table_shapes)]

    dp = compile_step(sharded=False)
    assert table_allreduce_lines(dp), \
        "dp baseline lost its dense table all-reduces; the sparsity " \
        "check below would no longer prove anything"
    hybrid = compile_step(sharded=True)
    assert "all-to-all" in hybrid, "lookup a2a missing from hybrid step"
    offenders = table_allreduce_lines(hybrid)
    assert not offenders, \
        f"dense table all-reduce in the hybrid step: {offenders[:2]}"


def test_hybrid_rejects_unhonorable_compositions():
    set_seed(1)
    model = WideAndDeep(64, 32, embed_dim=8, mlp_dims=(16,))
    mesh = _mesh()
    with pytest.raises(HybridPlanError, match="no ShardedEmbeddingTable"):
        resolve_hybrid(nn.Sequential(nn.Linear(4, 2)), mesh)
    with pytest.raises(HybridPlanError, match="not on the mesh"):
        resolve_hybrid(model, mesh, axis="fsdp")
    with pytest.raises(HybridPlanError, match="hierarchical"):
        resolve_hybrid(model, mesh, hierarchical=True)
    from bigdl_tpu.parallel.mesh import MeshConfig
    tp_mesh = MeshConfig(data=4, model=2).build()
    with pytest.raises(HybridPlanError, match="batch-parallel meshes"):
        resolve_hybrid(model, tp_mesh)
    odd = WideAndDeep(60, 32, embed_dim=8, mlp_dims=(16,))
    with pytest.raises(HybridPlanError, match="not\\s+divisible"):
        resolve_hybrid(odd, mesh)
    from bigdl_tpu.optim import SGD
    with pytest.raises(HybridPlanError, match="BOTH table_method"):
        from bigdl_tpu.optim import Optimizer
        opt = (Optimizer(model, _wd_dataset(), nn.BCECriterion())
               .set_optim_method(SGD(0.1)))
        configure_hybrid(opt, axes={"data": 8}, table_method=SGD(0.5))


def test_hybrid_optim_methods_split_never_aliases():
    from bigdl_tpu.optim import SGD
    set_seed(1)
    model = WideAndDeep(64, 32, embed_dim=8, mlp_dims=(16,))
    methods = hybrid_optim_methods(model, SGD(0.5), SGD(0.1))
    assert set(methods) == {"user_table", "item_table", "wide_user",
                            "wide_item", "tower"}
    assert methods["user_table"].learning_rate == 0.5
    assert methods["tower"].learning_rate == 0.1
    owners = [id(m) for m in methods.values()]
    assert len(set(owners)) == len(owners), "method instances alias"
    with pytest.raises(HybridPlanError, match="IS a single table"):
        hybrid_optim_methods(ShardedEmbeddingTable(8, 2), SGD(1), SGD(1))


# ---------------------------------------------------------------------------
# streaming eval: interrupted-and-resumed == one-shot
# ---------------------------------------------------------------------------

def _ranking_rows(n_users=24, neg=7, seed=5):
    """[U, 1+neg, 2] id rows: positive item first, then negatives."""
    rng = np.random.default_rng(seed)
    rows = np.zeros((n_users, 1 + neg, 2), np.int32)
    for u in range(n_users):
        rows[u, :, 0] = u + 1
        rows[u, :, 1] = rng.permutation(32)[:1 + neg] + 1
    return rows


def _eval_model():
    set_seed(8)
    return WideAndDeep(64, 32, embed_dim=8, mlp_dims=(16,))


def test_streaming_eval_equals_oneshot_with_resume():
    model = _eval_model()
    rows = _ranking_rows()
    oneshot, final_state = StreamingRecEval(
        model, batch_size=8).evaluate(rows)
    assert oneshot is not None and len(oneshot) == 2

    # chunked: 1 batch at a time, state JSON-round-tripped like the
    # sidecar file it rides in
    state, results = None, None
    for _ in range(10):
        ev = StreamingRecEval(model, batch_size=8)
        results, state = ev.evaluate(rows, state=state, max_batches=1)
        if results is not None:
            break
        state = json.loads(json.dumps(state))
    assert results is not None
    for a, b in zip(oneshot, results):
        assert abs(a.result()[0] - b.result()[0]) <= 1e-6, (a, b)
    assert state["partials"] == final_state["partials"]

    # HitRatio/NDCG must be genuinely informative (not NaN/zero-den)
    assert all(0.0 <= r.result()[0] <= 1.0 for r in oneshot)


def test_streaming_eval_state_validation():
    model = _eval_model()
    rows = _ranking_rows(n_users=8)
    _, state = StreamingRecEval(model, batch_size=4).evaluate(
        rows, max_batches=1)
    with pytest.raises(ValueError, match="version"):
        StreamingRecEval(model, batch_size=4).evaluate(
            rows, state={**state, "version": 99})
    from bigdl_tpu.optim.validation import HitRatio
    with pytest.raises(ValueError, match="same method list"):
        StreamingRecEval(model, methods=[HitRatio(5)],
                         batch_size=4).evaluate(rows, state=state)


def test_streaming_eval_over_mixed_dataset_resumes():
    from bigdl_tpu.data.mixing import MixedDataSet
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import DataSet, Sample

    model = _eval_model()

    def child(rows):
        return DataSet.array(
            [Sample(rows[i], 1) for i in range(rows.shape[0])],
            shuffle=False)

    def mixed():
        a = child(_ranking_rows(n_users=12, seed=21))
        b = child(_ranking_rows(n_users=12, seed=22))
        return (MixedDataSet([a, b], weights=[1, 1], seed=77)
                .transform(SampleToMiniBatch(4)))

    oneshot, _ = StreamingRecEval(model).evaluate(mixed())
    results, state = None, None
    while results is None:
        results, state = StreamingRecEval(model).evaluate(
            mixed(), state=state, max_batches=2)
    for a, b in zip(oneshot, results):
        assert abs(a.result()[0] - b.result()[0]) <= 1e-6
    # a differently-configured mixture must be rejected on resume
    _, mid = StreamingRecEval(model).evaluate(mixed(), max_batches=1)
    a = child(_ranking_rows(n_users=12, seed=21))
    b = child(_ranking_rows(n_users=12, seed=22))
    other = (MixedDataSet([a, b], weights=[3, 1], seed=77)
             .transform(SampleToMiniBatch(4)))
    with pytest.raises(ValueError, match="mixing"):
        StreamingRecEval(model).evaluate(other, state=mid)


# ---------------------------------------------------------------------------
# synthetic 100M-row-scale id stream
# ---------------------------------------------------------------------------

def test_synthetic_id_stream_deterministic_labels():
    from bigdl_tpu.dataset.movielens import synthetic_id_stream
    a = list(synthetic_id_stream(n_users=1000, n_items=400,
                                 batch_size=64, batches=3, seed=7))
    b = list(synthetic_id_stream(n_users=1000, n_items=400,
                                 batch_size=64, batches=3, seed=7))
    assert len(a) == 3
    for (pa, la), (pb, lb) in zip(a, b):
        assert pa.dtype == np.int32 and la.dtype == np.float32
        assert pa.shape == (64, 2) and la.shape == (64, 1)
        np.testing.assert_array_equal(pa, pb)
        np.testing.assert_array_equal(la, lb)
        assert pa.min() >= 1
    # labels are a pure function of the pair — ACROSS seeds too
    seen = {}
    for seed in (1, 2):
        for p, l in synthetic_id_stream(n_users=5, n_items=3,
                                        batch_size=256, batches=2,
                                        seed=seed):
            for (u, i), y in zip(p, l[:, 0]):
                assert seen.setdefault((int(u), int(i)),
                                       float(y)) == float(y)
    # the default id space is the 100M-row scale and stays int32
    p, _ = next(synthetic_id_stream(batch_size=8, batches=1))
    assert p.dtype == np.int32
    with pytest.raises(ValueError, match="int32"):
        next(synthetic_id_stream(n_users=2 ** 40, batches=1))


# ---------------------------------------------------------------------------
# serving: shard affinity + end-to-end scored request
# ---------------------------------------------------------------------------

def test_shard_affinity_same_shard_sessions_share_home(tmp_path):
    from bigdl_tpu.serving import Replica, Router

    class _FakeTarget:
        def submit_generate_async(self, prompt, max_new_tokens,
                                  eos_id=None, on_token=None,
                                  timeout=None):
            from concurrent.futures import Future
            f = Future()
            f.set_result(np.zeros(1, np.float32))
            return f

        def shutdown(self, drain=True, timeout=None):
            pass

        def admitted_outstanding(self):
            return 0

        def queue_depth(self):
            return 0

        def stats(self):
            return {"slots": 2}

    d = str(tmp_path)
    reps = [Replica(i, _FakeTarget(), snapshot_dir=d,
                    publish_interval_s=0.05) for i in (0, 1, 2)]
    router = Router(replicas=reps, snapshot_dir=d, start=False,
                    poll_interval_s=0.01)
    try:
        # every user in one shard's row block produces the SAME key,
        # hence the same home replica (warm rows stay warm)
        for shard in range(8):
            users = [shard * 8 + k + 1 for k in (0, 3, 7)]  # 64 rows/8
            keys = {shard_affinity_key(u, 64, 8) for u in users}
            assert len(keys) == 1
            homes = {router._ring.preference(k)[0] for k in keys}
            assert len(homes) == 1
        # distinct shards spread: not everything lands on one replica
        all_homes = {router._ring.preference(
            shard_affinity_key(s * 8 + 1, 64, 8))[0] for s in range(8)}
        assert len(all_homes) > 1
    finally:
        # close_replicas=True: the fakes shut down cleanly, and leaving
        # three 20Hz publisher threads running would tax every later
        # test in the suite on a small box
        router.shutdown(drain=False)


@pytest.mark.slow
def test_scored_request_end_to_end_through_router(tmp_path):
    from bigdl_tpu.serving import Replica, Router

    set_seed(9)
    model = zoo("wide_and_deep")
    scorer = RecommenderScorer(model, max_batch=4)
    d = str(tmp_path)
    rep = Replica(0, scorer, snapshot_dir=d, publish_interval_s=0.05)
    router = Router(replicas=[rep], snapshot_dir=d, poll_interval_s=0.01)
    try:
        user, item = 17, 5
        key = shard_affinity_key(user, 256, 8, model="wide_and_deep")
        fut = router.submit_generate_async(
            np.asarray([user, item], np.int32), 1, session=key)
        score = np.asarray(fut.result(120))
        expected = np.asarray(model.forward(
            jnp.asarray([[user, item]], jnp.int32)))[0]
        np.testing.assert_allclose(score, expected, rtol=1e-5, atol=1e-6)
        assert 0.0 <= float(score.reshape(())) <= 1.0
    finally:
        router.shutdown()


def test_zoo_entry():
    from bigdl_tpu.models import zoo_sample_shape
    m = zoo("wide_and_deep")
    assert isinstance(m, WideAndDeep)
    assert zoo_sample_shape("wide_and_deep") == (2,)
    out = np.asarray(m.forward(jnp.asarray([[1, 1], [256, 128]],
                                           jnp.int32)))
    assert out.shape == (2, 1)
    assert np.all((out >= 0) & (out <= 1))
