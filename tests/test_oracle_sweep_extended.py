"""Extended numerical-oracle sweep: criterions, recurrent cells, and
layer-zoo tail vs torch CPU, plus the zoo-wide coverage manifest.

Widens tests/test_layers_torch_oracle.py toward the reference's per-layer
spec density (reference: spark/dl/src/test/.../nn/ has ~205 per-layer
specs and integration/torch/TH.scala drives a live Torch7 oracle; here
torch-cpu is the in-process oracle).  Criterions compare loss VALUES and
input GRADIENTS; recurrent cells run full sequences through Recurrent()
against a hand-rolled torch time loop (fwd + grads).

The manifest test at the bottom classifies EVERY public nn export:
oracle-swept here or in the base file, covered by a named test file
(claim verified against that file's source), or waived with a reason.
Adding a new export without classifying it fails the suite.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn

RTOL, ATOL = 1e-4, 1e-5


def rnd(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def pos(*shape, seed=0, lo=0.05, hi=0.95):
    r = np.random.RandomState(seed).uniform(lo, hi, shape)
    return r.astype(np.float32)


def classes(n, k, seed=0):
    """1-based class targets, reference convention."""
    return np.random.RandomState(seed).randint(1, k + 1, n).astype(np.int64)


def signs(*shape, seed=0):
    return np.where(np.random.RandomState(seed).rand(*shape) > 0.5,
                    1.0, -1.0).astype(np.float32)


# ---------------------------------------------------------------------------
# Criterion sweep: (name, make_ours, torch_fn(inp..., target), make_data)
# make_data -> (inputs_list, target); a list of >1 inputs is passed as a
# table.  torch_fn receives torch tensors mirroring (inputs..., target).
# ---------------------------------------------------------------------------

def _t(x):
    return torch.tensor(x)


CRITERION_SWEEP = [
    ("AbsCriterion", lambda: nn.AbsCriterion(),
     lambda x, t: F.l1_loss(x, t),
     lambda: ([rnd(4, 5, seed=1)], rnd(4, 5, seed=2))),
    ("MSECriterion", lambda: nn.MSECriterion(),
     lambda x, t: F.mse_loss(x, t),
     lambda: ([rnd(4, 5, seed=3)], rnd(4, 5, seed=4))),
    ("SmoothL1Criterion", lambda: nn.SmoothL1Criterion(),
     lambda x, t: F.smooth_l1_loss(x, t),
     lambda: ([rnd(4, 5, seed=5)], rnd(4, 5, seed=6))),
    ("BCECriterion", lambda: nn.BCECriterion(),
     lambda x, t: F.binary_cross_entropy(x, t),
     lambda: ([pos(4, 5, seed=7)], pos(4, 5, seed=8))),
    ("ClassNLLCriterion", lambda: nn.ClassNLLCriterion(),
     lambda x, t: F.nll_loss(x, t.long() - 1),
     lambda: ([np.log(pos(4, 6, seed=9))], classes(4, 6, seed=10))),
    ("CrossEntropyCriterion", lambda: nn.CrossEntropyCriterion(),
     lambda x, t: F.cross_entropy(x, t.long() - 1),
     lambda: ([rnd(4, 6, seed=11)], classes(4, 6, seed=12))),
    ("CategoricalCrossEntropy", lambda: nn.CategoricalCrossEntropy(),
     lambda x, t: -(t * x.clamp(1e-8, 1.0).log()).sum(-1).mean(),
     lambda: ([pos(4, 6, seed=13)],
              np.eye(6, dtype=np.float32)[classes(4, 6, seed=14) - 1])),
    ("DistKLDivCriterion", lambda: nn.DistKLDivCriterion(),
     lambda x, t: F.kl_div(x, t, reduction="mean"),
     lambda: ([np.log(pos(4, 6, seed=15))], pos(4, 6, seed=16))),
    ("SoftMarginCriterion", lambda: nn.SoftMarginCriterion(),
     lambda x, t: F.soft_margin_loss(x, t),
     lambda: ([rnd(4, 5, seed=17)], signs(4, 5, seed=18))),
    ("MarginCriterion", lambda: nn.MarginCriterion(),
     lambda x, t: F.relu(1.0 - x * t).mean(),
     lambda: ([rnd(4, 5, seed=19)], signs(4, 5, seed=20))),
    ("MarginCriterion_squared",
     lambda: nn.MarginCriterion(squared=True),
     lambda x, t: F.relu(1.0 - x * t).pow(2).mean(),
     lambda: ([rnd(4, 5, seed=21)], signs(4, 5, seed=22))),
    ("HingeEmbeddingCriterion", lambda: nn.HingeEmbeddingCriterion(1.0),
     lambda x, t: F.hinge_embedding_loss(x, t, margin=1.0),
     lambda: ([np.abs(rnd(4, 5, seed=23))], signs(4, 5, seed=24))),
    ("MarginRankingCriterion", lambda: nn.MarginRankingCriterion(1.0),
     lambda a, b, t: F.margin_ranking_loss(a, b, t, margin=1.0),
     lambda: ([rnd(6, seed=25), rnd(6, seed=26)], signs(6, seed=27))),
    ("CosineEmbeddingCriterion",
     lambda: nn.CosineEmbeddingCriterion(0.1),
     lambda a, b, t: F.cosine_embedding_loss(a, b, t, margin=0.1),
     lambda: ([rnd(5, 8, seed=28), rnd(5, 8, seed=29)],
              signs(5, seed=30))),
    ("L1HingeEmbeddingCriterion",
     lambda: nn.L1HingeEmbeddingCriterion(1.0),
     lambda a, b, t: torch.where(
         t > 0, (a - b).abs().sum(-1),
         F.relu(1.0 - (a - b).abs().sum(-1))).sum(),
     lambda: ([rnd(5, 8, seed=31), rnd(5, 8, seed=32)],
              signs(5, seed=33))),
    ("MultiLabelSoftMarginCriterion",
     lambda: nn.MultiLabelSoftMarginCriterion(),
     lambda x, t: F.multilabel_soft_margin_loss(x, t),
     lambda: ([rnd(4, 6, seed=34)],
              (np.random.RandomState(35).rand(4, 6) > 0.5
               ).astype(np.float32))),
    ("MultiMarginCriterion", lambda: nn.MultiMarginCriterion(),
     lambda x, t: F.multi_margin_loss(x, t.long() - 1, margin=1.0),
     lambda: ([rnd(4, 6, seed=36)], classes(4, 6, seed=37))),
    ("MultiMarginCriterion_p2",
     lambda: nn.MultiMarginCriterion(p=2),
     lambda x, t: F.multi_margin_loss(x, t.long() - 1, p=2, margin=1.0),
     lambda: ([rnd(4, 6, seed=38)], classes(4, 6, seed=39))),
    ("CosineDistanceCriterion", lambda: nn.CosineDistanceCriterion(),
     lambda x, t: (1.0 - F.cosine_similarity(x, t, dim=-1)).mean(),
     lambda: ([rnd(5, 8, seed=40)], rnd(5, 8, seed=41))),
    ("CosineProximityCriterion",
     lambda: nn.CosineProximityCriterion(),
     lambda x, t: -(F.normalize(x, dim=-1)
                    * F.normalize(t, dim=-1)).sum(-1).mean(),
     lambda: ([rnd(5, 8, seed=42)], rnd(5, 8, seed=43))),
    ("DotProductCriterion", lambda: nn.DotProductCriterion(),
     lambda x, t: -(x * t).sum(),
     lambda: ([rnd(4, 5, seed=44)], rnd(4, 5, seed=45))),
    ("PoissonCriterion", lambda: nn.PoissonCriterion(),
     lambda x, t: F.poisson_nll_loss(x, t, log_input=False, eps=1e-8),
     lambda: ([pos(4, 5, seed=46, lo=0.2, hi=3.0)],
              pos(4, 5, seed=47, lo=0.0, hi=4.0))),
    ("MeanAbsolutePercentageCriterion",
     lambda: nn.MeanAbsolutePercentageCriterion(),
     lambda x, t: 100.0 * ((t - x).abs()
                           / t.abs().clamp(min=1e-7)).mean(),
     lambda: ([rnd(4, 5, seed=48)], rnd(4, 5, seed=49))),
    ("MeanSquaredLogarithmicCriterion",
     lambda: nn.MeanSquaredLogarithmicCriterion(),
     lambda x, t: ((x.clamp(min=1e-7) + 1).log()
                   - (t.clamp(min=1e-7) + 1).log()).pow(2).mean(),
     lambda: ([pos(4, 5, seed=50, lo=0.1, hi=3.0)],
              pos(4, 5, seed=51, lo=0.1, hi=3.0))),
    ("KullbackLeiblerDivergenceCriterion",
     lambda: nn.KullbackLeiblerDivergenceCriterion(),
     lambda x, t: (t.clamp(1e-7, 1.0)
                   * (t.clamp(1e-7, 1.0).log()
                      - x.clamp(1e-7, 1.0).log())).sum(-1).mean(),
     lambda: ([pos(4, 6, seed=52)], pos(4, 6, seed=53))),
    ("MultiLabelMarginCriterion",
     lambda: nn.MultiLabelMarginCriterion(),
     # torch targets are 0-based padded with -1; ours 1-based padded 0,
     # so t-1 maps exactly
     lambda x, t: F.multilabel_margin_loss(x, t.long() - 1),
     lambda: ([rnd(4, 6, seed=110)],
              np.stack([np.concatenate([
                  np.random.RandomState(111 + i).choice(
                      np.arange(1, 7), 2, replace=False),
                  np.zeros(4)]).astype(np.int64) for i in range(4)]))),
    ("L1Cost", lambda: nn.L1Cost(),
     lambda x, t: x.abs().sum(),
     lambda: ([rnd(4, 5, seed=54)], rnd(4, 5, seed=55))),
    ("DiceCoefficientCriterion",
     lambda: nn.DiceCoefficientCriterion(epsilon=1.0),
     lambda x, t: (1.0 - (2.0 * (x * t).sum(1) + 1.0)
                   / (x.sum(1) + t.sum(1) + 1.0)).mean(),
     lambda: ([pos(4, 10, seed=56)],
              (np.random.RandomState(57).rand(4, 10) > 0.5
               ).astype(np.float32))),
    ("PGCriterion", lambda: nn.PGCriterion(),
     lambda x, t: -(x.clamp(1e-8, 1.0).log() * t).sum(),
     lambda: ([pos(4, 5, seed=58)], rnd(4, 5, seed=59))),
    ("KLDCriterion", lambda: nn.KLDCriterion(),
     lambda m, lv, t: 0.5 * (m.pow(2) + lv.exp() - lv - 1.0).sum(),
     lambda: ([rnd(4, 6, seed=60), rnd(4, 6, seed=61) * 0.3],
              rnd(4, 6, seed=62))),
    ("GaussianCriterion", lambda: nn.GaussianCriterion(),
     lambda m, lv, t: 0.5 * (lv + (t - m).pow(2) / lv.exp()
                             + np.log(2 * np.pi)).sum(),
     lambda: ([rnd(4, 6, seed=63), rnd(4, 6, seed=64) * 0.3],
              rnd(4, 6, seed=65))),
    ("ClassSimplexCriterion", lambda: nn.ClassSimplexCriterion(5),
     lambda x, t, o=None: None,  # torch fn built per-instance below
     lambda: ([rnd(4, 5, seed=66)], classes(4, 5, seed=67))),
    ("TimeDistributedCriterion",
     lambda: nn.TimeDistributedCriterion(nn.MSECriterion()),
     lambda x, t: sum(F.mse_loss(x[:, i], t[:, i])
                      for i in range(x.shape[1])),
     lambda: ([rnd(3, 4, 5, seed=68)], rnd(3, 4, 5, seed=69))),
    ("MultiCriterion",
     lambda: nn.MultiCriterion().add(nn.MSECriterion(), 0.5).add(
         nn.AbsCriterion(), 2.0),
     lambda x, t: 0.5 * F.mse_loss(x, t) + 2.0 * F.l1_loss(x, t),
     lambda: ([rnd(4, 5, seed=70)], rnd(4, 5, seed=71))),
]


@pytest.mark.parametrize("case", CRITERION_SWEEP, ids=lambda c: c[0])
def test_criterion_sweep_value_and_grad(case):
    name, make_ours, tfn, make_data = case
    ours = make_ours()
    inputs, target = make_data()
    jx = [jnp.asarray(a) for a in inputs]
    tx = [torch.tensor(a, requires_grad=True) for a in inputs]
    tt = _t(target)

    if name == "ClassSimplexCriterion":
        # torch mirror needs the instance's simplex embedding buffer
        simplex = torch.tensor(np.asarray(ours.simplex))

        def tfn(x, t):
            emb = simplex[t.long() - 1]
            return (x - emb).pow(2).sum(-1).mean()

    def fwd(args):
        inp = args[0] if len(args) == 1 else list(args)
        return ours.forward(inp, jnp.asarray(target))

    out = float(fwd(jx))
    tout = tfn(*tx, tt)
    np.testing.assert_allclose(out, float(tout), rtol=RTOL, atol=ATOL,
                               err_msg=f"{name}: loss value")

    gs = jax.grad(lambda args: fwd(args))(tuple(jx))
    tout.backward()
    for i, (g, t) in enumerate(zip(gs, tx)):
        np.testing.assert_allclose(
            np.asarray(g), t.grad.numpy(), rtol=RTOL, atol=ATOL,
            err_msg=f"{name}: grad of input {i}")


# ---------------------------------------------------------------------------
# Recurrent cells: full sequences through Recurrent(cell) vs a torch
# time loop with copied weights (fwd + input grads).
# ---------------------------------------------------------------------------

def _torch_rnn_loop(step, x, state):
    outs = []
    for t in range(x.shape[1]):
        out, state = step(x[:, t], state)
        outs.append(out)
    return torch.stack(outs, dim=1)


def _np(p):
    return torch.tensor(np.asarray(p))


CELL_SWEEP = [
    ("RnnCell", lambda: nn.RnnCell(6, 5),
     lambda c: (lambda x: _torch_rnn_loop(
         lambda xt, h: ((lambda hn: (hn, hn))(
             torch.tanh(xt @ _np(c.w_input) + _np(c.bias)
                        + h @ _np(c.w_hidden)))),
         x, torch.zeros(x.shape[0], 5)))),
    ("LSTM", lambda: nn.LSTM(6, 5),
     lambda c: (lambda x: _torch_rnn_loop(
         lambda xt, st: (lambda gates: (lambda i, f, g, o: (
             lambda cn: (torch.sigmoid(o) * torch.tanh(cn),
                         (torch.sigmoid(o) * torch.tanh(cn), cn)))(
             torch.sigmoid(f) * st[1]
             + torch.sigmoid(i) * torch.tanh(g)))(
             *gates.chunk(4, dim=-1)))(
             xt @ _np(c.w_input) + _np(c.bias)
             + st[0] @ _np(c.w_hidden)),
         x, (torch.zeros(x.shape[0], 5), torch.zeros(x.shape[0], 5))))),
    ("LSTMPeephole", lambda: nn.LSTMPeephole(6, 5),
     lambda c: (lambda x: _torch_rnn_loop(
         lambda xt, st: (lambda gates: (lambda ii, ff, gg, oo: (
             lambda i, f: (lambda cn: (lambda o:
                           (o * torch.tanh(cn), (o * torch.tanh(cn), cn)))(
                 torch.sigmoid(oo + _np(c.peep_o) * cn)))(
                 f * st[1] + i * torch.tanh(gg)))(
             torch.sigmoid(ii + _np(c.peep_i) * st[1]),
             torch.sigmoid(ff + _np(c.peep_f) * st[1])))(
             *gates.chunk(4, dim=-1)))(
             xt @ _np(c.w_input) + _np(c.bias)
             + st[0] @ _np(c.w_hidden)),
         x, (torch.zeros(x.shape[0], 5), torch.zeros(x.shape[0], 5))))),
    ("GRU", lambda: nn.GRU(6, 5),
     lambda c: (lambda x: _torch_rnn_loop(
         lambda xt, h: (lambda xp: (lambda rz: (lambda r, z: (
             lambda g: ((1 - z) * g + z * h, (1 - z) * g + z * h))(
             torch.tanh(xp[..., 10:] + (r * h) @ _np(c.w_candidate))))(
             *rz.chunk(2, dim=-1)))(
             torch.sigmoid(xp[..., :10] + h @ _np(c.w_hidden))))(
             xt @ _np(c.w_input) + _np(c.bias)),
         x, torch.zeros(x.shape[0], 5)))),
]


@pytest.mark.parametrize("case", CELL_SWEEP, ids=lambda c: c[0])
def test_recurrent_cell_sweep(case):
    name, make_cell, make_torch = case
    from bigdl_tpu.utils import set_seed
    set_seed(hash(name) % 10000)
    cell = make_cell().eval_mode()
    rec = nn.Recurrent(cell).eval_mode()
    x = rnd(3, 4, 6, seed=80)
    tfn = make_torch(cell)

    jx = jnp.asarray(x)
    tx = torch.tensor(x, requires_grad=True)
    out = rec(jx)
    tout = tfn(tx)
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=RTOL, atol=ATOL,
                               err_msg=f"{name}: forward")

    g = jax.grad(lambda a: jnp.sum(rec(a) ** 2))(jx)
    (tout ** 2).sum().backward()
    np.testing.assert_allclose(np.asarray(g), tx.grad.numpy(),
                               rtol=1e-3, atol=1e-4,
                               err_msg=f"{name}: input grad")


def test_multi_rnn_cell_matches_composition():
    """MultiRNNCell([a, b]) == feeding a's output stream into b."""
    from bigdl_tpu.utils import set_seed
    set_seed(2)
    a = nn.RnnCell(6, 6)
    b = nn.RnnCell(6, 5)
    stack = nn.Recurrent(nn.MultiRNNCell([a, b])).eval_mode()
    x = jnp.asarray(rnd(3, 4, 6, seed=81))
    out = stack(x)
    ref = nn.Recurrent(b).eval_mode()(nn.Recurrent(a).eval_mode()(x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


# ---------------------------------------------------------------------------
# Layer-zoo tail rows (same harness shape as the base SWEEP)
# ---------------------------------------------------------------------------

EXTRA_SWEEP = [
    ("Swish", lambda: nn.Swish(), lambda o: F.silu,
     lambda: [rnd(3, 6, seed=90)]),
    ("BinaryThreshold", lambda: nn.BinaryThreshold(0.2),
     lambda o: (lambda x: (x > 0.2).float() + x * 0),
     lambda: [rnd(3, 6, seed=91)]),
    ("Flatten", lambda: nn.Flatten(),
     lambda o: (lambda x: x.reshape(x.shape[0], -1)),
     lambda: [rnd(3, 4, 5, seed=92)]),
    ("Echo", lambda: nn.Echo(), lambda o: (lambda x: x),
     lambda: [rnd(3, 4, seed=93)]),
    ("GlobalAveragePooling2D", lambda: nn.GlobalAveragePooling2D(),
     lambda o: (lambda x: x.mean(dim=(1, 2))),
     lambda: [rnd(2, 5, 5, 3, seed=94)]),
    ("GlobalAveragePooling3D", lambda: nn.GlobalAveragePooling3D(),
     lambda o: (lambda x: x.mean(dim=(1, 2, 3))),
     lambda: [rnd(2, 4, 4, 4, 3, seed=95)]),
    ("GlobalMaxPooling3D", lambda: nn.GlobalMaxPooling3D(),
     lambda o: (lambda x: x.amax(dim=(1, 2, 3))),
     lambda: [rnd(2, 4, 4, 4, 3, seed=96)]),
    ("GroupNorm", lambda: nn.GroupNorm(8, n_groups=4),
     lambda o: (lambda x: F.group_norm(
         x.permute(0, 3, 1, 2), 4,
         _np(o.weight), _np(o.bias), eps=1e-5).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 5, 5, 8, seed=97)]),
    ("SReLU", lambda: nn.SReLU((6,)),
     lambda o: (lambda x: (lambda y: torch.where(
         y <= _np(o.t_left),
         _np(o.t_left) + _np(o.a_left) * (y - _np(o.t_left)), y))(
         torch.where(x >= _np(o.t_right),
                     _np(o.t_right) + _np(o.a_right) * (x - _np(o.t_right)),
                     x))),
     lambda: [rnd(3, 6, seed=98) * 2]),
    ("Highway", lambda: nn.Highway(5, activation=nn.ReLU()),
     lambda o: (lambda x: (lambda t, h: t * h + (1 - t) * x)(
         torch.sigmoid(F.linear(x, _np(o.gate.weight), _np(o.gate.bias))),
         F.relu(F.linear(x, _np(o.transform.weight),
                         _np(o.transform.bias))))),
     lambda: [rnd(4, 5, seed=99)]),
    ("InferReshape", lambda: nn.InferReshape((0, -1), batch_mode=False),
     lambda o: (lambda x: x.reshape(x.shape[0], -1)),
     lambda: [rnd(3, 4, 5, seed=100)]),
    ("Scale", lambda: nn.Scale((4,)),
     lambda o: (lambda x: x * _np(o.cmul.weight) + _np(o.cadd.bias)),
     lambda: [rnd(3, 4, seed=101)]),
    ("TimeDistributed", lambda: nn.TimeDistributed(nn.Linear(5, 3)),
     lambda o: (lambda x: F.linear(x, _np(o.layer.weight),
                                   _np(o.layer.bias))),
     lambda: [rnd(3, 4, 5, seed=102)]),
    ("SpatialShareConvolution",
     lambda: nn.SpatialShareConvolution(3, 6, 3, 3, 1, 1, 1, 1),
     lambda o: (lambda x: F.conv2d(
         x.permute(0, 3, 1, 2),
         _np(np.transpose(np.asarray(o.weight), (3, 2, 0, 1))),
         _np(o.bias), padding=1).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 6, 6, 3, seed=103)]),
    ("ResizeBilinear_align",
     lambda: nn.ResizeBilinear(7, 9, align_corners=True),
     lambda o: (lambda x: F.interpolate(
         x.permute(0, 3, 1, 2), size=(7, 9), mode="bilinear",
         align_corners=True).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 4, 5, 3, seed=104)]),
]


@pytest.mark.parametrize("case", EXTRA_SWEEP, ids=lambda c: c[0])
def test_extra_layer_sweep(case):
    name, make_ours, make_torch, make_inputs = case
    from bigdl_tpu.utils import set_seed
    set_seed(sum(map(ord, name)) % 7919)
    ours = make_ours().eval_mode()
    tfn = make_torch(ours)
    inputs = make_inputs()
    jx = [jnp.asarray(a) for a in inputs]
    tx = [torch.tensor(a, requires_grad=True) for a in inputs]

    out = ours.forward(jx[0] if len(jx) == 1 else list(jx))
    tout = tfn(*tx)
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=RTOL, atol=ATOL,
                               err_msg=f"{name}: forward")

    gs = jax.grad(lambda args: jnp.sum(
        ours.forward(args[0] if len(args) == 1 else list(args)) ** 2))(
        tuple(jx))
    (tout ** 2).sum().backward()
    for i, (g, t) in enumerate(zip(gs, tx)):
        if t.grad is None:
            continue  # non-differentiable path (e.g. thresholds)
        np.testing.assert_allclose(np.asarray(g), t.grad.numpy(),
                                   rtol=1e-3, atol=1e-4,
                                   err_msg=f"{name}: grad of input {i}")


def test_gradient_reversal_flips_and_scales():
    """No torch counterpart needed: the contract IS the gradient."""
    layer = nn.GradientReversal(0.7)
    x = jnp.asarray(rnd(3, 4, seed=105))
    np.testing.assert_allclose(np.asarray(layer(x)), np.asarray(x))
    g = jax.grad(lambda a: jnp.sum(layer(a)))(x)
    np.testing.assert_allclose(np.asarray(g), -0.7 * np.ones_like(x),
                               rtol=1e-6, atol=1e-6)


def test_penalty_layers_record_loss():
    """L1Penalty / ActivityRegularization / NegativeEntropyPenalty are
    identity forwards whose penalty value must match the formula."""
    x = jnp.asarray(rnd(3, 4, seed=106))
    l1 = nn.L1Penalty(0.5)
    np.testing.assert_allclose(np.asarray(l1(x)), np.asarray(x))
    np.testing.assert_allclose(float(l1.loss),
                               0.5 * float(jnp.sum(jnp.abs(x))), rtol=1e-6)
    ar = nn.ActivityRegularization(l1=0.3, l2=0.7)
    ar(x)
    np.testing.assert_allclose(
        float(ar.loss),
        0.3 * float(jnp.sum(jnp.abs(x))) + 0.7 * float(jnp.sum(x * x)),
        rtol=1e-6)
    p = jnp.asarray(pos(3, 4, seed=107))
    p = p / jnp.sum(p, -1, keepdims=True)
    ne = nn.NegativeEntropyPenalty(0.2)
    ne(p)
    np.testing.assert_allclose(
        float(ne.loss), 0.2 * float(jnp.sum(p * jnp.log(p))), rtol=1e-5)


# ---------------------------------------------------------------------------
# Coverage manifest: every public nn export is classified.
# ---------------------------------------------------------------------------

# covered by a DEDICATED oracle test in the base file (function-style
# tests there, not table rows)
BASE_DEDICATED = {
    "Linear", "SpatialConvolution", "SpatialFullConvolution",
}

# name -> test file that covers it (claim VERIFIED against file source)
ELSEWHERE = {
    # detection stack
    "Anchor": "test_detection.py",
    "PriorBox": "test_detection.py", "Proposal": "test_detection.py",
    "RegionProposal": "test_detection.py",
    "DetectionOutputSSD": "test_detection.py",
    "BoxHead": "test_detection.py", "MaskHead": "test_detection.py",
    "FPN": "test_detection.py", "Pooler": "test_detection.py",
    "RoiAlign": "test_detection.py", "RoiPooling": "test_detection.py",
    "SmoothL1CriterionWithWeights": "test_detection.py",
    "SoftmaxWithCriterion": "test_detection.py",
    # attention / transformer stack (oracled vs torch SDPA there)
    "Attention": "test_attention.py",
    "FeedForwardNetwork": "test_serializer.py",
    "Transformer": "test_transformer_lm.py",
    "TransformerEncoderLayer": "test_parallel.py",
    "TransformerDecoderLayer": "test_attention.py",
    "SequenceBeamSearch": "test_attention.py",
    # sparse / tree
    "SparseTensor": "test_sparse_tree_misc.py",
    "SparseLinear": "test_sparse_tree_misc.py",
    "SparseJoinTable": "test_sparse_tree_misc.py",
    "LookupTableSparse": "test_sparse_tree_misc.py",
    "DenseToSparse": "test_sparse_tree_misc.py",
    "TreeLSTM": "test_sparse_tree_misc.py",
    "BinaryTreeLSTM": "test_sparse_tree_misc.py",
    # int8 (fidelity harness is the oracle)
    "Quantizer": "test_quantized.py",
    "QuantizedLinear": "test_quantized.py",
    "QuantizedSpatialConvolution": "test_quantized.py",
    "TableOperation": "test_t7_table_metrics.py",
    # parallel / moe
    "MoE": "test_parallel.py",
    # containers & recurrent variants exercised with numerics elsewhere
    "Sequential": "test_optim.py",
    "ConvLSTMPeephole3D": "test_sparse_tree_misc.py",
    "LocallyConnected1D": "test_keras.py",
    "LocallyConnected2D": "test_keras.py",
    "SpatialConvolutionMap": "test_sparse_tree_misc.py",
    "SpatialSubtractiveNormalization": "test_sparse_tree_misc.py",
    "SpatialDivisiveNormalization": "test_sparse_tree_misc.py",
    "SpatialContrastiveNormalization": "test_sparse_tree_misc.py",
    "BatchNormalization": "test_optim.py",
    "ParallelCriterion": "test_criterions.py",
}

# name -> why no torch oracle applies (abstract bases, stochastic
# layers, debug aids)
WAIVED = {
    "Module": "abstract base (infrastructure, not a layer)",
    "ModuleList": "container infrastructure",
    "Container": "abstract base",
    "Criterion": "abstract base",
    "Cell": "abstract recurrent base",
    "Node": "graph-DSL infrastructure",
    "RNN": "alias wrapper over Recurrent(RnnCell) — both oracled",
    "SpatialDropout1D": "stochastic; eval-identity + mask shape are the "
                        "contract, locked in test_keras.py",
    "SpatialDropout2D": "stochastic; see SpatialDropout1D",
    "SpatialDropout3D": "stochastic; see SpatialDropout1D",
}


def _nn_exports():
    import glob
    import os
    names = set()
    pat = os.path.join(os.path.dirname(nn.__file__), "*.py")
    for f in glob.glob(pat):
        src = open(f).read()
        m = re.search(r"__all__\s*=\s*\[([^\]]*)\]", src, re.S)
        if m:
            names |= set(re.findall(r'"([A-Za-z0-9_]+)"', m.group(1)))
    return {n for n in names if n[:1].isupper()}


def _table_names(table):
    return {row[0].split("_")[0] for row in table}


def test_zoo_coverage_manifest():
    """Every public nn export must be oracle-swept, covered by a named
    test file (verified), or waived with a reason."""
    import os
    from tests.test_layers_torch_oracle import SWEEP

    here = os.path.dirname(os.path.abspath(__file__))
    this_src = open(os.path.join(here, "test_oracle_sweep_extended.py")
                    ).read()
    base_src = open(os.path.join(here, "test_layers_torch_oracle.py")
                    ).read()

    oracled = (_table_names(SWEEP) | _table_names(CRITERION_SWEEP)
               | _table_names(CELL_SWEEP) | _table_names(EXTRA_SWEEP)
               | BASE_DEDICATED)
    # dedicated function-style tests in either oracle file also count
    for src in (this_src, base_src):
        oracled |= set(re.findall(r"nn\.([A-Z][A-Za-z0-9]*)\(", src))

    exports = _nn_exports()
    unclassified = sorted(
        exports - oracled - set(ELSEWHERE) - set(WAIVED))
    assert not unclassified, (
        f"unclassified nn exports (add an oracle row, an ELSEWHERE "
        f"entry, or a waiver): {unclassified}")

    # ELSEWHERE claims must be true: the named file must reference the
    # name (guards against stale claims as tests move)
    for name, fname in ELSEWHERE.items():
        path = os.path.join(here, fname)
        assert os.path.exists(path), f"{name}: {fname} does not exist"
        src = open(path).read()
        assert re.search(rf"\b{name}\b", src), (
            f"ELSEWHERE claims {name} is covered by {fname}, but that "
            f"file never mentions it")

    # no double-booking between waivers and real coverage
    assert not (set(WAIVED) & oracled)


# ---------------------------------------------------------------------------
# Behavior oracles for names no other test exercised (found by this
# file's manifest audit): table algebra, containers, detection post-ops,
# stochastic/autoregressive layers.
# ---------------------------------------------------------------------------

def test_table_ops_semantics():
    a, b, c = (jnp.asarray(rnd(3, 4, seed=120 + i)) for i in range(3))

    assert all(np.allclose(x, y) for x, y in zip(
        nn.ConcatTable(nn.Identity(), nn.Identity())(a), (a, a)))
    pt = nn.ParallelTable(nn.ReLU(), nn.Tanh())([a, b])
    np.testing.assert_allclose(pt[0], np.maximum(np.asarray(a), 0))
    np.testing.assert_allclose(pt[1], np.tanh(np.asarray(b)), rtol=1e-6)
    mt = nn.MapTable(nn.ReLU())([a, b])
    np.testing.assert_allclose(mt[1], np.maximum(np.asarray(b), 0))
    np.testing.assert_allclose(nn.SelectTable(2)([a, b, c]), b)
    np.testing.assert_allclose(nn.SelectTable(-1)([a, b, c]), c)
    flat = nn.FlattenTable()([a, (b, (c,))])
    assert len(flat) == 3 and np.allclose(flat[2], c)
    nt = nn.NarrowTable(2, 2)([a, b, c])
    assert len(nt) == 2 and np.allclose(nt[0], b)

    parts = nn.SplitTable(2)(a)  # split dim 2 (1-based) -> 4 slices
    assert len(parts) == 4
    np.testing.assert_allclose(parts[1], np.asarray(a)[:, 1])
    lo, hi = nn.BifurcateSplitTable(2)(a)
    np.testing.assert_allclose(lo, np.asarray(a)[:, :2])
    np.testing.assert_allclose(hi, np.asarray(a)[:, 2:])

    g = jax.nn.softmax(jnp.asarray(rnd(3, 2, seed=123)))
    mix = nn.MixtureTable()([g, (a, b)])
    ref = (np.asarray(g)[:, :1] * np.asarray(a)
           + np.asarray(g)[:, 1:] * np.asarray(b))
    np.testing.assert_allclose(mix, ref, rtol=1e-5)

    cp = nn.CrossProduct()([a, b, c])
    ref = np.stack([np.sum(np.asarray(a) * np.asarray(b), -1),
                    np.sum(np.asarray(a) * np.asarray(c), -1),
                    np.sum(np.asarray(b) * np.asarray(c), -1)], -1)
    np.testing.assert_allclose(cp, ref, rtol=1e-5)
    # table algebra must be differentiable end to end
    gr = jax.grad(lambda x: jnp.sum(nn.CrossProduct()([x, b, c]) ** 2))(a)
    assert np.isfinite(np.asarray(gr)).all()


def test_concat_and_bottle_containers():
    from bigdl_tpu.utils import set_seed
    set_seed(9)
    l1, l2 = nn.Linear(4, 3), nn.Linear(4, 5)
    cat = nn.Concat(2, l1, l2)
    x = jnp.asarray(rnd(3, 4, seed=124))
    np.testing.assert_allclose(
        cat(x), np.concatenate([np.asarray(l1(x)), np.asarray(l2(x))], 1),
        rtol=1e-6)

    inner = nn.Linear(5, 2)
    bot = nn.Bottle(inner, 2, 2)
    y = jnp.asarray(rnd(3, 4, 5, seed=125))
    ref = np.asarray(inner(y.reshape(12, 5))).reshape(3, 4, 2)
    np.testing.assert_allclose(bot(y), ref, rtol=1e-6)


def test_nms_behavior():
    boxes = jnp.asarray(np.array([
        [0, 0, 10, 10], [1, 1, 10.5, 10.5],   # heavy overlap pair
        [20, 20, 30, 30],                      # isolated
        [0, 0, 10.2, 9.8],                     # overlaps the first pair
    ], np.float32))
    scores = jnp.asarray(np.array([0.9, 0.8, 0.95, 0.7], np.float32))
    keep, valid = nn.Nms(iou_threshold=0.5, max_output=4)(scores, boxes)
    kept = [int(k) for k, v in zip(keep, valid) if bool(v)]
    # score order: box2 (isolated), box0; boxes 1 and 3 suppressed
    assert kept == [2, 0], kept


def test_normalize_scale_matches_formula():
    layer = nn.NormalizeScale(p=2.0, scale=3.0, size=(5,))
    x = jnp.asarray(rnd(4, 5, seed=126))
    n = np.asarray(x) / (np.linalg.norm(np.asarray(x), axis=-1,
                                        keepdims=True) + 1e-10)
    np.testing.assert_allclose(layer(x), n * 3.0, rtol=1e-5)


def test_spatial_within_channel_lrn_matches_torch_compose():
    layer = nn.SpatialWithinChannelLRN(size=3, alpha=1.0, beta=0.75)
    x = rnd(2, 6, 6, 4, seed=127)
    tx = torch.tensor(np.transpose(x, (0, 3, 1, 2)))
    local_sum = F.avg_pool2d(tx * tx, 3, stride=1, padding=1,
                             count_include_pad=True) * 9.0
    ref = tx * (1.0 + (1.0 / 9.0) * local_sum).pow(-0.75)
    np.testing.assert_allclose(
        np.transpose(np.asarray(layer(jnp.asarray(x))), (0, 3, 1, 2)),
        ref.numpy(), rtol=1e-4, atol=1e-5)


def test_gaussian_sampler_reparameterization():
    from bigdl_tpu.core.module import forward_context
    mean = jnp.asarray(rnd(4, 6, seed=128))
    log_var = jnp.asarray(rnd(4, 6, seed=129) * 0.2)
    layer = nn.GaussianSampler()
    with forward_context(rng=jax.random.key(3)):
        z1 = layer([mean, log_var])
    with forward_context(rng=jax.random.key(3)):
        z2 = layer([mean, log_var])
    np.testing.assert_allclose(z1, z2)  # same rng -> same sample
    eps = (np.asarray(z1) - np.asarray(mean)) / np.exp(
        0.5 * np.asarray(log_var))
    assert np.abs(eps).max() < 6.0  # standardized residual is N(0,1)
    with pytest.raises(Exception):
        # stochastic layers must fail loudly without an rng context
        layer.train_mode()([mean, log_var])


def test_recurrent_decoder_feeds_back_output():
    from bigdl_tpu.utils import set_seed
    set_seed(4)
    cell = nn.RnnCell(5, 5)
    dec = nn.RecurrentDecoder(3, cell).eval_mode()
    x0 = jnp.asarray(rnd(2, 5, seed=130))
    out = dec(x0)
    # manual unroll: input of step t+1 is output of step t
    h = cell.init_state(2)
    inp, outs = x0, []
    for _ in range(3):
        o, h = cell.step(cell.precompute_inputs(inp), h)
        outs.append(np.asarray(o))
        inp = o
    np.testing.assert_allclose(np.asarray(out), np.stack(outs, 1),
                               rtol=1e-5, atol=1e-6)


def test_transformer_and_masked_criterion_wrappers():
    mse = nn.MSECriterion()
    tc = nn.TransformerCriterion(mse, input_transformer=nn.Tanh())
    x = jnp.asarray(rnd(3, 4, seed=131))
    t = jnp.asarray(rnd(3, 4, seed=132))
    np.testing.assert_allclose(
        float(tc(x, t)), float(mse(jnp.tanh(x), t)), rtol=1e-6)

    td = nn.TimeDistributedMaskCriterion(
        nn.ClassNLLCriterion(paddingValue=0))
    logp = jnp.asarray(np.log(pos(2, 3, 4, seed=133)))
    tgt = np.array([[1, 2, 0], [3, 0, 0]], np.int64)  # 0 = pad
    out = float(td(logp, jnp.asarray(tgt)))
    assert np.isfinite(out)
    # padded positions contribute nothing: changing their logits is a
    # no-op on the loss
    logp2 = logp.at[0, 2].set(logp[0, 2] - 5.0)
    np.testing.assert_allclose(out, float(td(logp2, jnp.asarray(tgt))),
                               rtol=1e-6)


def test_detection_output_frcnn_shapes_and_ranking():
    """Synthetic ROI-head outputs through the Faster-R-CNN post-op:
    fixed [max_per_image, 6] rows, finite, scores descending over the
    valid prefix, labels in range."""
    n, C = 8, 4
    rs = np.random.RandomState(134)
    rois = np.concatenate(
        [np.zeros((n, 1), np.float32),
         np.abs(rs.rand(n, 4).astype(np.float32)) * 40], axis=1)
    rois[:, 3:5] = rois[:, 1:3] + 10 + rois[:, 3:5]  # x2>x1, y2>y1
    cls_prob = rs.dirichlet(np.ones(C), n).astype(np.float32)
    bbox_pred = (rs.randn(n, 4 * C) * 0.1).astype(np.float32)
    im_info = jnp.asarray(np.array([60.0, 60.0, 1.0], np.float32))
    layer = nn.DetectionOutputFrcnn(n_classes=C, max_per_image=6)
    out = np.asarray(layer([im_info, jnp.asarray(cls_prob),
                            jnp.asarray(bbox_pred), jnp.asarray(rois)]))
    assert out.shape == (6, 6)
    valid = out[:, 1] > 0
    assert np.isfinite(out[valid]).all()
    sc = out[valid, 1]
    assert (np.diff(sc) <= 1e-6).all()  # sorted by score
    assert ((out[valid, 0] >= 1) & (out[valid, 0] < C)).all()


def test_index_and_masked_select():
    x = jnp.asarray(rnd(3, 5, seed=135))
    idx = jnp.asarray(np.array([2, 1, 4], np.int64))
    out = nn.Index(2)([x, idx])  # 1-based index_select along dim 2
    ref = np.asarray(x)[:, [1, 0, 3]]
    np.testing.assert_allclose(out, ref)

    mask = jnp.asarray((rnd(3, 5, seed=136) > 0))
    vals = nn.MaskedSelect()([x, mask])
    np.testing.assert_allclose(
        np.asarray(vals), np.asarray(x)[np.asarray(mask)])
