"""Decoder-only Transformer LM (models/transformer_lm.py; the
reference's nn/Transformer.scala LanguageModel configuration)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.module import combine, partition
from bigdl_tpu.models import transformer_lm
from bigdl_tpu.utils import set_seed

import bigdl_tpu.nn as nn


def _model(**kw):
    set_seed(0)
    cfg = dict(vocab_size=50, hidden_size=32, num_layers=2, num_heads=4,
               filter_size=64, max_len=32)
    cfg.update(kw)
    return transformer_lm(**cfg)


def test_forward_shape_and_finite():
    m = _model().eval_mode()
    toks = jnp.asarray(np.random.default_rng(0).integers(1, 51, (2, 12)))
    out = m.forward(toks)
    assert out.shape == (2, 12, 51)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_causality():
    """Changing future tokens must not change past logits."""
    m = _model().eval_mode()
    rng = np.random.default_rng(1)
    a = rng.integers(1, 51, (1, 10))
    b = a.copy()
    b[0, 7:] = rng.integers(1, 51, 3)  # mutate only positions >= 7
    out_a = np.asarray(m.forward(jnp.asarray(a)))
    out_b = np.asarray(m.forward(jnp.asarray(b)))
    np.testing.assert_allclose(out_a[0, :7], out_b[0, :7],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(out_a[0, 7:], out_b[0, 7:])


def test_remat_matches_plain():
    """jax.checkpoint must change memory, not math: same loss and grads."""
    set_seed(0)
    plain = _model(remat=False)
    set_seed(0)
    remat = _model(remat=True)
    toks = jnp.asarray(np.random.default_rng(2).integers(1, 51, (2, 8)))
    y = jnp.asarray(np.random.default_rng(3).integers(1, 51, (2, 8)))
    crit = nn.CrossEntropyCriterion()

    def loss_of(model):
        params, rest = partition(model)

        def f(p):
            mm = combine(p, rest)
            out = mm.forward(toks).reshape(-1, 51)
            return crit(out, y.reshape(-1))

        return jax.value_and_grad(f)(params)

    l1, g1 = loss_of(plain)
    l2, g2 = loss_of(remat)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_tied_embedding_head():
    """The output head must literally be the embedding matrix: one shared
    parameter, so vocab logits track embedding updates."""
    m = _model()
    params, _ = partition(m)
    leaves = jax.tree_util.tree_leaves_with_path(params)
    emb_leaves = [kp for kp, v in leaves
                  if "embedding" in jax.tree_util.keystr(kp)]
    assert len(emb_leaves) == 1  # no separate head weight


def test_trains_via_optimizer():
    from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.optim.methods import Adam
    from bigdl_tpu.core.module import Module

    set_seed(4)
    rng = np.random.default_rng(4)
    # learnable pattern: next token = current token + 1 (mod vocab)
    seqs = (np.cumsum(np.ones((64, 9), np.int64), axis=1)
            + rng.integers(0, 40, (64, 1))) % 40 + 1

    class LMWrap(Module):
        """LM + flatten to [B*T, V] so ClassNLL-style criteria apply."""

        def __init__(self):
            super().__init__()
            self.lm = _model(vocab_size=41, num_layers=1, hidden_size=16,
                             filter_size=32, num_heads=2)

        def forward(self, x):
            out = self.lm.forward(x)
            return out.reshape(-1, out.shape[-1])

    batches = [MiniBatch(seqs[i:i + 16, :-1].astype(np.int32),
                         seqs[i:i + 16, 1:].reshape(-1).astype(np.int32))
               for i in range(0, 64, 16)]
    opt = (Optimizer(LMWrap(), DataSet.array(batches),
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(3e-3))
           .set_end_when(Trigger.max_epoch(10)))
    opt.optimize()
    losses = opt.state["loss"]
    assert np.isfinite(losses)
    assert losses < 3.0  # well below ln(41) ~ 3.71 => it is learning
