"""Decoder-only Transformer LM (models/transformer_lm.py; the
reference's nn/Transformer.scala LanguageModel configuration)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.core.module import combine, partition
from bigdl_tpu.models import transformer_lm
from bigdl_tpu.models.transformer_lm import TransformerLM
from bigdl_tpu.utils import set_seed

import bigdl_tpu.nn as nn


def _model(**kw):
    set_seed(0)
    cfg = dict(vocab_size=50, hidden_size=32, num_layers=2, num_heads=4,
               filter_size=64, max_len=32)
    cfg.update(kw)
    return transformer_lm(**cfg)


def test_forward_shape_and_finite():
    m = _model().eval_mode()
    toks = jnp.asarray(np.random.default_rng(0).integers(1, 51, (2, 12)))
    out = m.forward(toks)
    assert out.shape == (2, 12, 51)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_causality():
    """Changing future tokens must not change past logits."""
    m = _model().eval_mode()
    rng = np.random.default_rng(1)
    a = rng.integers(1, 51, (1, 10))
    b = a.copy()
    b[0, 7:] = rng.integers(1, 51, 3)  # mutate only positions >= 7
    out_a = np.asarray(m.forward(jnp.asarray(a)))
    out_b = np.asarray(m.forward(jnp.asarray(b)))
    np.testing.assert_allclose(out_a[0, :7], out_b[0, :7],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(out_a[0, 7:], out_b[0, 7:])


@pytest.mark.slow
def test_remat_matches_plain():
    """jax.checkpoint must change memory, not math: same loss and grads."""
    set_seed(0)
    plain = _model(remat=False)
    set_seed(0)
    remat = _model(remat=True)
    toks = jnp.asarray(np.random.default_rng(2).integers(1, 51, (2, 8)))
    y = jnp.asarray(np.random.default_rng(3).integers(1, 51, (2, 8)))
    crit = nn.CrossEntropyCriterion()

    def loss_of(model):
        params, rest = partition(model)

        def f(p):
            mm = combine(p, rest)
            out = mm.forward(toks).reshape(-1, 51)
            return crit(out, y.reshape(-1))

        return jax.value_and_grad(f)(params)

    l1, g1 = loss_of(plain)
    l2, g2 = loss_of(remat)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_tied_embedding_head():
    """The output head must literally be the embedding matrix: one shared
    parameter, so vocab logits track embedding updates."""
    m = _model()
    params, _ = partition(m)
    leaves = jax.tree_util.tree_leaves_with_path(params)
    emb_leaves = [kp for kp, v in leaves
                  if "embedding" in jax.tree_util.keystr(kp)]
    assert len(emb_leaves) == 1  # no separate head weight


@pytest.mark.slow
def test_trains_via_optimizer():
    from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.optim.methods import Adam
    from bigdl_tpu.core.module import Module

    set_seed(4)
    rng = np.random.default_rng(4)
    # learnable pattern: next token = current token + 1 (mod vocab)
    seqs = (np.cumsum(np.ones((64, 9), np.int64), axis=1)
            + rng.integers(0, 40, (64, 1))) % 40 + 1

    class LMWrap(Module):
        """LM + flatten to [B*T, V] so ClassNLL-style criteria apply."""

        def __init__(self):
            super().__init__()
            self.lm = _model(vocab_size=41, num_layers=1, hidden_size=16,
                             filter_size=32, num_heads=2)

        def forward(self, x):
            out = self.lm.forward(x)
            return out.reshape(-1, out.shape[-1])

    batches = [MiniBatch(seqs[i:i + 16, :-1].astype(np.int32),
                         seqs[i:i + 16, 1:].reshape(-1).astype(np.int32))
               for i in range(0, 64, 16)]
    opt = (Optimizer(LMWrap(), DataSet.array(batches),
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(3e-3))
           .set_end_when(Trigger.max_epoch(10)))
    opt.optimize()
    losses = opt.state["loss"]
    assert np.isfinite(losses)
    assert losses < 3.0  # well below ln(41) ~ 3.71 => it is learning


@pytest.mark.slow
def test_incremental_decode_matches_full_forward():
    """decode_step with the KV cache must reproduce each column of the
    full forward exactly (eval mode)."""
    m = _model().eval_mode()
    rng = np.random.default_rng(5)
    toks = jnp.asarray(rng.integers(1, 51, (2, 9)), jnp.int32)
    full = np.asarray(m.forward(toks))               # [2, 9, 51]
    caches = m.init_cache(2)
    for t in range(9):
        logits, caches = m.decode_step(toks[:, t:t + 1], t, caches)
        np.testing.assert_allclose(np.asarray(logits), full[:, t],
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_greedy_generate_consistent_with_full_forward():
    """Each generated token must be the argmax of the full forward over
    the sequence so far."""
    m = _model().eval_mode()
    rng = np.random.default_rng(6)
    prompt = jnp.asarray(rng.integers(1, 51, (1, 4)), jnp.int32)
    out = np.asarray(m.generate(prompt, max_new_tokens=5))
    assert out.shape == (1, 9)
    seq = np.asarray(prompt)
    for t in range(5):
        logits = np.asarray(m.forward(jnp.asarray(seq)))[:, -1]
        # 1-based criterion convention: logit index i = token i+1's
        # slot; the untrained last row is excluded from the argmax
        nxt = int(np.argmax(logits[:, :-1], axis=-1)[0]) + 1
        assert out[0, 4 + t] == nxt, (t, out, nxt)
        seq = np.concatenate([seq, [[nxt]]], axis=1)


def test_generate_stops_at_eos():
    m = _model().eval_mode()
    rng = np.random.default_rng(7)
    prompt = jnp.asarray(rng.integers(1, 51, (2, 3)), jnp.int32)
    # pick the first greedily-generated token of row 0 as the "EOS"
    free = np.asarray(m.generate(prompt, max_new_tokens=4))
    eos = int(free[0, 3])
    out = np.asarray(m.generate(prompt, max_new_tokens=4, eos_id=eos))
    assert out[0, 3] == eos
    assert (out[0, 4:] == 0).all()   # padded after EOS


@pytest.mark.slow
def test_beam_size_one_matches_greedy():
    m = _model().eval_mode()
    rng = np.random.default_rng(8)
    prompt = jnp.asarray(rng.integers(1, 51, (2, 4)), jnp.int32)
    greedy = np.asarray(m.generate(prompt, max_new_tokens=5))[:, 4:]
    seqs, scores = m.generate_beam(prompt, beam_size=1, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(seqs)[:, 0, :], greedy)
    assert np.all(np.isfinite(np.asarray(scores)))

@pytest.mark.slow
def test_incremental_decode_matches_full_forward_with_padding():
    """A prompt containing 0-padding must produce the same logits
    incrementally as forward(), whose padding_bias masks pad slots
    (regression: decode_step only masked future slots)."""
    m = _model().eval_mode()
    rng = np.random.default_rng(9)
    toks = np.asarray(rng.integers(1, 51, (2, 8)), np.int32)
    toks[0, 3] = 0
    toks[1, 5:] = 0
    full = np.asarray(m.forward(jnp.asarray(toks)))
    caches = m.init_cache(2)
    for t in range(8):
        logits, caches = m.decode_step(
            jnp.asarray(toks[:, t:t + 1]), t, caches)
        np.testing.assert_allclose(np.asarray(logits), full[:, t],
                                   rtol=2e-4, atol=2e-5)


def test_generate_never_emits_untrained_or_pad_token():
    """The tied head's LAST logit row (index vocab_size) is never a
    criterion target (1-based convention: target t trains index t-1),
    so it must be masked out of argmax/top_k — otherwise generation
    could emit the out-of-vocab token vocab_size+1.  Token 0 (padding)
    must never be emitted either."""
    m = _model().eval_mode()
    # bias the model so the untrained last row would dominate if unmasked
    from bigdl_tpu.core.module import Parameter
    w = np.array(m.embedding.weight)  # writable copy
    w[-1] = 10.0  # giant norm: with LN'd hidden, the last logit wins
    m.embedding.weight = Parameter(jnp.asarray(w))
    rng = np.random.default_rng(10)
    prompt = jnp.asarray(rng.integers(1, 51, (2, 3)), jnp.int32)
    out = np.asarray(m.generate(prompt, max_new_tokens=6))
    assert (out[:, 3:] != 0).all(), out
    assert (out[:, 3:] <= 50).all(), out   # never the out-of-vocab id
    seqs, _ = m.generate_beam(prompt, beam_size=2, max_new_tokens=4)
    assert (np.asarray(seqs) != 0).all(), seqs
    assert (np.asarray(seqs) <= 50).all(), seqs

@pytest.mark.slow
def test_train_then_generate_token_convention():
    """ADVICE r03 (high): a model trained with the framework's own
    1-based criteria must generate the continuation in TOKEN space —
    train next=cur+1, prompt [5,6,7,8] must continue 9,10,11 (the bug
    emitted raw logit indices, i.e. 8,8,8 shifted down by one)."""
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.optim.methods import Adam

    set_seed(1)
    vocab = 20
    m = TransformerLM(vocab, hidden_size=32, num_layers=1, num_heads=2,
                      filter_size=64, max_len=16)
    rng = np.random.default_rng(2)
    starts = rng.integers(1, vocab - 8, size=(64,))
    seqs = starts[:, None] + np.arange(9)[None, :]   # ascending runs
    x = jnp.asarray(seqs[:, :-1], jnp.int32)
    y = jnp.asarray(seqs[:, 1:], jnp.int32)
    crit = nn.CrossEntropyCriterion()
    params, rest = partition(m)
    method = Adam(5e-3)
    state = method.init_state(params)

    @jax.jit
    def step(p, s):
        def loss_fn(p):
            logits = combine(p, rest).forward(x)
            return crit(logits.reshape(-1, vocab + 1), y.reshape(-1))
        loss, g = jax.value_and_grad(loss_fn)(p)
        p, s = method.update(g, p, s)
        return p, s, loss

    for _ in range(120):
        params, state, loss = step(params, state)
    trained = combine(params, rest).eval_mode()
    out = np.asarray(trained.generate(
        jnp.asarray([[5, 6, 7, 8]], jnp.int32), max_new_tokens=3))
    np.testing.assert_array_equal(out[0], [5, 6, 7, 8, 9, 10, 11])
    seqs_b, _ = trained.generate_beam(
        jnp.asarray([[5, 6, 7, 8]], jnp.int32), beam_size=2,
        max_new_tokens=3)
    np.testing.assert_array_equal(np.asarray(seqs_b)[0, 0], [9, 10, 11])


def test_sequence_parallel_rejects_padded_batch():
    """ADVICE r03 (medium): the ring path has no padding mask — padded
    batches must fail loudly, not silently diverge from dense."""
    from jax.sharding import Mesh

    m = _model(max_len=64).eval_mode()
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("seq",))
    m.set_sequence_parallel(mesh, "seq")
    toks = np.ones((2, 16), np.int32)
    toks[1, 10:] = 0
    with pytest.raises(ValueError, match="padded"):
        m.forward(jnp.asarray(toks))
    # under jit the tokens are traced and can't raise: the output must
    # be NaN-poisoned (loudly wrong), while a clean batch stays finite
    jf = jax.jit(m.forward)
    assert not np.isfinite(np.asarray(jf(jnp.asarray(toks)))).all()
    clean = np.ones((2, 16), np.int32)
    assert np.isfinite(np.asarray(jf(jnp.asarray(clean)))).all()


@pytest.mark.slow
def test_sequence_parallel_matches_dense():
    """set_sequence_parallel (ring attention over the seq axis) must
    reproduce the dense forward and its gradients on an 8-way mesh,
    with the projection weights shared (not copied)."""
    from jax.sharding import Mesh
    from bigdl_tpu.parallel.ring_attention import RingSelfAttention

    m = _model(max_len=64).eval_mode()
    rng = np.random.default_rng(11)
    toks = jnp.asarray(rng.integers(1, 51, (2, 16)), jnp.int32)
    dense = np.asarray(m.forward(toks))

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("seq",))
    orig_q = m.blocks[0].self_attn.q_layer
    m.set_sequence_parallel(mesh, "seq")
    assert isinstance(m.blocks[0].self_attn, RingSelfAttention)
    # weights shared (same module object), not cloned
    assert m.blocks[0].self_attn.q_layer is orig_q
    # reconfiguring with another mesh must take effect, not be skipped
    mesh2 = Mesh(np.asarray(jax.devices()[:4]), ("seq",))
    m.set_sequence_parallel(mesh2, "seq")
    assert m.blocks[0].self_attn.mesh is mesh2
    m.set_sequence_parallel(mesh, "seq")
    ring_out = np.asarray(m.forward(toks))
    np.testing.assert_allclose(ring_out, dense, rtol=2e-4, atol=2e-5)

    # gradients agree too
    y = jnp.asarray(rng.integers(1, 51, (2, 16)), jnp.int32)
    crit = nn.CrossEntropyCriterion()

    def loss_of(model):
        params, rest = partition(model)

        def f(p):
            out = combine(p, rest).forward(toks).reshape(-1, 51)
            return crit(out, y.reshape(-1))

        return jax.grad(f)(params)

    set_seed(0)
    dense_m = _model(max_len=64).eval_mode()
    g1 = loss_of(dense_m)
    g2 = loss_of(m)
    # module re-assignment moves self_attn to the end of the module
    # dict, so leaf ORDER differs — compare by key path
    def by_path(g):
        return {jax.tree_util.keystr(kp): np.asarray(v) for kp, v in
                jax.tree_util.tree_leaves_with_path(g)}
    d1, d2 = by_path(g1), by_path(g2)
    assert set(d1) == set(d2)
    for k in d1:
        np.testing.assert_allclose(d1[k], d2[k], rtol=5e-4, atol=1e-5,
                                   err_msg=k)


def test_sequence_parallel_generation_falls_back_to_dense():
    """Incremental decoding (cache path) must keep working after the
    ring swap — the cache path falls back to dense attention."""
    from jax.sharding import Mesh
    m = _model(max_len=64).eval_mode()
    rng = np.random.default_rng(12)
    prompt = jnp.asarray(rng.integers(1, 51, (1, 4)), jnp.int32)
    want = np.asarray(m.generate(prompt, max_new_tokens=4))
    m.set_sequence_parallel(Mesh(np.asarray(jax.devices()[:8]), ("seq",)))
    got = np.asarray(m.generate(prompt, max_new_tokens=4))
    np.testing.assert_array_equal(got, want)


def test_ring_attention_dropout_training_raises():
    from jax.sharding import Mesh
    m = _model(max_len=64, dropout=0.1)
    m.set_sequence_parallel(Mesh(np.asarray(jax.devices()[:8]), ("seq",)))
    m.train_mode()
    toks = jnp.asarray(np.random.default_rng(13).integers(1, 51, (2, 8)))
    from bigdl_tpu.core.module import forward_context
    with pytest.raises(ValueError, match="ring"):
        with forward_context(rng=jax.random.key(0)):
            m.forward(toks)


@pytest.mark.slow
def test_eval_mode_survives_sequence_parallel_swap():
    """set_sequence_parallel after eval_mode() must not resurrect
    training=True on the swapped attention modules (regression: the
    rng-neutral constructor reset the flag, making generation with
    dropout>0 raise)."""
    from jax.sharding import Mesh
    m = _model(max_len=64, dropout=0.1).eval_mode()
    m.set_sequence_parallel(Mesh(np.asarray(jax.devices()[:8]), ("seq",)))
    assert not m.blocks[0].self_attn.training
    rng = np.random.default_rng(14)
    toks = jnp.asarray(rng.integers(1, 51, (2, 16)), jnp.int32)
    out = m.forward(toks)  # must not raise
    assert bool(jnp.all(jnp.isfinite(out)))
    out2 = m.generate(jnp.asarray(rng.integers(1, 51, (1, 4))), 3)
    assert out2.shape == (1, 7)


def test_ring_rejects_indivisible_sequence():
    from jax.sharding import Mesh
    m = _model(max_len=64).eval_mode()
    m.set_sequence_parallel(Mesh(np.asarray(jax.devices()[:8]), ("seq",)))
    toks = jnp.asarray(np.random.default_rng(15).integers(1, 51, (1, 12)))
    with pytest.raises(ValueError, match="divisible"):
        m.forward(toks)
