"""Tests for the inference runtime (reference optim/Predictor.scala,
Evaluator.scala, PredictionService.scala)."""

import io
import threading

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.dataset import Sample, LocalDataSet
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.optim import (
    Predictor, Evaluator, PredictionService, Top1Accuracy, Loss,
)
from bigdl_tpu.utils import set_seed


def _model():
    set_seed(3)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3),
                         nn.LogSoftMax())


def test_predict_matches_forward():
    m = _model()
    rng = np.random.default_rng(0)
    feats = [rng.normal(size=(4,)).astype(np.float32) for _ in range(10)]
    preds = Predictor(m, batch_size=4).predict([Sample(f) for f in feats])
    assert len(preds) == 10  # ragged tail (10 = 2*4 + 2) included
    import jax.numpy as jnp
    want = np.asarray(m.eval_mode().forward(jnp.stack(
        [jnp.asarray(f) for f in feats])))
    np.testing.assert_allclose(np.stack(preds), want, rtol=1e-5)


def test_predict_class_is_one_based():
    m = _model()
    rng = np.random.default_rng(1)
    feats = [rng.normal(size=(4,)).astype(np.float32) for _ in range(6)]
    classes = Predictor(m, batch_size=4).predict_class(
        [Sample(f) for f in feats])
    assert classes.shape == (6,)
    assert set(classes) <= {1, 2, 3}


def test_module_predict_convenience():
    m = _model()
    rng = np.random.default_rng(2)
    feats = [rng.normal(size=(4,)).astype(np.float32) for _ in range(4)]
    out = m.predict([Sample(f) for f in feats], batch_size=4)
    assert len(out) == 4


def test_evaluator_counts_every_sample():
    m = _model()
    rng = np.random.default_rng(3)
    samples = [Sample(rng.normal(size=(4,)).astype(np.float32),
                      int(rng.integers(1, 4)))
               for _ in range(11)]
    results = Evaluator(m, batch_size=4).evaluate(
        samples, [Top1Accuracy(), Loss(nn.ClassNLLCriterion())])
    (acc, acc_m), (loss, loss_m) = results
    assert acc.result()[1] == 11  # denominator counts all samples
    assert 0.0 <= acc.result()[0] <= 1.0
    assert np.isfinite(loss.result()[0])


def test_evaluate_on_transformed_dataset():
    m = _model()
    rng = np.random.default_rng(4)
    samples = [Sample(rng.normal(size=(4,)).astype(np.float32),
                      int(rng.integers(1, 4)))
               for _ in range(8)]
    ds = LocalDataSet(samples, shuffle=False).transform(
        SampleToMiniBatch(4))
    results = m.evaluate(ds, [Top1Accuracy()])
    assert results[0][0].result()[1] == 8


def test_prediction_service_concurrent():
    m = _model()
    svc = PredictionService(m, concurrency=3)
    rng = np.random.default_rng(5)
    xs = [rng.normal(size=(2, 4)).astype(np.float32) for _ in range(12)]
    outs = [None] * len(xs)
    errs = []

    def work(i):
        try:
            outs[i] = svc.predict(xs[i])
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(len(xs))]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs
    import jax.numpy as jnp
    for x, y in zip(xs, outs):
        want = np.asarray(m.eval_mode().forward(jnp.asarray(x)))
        np.testing.assert_allclose(y, want, rtol=1e-5)


def test_prediction_service_bytes_roundtrip():
    m = _model()
    svc = PredictionService(m)
    x = np.random.default_rng(6).normal(size=(2, 4)).astype(np.float32)
    buf = io.BytesIO()
    np.save(buf, x, allow_pickle=False)
    resp = svc.predict_bytes(buf.getvalue())
    y = np.load(io.BytesIO(resp), allow_pickle=False)
    assert y.shape == (2, 3)
