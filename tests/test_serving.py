"""Tests for bigdl_tpu.serving: dynamic batching, admission control,
scheduler deadlines, metrics, warmup, and drain-on-shutdown.

The load-bearing assertion (ISSUE 1 acceptance): N concurrent
single-sample requests complete in <= ceil(N / max_batch) model
invocations, proved with a counting backend wrapper.
"""

import io
import math
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.serving import (
    ModelServer, MetricsRegistry, QueueFullError, RequestSheddedError,
    ServerClosedError, bucket_sizes, pick_bucket, split_outputs,
    stack_requests,
)
from bigdl_tpu.utils import set_seed


def _model():
    set_seed(3)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3),
                         nn.LogSoftMax())


def _forward_batch(model, xs):
    import jax.numpy as jnp
    return np.asarray(model.eval_mode().forward(
        jnp.stack([jnp.asarray(x) for x in xs])))


class CountingBackend:
    """Counts device-side invocations; optionally gated so tests can
    hold the scheduler inside a dispatch while they fill the queue."""

    def __init__(self, model, gated: bool = False):
        import jax
        import jax.numpy as jnp
        m = model.clone().eval_mode()
        fn = jax.jit(lambda mm, x: mm.forward(x))
        self._run = lambda x: np.asarray(fn(m, jnp.asarray(x)))
        self.calls = 0
        self.batch_rows = []
        self.entered = threading.Event()
        self.gate = threading.Event()
        if not gated:
            self.gate.set()

    def __call__(self, x):
        self.entered.set()
        assert self.gate.wait(timeout=30), "backend gate never released"
        self.calls += 1
        self.batch_rows.append(np.asarray(x).shape[0])
        return self._run(x)


# ---------------------------------------------------------------------------
# bucketing primitives
# ---------------------------------------------------------------------------

def test_bucket_sizes_powers_of_two():
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_sizes(1) == (1,)
    assert bucket_sizes(24) == (1, 2, 4, 8, 16, 24)  # non-pow2 terminal


def test_pick_bucket_smallest_fit():
    b = bucket_sizes(16)
    assert pick_bucket(1, b) == 1
    assert pick_bucket(3, b) == 4
    assert pick_bucket(16, b) == 16
    with pytest.raises(ValueError):
        pick_bucket(17, b)


def test_stack_and_split_ragged_padding():
    xs = [np.full((3,), i, np.float32) for i in range(3)]
    batch = stack_requests(xs, bucket=4)
    assert batch.shape == (4, 3)
    # pad row repeats the last real sample, exactly like _pad_batch
    np.testing.assert_array_equal(batch[3], batch[2])
    rows = split_outputs(batch, 3)
    assert len(rows) == 3
    np.testing.assert_array_equal(rows[1], xs[1])


def test_stack_tuple_samples():
    xs = [(np.full((2,), i, np.float32), np.full((5,), -i, np.float32))
          for i in range(3)]
    cols = stack_requests(xs, bucket=4)
    assert isinstance(cols, tuple) and len(cols) == 2
    assert cols[0].shape == (4, 2) and cols[1].shape == (4, 5)
    rows = split_outputs(cols, 3)
    assert rows[2][0][0] == 2 and rows[2][1][0] == -2


# ---------------------------------------------------------------------------
# the acceptance test: coalescing proof + metrics
# ---------------------------------------------------------------------------

def test_concurrent_requests_coalesce_and_metrics_account():
    model = _model()
    backend = CountingBackend(model)
    n, max_batch = 12, 4
    server = ModelServer(backend, max_batch=max_batch,
                         batch_timeout_ms=500.0)
    rng = np.random.default_rng(0)
    xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(n)]
    outs = [None] * n
    errs = []

    def work(i):
        try:
            outs[i] = server.submit(xs[i], timeout=30)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    server.shutdown()
    assert not errs
    # the coalescing proof: every request served, in at most
    # ceil(N / max_batch) compiled invocations
    assert backend.calls <= math.ceil(n / max_batch)
    want = _forward_batch(model, xs)
    np.testing.assert_allclose(np.stack(outs), want, rtol=1e-5)

    snap = server.metrics.snapshot()
    assert snap["requests"] == n
    lat = snap["latency_ms"]
    assert lat["p50"] > 0 and lat["p99"] >= lat["p50"] > 0
    occ = snap["occupancy"]
    assert sum(size * count for size, count in occ.items()) == n
    assert sum(occ.values()) == snap["batches"] == backend.calls


def test_submit_many_coalesces_from_one_caller():
    model = _model()
    backend = CountingBackend(model)
    server = ModelServer(backend, max_batch=8, batch_timeout_ms=200.0)
    rng = np.random.default_rng(1)
    xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(8)]
    outs = server.submit_many(xs, timeout=30)
    server.shutdown()
    assert backend.calls <= 1  # 8 samples, one full bucket-8 dispatch
    np.testing.assert_allclose(np.stack(outs), _forward_batch(model, xs),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# scheduler deadline + ragged shapes
# ---------------------------------------------------------------------------

def test_lone_request_served_at_timeout():
    model = _model()
    backend = CountingBackend(model)
    server = ModelServer(backend, max_batch=8, batch_timeout_ms=20.0)
    x = np.ones((4,), np.float32)
    t0 = time.perf_counter()
    y = server.submit(x, timeout=30)
    elapsed = time.perf_counter() - t0
    server.shutdown()
    assert y.shape == (3,)
    assert elapsed < 20.0, "lone request waited far beyond the deadline"
    # one request -> one batch at bucket 1, occupancy histogram {1: 1}
    assert server.metrics.occupancy_histogram() == {1: 1}
    assert backend.batch_rows == [1]


def test_undersized_batch_pads_to_bucket_and_drops():
    model = _model()
    backend = CountingBackend(model)
    server = ModelServer(backend, max_batch=8, batch_timeout_ms=100.0)
    rng = np.random.default_rng(2)
    xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(3)]
    outs = server.submit_many(xs, timeout=30)
    server.shutdown()
    assert len(outs) == 3
    # 3 requests ride a padded bucket-of-4 dispatch
    assert 4 in backend.batch_rows
    assert server.metrics.padded_waste() > 0
    np.testing.assert_allclose(np.stack(outs), _forward_batch(model, xs),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def _gated_server(policy, capacity=2):
    model = _model()
    backend = CountingBackend(model, gated=True)
    server = ModelServer(backend, max_batch=1, batch_timeout_ms=0.0,
                         queue_capacity=capacity, admission=policy)
    return model, backend, server


def _fill(server, backend, capacity):
    """One request held inside the backend + ``capacity`` queued."""
    x = np.ones((4,), np.float32)
    futs = [server.submit_async(x)]
    assert backend.entered.wait(timeout=10)
    for _ in range(capacity):
        futs.append(server.submit_async(x))
    deadline = time.perf_counter() + 10
    while server.queue_depth() < capacity:
        assert time.perf_counter() < deadline
        time.sleep(0.005)
    return x, futs


def test_queue_full_reject_policy():
    _, backend, server = _gated_server("reject", capacity=2)
    x, futs = _fill(server, backend, 2)
    with pytest.raises(QueueFullError):
        server.submit_async(x)
    assert server.metrics.snapshot()["rejected"] == 1
    backend.gate.set()
    server.shutdown(drain=True)
    for f in futs:
        assert f.result(timeout=10).shape == (3,)


def test_queue_full_shed_oldest_policy():
    _, backend, server = _gated_server("shed_oldest", capacity=2)
    x, futs = _fill(server, backend, 2)
    late = server.submit_async(2 * x)
    # the OLDEST queued request (futs[1]; futs[0] is already on device)
    # was shed in favor of the newcomer
    with pytest.raises(RequestSheddedError):
        futs[1].result(timeout=10)
    assert server.metrics.snapshot()["shed"] == 1
    backend.gate.set()
    server.shutdown(drain=True)
    assert futs[0].result(timeout=10).shape == (3,)
    assert futs[2].result(timeout=10).shape == (3,)
    assert late.result(timeout=10).shape == (3,)


def test_queue_full_block_policy_waits_for_space():
    _, backend, server = _gated_server("block", capacity=1)
    x, futs = _fill(server, backend, 1)
    done = threading.Event()
    extra = []

    def blocked_submit():
        extra.append(server.submit_async(x))
        done.set()

    t = threading.Thread(target=blocked_submit)
    t.start()
    time.sleep(0.05)
    assert not done.is_set(), "blocking submit should wait on a full queue"
    backend.gate.set()  # scheduler drains -> space frees -> submit admitted
    assert done.wait(timeout=10)
    t.join()
    server.shutdown(drain=True)
    assert extra[0].result(timeout=10).shape == (3,)


# ---------------------------------------------------------------------------
# shutdown semantics
# ---------------------------------------------------------------------------

def test_shutdown_drains_queued_requests():
    _, backend, server = _gated_server("block", capacity=4)
    x, futs = _fill(server, backend, 4)
    stopper = threading.Thread(target=server.shutdown,
                               kwargs={"drain": True, "timeout": 30})
    stopper.start()
    backend.gate.set()
    stopper.join(timeout=30)
    assert not stopper.is_alive()
    for f in futs:  # every admitted request was still served
        assert f.result(timeout=10).shape == (3,)
    with pytest.raises(ServerClosedError):
        server.submit(x)


def test_shutdown_discard_fails_queued_requests():
    _, backend, server = _gated_server("block", capacity=3)
    x, futs = _fill(server, backend, 3)
    stopper = threading.Thread(target=server.shutdown,
                               kwargs={"drain": False, "timeout": 30})
    stopper.start()
    backend.gate.set()
    stopper.join(timeout=30)
    assert futs[0].result(timeout=10).shape == (3,)  # in-flight finishes
    for f in futs[1:]:
        with pytest.raises(ServerClosedError):
            f.result(timeout=10)


def test_backend_error_propagates_to_futures():
    def broken(x):
        raise RuntimeError("device on fire")

    server = ModelServer(broken, max_batch=2, batch_timeout_ms=5.0)
    fut = server.submit_async(np.ones((4,), np.float32))
    with pytest.raises(RuntimeError, match="device on fire"):
        fut.result(timeout=10)
    # the scheduler survives a failing batch and serves the next one
    fut2 = server.submit_async(np.ones((4,), np.float32))
    with pytest.raises(RuntimeError):
        fut2.result(timeout=10)
    server.shutdown()


# ---------------------------------------------------------------------------
# backends: Module, quantized int8, PredictionService
# ---------------------------------------------------------------------------

def test_module_backend_and_warmup():
    model = _model()
    server = ModelServer(model, max_batch=4, batch_timeout_ms=5.0)
    server.warmup(np.zeros((4,), np.float32))
    # warmup never touches request metrics
    assert server.metrics.snapshot()["requests"] == 0
    y = server.submit(np.ones((4,), np.float32), timeout=30)
    server.shutdown()
    want = _forward_batch(model, [np.ones((4,), np.float32)])[0]
    np.testing.assert_allclose(y, want, rtol=1e-5)


def test_quantized_int8_backend():
    from bigdl_tpu.nn.quantized import quantize
    model = _model()
    qmodel = quantize(model)
    server = ModelServer(qmodel, max_batch=4, batch_timeout_ms=10.0)
    rng = np.random.default_rng(4)
    xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(5)]
    outs = server.submit_many(xs, timeout=30)
    server.shutdown()
    # row-wise activation quantization makes padded rows inert: serving
    # through buckets must agree with the quantized model's own batch
    want = _forward_batch(qmodel, xs)
    np.testing.assert_allclose(np.stack(outs), want, rtol=1e-5, atol=1e-6)


def test_quantized_serving_accuracy_gate():
    """ROADMAP 4b release gate (≙ BigQuant whitepaper fig10): the int8
    backend served through the dynamic batcher must hold top-1 accuracy
    within 0.1% of fp32 on a fixed eval set — quantization fidelity is
    gated, not just round-trip-tested.  The fp32 model's own argmax is
    the eval label (teacher-as-ground-truth), so fp32 accuracy is
    exactly 1.0 and the drop IS the disagreement rate; seeds are pinned
    so the measurement is deterministic."""
    from bigdl_tpu.nn.quantized import quantize
    import jax.numpy as jnp

    model = _model()
    rng = np.random.default_rng(20)
    eval_x = rng.normal(size=(2000, 4)).astype(np.float32)
    labels = np.asarray(model.clone().eval_mode().forward(
        jnp.asarray(eval_x))).argmax(-1)

    qmodel = quantize(model)
    server = ModelServer(qmodel, max_batch=16, batch_timeout_ms=2.0,
                         queue_capacity=2048)
    outs = []
    for lo in range(0, len(eval_x), 256):
        outs.extend(server.submit_many(list(eval_x[lo:lo + 256]),
                                       timeout=120))
    server.shutdown()
    int8_acc = float((np.stack(outs).argmax(-1) == labels).mean())
    drop = 1.0 - int8_acc
    assert drop < 0.001, \
        f"int8 serving accuracy drop {drop:.4%} >= 0.1% " \
        f"(int8 acc {int8_acc:.4f} on 2000 fixed samples)"


def test_prediction_service_serve_frontend():
    from bigdl_tpu.optim import PredictionService
    model = _model()
    svc = PredictionService(model, concurrency=2)
    server = svc.serve(max_batch=4, batch_timeout_ms=10.0)
    rng = np.random.default_rng(5)
    xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(6)]
    outs = server.submit_many(xs, timeout=30)
    server.shutdown()
    np.testing.assert_allclose(np.stack(outs), _forward_batch(model, xs),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# metrics export through the visualization event-file writer
# ---------------------------------------------------------------------------

def test_metrics_publish_tensorboard_roundtrip(tmp_path):
    from bigdl_tpu.visualization import ServingSummary
    model = _model()
    server = ModelServer(model, max_batch=4, batch_timeout_ms=5.0)
    rng = np.random.default_rng(6)
    server.submit_many([rng.normal(size=(4,)).astype(np.float32)
                        for _ in range(6)], timeout=30)
    server.shutdown()
    summary = ServingSummary(str(tmp_path), "serve-test")
    server.publish_metrics(summary, step=7)
    summary.flush()
    got = dict(summary.read_scalar("serving/latency_ms_p50"))
    assert got[7] > 0
    reqs = dict(summary.read_scalar("serving/requests"))
    assert reqs[7] == 6.0
    summary.close()


def test_metrics_registry_empty_snapshot():
    reg = MetricsRegistry()
    snap = reg.snapshot()
    assert snap["requests"] == 0
    assert snap["latency_ms"]["p99"] == 0.0
    assert snap["padded_waste"] == 0.0


# ---------------------------------------------------------------------------
# CLI demo (python -m bigdl_tpu.serving)
# ---------------------------------------------------------------------------

def test_cli_stdin_stdout_autoencoder():
    from bigdl_tpu.serving.__main__ import main
    rng = np.random.default_rng(7)
    lines = "\n".join(" ".join(f"{v:.4f}" for v in rng.normal(size=784))
                      for _ in range(3))
    stdout, stderr = io.StringIO(), io.StringIO()
    rc = main(["--model", "autoencoder", "--max-batch", "2",
               "--no-warmup"],
              stdin=io.StringIO(lines + "\n"), stdout=stdout, stderr=stderr)
    assert rc == 0
    out_lines = stdout.getvalue().strip().splitlines()
    assert len(out_lines) == 3
    idx, cls, score = out_lines[1].split("\t")
    assert idx == "1" and int(cls) >= 1 and np.isfinite(float(score))
    import json
    snap = json.loads(stderr.getvalue().strip().splitlines()[-1])
    assert snap["requests"] == 3


@pytest.mark.slow
def test_cli_synthetic_lenet5_quantized(tmp_path):
    """Heavy end-to-end: int8 LeNet-5 through warmup of every bucket
    plus TensorBoard metrics publication."""
    from bigdl_tpu.serving.__main__ import main
    stdout, stderr = io.StringIO(), io.StringIO()
    rc = main(["--model", "lenet5", "--quantize", "--synthetic", "5",
               "--max-batch", "4", "--log-dir", str(tmp_path)],
              stdin=io.StringIO(""), stdout=stdout, stderr=stderr)
    assert rc == 0
    assert len(stdout.getvalue().strip().splitlines()) == 5
    assert "metrics event file" in stderr.getvalue()


@pytest.mark.slow
def test_soak_mixed_concurrency_fifo_order():
    """Soak: many bursts from many threads; every result must match the
    oracle (no cross-request row mixups under sustained load)."""
    model = _model()
    server = ModelServer(model, max_batch=8, batch_timeout_ms=2.0,
                         queue_capacity=256)
    rng = np.random.default_rng(8)
    xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(200)]
    outs = [None] * len(xs)

    def work(lo, hi):
        for i in range(lo, hi):
            outs[i] = server.submit(xs[i], timeout=60)

    threads = [threading.Thread(target=work, args=(i * 25, (i + 1) * 25))
               for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    server.shutdown()
    np.testing.assert_allclose(np.stack(outs), _forward_batch(model, xs),
                               rtol=1e-5)
    assert server.metrics.snapshot()["requests"] == 200


def test_zoo_registry():
    from bigdl_tpu.models import zoo, zoo_sample_shape
    m = zoo("autoencoder")
    assert hasattr(m, "forward")
    assert zoo_sample_shape("lenet5") == (784,)
    with pytest.raises(ValueError):
        zoo("not_a_model")


def test_cancelled_future_does_not_kill_scheduler():
    """A future cancelled while queued must be dropped at dispatch, not
    raise InvalidStateError inside the single scheduler thread."""
    _, backend, server = _gated_server("block", capacity=4)
    x, futs = _fill(server, backend, 3)
    assert futs[1].cancel()  # still queued -> cancellable
    backend.gate.set()
    # the remaining queued requests are still served by a live scheduler
    assert futs[0].result(timeout=10).shape == (3,)
    assert futs[2].result(timeout=10).shape == (3,)
    assert futs[3].result(timeout=10).shape == (3,)
    y = server.submit(x, timeout=10)  # scheduler survived the cancel
    assert y.shape == (3,)
    server.shutdown()


def test_cli_overload_prints_error_rows():
    """Under shed_oldest the CLI emits ERROR rows for shed requests and
    still prints the metrics snapshot."""
    import json
    from bigdl_tpu.serving.__main__ import main
    stdout, stderr = io.StringIO(), io.StringIO()
    rc = main(["--model", "autoencoder", "--synthetic", "40",
               "--max-batch", "1", "--batch-timeout-ms", "0",
               "--queue-capacity", "1", "--policy", "shed_oldest",
               "--no-warmup"],
              stdin=io.StringIO(""), stdout=stdout, stderr=stderr)
    assert rc == 0
    lines = stdout.getvalue().strip().splitlines()
    assert len(lines) == 40  # one row per sample, served or ERROR
    snap = json.loads(stderr.getvalue().strip().splitlines()[-1])
    served = sum(1 for ln in lines if "\tERROR\t" not in ln)
    assert served == snap["requests"]
    assert snap["shed"] == sum(1 for ln in lines if "RequestSheddedError" in ln)


def test_shed_of_cancelled_future_does_not_crash_submitter():
    """shed_oldest popping a future the client already cancelled must
    not raise InvalidStateError in the submitting thread."""
    _, backend, server = _gated_server("shed_oldest", capacity=2)
    x, futs = _fill(server, backend, 2)
    assert futs[1].cancel()          # oldest queued request, cancelled
    late = server.submit_async(x)    # sheds the cancelled one: no crash
    backend.gate.set()
    server.shutdown(drain=True)
    assert late.result(timeout=10).shape == (3,)


def test_discard_shutdown_with_cancelled_future():
    _, backend, server = _gated_server("block", capacity=2)
    x, futs = _fill(server, backend, 2)
    assert futs[1].cancel()
    stopper = threading.Thread(target=server.shutdown,
                               kwargs={"drain": False, "timeout": 30})
    stopper.start()
    backend.gate.set()
    stopper.join(timeout=30)
    assert not stopper.is_alive()    # close() survived the cancelled future
    with pytest.raises(ServerClosedError):
        futs[2].result(timeout=10)


def test_tuple_output_model_through_both_backends():
    """Multi-head models (tuple outputs, different head shapes) must
    round-trip per-request through Module AND PredictionService
    backends."""
    from bigdl_tpu.core.module import Module
    from bigdl_tpu.optim import PredictionService

    class TwoHead(Module):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 3)
            self.b = nn.Linear(4, 5)

        def forward(self, x):
            return self.a(x), self.b(x)

    set_seed(9)
    model = TwoHead()
    rng = np.random.default_rng(10)
    xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(3)]
    import jax.numpy as jnp
    ref = model.clone().eval_mode()
    wa, wb = (np.asarray(a) for a in ref.forward(
        jnp.stack([jnp.asarray(x) for x in xs])))

    for backend in (model, PredictionService(model)):
        server = ModelServer(backend, max_batch=2, batch_timeout_ms=10.0)
        outs = server.submit_many(xs, timeout=30)
        server.shutdown()
        for i, (ya, yb) in enumerate(outs):
            assert ya.shape == (3,) and yb.shape == (5,)
            np.testing.assert_allclose(ya, wa[i], rtol=1e-5)
            np.testing.assert_allclose(yb, wb[i], rtol=1e-5)


def test_http_server_with_dynamic_batching():
    """examples/serve.py --dynamic-batch path: concurrent HTTP clients
    coalesce through the ModelServer behind the npy byte protocol."""
    import http.client
    from bigdl_tpu.examples.serve import make_server, BatchedBytesFrontend

    model = _model()
    backend = CountingBackend(model)
    mserver = ModelServer(backend, max_batch=4, batch_timeout_ms=200.0)
    httpd = make_server(BatchedBytesFrontend(mserver), "127.0.0.1", 0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        port = httpd.server_port
        rng = np.random.default_rng(11)
        xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(8)]
        outs = [None] * len(xs)

        def post(i):
            buf = io.BytesIO()
            np.save(buf, xs[i], allow_pickle=False)
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("POST", "/predict", buf.getvalue())
            outs[i] = np.load(io.BytesIO(conn.getresponse().read()),
                              allow_pickle=False)
            conn.close()

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(len(xs))]
        [th.start() for th in threads]
        [th.join() for th in threads]
    finally:
        httpd.shutdown()
        httpd.server_close()
        mserver.shutdown()
    np.testing.assert_allclose(np.stack(outs), _forward_batch(model, xs),
                               rtol=1e-5)
    # HTTP threads coalesced: fewer device calls than requests
    assert backend.calls <= math.ceil(len(xs) / 4)


def test_http_generate_endpoint():
    """examples/serve.py --generate path: POST /generate JSON routes
    through the continuous-batching engine; concurrent HTTP clients
    share the slot pool."""
    import http.client
    import json
    from bigdl_tpu.examples.serve import (
        GenerateJsonFrontend, make_server,
    )
    from bigdl_tpu.models import transformer_lm

    set_seed(0)
    lm = transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                        num_heads=4, filter_size=64,
                        max_len=64).eval_mode()
    mserver = ModelServer(generator=lm, slots=2)
    httpd = make_server(None, "127.0.0.1", 0,
                        generate_frontend=GenerateJsonFrontend(
                            mserver, max_new_cap=8))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        port = httpd.server_port
        rng = np.random.default_rng(23)
        prompts = [rng.integers(1, 51, 4).tolist() for _ in range(4)]
        outs = [None] * len(prompts)

        def post(i):
            body = json.dumps({"prompt": prompts[i],
                               "max_new_tokens": 5}).encode()
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            conn.request("POST", "/generate", body)
            outs[i] = json.loads(conn.getresponse().read())
            conn.close()

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(len(prompts))]
        [th.start() for th in threads]
        [th.join() for th in threads]
        # over-cap budget is a client error, not a crash
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/generate", json.dumps(
            {"prompt": prompts[0], "max_new_tokens": 99}).encode())
        assert conn.getresponse().status == 400
        conn.close()
    finally:
        httpd.shutdown()
        httpd.server_close()
        mserver.shutdown()
    import jax.numpy as jnp
    for p, out in zip(prompts, outs):
        want = np.asarray(lm.generate(
            jnp.asarray(p, jnp.int32)[None], 5))[0]
        assert out["tokens"] == [int(v) for v in want]


def test_generation_cli_synthetic():
    """python -m bigdl_tpu.serving --generate round-trips token-id
    prompts through the slot pool and prints the stats snapshot."""
    import json
    from bigdl_tpu.serving.__main__ import main
    stdout, stderr = io.StringIO(), io.StringIO()
    rc = main(["--model", "transformer_lm_tiny", "--generate", "4",
               "--slots", "2", "--synthetic", "3"],
              stdin=io.StringIO(""), stdout=stdout, stderr=stderr)
    assert rc == 0
    lines = stdout.getvalue().strip().splitlines()
    assert len(lines) == 3
    idx, toks = lines[2].split("\t")
    assert idx == "2" and len(toks.split()) >= 5
    snap = json.loads(stderr.getvalue().strip().splitlines()[-1])
    assert snap["requests_done"] == 3
    assert snap["tokens_emitted"] == 12
    # --quantize cannot combine with --generate: rejected loudly, never
    # silently served as fp32
    err = io.StringIO()
    rc = main(["--model", "transformer_lm_tiny", "--generate", "4",
               "--quantize", "--synthetic", "1"],
              stdin=io.StringIO(""), stdout=io.StringIO(), stderr=err)
    assert rc == 2 and "--quantize" in err.getvalue()
    # a malformed stdin line becomes ONE error row; the valid lines
    # around it still print their generations
    stdout2, stderr2 = io.StringIO(), io.StringIO()
    rc = main(["--model", "transformer_lm_tiny", "--generate", "3",
               "--slots", "2"],
              stdin=io.StringIO("1 2 3\n4 foo 6\n7 8\n"),
              stdout=stdout2, stderr=stderr2)
    assert rc == 0
    rows = stdout2.getvalue().strip().splitlines()
    assert len(rows) == 3
    assert "\tERROR\t" in rows[1]
    assert len(rows[0].split("\t")[1].split()) == 6
    assert len(rows[2].split("\t")[1].split()) == 5


def test_model_server_generator_failure_does_not_leak_scheduler():
    """A bad generator must not leave the already-started one-shot
    scheduler thread running with no handle to stop it."""
    before = {t.name for t in threading.enumerate()}
    with pytest.raises(TypeError, match="incremental-decode"):
        ModelServer(lambda x: np.asarray(x), max_batch=2,
                    generator=object())
    time.sleep(0.05)
    leaked = {t.name for t in threading.enumerate()} - before
    assert not any("serving" in n for n in leaked), leaked


def test_submit_timeout_bounds_blocked_admission():
    """submit(x, timeout=N) must give up after ~N even when the queue is
    full under the block policy (wedged-backend scenario)."""
    _, backend, server = _gated_server("block", capacity=1)
    x, futs = _fill(server, backend, 1)
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        server.submit(x, timeout=0.3)
    assert time.perf_counter() - t0 < 5.0
    backend.gate.set()
    server.shutdown(drain=True)
    for f in futs:
        assert f.result(timeout=10).shape == (3,)


def test_weighted_histogram_matches_expanded():
    """make_histogram(values, weights) ≡ make_histogram(expanded)."""
    from bigdl_tpu.visualization.proto import make_histogram
    occ = {1: 3, 2: 7, 4: 2, 8: 1}
    sizes = sorted(occ)
    weighted = make_histogram(np.asarray(sizes, np.float64),
                              weights=[occ[s] for s in sizes])
    expanded = make_histogram(np.concatenate(
        [np.full(c, s, np.float64) for s, c in occ.items()]))
    assert weighted.num == expanded.num == 13
    assert weighted.sum == expanded.sum
    assert weighted.sum_squares == expanded.sum_squares
    assert weighted.bucket == expanded.bucket
    assert weighted.min == expanded.min and weighted.max == expanded.max


def test_generation_drain_mid_decode_finishes_admitted():
    """ISSUE 10 satellite: generation futures are MULTI-STEP, so drain
    must wait for every admitted request's LAST token, not just the
    current dispatch.  shutdown(drain=True) fired mid-decode completes
    every burst-submitted future with the exact solo-generate row."""
    import jax.numpy as jnp
    from bigdl_tpu.models import transformer_lm

    set_seed(0)
    lm = transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                        num_heads=4, filter_size=64,
                        max_len=64).eval_mode()
    rng = np.random.default_rng(21)
    prompts = [rng.integers(1, 51, rng.integers(2, 10)).astype(np.int32)
               for _ in range(6)]
    server = ModelServer(generator=lm, slots=2)
    futs = [server.submit_generate_async(p, 12) for p in prompts]
    # let the pool get genuinely mid-decode before draining
    deadline = time.perf_counter() + 30
    while server.generation_stats()["decode_steps"] < 2:
        assert time.perf_counter() < deadline
        time.sleep(0.01)
    server.shutdown(drain=True, timeout=120)
    for p, f in zip(prompts, futs):
        want = np.asarray(lm.generate(jnp.asarray(p)[None], 12))[0]
        np.testing.assert_array_equal(f.result(timeout=1), want)
    with pytest.raises(ServerClosedError):
        server.submit_generate_async(prompts[0], 2)


def test_generation_discard_shutdown_rejects_unadmitted():
    """shutdown(drain=False) mid-decode: requests already IN a KV slot
    still finish (a half-emitted generation is never dropped); queued
    ones reject cleanly with ServerClosedError."""
    from bigdl_tpu.models import transformer_lm

    set_seed(0)
    lm = transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                        num_heads=4, filter_size=64,
                        max_len=64).eval_mode()
    rng = np.random.default_rng(22)
    prompts = [rng.integers(1, 51, 6).astype(np.int32)
               for _ in range(8)]
    server = ModelServer(generator=lm, slots=2)
    futs = [server.submit_generate_async(p, 30) for p in prompts]
    deadline = time.perf_counter() + 30
    while server.generation_stats()["decode_steps"] < 2:
        assert time.perf_counter() < deadline
        time.sleep(0.01)
    server.shutdown(drain=False, timeout=120)
    finished = rejected = 0
    for p, f in zip(prompts, futs):
        try:
            row = f.result(timeout=1)
            assert row.shape == (36,) and row[:6].tolist() == p.tolist()
            finished += 1
        except ServerClosedError:
            rejected += 1
    assert finished + rejected == len(futs)
    # the two occupying slots at discard time must have finished; with
    # 8 long requests over 2 slots some were still queued and rejected
    assert finished >= 2
    assert rejected >= 1


def test_shutdown_signal_unwinds_into_drain():
    """install_shutdown_signals (ISSUE 2 satellite): SIGTERM raises
    KeyboardInterrupt in the main thread so the caller's
    shutdown(drain=True) path runs — every already-admitted request is
    still answered."""
    import os
    import signal as sg

    from bigdl_tpu.serving.server import install_shutdown_signals

    server = ModelServer(lambda x: np.asarray(x) * 2.0, max_batch=4,
                         batch_timeout_ms=1.0)
    restore = install_shutdown_signals(server, signals=(sg.SIGTERM,))
    try:
        futs = [server.submit_async(np.full((3,), i, np.float32))
                for i in range(4)]
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), sg.SIGTERM)
            # give the interpreter a bytecode boundary to deliver on
            for _ in range(1000):
                time.sleep(0.001)
        # the drain path the unwound caller runs:
        server.shutdown(drain=True)
        for i, f in enumerate(futs):
            np.testing.assert_allclose(f.result(timeout=5),
                                       np.full((3,), 2.0 * i))
        with pytest.raises(ServerClosedError):
            server.submit_async(np.zeros((3,), np.float32))
    finally:
        restore()
    # the previous SIGTERM disposition is back
    assert sg.getsignal(sg.SIGTERM) is sg.SIG_DFL
