"""Request-scoped distributed tracing tests (telemetry/request_trace.py
wired through the serving fabric): context minting at router admission,
span recording across dispatch / engine phases / reliability hops,
tail-based retention with watermark promotion, cross-process shard
stitching, and the exemplar -> /tracez?trace=<id> resolution step.

The load-bearing assertions: (a) a replica hard-killed mid-decode
yields ONE assembled trace — admission, both dispatches, the aborted
decode on the dead replica, the failover hop, and the survivor's
prefill/decode/emit — with exactly-once token accounting across the
decode spans; (b) a hedged request's losing twin is marked cancelled
inside the SAME trace as the winner; (c) with telemetry disabled the
request carries no context and zero ``request/*`` spans exist."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import telemetry
from bigdl_tpu.models import transformer_lm
from bigdl_tpu.serving import (
    HedgePolicy, ModelServer, ReliabilityPolicy, Replica, RetryPolicy,
    Router,
)
from bigdl_tpu.telemetry import events, families, request_trace, tracing
from bigdl_tpu.telemetry.debugz import Debugz, DebugzServer
from bigdl_tpu.utils import chaos, set_seed


@pytest.fixture(autouse=True)
def _telemetry_on():
    telemetry.enable()
    telemetry.reset()
    events.reset_events()
    yield
    chaos.reset()
    telemetry.reset()
    telemetry.disable()
    request_trace.set_bulk_capacity(256)
    request_trace.set_retained_capacity(256)


@pytest.fixture(scope="module")
def lm():
    set_seed(0)
    return transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                          num_heads=4, filter_size=64,
                          max_len=64).eval_mode()


def solo(model, prompt, max_new, eos_id=None):
    import jax.numpy as jnp
    return np.asarray(model.generate(
        jnp.asarray(prompt, jnp.int32)[None], int(max_new),
        eos_id=eos_id))[0]


def _replica(lm, rid, d, slots=2, interval=0.05):
    return Replica(rid, ModelServer(generator=lm, slots=slots),
                   snapshot_dir=d, publish_interval_s=interval)


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.perf_counter() + timeout
    while not cond():
        if time.perf_counter() > deadline:
            raise TimeoutError(f"{msg} not reached in {timeout}s")
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# the store: mint / mark / finish / tail retention (pure, no model)
# ---------------------------------------------------------------------------

def test_off_mints_nothing_and_every_site_noops():
    telemetry.disable()
    assert request_trace.mint() is None
    # every instrumentation site takes the None context without caring
    assert request_trace.record_span("request/queue", 0.0, 1.0,
                                     ctx=None) is None
    request_trace.mark(None, "deadline")
    request_trace.finish(None, outcome="ok")
    request_trace.observe_ttft(None, 0.5)
    request_trace.observe_inter_token(None, 0.5)
    assert request_trace.trace_ids() == []


def test_mark_rejects_reasons_outside_the_vocabulary():
    ctx = request_trace.mint()
    assert ctx is not None
    with pytest.raises(ValueError):
        request_trace.mark(ctx, "felt_slow")


def test_tail_retention_bulk_drops_marked_survives():
    """The Tail-at-Scale shape: healthy traffic is sampled OUT by the
    bounded bulk ring (drop counter ticks), the marked trace survives
    in the retained store no matter how much traffic follows."""
    request_trace.set_bulk_capacity(4)
    dropped0 = families.request_traces_dropped_total().value()
    t = time.perf_counter()
    slow = request_trace.mint()
    request_trace.record_span("request/queue", t, t + 0.001, ctx=slow)
    request_trace.mark(slow, "deadline")
    request_trace.finish(slow, outcome="deadline")
    healthy = []
    for _ in range(8):
        c = request_trace.mint()
        request_trace.record_span("request/queue", t, t + 0.001, ctx=c)
        request_trace.finish(c, outcome="ok")
        healthy.append(c.trace_id)
    assert slow.trace_id in request_trace.retained_ids()
    assert request_trace.retained_reasons()[slow.trace_id] == ["deadline"]
    held = request_trace.trace_ids()
    # bulk kept only the newest 4 healthy traces; the oldest 4 dropped
    assert [h for h in healthy if h in held] == healthy[-4:]
    assert (families.request_traces_dropped_total().value()
            - dropped0) == 4
    assert families.request_traces_retained_total().labels(
        "deadline").value() >= 1
    asm = request_trace.assemble_trace(slow.trace_id)
    assert asm["retained_reasons"] == ["deadline"]
    assert asm["outcome"] == "deadline"


def test_late_mark_promotes_a_filed_trace_out_of_bulk():
    """A hedge verdict resolving just behind the future: the trace was
    already filed unmarked into the droppable bulk ring; the late mark
    must move it to retained and count it exactly once."""
    ctx = request_trace.mint()
    request_trace.finish(ctx, outcome="ok")
    assert ctx.trace_id not in request_trace.retained_ids()
    before = families.request_traces_retained_total().labels(
        "hedge_won").value()
    request_trace.mark(ctx, "hedge_won")
    assert ctx.trace_id in request_trace.retained_ids()
    assert families.request_traces_retained_total().labels(
        "hedge_won").value() == before + 1
    request_trace.mark(ctx, "hedge_won")  # duplicate: nothing new
    assert families.request_traces_retained_total().labels(
        "hedge_won").value() == before + 1


# ---------------------------------------------------------------------------
# cross-process stitching (fleet file transport)
# ---------------------------------------------------------------------------

def test_shard_write_and_assemble_across_processes(tmp_path):
    d = str(tmp_path)
    ctx = request_trace.mint()
    t = time.perf_counter()
    request_trace.record_span("request/queue", t, t + 0.01, ctx=ctx)
    path = request_trace.write_trace_shard(d)
    assert path is not None and os.path.exists(path)
    # a second "process": a hand-written shard under a foreign pid,
    # spans already wall-converted (the write-side contract)
    wall = tracing.wall_time_of(t)
    foreign = {"pid": 99991, "time": time.time(), "traces": {
        ctx.trace_id: {
            "origin_pid": os.getpid(), "marks": ["failover"],
            "outcome": None,
            "spans": [{"name": "request/decode",
                       "t_start_wall": wall + 0.02,
                       "t_end_wall": wall + 0.03,
                       "duration_s": 0.01, "span_id": 1,
                       "pid": 99991, "args": {"new_tokens": 3}}]}}}
    with open(os.path.join(
            d, f"{request_trace.SHARD_PREFIX}99991.json"), "w") as f:
        json.dump(foreign, f)
    # a torn shard must be skipped, never fatal (fleet reader idiom)
    with open(os.path.join(
            d, f"{request_trace.SHARD_PREFIX}7.json"), "w") as f:
        f.write("{torn")
    asm = request_trace.assemble_trace(ctx.trace_id, directory=d)
    assert asm is not None
    assert sorted(asm["pids"]) == sorted([os.getpid(), 99991])
    # wall-clock merge order, local span first; our own shard re-read
    # did NOT duplicate the local span
    assert asm["names"] == ["request/queue", "request/decode"]
    assert "failover" in asm["retained_reasons"]
    assert request_trace.assemble_trace("nope", directory=d) is None


def test_merge_chrome_traces_rebases_onto_earliest_anchor(tmp_path):
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    mk = lambda name, pid, wall, dropped: {
        "traceEvents": [{"ph": "X", "name": name, "cat": "bigdl_tpu",
                         "ts": 1000.0, "dur": 10.0, "pid": pid,
                         "tid": "main"}],
        "displayTimeUnit": "ms",
        "otherData": {"dropped_spans": dropped, "epoch_wall": wall}}
    with open(pa, "w") as f:
        json.dump(mk("early", 1, 100.0, 2), f)
    with open(pb, "w") as f:
        json.dump(mk("late", 2, 100.5, 3), f)
    merged = tracing.merge_chrome_traces([pa, pb])
    assert merged["otherData"]["epoch_wall"] == 100.0
    assert merged["otherData"]["dropped_spans"] == 5
    assert merged["otherData"]["merged_files"] == 2
    assert [e["name"] for e in merged["traceEvents"]] == ["early", "late"]
    # the later file's events shifted by the anchor delta (0.5 s in us)
    assert merged["traceEvents"][1]["ts"] == pytest.approx(
        1000.0 + 0.5e6)
    assert merged["traceEvents"][0]["ts"] == pytest.approx(1000.0)


# ---------------------------------------------------------------------------
# integration: the fabric writes one stitched story per request
# ---------------------------------------------------------------------------

def test_hard_kill_mid_decode_yields_one_assembled_trace(lm, tmp_path):
    """THE acceptance scenario: chaos hard-kill mid-decode produces ONE
    trace whose timeline shows admission -> dispatch -> prefill ->
    decode (aborted on the dead replica) -> failover -> survivor
    dispatch/prefill/decode -> emit, with exactly-once token accounting
    across the decode spans and the trace retained (reason failover)."""
    d = str(tmp_path)
    prompt = np.array([4, 8, 15, 16, 23], np.int32)
    max_new = 20
    expect = solo(lm, prompt, max_new)
    got = []
    seen3 = threading.Event()

    def on_token(t):
        got.append(int(t))
        if len(got) >= 3:
            seen3.set()

    rel = ReliabilityPolicy(
        retry=RetryPolicy(times=2, backoff_s=0.01, backoff_cap_s=0.05,
                          jitter=0.0))
    with Router([_replica(lm, 0, d), _replica(lm, 1, d)],
                snapshot_dir=d, registry_max_age_s=5.0,
                shed_after_s=30.0, reliability=rel) as router:
        _wait(lambda: sum(
            1 for r in router.records().values() if r["healthy"]) == 2,
            msg="both replicas healthy")
        fut = router.submit_generate_async(prompt, max_new,
                                           on_token=on_token)
        assert seen3.wait(60.0), "stream never started"
        primary = next(rid for rid, n in
                       router.stats()["inflight"].items() if n > 0)
        router.replica(primary).kill()
        row = fut.result(timeout=120.0)
        np.testing.assert_array_equal(row, expect)
    assert got == list(expect[len(prompt):])

    # the failover event names the trace — a metric/event breach
    # resolves to the timeline without grepping anything
    fo_ev = [e for e in events.recent_events()
             if e["kind"] == "generation_failover"]
    assert fo_ev and fo_ev[0].get("trace_id")
    tid = fo_ev[0]["trace_id"]

    asm = request_trace.assemble_trace(tid, directory=d)
    assert asm is not None
    names = asm["names"]
    assert names[0] == "request/admission"
    assert names.count("request/admission") == 1
    assert names.count("request/dispatch") >= 2
    assert "request/failover" in names
    assert "request/prefill" in names
    assert names.count("request/emit") == 1
    # BOTH replicas appear in one trace, by dispatch target
    dispatched_to = {s["args"]["replica"] for s in asm["spans"]
                     if s["name"] == "request/dispatch"}
    assert dispatched_to == {0, 1}
    # exactly-once accounting: the aborted decode's salvaged tokens
    # plus the survivor's remainder cover the budget with no overlap
    decode = [s for s in asm["spans"] if s["name"] == "request/decode"]
    aborted = [s for s in decode if (s["args"] or {}).get("aborted")]
    clean = [s for s in decode if not (s["args"] or {}).get("aborted")]
    assert len(aborted) == 1
    assert aborted[0]["args"]["aborted"] == "ReplicaDeadError"
    assert len(clean) >= 1
    assert sum(s["args"]["new_tokens"] for s in decode) == max_new
    fo = next(s for s in asm["spans"]
              if s["name"] == "request/failover")
    assert fo["args"]["dead_replica"] == primary
    # tail sampler verdict: retained, reason failover, outcome ok
    assert "failover" in asm["retained_reasons"]
    assert asm["outcome"] == "ok"
    assert tid in request_trace.retained_ids()
    assert families.request_traces_retained_total().labels(
        "failover").value() >= 1


def test_hedge_loser_cancelled_inside_the_winners_trace(lm, tmp_path):
    """Both hedge legs belong to ONE trace: two dispatch markers (one
    twin), and the losing leg's cancellation is a span in the same
    timeline naming the winner."""
    d = str(tmp_path)
    srv0 = ModelServer(generator=lm, slots=2)
    r0 = Replica(0, srv0, snapshot_dir=d, publish_interval_s=0.05)
    r1 = _replica(lm, 1, d)
    prompt = np.array([6, 2, 9], np.int32)
    rel = ReliabilityPolicy(
        retry=RetryPolicy(times=2, backoff_s=0.01, jitter=0.0),
        hedge=HedgePolicy(enabled=True, after_s=0.1))
    with Router([r0, r1], snapshot_dir=d, registry_max_age_s=5.0,
                shed_after_s=30.0, reliability=rel) as router:
        _wait(lambda: sum(
            1 for r in router.records().values() if r["healthy"]) == 2,
            msg="both replicas healthy")
        session = next(s for s in (f"s{i}" for i in range(64))
                       if router._ring.preference(s)[0] == 0)
        fillers = [srv0.submit_generate_async(
            np.array([1, 1, 1, i], np.int32), 45) for i in range(2)]
        fut = router.submit_generate_async(prompt, 8, session=session)
        row = fut.result(timeout=120.0)
        np.testing.assert_array_equal(row, solo(lm, prompt, 8))
        _wait(lambda: router.stats()["hedges"] >= 1, timeout=60.0,
              msg="hedge resolution")
        for f in fillers:
            f.result(timeout=120.0)

    # the fillers bypassed the router: exactly one context was minted
    tids = request_trace.trace_ids()
    assert len(tids) == 1
    asm = request_trace.assemble_trace(tids[0])
    dispatches = [s for s in asm["spans"]
                  if s["name"] == "request/dispatch"]
    assert len(dispatches) == 2
    assert sorted(s["args"]["twin"] for s in dispatches) == [False, True]
    cancelled = [s for s in asm["spans"]
                 if s["name"] == "request/hedge_cancelled"]
    assert len(cancelled) == 1
    assert cancelled[0]["args"]["replica"] != cancelled[0]["args"]["winner"]
    rec = [e for e in events.recent_events()
           if e["kind"] == "request_hedge"]
    assert len(rec) == 1 and rec[0]["trace_id"] == tids[0]
    if rec[0]["outcome"] == "hedge_won":
        assert "hedge_won" in request_trace.retained_reasons().get(
            tids[0], [])


def test_off_by_default_request_rides_with_no_context(lm, tmp_path):
    """Telemetry disabled: no context is allocated at admission, zero
    ``request/*`` spans land anywhere, and the trace stores stay
    empty — the fabric pays only the existing one-bool checks."""
    telemetry.disable()
    d = str(tmp_path)
    prompt = np.array([3, 1, 4], np.int32)
    with Router([_replica(lm, 0, d)], snapshot_dir=d,
                registry_max_age_s=5.0, shed_after_s=30.0) as router:
        _wait(lambda: any(
            r["healthy"] for r in router.records().values()),
            msg="replica healthy")
        out = router.submit_generate(prompt, 4, timeout=60.0)
        np.testing.assert_array_equal(out, solo(lm, prompt, 4))
    assert request_trace.trace_ids() == []
    assert request_trace.retained_ids() == []
    assert not any(r.name.startswith("request/")
                   for r in tracing.finished_spans())
    assert request_trace.write_trace_shard(d) is None


def test_ttft_exemplar_resolves_via_tracez(lm, tmp_path):
    """The SLO-debugging loop: a TTFT histogram bucket carries an
    exemplar trace id, and /tracez?trace=<that id> returns the full
    assembled timeline in one step."""
    d = str(tmp_path)
    prompt = np.array([7, 7, 7], np.int32)
    with Router([_replica(lm, 0, d)], snapshot_dir=d,
                registry_max_age_s=5.0, shed_after_s=30.0) as router:
        _wait(lambda: any(
            r["healthy"] for r in router.records().values()),
            msg="replica healthy")
        out = router.submit_generate(prompt, 4, timeout=60.0)
        np.testing.assert_array_equal(out, solo(lm, prompt, 4))
    snap = families.generation_queue_to_first_token_seconds().snapshot()
    exemplars = snap.get("exemplars")
    assert exemplars, "TTFT observation carried no exemplar"
    tid = next(iter(exemplars.values()))["trace_id"]
    dz = Debugz(trace_shard_dir=d)
    resp = dz.tracez(trace=tid)
    assert resp["trace"]["trace_id"] == tid
    assert "request/admission" in resp["trace"]["names"]
    assert "request/decode" in resp["trace"]["names"]
    with pytest.raises(KeyError):
        dz.tracez(trace="no-such-trace")


def test_tracez_http_name_filter_and_400_contract():
    ctx = request_trace.mint()
    t = time.perf_counter()
    request_trace.record_span("request/queue", t, t + 0.01, ctx=ctx)
    tracing.record_span("optimizer/step", t, t + 0.01)
    srv = DebugzServer(Debugz()).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/tracez?name=request/",
                                    timeout=30) as r:
            body = json.load(r)
        assert body["name"] == "request/"
        assert body["spans"]
        assert all(s["name"].startswith("request/")
                   for s in body["spans"])
        with urllib.request.urlopen(
                base + f"/tracez?trace={ctx.trace_id}", timeout=30) as r:
            body = json.load(r)
        assert body["trace"]["trace_id"] == ctx.trace_id
        assert "retained" in body
        for bad in ("/tracez?limit=abc", "/tracez?bogus=1",
                    "/tracez?trace=missing"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + bad, timeout=30)
            assert ei.value.code == 400
    finally:
        srv.stop()
