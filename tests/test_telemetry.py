"""bigdl_tpu.telemetry: primitives, labels, tracing, exposition, the
serving bridge, thread-safety under fire, and the optimizer/chaos
integration the subsystem exists for — plus the satellite regressions
(utils/logger.log_file level, optim/profiling._timed restore).
"""

import io
import json
import logging
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import nn, telemetry
from bigdl_tpu.telemetry import families, tracing
from bigdl_tpu.telemetry.export import (
    PeriodicExporter, json_snapshot, prometheus_text,
)
from bigdl_tpu.telemetry.metrics import (
    Counter, Gauge, Histogram, TelemetryRegistry, get_registry,
)


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Each test starts enabled with zeroed metrics/spans and leaves
    the process disabled (the repo-wide default other tests assume)."""
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


# --------------------------------------------------------------------------
# primitives
# --------------------------------------------------------------------------

class TestPrimitives:
    def test_counter_semantics(self):
        r = TelemetryRegistry()
        c = r.counter("requests_total", "help text")
        c.inc()
        c.inc(3)
        assert c.value() == 4
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1)

    def test_gauge_semantics(self):
        r = TelemetryRegistry()
        g = r.gauge("depth")
        g.set(7)
        g.inc()
        g.dec(3)
        assert g.value() == 5

    def test_histogram_buckets_sum_count(self):
        r = TelemetryRegistry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        # +Inf bucket is appended automatically
        assert snap["buckets"] == [0.1, 1.0, 10.0, float("inf")]
        assert snap["counts"] == [1, 2, 1, 1]
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(56.05)

    def test_get_or_create_is_idempotent_and_type_checked(self):
        r = TelemetryRegistry()
        c1 = r.counter("a_total")
        assert r.counter("a_total") is c1
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("a_total")
        r.histogram("h_seconds")
        with pytest.raises(ValueError, match="already registered"):
            r.counter("h_seconds")

    def test_label_cardinality_enforced(self):
        r = TelemetryRegistry()
        c = r.counter("by_kind_total", labelnames=("kind",))
        c.labels("a").inc()
        c.labels("a").inc()
        c.labels("b").inc(5)
        assert c.labels("a").value() == 2
        assert c.labels("b").value() == 5
        with pytest.raises(ValueError, match="label value"):
            c.labels("a", "extra")
        with pytest.raises(ValueError, match=r"\.labels"):
            c.inc()  # labeled metric needs .labels() first
        with pytest.raises(ValueError, match="labels"):
            r.counter("by_kind_total", labelnames=("other",))

    def test_reset_zeroes_in_place_and_handles_stay_valid(self):
        r = TelemetryRegistry()
        c = r.counter("n_total")
        h = r.histogram("t_seconds")
        c.inc(9)
        h.observe(1.0)
        r.reset()
        assert c.value() == 0
        assert h.snapshot()["count"] == 0
        c.inc()  # the pre-reset handle still writes into the registry
        assert r.counter("n_total").value() == 1


# --------------------------------------------------------------------------
# tracing
# --------------------------------------------------------------------------

class TestTracing:
    def test_nesting_parent_child(self):
        with tracing.span("outer") as outer_id:
            with tracing.span("inner") as inner_id:
                assert tracing.current_span() == inner_id
            assert tracing.current_span() == outer_id
        spans = {s.name: s for s in tracing.finished_spans()}
        assert spans["inner"].parent_id == outer_id
        assert spans["outer"].parent_id is None
        assert spans["inner"].t_start >= spans["outer"].t_start
        assert spans["inner"].t_end <= spans["outer"].t_end

    def test_propagation_across_threads(self):
        token = {}

        def worker():
            with tracing.propagate(token["parent"]):
                with tracing.span("child_in_worker"):
                    pass

        with tracing.span("parent_span") as pid:
            token["parent"] = tracing.current_span()
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        spans = {s.name: s for s in tracing.finished_spans()}
        assert spans["child_in_worker"].parent_id == pid
        assert spans["child_in_worker"].thread != spans["parent_span"].thread

    def test_disabled_span_is_noop(self):
        telemetry.disable()
        with tracing.span("invisible") as sid:
            assert sid is None
        assert tracing.finished_spans() == []
        telemetry.enable()

    def test_record_span_retroactive(self):
        t0 = time.perf_counter()
        sid = tracing.record_span("retro", t0 - 1.0, t0, note="x")
        (s,) = tracing.finished_spans()
        assert s.span_id == sid and s.name == "retro"
        assert s.duration_s == pytest.approx(1.0)
        assert s.args == {"note": "x"}

    def test_ring_buffer_bounded(self):
        tracing.set_ring_capacity(8)
        try:
            for i in range(20):
                with tracing.span("s"):
                    pass
            assert len(tracing.finished_spans()) == 8
            assert tracing.dropped_spans() == 12
        finally:
            tracing.reset_spans()
            tracing.set_ring_capacity(16384)

    def test_chrome_trace_json_roundtrip(self):
        with tracing.span("alpha", foo=1):
            with tracing.span("beta"):
                pass
        trace = json.loads(json.dumps(tracing.chrome_trace()))
        events = trace["traceEvents"]
        assert {e["name"] for e in events} == {"alpha", "beta"}
        for e in events:
            for key in ("ph", "name", "cat", "ts", "dur", "pid", "tid",
                        "args"):
                assert key in e
            assert e["ph"] == "X" and e["dur"] >= 0
        beta = next(e for e in events if e["name"] == "beta")
        alpha = next(e for e in events if e["name"] == "alpha")
        assert beta["args"]["parent_id"] == alpha["args"]["span_id"]
        assert alpha["args"]["foo"] == 1

    def test_write_chrome_trace_file(self, tmp_path):
        with tracing.span("disk"):
            pass
        p = tracing.write_chrome_trace(str(tmp_path / "trace.json"))
        with open(p) as f:
            data = json.load(f)
        assert data["traceEvents"][0]["name"] == "disk"


# --------------------------------------------------------------------------
# exposition
# --------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"(\+Inf|-Inf|NaN|[0-9eE.+-]+)$")


class TestExposition:
    def test_prometheus_text_parses(self):
        r = TelemetryRegistry()
        r.counter("a_total", "with \"quotes\" and\nnewline").inc(2)
        r.gauge("g", labelnames=("k",)).labels('va"l').set(1.5)
        h = r.histogram("h_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = prometheus_text(r)
        for line in text.strip().splitlines():
            if line.startswith("#"):
                assert line.startswith(("# HELP ", "# TYPE "))
            else:
                assert _PROM_LINE.match(line), line
        # histogram: cumulative buckets, +Inf present, count/sum lines
        assert 'h_seconds_bucket{le="0.1"} 1' in text
        assert 'h_seconds_bucket{le="1"} 1' in text
        assert 'h_seconds_bucket{le="+Inf"} 2' in text
        assert "h_seconds_count 2" in text
        assert "a_total 2" in text

    def test_histogram_bucket_counts_monotone(self):
        r = TelemetryRegistry()
        h = r.histogram("m_seconds")
        rng = np.random.default_rng(0)
        for v in rng.exponential(0.3, size=200):
            h.observe(float(v))
        cums = [int(line.rsplit(" ", 1)[1])
                for line in prometheus_text(r).splitlines()
                if line.startswith("m_seconds_bucket")]
        assert cums == sorted(cums) and cums[-1] == 200

    def test_json_snapshot_shape(self):
        families.optimizer_retries_total().inc()
        families.optimizer_step_seconds().observe(0.01)
        with tracing.span("snap_span"):
            pass
        text = json.dumps(json_snapshot())
        # strict RFC-8259: the +Inf histogram bound must never leak as
        # the bare `Infinity` token (jq / JSON.parse reject the file)
        assert "Infinity" not in text
        snap = json.loads(text)
        m = snap["metrics"]["optimizer_retries_total"]
        assert m["kind"] == "counter"
        assert m["values"][0]["value"] == 1
        hist = snap["metrics"]["optimizer_step_seconds"]["values"][0]
        assert hist["value"]["buckets"][-1] == "+Inf"
        assert snap["spans"]["by_name"]["snap_span"]["count"] == 1

    def test_disabled_bridge_stays_inert(self):
        # --no-telemetry contract: with the switch off, a live serving
        # registry must not materialize serving_* families on scrape
        from bigdl_tpu.serving.metrics import MetricsRegistry
        fresh = TelemetryRegistry()
        import bigdl_tpu.telemetry.metrics as tmetrics
        orig = tmetrics._REGISTRY
        tmetrics._REGISTRY = fresh
        try:
            sreg = MetricsRegistry()
            sreg.record_batch(n_real=1, bucket=1, queue_depth=0,
                              latencies_s=[0.01])
            telemetry.disable()
            assert prometheus_text(fresh).strip() == ""
            telemetry.enable()
            assert "serving_requests_total 1" in prometheus_text(fresh)
        finally:
            tmetrics._REGISTRY = orig

    def test_serving_bridge_lands_in_unified_registry(self):
        from bigdl_tpu.serving.metrics import MetricsRegistry
        reg = MetricsRegistry()
        reg.record_batch(n_real=3, bucket=4, queue_depth=2,
                         latencies_s=[0.01, 0.02, 0.03])
        reg.record_shed()
        text = prometheus_text()
        assert re.search(r'serving_latency_ms\{quantile="p50"\} [0-9.]+',
                         text)
        assert "serving_requests_total 3" in text
        assert "serving_batches_total 1" in text
        assert "serving_shed_total 1" in text
        assert 'serving_batch_occupancy{rows="3"} 1' in text
        # the serving registry's own public schema is unchanged
        snap = reg.snapshot()
        assert set(snap) >= {"requests", "batches", "latency_ms",
                             "occupancy", "queue_depth_mean"}

    def test_dead_serving_registry_retires_its_collector(self):
        import gc
        from bigdl_tpu.serving.metrics import MetricsRegistry
        reg = get_registry()
        gc.collect()
        reg.run_collectors()  # purge corpses left by earlier tests
        before = len(reg._collectors)
        sreg = MetricsRegistry()
        assert len(reg._collectors) == before + 1
        del sreg
        gc.collect()
        reg.run_collectors()  # dead weakref -> collector unregisters
        assert len(reg._collectors) == before

    def test_preregistered_catalog_in_fresh_exposition(self):
        # enable() preregisters: a process that never trained still
        # exposes the optimizer/checkpoint families (at zero) — the
        # acceptance contract for one scrape config across roles
        text = prometheus_text()
        for fam in ("optimizer_step_seconds", "optimizer_retries_total",
                    "checkpoint_commit_seconds", "prefetch_queue_depth",
                    "serving_latency_ms"):
            assert f"# TYPE {fam} " in text

    def test_periodic_exporter_writes_and_stops_clean(self, tmp_path):
        families.prefetch_queue_depth().set(4)
        path = str(tmp_path / "telemetry.json")
        exp = PeriodicExporter(interval_s=0.05, path=path)
        exp.start()
        time.sleep(0.2)
        exp.stop(timeout=5.0)
        assert exp.exports >= 2 and exp.errors == 0
        with open(path) as f:
            data = json.load(f)
        vals = data["metrics"]["prefetch_queue_depth"]["values"]
        assert vals[0]["value"] == 4
        # stopped: no further exports
        n = exp.exports
        time.sleep(0.15)
        assert exp.exports == n

    def test_periodic_exporter_survives_raising_callback(self):
        """A callback raising mid-cycle must not kill the daemon: the
        failure is counted, later cycles still export, and stop() still
        runs its clean final export."""
        calls = []
        stop_seen = threading.Event()

        def fn(snap):
            calls.append(snap)
            if len(calls) == 1:
                raise RuntimeError("exporter backend down")
            stop_seen.set()

        exp = PeriodicExporter(interval_s=0.03, fn=fn)
        exp.start()
        assert stop_seen.wait(5.0), "daemon died after the first error"
        n_before_stop = len(calls)
        exp.stop(timeout=5.0)
        assert exp.errors == 1
        assert exp.exports >= 1
        # the clean final export on stop() ran (one more callback at
        # minimum beyond what the interval loop had already done)
        assert len(calls) >= n_before_stop + 1
        assert exp.exports + exp.errors == len(calls)
        # fully stopped: no further callbacks
        n = len(calls)
        time.sleep(0.1)
        assert len(calls) == n

    def test_telemetry_summary_tensorboard_roundtrip(self, tmp_path):
        from bigdl_tpu.visualization import TelemetrySummary
        families.optimizer_retries_total().inc(3)
        families.optimizer_step_seconds().observe(0.2)
        ts = TelemetrySummary(str(tmp_path), "app")
        ts.publish(step=1)
        vals = ts.read_scalar("telemetry/optimizer_retries_total")
        assert vals == [(1, 3.0)]
        ts.close()

    def test_runtime_sampling(self):
        from bigdl_tpu.telemetry.runtime import sample_runtime
        sample_runtime()
        assert families.process_rss_bytes().value() > 1 << 20
        # gc counters exist with per-generation labels
        text = prometheus_text()
        assert 'gc_collections_total{generation="0"}' in text


# --------------------------------------------------------------------------
# thread-safety under fire
# --------------------------------------------------------------------------

@pytest.mark.parametrize("n_writers", [8])
def test_stress_writers_vs_concurrent_snapshots(n_writers):
    """Writers hammer counters/histograms while snapshot/export run
    concurrently: totals must come out exact, and no reader may crash
    on a half-updated structure."""
    c = families.optimizer_retries_total()
    h = families.optimizer_step_seconds()
    per_thread = 2000
    stop_readers = threading.Event()
    reader_errors = []

    def write():
        for i in range(per_thread):
            c.inc()
            h.observe(0.001 * (i % 7))
            if i % 64 == 0:
                with tracing.span("stress"):
                    pass

    def read():
        while not stop_readers.is_set():
            try:
                prometheus_text()
                json_snapshot()
                get_registry().snapshot()
            except Exception as e:  # pragma: no cover - the assertion
                reader_errors.append(e)
                return

    readers = [threading.Thread(target=read) for _ in range(2)]
    writers = [threading.Thread(target=write) for _ in range(n_writers)]
    [t.start() for t in readers + writers]
    [t.join() for t in writers]
    stop_readers.set()
    [t.join(5.0) for t in readers]
    assert not reader_errors
    assert c.value() == n_writers * per_thread
    assert h.snapshot()["count"] == n_writers * per_thread


# --------------------------------------------------------------------------
# optimizer integration (the tentpole's acceptance scenario)
# --------------------------------------------------------------------------

def _samples(n=32, dim=6, classes=4, seed=0):
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(seed)
    return [Sample(rng.normal(size=(dim,)).astype(np.float32),
                   int(rng.integers(1, classes + 1))) for _ in range(n)]


def _model(dim=6, classes=4):
    return nn.Sequential(nn.Linear(dim, 8), nn.ReLU(),
                         nn.Linear(8, classes), nn.LogSoftMax())


def _dataset(samples, batch=16):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    return DataSet.array(samples).transform(SampleToMiniBatch(batch))


def test_optimizer_populates_step_phase_histograms(tmp_path):
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.optim.validation import Top1Accuracy
    samples = _samples()
    opt = (Optimizer(_model(), _dataset(samples), nn.ClassNLLCriterion())
           .set_end_when(Trigger.max_epoch(2))
           .set_validation(Trigger.every_epoch(), _dataset(samples),
                           [Top1Accuracy()])
           .set_checkpoint(str(tmp_path / "ck"), Trigger.every_epoch()))
    opt.optimize()
    # 2 epochs x 2 batches: every phase histogram saw real observations
    assert families.optimizer_step_seconds().snapshot()["count"] == 4
    assert families.optimizer_data_wait_seconds().snapshot()["count"] == 4
    assert families.optimizer_validation_seconds().snapshot()["count"] == 2
    assert families.checkpoint_commit_seconds().snapshot()["count"] == 2
    names = {s.name for s in tracing.finished_spans()}
    assert {"optimizer/step", "optimizer/data_wait",
            "optimizer/validation", "checkpoint/commit"} <= names
    # single timeline: every span (record_span'd from the loop AND
    # span()'d from validation/checkpoint) must share one clock — a
    # time.time() stamp leaking into the perf_counter trace would land
    # ~an epoch away
    ts = [e["ts"] for e in tracing.chrome_trace()["traceEvents"]]
    assert max(ts) - min(ts) < 600e6  # all within 10 minutes


def test_chaos_run_retry_counter_matches_faults_and_trace_breakdown(
        tmp_path):
    """The ISSUE acceptance scenario: a chaos-enabled optimize() whose
    Chrome trace shows the data-wait/step/validation/checkpoint
    breakdown and whose retry counter equals the injected fault
    count."""
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.optim.validation import Top1Accuracy
    from bigdl_tpu.utils import chaos
    chaos.reset()
    ctrl = chaos.install(fail_at_step=3)
    try:
        samples = _samples()
        opt = (Optimizer(_model(), _dataset(samples),
                         nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(3))
               .set_validation(Trigger.every_epoch(), _dataset(samples),
                               [Top1Accuracy()])
               .set_checkpoint(str(tmp_path / "ck"),
                               Trigger.every_epoch(), keep_n=3)
               .set_failure_retry(2, interval_s=300, backoff_s=0.01,
                                  backoff_cap_s=0.02))
        opt.optimize()
    finally:
        chaos.reset()
    injected = sum("injected failure" in e for e in ctrl.events)
    assert injected == 1
    assert families.chaos_faults_injected_total().value() == injected
    assert families.optimizer_retries_total().value() == injected
    trace = json.loads(json.dumps(tracing.chrome_trace()))
    by_name = {}
    for e in trace["traceEvents"]:
        by_name.setdefault(e["name"], []).append(e)
    for phase in ("optimizer/data_wait", "optimizer/step",
                  "optimizer/validation", "checkpoint/commit"):
        assert by_name.get(phase), f"missing {phase} spans"
    # the step spans carry the data-wait attribution for the breakdown
    assert all("data_wait_s" in e["args"]
               for e in by_name["optimizer/step"])


def test_prefetch_gauge_and_wait_counters():
    from bigdl_tpu.dataset.prefetch import Prefetch

    out = []
    depths = []
    gauge = families.prefetch_queue_depth()
    # slow consumer: the producer races ahead, fills the n_ahead=2
    # queue, and must wait — the signature of a healthy pipeline
    for item in Prefetch(n_ahead=2).apply(iter(range(6))):
        time.sleep(0.05)
        depths.append(gauge.value())
        out.append(item)
    assert out == list(range(6))
    assert families.prefetch_producer_wait_total().value() >= 1
    assert max(depths) >= 1  # ready batches were buffered ahead


def test_serving_spans_and_http_metrics_endpoint():
    """curl-level acceptance: /metrics under --dynamic-batch load
    returns Prometheus text with serving quantiles, queue depth, AND
    optimizer/checkpoint families from the same registry."""
    import http.client
    from bigdl_tpu.examples.serve import BatchedBytesFrontend, make_server
    from bigdl_tpu.serving import ModelServer

    model = _model(dim=4, classes=3)
    mserver = ModelServer(model, max_batch=4, batch_timeout_ms=50.0)
    httpd = make_server(BatchedBytesFrontend(mserver), "127.0.0.1", 0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        port = httpd.server_port
        rng = np.random.default_rng(3)
        xs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(6)]

        def post(x):
            buf = io.BytesIO()
            np.save(buf, x, allow_pickle=False)
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            conn.request("POST", "/predict", buf.getvalue())
            np.load(io.BytesIO(conn.getresponse().read()),
                    allow_pickle=False)
            conn.close()

        threads = [threading.Thread(target=post, args=(x,)) for x in xs]
        [th.start() for th in threads]
        [th.join() for th in threads]

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        conn.close()
    finally:
        httpd.shutdown()
        httpd.server_close()
        mserver.shutdown()
    assert "serving_requests_total 6" in body
    assert re.search(r'serving_latency_ms\{quantile="p99"\} [0-9.]+',
                     body)
    assert "serving_queue_depth" in body
    # optimizer + checkpoint families in the SAME exposition
    assert "# TYPE optimizer_step_seconds histogram" in body
    assert "# TYPE checkpoint_commit_seconds histogram" in body
    # request-path spans were recorded
    names = {s.name for s in tracing.finished_spans()}
    assert {"serving/enqueue", "serving/batch", "serving/execute",
            "serving/reply"} <= names


def test_metrics_lint_passes_on_this_tree():
    proc = subprocess.run(
        [sys.executable, "scripts/metrics_lint.py"],
        capture_output=True, text=True,
        cwd=__import__("os").path.dirname(__import__("os").path.dirname(
            __import__("os").path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --------------------------------------------------------------------------
# satellite regressions
# --------------------------------------------------------------------------

def test_log_file_captures_info_records(tmp_path):
    """utils/logger.log_file: the bigdl_tpu logger defaulted to the
    root WARNING level, so INFO framework records never reached the
    file — the handler must come with an INFO logger level."""
    from bigdl_tpu.utils.logger import log_file
    path = str(tmp_path / "bigdl.log")
    lg = logging.getLogger("bigdl_tpu")
    prev_level = lg.level
    try:
        lg.setLevel(logging.NOTSET)  # the broken default
        log_file(path)
        logging.getLogger("bigdl_tpu.optim").info("iteration 1 done")
        for h in lg.handlers:
            h.flush()
        with open(path) as f:
            content = f.read()
        assert "iteration 1 done" in content
    finally:
        from bigdl_tpu.utils.logger import _drop_ours
        _drop_ours(lg, path)
        lg.setLevel(prev_level)


def test_log_file_does_not_lower_debug_level(tmp_path):
    from bigdl_tpu.utils.logger import _drop_ours, log_file
    path = str(tmp_path / "bigdl2.log")
    lg = logging.getLogger("bigdl_tpu")
    prev_level = lg.level
    try:
        lg.setLevel(logging.DEBUG)
        log_file(path)
        assert lg.level == logging.DEBUG  # opt-in verbosity kept
    finally:
        _drop_ours(lg, path)
        lg.setLevel(prev_level)


def test_timed_restores_preexisting_instance_forward():
    """optim/profiling._timed: restore must put back a pre-existing
    INSTANCE-level forward binding instead of deleting it (the old
    object.__delattr__ path destroyed user monkeypatches)."""
    from bigdl_tpu.optim.profiling import module_forward_times
    model = _model(dim=4, classes=3)
    lin = model[0]
    calls = []
    orig_forward = lin.forward

    def counting_forward(*a, **k):
        calls.append(1)
        return orig_forward(*a, **k)

    object.__setattr__(lin, "forward", counting_forward)
    x = np.zeros((2, 4), np.float32)
    records = module_forward_times(model, x)
    assert records  # timing ran
    # the instance-level binding survived the restore
    assert lin.__dict__.get("forward") is counting_forward
    n_before = len(calls)
    model.forward(x)
    assert len(calls) == n_before + 1
    # modules with NO prior instance forward got theirs cleanly removed
    assert "forward" not in model[2].__dict__


def test_module_forward_times_routes_into_telemetry():
    from bigdl_tpu.optim.profiling import module_forward_times
    model = _model(dim=4, classes=3)
    module_forward_times(model, np.zeros((2, 4), np.float32))
    hist = families.module_forward_seconds()
    assert hist.labels("Linear").snapshot()["count"] == 2
    assert hist.labels("ReLU").snapshot()["count"] == 1
