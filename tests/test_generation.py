"""Continuous batching for generation (serving/generation.py): greedy
equivalence with solo ``generate()`` across join/leave orderings, O(1)
compile counts, slot-pool cache donation, streaming, admission, drain,
and the ModelServer generation backend.

The load-bearing assertion (ISSUE 10 acceptance): every request's
emitted tokens are BIT-IDENTICAL to a solo ``model.generate()`` call at
fixed seed, regardless of which requests share the pool or the order
they join and leave.
"""

import threading
import time

import numpy as np
import pytest

from bigdl_tpu.models import transformer_lm
from bigdl_tpu.serving import (
    GenerationScheduler, ModelServer, QueueFullError, ServerClosedError,
)
from bigdl_tpu.serving.generation import SlotPool, run_mixed_workload
from bigdl_tpu.utils import set_seed


@pytest.fixture(scope="module")
def lm():
    set_seed(0)
    return transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                          num_heads=4, filter_size=64,
                          max_len=64).eval_mode()


_SOLO_CACHE = {}


def solo(model, prompt, max_new, eos_id=None):
    """Reference row from model.generate, memoized (eager generate
    re-traces per shape, the expensive part of these tests)."""
    import jax.numpy as jnp
    key = (id(model), prompt.tobytes(), int(max_new), eos_id)
    if key not in _SOLO_CACHE:
        _SOLO_CACHE[key] = np.asarray(model.generate(
            jnp.asarray(prompt, jnp.int32)[None], int(max_new),
            eos_id=eos_id))[0]
    return _SOLO_CACHE[key]


def _requests(rng, n, max_len=64, pmax=20, nmax=10):
    prompts = [rng.integers(1, 51, rng.integers(1, pmax)).astype(np.int32)
               for _ in range(n)]
    max_news = [int(rng.integers(2, nmax)) for _ in range(n)]
    return prompts, max_news


# ---------------------------------------------------------------------------
# the acceptance property: bit-identical greedy rows, any pool sharing
# ---------------------------------------------------------------------------

def test_greedy_equivalence_mixed_lengths(lm):
    rng = np.random.default_rng(0)
    prompts, max_news = _requests(rng, 10)
    eng = GenerationScheduler(lm, slots=4, prefill_batch=2)
    try:
        futs = [eng.submit_async(p, m)
                for p, m in zip(prompts, max_news)]
        rows = [f.result(timeout=120) for f in futs]
    finally:
        eng.shutdown()
    for p, m, row in zip(prompts, max_news, rows):
        np.testing.assert_array_equal(row, solo(lm, p, m))


def test_greedy_equivalence_randomized_arrivals(lm):
    """Property-style: the SAME request set under different randomized
    arrival schedules (submission order + staggering) must emit the
    same bit-identical rows — join/leave ordering cannot leak between
    co-resident slots."""
    rng = np.random.default_rng(1)
    prompts, max_news = _requests(rng, 8)
    want = [solo(lm, p, m) for p, m in zip(prompts, max_news)]
    for schedule_seed in (0, 1, 2):
        srng = np.random.default_rng(schedule_seed)
        order = srng.permutation(len(prompts))
        eng = GenerationScheduler(lm, slots=3, prefill_batch=2)
        try:
            futs = {}
            for i in order:
                futs[i] = eng.submit_async(prompts[i], max_news[i])
                if srng.random() < 0.5:
                    # stagger: some requests join mid-decode of others
                    time.sleep(float(srng.random()) * 0.05)
            for i, f in futs.items():
                np.testing.assert_array_equal(
                    f.result(timeout=120), want[i],
                    err_msg=f"schedule {schedule_seed}, request {i}")
        finally:
            eng.shutdown()


def test_eos_leaves_slot_without_disturbing_neighbors(lm):
    """A request hitting EOS leaves mid-flight; its row matches solo
    generate (EOS emitted, zeros after) and co-resident requests are
    unaffected."""
    rng = np.random.default_rng(2)
    prompts, _ = _requests(rng, 4)
    # pick row 0's first greedily-generated token as the "EOS" so it
    # fires on the very first decode step for that request
    eos = int(solo(lm, prompts[0], 6)[len(prompts[0])])
    want = [solo(lm, p, 6, eos_id=eos) for p in prompts]
    eng = GenerationScheduler(lm, slots=4, eos_id=eos)
    try:
        futs = [eng.submit_async(p, 6) for p in prompts]
        rows = [f.result(timeout=120) for f in futs]
    finally:
        eng.shutdown()
    for row, w in zip(rows, want):
        np.testing.assert_array_equal(row, w)
    # row 0 really stopped at EOS: everything after it is 0-padding
    i0 = len(prompts[0])
    assert rows[0][i0] == eos and not rows[0][i0 + 1:].any()


# ---------------------------------------------------------------------------
# compiled-program budget + donation
# ---------------------------------------------------------------------------

def test_decode_compile_count_is_o1_in_requests(lm):
    """The pooled decode step compiles ONCE per (S, dtype) and prefill
    once per prompt bucket, across many requests joining and leaving in
    arbitrary order (the hlo-recompile determinism idea, applied to the
    engine)."""
    rng = np.random.default_rng(3)
    prompts, max_news = _requests(rng, 14, pmax=33)
    eng = GenerationScheduler(lm, slots=4, prefill_batch=2)
    try:
        futs = [eng.submit_async(p, m)
                for p, m in zip(prompts, max_news)]
        [f.result(timeout=120) for f in futs]
        counts = dict(eng.pool.trace_counts)
    finally:
        eng.shutdown()
    assert counts["decode"] == 1, counts
    assert counts["prefill"], "no prefill bucket was traced"
    assert all(n == 1 for n in counts["prefill"].values()), counts
    assert all(n == 1 for n in counts["scatter"].values()), counts
    # buckets are powers of two over the prompt lengths seen
    for b in counts["prefill"]:
        assert b & (b - 1) == 0, f"non-power-of-two bucket {b}"


def test_slot_pool_cache_donation_hlo_alias(lm):
    """The compiled decode step's input_output_alias must cover at
    least the full slot-pool cache bytes — donation really elides the
    per-iteration copy of S x layers x max_len K/V (the existing
    hlo-donation machinery, pointed at the serving program)."""
    from bigdl_tpu.analysis.hlo_lint import donated_alias_bytes
    pool = SlotPool(lm, slots=4)
    need = pool.cache_nbytes()
    got, n = donated_alias_bytes(pool.decode_hlo_text())
    assert n > 0
    assert got >= need, (got, need)


# ---------------------------------------------------------------------------
# streaming, stats, validation, admission
# ---------------------------------------------------------------------------

def test_on_token_streams_in_decode_order(lm):
    rng = np.random.default_rng(4)
    prompt = rng.integers(1, 51, 5).astype(np.int32)
    got = []
    eng = GenerationScheduler(lm, slots=2)
    try:
        fut = eng.submit_async(prompt, 6, on_token=got.append)
        row = fut.result(timeout=120)
    finally:
        eng.shutdown()
    want = solo(lm, prompt, 6)
    np.testing.assert_array_equal(row, want)
    assert got == [int(t) for t in want[len(prompt):len(prompt) + 6]]


def test_stats_and_queue_to_first_token(lm):
    rng = np.random.default_rng(5)
    prompts, max_news = _requests(rng, 5)
    eng = GenerationScheduler(lm, slots=2)
    try:
        futs = [eng.submit_async(p, m)
                for p, m in zip(prompts, max_news)]
        [f.result(timeout=120) for f in futs]
        stats = eng.stats()
    finally:
        eng.shutdown()
    assert stats["requests_done"] == 5
    assert stats["tokens_emitted"] == sum(max_news)
    assert stats["decode_steps"] >= max(max_news)
    assert 0 < stats["slot_occupancy_mean"] <= 2.0
    assert stats["queue_to_first_token_s_mean"] > 0
    assert stats["tokens_per_second"] > 0
    assert stats["prefill_calls"] >= 1


def test_validation_errors(lm):
    eng = GenerationScheduler(lm, slots=2)
    try:
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit_async(np.arange(1, 60, dtype=np.int32), 30)
        with pytest.raises(ValueError, match="empty"):
            eng.submit_async(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit_async(np.ones((3,), np.int32), 0)
    finally:
        eng.shutdown()


def test_generation_admission_reject_policy(lm):
    """The bounded generation queue honors the one-shot admission
    policies: reject fails fast once capacity is hit."""
    eng = GenerationScheduler(lm, slots=1, queue_capacity=1,
                              admission="reject", start=False)
    # not started: nothing drains the queue, so capacity is decisive
    eng.submit_async(np.ones((2,), np.int32), 2)
    with pytest.raises(QueueFullError):
        eng.submit_async(np.ones((2,), np.int32), 2)
    eng.start()
    eng.shutdown(drain=True)


def test_cancelled_future_frees_no_slot(lm):
    rng = np.random.default_rng(6)
    prompts, max_news = _requests(rng, 3)
    eng = GenerationScheduler(lm, slots=1, start=False)
    futs = [eng.submit_async(p, m) for p, m in zip(prompts, max_news)]
    assert futs[1].cancel()     # still queued -> cancellable
    eng.start()
    eng.shutdown(drain=True)
    np.testing.assert_array_equal(futs[0].result(timeout=60),
                                  solo(lm, prompts[0], max_news[0]))
    np.testing.assert_array_equal(futs[2].result(timeout=60),
                                  solo(lm, prompts[2], max_news[2]))
    assert futs[1].cancelled()


def test_engine_survives_decode_failure(lm):
    """A failing pooled decode fails the RESIDENT futures with the
    error and keeps the engine thread alive for later arrivals — the
    BatchScheduler invariant, kept for the multi-step plane (a dead
    engine thread would strand RUNNING futures forever)."""
    rng = np.random.default_rng(11)
    p1 = rng.integers(1, 51, 4).astype(np.int32)
    p2 = rng.integers(1, 51, 4).astype(np.int32)
    eng = GenerationScheduler(lm, slots=2)
    try:
        calls = {"n": 0}
        orig = eng.pool.decode_dispatch

        def boom():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("device on fire")
            return orig()

        # engine is idle (blocked on the queue) here, so the patch
        # lands before any decode of p1 can start
        eng.pool.decode_dispatch = boom
        f1 = eng.submit_async(p1, 4)
        with pytest.raises(RuntimeError, match="device on fire"):
            f1.result(timeout=60)
        assert eng.alive
        f2 = eng.submit_async(p2, 4)
        np.testing.assert_array_equal(f2.result(timeout=60),
                                      solo(lm, p2, 4))
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# ModelServer generation backend
# ---------------------------------------------------------------------------

def test_model_server_generation_backend(lm):
    rng = np.random.default_rng(7)
    prompts, max_news = _requests(rng, 6)
    server = ModelServer(generator=lm, slots=3)
    try:
        rows = server.submit_generate_many(prompts, max_news,
                                           timeout=120)
        for p, m, row in zip(prompts, max_news, rows):
            np.testing.assert_array_equal(row, solo(lm, p, m))
        one = server.submit_generate(prompts[0], max_news[0],
                                     timeout=120)
        np.testing.assert_array_equal(one,
                                      solo(lm, prompts[0], max_news[0]))
        # a numpy integer budget (rng.integers) broadcasts like an int
        np_rows = server.submit_generate_many(prompts[:2], np.int64(3),
                                              timeout=120)
        np.testing.assert_array_equal(np_rows[1], solo(lm, prompts[1], 3))
        # a short per-prompt budget list is an error, not silent drops
        with pytest.raises(ValueError, match="per prompt"):
            server.submit_generate_many(prompts[:3], [2, 2])
        # generation-only server: one-shot submission is a clear error
        with pytest.raises(RuntimeError, match="one-shot"):
            server.submit(np.ones((4,), np.float32))
        assert server.generation_stats()["requests_done"] == 9
    finally:
        server.shutdown()
    with pytest.raises(ServerClosedError):
        server.submit_generate_async(prompts[0], 2)


def test_model_server_requires_some_backend():
    with pytest.raises(TypeError, match="backend"):
        ModelServer()


def test_model_server_both_backends(lm):
    """A server may carry the one-shot batcher AND the generation
    engine; each request class routes to its own scheduler."""
    import bigdl_tpu.nn as nn
    set_seed(3)
    clf = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3),
                        nn.LogSoftMax())
    server = ModelServer(clf, max_batch=4, batch_timeout_ms=5.0,
                         generator=lm, slots=2)
    try:
        y = server.submit(np.ones((4,), np.float32), timeout=60)
        assert y.shape == (3,)
        prompt = np.asarray([3, 1, 4], np.int32)
        row = server.submit_generate(prompt, 3, timeout=120)
        np.testing.assert_array_equal(row, solo(lm, prompt, 3))
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# telemetry wiring
# ---------------------------------------------------------------------------

def test_generation_families_recorded_when_enabled(lm):
    from bigdl_tpu import telemetry
    telemetry.enable()
    telemetry.reset()
    try:
        rng = np.random.default_rng(8)
        prompts, max_news = _requests(rng, 4)
        eng = GenerationScheduler(lm, slots=2)
        try:
            futs = [eng.submit_async(p, m)
                    for p, m in zip(prompts, max_news)]
            [f.result(timeout=120) for f in futs]
        finally:
            eng.shutdown()
        text = telemetry.prometheus_text()
        assert 'generation_phase_seconds_count{phase="decode"}' in text
        assert 'generation_phase_seconds_count{phase="prefill"}' in text
        assert "generation_slot_occupancy" in text
        assert "generation_queue_to_first_token_seconds_count" in text
        assert "generation_tokens_per_second" in text
        # spans: prefill batches + one retroactive span per request
        names = {s.name for s in telemetry.finished_spans()}
        assert "serving/prefill" in names
        assert "serving/generate" in names
    finally:
        telemetry.reset()
        telemetry.disable()


def test_generation_telemetry_off_by_default(lm):
    """With telemetry disabled the engine must not create families."""
    from bigdl_tpu import telemetry
    telemetry.disable()
    telemetry.get_registry().clear()
    rng = np.random.default_rng(9)
    eng = GenerationScheduler(lm, slots=2)
    try:
        eng.submit(rng.integers(1, 51, 4).astype(np.int32), 3,
                   timeout=120)
    finally:
        eng.shutdown()
    assert "generation_" not in telemetry.prometheus_text()


# ---------------------------------------------------------------------------
# workload harness (shared with bench.py + serving_gen_smoke.sh)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_run_mixed_workload_speedup_and_equivalence(lm):
    """The acceptance harness end-to-end at reduced scale: continuous
    batching beats sequential generate() and stays bit-identical.  The
    full 32-request, >=3x assertion lives in serving_gen_smoke.sh and
    the bench generate_serving phase."""
    rng = np.random.default_rng(10)
    prompts = [rng.integers(1, 51, rng.integers(4, 25)).astype(np.int32)
               for _ in range(10)]
    max_news = [int(rng.integers(6, 20)) for _ in range(10)]
    out = run_mixed_workload(lm, prompts, max_news, slots=4)
    # no sequential_sample: every row was compared against its oracle
    assert out["greedy_checked_requests"] == len(prompts)
    assert out["greedy_equal_checked"]
    assert out["speedup_vs_sequential"] > 1.5
    assert out["total_new_tokens"] == sum(max_news)
