"""Self-driving fleet tests (fleet/policy.py + fleet/controller.py +
fleet/watcher.py): hysteresis/cooldown/clamp policy logic with
injected time, dead-replica replacement and breach-driven scaling
against a fake router, zero-drop drain-out on scale-down, doctored
registry reads holding (never wedging) the loop, rolling hot-deploy of
new CRC-verified checkpoint generations with torn payloads never
deploying, the ``/statusz`` ``controller`` section, auto-resume of
preempted training, and the closed-loop acceptance scenario: chaos
kill under load -> replacement + scale-up -> live hot-deploy with
greedy rows bit-identical across the swap and zero dropped admitted
requests.

The load-bearing assertions: (a) the controller acts with NO operator
step — the fault-to-recovery path is registry poll -> policy ->
actuation only; (b) every removal (dead, drain-out, deploy) waits for
``admitted_outstanding() == 0``; (c) the four controller event kinds
each have exactly ONE emission site in the tree.
"""

import ast
import os
import re
import time

import numpy as np
import pytest

from bigdl_tpu.fleet.controller import (FleetController,
                                        TrainingSupervisor,
                                        controller_statusz,
                                        register_statusz,
                                        unregister_statusz)
from bigdl_tpu.fleet.policy import (Decision, Observation, PoolSpec,
                                    ScalingPolicy)
from bigdl_tpu.fleet.watcher import CheckpointWatcher
from bigdl_tpu.telemetry import events
from bigdl_tpu.utils.file import CheckpointManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_replica_ids():
    # the allocator is process-global and monotonic on purpose (id
    # reuse would pin stale registry records onto fresh replicas);
    # tests reset it so spawned-id assertions are deterministic
    import bigdl_tpu.fleet.controller as _ctl
    with _ctl._id_lock:
        _ctl._next_rid = 0
    yield


# ---------------------------------------------------------------------------
# policy: hysteresis, cooldown, clamps (pure, injected time)
# ---------------------------------------------------------------------------

def _spec(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("queue_high", 5)
    kw.setdefault("queue_low", 1)
    kw.setdefault("breach_consecutive", 2)
    kw.setdefault("clear_consecutive", 3)
    kw.setdefault("cooldown_s", 10.0)
    return PoolSpec(**kw)


def test_policy_breach_needs_consecutive_ticks():
    pol = ScalingPolicy(_spec())
    hot = Observation(live=1, desired=1, queue_depth=9)
    calm = Observation(live=1, desired=1, queue_depth=0)
    assert pol.decide(hot, now=0.0).action is None   # streak 1 of 2
    # one calm tick resets the streak: a noisy snapshot never scales
    assert pol.decide(calm, now=1.0).action is None
    assert pol.decide(hot, now=2.0).action is None
    d = pol.decide(hot, now=3.0)
    assert d.action == "up" and "queue depth 9" in d.reason


def test_policy_cooldown_holds_with_stable_key():
    pol = ScalingPolicy(_spec())
    hot = Observation(live=1, desired=1, queue_depth=9)
    pol.decide(hot, now=0.0)
    assert pol.decide(hot, now=1.0).action == "up"
    pol.actuated(now=1.0)
    pol.decide(hot, now=2.0)
    held = pol.decide(hot, now=3.0)
    assert held.action == "hold" and held.key == "cooldown"
    assert "cooling down" in held.reason
    assert pol.cooldown_remaining(3.0) == pytest.approx(8.0)
    # past the cooldown the same breach goes through
    pol.decide(hot, now=12.0)
    assert pol.decide(hot, now=12.5).action == "up"


def test_policy_holds_at_max_and_steady_at_min():
    pol = ScalingPolicy(_spec(max_replicas=2))
    hot = Observation(live=2, desired=2, queue_depth=9)
    pol.decide(hot, now=0.0)
    d = pol.decide(hot, now=1.0)
    assert d.action == "hold" and d.key == "at-max"
    assert "max_replicas=2" in d.reason
    # idle at the floor is steady state, not a suppressed action
    pol2 = ScalingPolicy(_spec())
    idle = Observation(live=1, desired=1, queue_depth=0, inflight=0)
    for t in range(5):
        d = pol2.decide(idle, now=float(t))
    assert d.action is None and d.reason == ""


def test_policy_scales_down_after_clear_streak():
    pol = ScalingPolicy(_spec(cooldown_s=0.0))
    idle = Observation(live=3, desired=3, queue_depth=0, inflight=1)
    assert pol.decide(idle, now=0.0).action is None
    assert pol.decide(idle, now=1.0).action is None
    d = pol.decide(idle, now=2.0)
    assert d.action == "down" and "idle for 3 ticks" in d.reason


def test_policy_ttft_and_shed_breaches():
    pol = ScalingPolicy(_spec(ttft_high_s=0.5, breach_consecutive=1))
    d = pol.decide(Observation(live=1, desired=1, ttft_p99_s=0.9),
                   now=0.0)
    assert d.action == "up" and "ttft_p99" in d.reason
    pol2 = ScalingPolicy(_spec(breach_consecutive=1))
    d = pol2.decide(Observation(live=1, desired=1, shed_delta=3),
                    now=0.0)
    assert d.action == "up" and "3 request(s) shed" in d.reason


def test_pool_spec_validates_envelope_and_dead_band():
    with pytest.raises(ValueError, match="min_replicas"):
        PoolSpec(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        PoolSpec(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="dead band"):
        PoolSpec(queue_high=4, queue_low=4)
    assert _spec().clamp(99) == 4 and _spec().clamp(0) == 1


# ---------------------------------------------------------------------------
# event vocabulary: pinned, and one emission site per kind
# ---------------------------------------------------------------------------

def test_controller_kinds_in_pinned_vocabulary():
    for kind in ("scale_up", "scale_down", "hot_deploy",
                 "controller_hold"):
        assert kind in events.EVENT_KINDS


def _record_event_literals():
    """Every ``record_event("<literal>", ...)`` call site in the
    shipped tree, kind -> [file, ...]."""
    sites = {}
    for root, _dirs, files in os.walk(os.path.join(REPO, "bigdl_tpu")):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            if "record_event" not in src:
                continue
            for node in ast.walk(ast.parse(src)):
                if isinstance(node, ast.Call) \
                        and getattr(node.func, "attr",
                                    getattr(node.func, "id", None)) \
                        == "record_event" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    sites.setdefault(node.args[0].value, []).append(
                        os.path.relpath(path, REPO))
    return sites


def test_every_shipped_call_site_uses_vocabulary_kind():
    sites = _record_event_literals()
    unknown = {k: v for k, v in sites.items()
               if k not in events.EVENT_KINDS}
    assert not unknown, f"record_event kinds outside EVENT_KINDS: " \
                        f"{unknown}"


def test_controller_kinds_have_exactly_one_emission_site():
    sites = _record_event_literals()
    for kind in ("scale_up", "scale_down", "hot_deploy",
                 "controller_hold"):
        assert len(sites.get(kind, [])) == 1, \
            f"{kind} must have exactly one emission site, " \
            f"got {sites.get(kind)}"


# ---------------------------------------------------------------------------
# controller against a fake router (deterministic reconcile_once)
# ---------------------------------------------------------------------------

class FakeReplica:
    def __init__(self, rid, model="default", outstanding=0):
        self.id = rid
        self.model = model
        self.outstanding = outstanding

    def admitted_outstanding(self):
        return self.outstanding


class FakeRouter:
    """Registry + membership + actuation surface the controller
    drives, with scriptable records (``recs``) and stats."""

    def __init__(self, replicas=(), records=None, stats=None):
        self.replicas = {r.id: r for r in replicas}
        self.recs = dict(records or {})
        self.stats_d = dict(stats or {})
        self.added, self.drained, self.removed = [], [], []
        self.registry = self
        self.poll_error = None

    # registry half
    def poll(self):
        if self.poll_error is not None:
            raise self.poll_error
        return dict(self.recs)

    def records(self):                        # Router.records() shape
        return dict(self.recs)

    # router half
    def stats(self):
        return dict(self.stats_d)

    def replica_ids(self):
        return sorted(self.replicas)

    def replica(self, rid):
        return self.replicas.get(int(rid))

    def add_replica(self, replica):
        self.replicas[replica.id] = replica
        self.added.append(replica.id)

    def drain(self, rid):
        self.drained.append(rid)

    def remove_replica(self, rid, drain=True, timeout=None):
        self.replicas.pop(rid, None)
        self.removed.append(rid)

    def set_slo_class(self, model, slo):
        self.stats_d.setdefault("slo_classes", {})[model] = slo

    def set_admission_budget(self, model, budget):
        self.stats_d.setdefault("budgets", {})[model] = budget


def _healthy(rid, model="default", **kw):
    rec = {"id": rid, "healthy": True, "reason": None,
           "draining": False, "model": model, "queue_depth": 0,
           "ttft_p99_s": 0.0}
    rec.update(kw)
    return rec


def _mk_controller(router, **spec_kw):
    spec_kw.setdefault("cooldown_s", 0.0)
    spec_kw.setdefault("max_replicas", 4)
    factory_calls = []

    def factory(rid, model, ckpt):
        factory_calls.append((rid, model, ckpt))
        return FakeReplica(rid, model)

    ctl = FleetController(router, factory,
                          pools=[PoolSpec(**spec_kw)],
                          interval_s=0.01)
    ctl._factory_calls = factory_calls
    return ctl


def test_controller_replaces_dead_after_streak():
    # the victim carries admitted work so its removal must wait for
    # the drain, not ride along with the replacement tick
    router = FakeRouter([FakeReplica(0), FakeReplica(1, outstanding=3)],
                        records={0: _healthy(0), 1: _healthy(1)})
    ctl = _mk_controller(router, dead_after_polls=2)
    ctl.reconcile_once()                     # desired pins to 2
    router.recs[1] = _healthy(1, healthy=False, reason="stale")
    st = ctl.reconcile_once()                # streak 1: no action yet
    assert router.added == [] and st["pools"]["default"]["live"] == 2
    before = len(events.recent_events(500))
    st = ctl.reconcile_once()                # streak 2: dead -> replace
    pool = st["pools"]["default"]
    assert router.added == [2]
    assert 1 in pool["dying"]
    assert ctl._factory_calls[-1] == (2, "default", None)
    new = [e for e in events.recent_events(500)[before:]
           if e["kind"] == "scale_up"]
    assert len(new) == 1 and "dead" in new[0]["reason"]
    # the dead replica leaves only once admitted work drains to zero
    ctl.reconcile_once()
    assert 1 not in router.removed
    router.replicas[1].outstanding = 0
    ctl.reconcile_once()
    assert 1 in router.removed


def test_controller_scales_up_on_queue_breach():
    router = FakeRouter([FakeReplica(0)], records={0: _healthy(0)})
    ctl = _mk_controller(router, queue_high=5, breach_consecutive=2)
    ctl.reconcile_once()
    router.recs[0] = _healthy(0, queue_depth=9)
    ctl.reconcile_once()                     # streak 1
    assert router.added == []
    st = ctl.reconcile_once()                # streak 2 -> up + spawn
    pool = st["pools"]["default"]
    assert pool["desired"] == 2 and router.added == [1]
    assert "queue depth" in pool["last_decision"]["reason"]


def test_controller_scale_down_drains_zero_drop():
    router = FakeRouter(
        [FakeReplica(0, outstanding=2), FakeReplica(1, outstanding=5)],
        records={0: _healthy(0), 1: _healthy(1)})
    ctl = _mk_controller(router, clear_consecutive=2)
    ctl.reconcile_once()                     # desired 2
    st = ctl.reconcile_once()                # idle streak 2 -> down
    pool = st["pools"]["default"]
    # victim = least admitted work (0), drained but NOT removed while
    # its admitted requests are still in flight
    assert pool["desired"] == 1 and router.drained == [0]
    assert 0 in pool["draining_out"] and router.removed == []
    router.replicas[0].outstanding = 0
    st = ctl.reconcile_once()
    assert router.removed == [0]
    assert st["pools"]["default"]["draining_out"] == []
    kinds = [e["kind"] for e in events.recent_events(100)]
    assert "scale_down" in kinds


def test_controller_never_scales_below_min():
    router = FakeRouter([FakeReplica(0)], records={0: _healthy(0)})
    ctl = _mk_controller(router, clear_consecutive=1)
    for _ in range(5):
        st = ctl.reconcile_once()
    assert st["pools"]["default"]["desired"] == 1
    assert router.drained == [] and router.removed == []


def test_controller_unreadable_registry_holds_without_wedging():
    router = FakeRouter([FakeReplica(0)], records={0: _healthy(0)})
    ctl = _mk_controller(router, dead_after_polls=2)
    ctl.reconcile_once()
    router.poll_error = OSError("doctored snapshot dir")
    for _ in range(5):                       # no spawn/kill storm
        st = ctl.reconcile_once()
    assert st["pools"]["default"]["error"] \
        == "registry unreadable; holding"
    assert router.added == [] and router.removed == []
    router.poll_error = None                 # and the loop recovers
    st = ctl.reconcile_once()
    assert st["pools"]["default"]["live"] == 1
    assert "error" not in st["pools"]["default"]


def test_controller_corrupt_snapshot_reads_unhealthy_then_replaces():
    # the registry's corrupt-record shape: no model key, healthy False
    router = FakeRouter(
        [FakeReplica(0), FakeReplica(1)],
        records={0: _healthy(0),
                 1: {"id": 1, "healthy": False, "reason": "corrupt",
                     "draining": False, "age_s": None}})
    ctl = _mk_controller(router, dead_after_polls=2)
    ctl.reconcile_once()
    ctl.reconcile_once()
    assert router.added == [2]               # replaced, not wedged


def test_controller_hold_event_latched_per_episode():
    router = FakeRouter([FakeReplica(0)], records={0: _healthy(0)})
    ctl = _mk_controller(router, queue_high=5, breach_consecutive=1,
                         cooldown_s=60.0, max_replicas=4)
    ctl.reconcile_once()
    router.recs[0] = _healthy(0, queue_depth=9)
    ctl.reconcile_once()                     # up (no cooldown yet? no:
    # cooldown_s=60 but _last_action_at None -> acts, then stamps)
    before = len([e for e in events.recent_events(500)
                  if e["kind"] == "controller_hold"])
    for _ in range(6):                       # all suppressed by cooldown
        st = ctl.reconcile_once()
    after = [e for e in events.recent_events(500)
             if e["kind"] == "controller_hold"]
    assert len(after) - before == 1          # one event per episode
    pool = st["pools"]["default"]
    assert pool["cooldown_remaining_s"] > 0
    assert pool["last_decision"]["action"] == "hold"
    assert "cooling down" in pool["last_decision"]["reason"]


def test_controller_rejects_duplicate_pools():
    router = FakeRouter()
    with pytest.raises(ValueError, match="duplicate"):
        FleetController(router, lambda *a: None,
                        pools=[PoolSpec(model="m"), PoolSpec(model="m")])


def test_controller_multi_pool_scales_independently():
    router = FakeRouter(
        [FakeReplica(0, model="a"), FakeReplica(1, model="b")],
        records={0: _healthy(0, model="a", queue_depth=9),
                 1: _healthy(1, model="b")})
    calls = []

    def factory(rid, model, ckpt):
        calls.append((rid, model))
        return FakeReplica(rid, model)

    ctl = FleetController(
        router, factory,
        pools=[PoolSpec(model="a", queue_high=5, breach_consecutive=1,
                        cooldown_s=60.0),
               PoolSpec(model="b", queue_high=5, cooldown_s=60.0)])
    st = ctl.reconcile_once()                # a: breach streak 1 -> up
    st = ctl.reconcile_once()                # a: cooling down -> hold
    assert calls == [(2, "a")]
    assert st["pools"]["a"]["desired"] == 2
    assert st["pools"]["b"]["desired"] == 1


def test_controller_start_pushes_slo_class_and_budget():
    router = FakeRouter([FakeReplica(0)], records={0: _healthy(0)})
    ctl = FleetController(
        router, lambda rid, m, c: FakeReplica(rid, m),
        pools=[PoolSpec(model="default", slo_ttft_p99_s=0.75,
                        admission_budget=16)], interval_s=0.01)
    ctl.start()
    try:
        assert router.stats_d["slo_classes"]["default"] == 0.75
        assert router.stats_d["budgets"]["default"] == 16
        deadline = time.perf_counter() + 10.0
        while not ctl.status().get("pools"):
            assert time.perf_counter() < deadline
            time.sleep(0.01)
        assert ctl.status()["running"]
    finally:
        ctl.stop()
    assert not ctl._thread.is_alive()


# ---------------------------------------------------------------------------
# checkpoint watcher: baseline, deploy, torn generations
# ---------------------------------------------------------------------------

class FakeDeployRouter(FakeRouter):
    def __init__(self, replicas=(), records=None):
        super().__init__(replicas, records)
        self.deploys = []

    def deploy(self, new_replica, replaces, timeout=None):
        assert self.replicas[replaces].admitted_outstanding() == 0
        self.replicas.pop(replaces)
        self.replicas[new_replica.id] = new_replica
        self.deploys.append((replaces, new_replica.id))
        return {"added": new_replica.id, "replaced": replaces,
                "outstanding_at_removal": 0}


def _commit(tmp_path, gen):
    return CheckpointManager(str(tmp_path)).save(
        {"params": {"w": np.arange(4.0) + gen}}, [],
        {"epoch": 0, "neval": gen}, generation=gen)


def _mk_watcher(tmp_path, router, **kw):
    built = []

    def factory(rid, model, ckpt):
        assert ckpt is not None    # deploys always pin the payload
        built.append((rid, ckpt))
        return FakeReplica(rid, model)

    w = CheckpointWatcher(CheckpointManager(str(tmp_path)), router,
                          factory, **kw)
    w._built = built
    return w


def test_watcher_baselines_existing_generation(tmp_path):
    _commit(tmp_path, 1)
    router = FakeDeployRouter([FakeReplica(0)],
                              records={0: _healthy(0)})
    w = _mk_watcher(tmp_path, router)
    assert w.check_once() is None            # baseline, no deploy
    assert w.status()["deployed_generation"] == 1
    assert router.deploys == []
    p2 = _commit(tmp_path, 2)
    report = w.check_once()
    assert report["generation"] == 2
    assert router.deploys == [(0, w._built[0][0])]
    assert w._built[0][1] == p2
    assert report["freshness_s"] is not None \
        and report["freshness_s"] >= 0.0
    assert w.check_once() is None            # idempotent per generation


def test_watcher_deploy_existing_rolls_out_first_generation(tmp_path):
    _commit(tmp_path, 1)
    router = FakeDeployRouter([FakeReplica(0)],
                              records={0: _healthy(0)})
    w = _mk_watcher(tmp_path, router, deploy_existing=True)
    assert w.check_once()["generation"] == 1
    assert len(router.deploys) == 1


def test_watcher_torn_generation_never_deploys(tmp_path):
    _commit(tmp_path, 1)
    router = FakeDeployRouter([FakeReplica(0)],
                              records={0: _healthy(0)})
    w = _mk_watcher(tmp_path, router)
    w.check_once()
    p2 = _commit(tmp_path, 2)
    with open(p2, "r+b") as f:               # torn payload, manifest
        f.truncate(16)                       # intact: CRC must fail
    assert w.check_once() is None
    assert router.deploys == []
    p3 = _commit(tmp_path, 3)                # next good gen deploys
    report = w.check_once()
    assert report["generation"] == 3 and w._built[-1][1] == p3


def test_watcher_skips_unhealthy_and_foreign_models(tmp_path):
    _commit(tmp_path, 1)
    router = FakeDeployRouter(
        [FakeReplica(0), FakeReplica(1), FakeReplica(2, model="other")],
        records={0: _healthy(0),
                 1: _healthy(1, healthy=False, reason="stale"),
                 2: _healthy(2, model="other")})
    w = _mk_watcher(tmp_path, router)
    w.check_once()
    _commit(tmp_path, 2)
    report = w.check_once()
    assert [old for old, _new in router.deploys] == [0]
    assert report["swapped"][0][0] == 0
    assert 1 in router.replicas and 2 in router.replicas


def test_watcher_empty_directory_is_quiet(tmp_path):
    router = FakeDeployRouter()
    w = _mk_watcher(tmp_path, router)
    assert w.check_once() is None and router.deploys == []


# ---------------------------------------------------------------------------
# /statusz controller section
# ---------------------------------------------------------------------------

def test_statusz_registry_merges_and_survives_broken_provider():
    assert controller_statusz() is None
    register_statusz("good", lambda: {"x": 1})
    register_statusz("broken", lambda: 1 / 0)
    try:
        out = controller_statusz()
        assert out["good"] == {"x": 1}
        assert "ZeroDivisionError" in out["broken"]["error"]
    finally:
        unregister_statusz("good")
        unregister_statusz("broken")
    assert controller_statusz() is None


def test_serve_statusz_gains_controller_section():
    from bigdl_tpu.examples.serve import make_server
    import json
    import urllib.request
    import threading
    server = make_server(object(), "127.0.0.1", 0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    register_statusz("fleet", lambda: {"pools": {"default": {}}})
    try:
        url = f"http://127.0.0.1:{server.server_port}/statusz"
        with urllib.request.urlopen(url, timeout=10) as resp:
            page = json.loads(resp.read())
        assert "pools" in page["controller"]["fleet"]
    finally:
        unregister_statusz("fleet")
        server.shutdown()
        server.server_close()
        t.join(5.0)


# ---------------------------------------------------------------------------
# training supervisor: auto-resume past preemption
# ---------------------------------------------------------------------------

class FakeOptimizer:
    def __init__(self, ckpt_dir, preempt_times=1):
        self.checkpoint_path = ckpt_dir
        self.preempt_times = preempt_times
        self.calls = 0
        self.preempted = False
        self.resumed_from = []

    def optimize(self):
        self.calls += 1
        self.preempted = self.calls <= self.preempt_times
        return "trained-model"

    def resume(self, path):
        self.resumed_from.append(path)


def test_supervisor_resumes_preempted_run_from_latest_good(tmp_path):
    good = _commit(tmp_path, 7)
    opt = FakeOptimizer(str(tmp_path), preempt_times=2)
    sup = TrainingSupervisor(opt)
    assert sup.run() == "trained-model"
    assert opt.calls == 3
    assert opt.resumed_from == [good, good]
    assert sup.resumes == 2 and sup.last_resume_from == good
    st = sup.statusz()
    assert st["resumes"] == 2 and not st["preempted"]
    assert controller_statusz() is None      # unregistered on exit


def test_supervisor_requires_checkpoint_dir_and_committed_gen(tmp_path):
    class NoCkpt:
        checkpoint_path = None
    with pytest.raises(ValueError, match="checkpoint directory"):
        TrainingSupervisor(NoCkpt())
    opt = FakeOptimizer(str(tmp_path), preempt_times=1)
    with pytest.raises(RuntimeError, match="before any checkpoint"):
        TrainingSupervisor(opt).run()


def test_supervisor_gives_up_past_max_resumes(tmp_path):
    _commit(tmp_path, 1)
    opt = FakeOptimizer(str(tmp_path), preempt_times=99)
    with pytest.raises(RuntimeError, match="max_resumes"):
        TrainingSupervisor(opt, max_resumes=2).run()


# ---------------------------------------------------------------------------
# telemetry families
# ---------------------------------------------------------------------------

def test_fleet_families_recorded_when_enabled():
    from bigdl_tpu import telemetry
    telemetry.enable()
    telemetry.reset()
    try:
        router = FakeRouter([FakeReplica(0)],
                            records={0: _healthy(0, queue_depth=9)})
        ctl = _mk_controller(router, queue_high=5,
                             breach_consecutive=1)
        ctl.reconcile_once()
        ctl.reconcile_once()
        text = telemetry.prometheus_text()
        assert 'fleet_replicas_desired{model="default"}' in text
        assert 'fleet_replicas_live{model="default"}' in text
        assert 'fleet_scale_events_total{direction="up"}' in text
    finally:
        telemetry.reset()
        telemetry.disable()


# ---------------------------------------------------------------------------
# the closed loop, end to end (fast) and under soak (slow)
# ---------------------------------------------------------------------------

def test_closed_loop_kill_replace_deploy_bit_identical(tmp_path):
    """The acceptance e2e at test budget: chaos kill under load ->
    controller replaces with no operator step; a new checkpoint
    generation rolling-hot-deploys through drain/deploy; greedy rows
    after the swap are bit-identical to solo generate(); nothing
    admitted is dropped."""
    from bigdl_tpu.fleet.harness import run_fleet_scenario
    # timeout_s is a pure safety net -- the loop closes in seconds on an
    # idle many-core box, but late in the full suite on a 1-CPU host the
    # same closure takes 2+ minutes; a high ceiling makes slowness slow,
    # not red
    report = run_fleet_scenario(str(tmp_path), load_s=1.2,
                                spike_requests=12,
                                wait_scale_down=False,
                                timeout_s=600.0)
    assert report["killed_replica"] == 0
    assert 0 not in report["replaced_with"]
    assert report["dropped"] == 0
    assert report["ok"] + report["shed"] == report["submitted"]
    assert report["deployed_generation"] == 2
    assert report["freshness_s"] is not None \
        and report["freshness_s"] < 60.0
    assert report["greedy_rows_equal"]
    assert report["admitted_outstanding"] == 0
    assert report["events"]["scale_up"] >= 1
    assert report["events"]["hot_deploy"] == 1
    assert report["events"]["chaos_fault"] >= 1


@pytest.mark.slow
def test_soak_closed_loop_scales_and_recovers(tmp_path):
    """The chaos-driven closure soak: sustained load + kill + spike ->
    replacement AND breach-driven scale-up, live deploy mid-fleet,
    idle scale-down back toward the floor, zero drops throughout."""
    from bigdl_tpu.fleet.harness import run_fleet_scenario
    report = run_fleet_scenario(str(tmp_path), load_s=5.0,
                                spike_requests=24,
                                wait_scale_down=True)
    assert report["dropped"] == 0
    assert report["ok"] > 0
    assert report["live_after_spike"] >= 2   # spike grew the pool
    assert report["live_final"] < report["live_after_spike"]
    assert report["events"]["scale_up"] >= 2  # replacement + growth
    assert report["events"]["scale_down"] >= 1
    assert report["greedy_rows_equal"]
    assert report["admitted_outstanding"] == 0
    pools = report["controller_status"]["pools"]
    assert pools["default"]["dying"] == []
