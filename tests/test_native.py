"""Native C++ extension tests: CRC32C vs pure-Python oracle, int8
quantization kernels vs numpy, TFRecord framing roundtrip (and
compatibility between native writer and python reader paths).

Mirrors the reference's native-library tests (BigQuant/Crc32c are
exercised through nn/quantized specs and RecordWriter specs).
"""

import os
import struct

import numpy as np
import pytest

from bigdl_tpu import native
from bigdl_tpu.visualization.crc32c import (crc32c as py_crc32c,
                                            masked_crc32c as py_masked)


def test_native_builds():
    assert native.available(), "g++ toolchain present — build must work"


def test_crc32c_matches_pure_python():
    rng = np.random.RandomState(0)
    for n in (0, 1, 7, 8, 9, 64, 1000):
        data = rng.bytes(n)
        assert native.crc32c(data) == py_crc32c(data)
    # known vector: crc32c of "123456789" is 0xE3069283
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native.masked_crc32c(b"hello") == py_masked(b"hello")


def test_crc32c_incremental():
    data = b"The quick brown fox jumps over the lazy dog"
    whole = native.crc32c(data)
    part = native.crc32c(data[7:], native.crc32c(data[:7]))
    assert whole == part


def test_quantize_roundtrip():
    rng = np.random.RandomState(1)
    w = rng.randn(8, 32).astype(np.float32) * 3
    q, scales = native.quantize_rows(w)
    assert q.dtype == np.int8 and scales.shape == (8,)
    back = native.dequantize_rows(q, scales)
    # quantization error bounded by scale/2 per element
    assert np.abs(back - w).max() <= scales.max() * 0.51
    # numpy fallback parity
    mx = np.abs(w).max(axis=1)
    want_scales = np.where(mx > 0, mx / 127.0, 1.0)
    np.testing.assert_allclose(scales, want_scales, rtol=1e-6)


def test_mix_precision_gemm_close_to_float():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 64).astype(np.float32)
    w = rng.randn(10, 64).astype(np.float32)
    q, scales = native.quantize_rows(w)
    got = native.mix_precision_gemm(x, q, scales)
    want = x @ w.T
    # int8 x int8 should track float gemm within ~2%
    denom = np.abs(want).mean()
    assert np.abs(got - want).mean() / denom < 0.02


def test_tfrecord_frame_and_scan_roundtrip(tmp_path):
    payloads = [b"alpha", b"", b"x" * 1000, b"tail"]
    buf = b"".join(native.tfrecord_frame(p) for p in payloads)
    spans = native.tfrecord_scan(buf)
    assert [buf[o:o + l] for o, l in spans] == payloads
    # corrupted byte → CRC error with position
    bad = bytearray(buf)
    bad[13] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        native.tfrecord_scan(bytes(bad))


def test_tfrecord_file_io(tmp_path):
    from bigdl_tpu.dataset.tfrecord import (TFRecordWriter, read_tfrecords,
                                            tfrecord_dataset,
                                            write_tfrecords)
    p = tmp_path / "data.tfrecord"
    write_tfrecords(str(p), [b"one", b"two", b"three"])
    assert read_tfrecords(str(p)) == [b"one", b"two", b"three"]
    ds = tfrecord_dataset(str(p), shuffle=False)
    assert ds.size() == 3


def test_native_frame_matches_python_frame():
    """Native framing and the pure-python fallback must be
    byte-identical (cross-version file compatibility)."""
    payload = b"payload-bytes"
    native_framed = native.tfrecord_frame(payload)
    header = struct.pack("<Q", len(payload))
    py_framed = (header + struct.pack("<I", py_masked(header))
                 + payload + struct.pack("<I", py_masked(payload)))
    assert native_framed == py_framed


def test_event_writer_uses_native_crc(tmp_path):
    """TensorBoard event files written through the native CRC must be
    readable back by the FileReader."""
    from bigdl_tpu.visualization import TrainSummary
    logdir = str(tmp_path / "logs")
    s = TrainSummary(logdir, "app")
    s.add_scalar("Loss", 1.5, 1).add_scalar("Loss", 1.0, 2)
    got = s.read_scalar("Loss")
    s.close()
    assert got == [(1, 1.5), (2, 1.0)]


def test_quantize_bytes_match_fallback():
    """Native kernels and numpy fallback must produce identical int8
    bytes (ties round half-away-from-zero in both)."""
    from bigdl_tpu.native import _round_half_away, quantize_rows
    # 62.5 is a representable tie: scale=2/127, w=125/127 → q=62.5
    w = np.asarray([[2.0, 125.0 / 127.0]], np.float32)
    q, scales = quantize_rows(w)
    mx = np.abs(w).max(axis=1)
    fs = np.where(mx > 0, mx / 127.0, 1.0).astype(np.float32)
    fq = np.clip(_round_half_away(w / fs[:, None]), -127, 127)
    np.testing.assert_array_equal(q, fq.astype(np.int8))
    assert q[0, 1] == 63  # half-away-from-zero, not ties-to-even (62)


def test_tfrecord_scan_huge_length_is_safe():
    """A corrupt 64-bit length field must not wrap the bounds check."""
    frame = bytearray(native.tfrecord_frame(b"data"))
    frame[0:8] = struct.pack("<Q", 0xFFFFFFFFFFFFFFF8)
    spans = native.tfrecord_scan(bytes(frame), verify_crc=False)
    assert spans == []  # treated as truncated tail, no crash


def test_native_jpeg_decode_matches_pil_exact():
    """Full-size native decode must be byte-exact vs PIL (both wrap
    libjpeg with the default DCT method)."""
    pytest.importorskip("PIL")
    import io
    from PIL import Image
    from bigdl_tpu.native import jpeg_available, jpeg_decode_scaled
    if not jpeg_available():
        pytest.skip("libjpeg toolchain unavailable")
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 256, size=(96, 130, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=92)
    data = buf.getvalue()
    ours = jpeg_decode_scaled(data, 0)
    ref = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    # <=1 LSB: Pillow may bundle a different libjpeg build than g++
    # links (turbo SIMD variants differ in last-bit rounding)
    assert np.abs(ours.astype(int) - ref.astype(int)).max() <= 1


def test_native_jpeg_dct_downscale_and_fallback():
    pytest.importorskip("PIL")
    import io
    from PIL import Image
    from bigdl_tpu.native import jpeg_available, jpeg_decode_scaled
    if not jpeg_available():
        pytest.skip("libjpeg toolchain unavailable")
    rng = np.random.default_rng(1)
    arr = rng.integers(0, 256, size=(400, 600, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=92)
    out = jpeg_decode_scaled(buf.getvalue(), 150)
    # 4/8 scale: short side 200 >= 150, aspect preserved
    assert out.shape == (200, 300, 3)
    # grayscale converts to RGB like PIL's convert("RGB")
    gbuf = io.BytesIO()
    Image.fromarray(arr[..., 0]).save(gbuf, format="JPEG")
    g = jpeg_decode_scaled(gbuf.getvalue(), 0)
    assert g.shape == (400, 600, 3)
    assert (g[..., 0] == g[..., 1]).all()
    # garbage -> None (callers fall back to PIL)
    assert jpeg_decode_scaled(b"definitely not a jpeg", 10) is None
    # TRUNCATED file -> None too (gray-filled silent decode would
    # diverge from the PIL fallback, which raises on the same bytes)
    whole = buf.getvalue()
    assert jpeg_decode_scaled(whole[: len(whole) // 2], 0) is None


def test_decode_rgb_native_and_pil_paths_agree(tmp_path):
    """The pipeline's _decode_rgb must give the same full-size pixels
    through either backend, and the min_short fast path must feed the
    augment something AspectScale-compatible."""
    pytest.importorskip("PIL")
    from PIL import Image
    from bigdl_tpu.examples.imagenet import _decode_rgb
    rng = np.random.default_rng(2)
    p = str(tmp_path / "x.jpg")
    Image.fromarray(rng.integers(0, 256, size=(300, 450, 3),
                                 dtype=np.uint8)).save(p, quality=90)
    full = _decode_rgb(p)
    assert full.shape == (300, 450, 3) and full.dtype == np.float32
    fast = _decode_rgb(p, min_short=140)
    # short side stays >= the augment target, aspect preserved
    assert min(fast.shape[:2]) >= 140
    assert abs(fast.shape[1] / fast.shape[0] - 1.5) < 0.02
