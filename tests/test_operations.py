"""Tests for TF-style stateless ops (reference nn/ops/)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import ops
from bigdl_tpu.core.module import forward_context


def test_elementwise_unary_ops():
    x = jnp.asarray([[1.3, -2.7], [0.0, 4.5]])
    np.testing.assert_allclose(ops.Ceil()(x), np.ceil(np.asarray(x)))
    np.testing.assert_allclose(ops.Floor()(x), np.floor(np.asarray(x)))
    np.testing.assert_allclose(ops.Round()(x), np.round(np.asarray(x)))
    np.testing.assert_allclose(ops.Sign()(x), np.sign(np.asarray(x)))
    np.testing.assert_allclose(ops.Log1p()(jnp.abs(x)),
                               np.log1p(np.abs(np.asarray(x))), rtol=1e-6)
    np.testing.assert_allclose(
        ops.Rsqrt()(jnp.asarray([4.0, 16.0])), [0.5, 0.25], rtol=1e-6)
    np.testing.assert_allclose(
        ops.Inv()(jnp.asarray([2.0, 4.0])), [0.5, 0.25], rtol=1e-6)


def test_special_functions_match_scipy():
    sps = pytest.importorskip("scipy.special")
    x = jnp.asarray([0.5, 1.5, 2.5])
    np.testing.assert_allclose(ops.Erf()(x), sps.erf(np.asarray(x)),
                               rtol=1e-5)
    np.testing.assert_allclose(ops.Lgamma()(x),
                               sps.gammaln(np.asarray(x)), rtol=1e-5)
    np.testing.assert_allclose(ops.Digamma()(x),
                               sps.digamma(np.asarray(x)), rtol=1e-4)


def test_comparisons_and_logical():
    a = jnp.asarray([1, 2, 3])
    b = jnp.asarray([2, 2, 2])
    assert list(ops.Greater()((a, b))) == [False, False, True]
    assert list(ops.LessEqual()((a, b))) == [True, True, False]
    assert list(ops.Equal()((a, b))) == [False, True, False]
    t = jnp.asarray([True, False])
    f = jnp.asarray([True, True])
    assert list(ops.LogicalAnd()((t, f))) == [True, False]
    assert list(ops.LogicalNot()(t)) == [False, True]


def test_reductions_with_axis_table():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(ops.SumOp()((x, 0)), [4.0, 6.0])
    np.testing.assert_allclose(ops.Prod(axis=1)(x), [2.0, 12.0])
    assert bool(ops.All()((x > 0, 0)).all())
    assert bool(ops.Any()((x > 3, None)))


def test_batch_matmul_adjoints():
    rng = np.random.default_rng(0)
    a = rng.normal(size=(2, 3, 4)).astype(np.float32)
    b = rng.normal(size=(2, 4, 5)).astype(np.float32)
    got = ops.BatchMatMul()((jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got, a @ b, rtol=1e-5)
    got_t = ops.BatchMatMul(adj_x=True)(
        (jnp.asarray(a.transpose(0, 2, 1)), jnp.asarray(b)))
    np.testing.assert_allclose(got_t, a @ b, rtol=1e-5)


def test_one_hot_and_pad_and_slice():
    oh = ops.OneHot()((jnp.asarray([0, 2]), 3, 5.0, -1.0))
    np.testing.assert_allclose(
        oh, [[5, -1, -1], [-1, -1, 5]])
    padded = ops.Pad()((jnp.ones((2, 2)), [[1, 1], [0, 0]]))
    assert padded.shape == (4, 2)
    assert float(padded[0, 0]) == 0.0
    x = jnp.arange(24).reshape(2, 3, 4)
    s = ops.Slice(begin=(0, 1, 0), size=(2, 2, -1))(x)
    assert s.shape == (2, 2, 4)
    np.testing.assert_array_equal(s, np.asarray(x)[:, 1:3, :])


def test_topk_select_squared_difference():
    v, i = ops.TopK(2)(jnp.asarray([1.0, 5.0, 3.0, 4.0]))
    assert list(np.asarray(v)) == [5.0, 4.0]
    assert list(np.asarray(i)) == [1, 3]
    sel = ops.SelectOp()((jnp.asarray([True, False]),
                          jnp.asarray([1.0, 2.0]),
                          jnp.asarray([9.0, 9.0])))
    assert list(np.asarray(sel)) == [1.0, 9.0]
    np.testing.assert_allclose(
        ops.SquaredDifference()((jnp.asarray([3.0]), jnp.asarray([1.0]))),
        [4.0])


def test_random_ops_need_rng_and_are_deterministic_per_key():
    with pytest.raises(RuntimeError):
        ops.RandomUniform()(jnp.asarray([2, 2]))
    key = jax.random.key(0)
    with forward_context(rng=key):
        a = ops.RandomUniform(0.0, 1.0)(jnp.asarray([4]))
    with forward_context(rng=key):
        b = ops.RandomUniform(0.0, 1.0)(jnp.asarray([4]))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (4,)
    assert (np.asarray(a) >= 0).all() and (np.asarray(a) < 1).all()
    with forward_context(rng=key):
        t = ops.TruncatedNormal(stddev=2.0)(jnp.asarray([1000]))
    assert np.abs(np.asarray(t)).max() <= 4.0 + 1e-5  # truncated at 2σ


def test_bucketized_col_and_cross_entropy():
    b = ops.BucketizedCol(boundaries=[0.0, 10.0, 100.0])
    np.testing.assert_array_equal(
        b(jnp.asarray([-5.0, 5.0, 50.0, 500.0])), [0, 1, 2, 3])
    logits = jnp.asarray([[2.0, 1.0, 0.1]])
    labels = jnp.asarray([[1.0, 0.0, 0.0]])
    ce = ops.CrossEntropy()((logits, labels))
    want = -np.log(np.exp(2.0) / np.exp([2.0, 1.0, 0.1]).sum())
    np.testing.assert_allclose(ce, [want], rtol=1e-5)


def test_depthwise_conv2d():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    rng = np.random.default_rng(1)
    x = rng.normal(size=(1, 6, 6, 3)).astype(np.float32)
    w = rng.normal(size=(3, 3, 3, 2)).astype(np.float32)  # HWCM
    got = ops.DepthwiseConv2D(padding="VALID")(
        (jnp.asarray(x), jnp.asarray(w)))
    # torch: depthwise = groups=C, weight [C*M, 1, kh, kw]
    tw = torch.tensor(w.transpose(2, 3, 0, 1).reshape(6, 1, 3, 3))
    tx = torch.tensor(x.transpose(0, 3, 1, 2))
    want = F.conv2d(tx, tw, groups=3).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                               atol=1e-5)


def test_cast_rank_range():
    x = jnp.asarray([1.7, 2.3])
    assert ops.Cast(jnp.int32)(x).dtype == jnp.int32
    assert int(ops.Rank()(jnp.ones((2, 3, 4)))) == 3
    np.testing.assert_array_equal(ops.RangeOps()((1, 7, 2)), [1, 3, 5])


def test_tensor_op_combinators():
    """TensorOp chaining (reference nn/ops/TensorOp.scala)."""
    from bigdl_tpu.ops import TensorOp
    x = jnp.asarray([[1.0, -4.0], [9.0, 16.0]])
    op = (TensorOp() * 2.0 + 2.0).abs().sqrt()
    np.testing.assert_allclose(np.asarray(op(x)),
                               np.sqrt(np.abs(np.asarray(x) * 2 + 2)))
    # op-op arithmetic: (f + g)(x) = f(x) + g(x)
    combo = TensorOp(lambda v: v * 3.0) + TensorOp(jnp.abs)
    np.testing.assert_allclose(np.asarray(combo(x)),
                               np.asarray(x) * 3 + np.abs(np.asarray(x)))
    # reductions and activations chain
    s = TensorOp().relu().sum(axis=1)
    np.testing.assert_allclose(
        np.asarray(s(x)), np.maximum(np.asarray(x), 0).sum(1))


def test_feature_column_ops_wide_and_deep():
    """Feature-column host ops (reference nn/ops/CategoricalCol*,
    CrossCol, IndicatorCol, MkString, Kv2Tensor — the wide-and-deep
    input path)."""
    from bigdl_tpu.ops import (
        CategoricalColHashBucket, CategoricalColVocaList, CrossCol,
        IndicatorCol, Kv2Tensor, MkString,
    )
    from bigdl_tpu.ops.feature_columns import java_string_hash

    # hash bucketing: deterministic, in range, multi-value support
    hb = CategoricalColHashBucket(hash_bucket_size=100)
    sp = hb(np.asarray(["apple", "pear,plum", ""], dtype=object))
    assert sp.shape == (3, 2)
    vals = np.asarray(sp.values)
    # ids are 1-based: 0 is the padding sentinel
    assert ((1 <= vals) & (vals <= 100)).all()
    assert vals[0] == java_string_hash("apple") % 100 + 1
    dense = CategoricalColHashBucket(100, is_sparse=False)(
        np.asarray(["apple", "pear,plum", ""], dtype=object))
    assert dense.shape == (3, 2) and dense[2, 0] == 0

    # vocabulary lookup: strict raises, default maps to len(vocab)
    vl = CategoricalColVocaList(["a", "b"], is_set_default=True)
    spv = vl(np.asarray(["a", "b,zzz"], dtype=object))
    got = np.asarray(spv.values).tolist()
    assert got == [1, 2, 3]
    with pytest.raises(ValueError, match="vocabulary"):
        CategoricalColVocaList(["a"])(np.asarray(["q"], dtype=object))

    # crossing: cartesian product per row, hashed
    cc = CrossCol(hash_bucket_size=50)
    spc = cc([np.asarray(["u1", "u2"], dtype=object),
              np.asarray(["x,y", "x"], dtype=object)])
    assert spc.shape == (2, 2)
    assert np.asarray(spc.values)[0] ==         java_string_hash("u1_x") % 50 + 1

    # indicator: multi-hot with counts
    ind = IndicatorCol(feat_len=5)
    out = ind(spv)
    assert out.shape == (2, 5)
    assert out[0, 0] == 1.0 and out[1, 1] == 1.0 and out[1, 2] == 1.0

    # MkString round-trips sparse ids to strings (0 = padding skipped)
    s = MkString()(spv)
    assert list(s) == ["1", "2,3"]

    # padding entries in fixed-capacity sparse tensors are ignored
    from bigdl_tpu.nn.sparse import SparseTensor
    padded = SparseTensor(np.asarray([[0, 0], [0, 0]], np.int32),
                          np.asarray([3, 0], np.int32), (1, 4))
    np.testing.assert_allclose(IndicatorCol(5)(padded),
                               [[0, 0, 1, 0, 0]])
    assert list(MkString()(padded)) == ["3"]

    # Kv2Tensor key validation + duplicate-key summing parity
    with pytest.raises(ValueError, match="out of range"):
        Kv2Tensor()((np.asarray(["7:1.0"], dtype=object), 4))
    dup_dense = Kv2Tensor()((np.asarray(["0:1.0,0:2.0"], dtype=object), 2))
    dup_sparse = Kv2Tensor(trans_type=1)(
        (np.asarray(["0:1.0,0:2.0"], dtype=object), 2))
    np.testing.assert_allclose(dup_dense,
                               np.asarray(dup_sparse.to_dense())
                               .reshape(1, 2))

    # Kv2Tensor: "k:v" strings to dense
    kv = Kv2Tensor()
    out = kv((np.asarray(["0:1.5,2:3.0", "1:2.0"], dtype=object), 4))
    np.testing.assert_allclose(out, [[1.5, 0, 3.0, 0], [0, 2.0, 0, 0]])


def test_remaining_reference_ops():
    """The last 10 nn/ops files: ApproximateEqual, Gather, InTopK,
    SegmentSum, ModuleToOperation, Dilation2D, Substr + aliases."""
    from bigdl_tpu import ops

    assert np.asarray(ops.ApproximateEqual(0.1)(
        (jnp.asarray([1.0, 2.0]), jnp.asarray([1.05, 3.0])))).tolist() \
        == [True, False]

    params = jnp.asarray([[1.0, 2], [3, 4], [5, 6]])
    np.testing.assert_allclose(
        np.asarray(ops.Gather()((params, jnp.asarray([2, 0])))),
        [[5.0, 6], [1, 2]])

    preds = jnp.asarray([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    got = np.asarray(ops.InTopK(1)((preds, jnp.asarray([1, 1]))))
    assert got.tolist() == [True, False]
    got = np.asarray(ops.InTopK(1, start_from_1=True)(
        (preds, jnp.asarray([2, 2]))))
    assert got.tolist() == [True, False]

    data = jnp.asarray([[1.0, 2], [3, 4], [5, 6]])
    np.testing.assert_allclose(
        np.asarray(ops.SegmentSum()((data, jnp.asarray([0, 0, 1])))),
        [[4.0, 6], [5, 6]])

    import bigdl_tpu.nn as nn
    m2o = ops.ModuleToOperation(nn.ReLU())
    np.testing.assert_allclose(
        np.asarray(m2o(jnp.asarray([-1.0, 2.0]))), [0.0, 2.0])

    # Dilation2D against a hand-computed 1-channel case
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1))
    filt = jnp.zeros((2, 2, 1), jnp.float32)
    out = np.asarray(ops.Dilation2D((1, 1, 1, 1), (1, 1, 1, 1))(
        (x, filt)))
    # max of each 2x2 window (filter zero)
    want = np.asarray([[5, 6, 7], [9, 10, 11], [13, 14, 15]],
                      np.float32).reshape(1, 3, 3, 1)
    np.testing.assert_allclose(out, want)
    # non-zero filter adds before the max
    filt2 = jnp.asarray([[[0.0]], [[10.0]]])  # kh=2,kw=1? -> (2,1,1)
    out2 = np.asarray(ops.Dilation2D((1, 1, 1, 1), (1, 1, 1, 1))(
        (x, jnp.reshape(filt2, (2, 1, 1)))))
    # window col of 2: max(x[y,x], x[y+1,x]+10) = x[y+1,x]+10
    np.testing.assert_allclose(out2[0, :, :, 0],
                               np.arange(16).reshape(4, 4)[1:, :] + 10)

    subs = ops.Substr()((np.asarray([b"hello", b"world"], object), 1, 3))
    assert subs.tolist() == [b"ell", b"orl"]

    assert ops.Maximum is ops.MaximumOp and ops.Minimum is ops.MinimumOp


def test_new_ops_edge_cases():
    """Review regressions: SAME dilation must -inf-pad (borders of a
    negative image stay negative); Substr handles 0-d; InTopK returns
    False for out-of-range targets; SegmentSum jits with a static
    num_segments."""
    from bigdl_tpu import ops

    x = jnp.full((1, 3, 3, 1), -5.0)
    filt = jnp.zeros((2, 2, 1), jnp.float32)
    out = np.asarray(ops.Dilation2D((1, 1, 1, 1), (1, 1, 1, 1),
                                    padding="SAME")((x, filt)))
    assert out.shape == (1, 3, 3, 1)
    np.testing.assert_allclose(out, -5.0)

    assert ops.Substr()((np.asarray(b"hello", object), 1, 3)) == b"ell"

    preds = jnp.asarray([[0.1, 0.9, 0.0]])
    assert np.asarray(ops.InTopK(3)((preds, jnp.asarray([5])))).tolist() \
        == [False]
    assert np.asarray(ops.InTopK(3, start_from_1=True)(
        (preds, jnp.asarray([0])))).tolist() == [False]

    seg = ops.SegmentSum(num_segments=2)
    fn = jax.jit(lambda d, i: seg((d, i)))
    np.testing.assert_allclose(
        np.asarray(fn(jnp.asarray([[1.0], [2.0], [4.0]]),
                      jnp.asarray([0, 0, 1]))), [[3.0], [4.0]])


def test_new_ops_tf_edge_semantics():
    """TF-matching edges from review: NaN target prediction is not in
    top-k; integer-dtype SAME dilation works; Substr raises on bad pos."""
    from bigdl_tpu import ops

    preds = jnp.asarray([[jnp.nan, 0.5]])
    assert np.asarray(ops.InTopK(1)((preds, jnp.asarray([0])))).tolist() \
        == [False]

    x = jnp.full((1, 3, 3, 1), -5, jnp.int32)
    out = np.asarray(ops.Dilation2D((1, 1, 1, 1), (1, 1, 1, 1),
                                    padding="SAME")(
        (x, jnp.zeros((2, 2, 1), jnp.int32))))
    np.testing.assert_array_equal(out, np.full((1, 3, 3, 1), -5))

    with pytest.raises(ValueError, match="out of range"):
        ops.Substr()((np.asarray([b"hi"], object), 5, 2))
    with pytest.raises(ValueError, match="out of range"):
        ops.Substr()((np.asarray(b"hello", object), -2, 2))
