"""Concurrent pipeline stages (reference MTImageFeatureToBatch /
MTLabeledBGRImgToBatch multithreaded batching analog)."""

import threading
import time

import numpy as np
import pytest

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.prefetch import ParallelMap, Prefetch


def test_prefetch_preserves_stream():
    out = list(Prefetch(3)(iter(range(100))))
    assert out == list(range(100))


def test_prefetch_propagates_upstream_exception():
    def bad():
        yield 1
        yield 2
        raise ValueError("decode failed")

    it = Prefetch(2)(bad())
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(ValueError, match="decode failed"):
        next(it)


def test_prefetch_early_drop_stops_producer():
    produced = []

    def src():
        for i in range(10_000):
            produced.append(i)
            yield i

    it = Prefetch(2)(src())
    for _ in range(3):
        next(it)
    it.close()  # generator drop
    time.sleep(0.3)
    n = len(produced)
    time.sleep(0.3)
    # producer must have stopped (bounded queue + stop flag), not
    # drained all 10k items
    assert len(produced) == n
    assert n < 100


def test_prefetch_producer_exits_when_consumer_drops_after_exhaustion():
    """Regression: the final _STOP/_Failure puts must honor the stop
    flag — a producer that exhausted its upstream while the queue was
    full used to block in q.put forever after the consumer went away,
    leaking the thread and its buffered items."""
    before = {t.ident for t in threading.enumerate()}
    it = Prefetch(1)(iter(range(3)))  # 3 items > n_ahead=1
    next(it)
    time.sleep(0.2)   # let the producer fill the queue and reach _STOP
    it.close()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.ident not in before and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"prefetch producer thread leaked: {leaked}"


def test_prefetch_never_advanced_generator_starts_no_thread():
    """Regression: an eagerly-started producer could never be stopped
    if the consumer generator was dropped before its first next() —
    the thread must start lazily on first advance."""
    before = threading.active_count()
    it = Prefetch(2)(iter(range(100)))
    time.sleep(0.1)
    assert threading.active_count() == before  # nothing started yet
    it.close()
    assert threading.active_count() == before


def test_parallel_map_early_close_cancels_queued_work():
    """Regression: generator close must drop queued fn calls
    (shutdown(cancel_futures=True)), not run them all to completion."""
    started = []

    def fn(i):
        started.append(i)
        time.sleep(0.05)
        return i

    it = ParallelMap(fn, workers=2, queue_factor=4)(iter(range(1000)))
    next(it)
    it.close()
    time.sleep(0.3)  # in-flight items finish; queued ones must not run
    n = len(started)
    time.sleep(0.3)
    assert len(started) == n
    assert n <= 2 * (1 + 4) + 2  # nothing beyond the in-flight window


def test_prefetch_overlaps_producer_and_consumer():
    """With 50ms produce + 50ms consume x 6 items, serial is ~600ms;
    overlapped must be well under it."""
    def src():
        for i in range(6):
            time.sleep(0.05)
            yield i

    t0 = time.perf_counter()
    for _ in Prefetch(2)(src()):
        time.sleep(0.05)
    overlapped = time.perf_counter() - t0
    assert overlapped < 0.5, overlapped


def test_parallel_map_order_and_concurrency():
    active = []
    peak = []
    lock = threading.Lock()

    def fn(i):
        with lock:
            active.append(i)
            peak.append(len(active))
        time.sleep(0.02)
        with lock:
            active.remove(i)
        return i * i

    out = list(ParallelMap(fn, workers=4)(iter(range(40))))
    assert out == [i * i for i in range(40)]
    assert max(peak) > 1  # actually ran concurrently


def test_parallel_map_propagates_fn_exception_in_order():
    def fn(i):
        if i == 5:
            raise RuntimeError("boom")
        return i

    it = ParallelMap(fn, workers=3)(iter(range(10)))
    got = []
    with pytest.raises(RuntimeError, match="boom"):
        for v in it:
            got.append(v)
    assert got == [0, 1, 2, 3, 4]


def test_parallel_map_bounds_in_flight():
    submitted = []

    def fn(i):
        submitted.append(i)
        time.sleep(0.01)
        return i

    pm = ParallelMap(fn, workers=2, queue_factor=1)
    it = pm(iter(range(1000)))
    next(it)
    # after one yield, at most in_flight + 1 items were ever submitted
    assert len(submitted) <= pm.in_flight + 1
    it.close()


def test_pipeline_integration_with_dataset():
    data = DataSet.array(list(range(32)), shuffle=False) \
        .transform(ParallelMap(lambda x: np.float32(x) * 2, workers=3)) \
        .transform(Prefetch(2))
    got = list(data.data(train=False))
    assert got == [np.float32(i) * 2 for i in range(32)]
