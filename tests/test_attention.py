"""Attention/Transformer stack tests.

Oracles: torch F.scaled_dot_product_attention for the kernel;
self-consistency between the Pallas flash kernel and the XLA path;
incremental decode vs full causal forward; beam search on a toy scorer.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn
from bigdl_tpu.ops.attention_kernels import flash_attention, xla_attention


def rnd(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_xla_attention_matches_torch_sdpa():
    q, k, v = rnd(2, 4, 10, 16, seed=1), rnd(2, 4, 12, 16, seed=2), \
        rnd(2, 4, 12, 16, seed=3)
    out = xla_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    ref = F.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v)).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_xla_attention_causal_matches_torch():
    q, k, v = rnd(2, 2, 8, 16, seed=4), rnd(2, 2, 8, 16, seed=5), \
        rnd(2, 2, 8, 16, seed=6)
    out = xla_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                        causal=True)
    ref = F.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v),
        is_causal=True).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_matches_xla(causal):
    q = jnp.asarray(rnd(2, 3, 256, 64, seed=7))
    k = jnp.asarray(rnd(2, 3, 256, 64, seed=8))
    v = jnp.asarray(rnd(2, 3, 256, 64, seed=9))
    bias = None if causal else jnp.asarray(rnd(2, 1, 256, 256, seed=10))
    out = flash_attention(q, k, v, bias, causal=causal, interpret=True)
    ref = xla_attention(q, k, v, bias, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal,with_bias", [
    (False, False), (True, False), (False, True), (True, True)])
def test_flash_grads_match_xla(causal, with_bias):
    """VERDICT r03 missing #2: jax.grad through flash_attention used to
    crash (no AD rule on the pallas_call); now a blockwise custom_vjp."""
    q = jnp.asarray(rnd(2, 2, 256, 32, seed=20))
    k = jnp.asarray(rnd(2, 2, 256, 32, seed=21))
    v = jnp.asarray(rnd(2, 2, 256, 32, seed=22))
    bias = jnp.asarray(rnd(2, 1, 256, 256, seed=23)) if with_bias else None
    w = jnp.asarray(rnd(2, 2, 256, 32, seed=24))

    def loss_flash(q, k, v, bias):
        return jnp.sum(
            flash_attention(q, k, v, bias, causal=causal, interpret=True)
            * w)

    def loss_xla(q, k, v, bias):
        return jnp.sum(xla_attention(q, k, v, bias, causal=causal) * w)

    args = (q, k, v, bias) if with_bias else (q, k, v, None)
    argnums = (0, 1, 2, 3) if with_bias else (0, 1, 2)
    gf = jax.grad(loss_flash, argnums)(*args)
    gx = jax.grad(loss_xla, argnums)(*args)
    for a, b, name in zip(gf, gx, "qkvb"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4,
                                   err_msg=f"grad d{name}")


@pytest.mark.parametrize("bias_shape", [
    (1, 1, 128, 128), (2, 1, 128, 128), (128, 128), (1, 128, 128)])
def test_flash_dbias_unbroadcast(bias_shape):
    """Bias cotangent must reduce back over broadcast dims, including
    biases with fewer than 4 dims (right-aligned numpy broadcasting)."""
    q = jnp.asarray(rnd(2, 3, 128, 16, seed=30))
    k = jnp.asarray(rnd(2, 3, 128, 16, seed=31))
    v = jnp.asarray(rnd(2, 3, 128, 16, seed=32))
    bias = jnp.asarray(rnd(*bias_shape, seed=33))

    def loss(fn, b):
        return jnp.sum(fn(q, k, v, b) ** 2)

    gf = jax.grad(lambda b: loss(
        lambda *a: flash_attention(*a, interpret=True), bias))(bias)
    gx = jax.grad(lambda b: loss(xla_attention, bias))(bias)
    assert gf.shape == bias.shape
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gx),
                               rtol=2e-3, atol=2e-4)

@pytest.mark.slow
def test_transformer_training_step_forces_flash(monkeypatch):
    """VERDICT r03 'done' criterion: a TransformerLM training step with
    the dispatch forced to the flash kernel (interpret mode on CPU) under
    jax.value_and_grad matches the xla-path gradients.  T=128 so the
    shapes tile; BIGDL_TPU_ATTENTION=flash forces the kernel even off-TPU
    (reference trains nn/Transformer.scala:749 — our TPU path must too)."""
    model = nn.Transformer(vocab_size=29, hidden_size=16, num_heads=2,
                           filter_size=32, num_hidden_layers=2,
                           with_share_weights_linear=True).eval_mode()
    tokens = jnp.asarray(
        np.random.RandomState(3).randint(1, 29, size=(2, 128)))
    targets = jnp.asarray(
        np.random.RandomState(4).randint(1, 29, size=(2, 128)))
    crit = nn.CrossEntropyCriterion()

    from bigdl_tpu.core.module import partition, combine
    params, rest = partition(model)

    def loss_fn(p):
        logits = combine(p, rest).forward(tokens)
        return crit(logits.reshape(-1, 29), targets.reshape(-1))

    def run():
        return jax.value_and_grad(loss_fn)(params)

    monkeypatch.setenv("BIGDL_TPU_ATTENTION", "flash")
    loss_f, grads_f = run()
    monkeypatch.setenv("BIGDL_TPU_ATTENTION", "xla")
    loss_x, grads_x = run()

    np.testing.assert_allclose(float(loss_f), float(loss_x),
                               rtol=1e-4, atol=1e-5)
    flat_f = jax.tree_util.tree_leaves_with_path(grads_f)
    flat_x = dict(jax.tree_util.tree_leaves_with_path(grads_x))
    assert flat_f
    for path, gf in flat_f:
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(flat_x[path]), rtol=5e-3, atol=5e-4,
            err_msg=jax.tree_util.keystr(path))


def test_multihead_attention_matches_torch():
    h, heads, b, t = 32, 4, 2, 6
    x = rnd(b, t, h, seed=11)
    layer = nn.Attention(h, heads).eval_mode()
    tl = torch.nn.MultiheadAttention(h, heads, bias=False, batch_first=True)
    with torch.no_grad():
        wq = torch.tensor(np.asarray(layer.q_layer.weight))
        wk = torch.tensor(np.asarray(layer.k_layer.weight))
        wv = torch.tensor(np.asarray(layer.v_layer.weight))
        tl.in_proj_weight.copy_(torch.cat([wq, wk, wv], 0))
        tl.out_proj.weight.copy_(
            torch.tensor(np.asarray(layer.output_layer.weight)))
    out = layer(jnp.asarray(x))
    ref, _ = tl(torch.tensor(x), torch.tensor(x), torch.tensor(x))
    np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-4)


def test_transformer_lm_forward_and_grad():
    model = nn.Transformer(vocab_size=17, hidden_size=16, num_heads=2,
                           filter_size=32, num_hidden_layers=2,
                           with_share_weights_linear=True).eval_mode()
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(1, 17, size=(2, 5)))
    logits = model(tokens)
    assert logits.shape == (2, 5, 17)

    from bigdl_tpu.core.module import partition, combine
    params, rest = partition(model)

    def loss_fn(p):
        return jnp.sum(combine(p, rest).forward(tokens) ** 2)

    grads = jax.grad(loss_fn)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert leaves and all(np.isfinite(np.asarray(g)).all() for g in leaves)


def test_transformer_causality():
    """Changing a future token must not change past logits."""
    model = nn.Transformer(vocab_size=11, hidden_size=16, num_heads=2,
                           filter_size=32, num_hidden_layers=2,
                           with_share_weights_linear=True).eval_mode()
    t1 = jnp.asarray([[1, 2, 3, 4, 5]])
    t2 = jnp.asarray([[1, 2, 3, 9, 5]])
    l1, l2 = model(t1), model(t2)
    # positions 0..3 see tokens shifted-right 0..2 / 0..3 → first 3 match
    np.testing.assert_allclose(np.asarray(l1[:, :3]), np.asarray(l2[:, :3]),
                               rtol=1e-5, atol=1e-6)
    assert not np.allclose(np.asarray(l1[:, 4]), np.asarray(l2[:, 4]))


def test_attention_causal_flag_matches_bias_small():
    """Quick default-suite lock on the kernel-side causal path (the
    heavyweight TransformerLM parity test is @slow): nn.Attention with
    causal=True must equal an explicit lower-triangular additive bias,
    and the decode-cache misuse paths must fail loudly."""
    h, heads, b, t = 16, 2, 2, 8
    x = jnp.asarray(rnd(b, t, h, seed=23))
    layer = nn.Attention(h, heads).eval_mode()
    tril = np.tril(np.ones((t, t), np.float32))
    bias = jnp.asarray(np.where(tril, 0.0, -1e9)[None, None])
    np.testing.assert_allclose(np.asarray(layer(x, causal=True)),
                               np.asarray(layer(x, None, bias)),
                               rtol=1e-5, atol=1e-6)
    cache = layer.init_cache(b, t)
    with pytest.raises(ValueError, match="decode cache"):
        layer(x[:, :1], cache=cache, cache_index=0, causal=True)
    dec = nn.TransformerDecoderLayer(h, heads, 32,
                                     with_cross_attention=False).eval_mode()
    with pytest.raises(ValueError, match="self_bias"):
        dec(x[:, :1], cache={"self": dec.self_attn.init_cache(b, t)},
            cache_index=0, self_causal=True)


def test_incremental_decode_matches_full_forward():
    model = nn.Transformer(vocab_size=13, hidden_size=16, num_heads=2,
                           filter_size=32, num_hidden_layers=2,
                           with_share_weights_linear=True).eval_mode()
    tokens = jnp.asarray(np.random.RandomState(1).randint(1, 13, size=(2, 6)))
    full = model(tokens)  # logits at position i use tokens < i (shifted)
    cache = model.init_decode_cache(2, 8)
    # Incremental convention (reference SequenceBeamSearch: ids start at
    # 0 = pad/start): feeding shifted token s_i = [0, t_0, t_1, ...][i]
    # at step i reproduces full[:, i].
    shifted = jnp.concatenate(
        [jnp.zeros((2, 1), tokens.dtype), tokens[:, :-1]], axis=1)
    for i in range(6):
        logits, cache = model.decode_step(shifted[:, i:i + 1], i, cache)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, i]),
            rtol=1e-4, atol=1e-4)


def test_beam_search_toy():
    """Scorer that deterministically prefers token (step+2) then EOS."""
    vocab, beam, tmax, eos = 8, 3, 5, 1

    def logits_fn(ids, step, cache):
        b = ids.shape[0]
        # strongly prefer token 2 at step 0, 3 at step 1, then EOS
        prefs = jnp.where(step == 0, 2, jnp.where(step == 1, 3, eos))
        logits = jnp.full((b, vocab), -5.0)
        logits = logits.at[:, prefs].set(5.0)
        return logits, cache

    bs = nn.SequenceBeamSearch(vocab, beam, alpha=0.6,
                               max_decode_length=tmax, eos_id=eos)
    bs.set_logit_fn(logits_fn)
    seq, scores = bs.search(2, {"dummy": jnp.zeros((2, 1))})
    assert seq.shape == (2, beam, tmax)
    # best hypothesis: [2, 3, eos, ...]
    np.testing.assert_array_equal(np.asarray(seq[0, 0, :3]), [2, 3, eos])
    assert float(scores[0, 0]) > float(scores[0, 1]) - 1e-6


def test_transformer_translation_mode():
    model = nn.Transformer(vocab_size=15, hidden_size=16, num_heads=2,
                           filter_size=32, num_hidden_layers=1,
                           transformer_type="translation",
                           with_share_weights_linear=True).eval_mode()
    src = jnp.asarray([[1, 2, 3, 0], [4, 5, 0, 0]])
    tgt = jnp.asarray([[6, 7], [8, 9]])
    out = model(src, tgt)
    assert out.shape == (2, 2, 15)
    assert np.isfinite(np.asarray(out)).all()


def test_auto_blocks_divide_and_fit():
    from bigdl_tpu.ops.attention_kernels import _auto_blocks

    # big clean lengths -> large square tiles
    assert _auto_blocks(4096, 4096, 64) == (1024, 1024)
    # a bias adds two more f32 score-shaped tiles; the picker must
    # shrink below the unbiased choice to stay inside scoped VMEM
    bq, bk = _auto_blocks(4096, 4096, 64, bias=True)
    assert 20 * bq * bk + 6 * (bq + bk) * 64 <= 14 * 2 ** 20
    assert (bq * bk) < 1024 * 1024
    # awkward lengths (divisible by 8, not 128, too big for one tile)
    # must still return exact divisors, never the old (128, 128)
    for t in (1160, 2056, 3000):
        bq, bk = _auto_blocks(t, t, 64)
        assert t % bq == 0 and t % bk == 0, (t, bq, bk)
    # explicit sizes always win over auto
    from bigdl_tpu.ops.attention_kernels import _resolve_blocks
    assert _resolve_blocks(256, None, 4096, 4096, 64) == (256, 1024)

@pytest.mark.slow
def test_padded_inputs_false_matches_bias_path():
    """padded_inputs=False moves the causal mask into the attention
    kernel; on a pad-free batch it must match the additive-bias path
    exactly (values and grads), and a padded batch must fail loudly."""
    import jax
    from bigdl_tpu.models.transformer_lm import TransformerLM
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.utils import set_seed

    set_seed(11)
    m_bias = TransformerLM(vocab_size=50, hidden_size=32, num_layers=2,
                           num_heads=2, filter_size=64, max_len=16)
    set_seed(11)
    m_ck = TransformerLM(vocab_size=50, hidden_size=32, num_layers=2,
                         num_heads=2, filter_size=64, max_len=16,
                         padded_inputs=False)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(1, 51, size=(3, 16)))

    def loss(m, t):
        params, rest = partition(m)
        def f(p):
            return jnp.sum(combine(p, rest).forward(t) ** 2)
        return jax.value_and_grad(f)(params)

    v1, g1 = loss(m_bias, toks)
    v2, g2 = loss(m_ck, toks)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                               rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    # padding must fail loudly, not silently attend to pad positions
    padded = toks.at[0, -3:].set(0)
    with pytest.raises(ValueError, match="padded"):
        m_ck.forward(padded)
