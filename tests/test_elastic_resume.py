"""Elastic fleet: topology-portable checkpoints and N->M resharded
resume (ISSUE 15 / ROADMAP item 3).

The acceptance invariant pinned here, on the 8-fake-device CPU mesh so
it lives in tier-1 and not only in multiprocess-capable envs: a
checkpoint written on an N-way mesh restores onto an M-way mesh with a
per-iteration loss trajectory EQUAL to the uninterrupted fixed-seed
run — fp32 exact when the data-parallel shard count is preserved (a
mesh reshape, 8 -> 2x4 / 4x2, slices the batch identically so every
reduction keeps its order), and within float tolerance when the shard
count itself changes (8 -> 4: the gradient all-reduce sums in a
different order).  The resumed run consumes exactly the
not-yet-consumed samples (pull-trace asserted) or explicitly falls
back to epoch-start replay — never a silent wrong-sample resume.
"""

import json
import logging
import os
import zlib

import numpy as np
import pytest

import jax

from bigdl_tpu import nn, telemetry
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import Sample
from bigdl_tpu.optim import Optimizer, Trigger
from bigdl_tpu.optim.methods import SGD, Adam
from bigdl_tpu.parallel import MeshConfig
from bigdl_tpu.telemetry import events as te
from bigdl_tpu.telemetry import families
from bigdl_tpu.telemetry.export import prometheus_text
from bigdl_tpu.utils import chaos, set_seed
from bigdl_tpu.utils.file import (
    CheckpointManager, checkpoint_manifest_path, checkpoint_topology,
    describe_topology, load_checkpoint_sharded,
    load_checkpoint_topology, save_checkpoint_sharded,
)


def _has_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401
        return True
    except ImportError:
        return False


needs_orbax = pytest.mark.skipif(not _has_orbax(),
                                 reason="orbax-checkpoint not installed")

N_SAMPLES = 64
BATCH = 16


def make_samples(n=N_SAMPLES):
    return [Sample(np.full((6,), i, np.float32), (i % 4) + 1)
            for i in range(n)]


def make_model():
    set_seed(77)
    return nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 4),
                         nn.LogSoftMax())


class LossLog:
    def __init__(self):
        self.losses = {}

    def add_scalar(self, name, v, step):
        if name == "Loss":
            self.losses[step] = v

    def flush(self):
        pass


class PullRecorder:
    """Transformer stage recording every sample id pulled through the
    pipeline (pre-batching) — the pull trace the acceptance criterion
    asserts on."""

    def __init__(self):
        self.ids = []

    def __call__(self, it):
        for s in it:
            self.ids.append(int(s.feature[0]))
            yield s


def run_train(reshard_at=None, reshard_to=None, ckdir=None,
              sharded=False, method=None, batch=BATCH, recorder=None,
              retries=3, epochs=3, shuffle=True):
    """One fixed-seed training run, optionally chaos-resharded mid-run
    (the fault makes the retry rebuild the mesh at the new width and
    resume from latest_good())."""
    set_seed(1234)
    chaos.reset()
    log = LossLog()
    ds = DataSet.array(make_samples(), shuffle=shuffle)
    if recorder is not None:
        ds = ds.transform(recorder)
    ds = ds.transform(SampleToMiniBatch(batch))
    opt = (Optimizer(make_model(), ds, nn.ClassNLLCriterion())
           .set_optim_method(method or SGD(0.1))
           .set_end_when(Trigger.max_epoch(epochs))
           .set_mesh(MeshConfig(data=-1))
           .set_train_summary(log))
    if ckdir is not None:
        opt.set_checkpoint(ckdir, Trigger.several_iteration(1),
                           sharded=sharded)
        opt.set_failure_retry(retries, interval_s=300, backoff_s=0.01,
                              backoff_cap_s=0.02)
    if reshard_at is not None:
        chaos.install(reshard_at_step=reshard_at, reshard_to=reshard_to)
    opt.optimize()
    chaos.reset()
    return opt, log.losses


# --------------------------------------------------------------------------
# Topology manifest
# --------------------------------------------------------------------------

class TestTopologyManifest:
    def test_manifest_records_topology_and_fence(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"params": {"w": np.zeros((4, 3), np.float32)}},
                 [{"t": np.int32(1)}], {"epoch": 1, "neval": 5},
                 generation=5)
        mpath = os.path.join(str(tmp_path),
                             "checkpoint.5.manifest.json")
        with open(mpath) as f:
            man = json.load(f)
        assert man["fence"] == 1
        topo = man["topology"]
        assert topo["process_count"] == 1
        assert topo["device_count"] == jax.device_count()
        leaves = topo["leaves"]
        (wkey,) = [k for k in leaves if "'w'" in k or "w" in k]
        assert leaves[wkey]["shape"] == [4, 3]
        assert leaves[wkey]["dtype"] == "float32"
        # module-level reader finds the same record next to the payload
        assert load_checkpoint_topology(
            os.path.join(str(tmp_path), "checkpoint.5.npz")) == topo

    def test_topology_mesh_from_writer_mesh(self, tmp_path):
        mesh = MeshConfig(dcn=2, data=4).build()
        topo = checkpoint_topology({"w": np.zeros((4,))}, [], mesh=mesh)
        assert topo["mesh"] == {"dcn": 2, "data": 4}
        assert "2 process" not in describe_topology(topo)
        assert "mesh {'dcn': 2, 'data': 4}" in describe_topology(topo)

    def test_topology_mesh_from_sharded_leaf(self):
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = MeshConfig(data=8).build()
        arr = jax.device_put(np.zeros((8, 2), np.float32),
                             NamedSharding(mesh, PartitionSpec("data")))
        topo = checkpoint_topology({"w": arr}, [])
        assert topo["mesh"] == {"data": 8}
        leaf = next(iter(topo["leaves"].values()))
        assert leaf["spec"] == ["data"]

    def test_load_topology_absent_is_none(self, tmp_path):
        assert load_checkpoint_topology(
            str(tmp_path / "checkpoint.npz")) is None
        assert "unknown topology" in describe_topology(None)


# --------------------------------------------------------------------------
# Writer fencing
# --------------------------------------------------------------------------

class TestWriterFencing:
    @staticmethod
    def _save(mgr, gen):
        mgr.save({"params": {"w": np.full((2,), gen, np.float32)}},
                 [], {"neval": gen}, generation=gen)

    def test_fence_monotonic_across_writers(self, tmp_path):
        a = CheckpointManager(str(tmp_path))
        self._save(a, 1)
        b = CheckpointManager(str(tmp_path))
        self._save(b, 2)
        assert a.claim_fence() == 1
        assert b.claim_fence() == 2
        c = CheckpointManager(str(tmp_path))
        assert c.claim_fence() == 3

    def test_partitioned_writer_race(self, tmp_path):
        """A rejoining primary (fence 2) resumed from an OLD generation
        must not be shadowed by a partitioned stale writer (fence 1)
        that keeps committing bigger generation numbers."""
        a = CheckpointManager(str(tmp_path))
        self._save(a, 5)
        self._save(a, 6)
        b = CheckpointManager(str(tmp_path))  # rejoins, claims fence 2
        self._save(b, 4)                      # resumed further back
        self._save(a, 7)                      # stale writer races on
        good = CheckpointManager(str(tmp_path)).latest_good()
        assert good.endswith("checkpoint.4.npz"), good
        from bigdl_tpu.utils.file import load_checkpoint
        _, _, driver = load_checkpoint(good)
        assert driver["neval"] == 4

    def test_legacy_unfenced_manifests_still_resolve(self, tmp_path):
        a = CheckpointManager(str(tmp_path))
        self._save(a, 3)
        mpath = os.path.join(str(tmp_path),
                             "checkpoint.3.manifest.json")
        with open(mpath) as f:
            man = json.load(f)
        del man["fence"]  # simulate a pre-fencing manifest
        with open(mpath, "w") as f:
            json.dump(man, f)
        assert CheckpointManager(str(tmp_path)).latest_good() \
            .endswith("checkpoint.3.npz")
        # and a new writer starts fence 1 above the legacy 0
        assert CheckpointManager(str(tmp_path)).claim_fence() == 1

    def test_gc_keeps_newest_fenced_lineage(self, tmp_path):
        a = CheckpointManager(str(tmp_path), keep_n=2)
        for g in (1, 2, 3):
            self._save(a, g)
        b = CheckpointManager(str(tmp_path), keep_n=2)
        self._save(b, 2)  # refenced lineage restarts at an older gen
        names = set(os.listdir(str(tmp_path)))
        # b's gen-2 (fence 2) and the newest survivor are kept; b's
        # save overwrote gen 2's payload+manifest under fence 2
        assert "checkpoint.2.npz" in names
        good = CheckpointManager(str(tmp_path)).latest_good()
        assert good.endswith("checkpoint.2.npz")


# --------------------------------------------------------------------------
# Chaos reshard seam
# --------------------------------------------------------------------------

class TestChaosReshard:
    def test_api_one_shot_carries_width(self):
        c = chaos.install(reshard_at_step=3, reshard_to=4)
        c.on_step(2)  # below threshold: no fire
        with pytest.raises(chaos.ReshardInjected) as ei:
            c.on_step(3)
        assert ei.value.new_width == 4
        assert isinstance(ei.value, chaos.FaultInjected)  # retryable
        c.on_step(4)  # one-shot: the retry must survive
        assert any("reshard" in e for e in c.events)
        chaos.reset()

    def test_env_form(self, monkeypatch):
        chaos.reset()
        monkeypatch.setenv("BIGDL_TPU_CHAOS_RESHARD", "2:6")
        try:
            with pytest.raises(chaos.ReshardInjected) as ei:
                chaos.on_step(2)
            assert ei.value.reshard_to == 6
        finally:
            chaos.reset()

    def test_env_malformed_raises_at_arm_time(self, monkeypatch):
        chaos.reset()
        monkeypatch.setenv("BIGDL_TPU_CHAOS_RESHARD", "nope")
        try:
            with pytest.raises(ValueError, match="step.*width"):
                chaos.on_step(1)
        finally:
            chaos.reset()

    def test_install_requires_both(self):
        with pytest.raises(ValueError, match="come together"):
            chaos.install(reshard_at_step=3)
        chaos.reset()

    def test_reshard_is_a_registered_event_kind(self):
        assert "reshard" in te.EVENT_KINDS


# --------------------------------------------------------------------------
# N->M resharded resume: the acceptance pins
# --------------------------------------------------------------------------

class TestElasticResume:
    def test_reshard_8_to_2x4_npz_exact(self, tmp_path):
        oracle, o_losses = run_train()
        te.reset_events()
        telemetry.reset()
        telemetry.enable()
        try:
            resharded, r_losses = run_train(
                reshard_at=6, reshard_to={"dcn": 2, "data": 4},
                ckdir=str(tmp_path))
            evs = [e for e in te.recent_events()
                   if e["kind"] == "reshard"]
            assert evs and evs[0]["new_axes"] == {"dcn": 2, "data": 4}
            counts = {}
            fam = families.checkpoint_reshard_restores_total()
            for labels, v in fam.samples():
                counts[labels[0]] = v
            assert counts.get("resharded", 0) >= 1, counts
        finally:
            telemetry.reset()
        assert r_losses == o_losses  # fp32 exact, every iteration
        for key in ("epoch", "neval", "records"):
            assert resharded.state[key] == oracle.state[key]

    @needs_orbax
    def test_reshard_8_to_4x2_sharded_exact(self, tmp_path):
        """The orbax path with a stateful method: momentum/variance
        restore through the abstract tree onto the reshaped mesh."""
        oracle, o_losses = run_train(method=Adam(0.05))
        resharded, r_losses = run_train(
            method=Adam(0.05), reshard_at=6,
            reshard_to={"dcn": 4, "data": 2}, ckdir=str(tmp_path),
            sharded=True)
        assert r_losses == o_losses
        for key in ("epoch", "neval", "records"):
            assert resharded.state[key] == oracle.state[key]

    def test_reshard_width_reduction_lost_devices(self, tmp_path):
        """8 -> data=4: half the devices gone (a lost slice).  The
        shard count changes, so the gradient all-reduce sums in a
        different order — losses agree to float tolerance, not
        bitwise (the documented bound)."""
        oracle, o_losses = run_train()
        resharded, r_losses = run_train(reshard_at=6, reshard_to=4,
                                        ckdir=str(tmp_path))
        assert set(r_losses) == set(o_losses)
        for s, v in o_losses.items():
            assert abs(r_losses[s] - v) <= 1e-5 * max(abs(v), 1.0), \
                (s, v, r_losses[s])
        assert resharded.state["records"] == oracle.state["records"]

    def test_resume_pull_trace_is_sample_accurate(self, tmp_path):
        """The resumed run consumes exactly the not-yet-consumed
        samples: the crashed attempt pulled a prefix of the epoch
        order, the retry re-pulls that prefix only to SKIP it (the
        restore cost), and everything trained after matches the
        oracle's order — asserted on the raw pull trace."""
        rec_o = PullRecorder()
        oracle, o_losses = run_train(recorder=rec_o, epochs=2)
        rec_c = PullRecorder()
        te.reset_events()
        crashed, c_losses = run_train(
            recorder=rec_c, epochs=2, reshard_at=6,
            reshard_to={"dcn": 2, "data": 4}, ckdir=str(tmp_path))
        assert c_losses == o_losses
        # the fault fires at iteration 6 = the 2nd batch of epoch 2
        # (4 steps/epoch), AFTER that batch was pulled: the crashed
        # attempt pulled epoch 1 + two epoch-2 batches (one trained,
        # one pulled-not-trained), and the resumed attempt re-pulled
        # the full epoch-2 order — the trained prefix only to SKIP it
        n_epoch = N_SAMPLES
        assert rec_c.ids[:n_epoch] == rec_o.ids[:n_epoch]  # epoch 1
        epoch2 = rec_o.ids[n_epoch:2 * n_epoch]
        crashed_prefix = rec_c.ids[n_epoch:n_epoch + 2 * BATCH]
        assert crashed_prefix == epoch2[:2 * BATCH]
        resumed = rec_c.ids[n_epoch + 2 * BATCH:]
        assert resumed == epoch2, \
            "resumed epoch must replay the identical global order"
        (ev,) = [e for e in te.recent_events()
                 if e["kind"] == "pipeline_restore"]
        assert ev["mode"] == "samples"
        assert ev["skipped"] == 1  # exactly the one TRAINED batch

    def test_explicit_resume_onto_new_mesh(self, tmp_path):
        """resume() a checkpoint into a SECOND Optimizer on a
        different mesh — the operator's runbook path (restart at
        reduced width), not the chaos seam."""
        oracle, o_losses = run_train(epochs=2)
        set_seed(1234)
        log1 = LossLog()
        ds = DataSet.array(make_samples()).transform(
            SampleToMiniBatch(BATCH))
        opt1 = (Optimizer(make_model(), ds, nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_iteration(6))
                .set_mesh(MeshConfig(data=-1))
                .set_checkpoint(str(tmp_path),
                                Trigger.several_iteration(1))
                .set_train_summary(log1))
        opt1.optimize()
        good = CheckpointManager(str(tmp_path)).latest_good()
        set_seed(1234)
        log2 = LossLog()
        ds2 = DataSet.array(make_samples()).transform(
            SampleToMiniBatch(BATCH))
        opt2 = (Optimizer(make_model(), ds2, nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_epoch(2))
                .set_mesh(MeshConfig(dcn=4, data=2))
                .set_train_summary(log2)
                .resume(good))
        opt2.optimize()
        merged = dict(log1.losses)
        merged.update(log2.losses)
        assert merged == o_losses
        for key in ("epoch", "neval", "records"):
            assert opt2.state[key] == oracle.state[key]


# --------------------------------------------------------------------------
# Pipeline fallback coverage: never a wrong-sample resume
# --------------------------------------------------------------------------

class TestPipelineTopologyFallback:
    def _opt(self, batch=BATCH):
        # explicit seed: make_model() re-seeds the process RNG, and
        # the plan's seed check must compare against the dataset's own
        ds = DataSet.array(make_samples(), seed=4357).transform(
            SampleToMiniBatch(batch))
        opt = Optimizer(make_model(), ds, nn.ClassNLLCriterion())
        opt.state["neval"] = 3
        return opt

    def _ps(self, **kw):
        base = {"version": 1, "seed": 4357, "epoch": 1, "offset": 2,
                "generation": 3}
        base.update(kw)
        return base

    def test_same_topology_uses_sample_mode(self):
        opt = self._opt()
        mode, n = opt._pipeline_restore_plan(
            self._ps(global_offset=32, process_count=1), epoch=1)
        assert (mode, n) == ("samples", 32)

    def test_legacy_sidecar_same_topology_uses_batches(self):
        opt = self._opt()
        mode, n = opt._pipeline_restore_plan(self._ps(), epoch=1)
        assert (mode, n) == ("batches", 2)

    def test_legacy_sidecar_changed_nproc_falls_back(self, caplog,
                                                     monkeypatch):
        """THE satellite case: sidecar written at nproc=4, read at a
        different process count, no global-offset fields -> epoch
        replay with a logged warning, never a wrong-sample skip."""
        opt = self._opt()
        opt._resume_topology = {"process_count": 4, "device_count": 8}
        with caplog.at_level(logging.WARNING, "bigdl_tpu.optim"):
            mode, n = opt._pipeline_restore_plan(self._ps(), epoch=1)
        assert (mode, n) == ("none", 0)
        assert "no global offset" in caplog.text
        assert "replaying the epoch" in caplog.text

    def test_legacy_sidecar_process_count_field_wins(self, caplog):
        opt = self._opt()
        with caplog.at_level(logging.WARNING, "bigdl_tpu.optim"):
            mode, n = opt._pipeline_restore_plan(
                self._ps(process_count=4), epoch=1)
        assert (mode, n) == ("none", 0)
        assert "written at process_count=4" in caplog.text

    def test_global_offset_not_divisible_falls_back(self, caplog,
                                                    monkeypatch):
        opt = self._opt()
        monkeypatch.setattr(jax, "process_count", lambda: 3)
        with caplog.at_level(logging.WARNING, "bigdl_tpu.optim"):
            mode, n = opt._pipeline_restore_plan(
                self._ps(global_offset=32, process_count=4), epoch=1)
        assert (mode, n) == ("none", 0)
        assert "does not divide" in caplog.text

    def test_divisible_converts_to_per_process_samples(self,
                                                       monkeypatch):
        opt = self._opt()
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        mode, n = opt._pipeline_restore_plan(
            self._ps(global_offset=32, process_count=4), epoch=1)
        assert (mode, n) == ("samples", 16)

    def test_mid_batch_misalignment_replays_epoch(self, tmp_path,
                                                  caplog):
        """Resume with a batch size whose boundaries don't hit the
        recorded global offset: the skip cannot split a batch, so the
        epoch replays from its start (with records reset), never a
        partial-batch resume."""
        set_seed(1234)
        ds = DataSet.array(make_samples()).transform(
            SampleToMiniBatch(16))
        opt1 = (Optimizer(make_model(), ds, nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_iteration(2))
                .set_checkpoint(str(tmp_path),
                                Trigger.several_iteration(1)))
        opt1.optimize()  # consumed 32 samples of epoch 1
        good = CheckpointManager(str(tmp_path)).latest_good()
        set_seed(1234)
        ds2 = DataSet.array(make_samples()).transform(
            SampleToMiniBatch(24))  # 24 does not divide 32
        opt2 = (Optimizer(make_model(), ds2, nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_epoch(1))
                .resume(good))
        with caplog.at_level(logging.WARNING, "bigdl_tpu.optim"):
            opt2.optimize()
        assert "lands mid-batch" in caplog.text
        # epoch replayed in full at batch 24 (drop_last trims the
        # ragged 16-sample tail): 48 samples counted, not 48 - 32
        assert opt2.state["records"] == 48

    def test_sidecar_doctored_on_disk_e2e(self, tmp_path, caplog):
        """File-level variant: strip the global fields from the
        on-disk sidecar and stamp the manifest's topology as nproc=4
        (keeping the CRC honest) — resume must warn and replay."""
        set_seed(1234)
        ds = DataSet.array(make_samples()).transform(
            SampleToMiniBatch(BATCH))
        opt1 = (Optimizer(make_model(), ds, nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_iteration(2))
                .set_checkpoint(str(tmp_path),
                                Trigger.several_iteration(1)))
        opt1.optimize()
        good = CheckpointManager(str(tmp_path)).latest_good()
        spath = os.path.join(str(tmp_path), "checkpoint.pipeline.json")
        with open(spath) as f:
            ps = json.load(f)
        for k in ("global_offset", "process_count", "global_batch"):
            ps.pop(k, None)
        data = json.dumps(ps, sort_keys=True).encode()
        with open(spath, "wb") as f:
            f.write(data)
        mpath = checkpoint_manifest_path(good)
        with open(mpath) as f:
            man = json.load(f)
        man["pipeline"]["crc32"] = zlib.crc32(data) & 0xFFFFFFFF
        man["pipeline"]["size"] = len(data)
        man["topology"]["process_count"] = 4
        with open(mpath, "w") as f:
            json.dump(man, f)
        set_seed(1234)
        ds2 = DataSet.array(make_samples()).transform(
            SampleToMiniBatch(BATCH))
        opt2 = (Optimizer(make_model(), ds2, nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_epoch(1))
                .resume(good))
        with caplog.at_level(logging.WARNING, "bigdl_tpu.optim"):
            opt2.optimize()
        assert "no global offset" in caplog.text
        assert opt2.state["records"] == N_SAMPLES  # full replay


# --------------------------------------------------------------------------
# Unportable-leaf diagnostics (the actionable error)
# --------------------------------------------------------------------------

@needs_orbax
class TestUnportableLeaf:
    def test_shape_mismatch_names_both_topologies(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"params": {"w": np.zeros((4, 3), np.float32)},
                  "buffers": {}},
                 [{"m": np.zeros((4, 3), np.float32)}],
                 {"epoch": 1}, generation=1, sharded=True)
        path = os.path.join(str(tmp_path), "checkpoint.1.orbax")
        abstract = {
            "model": {"params": {
                "w": jax.ShapeDtypeStruct((8, 3), np.float32)},
                "buffers": {}},
            "optim": [{"m": jax.ShapeDtypeStruct((8, 3), np.float32)}],
            "driver": {"epoch": jax.ShapeDtypeStruct((), np.int64)},
        }
        with pytest.raises(ValueError) as ei:
            load_checkpoint_sharded(path, abstract_state=abstract)
        msg = str(ei.value)
        assert "not portable" in msg
        assert "1 process(es)" in msg       # saved topology named
        assert "Re-save on the current mesh" in msg

    def test_matching_shapes_reshard_via_device_put(self, tmp_path):
        """Even when strict orbax restore fails, matching-shape leaves
        come back through the host + device_put path sharded onto the
        CURRENT mesh."""
        from jax.sharding import NamedSharding, PartitionSpec
        path = str(tmp_path / "ck.orbax")
        save_checkpoint_sharded(
            path, {"params": {"w": np.arange(16, dtype=np.float32)
                              .reshape(8, 2)}, "buffers": {}},
            [], {"epoch": 2})
        mesh = MeshConfig(dcn=2, data=4).build()
        sh = NamedSharding(mesh, PartitionSpec(("dcn", "data")))
        abstract = {
            "model": {"params": {"w": jax.ShapeDtypeStruct(
                (8, 2), np.float32, sharding=sh)}, "buffers": {}},
            "optim": [],
            "driver": {"epoch": jax.ShapeDtypeStruct((), np.int64)},
        }
        ms, _opt, driver = load_checkpoint_sharded(
            path, abstract_state=abstract)
        w = ms["params"]["w"]
        assert driver["epoch"] == 2
        np.testing.assert_array_equal(
            np.asarray(w), np.arange(16, dtype=np.float32).reshape(8, 2))
        assert w.sharding.mesh.shape["dcn"] == 2


# --------------------------------------------------------------------------
# Telemetry family
# --------------------------------------------------------------------------

class TestReshardFamily:
    def test_preregistered_and_labeled(self):
        telemetry.reset()
        telemetry.enable()
        try:
            families.preregister()
            text = prometheus_text()
            assert "checkpoint_reshard_restores_total" in text
            families.checkpoint_reshard_restores_total() \
                .labels("fallback").inc()
            text = prometheus_text()
            assert 'outcome="fallback"' in text
        finally:
            telemetry.reset()


# --------------------------------------------------------------------------
# Replica start-generation (serving fabric satellite)
# --------------------------------------------------------------------------

class TestReplicaStartGeneration:
    @staticmethod
    def _snap(directory, gen, **kw):
        from bigdl_tpu.serving.replica import replica_snapshot
        from bigdl_tpu.telemetry.fleet import write_host_snapshot
        snap = replica_snapshot(0, start_generation=gen, **kw)
        write_host_snapshot(directory, snap)

    def test_regressed_generation_is_rewarming(self, tmp_path):
        from bigdl_tpu.serving.replica import ReplicaRegistry
        reg = ReplicaRegistry(str(tmp_path), max_age_s=60.0)
        self._snap(str(tmp_path), gen=2)
        rec = reg.poll()[0]
        assert rec["healthy"] and not rec.get("rewarming")
        # the dead pre-restart incarnation's final write lands late,
        # carrying its drain flag and TTFT tail
        self._snap(str(tmp_path), gen=1, draining=True)
        rec = reg.poll()[0]
        assert rec["rewarming"] is True
        assert rec["healthy"] is True
        assert rec["draining"] is False
        assert rec["ttft_p99_s"] == 0.0

    def test_restart_clears_stale_healthz_verdict(self, tmp_path):
        from bigdl_tpu.serving.replica import ReplicaRegistry
        reg = ReplicaRegistry(str(tmp_path), max_age_s=60.0)
        self._snap(str(tmp_path), gen=1)
        reg.observe_healthz(0, 503, {"status": "draining"})
        assert reg.poll()[0]["draining"] is True
        # replica restarts under the same id: new incarnation
        self._snap(str(tmp_path), gen=2)
        rec = reg.poll()[0]
        assert rec["draining"] is False
        assert rec["healthy"] is True

    def test_replica_objects_stamp_increasing_generations(self):
        from bigdl_tpu.serving.replica import Replica, replica_snapshot
        snap = replica_snapshot(3, start_generation=17)
        assert snap["start_generation"] == 17

        class FakeTarget:
            def submit_generate_async(self, *a, **k):  # pragma: no cover
                raise NotImplementedError

            def shutdown(self, **k):
                pass

        r1 = Replica(1, FakeTarget(), start_generation=10)
        r2 = Replica(1, FakeTarget(), start_generation=11)
        assert r2.start_generation > r1.start_generation
        assert r1.snapshot()["start_generation"] == 10
        r1.close(drain=False)
        r2.close(drain=False)
