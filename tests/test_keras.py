"""Tests for the Keras-compatible API (reference nn/keras/Topology.scala
+ keras layer wrappers with shape inference)."""

import numpy as np
import pytest

from bigdl_tpu import keras
from bigdl_tpu.utils import set_seed


def test_shape_inference_at_add_time():
    set_seed(0)
    m = (keras.Sequential()
         .add(keras.Dense(16, activation="relu", input_shape=(8,)))
         .add(keras.Dense(4, activation="softmax")))
    layers = m.layers.modules()
    assert layers[0].built and layers[0].output_shape == (16,)
    assert layers[1].built and layers[1].output_shape == (4,)
    assert m.get_output_shape() == (4,)


def test_conv_pool_flatten_shapes():
    set_seed(0)
    m = (keras.Sequential()
         .add(keras.Convolution2D(6, 5, 5, activation="relu",
                                  input_shape=(28, 28, 1)))
         .add(keras.MaxPooling2D((2, 2)))
         .add(keras.Convolution2D(12, 5, 5, border_mode="same"))
         .add(keras.Flatten())
         .add(keras.Dense(10, activation="log_softmax")))
    mods = m.layers.modules()
    assert mods[0].output_shape == (24, 24, 6)
    assert mods[1].output_shape == (12, 12, 6)
    assert mods[2].output_shape == (12, 12, 12)
    assert mods[3].output_shape == (12 * 12 * 12,)
    x = np.random.default_rng(0).normal(size=(2, 28, 28, 1)) \
        .astype(np.float32)
    import jax.numpy as jnp
    y = m.eval_mode().forward(jnp.asarray(x))
    assert y.shape == (2, 10)


def test_same_padding_inference_matches_execution():
    import jax.numpy as jnp
    set_seed(7)
    # odd input + even kernel/pool: the hard case for SAME padding
    m = (keras.Sequential()
         .add(keras.Convolution2D(4, 2, 2, border_mode="same",
                                  subsample=(2, 2),
                                  input_shape=(5, 5, 3)))
         .add(keras.MaxPooling2D((2, 2), border_mode="same"))
         .add(keras.Flatten())
         .add(keras.Dense(2)))
    mods = m.layers.modules()
    x = jnp.ones((1, 5, 5, 3))
    y = m.eval_mode().forward(x)
    assert mods[0].output_shape == (3, 3, 4)
    assert mods[1].output_shape == (2, 2, 4)
    assert y.shape == (1, 2)


def test_lazy_build_on_first_forward():
    set_seed(0)
    m = keras.Sequential().add(keras.Dense(3))  # no input_shape anywhere
    import jax.numpy as jnp
    y = m.forward(jnp.ones((2, 7)))
    assert y.shape == (2, 3)
    assert m.layers[0].built and m.layers[0].input_shape == (7,)


def test_compile_fit_evaluate_predict():
    set_seed(1)
    rng = np.random.default_rng(0)
    # linearly separable 2-class problem
    x = rng.normal(size=(64, 6)).astype(np.float32)
    w = rng.normal(size=(6,))
    labels = (x @ w > 0).astype(np.int64) + 1  # 1-based classes
    m = (keras.Sequential()
         .add(keras.Dense(16, activation="relu", input_shape=(6,)))
         .add(keras.Dense(2, activation="log_softmax")))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, labels, batch_size=16, nb_epoch=15,
          validation_data=(x, labels))
    results = m.evaluate(x, labels, batch_size=16)
    acc = results[0][0].result()[0]
    assert acc > 0.85, f"keras fit failed to learn: acc={acc}"
    preds = m.predict(x, batch_size=16)
    assert preds.shape == (64, 2)
    classes = m.predict_classes(x, batch_size=16)
    assert set(classes) <= {1, 2}
    assert (classes == labels).mean() > 0.85


def test_lstm_and_embedding_shapes():
    set_seed(2)
    m = (keras.Sequential()
         .add(keras.Embedding(50, 8, input_shape=(12,)))
         .add(keras.LSTM(16, return_sequences=True))
         .add(keras.LSTM(6)))
    mods = m.layers.modules()
    assert mods[0].output_shape == (12, 8)
    assert mods[1].output_shape == (12, 16)
    assert mods[2].output_shape == (6,)
    import jax.numpy as jnp
    ids = jnp.asarray(np.random.default_rng(0).integers(
        1, 51, size=(3, 12)))
    y = m.eval_mode().forward(ids)
    assert y.shape == (3, 6)


def test_unknown_names_raise():
    with pytest.raises(ValueError):
        keras.Dense(4, activation="nope", input_shape=(3,)).build((3,))
    m = keras.Sequential().add(keras.Dense(4, input_shape=(3,)))
    with pytest.raises(ValueError):
        m.compile("sgd", "not_a_loss")
    with pytest.raises(ValueError):
        m.compile("not_an_opt", "mse")
    with pytest.raises(RuntimeError):
        keras.Sequential().add(keras.Dense(2, input_shape=(3,))).fit(
            np.ones((8, 3), np.float32), np.ones((8, 2), np.float32))
