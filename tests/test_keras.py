"""Tests for the Keras-compatible API (reference nn/keras/Topology.scala
+ keras layer wrappers with shape inference)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import keras
from bigdl_tpu import keras as kl
from bigdl_tpu.utils import set_seed


def test_shape_inference_at_add_time():
    set_seed(0)
    m = (keras.Sequential()
         .add(keras.Dense(16, activation="relu", input_shape=(8,)))
         .add(keras.Dense(4, activation="softmax")))
    layers = m.layers.modules()
    assert layers[0].built and layers[0].output_shape == (16,)
    assert layers[1].built and layers[1].output_shape == (4,)
    assert m.get_output_shape() == (4,)


def test_conv_pool_flatten_shapes():
    set_seed(0)
    m = (keras.Sequential()
         .add(keras.Convolution2D(6, 5, 5, activation="relu",
                                  input_shape=(28, 28, 1)))
         .add(keras.MaxPooling2D((2, 2)))
         .add(keras.Convolution2D(12, 5, 5, border_mode="same"))
         .add(keras.Flatten())
         .add(keras.Dense(10, activation="log_softmax")))
    mods = m.layers.modules()
    assert mods[0].output_shape == (24, 24, 6)
    assert mods[1].output_shape == (12, 12, 6)
    assert mods[2].output_shape == (12, 12, 12)
    assert mods[3].output_shape == (12 * 12 * 12,)
    x = np.random.default_rng(0).normal(size=(2, 28, 28, 1)) \
        .astype(np.float32)
    import jax.numpy as jnp
    y = m.eval_mode().forward(jnp.asarray(x))
    assert y.shape == (2, 10)


def test_same_padding_inference_matches_execution():
    import jax.numpy as jnp
    set_seed(7)
    # odd input + even kernel/pool: the hard case for SAME padding
    m = (keras.Sequential()
         .add(keras.Convolution2D(4, 2, 2, border_mode="same",
                                  subsample=(2, 2),
                                  input_shape=(5, 5, 3)))
         .add(keras.MaxPooling2D((2, 2), border_mode="same"))
         .add(keras.Flatten())
         .add(keras.Dense(2)))
    mods = m.layers.modules()
    x = jnp.ones((1, 5, 5, 3))
    y = m.eval_mode().forward(x)
    assert mods[0].output_shape == (3, 3, 4)
    assert mods[1].output_shape == (2, 2, 4)
    assert y.shape == (1, 2)


def test_lazy_build_on_first_forward():
    set_seed(0)
    m = keras.Sequential().add(keras.Dense(3))  # no input_shape anywhere
    import jax.numpy as jnp
    y = m.forward(jnp.ones((2, 7)))
    assert y.shape == (2, 3)
    assert m.layers[0].built and m.layers[0].input_shape == (7,)


def test_compile_fit_evaluate_predict():
    set_seed(1)
    rng = np.random.default_rng(0)
    # linearly separable 2-class problem
    x = rng.normal(size=(64, 6)).astype(np.float32)
    w = rng.normal(size=(6,))
    labels = (x @ w > 0).astype(np.int64) + 1  # 1-based classes
    m = (keras.Sequential()
         .add(keras.Dense(16, activation="relu", input_shape=(6,)))
         .add(keras.Dense(2, activation="log_softmax")))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    # 40 epochs, not 15: under this environment's jax the seeded run is
    # DETERMINISTIC but converges slower than the tolerance assumed
    # (measured on this seed: 15 epochs -> 0.797, 25 -> 0.875,
    # 40 -> 0.9375), so the old 15-epoch/0.85 pairing failed on every
    # run, not flakily.  40 epochs clears the bar with margin.
    m.fit(x, labels, batch_size=16, nb_epoch=40,
          validation_data=(x, labels))
    results = m.evaluate(x, labels, batch_size=16)
    acc = results[0][0].result()[0]
    assert acc > 0.85, f"keras fit failed to learn: acc={acc}"
    preds = m.predict(x, batch_size=16)
    assert preds.shape == (64, 2)
    classes = m.predict_classes(x, batch_size=16)
    assert set(classes) <= {1, 2}
    assert (classes == labels).mean() > 0.85


def test_lstm_and_embedding_shapes():
    set_seed(2)
    m = (keras.Sequential()
         .add(keras.Embedding(50, 8, input_shape=(12,)))
         .add(keras.LSTM(16, return_sequences=True))
         .add(keras.LSTM(6)))
    mods = m.layers.modules()
    assert mods[0].output_shape == (12, 8)
    assert mods[1].output_shape == (12, 16)
    assert mods[2].output_shape == (6,)
    import jax.numpy as jnp
    ids = jnp.asarray(np.random.default_rng(0).integers(
        1, 51, size=(3, 12)))
    y = m.eval_mode().forward(ids)
    assert y.shape == (3, 6)


def test_unknown_names_raise():
    with pytest.raises(ValueError):
        keras.Dense(4, activation="nope", input_shape=(3,)).build((3,))
    m = keras.Sequential().add(keras.Dense(4, input_shape=(3,)))
    with pytest.raises(ValueError):
        m.compile("sgd", "not_a_loss")
    with pytest.raises(ValueError):
        m.compile("not_an_opt", "mse")
    with pytest.raises(RuntimeError):
        keras.Sequential().add(keras.Dense(2, input_shape=(3,))).fit(
            np.ones((8, 3), np.float32), np.ones((8, 2), np.float32))


# ---- Keras-1.2.2 JSON/HDF5 converter (≙ pyspark keras/converter.py) ------

def _h5_weights(path, layers):
    """Write a Keras-1.2.2-layout HDF5 weight file."""
    h5py = pytest.importorskip("h5py")
    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = np.array(
            [n.encode() for n in layers], dtype="S32")
        for lname, ws in layers.items():
            g = f.create_group(lname)
            wnames = [f"{lname}/w_{i}".encode()
                      for i in range(len(ws))]
            g.attrs["weight_names"] = np.array(wnames, dtype="S64")
            for nm, w in zip(wnames, ws):
                g.create_dataset(nm.decode(), data=w)


def test_keras_json_dense_sequential(tmp_path):
    from bigdl_tpu.keras import load_keras
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "Dense", "config": {
            "name": "fc1", "output_dim": 5, "activation": "relu",
            "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "fc2", "output_dim": 3, "activation": "softmax"}},
    ]}
    rng = np.random.RandomState(0)
    w1, b1 = rng.randn(4, 5).astype(np.float32), \
        rng.randn(5).astype(np.float32)
    w2, b2 = rng.randn(5, 3).astype(np.float32), \
        rng.randn(3).astype(np.float32)
    jp = tmp_path / "model.json"
    jp.write_text(__import__("json").dumps(spec))
    hp = str(tmp_path / "weights.h5")
    _h5_weights(hp, {"fc1": [w1, b1], "fc2": [w2, b2]})
    model = load_keras(str(jp), hp)
    x = rng.randn(2, 4).astype(np.float32)
    got = np.asarray(model.eval_mode().forward(jnp.asarray(x)))
    h = np.maximum(x @ w1 + b1, 0)
    logits = h @ w2 + b2
    want = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_keras_json_conv_tf_ordering(tmp_path):
    from bigdl_tpu.keras import load_keras_hdf5_weights, load_keras_json
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "Convolution2D", "config": {
            "name": "c1", "nb_filter": 2, "nb_row": 3, "nb_col": 3,
            "dim_ordering": "tf", "border_mode": "same",
            "batch_input_shape": [None, 6, 6, 3]}},
        {"class_name": "Flatten", "config": {"name": "fl"}},
    ]}
    model = load_keras_json(spec)
    rng = np.random.RandomState(1)
    kw = rng.randn(3, 3, 3, 2).astype(np.float32)
    kb = rng.randn(2).astype(np.float32)
    hp = str(tmp_path / "w.h5")
    _h5_weights(hp, {"c1": [kw, kb]})
    load_keras_hdf5_weights(model, hp)
    x = rng.randn(1, 6, 6, 3).astype(np.float32)
    got = np.asarray(model.eval_mode().forward(jnp.asarray(x)))
    assert got.shape == (1, 72)
    tor = pytest.importorskip("torch")
    want = tor.nn.functional.conv2d(
        tor.tensor(x.transpose(0, 3, 1, 2)),
        tor.tensor(kw.transpose(3, 2, 0, 1)), tor.tensor(kb),
        padding=1).permute(0, 2, 3, 1).reshape(1, -1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras_th_ordering_end_to_end(tmp_path):
    """dim_ordering='th' (NCHW, the keras-1.x default; VERDICT r03
    missing #5): conv -> pool -> flatten -> dense with th weights,
    oracle = torch executing the same NCHW math.  The NCHW flatten
    order must match the Dense weights (the part a transpose-at-import
    shortcut would get wrong)."""
    tor = pytest.importorskip("torch")
    from bigdl_tpu.keras import load_keras_hdf5_weights, load_keras_json
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "Convolution2D", "config": {
            "name": "c1", "nb_filter": 4, "nb_row": 3, "nb_col": 3,
            "dim_ordering": "th", "activation": "relu",
            "batch_input_shape": [None, 3, 8, 8]}},
        {"class_name": "MaxPooling2D", "config": {
            "name": "p1", "pool_size": [2, 2], "dim_ordering": "th"}},
        {"class_name": "Flatten", "config": {"name": "fl"}},
        {"class_name": "Dense", "config": {
            "name": "fc", "output_dim": 5}},
    ]}
    model = load_keras_json(spec)
    rng = np.random.RandomState(3)
    kw = rng.randn(4, 3, 3, 3).astype(np.float32)   # th: (out,in,r,c)
    kb = rng.randn(4).astype(np.float32)
    fw = rng.randn(4 * 3 * 3, 5).astype(np.float32)  # keras (in, out)
    fb = rng.randn(5).astype(np.float32)
    hp = str(tmp_path / "w.h5")
    _h5_weights(hp, {"c1": [kw, kb], "fc": [fw, fb]})
    load_keras_hdf5_weights(model, hp)

    x = rng.randn(2, 3, 8, 8).astype(np.float32)     # NCHW input
    got = np.asarray(model.eval_mode().forward(jnp.asarray(x)))

    h = tor.nn.functional.relu(tor.nn.functional.conv2d(
        tor.tensor(x), tor.tensor(kw), tor.tensor(kb)))
    h = tor.nn.functional.max_pool2d(h, 2)
    h = h.reshape(2, -1)                              # NCHW flatten
    want = (h @ tor.tensor(fw) + tor.tensor(fb)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_keras_functional_model_with_merge():
    from bigdl_tpu.keras import load_keras_json
    spec = {"class_name": "Model", "config": {
        "layers": [
            {"class_name": "InputLayer", "name": "inp",
             "config": {"name": "inp", "batch_input_shape": [None, 4]},
             "inbound_nodes": []},
            {"class_name": "Dense", "name": "a",
             "config": {"name": "a", "output_dim": 4,
                        "activation": "relu"},
             "inbound_nodes": [[["inp", 0, 0]]]},
            {"class_name": "Dense", "name": "b",
             "config": {"name": "b", "output_dim": 4},
             "inbound_nodes": [[["inp", 0, 0]]]},
            {"class_name": "Merge", "name": "m",
             "config": {"name": "m", "mode": "concat",
                        "concat_axis": -1},
             "inbound_nodes": [[["a", 0, 0], ["b", 0, 0]]]},
        ],
        "input_layers": [["inp", 0, 0]],
        "output_layers": [["m", 0, 0]],
    }}
    model = load_keras_json(spec)
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    out = np.asarray(model.eval_mode().forward(jnp.asarray(x)))
    assert out.shape == (2, 8)


def test_keras_model_config_in_h5(tmp_path):
    h5py = pytest.importorskip("h5py")
    from bigdl_tpu.keras import load_keras
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "Dense", "config": {
            "name": "fc", "output_dim": 2,
            "batch_input_shape": [None, 3]}}]}
    rng = np.random.RandomState(2)
    w, b = rng.randn(3, 2).astype(np.float32), \
        rng.randn(2).astype(np.float32)
    hp = str(tmp_path / "full.h5")
    _h5_weights(hp, {"fc": [w, b]})
    with h5py.File(hp, "a") as f:
        f.attrs["model_config"] = __import__("json").dumps(spec)
    model = load_keras(hdf5_path=hp)
    x = rng.randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(model.eval_mode().forward(jnp.asarray(x))),
        x @ w + b, rtol=1e-5, atol=1e-6)


def test_keras_unknown_class_errors():
    from bigdl_tpu.keras import load_keras_json
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "FancyCustomLayer", "config": {}}]}
    with pytest.raises(ValueError, match="FancyCustomLayer"):
        load_keras_json(spec)


def test_keras_functional_input_order():
    """Graph inputs must follow input_layers order, not DFS order."""
    from bigdl_tpu.keras import load_keras_json
    spec = {"class_name": "Model", "config": {
        "layers": [
            {"class_name": "InputLayer", "name": "ia",
             "config": {"name": "ia", "batch_input_shape": [None, 2]},
             "inbound_nodes": []},
            {"class_name": "InputLayer", "name": "ib",
             "config": {"name": "ib", "batch_input_shape": [None, 2]},
             "inbound_nodes": []},
            {"class_name": "Merge", "name": "m",
             "config": {"name": "m", "mode": "concat",
                        "concat_axis": -1},
             # output traversal reaches ib FIRST
             "inbound_nodes": [[["ib", 0, 0], ["ia", 0, 0]]]},
        ],
        "input_layers": [["ia", 0, 0], ["ib", 0, 0]],
        "output_layers": [["m", 0, 0]],
    }}
    model = load_keras_json(spec)
    xa = jnp.asarray(np.zeros((1, 2), np.float32))
    xb = jnp.asarray(np.ones((1, 2), np.float32))
    out = np.asarray(model.eval_mode().forward((xa, xb)))
    # concat order is (ib, ia) per the merge, fed positionally (ia, ib)
    np.testing.assert_allclose(out, [[1, 1, 0, 0]])


def test_keras_lstm_variable_timesteps():
    from bigdl_tpu.keras import load_keras_json
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "LSTM", "config": {
            "name": "l", "output_dim": 4,
            "batch_input_shape": [None, None, 3]}}]}
    model = load_keras_json(spec)
    x = np.random.RandomState(0).randn(2, 7, 3).astype(np.float32)
    out = np.asarray(model.eval_mode().forward(jnp.asarray(x)))
    assert out.shape == (2, 4)


def test_new_keras_layers_forward_shapes():
    """Every new wrapper builds and produces its inferred shape."""
    from bigdl_tpu import keras as K
    set_seed(0)
    rng = np.random.RandomState(0)
    cases = [
        (K.Convolution1D(4, 3, input_shape=(10, 6)), (2, 10, 6), (2, 8, 4)),
        (K.MaxPooling1D(2, input_shape=(10, 6)), (2, 10, 6), (2, 5, 6)),
        (K.AveragePooling1D(2, input_shape=(10, 6)), (2, 10, 6),
         (2, 5, 6)),
        (K.GlobalMaxPooling1D(input_shape=(10, 6)), (2, 10, 6), (2, 6)),
        (K.GlobalAveragePooling1D(input_shape=(10, 6)), (2, 10, 6),
         (2, 6)),
        (K.GlobalMaxPooling2D(input_shape=(5, 6, 3)), (2, 5, 6, 3),
         (2, 3)),
        (K.ZeroPadding2D((1, 2), input_shape=(5, 6, 3)), (2, 5, 6, 3),
         (2, 7, 10, 3)),
        (K.UpSampling2D((2, 3), input_shape=(4, 5, 3)), (2, 4, 5, 3),
         (2, 8, 15, 3)),
        (K.RepeatVector(4, input_shape=(6,)), (2, 6), (2, 4, 6)),
        (K.Permute((2, 1), input_shape=(3, 5)), (2, 3, 5), (2, 5, 3)),
        (K.Masking(0.0, input_shape=(4, 3)), (2, 4, 3), (2, 4, 3)),
        (K.TimeDistributedDense(7, input_shape=(4, 3)), (2, 4, 3),
         (2, 4, 7)),
        (K.ELU(input_shape=(5,)), (2, 5), (2, 5)),
        (K.LeakyReLU(input_shape=(5,)), (2, 5), (2, 5)),
        (K.ThresholdedReLU(0.5, input_shape=(5,)), (2, 5), (2, 5)),
    ]
    for layer, in_shape, want in cases:
        x = jnp.asarray(rng.randn(*in_shape).astype(np.float32))
        out = layer.eval_mode().forward(x)
        assert tuple(out.shape) == want, \
            (type(layer).__name__, tuple(out.shape), want)
        assert layer.output_shape == want[1:], type(layer).__name__


def test_permute_values():
    from bigdl_tpu import keras as K
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    lay = K.Permute((2, 1), input_shape=(3, 4))
    out = np.asarray(lay.eval_mode().forward(jnp.asarray(x)))
    np.testing.assert_array_equal(out, x.transpose(0, 2, 1))
    x2 = np.arange(48, dtype=np.float32).reshape(2, 2, 3, 4)
    lay2 = K.Permute((3, 1, 2), input_shape=(2, 3, 4))
    out2 = np.asarray(lay2.eval_mode().forward(jnp.asarray(x2)))
    np.testing.assert_array_equal(out2, x2.transpose(0, 3, 1, 2))


def test_bidirectional_lstm():
    from bigdl_tpu import keras as K
    set_seed(2)
    layer = K.Bidirectional(
        K.LSTM(4, return_sequences=True, input_shape=(6, 3)))
    x = jnp.asarray(np.random.RandomState(1).randn(2, 6, 3)
                    .astype(np.float32))
    out = layer.eval_mode().forward(x)
    assert tuple(out.shape) == (2, 6, 8)
    assert layer.output_shape == (6, 8)


def test_new_layers_via_json_converter():
    from bigdl_tpu.keras import load_keras_json
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "Convolution1D", "config": {
            "name": "c", "nb_filter": 4, "filter_length": 3,
            "activation": "relu", "batch_input_shape": [None, 10, 6]}},
        {"class_name": "GlobalMaxPooling1D", "config": {"name": "g"}},
        {"class_name": "RepeatVector", "config": {"name": "r", "n": 5}},
        {"class_name": "TimeDistributedDense", "config": {
            "name": "t", "output_dim": 2}},
    ]}
    model = load_keras_json(spec)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 10, 6)
                    .astype(np.float32))
    out = model.eval_mode().forward(x)
    assert tuple(out.shape) == (2, 5, 2)


def test_pool1d_same_border_rejected():
    from bigdl_tpu.keras import load_keras_json
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "MaxPooling1D", "config": {
            "name": "p", "pool_length": 2, "border_mode": "same",
            "batch_input_shape": [None, 10, 6]}}]}
    with pytest.raises(ValueError, match="border_mode"):
        load_keras_json(spec)


def test_th_ordering_global_pools():
    """th global pooling reduces the trailing spatial dims (channels
    stay axis 1)."""
    from bigdl_tpu.keras import load_keras_json
    rng = np.random.RandomState(4)
    x = rng.randn(2, 3, 5, 6).astype(np.float32)
    for cls, red in (("GlobalMaxPooling2D", np.max),
                     ("GlobalAveragePooling2D", np.mean)):
        spec = {"class_name": "Sequential", "config": [
            {"class_name": cls, "config": {
                "name": "g", "dim_ordering": "th",
                "batch_input_shape": [None, 3, 5, 6]}}]}
        m = load_keras_json(spec)
        got = np.asarray(m.eval_mode().forward(jnp.asarray(x)))
        np.testing.assert_allclose(got, red(x, axis=(2, 3)),
                                   rtol=1e-5, atol=1e-6, err_msg=cls)


@pytest.mark.parametrize("layer_fn,in_shape", [
    (lambda: kl.Convolution3D(4, 2, 2, 2), (5, 6, 7, 3)),
    (lambda: kl.Convolution3D(4, 2, 2, 2, border_mode="same",
                              subsample=(2, 2, 2)), (5, 6, 7, 3)),
    (lambda: kl.MaxPooling3D(), (4, 6, 8, 3)),
    (lambda: kl.AveragePooling3D((2, 2, 2), (1, 2, 2)), (4, 6, 8, 3)),
    (lambda: kl.GlobalAveragePooling3D(), (4, 6, 8, 3)),
    (lambda: kl.GlobalMaxPooling3D(), (4, 6, 8, 3)),
    (lambda: kl.AtrousConvolution2D(4, 3, 3, atrous_rate=(2, 2)),
     (9, 9, 2)),
    (lambda: kl.AtrousConvolution1D(4, 3, atrous_rate=2), (10, 5)),
    (lambda: kl.SeparableConvolution2D(6, 3, 3, depth_multiplier=2),
     (8, 8, 3)),
    (lambda: kl.Deconvolution2D(4, 3, 3, subsample=(2, 2)), (5, 5, 3)),
    (lambda: kl.LocallyConnected1D(4, 3), (8, 5)),
    (lambda: kl.LocallyConnected2D(4, 3, 3), (6, 7, 2)),
    (lambda: kl.Cropping1D((1, 2)), (8, 3)),
    (lambda: kl.Cropping2D(((1, 1), (2, 0))), (6, 8, 3)),
    (lambda: kl.Cropping3D(), (6, 6, 6, 2)),
    (lambda: kl.ZeroPadding1D(2), (5, 3)),
    (lambda: kl.ZeroPadding3D((1, 2, 3)), (4, 4, 4, 2)),
    (lambda: kl.UpSampling1D(3), (4, 2)),
    (lambda: kl.UpSampling3D((2, 1, 2)), (3, 4, 5, 2)),
    (lambda: kl.MaxoutDense(6, nb_feature=3), (10,)),
    (lambda: kl.SReLU(), (7,)),
    (lambda: kl.SoftMax(), (9,)),
    (lambda: kl.TimeDistributed(kl.Dense(4)), (5, 7)),
    (lambda: kl.ConvLSTM2D(4, 3), (3, 6, 6, 2)),
    (lambda: kl.ConvLSTM2D(4, 3, return_sequences=True), (3, 6, 6, 2)),
])
def test_keras_wrapper_shape_contract(layer_fn, in_shape):
    """Every wrapper's inferred output shape must match the shape the
    built module actually produces (batch excluded), and the forward
    must be finite."""
    from bigdl_tpu.core.module import forward_context
    layer = layer_fn()
    out_shape = layer.build(in_shape)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(2,) + tuple(in_shape)).astype(np.float32))
    with forward_context(rng=jax.random.key(0)):
        y = layer.forward(x)
    assert tuple(y.shape) == (2,) + tuple(out_shape), \
        f"inferred {out_shape}, got {y.shape[1:]}"
    assert bool(jnp.all(jnp.isfinite(y)))


def test_keras_bias_flag_respected():
    """bias=False must remove the bias parameter, not silently keep it
    (regression: atrous/locally-connected wrappers ignored the flag)."""
    for layer, shape in [
        (kl.AtrousConvolution2D(4, 3, 3, bias=False), (9, 9, 2)),
        (kl.AtrousConvolution1D(4, 3, bias=False), (10, 5)),
        (kl.LocallyConnected1D(4, 3, bias=False), (8, 5)),
        (kl.Deconvolution2D(4, 3, 3, bias=False), (5, 5, 3)),
        (kl.SeparableConvolution2D(6, 3, 3, bias=False), (8, 8, 3)),
    ]:
        layer.build(shape)
        from bigdl_tpu.core.module import partition
        paths = [jax.tree_util.keystr(kp) for kp, _ in
                 jax.tree_util.tree_leaves_with_path(partition(layer)[0])]
        assert not any("bias" in p_ for p_ in paths), \
            f"{type(layer).__name__}: {paths}"


def test_keras_time_distributed_single_registration():
    """TimeDistributed must register the wrapped layer once (regression:
    it appeared as both self.layer and inside nn.TimeDistributed,
    duplicating every parameter leaf)."""
    layer = kl.TimeDistributed(kl.Dense(4))
    layer.build((5, 7))
    from bigdl_tpu.core.module import partition
    leaves = jax.tree_util.tree_leaves(partition(layer)[0])
    assert len(leaves) == 2, len(leaves)  # weight + bias only
    assert layer.n_parameters() == 7 * 4 + 4


# ---- recurrent weight import (VERDICT r03 #9) -----------------------------
# Keras-1.2.2 per-gate arrays -> fused cells, same positional semantics
# as the reference's convert_lstm/convert_gru/convert_simplernn
# (pyspark/bigdl/keras/converter.py:218-241).

def _load_rnn(tmp_path, cls_name, cfg_extra, weights):
    from bigdl_tpu.keras import load_keras_hdf5_weights, load_keras_json
    spec = {"class_name": "Sequential", "config": [
        {"class_name": cls_name, "config": dict({
            "name": "rnn1", "return_sequences": True}, **cfg_extra)},
    ]}
    model = load_keras_json(spec)
    hp = str(tmp_path / "w.h5")
    _h5_weights(hp, {"rnn1": weights})
    load_keras_hdf5_weights(model, hp)
    return model.eval_mode()


def test_keras_lstm_weight_import_matches_torch(tmp_path):
    """Oracle: torch LSTM == keras-1.2.2 LSTM equations.  Torch packs
    (i,f,g,o); keras 1.2.2 lists per-gate groups (i,c,f,o)."""
    tor = pytest.importorskip("torch")
    T, F, H = 5, 3, 4
    rng = np.random.RandomState(7)
    tl = tor.nn.LSTM(F, H, batch_first=True)
    w_ih = tl.weight_ih_l0.detach().numpy()   # [4H, F] (i,f,g,o)
    w_hh = tl.weight_hh_l0.detach().numpy()
    b = (tl.bias_ih_l0 + tl.bias_hh_l0).detach().numpy()
    gi, gf, gg, go = [slice(k * H, (k + 1) * H) for k in range(4)]
    weights = [w_ih[gi].T, w_hh[gi].T, b[gi],     # i
               w_ih[gg].T, w_hh[gg].T, b[gg],     # c (torch "g")
               w_ih[gf].T, w_hh[gf].T, b[gf],     # f
               w_ih[go].T, w_hh[go].T, b[go]]     # o
    # torch gates are plain sigmoid; keras-1.x DEFAULT is hard_sigmoid,
    # so the config must say sigmoid explicitly for this oracle
    model = _load_rnn(tmp_path, "LSTM",
                      {"output_dim": H, "activation": "tanh",
                       "inner_activation": "sigmoid",
                       "batch_input_shape": [None, T, F]}, weights)
    x = rng.randn(2, T, F).astype(np.float32)
    got = np.asarray(model.forward(jnp.asarray(x)))
    want, _ = tl(tor.tensor(x))
    np.testing.assert_allclose(got, want.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_gru_weight_import_matches_keras_equations(tmp_path):
    """Torch GRU applies r AFTER the U_h matmul, keras 1.2.2 before —
    so the oracle is the keras equations in numpy (z,r,h groups)."""
    T, F, H = 4, 3, 5
    rng = np.random.RandomState(8)
    wz, wr, wh = (rng.randn(F, H).astype(np.float32) for _ in range(3))
    uz, ur, uh = (rng.randn(H, H).astype(np.float32) for _ in range(3))
    bz, br, bh = (rng.randn(H).astype(np.float32) * 0.1 for _ in range(3))
    # keras-1.x default gates are HARD sigmoid: clip(0.2x + 0.5, 0, 1)
    model = _load_rnn(tmp_path, "GRU",
                      {"output_dim": H,
                       "batch_input_shape": [None, T, F]},
                      [wz, uz, bz, wr, ur, br, wh, uh, bh])
    x = rng.randn(2, T, F).astype(np.float32)
    got = np.asarray(model.forward(jnp.asarray(x)))

    def hard_sig(v):
        return np.clip(0.2 * v + 0.5, 0.0, 1.0)

    h = np.zeros((2, H), np.float32)
    want = []
    for t in range(T):
        xt = x[:, t]
        z = hard_sig(xt @ wz + h @ uz + bz)
        r = hard_sig(xt @ wr + h @ ur + br)
        hh = np.tanh(xt @ wh + (r * h) @ uh + bh)
        h = z * h + (1 - z) * hh
        want.append(h)
    np.testing.assert_allclose(got, np.stack(want, axis=1),
                               rtol=1e-4, atol=1e-5)


def test_keras_lstm_default_hard_sigmoid_differs_from_sigmoid(tmp_path):
    """A default-config keras LSTM must import with hard_sigmoid gates
    (regression: the converter used to drop inner_activation and the
    model silently computed sigmoid gates)."""
    T, F, H = 4, 3, 4
    rng = np.random.RandomState(11)
    ws = [rng.randn(*s).astype(np.float32) for s in
          [(F, H), (H, H), (H,)] * 4]
    m_default = _load_rnn(tmp_path, "LSTM",
                          {"output_dim": H,
                           "batch_input_shape": [None, T, F]}, ws)
    m_sigmoid = _load_rnn(tmp_path, "LSTM",
                          {"output_dim": H, "inner_activation": "sigmoid",
                           "batch_input_shape": [None, T, F]}, ws)
    x = rng.randn(2, T, F).astype(np.float32) * 2
    out_d = np.asarray(m_default.forward(jnp.asarray(x)))
    out_s = np.asarray(m_sigmoid.forward(jnp.asarray(x)))
    assert not np.allclose(out_d, out_s, atol=1e-4)


def test_keras_simplernn_go_backwards(tmp_path):
    """go_backwards prepends Reverse on the time axis (reference
    __process_recurrent_layer:885-895)."""
    T, F, H = 4, 3, 5
    rng = np.random.RandomState(12)
    w = rng.randn(F, H).astype(np.float32)
    u = rng.randn(H, H).astype(np.float32)
    b = np.zeros(H, np.float32)
    model = _load_rnn(tmp_path, "SimpleRNN",
                      {"output_dim": H, "go_backwards": True,
                       "batch_input_shape": [None, T, F]}, [w, u, b])
    x = rng.randn(2, T, F).astype(np.float32)
    got = np.asarray(model.forward(jnp.asarray(x)))
    h = np.zeros((2, H), np.float32)
    want = []
    for t in reversed(range(T)):
        h = np.tanh(x[:, t] @ w + h @ u + b)
        want.append(h)
    np.testing.assert_allclose(got, np.stack(want, axis=1),
                               rtol=1e-4, atol=1e-5)


def test_keras_simplernn_weight_import(tmp_path):
    T, F, H = 4, 3, 5
    rng = np.random.RandomState(9)
    w = rng.randn(F, H).astype(np.float32)
    u = rng.randn(H, H).astype(np.float32)
    b = rng.randn(H).astype(np.float32) * 0.1
    model = _load_rnn(tmp_path, "SimpleRNN",
                      {"output_dim": H,
                       "batch_input_shape": [None, T, F]}, [w, u, b])
    x = rng.randn(2, T, F).astype(np.float32)
    got = np.asarray(model.forward(jnp.asarray(x)))
    h = np.zeros((2, H), np.float32)
    want = []
    for t in range(T):
        h = np.tanh(x[:, t] @ w + h @ u + b)
        want.append(h)
    np.testing.assert_allclose(got, np.stack(want, axis=1),
                               rtol=1e-4, atol=1e-5)


def test_keras_recurrent_linear_activation_is_identity(tmp_path):
    """activation='linear' must import as identity, not silently fall
    back to the cell's tanh default."""
    T, F, H = 3, 2, 4
    rng = np.random.RandomState(13)
    w = rng.randn(F, H).astype(np.float32)
    u = rng.randn(H, H).astype(np.float32) * 0.1
    b = np.zeros(H, np.float32)
    model = _load_rnn(tmp_path, "SimpleRNN",
                      {"output_dim": H, "activation": "linear",
                       "batch_input_shape": [None, T, F]}, [w, u, b])
    x = rng.randn(2, T, F).astype(np.float32)
    got = np.asarray(model.forward(jnp.asarray(x)))
    h = np.zeros((2, H), np.float32)
    want = []
    for t in range(T):
        h = x[:, t] @ w + h @ u + b    # identity activation
        want.append(h)
    np.testing.assert_allclose(got, np.stack(want, axis=1),
                               rtol=1e-4, atol=1e-5)


def test_keras_recurrent_dropout_flags(tmp_path):
    """dropout_W maps to the cell's input dropout; dropout_U (recurrent
    state dropout) is rejected loudly, not silently dropped."""
    from bigdl_tpu.keras import load_keras_json
    spec_u = {"class_name": "Sequential", "config": [
        {"class_name": "LSTM", "config": {
            "name": "l", "output_dim": 4, "dropout_U": 0.3,
            "batch_input_shape": [None, 3, 2]}}]}
    with pytest.raises(ValueError, match="dropout_U"):
        load_keras_json(spec_u)
    spec_w = {"class_name": "Sequential", "config": [
        {"class_name": "LSTM", "config": {
            "name": "l", "output_dim": 4, "dropout_W": 0.25,
            "batch_input_shape": [None, 3, 2]}}]}
    model = load_keras_json(spec_w)
    model.build((3, 2))
    from bigdl_tpu.keras.converter import _rnn_cell
    layer = model.layers[0] if hasattr(model, "layers") else model
    assert _rnn_cell(layer).p == 0.25


def test_keras_bidirectional_lstm_import_matches_torch(tmp_path):
    """Bidirectional LSTM: forward weights then backward weights
    (reference convert_bidirectional midpoint split); oracle = torch
    nn.LSTM(bidirectional=True), whose output is [fwd, bwd-aligned]
    concat — the same semantics as BiRecurrent."""
    tor = pytest.importorskip("torch")
    from bigdl_tpu.keras import load_keras_hdf5_weights, load_keras_json
    T, F, H = 5, 3, 4
    tl = tor.nn.LSTM(F, H, batch_first=True, bidirectional=True)

    def keras_half(sfx):
        w_ih = getattr(tl, f"weight_ih_l0{sfx}").detach().numpy()
        w_hh = getattr(tl, f"weight_hh_l0{sfx}").detach().numpy()
        b = (getattr(tl, f"bias_ih_l0{sfx}")
             + getattr(tl, f"bias_hh_l0{sfx}")).detach().numpy()
        gi, gf, gg, go = [slice(k * H, (k + 1) * H) for k in range(4)]
        return [w_ih[gi].T, w_hh[gi].T, b[gi],
                w_ih[gg].T, w_hh[gg].T, b[gg],
                w_ih[gf].T, w_hh[gf].T, b[gf],
                w_ih[go].T, w_hh[go].T, b[go]]

    weights = keras_half("") + keras_half("_reverse")
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "Bidirectional", "config": {
            "name": "bi", "merge_mode": "concat",
            "batch_input_shape": [None, T, F],
            "layer": {"class_name": "LSTM", "config": {
                "name": "inner", "output_dim": H,
                "return_sequences": True, "activation": "tanh",
                "inner_activation": "sigmoid"}}}},
    ]}
    model = load_keras_json(spec)
    hp = str(tmp_path / "w.h5")
    _h5_weights(hp, {"bi": weights})
    load_keras_hdf5_weights(model, hp)

    rng = np.random.RandomState(21)
    x = rng.randn(2, T, F).astype(np.float32)
    got = np.asarray(model.eval_mode().forward(jnp.asarray(x)))
    want, _ = tl(tor.tensor(x))
    np.testing.assert_allclose(got, want.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
