import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Module, Parameter, partition, combine, forward_context
from bigdl_tpu.core import init
from bigdl_tpu.utils import set_seed, next_key


class Affine(Module):
    def __init__(self, fin, fout):
        super().__init__()
        self.weight = Parameter(init.Xavier(next_key(), (fout, fin)))
        self.bias = Parameter(jnp.zeros(fout))
        self.calls = jnp.zeros(())

    def forward(self, x):
        self.calls = self.calls + 1
        return x @ self.weight.T + self.bias


class MLP(Module):
    def __init__(self):
        super().__init__()
        self.a = Affine(4, 8)
        self.b = Affine(8, 2)

    def forward(self, x):
        return self.b(jax.nn.relu(self.a(x)))


def test_pytree_roundtrip():
    m = MLP()
    leaves, treedef = jax.tree_util.tree_flatten(m)
    m2 = jax.tree_util.tree_unflatten(treedef, leaves)
    x = jnp.ones((3, 4))
    np.testing.assert_allclose(m.forward(x), m2.forward(x))


def test_partition_grad_and_buffer_update():
    m = MLP()
    x = jnp.ones((3, 4))
    params, rest = partition(m)

    def loss_fn(p):
        mm = combine(p, rest)
        return jnp.sum(mm.forward(x) ** 2), mm

    (loss, m2), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    assert float(m2.a.calls) == 1.0
    n_param_grads = len(jax.tree_util.tree_leaves(grads))
    assert n_param_grads == 4


def test_jit_model_as_arg_is_functional():
    m = MLP()
    x = jnp.ones((3, 4))

    @jax.jit
    def step(model, x):
        y = model.forward(x)
        return y, model

    _, m1 = step(m, x)
    _, m2 = step(m1, x)
    assert float(m.a.calls) == 0.0  # original untouched
    assert float(m2.a.calls) == 2.0


def test_freeze_excludes_params():
    m = MLP()
    m.a.freeze()
    params, _ = partition(m)
    assert len(jax.tree_util.tree_leaves(params)) == 2
    m.unfreeze()
    params, _ = partition(m)
    assert len(jax.tree_util.tree_leaves(params)) == 4


def test_get_parameters_flat_view():
    m = MLP()
    flat, unravel = m.get_parameters()
    assert flat.shape == (4 * 8 + 8 + 8 * 2 + 2,)
    tree = unravel(flat)
    assert "a" in tree and "weight" in tree["a"]


def test_train_eval_mode_recursive():
    m = MLP()
    m.eval_mode()
    assert not m.a.training and not m.b.training
    m.train_mode()
    assert m.a.training


def test_init_methods_reproducible():
    set_seed(7)
    k = next_key()
    a = init.Xavier(k, (16, 16))
    b = init.Xavier(k, (16, 16))
    np.testing.assert_allclose(a, b)
    z = init.Zeros(k, (3,))
    assert float(jnp.sum(jnp.abs(z))) == 0.0
    # non-average MSRA uses fan_out (reference InitializationMethod.scala:322)
    msra = init.MsraFiller(False)(k, (64, 32, 3, 3))
    assert abs(float(jnp.std(msra)) - (2.0 / (64 * 9)) ** 0.5) < 0.01


def test_forward_context_rng():
    from bigdl_tpu.core.module import next_rng_key, has_rng
    assert not has_rng()
    with forward_context(rng=jax.random.key(0)):
        assert has_rng()
        k1 = next_rng_key()
        k2 = next_rng_key()
        assert not np.array_equal(jax.random.key_data(k1),
                                  jax.random.key_data(k2))
    assert not has_rng()
    with pytest.raises(RuntimeError):
        next_rng_key()


def test_buffer_reassignment_keeps_pytree_structure():
    """Same-kind attribute re-assignment must update in place: dict
    order is pytree STRUCTURE, so if different forward paths assign
    buffers in different orders the module's treedef would flip between
    jit traces (observed with MoE.aux_loss/drop_rate)."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.core.module import Module

    class M(Module):
        def __init__(self):
            super().__init__()
            self.a = jnp.zeros(())
            self.b = jnp.zeros(())

        def forward(self, x, path=0):
            if path:
                self.b = jnp.sum(x)
                self.a = jnp.sum(x) * 2
            else:
                self.a = jnp.sum(x)
            return x

    m = M()
    t0 = jax.tree_util.tree_structure(m)
    m.forward(jnp.ones(3), path=0)
    assert jax.tree_util.tree_structure(m) == t0
    m.forward(jnp.ones(3), path=1)
    assert jax.tree_util.tree_structure(m) == t0
