"""Control-flow modules (≙ nn/Scheduler + nn/FrameManager + nn/tf
ControlOps/DataFlowOps, redesigned as lax.cond/while/scan) and the TF
Switch/Merge cond-pattern import."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module
from bigdl_tpu.ops import Cond, Scan, TensorArrayScan, WhileLoop
from bigdl_tpu.utils import set_seed


class _Lam(Module):
    def __init__(self, fn):
        super().__init__()
        self.fn = fn

    def forward(self, x):
        return self.fn(x)


def test_cond_branches():
    set_seed(0)
    c = Cond(_Lam(lambda x: x * 2.0), _Lam(lambda x: -x))
    x = jnp.asarray([1.0, 2.0])
    np.testing.assert_allclose(np.asarray(c((True, x))), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(c((False, x))), [-1.0, -2.0])
    # under jit with a traced predicate
    f = jax.jit(lambda p, v: c((p, v)))
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(True), x)),
                               [2.0, 4.0])


def test_cond_with_parameterized_branches_grads():
    set_seed(1)
    from bigdl_tpu.core.module import combine, partition
    c = Cond(nn.Linear(4, 4), nn.Identity())
    x = jnp.ones((2, 4))
    params, rest = partition(c)

    def loss(p, pred):
        return jnp.sum(combine(p, rest)((pred, x)) ** 2)

    g_true = jax.grad(loss)(params, jnp.asarray(True))
    leaves = jax.tree_util.tree_leaves(g_true)
    assert any(float(jnp.abs(l).max()) > 0 for l in leaves)


def test_while_loop_and_guard():
    body = _Lam(lambda s: s + 1.0)
    w = WhileLoop(lambda s: s < 10.0, body)
    assert float(w(jnp.asarray(0.0))) == 10.0
    w2 = WhileLoop(lambda s: s < 10.0, body, max_iterations=3)
    assert float(w2(jnp.asarray(0.0))) == 3.0


def test_scan_carries_state():
    class Acc(Module):
        def forward(self, inputs):
            state, x = inputs
            s2 = state + x
            return s2, s2

    s = Scan(Acc(), time_axis=1)
    xs = jnp.asarray(np.ones((2, 5, 3), np.float32))
    final, ys = s((jnp.zeros((2, 3)), xs))
    np.testing.assert_allclose(np.asarray(final), np.full((2, 3), 5.0))
    np.testing.assert_allclose(np.asarray(ys)[:, -1], np.full((2, 3), 5.0))
    np.testing.assert_allclose(np.asarray(ys)[:, 0], np.ones((2, 3)))


def test_tensor_array_scan():
    t = TensorArrayScan(_Lam(lambda x: x * 2.0), time_axis=1)
    xs = jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 3, 2))
    out = np.asarray(t(xs))
    np.testing.assert_allclose(out, np.asarray(xs) * 2.0)


def test_tf_switch_merge_cond_import():
    from tests.test_tensorflow_interop import (
        attr, const_node, graphdef, node,
    )
    from bigdl_tpu.interop.tensorflow import load_tf_graph
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("zero", np.asarray([0.0], np.float32)),
        node("s", "Sum", ["x", "axes"]),
        const_node("axes", np.asarray([0], np.int32)),
        node("pred", "Greater", ["s", "zero"]),
        node("sw", "Switch", ["x", "pred"]),
        const_node("two", np.asarray(2.0, np.float32)),
        node("tbr", "Mul", ["sw:1", "two"]),
        node("fbr", "Neg", ["sw"]),
        node("out", "Merge", ["fbr", "tbr"]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["out"])
    x_pos = jnp.asarray([1.0, 2.0])
    x_neg = jnp.asarray([-1.0, -2.0])
    np.testing.assert_allclose(np.asarray(model(x_pos)), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(model(x_neg)), [1.0, 2.0])


def test_tf_merge_rejects_loop_pattern():
    from tests.test_tensorflow_interop import graphdef, node
    from bigdl_tpu.interop.tensorflow import load_tf_graph
    gd = graphdef(
        node("x", "Placeholder"),
        node("a", "Neg", ["x"]),
        node("b", "Neg", ["x"]),
        node("out", "Merge", ["a", "b"]),
    )
    with pytest.raises(ValueError, match="Switch/Merge"):
        load_tf_graph(gd, ["x"], ["out"])


def test_tf_while_loop_import():
    """A canonical TF-v1 while frame (Enter/Merge/LoopCond/Switch/
    NextIteration/Exit, with a loop-invariant Enter) imports as one
    lax.while_loop; each Exit selects its carry variable."""
    from tests.test_tensorflow_interop import attr, const_node, graphdef, \
        node
    from bigdl_tpu.interop.protowire import BYTES
    from bigdl_tpu.interop.tensorflow import load_tf_graph
    fr = [attr("frame_name", [(2, BYTES, b"loop")])]
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("i0", np.asarray(0.0, np.float32)),
        const_node("lim", np.asarray(5.0, np.float32)),
        const_node("one", np.asarray(1.0, np.float32)),
        const_node("two", np.asarray(2.0, np.float32)),
        node("i_enter", "Enter", ["i0"], fr),
        node("a_enter", "Enter", ["x"], fr),
        node("lim_enter", "Enter", ["lim"], fr),  # invariant: no Merge
        node("i_merge", "Merge", ["i_enter", "i_next"]),
        node("a_merge", "Merge", ["a_enter", "a_next"]),
        node("pred", "Less", ["i_merge", "lim_enter"]),
        node("lc", "LoopCond", ["pred"]),
        node("i_sw", "Switch", ["i_merge", "lc"]),
        node("a_sw", "Switch", ["a_merge", "lc"]),
        node("i_body", "Add", ["i_sw:1", "one"]),
        node("a_body", "Mul", ["a_sw:1", "two"]),
        node("i_next", "NextIteration", ["i_body"]),
        node("a_next", "NextIteration", ["a_body"]),
        node("i_exit", "Exit", ["i_sw"]),
        node("a_exit", "Exit", ["a_sw"]),
    )
    model, layer_map = load_tf_graph(gd, ["x"], ["a_exit", "i_exit"])
    a, i = model(jnp.asarray([1.5, -2.0]))
    np.testing.assert_allclose(np.asarray(a), [1.5 * 32, -2.0 * 32])
    np.testing.assert_allclose(np.asarray(i), 5.0)
    assert "while:loop" in layer_map
    # the imported loop must also be jittable end-to-end
    import jax
    out = jax.jit(lambda m, x: m.forward(x)[0])(model, jnp.asarray([2.0]))
    np.testing.assert_allclose(np.asarray(out), [64.0])


def test_tf_while_subgraph_build_does_not_override_outer_fusion():
    """Regression: the re-entrant cond/body _build_graph used to re-run
    the BiasAdd-fusion pre-pass on the SHARED node dict, marking a
    MatMul+BiasAdd pair as fused even though the outer graph observes
    the pre-bias MatMul output."""
    from tests.test_tensorflow_interop import attr, const_node, graphdef, \
        node
    from bigdl_tpu.interop.protowire import BYTES
    from bigdl_tpu.interop.tensorflow import load_tf_graph
    fr = [attr("frame_name", [(2, BYTES, b"f2")])]
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("w", np.eye(2, dtype=np.float32)),
        const_node("bias", np.asarray([10.0, 10.0], np.float32)),
        node("mm", "MatMul", ["x", "w"]),
        node("ba", "BiasAdd", ["mm", "bias"]),
        const_node("i0", np.asarray(0.0, np.float32)),
        const_node("lim", np.asarray(3.0, np.float32)),
        const_node("one", np.asarray(1.0, np.float32)),
        node("i_enter", "Enter", ["i0"], fr),
        node("i_merge", "Merge", ["i_enter", "i_next"]),
        node("pred", "Less", ["i_merge", "lim"]),
        node("lc", "LoopCond", ["pred"]),
        node("i_sw", "Switch", ["i_merge", "lc"]),
        node("i_body", "Add", ["i_sw:1", "one"]),
        node("i_next", "NextIteration", ["i_body"]),
        node("i_exit", "Exit", ["i_sw"]),
    )
    # outer outputs observe BOTH mm (pre-bias) and ba (post-bias):
    # the outer pre-pass must keep them distinct even after the loop's
    # subgraph builds run their own pre-pass
    model, _ = load_tf_graph(gd, ["x"], ["i_exit", "mm", "ba"])
    i, mm, ba = model(jnp.asarray([[1.0, 2.0]]))
    np.testing.assert_allclose(np.asarray(i), 3.0)
    np.testing.assert_allclose(np.asarray(mm), [[1.0, 2.0]])
    np.testing.assert_allclose(np.asarray(ba), [[11.0, 12.0]])


def test_tf_while_variable_with_two_exits():
    """One Switch legally feeding two Exit nodes: both must resolve to
    the same carry variable (used to KeyError on the second)."""
    from tests.test_tensorflow_interop import attr, const_node, graphdef, \
        node
    from bigdl_tpu.interop.protowire import BYTES
    from bigdl_tpu.interop.tensorflow import load_tf_graph
    fr = [attr("frame_name", [(2, BYTES, b"f3")])]
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("lim", np.asarray(4.0, np.float32)),
        const_node("one", np.asarray(1.0, np.float32)),
        node("i_enter", "Enter", ["x"], fr),
        node("i_merge", "Merge", ["i_enter", "i_next"]),
        node("pred", "Less", ["i_merge", "lim"]),
        node("lc", "LoopCond", ["pred"]),
        node("i_sw", "Switch", ["i_merge", "lc"]),
        node("i_body", "Add", ["i_sw:1", "one"]),
        node("i_next", "NextIteration", ["i_body"]),
        node("exit_a", "Exit", ["i_sw"]),
        node("exit_b", "Exit", ["i_sw"]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["exit_a", "exit_b"])
    a, b = model(jnp.asarray(0.0))
    np.testing.assert_allclose(np.asarray(a), 4.0)
    np.testing.assert_allclose(np.asarray(b), 4.0)
