"""Training-runtime tests: optim methods vs torch oracle, triggers,
validation methods, Optimizer e2e on the 8-device mesh, checkpoint/resume,
and the single-vs-multi-device equivalence oracle (≙ the reference's
RefDistriOptimizer equivalence specs, optim/RefDistriOptimizer.scala)."""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

import bigdl_tpu.nn as nn
from bigdl_tpu.optim import (
    Optimizer, SGD, Adam, Adagrad, RMSprop, Adadelta, Adamax, LarsSGD,
    Ftrl, LBFGS, Trigger, Top1Accuracy, Top5Accuracy, Loss, MAE,
    Step, MultiStep, Poly, Warmup, SequentialSchedule, Plateau,
)
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import Sample
from bigdl_tpu.dataset.image import synthetic_mnist, GreyImgNormalizer
from bigdl_tpu.parallel import MeshConfig
from bigdl_tpu.utils import set_seed


def quad_params():
    return {"w": jnp.asarray([1.0, -2.0, 3.0])}


def quad_grad(p):
    return {"w": 2.0 * p["w"]}  # grad of sum(w^2)


@pytest.mark.parametrize("method,torch_ctor", [
    (SGD(0.1), lambda p: torch.optim.SGD(p, lr=0.1)),
    # note: reference SGD defaults dampening=momentum (SGD.scala), torch
    # defaults dampening=0 — align explicitly for the oracle
    (SGD(0.1, momentum=0.9, dampening=0.0),
     lambda p: torch.optim.SGD(p, 0.1, momentum=0.9)),
    (SGD(0.1, momentum=0.9, dampening=0.0, nesterov=True),
     lambda p: torch.optim.SGD(p, 0.1, momentum=0.9, nesterov=True)),
    (SGD(0.1, weight_decay=0.01),
     lambda p: torch.optim.SGD(p, 0.1, weight_decay=0.01)),
    (Adam(0.01), lambda p: torch.optim.Adam(p, 0.01)),
    (Adagrad(0.05), lambda p: torch.optim.Adagrad(p, 0.05, eps=1e-10)),
    (RMSprop(0.01, decay_rate=0.9),
     lambda p: torch.optim.RMSprop(p, 0.01, alpha=0.9)),
    (Adadelta(0.9, 1e-6),
     lambda p: torch.optim.Adadelta(p, lr=1.0, rho=0.9, eps=1e-6)),
])
def test_optim_methods_match_torch(method, torch_ctor):
    params = quad_params()
    state = method.init_state(params)
    tw = torch.tensor(np.asarray(params["w"]), requires_grad=True)
    topt = torch_ctor([tw])
    for _ in range(5):
        grads = quad_grad(params)
        params, state = method.update(grads, params, state)
        topt.zero_grad()
        (tw ** 2).sum().backward()
        topt.step()
    np.testing.assert_allclose(np.asarray(params["w"]),
                               tw.detach().numpy(), rtol=1e-4, atol=1e-5)


def test_adamax_converges():
    method = Adamax(0.05)
    params = quad_params()
    state = method.init_state(params)
    for _ in range(200):
        params, state = method.update(quad_grad(params), params, state)
    assert float(jnp.sum(params["w"] ** 2)) < 1e-2


def test_ftrl_and_lars_and_lbfgs_decrease_loss():
    for method in [Ftrl(0.5), LarsSGD(0.1, trust_coefficient=0.02),
                   LBFGS(learning_rate=0.2)]:
        params = quad_params()
        state = method.init_state(params)
        start = float(jnp.sum(params["w"] ** 2))
        for _ in range(30):
            params, state = method.update(quad_grad(params), params, state)
        end = float(jnp.sum(params["w"] ** 2))
        assert end < start, f"{type(method).__name__} did not descend"


def test_lr_schedules():
    s = Step(10, 0.5)
    assert float(s(1.0, 0, 0)) == 1.0
    assert float(s(1.0, 10, 0)) == 0.5
    assert float(s(1.0, 25, 0)) == 0.25
    ms = MultiStep([5, 15], 0.1)
    assert float(ms(1.0, 4, 0)) == pytest.approx(1.0)
    assert float(ms(1.0, 5, 0)) == pytest.approx(0.1)
    assert float(ms(1.0, 20, 0)) == pytest.approx(0.01)
    p = Poly(2.0, 100)
    assert float(p(1.0, 0, 0)) == pytest.approx(1.0)
    assert float(p(1.0, 50, 0)) == pytest.approx(0.25)
    assert float(p(1.0, 100, 0)) == pytest.approx(0.0)
    seq = SequentialSchedule().add(Warmup(0.1), 10).add(Poly(1.0, 100), 100)
    assert float(seq(1.0, 5, 0)) == pytest.approx(1.5)


def test_plateau_schedule():
    pl = Plateau(factor=0.5, patience=2, mode="min")
    for v in [1.0, 0.9, 0.95, 0.95, 0.95]:
        pl.on_metric(v)
    assert pl.current_factor == pytest.approx(0.5)


def test_triggers():
    assert Trigger.max_epoch(3)({"epoch": 4})
    assert not Trigger.max_epoch(3)({"epoch": 3})
    assert Trigger.several_iteration(5)({"neval": 10})
    assert Trigger.every_epoch()({"is_epoch_end": True})
    assert Trigger.and_(Trigger.max_epoch(1), Trigger.min_loss(1.0))(
        {"epoch": 2, "loss": 0.5})
    assert Trigger.or_(Trigger.max_epoch(9), Trigger.min_loss(1.0))(
        {"epoch": 2, "loss": 0.5})


def test_validation_methods():
    out = jnp.asarray([[0.1, 0.7, 0.2], [0.8, 0.1, 0.1], [0.2, 0.3, 0.5]])
    target = jnp.asarray([2, 1, 1])  # 1-based
    top1 = Top1Accuracy()(out, target)
    v, n = top1.result()
    assert n == 3 and v == pytest.approx(2.0 / 3)
    merged = top1 + Top1Accuracy()(out, jnp.asarray([2, 1, 3]))
    v2, n2 = merged.result()
    assert n2 == 6 and v2 == pytest.approx((2 + 3) / 6)
    mae = MAE()(jnp.ones((2, 3)), jnp.zeros((2, 3)))
    assert mae.result()[0] == pytest.approx(1.0)
    # Top5 on tiny output
    t5 = Top5Accuracy()(jnp.asarray(np.random.randn(4, 6)), jnp.asarray([1, 2, 3, 4]))
    assert t5.result()[1] == 4


def _mnist_pipeline(n=512, batch=64, seed=0):
    return DataSet.array(synthetic_mnist(n, seed=seed)) \
        .transform(GreyImgNormalizer(128.0, 128.0)) \
        .transform(SampleToMiniBatch(batch))


def _mlp():
    return nn.Sequential(
        nn.Flatten(), nn.Linear(784, 32), nn.Tanh(),
        nn.Linear(32, 10), nn.LogSoftMax())


def test_optimizer_e2e_learns():
    set_seed(5)
    model = _mlp()
    opt = (Optimizer(model, _mnist_pipeline(), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(3))
           .set_validation(Trigger.every_epoch(),
                           _mnist_pipeline(256, seed=7), [Top1Accuracy()]))
    opt.optimize()
    assert opt.state["score"] > 0.9


def test_optimizer_mesh_size_invariance():
    """Training on a 1-device mesh and an 8-device data-parallel mesh
    must produce the same weights (SPMD correctness oracle)."""
    losses = {}
    weights = {}
    for ndev in [1, 8]:
        set_seed(11)
        model = _mlp()
        opt = (Optimizer(model, _mnist_pipeline(256, 64),
                         nn.ClassNLLCriterion())
               .set_optim_method(SGD(0.1))
               .set_end_when(Trigger.max_iteration(6)))
        opt.set_mesh(MeshConfig(data=ndev)) if ndev > 1 else None
        if ndev == 1:
            opt.mesh_config = MeshConfig(data=1)
        opt.optimize()
        losses[ndev] = opt.state["loss"]
        weights[ndev], _ = model.get_parameters()
    np.testing.assert_allclose(losses[1], losses[8], rtol=1e-4)
    np.testing.assert_allclose(np.asarray(weights[1]),
                               np.asarray(weights[8]), rtol=1e-3, atol=1e-5)


def test_optimizer_multi_methods_and_clipping():
    set_seed(3)
    model = nn.Sequential(
        nn.Sequential(nn.Flatten(), nn.Linear(784, 32),
                      nn.Tanh()).set_name("features"),
        nn.Sequential(nn.Linear(32, 10), nn.LogSoftMax()).set_name("head"))
    opt = (Optimizer(model, _mnist_pipeline(256, 64), nn.ClassNLLCriterion())
           .set_optim_methods({"features": SGD(0.2), "head": Adam(1e-2)})
           .set_gradient_clipping_by_l2_norm(1.0)
           .set_end_when(Trigger.max_epoch(2)))
    opt.optimize()
    assert opt.state["loss"] < 2.0


def test_optimizer_missing_method_coverage_errors():
    model = nn.Sequential(
        nn.Sequential(nn.Linear(4, 4)).set_name("covered"),
        nn.Linear(4, 2))
    opt = (Optimizer(model, [Sample(np.ones(4, np.float32), 1)],
                     nn.MSECriterion(), batch_size=1)
           .set_optim_methods({"covered": SGD(0.1)}))
    with pytest.raises(ValueError, match="no optim method covers"):
        opt.optimize()


def test_checkpoint_resume_roundtrip(tmp_path):
    set_seed(9)
    model = _mlp()
    data = _mnist_pipeline(256, 64)
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(1))
           .set_checkpoint(str(tmp_path), Trigger.every_epoch()))
    opt.optimize()
    ck = os.path.join(str(tmp_path), "checkpoint.npz")
    assert os.path.exists(ck)
    set_seed(9)
    model2 = _mlp()
    opt2 = (Optimizer(model2, data, nn.ClassNLLCriterion())
            .set_optim_method(Adam(1e-2))
            .set_end_when(Trigger.max_epoch(2))
            .resume(ck))
    opt2.optimize()
    assert opt2.state["epoch"] == 3
    assert opt2.state["loss"] < opt.state["loss"] + 0.2


def test_sharded_checkpoint_resume_roundtrip(tmp_path):
    """set_checkpoint(sharded=True): orbax directory checkpoints of
    fsdp-SHARDED device params (no host gather on the save path — the
    .npz format would np.asarray every leaf, impossible once shards
    live on mutually-unaddressable hosts), resumed transparently by the
    same resume() used for .npz files."""
    from bigdl_tpu.parallel import MeshConfig, ShardingRules

    set_seed(9)
    model = _mlp()
    data = _mnist_pipeline(256, 64)
    cfg = MeshConfig(data=2, fsdp=4)
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(1))
           .set_mesh(cfg, ShardingRules(fsdp=True))
           .set_checkpoint(str(tmp_path), Trigger.every_epoch(),
                           sharded=True))
    opt.optimize()
    ck = os.path.join(str(tmp_path), "checkpoint.orbax")
    assert os.path.isdir(ck)

    # the saved tree matches the trained model exactly
    from bigdl_tpu.utils.file import load_checkpoint
    model_state, saved_opt, driver = load_checkpoint(ck)
    assert driver["epoch"] == 2 and driver["neval"] >= 4
    flat_saved = jax.tree_util.tree_leaves(model_state["params"])
    flat_live = jax.tree_util.tree_leaves(model.parameters())
    for a, b in zip(flat_saved, flat_live):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6)

    set_seed(9)
    model2 = _mlp()
    opt2 = (Optimizer(model2, data, nn.ClassNLLCriterion())
            .set_optim_method(Adam(1e-2))
            .set_end_when(Trigger.max_epoch(2))
            .set_mesh(cfg, ShardingRules(fsdp=True))
            .resume(ck))
    opt2.optimize()
    assert opt2.state["epoch"] == 3
    assert opt2.state["loss"] < opt.state["loss"] + 0.2


@pytest.mark.slow
def test_sharded_checkpoint_multi_group_methods(tmp_path):
    """Per-submodule optim methods (reference setOptimMethods) produce
    GROUP-structured optimizer state; the sharded checkpoint must carry
    that structure through orbax's strict restore."""
    from bigdl_tpu.parallel import MeshConfig, ShardingRules

    def build():
        set_seed(4)
        return nn.Sequential(
            nn.Sequential(nn.Linear(16, 32), nn.ReLU()).set_name("trunk"),
            nn.Sequential(nn.Linear(32, 4), nn.LogSoftMax())
            .set_name("head"))

    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(16,)).astype(np.float32),
                      int(rng.integers(1, 5))) for _ in range(64)]
    data = DataSet.array(samples).transform(SampleToMiniBatch(16))
    methods = lambda: {"trunk": SGD(0.1, momentum=0.9),  # noqa: E731
                       "head": Adam(1e-2)}
    cfg = MeshConfig(data=2, fsdp=4)
    opt = (Optimizer(build(), data, nn.ClassNLLCriterion())
           .set_optim_methods(methods())
           .set_end_when(Trigger.max_epoch(1))
           .set_mesh(cfg, ShardingRules(fsdp=True))
           .set_checkpoint(str(tmp_path), Trigger.every_epoch(),
                           sharded=True))
    opt.optimize()
    opt2 = (Optimizer(build(), data, nn.ClassNLLCriterion())
            .set_optim_methods(methods())
            .set_end_when(Trigger.max_epoch(3))
            .set_mesh(cfg, ShardingRules(fsdp=True))
            .resume(os.path.join(str(tmp_path), "checkpoint.orbax")))
    opt2.optimize()
    assert opt2.state["epoch"] == 4


def test_frozen_submodule_not_updated():
    set_seed(2)
    model = _mlp()
    model.layers[1].freeze()  # first Linear
    before = np.asarray(model.layers[1].weight).copy()
    opt = (Optimizer(model, _mnist_pipeline(128, 64), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.5))
           .set_end_when(Trigger.max_iteration(3)))
    opt.optimize()
    np.testing.assert_array_equal(before, np.asarray(model.layers[1].weight))
    # unfrozen layer did move
    assert not np.allclose(before.sum(),
                           np.asarray(model.layers[3].weight).sum())


def test_resume_restores_bn_buffers(tmp_path):
    import bigdl_tpu.nn as nnm
    set_seed(4)
    model = nn.Sequential(nn.Flatten(), nn.Linear(784, 16),
                          nn.BatchNormalization(16), nn.ReLU(),
                          nn.Linear(16, 10), nn.LogSoftMax())
    opt = (Optimizer(model, _mnist_pipeline(128, 64), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(1))
           .set_checkpoint(str(tmp_path), Trigger.every_epoch()))
    opt.optimize()
    stats = np.asarray(model.layers[2].running_mean).copy()
    assert np.abs(stats).sum() > 0
    set_seed(99)  # different init
    model2 = nn.Sequential(nn.Flatten(), nn.Linear(784, 16),
                           nn.BatchNormalization(16), nn.ReLU(),
                           nn.Linear(16, 10), nn.LogSoftMax())
    opt2 = (Optimizer(model2, _mnist_pipeline(128, 64),
                      nn.ClassNLLCriterion())
            .set_optim_method(SGD(0.1))
            .set_end_when(Trigger.max_epoch(1))  # ends immediately (epoch=2)
            .resume(os.path.join(str(tmp_path), "checkpoint.npz")))
    opt2.optimize()
    np.testing.assert_allclose(np.asarray(model2.layers[2].running_mean),
                               stats, rtol=1e-5)


def test_iteration_trigger_fires_once_at_epoch_boundary(tmp_path, monkeypatch):
    set_seed(6)
    model = _mlp()
    calls = []
    opt = (Optimizer(model, _mnist_pipeline(128, 64), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(1))
           .set_validation(Trigger.several_iteration(2),
                           _mnist_pipeline(64, 64, seed=7),
                           [Top1Accuracy()]))
    orig = opt._validate

    def counting(*a, **k):
        calls.append(opt.state["neval"])
        return orig(*a, **k)

    monkeypatch.setattr(opt, "_validate", counting)
    opt.optimize()
    assert len(calls) == len(set(calls)), f"double-fired at {calls}"


def test_lars_momentum_zero_no_crash():
    m = LarsSGD(0.1, momentum=0.0)
    params = quad_params()
    state = m.init_state(params)
    params, state = m.update(quad_grad(params), params, state)
    assert np.isfinite(np.asarray(params["w"]).sum())


def test_async_log_interval_still_logs_every_iteration(caplog):
    """Loss readback batched every 4 steps must still emit one reference-
    format log line per iteration, with correct per-iteration losses."""
    import logging
    set_seed(5)
    model = _mlp()
    opt = (Optimizer(model, _mnist_pipeline(384, 64), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_log_interval(4)
           .set_end_when(Trigger.max_iteration(6)))
    with caplog.at_level(logging.INFO, logger="bigdl_tpu.optim"):
        opt.optimize()
    lines = [r.getMessage() for r in caplog.records
             if "Loss is" in r.getMessage()]
    assert len(lines) == 6
    its = [int(l.split("Iteration ")[1].split("]")[0]) for l in lines]
    assert its == [1, 2, 3, 4, 5, 6]
    losses = [float(l.rsplit("Loss is ", 1)[1].rstrip(".")) for l in lines]
    assert all(np.isfinite(losses))
    assert abs(opt.state["loss"] - losses[-1]) < 1e-4


def test_min_loss_trigger_forces_per_iteration_loss():
    """A loss-reading end trigger must see a fresh loss every iteration
    (the async window auto-collapses to 1)."""
    set_seed(5)
    model = _mlp()
    opt = (Optimizer(model, _mnist_pipeline(512, 64), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.or_(Trigger.min_loss(1.0),
                                     Trigger.max_epoch(50))))
    opt.optimize()
    assert opt.state["loss"] < 1.0
    assert opt.state["epoch"] < 50  # stopped by loss, not the epoch cap


def test_module_forward_times_and_unpatch():
    from bigdl_tpu.optim import module_forward_times, times_by_module_type
    import jax.numpy as jnp
    set_seed(2)
    model = _mlp().eval_mode()
    x = jnp.ones((2, 28, 28, 1), jnp.float32)
    recs = module_forward_times(model, x)
    names = [t for _, t, _ in recs]
    assert names.count("Linear") == 2 and "Sequential" in names
    assert all(sec >= 0 for _, _, sec in recs)
    by_type = times_by_module_type(recs)
    assert by_type["Linear"][0] == 2
    # patching must be fully undone: forward still works and is the
    # class's own method again
    assert "forward" not in model.__dict__
    out = model.forward(x)
    assert np.isfinite(np.asarray(out)).all()


def test_failure_retry_resumes_from_checkpoint(tmp_path):
    """Driver-level failure retry (≙ DistriOptimizer.scala:901-983):
    an injected mid-epoch failure resumes from the latest checkpoint
    and training completes."""
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import Sample

    class Flaky:
        def __init__(self, inner):
            self.inner = inner
            self.epochs = 0
            self.fired = False

        def data(self, train=True):
            self.epochs += 1
            it = self.inner.data(train)
            if self.epochs == 2 and not self.fired:
                self.fired = True

                def gen():
                    yield next(it)
                    raise RuntimeError("injected preemption")
                return gen()
            return it

        def size(self):
            return self.inner.size()

    set_seed(21)
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(6,)).astype(np.float32),
                      int(rng.integers(1, 5))) for _ in range(32)]
    data = Flaky(DataSet.array(samples).transform(SampleToMiniBatch(16)))
    model = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 4),
                          nn.LogSoftMax())
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(3))
           .set_checkpoint(str(tmp_path), Trigger.every_epoch())
           .set_failure_retry(2, interval_s=300))
    opt.optimize()
    assert data.fired, "failure was never injected"
    assert opt.state["epoch"] >= 4, "training did not complete"


def test_failure_retry_exhausted_reraises(tmp_path):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import Sample

    class AlwaysFails:
        def __init__(self, inner):
            self.inner = inner
            self.epochs = 0

        def data(self, train=True):
            self.epochs += 1
            it = self.inner.data(train)
            if self.epochs >= 2:
                def gen():
                    yield next(it)
                    raise RuntimeError("hard failure")
                return gen()
            return it

        def size(self):
            return self.inner.size()

    set_seed(22)
    rng = np.random.default_rng(1)
    samples = [Sample(rng.normal(size=(6,)).astype(np.float32),
                      int(rng.integers(1, 5))) for _ in range(32)]
    data = AlwaysFails(
        DataSet.array(samples).transform(SampleToMiniBatch(16)))
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(4))
           .set_checkpoint(str(tmp_path), Trigger.every_epoch())
           .set_failure_retry(2, interval_s=300))
    with pytest.raises(RuntimeError, match="hard failure"):
        opt.optimize()


def test_no_retry_without_checkpoint():
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import Sample

    class Fails:
        def __init__(self, inner):
            self.inner = inner

        def data(self, train=True):
            raise RuntimeError("boom")

        def size(self):
            return self.inner.size()

    samples = [Sample(np.zeros(4, np.float32), 1) for _ in range(8)]
    data = Fails(DataSet.array(samples).transform(SampleToMiniBatch(4)))
    opt = (Optimizer(nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax()),
                     data, nn.ClassNLLCriterion())
           .set_failure_retry(3))
    with pytest.raises(RuntimeError, match="boom"):
        opt.optimize()


def test_checkpoint_remote_filesystem():
    """gs://-style remote checkpoints route through fsspec
    (≙ utils/File.scala HDFS/S3 dispatch); exercised on memory://."""
    pytest.importorskip("fsspec")
    from bigdl_tpu.utils.file import load_pytree, save_pytree
    tree = {"w": np.arange(4, dtype=np.float32), "meta": {"epoch": 3}}
    path = "memory://bigdl_tpu_test/ckpt.npz"
    save_pytree(tree, path)
    back = load_pytree(path)
    np.testing.assert_array_equal(back["w"], tree["w"])
    assert back["meta"]["epoch"] == 3


def test_optimizer_multi_input_model():
    """Tuple (Table-activity) minibatch inputs must flow through the
    jitted train step, sharded staging, and validation (regression:
    jnp.asarray(tuple) raised on inhomogeneous shapes)."""
    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.module import Module
    from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
    from bigdl_tpu.optim import Optimizer, Top1Accuracy, Trigger
    from bigdl_tpu.optim.methods import SGD
    from bigdl_tpu.utils import set_seed

    set_seed(3)

    class TwoTower(Module):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(6, 8)
            self.b = nn.Linear(3, 8)
            self.head = nn.Linear(8, 4)
            self.out = nn.LogSoftMax()

        def forward(self, xs):
            xa, xb = xs
            h = jnp.tanh(self.a.forward(xa) + self.b.forward(xb))
            return self.out.forward(self.head.forward(h))

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(4):
        xa = rng.normal(size=(16, 6)).astype(np.float32)
        xb = rng.normal(size=(16, 3)).astype(np.float32)
        y = rng.integers(1, 5, size=(16,)).astype(np.int32)
        batches.append(MiniBatch((xa, xb), y))
    data = DataSet.array(batches)
    opt = (Optimizer(TwoTower(), data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.2))
           .set_end_when(Trigger.max_epoch(3))
           .set_validation(Trigger.every_epoch(),
                           DataSet.array(batches[:1], shuffle=False),
                           [Top1Accuracy()]))
    model = opt.optimize()
    assert model is not None
    assert np.isfinite(opt.state["loss"])


# -------------------------------------------------------------------------
# Per-layer regularizers + scaleW/scaleB (VERDICT r03 #6)
# Oracle: optim/Regularizer.scala accRegularization + the layer's
# accGradParameters scaling (nn/Linear.scala:144-166):
#   g_eff = scale * (g_raw + l1*sign(p) + l2*p)
# -------------------------------------------------------------------------

def _one_sgd_step(model, x, y, lr=0.1):
    """One Optimizer SGD step on a single MiniBatch; returns the params
    before and after as flat numpy leaf lists."""
    from bigdl_tpu.dataset.dataset import MiniBatch
    before = [np.array(l) for l in
              jax.tree_util.tree_leaves(model.parameters())]
    data = DataSet.array([MiniBatch(x, y)], shuffle=False)
    opt = (Optimizer(model, data, nn.MSECriterion())
           .set_optim_method(SGD(lr))
           .set_end_when(Trigger.max_iteration(1)))
    opt.optimize()
    after = [np.array(l) for l in
             jax.tree_util.tree_leaves(model.parameters())]
    return before, after


def test_regularizer_semantics_oracle():
    """g_eff = scale*(g + l1*sign(p) + l2*p), per layer, per w/b."""
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.optim import L1L2Regularizer
    set_seed(0)
    l1, l2, sw, sb, lr = 0.03, 0.2, 2.0, 0.5, 0.1
    model = nn.Linear(4, 3)
    model.set_regularizers(w_regularizer=L1L2Regularizer(l1, l2),
                           b_regularizer=L1L2Regularizer(0.0, l2))
    model.set_scale_w(sw)
    model.set_scale_b(sb)
    # scale_w/scale_b setters propagate to all modules incl. self; for a
    # leaf Linear both target the same module but apply per-param-name
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = rng.normal(size=(8, 3)).astype(np.float32)

    # raw grads of the same loss, no reg/scale
    ref = model.clone()
    params, rest = partition(ref)
    crit = nn.MSECriterion()

    def loss_fn(p):
        return crit(combine(p, rest).forward(jnp.asarray(x)),
                    jnp.asarray(y))

    raw = jax.grad(loss_fn)(params)
    grads = {n: np.array(raw._params[n]) for n in model._params}
    before = {n: np.array(model._params[n]) for n in model._params}
    _one_sgd_step(model, x, y, lr)
    after = {n: np.array(model._params[n]) for n in model._params}
    for name in before:
        p0, p1, g = before[name], after[name], grads[name]
        if "bias" in name:
            expect = p0 - lr * sb * (g + l2 * p0)
        else:
            expect = p0 - lr * sw * (g + l1 * np.sign(p0) + l2 * p0)
        np.testing.assert_allclose(p1, expect, rtol=1e-5, atol=1e-6,
                                   err_msg=name)


def test_l2_regularizer_matches_torch_weight_decay():
    """Our per-layer L2 == torch SGD weight_decay on the same problem."""
    from bigdl_tpu.optim import L2Regularizer
    set_seed(0)
    wd, lr = 0.1, 0.05
    model = nn.Linear(5, 2)
    model.set_regularizers(w_regularizer=L2Regularizer(wd),
                           b_regularizer=L2Regularizer(wd))
    w0 = np.array(model.weight)
    b0 = np.array(model.bias)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(16, 5)).astype(np.float32)
    y = rng.normal(size=(16, 2)).astype(np.float32)

    tl = torch.nn.Linear(5, 2)
    with torch.no_grad():
        tl.weight.copy_(torch.tensor(w0))
        tl.bias.copy_(torch.tensor(b0))
    topt = torch.optim.SGD(tl.parameters(), lr=lr, weight_decay=wd)
    tloss = torch.nn.functional.mse_loss(
        tl(torch.tensor(x)), torch.tensor(y), reduction="mean")
    topt.zero_grad(); tloss.backward(); topt.step()

    _one_sgd_step(model, x, y, lr)
    np.testing.assert_allclose(np.array(model.weight),
                               tl.weight.detach().numpy(),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.array(model.bias),
                               tl.bias.detach().numpy(),
                               rtol=1e-4, atol=1e-6)


def test_ctor_regularizer_args_reach_the_optimizer():
    """nn.Linear(..., w_regularizer=...) — the reference-parity ctor
    spelling (nn/Linear.scala:48) — must produce the same specs as
    set_regularizers (regression: the ctor slots were ignored)."""
    from bigdl_tpu.optim import L2Regularizer
    from bigdl_tpu.optim.regularizer import leaf_reg_specs
    m = nn.Linear(4, 3, w_regularizer=L2Regularizer(0.3),
                  b_regularizer=L2Regularizer(0.1))
    specs = dict(zip(["weight", "bias"], leaf_reg_specs(m)))
    # param order: _params insertion order = weight, bias
    assert specs["weight"] == (0.0, 0.3, 1.0), specs
    assert specs["bias"] == (0.0, 0.1, 1.0), specs


def test_regularizer_specs_align_with_frozen_modules():
    """leaf_reg_specs must stay aligned with param_paths when some
    modules are frozen (both exclude them)."""
    from bigdl_tpu.core.module import param_paths
    from bigdl_tpu.optim import L2Regularizer
    from bigdl_tpu.optim.regularizer import leaf_reg_specs
    model = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4),
                          nn.Linear(4, 2))
    model.layers[1].set_regularizers(w_regularizer=L2Regularizer(0.7))
    model.layers[0].freeze()
    paths = param_paths(model)
    specs = leaf_reg_specs(model)
    assert len(paths) == len(specs)
    by_path = dict(zip(paths, specs))
    assert all("layers[0]" not in p for p in paths)
    assert by_path["layers[1].weight"] == (0.0, 0.7, 1.0)
    assert by_path["layers[1].bias"] == (0.0, 0.0, 1.0)
    assert by_path["layers[2].weight"] == (0.0, 0.0, 1.0)


def test_set_regularizers_does_not_wipe_other_slot():
    """Regression (doc example hazard): setting one regularizer slot
    must not silently clear the other; explicit None clears."""
    from bigdl_tpu.optim import L1Regularizer, L2Regularizer
    from bigdl_tpu.optim.regularizer import leaf_reg_specs
    m = nn.Linear(4, 3, w_regularizer=L2Regularizer(1e-4))
    m.set_regularizers(b_regularizer=L1Regularizer(1e-5))
    specs = dict(zip(["weight", "bias"], leaf_reg_specs(m)))
    assert specs["weight"] == (0.0, 1e-4, 1.0), specs
    assert specs["bias"] == (1e-5, 0.0, 1.0), specs
    m.set_regularizers(w_regularizer=None)   # explicit clear
    specs = dict(zip(["weight", "bias"], leaf_reg_specs(m)))
    assert specs["weight"] == (0.0, 0.0, 1.0), specs
    assert specs["bias"] == (1e-5, 0.0, 1.0), specs


def test_aggregate_across_processes_single_process_identity():
    """Single process: the cross-process (n, d) psum is the identity."""
    from bigdl_tpu.optim.validation import (
        ValidationResult, aggregate_across_processes,
    )
    rs = [ValidationResult(3.0, 4.0, "Top1Accuracy"),
          ValidationResult(1.5, 6.0, "Loss")]
    out = aggregate_across_processes(rs)
    assert out is rs


def test_aggregate_across_processes_sums_counts(monkeypatch):
    """With >1 processes the (numerator, denominator) pairs are summed
    globally; the allgather is faked to a 2-process stack so the psum
    arithmetic is checked without a pod."""
    import jax as _jax
    from jax.experimental import multihost_utils
    from bigdl_tpu.optim import validation as V
    monkeypatch.setattr(_jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x: np.stack([x, 2.0 * x]))
    rs = [V.ValidationResult(3.0, 4.0, "Top1Accuracy"),
          V.ValidationResult(1.0, 2.0, "Loss")]
    out = V.aggregate_across_processes(rs)
    assert [(r.fmt, r.numerator, r.denominator) for r in out] == [
        ("Top1Accuracy", 9.0, 12.0), ("Loss", 3.0, 6.0)]


def test_aggregate_across_processes_rejects_array_metrics(monkeypatch):
    """MAP/AUC accumulate ragged raw-score arrays that a count psum
    cannot merge — they must demand replicated validation data."""
    import jax as _jax
    from bigdl_tpu.optim import validation as V
    monkeypatch.setattr(_jax, "process_count", lambda: 2)
    r = V.MAPResult("MAP@all", np.zeros((4, 3), np.float32),
                    np.ones((4,), np.int32))
    with pytest.raises(ValueError, match="replicated"):
        V.aggregate_across_processes([r])


def test_sharded_val_dataset_accepted_single_process(tmp_path):
    """PR 1 rejected per-process-sharded validation datasets outright;
    with cross-process (n, d) aggregation they are supported — the
    optimizer must not raise and validation must still run."""
    set_seed(61)
    rng = np.random.default_rng(11)
    samples = [Sample(rng.normal(size=(6,)).astype(np.float32),
                      int(rng.integers(1, 5))) for _ in range(32)]
    data = DataSet.array(samples).transform(SampleToMiniBatch(16))
    val = DataSet.array(samples[:16]).transform(SampleToMiniBatch(16))
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(2))
           .set_validation(Trigger.every_epoch(), val, [Top1Accuracy()]))
    opt.optimize()
    assert np.isfinite(opt.state["score"])
