"""Numerical oracle tests: layer outputs and input-grads vs torch CPU.

Mirrors the reference's cross-framework oracle strategy
(integration/torch/TH.scala runs Torch7 and compares; here torch-cpu is
in-process).  NHWC inputs are transposed to NCHW for the torch side.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Parameter


RTOL, ATOL = 1e-4, 1e-5


def to_nchw(x):
    return np.transpose(x, (0, 3, 1, 2))


def rnd(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_linear_matches_torch():
    x = rnd(4, 10)
    layer = nn.Linear(10, 6)
    tl = torch.nn.Linear(10, 6)
    with torch.no_grad():
        tl.weight.copy_(torch.tensor(np.asarray(layer.weight)))
        tl.bias.copy_(torch.tensor(np.asarray(layer.bias)))
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(x))),
        tl(torch.tensor(x)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_conv2d_matches_torch():
    x = rnd(2, 9, 9, 3)
    layer = nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1)
    w = np.asarray(layer.weight)  # HWIO
    w_t = np.transpose(w, (3, 2, 0, 1))  # OIHW
    out = layer(jnp.asarray(x))
    ref = F.conv2d(torch.tensor(to_nchw(x)), torch.tensor(w_t),
                   torch.tensor(np.asarray(layer.bias)),
                   stride=2, padding=1)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(out, (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_grouped_conv_matches_torch():
    x = rnd(2, 8, 8, 4)
    layer = nn.SpatialConvolution(4, 6, 3, 3, n_group=2)
    w = np.transpose(np.asarray(layer.weight), (3, 2, 0, 1))
    ref = F.conv2d(torch.tensor(to_nchw(x)), torch.tensor(w),
                   torch.tensor(np.asarray(layer.bias)), groups=2)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_conv_transpose_matches_torch():
    x = rnd(1, 8, 8, 3)
    layer = nn.SpatialFullConvolution(3, 5, 4, 4, 2, 2, 1, 1)
    w = np.asarray(layer.weight)  # HWIO: (kh, kw, in, out)
    w_t = np.transpose(w, (2, 3, 0, 1))  # IOHW for torch transposed
    ref = F.conv_transpose2d(
        torch.tensor(to_nchw(x)), torch.tensor(w_t),
        torch.tensor(np.asarray(layer.bias)), stride=2, padding=1)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_dilated_conv_matches_torch():
    x = rnd(1, 9, 9, 3)
    layer = nn.SpatialDilatedConvolution(3, 4, 3, 3, 1, 1, 1, 1, 2, 2)
    w = np.transpose(np.asarray(layer.weight), (3, 2, 0, 1))
    ref = F.conv2d(torch.tensor(to_nchw(x)), torch.tensor(w),
                   torch.tensor(np.asarray(layer.bias)),
                   padding=1, dilation=2)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_maxpool_matches_torch():
    x = rnd(2, 8, 8, 3)
    layer = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
    ref = F.max_pool2d(torch.tensor(to_nchw(x)), 3, 2, padding=1)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_maxpool_ceil_mode_matches_torch():
    x = rnd(1, 7, 7, 2)
    layer = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
    ref = F.max_pool2d(torch.tensor(to_nchw(x)), 3, 2, ceil_mode=True)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_avgpool_matches_torch():
    x = rnd(2, 8, 8, 3)
    layer = nn.SpatialAveragePooling(2, 2, 2, 2)
    ref = F.avg_pool2d(torch.tensor(to_nchw(x)), 2, 2)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_batchnorm_train_and_eval_match_torch():
    x = rnd(4, 6, 6, 5)
    layer = nn.SpatialBatchNormalization(5)
    tb = torch.nn.BatchNorm2d(5)
    with torch.no_grad():
        tb.weight.copy_(torch.tensor(np.asarray(layer.weight)))
        tb.bias.copy_(torch.tensor(np.asarray(layer.bias)))
    out = layer(jnp.asarray(x))
    ref = tb(torch.tensor(to_nchw(x)))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(out, (0, 3, 1, 2))),
        ref.detach().numpy(), rtol=1e-3, atol=1e-4)
    # running stats agree
    np.testing.assert_allclose(np.asarray(layer.running_mean),
                               tb.running_mean.numpy(), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(layer.running_var),
                               tb.running_var.numpy(), rtol=1e-3, atol=1e-4)
    # eval mode
    layer.eval_mode()
    tb.eval()
    out_e = layer(jnp.asarray(x))
    ref_e = tb(torch.tensor(to_nchw(x)))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(out_e, (0, 3, 1, 2))),
        ref_e.detach().numpy(), rtol=1e-3, atol=1e-4)


def test_batchnorm_large_mean_variance_stability():
    """The one-pass variance must not catastrophically cancel for a
    channel whose mean is huge relative to its std once the running
    mean tracks it (regression for the unshifted E[x^2]-E[x]^2 form,
    which returns var ~ 0 for |mean|/std > ~3e3 in f32)."""
    rng = np.random.default_rng(0)
    x = (3000.0 + 0.1 * rng.normal(size=(8, 4, 4, 3))).astype(np.float32)
    layer = nn.SpatialBatchNormalization(3, affine=False)
    # steady state: running mean near the data mean (exactness only
    # needs |E[x] - K| << |E[x]|, not equality)
    layer.running_mean = jnp.asarray([2999.0, 3000.5, 3001.0])
    out = np.asarray(layer.forward(jnp.asarray(x)))
    true_var = x.astype(np.float64).reshape(-1, 3).var(axis=0)
    got = np.asarray(layer.running_var)  # momentum 0.1 from var=1.0
    implied_batch_var = (got - 0.9 * 1.0) / 0.1
    np.testing.assert_allclose(implied_batch_var, true_var, rtol=0.05)
    # the normalized OUTPUT must be accurate too (regression for the
    # folded x*scale+shift form, which cancels in the output)
    mean64 = x.astype(np.float64).reshape(-1, 3).mean(axis=0)
    ref = ((x.astype(np.float64) - mean64)
           / np.sqrt(true_var + 1e-5)).astype(np.float32)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-3)


def test_batchnorm_bf16_moderate_mean_output_accuracy():
    """bf16 activations with mean ~50, std ~1: the input still encodes
    the signal (ulp at 50 is 0.25), and subtract-first normalization
    must return an O(1)-accurate output.  The folded x*scale+shift form
    differences two ~50 bf16 intermediates and was ~25% wrong here."""
    rng = np.random.default_rng(1)
    x = (50.0 + rng.normal(size=(8, 8, 8, 3))).astype(np.float32)
    x_bf = np.asarray(jnp.asarray(x, jnp.bfloat16).astype(np.float32))
    layer = nn.SpatialBatchNormalization(3, affine=False)
    layer.running_mean = jnp.asarray([50.0, 50.0, 50.0])
    out = np.asarray(layer.forward(
        jnp.asarray(x, jnp.bfloat16)).astype(jnp.float32))
    mean64 = x_bf.astype(np.float64).reshape(-1, 3).mean(axis=0)
    var64 = x_bf.astype(np.float64).reshape(-1, 3).var(axis=0)
    ref = ((x_bf.astype(np.float64) - mean64)
           / np.sqrt(var64 + 1e-5)).astype(np.float32)
    # output is written in bf16, so per-element error ~ bf16 ulp at O(1)
    np.testing.assert_allclose(out, ref, rtol=0.03, atol=0.03)


def test_layernorm_matches_torch():
    x = rnd(4, 12)
    layer = nn.LayerNormalization(12, eps=1e-5)
    t = torch.nn.LayerNorm(12, eps=1e-5)
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(x))),
        t(torch.tensor(x)).detach().numpy(), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("ours,theirs", [
    (nn.ReLU(), F.relu),
    (nn.Tanh(), torch.tanh),
    (nn.Sigmoid(), torch.sigmoid),
    (nn.ELU(), F.elu),
    (nn.SoftPlus(), F.softplus),
    (nn.SoftSign(), F.softsign),
    (nn.LeakyReLU(0.1), lambda t: F.leaky_relu(t, 0.1)),
    (nn.ReLU6(), F.relu6),
    (nn.HardShrink(0.5), lambda t: F.hardshrink(t, 0.5)),
    (nn.SoftShrink(0.5), lambda t: F.softshrink(t, 0.5)),
    (nn.TanhShrink(), F.tanhshrink),
    (nn.LogSigmoid(), F.logsigmoid),
    (nn.GELU(approximate=False), F.gelu),
])
def test_activations_match_torch(ours, theirs):
    x = rnd(3, 7, seed=3)
    np.testing.assert_allclose(
        np.asarray(ours(jnp.asarray(x))),
        theirs(torch.tensor(x)).numpy(), rtol=RTOL, atol=ATOL)


def test_logsoftmax_and_softmax_match_torch():
    x = rnd(3, 7)
    np.testing.assert_allclose(
        np.asarray(nn.LogSoftMax()(jnp.asarray(x))),
        F.log_softmax(torch.tensor(x), dim=-1).numpy(), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(
        np.asarray(nn.SoftMax()(jnp.asarray(x))),
        F.softmax(torch.tensor(x), dim=-1).numpy(), rtol=RTOL, atol=ATOL)


def test_lookup_table_matches_torch_embedding():
    layer = nn.LookupTable(20, 8)
    emb = torch.nn.Embedding(20, 8)
    with torch.no_grad():
        emb.weight.copy_(torch.tensor(np.asarray(layer.weight)))
    idx = np.array([[1, 5, 20], [3, 3, 7]])
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(idx))),
        emb(torch.tensor(idx) - 1).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_input_gradient_matches_torch():
    """backward() (vjp) vs torch autograd through a small conv net."""
    x = rnd(2, 8, 8, 3)
    conv = nn.SpatialConvolution(3, 4, 3, 3)
    w = np.transpose(np.asarray(conv.weight), (3, 2, 0, 1))
    xt = torch.tensor(to_nchw(x), requires_grad=True)
    ref = F.conv2d(xt, torch.tensor(w), torch.tensor(np.asarray(conv.bias)))
    ref.sum().backward()
    gi = conv.backward(jnp.asarray(x), jnp.ones(conv(jnp.asarray(x)).shape))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(gi, (0, 3, 1, 2))),
        xt.grad.numpy(), rtol=RTOL, atol=ATOL)


def test_lrn_matches_torch():
    x = rnd(2, 5, 5, 8)
    layer = nn.SpatialCrossMapLRN(size=5, alpha=1.0, beta=0.75, k=1.0)
    ref = torch.nn.LocalResponseNorm(5, alpha=1.0, beta=0.75, k=1.0)(
        torch.tensor(to_nchw(x)))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=1e-3, atol=1e-4)


def test_prelu_matches_torch():
    x = rnd(3, 4)
    layer = nn.PReLU(4)
    t = torch.nn.PReLU(4)
    with torch.no_grad():
        t.weight.copy_(torch.tensor(np.asarray(layer.weight)))
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(x))),
        t(torch.tensor(x)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_unsqueeze_batch_offset():
    x = jnp.ones((4, 7))
    assert nn.Unsqueeze(1, num_input_dims=1)(x).shape == (4, 1, 7)
    assert nn.Squeeze(1, num_input_dims=2)(
        jnp.ones((4, 1, 7))).shape == (4, 7)


def test_volumetric_avgpool_excl_pad():
    x = rnd(1, 4, 4, 4, 2)
    layer = nn.VolumetricAveragePooling(2, 2, 2, 2, 2, 2, 1, 1, 1,
                                        count_include_pad=False)
    ref = F.avg_pool3d(torch.tensor(np.transpose(x, (0, 4, 1, 2, 3))),
                       2, 2, padding=1, count_include_pad=False)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 4, 1, 2, 3))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_graph_arity_error():
    i1, i2 = nn.Input(), nn.Input()
    g = nn.Graph([i1, i2], nn.CAddTable()(i1, i2))
    with pytest.raises(ValueError, match="expects 2"):
        g(jnp.ones((2, 3)))


def test_birecurrent_positional_cell():
    cell = nn.LSTM(4, 6)
    bi = nn.BiRecurrent(cell)  # convenience positional form
    assert bi(jnp.ones((2, 3, 4))).shape == (2, 3, 12)
    with pytest.raises(ValueError, match="needs a cell"):
        nn.BiRecurrent()


def test_convlstm_strided():
    cl = nn.Recurrent(nn.ConvLSTMPeephole(3, 8, stride=2))
    out = cl(jnp.ones((2, 4, 8, 8, 3)))
    assert out.shape == (2, 4, 4, 4, 8)


def test_lstm_input_dropout_active():
    from bigdl_tpu import forward_context
    cell = nn.LSTM(4, 6, p=0.5)
    rec = nn.Recurrent(cell)
    x = jnp.ones((2, 3, 4))
    with forward_context(rng=jax.random.key(0)):
        a = rec(x)
    with forward_context(rng=jax.random.key(1)):
        b = rec(x)
    assert not np.allclose(np.asarray(a), np.asarray(b))
    rec.eval_mode()
    c, d = rec(x), rec(x)
    np.testing.assert_allclose(np.asarray(c), np.asarray(d))
