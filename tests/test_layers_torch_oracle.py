"""Numerical oracle tests: layer outputs and input-grads vs torch CPU.

Mirrors the reference's cross-framework oracle strategy
(integration/torch/TH.scala runs Torch7 and compares; here torch-cpu is
in-process).  NHWC inputs are transposed to NCHW for the torch side.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Parameter


RTOL, ATOL = 1e-4, 1e-5


def to_nchw(x):
    return np.transpose(x, (0, 3, 1, 2))


def rnd(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_linear_matches_torch():
    x = rnd(4, 10)
    layer = nn.Linear(10, 6)
    tl = torch.nn.Linear(10, 6)
    with torch.no_grad():
        tl.weight.copy_(torch.tensor(np.asarray(layer.weight)))
        tl.bias.copy_(torch.tensor(np.asarray(layer.bias)))
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(x))),
        tl(torch.tensor(x)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_conv2d_matches_torch():
    x = rnd(2, 9, 9, 3)
    layer = nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1)
    w = np.asarray(layer.weight)  # HWIO
    w_t = np.transpose(w, (3, 2, 0, 1))  # OIHW
    out = layer(jnp.asarray(x))
    ref = F.conv2d(torch.tensor(to_nchw(x)), torch.tensor(w_t),
                   torch.tensor(np.asarray(layer.bias)),
                   stride=2, padding=1)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(out, (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_grouped_conv_matches_torch():
    x = rnd(2, 8, 8, 4)
    layer = nn.SpatialConvolution(4, 6, 3, 3, n_group=2)
    w = np.transpose(np.asarray(layer.weight), (3, 2, 0, 1))
    ref = F.conv2d(torch.tensor(to_nchw(x)), torch.tensor(w),
                   torch.tensor(np.asarray(layer.bias)), groups=2)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_conv_transpose_matches_torch():
    x = rnd(1, 8, 8, 3)
    layer = nn.SpatialFullConvolution(3, 5, 4, 4, 2, 2, 1, 1)
    w = np.asarray(layer.weight)  # HWIO: (kh, kw, in, out)
    w_t = np.transpose(w, (2, 3, 0, 1))  # IOHW for torch transposed
    ref = F.conv_transpose2d(
        torch.tensor(to_nchw(x)), torch.tensor(w_t),
        torch.tensor(np.asarray(layer.bias)), stride=2, padding=1)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_dilated_conv_matches_torch():
    x = rnd(1, 9, 9, 3)
    layer = nn.SpatialDilatedConvolution(3, 4, 3, 3, 1, 1, 1, 1, 2, 2)
    w = np.transpose(np.asarray(layer.weight), (3, 2, 0, 1))
    ref = F.conv2d(torch.tensor(to_nchw(x)), torch.tensor(w),
                   torch.tensor(np.asarray(layer.bias)),
                   padding=1, dilation=2)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_maxpool_matches_torch():
    x = rnd(2, 8, 8, 3)
    layer = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
    ref = F.max_pool2d(torch.tensor(to_nchw(x)), 3, 2, padding=1)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_maxpool_ceil_mode_matches_torch():
    x = rnd(1, 7, 7, 2)
    layer = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
    ref = F.max_pool2d(torch.tensor(to_nchw(x)), 3, 2, ceil_mode=True)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_avgpool_matches_torch():
    x = rnd(2, 8, 8, 3)
    layer = nn.SpatialAveragePooling(2, 2, 2, 2)
    ref = F.avg_pool2d(torch.tensor(to_nchw(x)), 2, 2)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_batchnorm_train_and_eval_match_torch():
    x = rnd(4, 6, 6, 5)
    layer = nn.SpatialBatchNormalization(5)
    tb = torch.nn.BatchNorm2d(5)
    with torch.no_grad():
        tb.weight.copy_(torch.tensor(np.asarray(layer.weight)))
        tb.bias.copy_(torch.tensor(np.asarray(layer.bias)))
    out = layer(jnp.asarray(x))
    ref = tb(torch.tensor(to_nchw(x)))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(out, (0, 3, 1, 2))),
        ref.detach().numpy(), rtol=1e-3, atol=1e-4)
    # running stats agree
    np.testing.assert_allclose(np.asarray(layer.running_mean),
                               tb.running_mean.numpy(), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(layer.running_var),
                               tb.running_var.numpy(), rtol=1e-3, atol=1e-4)
    # eval mode
    layer.eval_mode()
    tb.eval()
    out_e = layer(jnp.asarray(x))
    ref_e = tb(torch.tensor(to_nchw(x)))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(out_e, (0, 3, 1, 2))),
        ref_e.detach().numpy(), rtol=1e-3, atol=1e-4)


def test_batchnorm_large_mean_variance_stability():
    """The one-pass variance must not catastrophically cancel for a
    channel whose mean is huge relative to its std once the running
    mean tracks it (regression for the unshifted E[x^2]-E[x]^2 form,
    which returns var ~ 0 for |mean|/std > ~3e3 in f32)."""
    rng = np.random.default_rng(0)
    x = (3000.0 + 0.1 * rng.normal(size=(8, 4, 4, 3))).astype(np.float32)
    layer = nn.SpatialBatchNormalization(3, affine=False)
    # steady state: running mean near the data mean (exactness only
    # needs |E[x] - K| << |E[x]|, not equality)
    layer.running_mean = jnp.asarray([2999.0, 3000.5, 3001.0])
    out = np.asarray(layer.forward(jnp.asarray(x)))
    true_var = x.astype(np.float64).reshape(-1, 3).var(axis=0)
    got = np.asarray(layer.running_var)  # momentum 0.1 from var=1.0
    implied_batch_var = (got - 0.9 * 1.0) / 0.1
    np.testing.assert_allclose(implied_batch_var, true_var, rtol=0.05)
    # the normalized OUTPUT must be accurate too (regression for the
    # folded x*scale+shift form, which cancels in the output)
    mean64 = x.astype(np.float64).reshape(-1, 3).mean(axis=0)
    ref = ((x.astype(np.float64) - mean64)
           / np.sqrt(true_var + 1e-5)).astype(np.float32)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-3)


def test_batchnorm_bf16_moderate_mean_output_accuracy():
    """bf16 activations with mean ~50, std ~1: the input still encodes
    the signal (ulp at 50 is 0.25), and subtract-first normalization
    must return an O(1)-accurate output.  The folded x*scale+shift form
    differences two ~50 bf16 intermediates and was ~25% wrong here."""
    rng = np.random.default_rng(1)
    x = (50.0 + rng.normal(size=(8, 8, 8, 3))).astype(np.float32)
    x_bf = np.asarray(jnp.asarray(x, jnp.bfloat16).astype(np.float32))
    layer = nn.SpatialBatchNormalization(3, affine=False)
    layer.running_mean = jnp.asarray([50.0, 50.0, 50.0])
    out = np.asarray(layer.forward(
        jnp.asarray(x, jnp.bfloat16)).astype(jnp.float32))
    mean64 = x_bf.astype(np.float64).reshape(-1, 3).mean(axis=0)
    var64 = x_bf.astype(np.float64).reshape(-1, 3).var(axis=0)
    ref = ((x_bf.astype(np.float64) - mean64)
           / np.sqrt(var64 + 1e-5)).astype(np.float32)
    # output is written in bf16, so per-element error ~ bf16 ulp at O(1)
    np.testing.assert_allclose(out, ref, rtol=0.03, atol=0.03)


def test_layernorm_matches_torch():
    x = rnd(4, 12)
    layer = nn.LayerNormalization(12, eps=1e-5)
    t = torch.nn.LayerNorm(12, eps=1e-5)
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(x))),
        t(torch.tensor(x)).detach().numpy(), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("ours,theirs", [
    (nn.ReLU(), F.relu),
    (nn.Tanh(), torch.tanh),
    (nn.Sigmoid(), torch.sigmoid),
    (nn.ELU(), F.elu),
    (nn.SoftPlus(), F.softplus),
    (nn.SoftSign(), F.softsign),
    (nn.LeakyReLU(0.1), lambda t: F.leaky_relu(t, 0.1)),
    (nn.ReLU6(), F.relu6),
    (nn.HardShrink(0.5), lambda t: F.hardshrink(t, 0.5)),
    (nn.SoftShrink(0.5), lambda t: F.softshrink(t, 0.5)),
    (nn.TanhShrink(), F.tanhshrink),
    (nn.LogSigmoid(), F.logsigmoid),
    (nn.GELU(approximate=False), F.gelu),
])
def test_activations_match_torch(ours, theirs):
    x = rnd(3, 7, seed=3)
    np.testing.assert_allclose(
        np.asarray(ours(jnp.asarray(x))),
        theirs(torch.tensor(x)).numpy(), rtol=RTOL, atol=ATOL)


def test_logsoftmax_and_softmax_match_torch():
    x = rnd(3, 7)
    np.testing.assert_allclose(
        np.asarray(nn.LogSoftMax()(jnp.asarray(x))),
        F.log_softmax(torch.tensor(x), dim=-1).numpy(), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(
        np.asarray(nn.SoftMax()(jnp.asarray(x))),
        F.softmax(torch.tensor(x), dim=-1).numpy(), rtol=RTOL, atol=ATOL)


def test_lookup_table_matches_torch_embedding():
    layer = nn.LookupTable(20, 8)
    emb = torch.nn.Embedding(20, 8)
    with torch.no_grad():
        emb.weight.copy_(torch.tensor(np.asarray(layer.weight)))
    idx = np.array([[1, 5, 20], [3, 3, 7]])
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(idx))),
        emb(torch.tensor(idx) - 1).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_input_gradient_matches_torch():
    """backward() (vjp) vs torch autograd through a small conv net."""
    x = rnd(2, 8, 8, 3)
    conv = nn.SpatialConvolution(3, 4, 3, 3)
    w = np.transpose(np.asarray(conv.weight), (3, 2, 0, 1))
    xt = torch.tensor(to_nchw(x), requires_grad=True)
    ref = F.conv2d(xt, torch.tensor(w), torch.tensor(np.asarray(conv.bias)))
    ref.sum().backward()
    gi = conv.backward(jnp.asarray(x), jnp.ones(conv(jnp.asarray(x)).shape))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(gi, (0, 3, 1, 2))),
        xt.grad.numpy(), rtol=RTOL, atol=ATOL)


def test_lrn_matches_torch():
    x = rnd(2, 5, 5, 8)
    layer = nn.SpatialCrossMapLRN(size=5, alpha=1.0, beta=0.75, k=1.0)
    ref = torch.nn.LocalResponseNorm(5, alpha=1.0, beta=0.75, k=1.0)(
        torch.tensor(to_nchw(x)))
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 3, 1, 2))),
        ref.numpy(), rtol=1e-3, atol=1e-4)


def test_prelu_matches_torch():
    x = rnd(3, 4)
    layer = nn.PReLU(4)
    t = torch.nn.PReLU(4)
    with torch.no_grad():
        t.weight.copy_(torch.tensor(np.asarray(layer.weight)))
    np.testing.assert_allclose(
        np.asarray(layer(jnp.asarray(x))),
        t(torch.tensor(x)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_unsqueeze_batch_offset():
    x = jnp.ones((4, 7))
    assert nn.Unsqueeze(1, num_input_dims=1)(x).shape == (4, 1, 7)
    assert nn.Squeeze(1, num_input_dims=2)(
        jnp.ones((4, 1, 7))).shape == (4, 7)


def test_volumetric_avgpool_excl_pad():
    x = rnd(1, 4, 4, 4, 2)
    layer = nn.VolumetricAveragePooling(2, 2, 2, 2, 2, 2, 1, 1, 1,
                                        count_include_pad=False)
    ref = F.avg_pool3d(torch.tensor(np.transpose(x, (0, 4, 1, 2, 3))),
                       2, 2, padding=1, count_include_pad=False)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(layer(jnp.asarray(x)), (0, 4, 1, 2, 3))),
        ref.numpy(), rtol=RTOL, atol=ATOL)


def test_graph_arity_error():
    i1, i2 = nn.Input(), nn.Input()
    g = nn.Graph([i1, i2], nn.CAddTable()(i1, i2))
    with pytest.raises(ValueError, match="expects 2"):
        g(jnp.ones((2, 3)))


def test_birecurrent_positional_cell():
    cell = nn.LSTM(4, 6)
    bi = nn.BiRecurrent(cell)  # convenience positional form
    assert bi(jnp.ones((2, 3, 4))).shape == (2, 3, 12)
    with pytest.raises(ValueError, match="needs a cell"):
        nn.BiRecurrent()


def test_convlstm_strided():
    cl = nn.Recurrent(nn.ConvLSTMPeephole(3, 8, stride=2))
    out = cl(jnp.ones((2, 4, 8, 8, 3)))
    assert out.shape == (2, 4, 4, 4, 8)


def test_lstm_input_dropout_active():
    from bigdl_tpu import forward_context
    cell = nn.LSTM(4, 6, p=0.5)
    rec = nn.Recurrent(cell)
    x = jnp.ones((2, 3, 4))
    with forward_context(rng=jax.random.key(0)):
        a = rec(x)
    with forward_context(rng=jax.random.key(1)):
        b = rec(x)
    assert not np.allclose(np.asarray(a), np.asarray(b))
    rec.eval_mode()
    c, d = rec(x), rec(x)
    np.testing.assert_allclose(np.asarray(c), np.asarray(d))


# ==========================================================================
# Parametrized sweep (VERDICT r03 #8): every §2.3 layer with a direct
# torch counterpart — forward AND input-gradient oracle, mirroring the
# reference's 205 per-layer specs + Torch7 integration sweep
# (integration/torch/TH.scala).  Each case: (name, build_ours,
# build_torch(ours) -> callable over torch tensors, build_inputs).
# ==========================================================================

def _pos(*shape, seed=0):
    return np.abs(rnd(*shape, seed=seed)) + 0.5


def _case_seed(name):
    import zlib
    # stable across interpreter runs (hash() is salted per process)
    return zlib.crc32(name.encode()) % 100000


def _lrn_torch(ours):
    import torch.nn as tnn
    m = tnn.LocalResponseNorm(5, alpha=1.0, beta=0.75, k=1.0)
    return lambda x: m(x.permute(0, 3, 1, 2)).permute(0, 2, 3, 1)


SWEEP = [
    # -- activations over [3, 7] ------------------------------------------
    ("Abs", lambda: nn.Abs(), lambda o: torch.abs, lambda: [rnd(3, 7, seed=1)]),
    ("Clamp", lambda: nn.Clamp(-1, 1),
     lambda o: (lambda x: torch.clamp(x, -1, 1)), lambda: [rnd(3, 7, seed=2) * 2]),
    ("ELU", lambda: nn.ELU(1.0), lambda o: F.elu, lambda: [rnd(3, 7, seed=3)]),
    ("Exp", lambda: nn.Exp(), lambda o: torch.exp, lambda: [rnd(3, 7, seed=4)]),
    ("HardShrink", lambda: nn.HardShrink(0.5), lambda o: F.hardshrink,
     lambda: [rnd(3, 7, seed=5)]),
    ("LeakyReLU", lambda: nn.LeakyReLU(0.03),
     lambda o: (lambda x: F.leaky_relu(x, 0.03)), lambda: [rnd(3, 7, seed=6)]),
    ("Log", lambda: nn.Log(), lambda o: torch.log, lambda: [_pos(3, 7, seed=7)]),
    ("LogSigmoid", lambda: nn.LogSigmoid(), lambda o: F.logsigmoid,
     lambda: [rnd(3, 7, seed=8)]),
    ("LogSoftMax", lambda: nn.LogSoftMax(),
     lambda o: (lambda x: F.log_softmax(x, dim=-1)), lambda: [rnd(3, 7, seed=9)]),
    ("Negative", lambda: nn.Negative(), lambda o: torch.neg,
     lambda: [rnd(3, 7, seed=10)]),
    ("Power", lambda: nn.Power(2.0, 1.5, 0.1),
     lambda o: (lambda x: (1.5 * x + 0.1) ** 2.0), lambda: [_pos(3, 7, seed=11)]),
    ("ReLU", lambda: nn.ReLU(), lambda o: F.relu, lambda: [rnd(3, 7, seed=12)]),
    ("ReLU6", lambda: nn.ReLU6(), lambda o: F.relu6, lambda: [rnd(3, 7, seed=13) * 4]),
    ("RReLU_eval", lambda: nn.RReLU(0.1, 0.3),
     lambda o: (lambda x: F.rrelu(x, 0.1, 0.3, training=False)),
     lambda: [rnd(3, 7, seed=14)]),
    ("Sigmoid", lambda: nn.Sigmoid(), lambda o: torch.sigmoid,
     lambda: [rnd(3, 7, seed=15)]),
    ("SoftMax", lambda: nn.SoftMax(),
     lambda o: (lambda x: F.softmax(x, dim=-1)), lambda: [rnd(3, 7, seed=16)]),
    ("SoftMin", lambda: nn.SoftMin(),
     lambda o: (lambda x: F.softmin(x, dim=-1)), lambda: [rnd(3, 7, seed=17)]),
    ("SoftPlus", lambda: nn.SoftPlus(), lambda o: F.softplus,
     lambda: [rnd(3, 7, seed=18)]),
    ("SoftShrink", lambda: nn.SoftShrink(0.5), lambda o: F.softshrink,
     lambda: [rnd(3, 7, seed=19)]),
    ("SoftSign", lambda: nn.SoftSign(), lambda o: F.softsign,
     lambda: [rnd(3, 7, seed=20)]),
    ("Sqrt", lambda: nn.Sqrt(), lambda o: torch.sqrt, lambda: [_pos(3, 7, seed=21)]),
    ("Square", lambda: nn.Square(), lambda o: torch.square,
     lambda: [rnd(3, 7, seed=22)]),
    ("Tanh", lambda: nn.Tanh(), lambda o: torch.tanh, lambda: [rnd(3, 7, seed=23)]),
    ("TanhShrink", lambda: nn.TanhShrink(),
     lambda o: (lambda x: x - torch.tanh(x)), lambda: [rnd(3, 7, seed=24)]),
    ("Threshold", lambda: nn.Threshold(0.1, -2.0),
     lambda o: (lambda x: F.threshold(x, 0.1, -2.0)), lambda: [rnd(3, 7, seed=25)]),
    ("HardSigmoid", lambda: nn.HardSigmoid(),
     lambda o: (lambda x: torch.clamp(0.2 * x + 0.5, 0, 1)),
     lambda: [rnd(3, 7, seed=26) * 4]),
    ("Identity", lambda: nn.Identity(), lambda o: (lambda x: x),
     lambda: [rnd(3, 7, seed=27)]),
    ("MulConstant", lambda: nn.MulConstant(2.5),
     lambda o: (lambda x: x * 2.5), lambda: [rnd(3, 7, seed=28)]),
    ("AddConstant", lambda: nn.AddConstant(0.7),
     lambda o: (lambda x: x + 0.7), lambda: [rnd(3, 7, seed=29)]),
    ("Dropout_eval", lambda: nn.Dropout(0.5), lambda o: (lambda x: x),
     lambda: [rnd(3, 7, seed=30)]),

    # -- parameterized dense-ish ------------------------------------------
    ("Linear", lambda: nn.Linear(10, 6),
     lambda o: (lambda x: F.linear(
         x, torch.tensor(np.asarray(o.weight)),
         torch.tensor(np.asarray(o.bias)))),
     lambda: [rnd(4, 10, seed=31)]),
    ("CAdd", lambda: nn.CAdd((7,)),
     lambda o: (lambda x: x + torch.tensor(np.asarray(o.bias))),
     lambda: [rnd(3, 7, seed=32)]),
    ("CMul", lambda: nn.CMul((7,)),
     lambda o: (lambda x: x * torch.tensor(np.asarray(o.weight))),
     lambda: [rnd(3, 7, seed=33)]),
    ("Mul", lambda: nn.Mul(),
     lambda o: (lambda x: x * torch.tensor(np.asarray(o.weight))),
     lambda: [rnd(3, 7, seed=34)]),
    ("Add", lambda: nn.Add(7),
     lambda o: (lambda x: x + torch.tensor(np.asarray(o.bias))),
     lambda: [rnd(3, 7, seed=35)]),
    ("LayerNormalization", lambda: nn.LayerNormalization(8, eps=1e-6),
     lambda o: (lambda x: F.layer_norm(
         x, (8,), torch.tensor(np.asarray(o.weight)),
         torch.tensor(np.asarray(o.bias)), eps=1e-6)),
     lambda: [rnd(3, 8, seed=36)]),
    ("Normalize", lambda: nn.Normalize(2.0),
     lambda o: (lambda x: F.normalize(x, p=2.0, dim=1)),
     lambda: [rnd(3, 7, seed=37)]),
    ("PairwiseDistance", lambda: nn.PairwiseDistance(),
     lambda o: F.pairwise_distance,
     lambda: [rnd(3, 7, seed=38), rnd(3, 7, seed=39)]),

    # -- conv / pool / resize (NHWC ours vs NCHW torch) --------------------
    ("SpatialDilatedConvolution",
     lambda: nn.SpatialDilatedConvolution(3, 6, 3, 3, 1, 1, 2, 2, 2, 2),
     lambda o: (lambda x: F.conv2d(
         x.permute(0, 3, 1, 2),
         torch.tensor(np.transpose(np.asarray(o.weight), (3, 2, 0, 1))),
         torch.tensor(np.asarray(o.bias)), padding=2,
         dilation=2).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 9, 9, 3, seed=40)]),
    ("SpatialMaxPooling", lambda: nn.SpatialMaxPooling(2, 2, 2, 2),
     lambda o: (lambda x: F.max_pool2d(
         x.permute(0, 3, 1, 2), 2, 2).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 8, 8, 3, seed=41)]),
    ("SpatialAveragePooling", lambda: nn.SpatialAveragePooling(2, 2, 2, 2),
     lambda o: (lambda x: F.avg_pool2d(
         x.permute(0, 3, 1, 2), 2, 2).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 8, 8, 3, seed=42)]),
    ("TemporalMaxPooling", lambda: nn.TemporalMaxPooling(2),
     lambda o: (lambda x: F.max_pool1d(
         x.permute(0, 2, 1), 2).permute(0, 2, 1)),
     lambda: [rnd(2, 8, 4, seed=43)]),
    ("VolumetricMaxPooling", lambda: nn.VolumetricMaxPooling(2, 2, 2),
     lambda o: (lambda x: F.max_pool3d(
         x.permute(0, 4, 1, 2, 3), 2).permute(0, 2, 3, 4, 1)),
     lambda: [rnd(2, 4, 6, 6, 2, seed=44)]),
    ("VolumetricAveragePooling", lambda: nn.VolumetricAveragePooling(2, 2, 2),
     lambda o: (lambda x: F.avg_pool3d(
         x.permute(0, 4, 1, 2, 3), 2).permute(0, 2, 3, 4, 1)),
     lambda: [rnd(2, 4, 6, 6, 2, seed=45)]),
    ("SpatialCrossMapLRN", lambda: nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0),
     _lrn_torch, lambda: [_pos(2, 6, 6, 7, seed=46)]),
    ("UpSampling1D", lambda: nn.UpSampling1D(2),
     lambda o: (lambda x: F.interpolate(
         x.permute(0, 2, 1), scale_factor=2, mode="nearest"
     ).permute(0, 2, 1)),
     lambda: [rnd(2, 5, 3, seed=47)]),
    ("UpSampling2D", lambda: nn.UpSampling2D((2, 2)),
     lambda o: (lambda x: F.interpolate(
         x.permute(0, 3, 1, 2), scale_factor=2, mode="nearest"
     ).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 4, 4, 3, seed=48)]),
    ("UpSampling3D", lambda: nn.UpSampling3D((2, 2, 2)),
     lambda o: (lambda x: F.interpolate(
         x.permute(0, 4, 1, 2, 3), scale_factor=2, mode="nearest"
     ).permute(0, 2, 3, 4, 1)),
     lambda: [rnd(1, 3, 3, 3, 2, seed=49)]),
    ("SpatialZeroPadding", lambda: nn.SpatialZeroPadding(1, 2, 3, 4),
     lambda o: (lambda x: F.pad(
         x.permute(0, 3, 1, 2), (1, 2, 3, 4)).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 5, 5, 3, seed=50)]),
    ("Cropping2D", lambda: nn.Cropping2D((1, 1), (2, 1)),
     lambda o: (lambda x: x[:, 1:-1, 2:-1, :]),
     lambda: [rnd(2, 6, 7, 3, seed=51)]),

    # -- shape ops ---------------------------------------------------------
    ("Unsqueeze", lambda: nn.Unsqueeze(2),
     lambda o: (lambda x: x.unsqueeze(1)), lambda: [rnd(3, 7, seed=52)]),
    ("Squeeze", lambda: nn.Squeeze(2),
     lambda o: (lambda x: x.squeeze(1)), lambda: [rnd(3, 1, 7, seed=53)]),
    ("Transpose", lambda: nn.Transpose([(2, 3)]),
     lambda o: (lambda x: x.transpose(1, 2)), lambda: [rnd(3, 4, 5, seed=54)]),
    ("Mean", lambda: nn.Mean(2),
     lambda o: (lambda x: x.mean(dim=1)), lambda: [rnd(3, 4, 5, seed=55)]),
    ("Sum", lambda: nn.Sum(2),
     lambda o: (lambda x: x.sum(dim=1)), lambda: [rnd(3, 4, 5, seed=56)]),
    ("Max", lambda: nn.Max(2),
     lambda o: (lambda x: x.amax(dim=1)), lambda: [rnd(3, 4, 5, seed=57)]),
    ("Min", lambda: nn.Min(2),
     lambda o: (lambda x: x.amin(dim=1)), lambda: [rnd(3, 4, 5, seed=58)]),
    ("ExpandSize", lambda: nn.ExpandSize([3, 7]),
     lambda o: (lambda x: x.expand(3, 7)), lambda: [rnd(1, 7, seed=59)]),
    ("Masking", lambda: nn.Masking(0.0),
     lambda o: (lambda x: x * (x.abs().sum(-1, keepdim=True) != 0)),
     lambda: [np.concatenate([rnd(2, 3, 4, seed=60),
                              np.zeros((2, 1, 4), np.float32)], axis=1)]),

    ("Bilinear", lambda: nn.Bilinear(4, 5, 3),
     lambda o: (lambda a, b: F.bilinear(
         a, b, torch.tensor(np.asarray(o.weight)),
         torch.tensor(np.asarray(o.bias)))),
     lambda: [rnd(6, 4, seed=83), rnd(6, 5, seed=84)]),
    ("TemporalConvolution", lambda: nn.TemporalConvolution(4, 6, 3),
     # ours: [T,F] frames, weight [kw, in, out]; torch conv1d NCW, OIW
     lambda o: (lambda x: F.conv1d(
         x.permute(0, 2, 1),
         torch.tensor(np.transpose(np.asarray(o.weight), (2, 1, 0))),
         torch.tensor(np.asarray(o.bias))).permute(0, 2, 1)),
     lambda: [rnd(2, 8, 4, seed=85)]),
    ("VolumetricConvolution", lambda: nn.VolumetricConvolution(2, 4, 3, 3, 3),
     # ours NDHWC, weight DHWIO; torch conv3d NCDHW, weight OIDHW
     lambda o: (lambda x: F.conv3d(
         x.permute(0, 4, 1, 2, 3),
         torch.tensor(np.transpose(np.asarray(o.weight), (4, 3, 0, 1, 2))),
         torch.tensor(np.asarray(o.bias))).permute(0, 2, 3, 4, 1)),
     lambda: [rnd(2, 5, 6, 6, 2, seed=86)]),
    ("SpatialSeparableConvolution",
     lambda: nn.SpatialSeparableConvolution(3, 6, 2, 3, 3),
     # depthwise [kh,kw,1,in*mult] then pointwise [1,1,in*mult,out]
     lambda o: (lambda x: F.conv2d(
         F.conv2d(
             x.permute(0, 3, 1, 2),
             torch.tensor(np.transpose(
                 np.asarray(o.depth_weight), (3, 2, 0, 1))),
             groups=3),
         torch.tensor(np.transpose(
             np.asarray(o.point_weight), (3, 2, 0, 1))),
         torch.tensor(np.asarray(o.bias))).permute(0, 2, 3, 1)),
     lambda: [rnd(2, 7, 7, 3, seed=87)]),

    # -- two-input table ops ----------------------------------------------
    ("CAddTable", lambda: nn.CAddTable(), lambda o: (lambda a, b: a + b),
     lambda: [rnd(3, 5, seed=61), rnd(3, 5, seed=62)]),
    ("CSubTable", lambda: nn.CSubTable(), lambda o: (lambda a, b: a - b),
     lambda: [rnd(3, 5, seed=63), rnd(3, 5, seed=64)]),
    ("CMulTable", lambda: nn.CMulTable(), lambda o: (lambda a, b: a * b),
     lambda: [rnd(3, 5, seed=65), rnd(3, 5, seed=66)]),
    ("CDivTable", lambda: nn.CDivTable(), lambda o: (lambda a, b: a / b),
     lambda: [rnd(3, 5, seed=67), _pos(3, 5, seed=68)]),
    ("CMaxTable", lambda: nn.CMaxTable(),
     lambda o: (lambda a, b: torch.maximum(a, b)),
     lambda: [rnd(3, 5, seed=69), rnd(3, 5, seed=70)]),
    ("CMinTable", lambda: nn.CMinTable(),
     lambda o: (lambda a, b: torch.minimum(a, b)),
     lambda: [rnd(3, 5, seed=71), rnd(3, 5, seed=72)]),
    ("CAveTable", lambda: nn.CAveTable(),
     lambda o: (lambda a, b: (a + b) / 2),
     lambda: [rnd(3, 5, seed=73), rnd(3, 5, seed=74)]),
    ("DotProduct", lambda: nn.DotProduct(),
     lambda o: (lambda a, b: (a * b).sum(dim=1)),
     lambda: [rnd(3, 5, seed=75), rnd(3, 5, seed=76)]),
    ("CosineDistance", lambda: nn.CosineDistance(),
     lambda o: (lambda a, b: F.cosine_similarity(a, b, dim=1)),
     lambda: [rnd(3, 5, seed=77), rnd(3, 5, seed=78)]),
    ("MM", lambda: nn.MM(),
     lambda o: (lambda a, b: torch.bmm(a, b)),
     lambda: [rnd(3, 4, 5, seed=79), rnd(3, 5, 6, seed=80)]),
    ("JoinTable", lambda: nn.JoinTable(2),
     lambda o: (lambda a, b: torch.cat([a, b], dim=1)),
     lambda: [rnd(3, 4, seed=81), rnd(3, 5, seed=82)]),

    # -- shape / indexing ops (Torch 1-based dims -> torch 0-based) --------
    ("HardTanh", lambda: nn.HardTanh(-0.5, 0.5),
     lambda o: (lambda x: F.hardtanh(x, -0.5, 0.5)),
     lambda: [rnd(3, 7, seed=88)]),
    ("Contiguous", lambda: nn.Contiguous(), lambda o: (lambda x: x),
     lambda: [rnd(3, 7, seed=89)]),
    ("GaussianDropout_eval", lambda: nn.GaussianDropout(0.3),
     lambda o: (lambda x: x), lambda: [rnd(3, 7, seed=90)]),
    ("GaussianNoise_eval", lambda: nn.GaussianNoise(0.3),
     lambda o: (lambda x: x), lambda: [rnd(3, 7, seed=91)]),
    ("Select", lambda: nn.Select(2, 1),
     lambda o: (lambda x: x.select(1, 0)), lambda: [rnd(2, 3, 4, seed=92)]),
    ("Narrow", lambda: nn.Narrow(2, 1, 2),
     lambda o: (lambda x: x.narrow(1, 0, 2)), lambda: [rnd(2, 5, 4, seed=93)]),
    ("Reverse", lambda: nn.Reverse(2),
     lambda o: (lambda x: x.flip(1)), lambda: [rnd(2, 5, 4, seed=94)]),
    ("Tile", lambda: nn.Tile(2, 3),
     lambda o: (lambda x: x.repeat(1, 3, 1)), lambda: [rnd(2, 3, 4, seed=95)]),
    ("Replicate", lambda: nn.Replicate(3, 1),
     lambda o: (lambda x: x.unsqueeze(0).expand(3, -1, -1, -1)),
     lambda: [rnd(2, 3, 4, seed=96)]),
    ("Padding_end", lambda: nn.Padding(2, 2, 3),
     lambda o: (lambda x: F.pad(x, (0, 0, 0, 2))),
     lambda: [rnd(2, 3, 4, seed=97)]),
    ("Padding_front", lambda: nn.Padding(2, -2, 3),
     lambda o: (lambda x: F.pad(x, (0, 0, 2, 0))),
     lambda: [rnd(2, 3, 4, seed=98)]),
    ("View", lambda: nn.View(12),
     lambda o: (lambda x: x.reshape(x.shape[0], 12)),
     lambda: [rnd(2, 3, 4, seed=99)]),
    ("Reshape", lambda: nn.Reshape([4, 3]),
     lambda o: (lambda x: x.reshape(x.shape[0], 4, 3)),
     lambda: [rnd(2, 3, 4, seed=100)]),
    ("Pack", lambda: nn.Pack(1),
     lambda o: (lambda a, b: torch.stack([a, b], dim=0)),
     lambda: [rnd(2, 4, seed=101), rnd(2, 4, seed=102)]),
    ("MV", lambda: nn.MV(),
     lambda o: (lambda a, v: torch.bmm(a, v.unsqueeze(2)).squeeze(2)),
     lambda: [rnd(2, 3, 4, seed=103), rnd(2, 4, seed=104)]),
    ("Cropping3D", lambda: nn.Cropping3D((1, 1), (0, 1), (1, 0)),
     lambda o: (lambda x: x[:, 1:-1, 0:-1, 1:, :]),
     lambda: [rnd(2, 5, 5, 5, 2, seed=108)]),

    # -- parameterized tail ------------------------------------------------
    ("Euclidean", lambda: nn.Euclidean(4, 3),
     lambda o: (lambda x: torch.cdist(
         x, torch.tensor(np.asarray(o.weight)))),
     lambda: [rnd(5, 4, seed=105)]),
    ("Cosine", lambda: nn.Cosine(4, 3),
     lambda o: (lambda x: F.cosine_similarity(
         x.unsqueeze(1),
         torch.tensor(np.asarray(o.weight)).unsqueeze(0), dim=2)),
     lambda: [rnd(5, 4, seed=106)]),
    ("Maxout", lambda: nn.Maxout(4, 3, 2),
     lambda o: (lambda x: F.linear(
         x, torch.tensor(np.asarray(o.layer.weight)),
         torch.tensor(np.asarray(o.layer.bias))
     ).reshape(-1, 2, 3).amax(1)),
     lambda: [rnd(5, 4, seed=107)]),
    ("VolumetricFullConvolution",
     lambda: nn.VolumetricFullConvolution(2, 3, 2, 2, 2, 2, 2, 2),
     # ours NDHWC, weight [kt,kh,kw,in,out]; torch NCDHW, [in,out,kT,kH,kW]
     lambda o: (lambda x: F.conv_transpose3d(
         x.permute(0, 4, 1, 2, 3),
         torch.tensor(np.transpose(np.asarray(o.weight), (3, 4, 0, 1, 2))),
         torch.tensor(np.asarray(o.bias)),
         stride=2).permute(0, 2, 3, 4, 1)),
     lambda: [rnd(1, 3, 4, 4, 2, seed=109)]),
]


@pytest.mark.parametrize("case", SWEEP, ids=lambda c: c[0])
def test_layer_sweep_forward_and_grad(case):
    name, make_ours, make_torch, make_inputs = case
    from bigdl_tpu.utils import set_seed
    set_seed(_case_seed(name))
    ours = make_ours().eval_mode()
    tfn = make_torch(ours)
    inputs = make_inputs()
    jx = [jnp.asarray(a) for a in inputs]
    tx = [torch.tensor(a, requires_grad=True) for a in inputs]

    def fwd(args):
        return ours.forward(args[0] if len(args) == 1 else list(args))

    out = fwd(jx)
    tout = tfn(*tx)
    np.testing.assert_allclose(
        np.asarray(out), tout.detach().numpy(), rtol=RTOL, atol=ATOL,
        err_msg=f"{name}: forward")

    # input-gradient oracle: d sum(out^2) / d inputs
    gs = jax.grad(lambda args: jnp.sum(fwd(args) ** 2))(tuple(jx))
    (tout ** 2).sum().backward()
    for i, (g, t) in enumerate(zip(gs, tx)):
        np.testing.assert_allclose(
            np.asarray(g), t.grad.numpy(), rtol=RTOL, atol=ATOL,
            err_msg=f"{name}: grad of input {i}")
