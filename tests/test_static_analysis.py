"""graftlint: the static-analysis suite (bigdl_tpu/analysis).

Per-rule fixture snippets (true positive / true negative / pragma),
suppression + baseline round-trips, an end-to-end run over a temp
package, the zero-error acceptance pin on the shipped tree, and the
compiled-HLO invariants on the 8-fake-device 2-slice mesh — including
the deliberately-unpinned decode reproducing the PR-8 widening
finding."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from bigdl_tpu.analysis import (
    Finding, apply_suppressions, counts_of, load_baseline, load_tree,
    pass_names, render_human, render_json, run_ast_passes,
    write_baseline,
)
from bigdl_tpu.analysis.passes import (
    clock_discipline, collective_discipline, lock_discipline,
    metrics_catalog, thread_lifecycle, trace_safety,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fixture plumbing
# ---------------------------------------------------------------------------

def _mini_repo(tmp_path, files):
    """A throwaway repo: {relpath: source} -> (root, SourceTree)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return load_tree(root=str(tmp_path / "bigdl_tpu"),
                     repo=str(tmp_path))


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


# ---------------------------------------------------------------------------
# framework: findings, registry, pragmas, baseline
# ---------------------------------------------------------------------------

def test_registry_has_every_pass():
    names = pass_names()
    # (collective-axis is a second rule id the collective-discipline
    # pass emits, not a separate registered pass)
    for expected in ("trace-safety", "lock-discipline", "lock-order",
                     "collective-discipline", "clock-discipline",
                     "metrics-catalog", "thread-lifecycle"):
        assert expected in names, names


def test_finding_identity_excludes_line():
    f = Finding("r", "error", "a.py", 42, "m", scope="S.f", code="x = 1")
    assert f.key() == {"rule": "r", "file": "a.py", "scope": "S.f",
                       "code": "x = 1"}
    with pytest.raises(ValueError):
        Finding("r", "fatal", "a.py", 1, "m")


def test_render_json_round_trip():
    f = Finding("r", "error", "a.py", 1, "m")
    doc = json.loads(render_json([f], {"root": "pkg"}))
    assert doc["schema"] == "graftlint_report"
    assert doc["counts"]["error"] == 1
    assert doc["findings"][0]["rule"] == "r"
    assert doc["root"] == "pkg"


def test_pragma_same_line_and_comment_block(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/optim/x.py": """\
        import time
        def f():
            t0 = time.time()
            a = time.time() - t0  # graftlint: disable=clock-discipline -- test
            # graftlint: disable=clock-discipline -- reason wraps
            # over more comment lines before the flagged one
            b = time.time() - t0
            c = time.time() - t0
            return a, b, c
        """})
    findings = clock_discipline.run(tree)
    apply_suppressions(findings, tree, [])
    active = _by_rule(findings, "clock-discipline")
    assert len(active) == 1  # only `c = ...` survives
    assert active[0].code.startswith("c =")
    assert sum(1 for f in findings if f.suppressed == "pragma") == 2


def test_baseline_round_trip_match_stale_and_justification(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/optim/x.py": """\
        import time
        def f():
            t0 = time.time()
            return time.time() - t0
        """})
    findings = clock_discipline.run(tree)
    assert len(findings) == 1
    # a justified entry suppresses; an unjustified one errors; a stale
    # one warns
    entries = [dict(findings[0].key(), justification="known; fine"),
               dict(findings[0].key(), code="nonexistent = 1",
                    justification="paid off")]
    path = write_baseline(entries, str(tmp_path / "base.json"))
    loaded = load_baseline(path)
    assert len(loaded) == 2
    apply_suppressions(findings, tree, loaded, baseline_path=path)
    assert findings[0].suppressed == "baseline"
    stale = _by_rule(findings, "baseline-stale")
    assert len(stale) == 1 and stale[0].severity == "warning"

    findings2 = clock_discipline.run(tree)
    entries2 = [dict(findings2[0].key(), justification="   ")]
    apply_suppressions(findings2, tree, entries2)
    assert findings2[0].suppressed is None  # empty reason: NOT excused
    missing = _by_rule(findings2, "baseline-justification")
    assert len(missing) == 1 and missing[0].severity == "error"


def test_baseline_stale_judged_only_for_ran_rules(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/optim/x.py": "x = 1\n"})
    entry = {"rule": "lock-discipline", "file": "a.py", "scope": "C.m",
             "code": "self.x = 1", "justification": "fine"}
    fs = apply_suppressions([], tree, [entry],
                            ran_rules={"clock-discipline"})
    assert _by_rule(fs, "baseline-stale") == []
    fs = apply_suppressions([], tree, [entry], ran_rules=None)
    assert len(_by_rule(fs, "baseline-stale")) == 1


def test_baseline_malformed_raises(tmp_path):
    p = tmp_path / "b.json"
    p.write_text('{"version": 99, "entries": []}')
    with pytest.raises(ValueError):
        load_baseline(str(p))


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------

def test_trace_safety_positive_negative_and_edge(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/parallel/x.py": """\
        import time
        import random
        import jax
        import helpers

        def helper(x):
            return helpers.unknown(x) + time.time()

        def step(params, x):
            t = time.time()          # positive: clock in traced root
            r = random.random()      # positive: host RNG
            print(x)                 # positive: trace-time print
            v = x.item()             # positive: host sync
            s = float(x)             # positive: float(param) in a ROOT
            return helper(params)    # edge into helper -> its clock too

        step_c = jax.jit(step)

        def not_traced(x):
            return time.time() - 0   # negative: unreachable from roots
        """})
    findings = trace_safety.run(tree)
    msgs = [f.message for f in findings]
    lines = sorted(f.line for f in findings)
    assert any("host clock" in m and "step" in m for m in msgs)
    assert any("host RNG" in m for m in msgs)
    assert any("print()" in m for m in msgs)
    assert any(".item()" in m for m in msgs)
    assert any("float() of parameter" in m for m in msgs)
    # the call edge reached helper's clock read (line 7)
    assert 7 in lines
    # not_traced's time.time is NOT a trace-safety finding
    assert all("not_traced" not in (f.scope or "") for f in findings)


def test_trace_safety_float_of_param_only_in_roots(tmp_path):
    """A transitively-reached helper coercing a (static-config) param
    with float()/int() is NOT flagged — only roots' params are traced
    arrays."""
    tree = _mini_repo(tmp_path, {"bigdl_tpu/parallel/x.py": """\
        import jax

        def cfg_helper(block):
            return int(block)

        def step(x):
            return x * cfg_helper(8)

        step_c = jax.jit(step)
        """})
    assert trace_safety.run(tree) == []


def test_trace_safety_mapped_prim_implicit_root(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/parallel/x.py": """\
        import time
        import jax

        def sync(grads):
            g = jax.lax.psum(grads, "data")
            t = time.time()
            return g, t

        def probe(axis):
            return jax.lax.psum(1, axis)   # size probe: NOT a root
        """})
    findings = trace_safety.run(tree)
    assert len(findings) == 1
    assert "sync" in findings[0].scope


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_LOCK_SRC = """\
    import threading

    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0          # negative: __init__ exempt
            self.name = "x"         # immutable config
            self.items = []

        def bump(self):
            with self._lock:
                self.count += 1
                self.items.append(1)

        def naked_write(self):
            self.count = 5          # positive: guarded attr, no lock

        def naked_read(self):
            return self.count       # positive

        def config_read(self):
            return self.name        # negative: never mutated post-init

        def locked_read(self):
            with self._lock:
                return self.count   # negative

    class Unlocked:
        def __init__(self):
            self.x = 1

        def touch(self):
            self.x += 1             # negative: class owns no lock
    """


def test_lock_discipline_positive_negative(tmp_path):
    tree = _mini_repo(tmp_path,
                      {"bigdl_tpu/telemetry/x.py": _LOCK_SRC})
    findings = lock_discipline.run(tree)
    assert {f.scope for f in findings} == {"Shared.naked_write",
                                           "Shared.naked_read"}
    assert all("count" in f.message for f in findings)


def test_lock_discipline_scoped_to_threaded_packages(tmp_path):
    # the same class in a non-threaded package is out of scope
    tree = _mini_repo(tmp_path, {"bigdl_tpu/nn/x.py": _LOCK_SRC})
    assert lock_discipline.run(tree) == []


def test_lock_discipline_mutator_calls_count_as_writes(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/data/x.py": """\
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.buf = []

            def locked(self):
                with self._lock:
                    return list(self.buf)

            def producer(self):
                self.buf.append(1)   # positive: in-place mutation
        """})
    findings = lock_discipline.run(tree)
    assert len(findings) == 1 and findings[0].scope == "Q.producer"


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

def test_lock_order_cycle_detected_and_single_order_clean(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/serving/x.py": """\
        import threading

        class Deadlocky:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Lock()
                self.n = 0

            def a(self):
                with self._lock:
                    with self._cond:
                        self.n += 1

            def b(self):
                with self._cond:
                    with self._lock:
                        self.n -= 1

        class OneOrder:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Lock()
                self.n = 0

            def a(self):
                with self._lock:
                    with self._cond:
                        self.n += 1

            def c(self):
                with self._lock:
                    self.n += 2        # negative: consistent order
        """})
    findings = lock_discipline.run_order(tree)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "lock-order" and "BOTH orders" in f.message
    assert "Deadlocky._lock" in f.message \
        and "Deadlocky._cond" in f.message


def test_lock_order_same_class_name_across_files_not_conflated(
        tmp_path):
    """Identity is (file, class, attr): two same-named classes in
    different modules nesting in opposite orders is NOT a cycle."""
    half = """\
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Lock()
                self.n = 0

            def f(self):
                with self.{outer}:
                    with self.{inner}:
                        self.n += 1
        """
    tree = _mini_repo(tmp_path, {
        "bigdl_tpu/serving/a.py": half.format(outer="_lock",
                                              inner="_cond"),
        "bigdl_tpu/telemetry/b.py": half.format(outer="_cond",
                                                inner="_lock"),
    })
    assert lock_discipline.run_order(tree) == []


def test_lock_order_cross_class_not_conflated(tmp_path):
    """Locks are identified per class: A._lock->A._cond in one class
    and B._cond->B._lock in another is NOT a cycle."""
    tree = _mini_repo(tmp_path, {"bigdl_tpu/telemetry/x.py": """\
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Lock()
                self.n = 0

            def f(self):
                with self._lock:
                    with self._cond:
                        self.n += 1

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Lock()
                self.n = 0

            def g(self):
                with self._cond:
                    with self._lock:
                        self.n += 1
        """})
    assert lock_discipline.run_order(tree) == []


def test_lock_order_pragma(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/data/x.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Lock()
                self.n = 0

            def a(self):
                with self._lock:
                    with self._cond:
                        self.n += 1

            def b(self):
                with self._cond:
                    # graftlint: disable=lock-order -- b only runs
                    # before the worker thread starts
                    with self._lock:
                        self.n -= 1
        """})
    findings = lock_discipline.run_order(tree)
    apply_suppressions(findings, tree, [])
    # the reported witness is the lexicographically-first edge's inner
    # `with` (C._cond->C._lock, i.e. b's nesting) — the pragma block
    # directly above that line silences the cycle with its reason
    assert len(findings) == 1
    assert findings[0].suppressed == "pragma"


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------

def test_thread_lifecycle_positive_negative_and_pragma(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/serving/x.py": """\
        import threading
        from threading import Thread

        def leak():
            t = threading.Thread(target=print)   # positive
            t.start()

        def ok_daemon():
            threading.Thread(target=print, daemon=True).start()

        def ok_daemon_attr():
            t = Thread(target=print)
            t.daemon = True
            t.start()

        def ok_joined():
            t = Thread(target=print)
            t.start()
            t.join(timeout=1.0)

        def fire_and_forget():
            # graftlint: disable=thread-lifecycle -- process-lifetime
            # worker, reaped by the OS at exit by design
            threading.Thread(target=print).start()

        class Owner:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.start()

            def stop(self):
                self._t.join()

        class Leaky:
            def start(self):
                self._t = threading.Thread(target=print)  # positive
                self._t.start()
        """})
    findings = thread_lifecycle.run(tree)
    apply_suppressions(findings, tree, [])
    active = [f for f in findings if not f.suppressed]
    assert sorted(f.scope for f in active) == ["Leaky.start", "leak"]
    assert all("non-daemon" in f.message for f in active)
    assert sum(1 for f in findings if f.suppressed == "pragma") == 1


def test_thread_lifecycle_annotated_assignment(tmp_path):
    """An annotated `self._t: threading.Thread = Thread(...)` binds
    the target like a plain assignment — joined in stop() passes,
    never-joined is flagged by NAME (not as an unnamed thread)."""
    tree = _mini_repo(tmp_path, {"bigdl_tpu/telemetry/x.py": """\
        import threading

        class Owner:
            def start(self):
                self._t: threading.Thread = threading.Thread(
                    target=print)
                self._t.start()

            def stop(self):
                self._t.join()

        class Leaky:
            def start(self):
                self._t: threading.Thread = threading.Thread(
                    target=print)
                self._t.start()
        """})
    findings = thread_lifecycle.run(tree)
    assert [f.scope for f in findings] == ["Leaky.start"]
    assert "self._t" in findings[0].message


def test_thread_lifecycle_unassigned_thread_flagged(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/data/x.py": """\
        import threading

        def bad():
            threading.Thread(target=print).start()   # positive
        """})
    findings = thread_lifecycle.run(tree)
    assert len(findings) == 1
    assert "unnamed thread" in findings[0].message


def test_thread_lifecycle_module_alias_resolved(tmp_path):
    """`import threading as t; t.Thread(...)` is the same ctor — an
    aliased module import must not evade the lint."""
    tree = _mini_repo(tmp_path, {"bigdl_tpu/serving/x.py": """\
        import threading as t

        def leak():
            t.Thread(target=print).start()     # positive

        def fine():
            t.Thread(target=print, daemon=True).start()
        """})
    findings = thread_lifecycle.run(tree)
    assert [f.scope for f in findings] == ["leak"]


def test_thread_lifecycle_shipped_tree_is_clean():
    """Every one of the framework's Thread sites is daemon or joined
    on its stop path — the triage-to-zero pin (no pragmas needed:
    the PR-2/PR-4 shutdown discipline already covered all ten)."""
    tree = load_tree()
    findings = thread_lifecycle.run(tree)
    apply_suppressions(findings, tree, [])
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(render_human(active))


# ---------------------------------------------------------------------------
# collective-discipline
# ---------------------------------------------------------------------------

def test_collective_discipline_and_axis_rules(tmp_path):
    tree = _mini_repo(tmp_path, {
        "bigdl_tpu/parallel/x.py": """\
        import jax
        from bigdl_tpu.telemetry import collectives as _coll

        def bad(x):
            return jax.lax.psum(x, "data")        # positive: raw

        def size_probe(axis):
            return jax.lax.psum(1, axis)           # negative: probe

        def good(x):
            return _coll.psum(x, "data")           # negative: wrapper

        def typo(x):
            return _coll.all_gather(x, "dcn2")     # positive: bad axis
        """,
        "bigdl_tpu/telemetry/collectives.py": """\
        import jax

        def psum(x, axis_name, **kw):
            return jax.lax.psum(x, axis_name, **kw)  # negative: home
        """,
    })
    findings = collective_discipline.run(tree)
    raw = _by_rule(findings, "collective-discipline")
    axis = _by_rule(findings, "collective-axis")
    assert len(raw) == 1 and raw[0].scope == "bad"
    assert len(axis) == 1 and "dcn2" in axis[0].message


def test_mesh_axes_parsed_from_real_tree():
    from bigdl_tpu.analysis.astutil import mesh_axes
    tree = load_tree()
    assert mesh_axes(tree) == {"dcn", "data", "fsdp", "model", "pipe",
                               "seq", "expert"}


# ---------------------------------------------------------------------------
# clock-discipline
# ---------------------------------------------------------------------------

def test_clock_discipline_taint_paths(tmp_path):
    tree = _mini_repo(tmp_path, {"bigdl_tpu/optim/x.py": """\
        import time

        class T:
            def __init__(self):
                self.t0 = time.time()

            def up(self):
                return time.time() - self.t0   # positive: attr taint

        def direct():
            return time.time() - 5.0           # positive: direct call

        def local_taint():
            t0 = time.time()
            return 8.0 - t0                    # positive: local taint

        def stamps_only():
            rec = {"time": time.time()}        # negative: timestamp
            return rec

        def perf_ok():
            t0 = time.perf_counter()
            return time.perf_counter() - t0    # negative: trace clock

        def span_stamp(tracing):
            t = time.time()
            tracing.record_span("x", t, t + 1)  # positive: span stamp
        """})
    findings = clock_discipline.run(tree)
    scopes = sorted(f.scope for f in findings)
    assert scopes == ["T.up", "direct", "local_taint", "span_stamp"]
    span = [f for f in findings if f.scope == "span_stamp"][0]
    assert "record_span" in span.message


# ---------------------------------------------------------------------------
# metrics-catalog through the framework
# ---------------------------------------------------------------------------

def test_metrics_catalog_pass_reproduces_zero_zero():
    tree = load_tree()
    findings = metrics_catalog.run(tree)
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity == "warning"]
    assert errors == [], render_human(errors)
    assert warnings == [], render_human(warnings)


def test_metrics_lint_shim_still_passes():
    out = subprocess.run(
        [sys.executable, "scripts/metrics_lint.py"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "metrics_lint: OK (0 issue(s), 0 warning(s))" in out.stdout


# ---------------------------------------------------------------------------
# end-to-end: temp package + the shipped tree
# ---------------------------------------------------------------------------

def test_e2e_temp_package_all_passes(tmp_path):
    tree = _mini_repo(tmp_path, {
        "bigdl_tpu/telemetry/bad.py": """\
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked(self):
                with self._lock:
                    self.n += 1

            def naked(self):
                self.n = 2

        def dur():
            t0 = time.time()
            return time.time() - t0
        """,
        "docs/observability.md": """\
        ## Span inventory

        | span | where |
        |------|-------|
        | `optimizer/step` | the loop |
        """,
    })
    tree, findings = run_ast_passes(tree)
    apply_suppressions(findings, tree, [])
    counts = counts_of(findings)
    rules = {f.rule for f in findings if not f.suppressed}
    assert counts["error"] >= 2
    assert {"lock-discipline", "clock-discipline"} <= rules
    # parse errors are findings, not crashes
    (tmp_path / "bigdl_tpu" / "broken.py").write_text("def oops(:\n")
    tree2 = load_tree(root=str(tmp_path / "bigdl_tpu"),
                      repo=str(tmp_path))
    assert [f.rule for f in tree2.parse_findings] == ["parse-error"]


def test_shipped_tree_is_zero_error_acceptance():
    """THE acceptance pin: zero unsuppressed findings across all AST
    passes on the shipped tree, every suppression carrying its reason
    (pragma text or baseline justification)."""
    tree, findings = run_ast_passes()
    baseline = load_baseline()
    apply_suppressions(findings, tree, baseline)
    active = [f for f in findings
              if not f.suppressed and f.severity == "error"]
    assert active == [], "\n".join(render_human(active))
    # every baseline entry justifies itself
    assert all(str(e.get("justification", "")).strip()
               for e in baseline)


def test_cli_fatal_vs_warn_only(tmp_path):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "bad.py").write_text(
        "import time\n\ndef f():\n"
        "    t0 = time.time()\n    return time.time() - t0\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base = [sys.executable, "-m", "bigdl_tpu.analysis",
            str(tmp_path / "pkg"), "--no-baseline"]
    fatal = subprocess.run(base, capture_output=True, text=True,
                           cwd=REPO, env=env)
    assert fatal.returncode == 1, fatal.stdout + fatal.stderr
    assert "clock-discipline" in fatal.stdout
    report = tmp_path / "report.json"
    warn = subprocess.run(base + ["--warn-only", "--json", str(report)],
                          capture_output=True, text=True, cwd=REPO,
                          env=env)
    assert warn.returncode == 0, warn.stdout + warn.stderr
    doc = json.loads(report.read_text())
    assert doc["schema"] == "graftlint_report"
    assert doc["counts"]["error"] >= 1


# ---------------------------------------------------------------------------
# compiled-HLO passes (8-fake-device, 2-slice mesh)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def hlo_programs():
    from bigdl_tpu.analysis.hlo_lint import _Programs
    return _Programs()


def test_hlo_flat_step_clean(hlo_programs):
    from bigdl_tpu.analysis.hlo_lint import _check_cross_slice
    assert _check_cross_slice(hlo_programs) == []


def test_hlo_ratio_and_fast_tier_hold(hlo_programs):
    from bigdl_tpu.analysis.hlo_lint import (
        _check_dcn_ratio, _check_fast_tier,
    )
    ratio = _check_dcn_ratio(hlo_programs)
    assert [f for f in ratio if f.severity == "error"] == [], \
        render_human(ratio)
    assert [f.severity for f in ratio] == ["info"]
    assert _check_fast_tier(hlo_programs) == []


def test_hlo_int8_step_narrow_on_wire(hlo_programs):
    from bigdl_tpu.analysis.hlo_lint import (
        _check_narrow_wire, narrow_wire_report,
    )
    assert _check_narrow_wire(hlo_programs) == []
    rep = narrow_wire_report(hlo_programs.compiled("dcn-hier-int8"),
                             hlo_programs.slice_map("dcn-flat"))
    assert rep["narrow_bytes"] > 0
    assert rep["wide_fraction"] <= 0.25


def test_hlo_donation_elides_param_copy(hlo_programs):
    from bigdl_tpu.analysis.hlo_lint import _check_donation
    findings = _check_donation(hlo_programs)
    assert [f.severity for f in findings] == ["info"], \
        render_human(findings)


def test_hlo_no_host_callbacks(hlo_programs):
    from bigdl_tpu.analysis.hlo_lint import _check_host_callback
    findings = _check_host_callback(hlo_programs)
    assert all(f.severity == "info" for f in findings), \
        render_human(findings)


def test_hlo_unpinned_decode_reproduces_widening(monkeypatch):
    """Acceptance: removing the optimization-barrier pin (the
    BIGDL_TPU_UNPIN_DCN_WIRE seam compiles the decode-above-the-
    exchange program the PR-8 hoist produced) FAILS the narrow-wire
    pass loudly — and the byte-ratio pin catches it independently."""
    from bigdl_tpu.analysis.hlo_lint import (
        _Programs, _check_dcn_ratio, _check_narrow_wire,
    )
    monkeypatch.setenv("BIGDL_TPU_UNPIN_DCN_WIRE", "1")
    progs = _Programs()
    narrow = _check_narrow_wire(progs)
    assert len(narrow) == 1 and narrow[0].severity == "error"
    assert "widened" in narrow[0].message
    ratio_errors = [f for f in _check_dcn_ratio(progs)
                    if f.severity == "error"
                    and f.scope == "dcn-hier-int8"]
    assert len(ratio_errors) == 1


def test_donated_alias_bytes_parser_units():
    from bigdl_tpu.analysis.hlo_lint import donated_alias_bytes
    text = ("HloModule m, input_output_alias={ {0}: (0, {}, may-alias), "
            "{1}: (2, {}, may-alias) }, entry_computation_layout="
            "{(f32[4,2]{1,0}, s32[]{:T(1)}, f32[8]{0})->(f32[4,2])}, "
            "other=x\n")
    total, n = donated_alias_bytes(text)
    assert n == 2
    assert total == 4 * 2 * 4 + 8 * 4  # params 0 and 2
    assert donated_alias_bytes("no alias here") == (0.0, 0)
