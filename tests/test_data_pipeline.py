"""bigdl_tpu.data — deterministic, checkpointable input pipeline.

Covers the determinism contract (epoch-keyed orders, global remix for
DistributedDataSet, independent transform() siblings), PipelineState
persistence through the CheckpointManager manifest, sample-accurate
crash/SIGTERM resume (the consumed sequence across crash+resume equals
the uninterrupted run's — proven by per-iteration loss equality, which
any replayed or skipped batch would break), weighted mixing with a
checkpointable sampler, async device prefetch (overlap + unchanged
semantics + off-by-default inertness), and the stall-pipeline chaos
fault tripping the data-starvation watchdog.
"""

import json
import os
import signal
import time

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.data import (
    DevicePrefetch, MixedDataSet, PipelineState, skip_batches,
)
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import (
    DeviceCachedDataSet, DistributedDataSet, LocalDataSet, Sample,
    epoch_permutation,
)
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.optim import Optimizer, Trigger
from bigdl_tpu.optim.methods import SGD
from bigdl_tpu.utils import chaos, set_seed
from bigdl_tpu.utils.file import (
    CheckpointManager, load_pipeline_state, pipeline_state_path,
)


@pytest.fixture(autouse=True)
def _chaos_reset():
    chaos.reset()
    yield
    chaos.reset()


def _indexed_samples(n=32, dim=6, classes=4):
    """Sample i's feature is the constant i — batch contents identify
    the global indices they came from."""
    return [Sample(np.full((dim,), i, np.float32), (i % classes) + 1)
            for i in range(n)]


def _model(dim=6, classes=4):
    return nn.Sequential(nn.Linear(dim, 8), nn.ReLU(),
                         nn.Linear(8, classes), nn.LogSoftMax())


class _RecordBatches(Transformer):
    """Terminal stage logging each pulled batch's sample indices into a
    shared list (one list per test run)."""

    def __init__(self, log):
        self.log = log

    def apply(self, it):
        for b in it:
            self.log.append(tuple(int(v)
                                  for v in np.asarray(b.input)[:, 0]))
            yield b


class _LossLog:
    """train_summary stub capturing per-iteration losses by neval."""

    def __init__(self):
        self.losses = {}

    def add_scalar(self, name, value, step):
        if name == "Loss":
            self.losses[step] = value

    def flush(self):
        pass


def _pipeline(samples, batch=8, log=None):
    ds = DataSet.array(samples).transform(SampleToMiniBatch(batch))
    if log is not None:
        ds = ds.transform(_RecordBatches(log))
    return ds


def _fast_retry(opt, times=3):
    return opt.set_failure_retry(times, interval_s=300,
                                 backoff_s=0.01, backoff_cap_s=0.02)


# --------------------------------------------------------------------------
# determinism contract
# --------------------------------------------------------------------------

class TestDeterministicIteration:
    def test_epoch_permutation_is_pure(self):
        a = epoch_permutation(100, 7, 3)
        b = epoch_permutation(100, 7, 3)
        np.testing.assert_array_equal(a, b)
        assert list(a) != list(epoch_permutation(100, 7, 4))
        assert list(a) != list(epoch_permutation(100, 8, 3))
        assert sorted(a) == list(range(100))

    def test_two_runs_consume_identical_orders(self):
        set_seed(21)
        data = _indexed_samples(16)
        runs = []
        for _ in range(2):
            ds = DataSet.array(data)
            runs.append([[s.feature[0] for s in ds.data(True, epoch=e)]
                         for e in (1, 2, 3)])
        assert runs[0] == runs[1]
        assert runs[0][0] != runs[0][1]  # epochs actually remix

    def test_distributed_shards_remix_and_stay_disjoint(self):
        """Each epoch: per-host shards partition the GLOBAL index space
        (consistent + non-overlapping), and a host's shard changes
        between epochs — the reference's per-epoch global reshuffle,
        not a frozen round-robin shard shuffled locally."""
        set_seed(33)
        data = _indexed_samples(24)
        per_epoch = {}
        for e in (1, 2):
            shards = []
            for p in range(3):
                ds = DistributedDataSet(data, process_index=p,
                                        process_count=3)
                shards.append([int(s.feature[0])
                               for s in ds.data(True, epoch=e)])
            flat = sum(shards, [])
            assert sorted(flat) == list(range(24))  # disjoint cover
            per_epoch[e] = shards
        # remix: at least one host sees a different SET of samples
        assert any(set(per_epoch[1][p]) != set(per_epoch[2][p])
                   for p in range(3))

    def test_unshuffled_distributed_keeps_round_robin(self):
        ds = DistributedDataSet(_indexed_samples(10), shuffle=False,
                                process_index=1, process_count=4)
        assert [int(s.feature[0]) for s in ds.data(train=False)] \
            == [1, 5, 9]
        assert ds.size() == 10

    def test_transform_siblings_have_independent_streams(self):
        """Regression: transform() shallow copies used to share one
        mutable RNG, so a sibling's iteration order depended on how
        many draws the other copy had made."""
        set_seed(13)
        base = DataSet.array(_indexed_samples(16))
        a = base.transform(SampleToMiniBatch(4))
        b = base.transform(SampleToMiniBatch(4))
        b_expected = [tuple(np.asarray(x.input)[:, 0])
                      for x in b.data(True, epoch=0)]
        # burn several draws on sibling a ...
        for _ in range(3):
            list(a.data(True))
        # ... b's next epoch-0 pass is unchanged
        fresh = DataSet.array(_indexed_samples(16)) \
            .transform(SampleToMiniBatch(4))
        got = [tuple(np.asarray(x.input)[:, 0])
               for x in fresh.data(True, epoch=0)]
        assert got == b_expected

    def test_shuffle_does_not_mutate_shared_data_list(self):
        """Regression: shuffle() used to reorder the _data list in
        place, silently reordering every transform() sibling."""
        set_seed(13)
        ds = DataSet.array(_indexed_samples(8))
        sibling = ds.transform(SampleToMiniBatch(4))
        before = [int(s.feature[0]) for s in ds._data]
        ds.shuffle()
        assert [int(s.feature[0]) for s in ds._data] == before
        assert sibling._data is ds._data  # still shared, still intact


# --------------------------------------------------------------------------
# DeviceCachedDataSet per-mode cache (satellite regression)
# --------------------------------------------------------------------------

class TestDeviceCachePerMode:
    def test_train_first_does_not_poison_eval(self):
        """Regression: the HBM cache was built from the FIRST call's
        train flag and then served for the other mode — a train-first
        call permanently served shuffled batches to evaluation."""
        set_seed(29)
        inner = _pipeline(_indexed_samples(16), batch=4)
        cached = DeviceCachedDataSet(inner)
        train_first = [tuple(np.asarray(b.get_input())[:, 0])
                       for b in cached.data(train=True)]
        eval_batches = [tuple(np.asarray(b.get_input())[:, 0])
                        for b in cached.data(train=False)]
        # eval serves the unshuffled natural order, whatever train did
        assert eval_batches == [(0, 1, 2, 3), (4, 5, 6, 7),
                                (8, 9, 10, 11), (12, 13, 14, 15)]
        assert sorted(sum(train_first, ())) == list(range(16))

    def test_train_cache_reshuffles_deterministically(self):
        set_seed(29)
        cached = DeviceCachedDataSet(_pipeline(_indexed_samples(16),
                                               batch=4))
        e1 = [tuple(np.asarray(b.get_input())[:, 0])
              for b in cached.data(True, epoch=1)]
        e1b = [tuple(np.asarray(b.get_input())[:, 0])
               for b in cached.data(True, epoch=1)]
        e2 = [tuple(np.asarray(b.get_input())[:, 0])
              for b in cached.data(True, epoch=2)]
        assert e1 == e1b and e1 != e2


# --------------------------------------------------------------------------
# PipelineState persistence (CheckpointManager manifest)
# --------------------------------------------------------------------------

class TestPipelineStatePersistence:
    def _save(self, tmp_path, mgr, gen, pipeline):
        return mgr.save({"params": {"w": np.ones((2,))}, "buffers": {}},
                        [{"t": np.asarray(gen)}], {"epoch": gen},
                        generation=gen, pipeline_state=pipeline)

    def test_snapshot_restore_roundtrip(self):
        ps = PipelineState(seed=7, epoch=3, offset=5,
                           sampler={"kind": "weighted_mixing"})
        snap = ps.snapshot()
        back = PipelineState.restore(json.loads(json.dumps(snap)))
        assert (back.seed, back.epoch, back.offset) == (7, 3, 5)
        assert back.sampler == {"kind": "weighted_mixing"}
        with pytest.raises(ValueError, match="version"):
            PipelineState.restore({**snap, "version": 99})

    def test_sidecar_written_and_crcd_in_manifest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        ps = PipelineState(seed=1, epoch=2, offset=3).snapshot()
        path = self._save(tmp_path, mgr, 4, ps)
        side = pipeline_state_path(path)
        assert os.path.isfile(side)
        assert load_pipeline_state(path) == ps
        man = next(m for m in mgr._manifests()
                   if m["generation"] == 4)
        assert man["pipeline"]["file"].endswith(".pipeline.json")
        assert man["pipeline"]["crc32"] is not None
        assert mgr.validate(man)

    def test_torn_sidecar_fails_validation_and_walks_back(self,
                                                          tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        ps = PipelineState(seed=1, epoch=1, offset=1).snapshot()
        p1 = self._save(tmp_path, mgr, 1, ps)
        p2 = self._save(tmp_path, mgr, 2, ps)
        with open(pipeline_state_path(p2), "w") as f:
            f.write('{"torn": tru')  # torn write
        assert mgr.latest_good() == p1

    def test_gc_sweeps_pipeline_sidecars(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_n=1)
        for g in (1, 2, 3):
            last = self._save(
                tmp_path, mgr,
                g, PipelineState(seed=0, epoch=g, offset=0).snapshot())
        names = os.listdir(tmp_path)
        assert sum(n.endswith(".pipeline.json") for n in names) == 1
        assert load_pipeline_state(last)["epoch"] == 3

    def test_missing_sidecar_is_none_not_crash(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        path = self._save(tmp_path, mgr, 1, None)
        assert load_pipeline_state(path) is None


# --------------------------------------------------------------------------
# sample-accurate resume (the acceptance scenario)
# --------------------------------------------------------------------------

def _train(dataset, *, summary=None, epochs=3, ckpt=None,
           ckpt_trigger=None, retry=False, seed=17):
    set_seed(seed)
    opt = (Optimizer(_model(), dataset, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(epochs)))
    if summary is not None:
        opt.set_train_summary(summary)
    if ckpt is not None:
        opt.set_checkpoint(str(ckpt),
                           ckpt_trigger or Trigger.several_iteration(1))
    if retry:
        _fast_retry(opt)
    return opt


class TestSampleAccurateResume:
    def test_crash_mid_epoch_resumes_at_exact_next_batch(self, tmp_path):
        """Chaos crash at iteration 6 (mid epoch 2 of 4-iteration
        epochs), checkpoints every iteration.  The consumed sequence
        across crash+resume must equal the uninterrupted run's: every
        iteration's loss matches (a replayed or skipped batch would
        shift the data order and break it), the resumed epoch's pull
        order matches, and the final driver state is identical."""
        clean_losses, clean_pulls = _LossLog(), []
        clean = _train(_pipeline(_indexed_samples(), log=clean_pulls),
                       summary=clean_losses)
        clean.optimize()

        from bigdl_tpu.telemetry import events as te
        te.reset_events()
        faulty_losses, faulty_pulls = _LossLog(), []
        chaos.install(fail_at_step=6)
        faulty = _train(_pipeline(_indexed_samples(), log=faulty_pulls),
                        summary=faulty_losses, ckpt=tmp_path,
                        retry=True)
        faulty.optimize()

        for key in ("epoch", "neval", "records"):
            assert faulty.state[key] == clean.state[key], key
        # no replayed, no skipped samples: losses agree per iteration
        assert set(faulty_losses.losses) == set(clean_losses.losses)
        for step, v in clean_losses.losses.items():
            assert faulty_losses.losses[step] == pytest.approx(
                v, abs=1e-6), f"iteration {step} diverged"
        # the flight recorder carries the pipeline lifecycle
        kinds = te.event_counts()
        assert kinds.get("pipeline_snapshot", 0) > 0
        assert kinds.get("pipeline_restore", 0) >= 1

    def test_resumed_epoch_pull_order_matches_uninterrupted(
            self, tmp_path):
        """The resumed run rebuilds the SAME epoch order the crashed
        run was consuming: its pulls for the interrupted epoch equal
        the uninterrupted run's pulls for that epoch (the first
        ``offset`` of them as skip-replay, the rest stepped)."""
        clean_pulls = []
        clean = _train(_pipeline(_indexed_samples(), log=clean_pulls),
                       epochs=2)
        clean.optimize()
        epoch2_clean = clean_pulls[4:8]  # 4 iters/epoch

        chaos.install(fail_at_step=6)  # 2 batches into epoch 2
        faulty_pulls = []
        faulty = _train(_pipeline(_indexed_samples(), log=faulty_pulls),
                        epochs=2, ckpt=tmp_path, retry=True)
        faulty.optimize()
        # pulls: epoch1(4) + epoch2 pre-crash(2; the second pulled but
        # never stepped) + resumed epoch2 replay-from-checkpoint:
        # 1 skip-replay + 3 live = the full epoch again
        assert faulty_pulls[:4] == clean_pulls[:4]
        assert faulty_pulls[-4:] == epoch2_clean
        assert faulty.state["neval"] == clean.state["neval"]

    def test_sigterm_preemption_resume_sample_accurate(self, tmp_path):
        """SIGTERM mid-epoch → final checkpoint at the step boundary
        with the PipelineState offset; a fresh optimizer resumes at the
        exact next batch and finishes with the uninterrupted run's
        driver state and per-iteration losses — the fault_tolerance.md
        'resume replays the unfinished epoch' caveat is gone."""
        clean_losses = _LossLog()
        clean = _train(_pipeline(_indexed_samples()),
                       summary=clean_losses, seed=19)
        clean.optimize()

        class KillOnce(Transformer):
            def __init__(self):
                self.batches = 0

            def apply(self, it):
                for b in it:
                    self.batches += 1
                    if self.batches == 6:  # mid epoch 2
                        os.kill(os.getpid(), signal.SIGTERM)
                    yield b

        set_seed(19)
        leg1_losses = _LossLog()
        ds = _pipeline(_indexed_samples()).transform(KillOnce())
        opt = _train(ds, summary=leg1_losses, seed=19, ckpt=tmp_path,
                     ckpt_trigger=Trigger.every_epoch())
        opt.optimize()
        assert opt.preempted
        assert opt.state["epoch"] == 2  # unfinished epoch not advanced

        ckpt = CheckpointManager(str(tmp_path)).latest_good()
        ps = load_pipeline_state(ckpt)
        assert ps is not None and ps["epoch"] == 2 and ps["offset"] > 0

        leg2_losses = _LossLog()
        set_seed(19)
        opt2 = (Optimizer(_model(), _pipeline(_indexed_samples()),
                          nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_epoch(3))
                .set_train_summary(leg2_losses)
                .resume(ckpt))
        opt2.optimize()
        assert not opt2.preempted
        for key in ("epoch", "neval", "records"):
            assert opt2.state[key] == clean.state[key], key
        merged = {**leg1_losses.losses, **leg2_losses.losses}
        assert set(merged) == set(clean_losses.losses)
        for step, v in clean_losses.losses.items():
            assert merged[step] == pytest.approx(v, abs=1e-6), \
                f"iteration {step} diverged"

    def test_stale_sidecar_generation_mismatch_replays_epoch(
            self, tmp_path, caplog):
        """Overwrite-mode crash window: the previous generation's
        sidecar next to a newer payload must NOT be applied (its offset
        would skip the wrong batches); restore detects the generation
        mismatch and falls back to epoch-start replay."""
        opt = _train(_pipeline(_indexed_samples()), epochs=2,
                     ckpt=tmp_path)
        opt.optimize()
        path = CheckpointManager(str(tmp_path)).latest_good()
        side = pipeline_state_path(path)
        with open(side) as f:
            ps = json.load(f)
        ps["generation"] -= 1  # sidecar from one commit earlier
        ps["offset"] = max(ps.get("offset", 1), 1)
        with open(side, "w") as f:
            json.dump(ps, f)
        set_seed(17)
        opt2 = (Optimizer(_model(), _pipeline(_indexed_samples()),
                          nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_epoch(3))
                .resume(path))
        with caplog.at_level("WARNING", logger="bigdl_tpu.optim"):
            opt2.optimize()
        assert opt2.state["epoch"] == 4
        assert any("stale sidecar" in r.message for r in caplog.records)

    def test_resume_without_sidecar_replays_epoch_start(self, tmp_path):
        """A pre-pipeline checkpoint (no sidecar) must resume exactly
        as before: replay the unfinished epoch from its start."""
        chaos.install(fail_at_step=6)
        pulls = []
        opt = _train(_pipeline(_indexed_samples(), log=pulls),
                     epochs=2, ckpt=tmp_path, retry=True)
        # strip every sidecar as soon as it is written
        real_save = CheckpointManager.save

        def save_no_sidecar(self, *a, **kw):
            kw["pipeline_state"] = None
            return real_save(self, *a, **kw)

        CheckpointManager.save = save_no_sidecar
        try:
            opt.optimize()
        finally:
            CheckpointManager.save = real_save
        # epoch 2 was replayed in full: its 4 batches appear twice
        # (once pre-crash partially, once fully after resume)
        assert opt.state["epoch"] == 3
        assert len(pulls) > 8  # strictly more pulls than a clean run


# --------------------------------------------------------------------------
# weighted mixing
# --------------------------------------------------------------------------

class TestMixedDataSet:
    def _corpora(self):
        a = DataSet.array([Sample(np.zeros((6,), np.float32), 1)
                           for _ in range(8)], shuffle=False)
        b = DataSet.array([Sample(np.ones((6,), np.float32), 2)
                           for _ in range(8)], shuffle=False)
        return a, b

    def test_deterministic_weighted_interleave(self):
        a, b = self._corpora()
        m = MixedDataSet([a, b], weights=[3, 1], seed=5)
        e1 = [s.label for s in m.data(True, epoch=1)]
        assert e1 == [s.label for s in m.data(True, epoch=1)]
        assert e1 != [s.label for s in m.data(True, epoch=2)]
        assert len(e1) == 16 and m.size() == 16
        share = sum(1 for x in e1 if x == 1) / len(e1)
        assert share > 0.5  # the weight-3 corpus dominates

    def test_small_corpus_cycles_with_reshuffle(self):
        small = DataSet.array(_indexed_samples(4))
        big = DataSet.array(_indexed_samples(32))
        set_seed(3)
        m = MixedDataSet([small, big], weights=[1, 1], seed=3,
                         items_per_epoch=24)
        items = list(m.data(True, epoch=1))
        assert len(items) == 24  # small corpus wrapped, stream endless

    def test_sampler_restore_rejects_changed_mixture(self):
        a, b = self._corpora()
        st = MixedDataSet([a, b], weights=[3, 1], seed=5).sampler_state()
        MixedDataSet([a, b], weights=[3, 1], seed=5).restore_sampler(st)
        with pytest.raises(ValueError, match="weights"):
            MixedDataSet([a, b], weights=[1, 1],
                         seed=5).restore_sampler(st)
        with pytest.raises(ValueError, match="seed"):
            MixedDataSet([a, b], weights=[3, 1],
                         seed=6).restore_sampler(st)
        with pytest.raises(ValueError, match="corpora"):
            MixedDataSet([a], weights=[1], seed=5).restore_sampler(st)

    def test_sharded_mixture_yields_per_process_share(self):
        """Regression: with per-process-sharded children, each host
        must yield size()/process_count items per epoch — serving the
        global count would consume every sample process_count times.
        All hosts draw the same child-choice sequence, so global
        batches stay consistent."""
        data_a = _indexed_samples(16)
        data_b = [Sample(np.full((6,), 100 + i, np.float32), 1)
                  for i in range(16)]
        per_host = []
        for p in range(2):
            a = DistributedDataSet(data_a, shuffle=False,
                                   process_index=p, process_count=2)
            b = DistributedDataSet(data_b, shuffle=False,
                                   process_index=p, process_count=2)
            m = MixedDataSet([a, b], weights=[1, 1], seed=9)
            assert m.size() == 32  # global, like DistributedDataSet
            items = list(m.data(True, epoch=1))
            assert len(items) == 16  # this host's share, not global
            per_host.append(items)
        # same choice sequence on every host: draw t picked the same
        # child (features < 100 = child a, >= 100 = child b)
        kinds = [[int(s.feature[0]) >= 100 for s in items]
                 for items in per_host]
        assert kinds[0] == kinds[1]
        # and the hosts served disjoint rows of each child
        got0 = {int(s.feature[0]) for s in per_host[0]}
        got1 = {int(s.feature[0]) for s in per_host[1]}
        assert not (got0 & got1)

    def test_sharded_child_smaller_than_process_count_rejected(self):
        """A corpus with fewer samples than processes leaves some
        hosts' shards empty — rejected at construction, not as a
        mid-epoch crash on one host while the others wedge in a
        collective."""
        tiny = DistributedDataSet(_indexed_samples(1), shuffle=False,
                                  process_index=0, process_count=2)
        big = DistributedDataSet(_indexed_samples(16), shuffle=False,
                                 process_index=0, process_count=2)
        with pytest.raises(ValueError, match="shards would be empty"):
            MixedDataSet([tiny, big], weights=[1, 1], seed=2)

    def test_mixed_sampler_state_rides_in_checkpoint(self, tmp_path):
        a, b = self._corpora()
        m = MixedDataSet([a, b], weights=[3, 1], seed=5) \
            .transform(SampleToMiniBatch(8))
        set_seed(5)
        opt = (Optimizer(_model(), m, nn.ClassNLLCriterion())
               .set_optim_method(SGD(0.1))
               .set_end_when(Trigger.max_epoch(1))
               .set_checkpoint(str(tmp_path),
                               Trigger.several_iteration(1)))
        opt.optimize()
        ckpt = CheckpointManager(str(tmp_path)).latest_good()
        ps = load_pipeline_state(ckpt)
        assert ps["sampler"]["kind"] == "weighted_mixing"
        assert ps["sampler"]["children"] == 2


# --------------------------------------------------------------------------
# async device prefetch
# --------------------------------------------------------------------------

class TestDevicePrefetch:
    def test_batch_n_plus_1_device_resident_before_n_drained(self):
        """The overlap demonstration: with the consumer holding batch N
        (step N conceptually still running — its result undrained), the
        producer has already staged batch N+1 (and N+2) into device
        memory."""
        import jax
        from bigdl_tpu.dataset.dataset import MiniBatch
        from bigdl_tpu.parallel.mesh import MeshConfig, batch_sharding
        mesh = MeshConfig(data=-1).build()
        sh = batch_sharding(mesh)
        batches = [MiniBatch(np.full((8, 6), i, np.float32),
                             np.ones((8,), np.int64)) for i in range(6)]
        it = DevicePrefetch(2, sharding=sh).apply(iter(batches))
        b0 = next(it)  # "step 0 running"; nothing else consumed
        deadline = time.time() + 10
        while it.occupancy() < 2 and time.time() < deadline:
            time.sleep(0.005)
        assert it.occupancy() >= 2, \
            "batch N+1 was not staged while batch N was outstanding"
        assert isinstance(b0.get_input(), jax.Array)
        assert b0.get_input().sharding == sh  # already mesh-sharded
        rest = list(it)
        assert len(rest) == 5 and it.staged_total == 6
        np.testing.assert_array_equal(
            np.asarray(rest[0].get_input())[:, 0], np.full((8,), 1.0))

    def test_prefetch_on_off_identical_losses(self):
        def run(dp):
            log = _LossLog()
            opt = _train(_pipeline(_indexed_samples()), summary=log,
                         epochs=2, seed=23)
            if dp:
                opt.set_device_prefetch(2)
            opt.optimize()
            return log.losses

        assert run(False) == run(True)

    def test_prefetch_closed_on_crash_and_retry(self, tmp_path):
        """Regression: an exception escaping the epoch loop must close
        the active prefetcher (its producer thread would otherwise
        spin forever holding device-resident batches, one leak per
        retry) — and the crash+retry run still matches the clean run's
        final driver state."""
        clean = _train(_pipeline(_indexed_samples()), epochs=3, seed=25)
        clean.optimize()

        chaos.install(fail_at_step=6)
        opt = _train(_pipeline(_indexed_samples()), epochs=3, seed=25,
                     ckpt=tmp_path, retry=True)
        opt.set_device_prefetch(2)
        opt.optimize()
        assert opt._active_dp is None  # crashed attempt's dp closed
        for key in ("epoch", "neval", "records"):
            assert opt.state[key] == clean.state[key], key

    def test_upstream_error_relayed_to_consumer(self):
        def boom():
            yield from _pipeline(_indexed_samples(8)).data(train=False)
            raise RuntimeError("decode failed")

        it = DevicePrefetch(1).apply(boom())
        with pytest.raises(RuntimeError, match="decode failed"):
            list(it)


# --------------------------------------------------------------------------
# off-by-default discipline (PR 3/4 pattern)
# --------------------------------------------------------------------------

class TestOffByDefault:
    def test_unused_subsystem_constructs_nothing_and_stages_as_before(
            self, monkeypatch):
        """With the pipeline subsystem unused: DevicePrefetch is never
        constructed, and the loop performs exactly the per-step host
        transfers it always did — one staging call per batch tensor
        (x and y), nothing more."""
        import bigdl_tpu.data.device_prefetch as dp_mod
        import bigdl_tpu.optim.optimizer as opt_mod

        def forbidden(*a, **k):
            raise AssertionError("DevicePrefetch constructed without "
                                 "set_device_prefetch")

        monkeypatch.setattr(dp_mod.DevicePrefetch, "apply", forbidden)
        stage_calls = []
        real_stage = opt_mod._stage

        def counting_stage(value, sharding=None):
            stage_calls.append(1)
            return real_stage(value, sharding)

        monkeypatch.setattr(opt_mod, "_stage", counting_stage)
        opt = _train(_pipeline(_indexed_samples()), epochs=2, seed=27)
        opt.optimize()
        iters = opt.state["neval"] - 1
        assert len(stage_calls) == 2 * iters  # x + y per step, exactly


# --------------------------------------------------------------------------
# chaos stall-pipeline fault + data-starvation watchdog
# --------------------------------------------------------------------------

class TestStallPipelineFault:
    def test_stall_sleeps_and_bounds(self):
        ctl = chaos.install(stall_pipeline_s=0.03,
                            stall_pipeline_batches=2)
        t0 = time.time()
        for _ in range(4):
            chaos.on_data_batch()
        dt = time.time() - t0
        assert 0.05 <= dt < 0.5
        assert ctl.stalled_batches == 2
        assert sum("stalling input pipeline" in e
                   for e in ctl.events) == 1  # one campaign, one event

    def test_env_driven_stall(self, monkeypatch):
        chaos.reset()
        monkeypatch.setenv("BIGDL_TPU_CHAOS_STALL_PIPELINE_S", "0.5")
        monkeypatch.setenv("BIGDL_TPU_CHAOS_STALL_PIPELINE_BATCHES", "3")
        ctl = chaos.active()
        assert ctl is not None and ctl.stall_pipeline_s == 0.5
        assert ctl.stall_pipeline_batches == 3

    def test_stall_trips_data_starvation_detector(self):
        """End-to-end: the injected pipeline stall dominates each
        window's wall time, so PR 4's data-starvation detector fires a
        verdict within a short run."""
        from bigdl_tpu.telemetry.health import HealthWatchdog
        chaos.install(stall_pipeline_s=0.05)
        wd = HealthWatchdog(data_starvation="warn",
                            starvation_fraction=0.4,
                            starvation_windows=3)
        opt = _train(_pipeline(_indexed_samples()), epochs=3, seed=31)
        opt.set_health_watchdog(wd)
        opt.optimize()
        assert wd.counts.get("data_starvation", 0) >= 1, wd.counts
        assert not opt.watchdog_halted  # warn policy keeps training
