"""Parallelism-conformance budgets (bigdl_tpu/analysis/hlo_budget).

Unit legs run the checks over synthetic matrices (no compiles); the
real-compile legs pin the committed ``scripts/parallel_budget.json``
against freshly lowered probes — including the PR-8 dcn envelope as
budget data — and the negative legs prove each gate actually fires:
a doubled budget entry trips ``hlo-budget-bytes``, a deliberately
mis-specified sharding rule trips ``hlo-reshard``."""

import json
import os
import subprocess
import sys

import pytest

from bigdl_tpu.analysis.findings import render_human
from bigdl_tpu.analysis.hlo_budget import (
    BUDGET_RULES, PROBES, ProbeSpec, load_budget, probe_matrix,
    run_budget_passes, tree_fingerprint, update_budget, write_budget,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _errors(findings, rule=None):
    return [f for f in findings if f.severity == "error"
            and (rule is None or f.rule == rule)]


def _spec(name="cnn/dp", expected=None, **kw):
    return ProbeSpec(name, *name.split("/", 1),
                     build=lambda: (_ for _ in ()).throw(
                         AssertionError("unit specs never build")),
                     expected=expected or {"data": ("all-reduce",)},
                     **kw)


def _metrics(name="cnn/dp", bytes_=None, **kw):
    out = {"probe": name, "model": name.split("/")[0],
           "composition": name.split("/")[1],
           "mesh_axes": {"data": 8},
           "collective_bytes": bytes_ or {"all-reduce|data": 36528.0},
           "collective_total": 36528.0, "flops": 266881.0,
           "plan_bytes": None, "param_bytes": 36520,
           "donated_bytes": 36524.0, "donated_params": 10,
           "argument_bytes": 38068, "temp_bytes": 78184,
           "output_bytes": 38000}
    out.update(kw)
    return out


def _entry(name="cnn/dp", **kw):
    e = {"probe": name, "tolerance": 0.05,
         "collective_bytes": {"all-reduce|data": 36528.0},
         "flops": 266881.0, "argument_bytes": 38068,
         "temp_bytes": 78184, "donated_bytes": 36524.0,
         "justification": "unit fixture"}
    e.update(kw)
    return e


# ---------------------------------------------------------------------------
# unit legs: the checks over synthetic matrices
# ---------------------------------------------------------------------------

def test_budget_green_when_matrix_matches():
    fs = run_budget_passes(specs={"cnn/dp": _spec()},
                           budget=[_entry()],
                           matrix={"cnn/dp": _metrics()})
    assert _errors(fs) == [], render_human(fs)


def test_doubled_budget_entry_trips_bytes_gate():
    """THE staleness negative leg: a budget entry whose bytes doubled
    (or halved) vs the measured program is a red gate naming the
    offending {op,axis}."""
    doubled = _entry(collective_bytes={"all-reduce|data": 73056.0})
    fs = run_budget_passes(specs={"cnn/dp": _spec()}, budget=[doubled],
                           matrix={"cnn/dp": _metrics()})
    errs = _errors(fs, "hlo-budget-bytes")
    assert len(errs) == 1
    assert "all-reduce|data" in errs[0].message
    assert errs[0].code == "all-reduce|data"


def test_unbudgeted_collective_is_drift():
    m = _metrics(bytes_={"all-reduce|data": 36528.0,
                         "all-gather|data": 50000.0})
    fs = run_budget_passes(specs={"cnn/dp": _spec()}, budget=[_entry()],
                           matrix={"cnn/dp": m})
    assert any("all-gather|data" in f.message
               for f in _errors(fs, "hlo-budget-bytes"))
    # ... and the same unexpected op is a reshard finding too
    assert any("all-gather" in f.message
               for f in _errors(fs, "hlo-reshard"))


def test_scalar_buckets_never_gate():
    m = _metrics(bytes_={"all-reduce|data": 36528.0,
                         "all-reduce|dcn": 4.0})
    fs = run_budget_passes(specs={"cnn/dp": _spec()}, budget=[_entry()],
                           matrix={"cnn/dp": m})
    assert _errors(fs) == [], render_human(fs)


def test_missing_entry_and_empty_justification_and_stale():
    specs = {"cnn/dp": _spec()}
    fs = run_budget_passes(specs=specs, budget=[],
                           matrix={"cnn/dp": _metrics()})
    assert any("no budget entry" in f.message
               for f in _errors(fs, "hlo-budget-bytes"))

    fs = run_budget_passes(specs=specs,
                           budget=[_entry(justification="  ")],
                           matrix={"cnn/dp": _metrics()})
    assert len(_errors(fs, "budget-justification")) == 1

    fs = run_budget_passes(specs=specs,
                           budget=[_entry(), _entry("gone/probe")],
                           matrix={"cnn/dp": _metrics()})
    stale = [f for f in fs if f.rule == "budget-stale"]
    assert len(stale) == 1 and stale[0].severity == "warning"


def test_flops_parity_bound_per_entry():
    specs = {"cnn/dp": _spec(),
             "cnn/fsdp": _spec("cnn/fsdp",
                               expected={"fsdp": ("all-reduce",)},
                               flops_baseline="cnn/dp")}
    matrix = {"cnn/dp": _metrics(),
              "cnn/fsdp": _metrics(
                  "cnn/fsdp", bytes_={"all-reduce|fsdp": 36528.0},
                  mesh_axes={"fsdp": 8}, flops=266881.0 * 4)}
    budget = [_entry(), _entry("cnn/fsdp",
                               collective_bytes={
                                   "all-reduce|fsdp": 36528.0},
                               flops_parity_bound=1.3)]
    fs = run_budget_passes(specs=specs, budget=budget, matrix=matrix)
    errs = _errors(fs, "hlo-flops-parity")
    assert len(errs) == 1 and "4.00x" in errs[0].message
    # raising the entry's bound (with its justification) clears it
    budget[1]["flops_parity_bound"] = 4.5
    fs = run_budget_passes(specs=specs, budget=budget, matrix=matrix)
    assert _errors(fs, "hlo-flops-parity") == []


def test_memory_watermark_and_donation_gates():
    shrunk = _metrics(temp_bytes=78184 * 3)
    fs = run_budget_passes(specs={"cnn/dp": _spec()}, budget=[_entry()],
                           matrix={"cnn/dp": shrunk})
    errs = _errors(fs, "hlo-budget-memory")
    assert len(errs) == 1 and "watermark" in errs[0].message

    lost_donation = _metrics(donated_bytes=0.0)
    fs = run_budget_passes(specs={"cnn/dp": _spec()}, budget=[_entry()],
                           matrix={"cnn/dp": lost_donation})
    assert any("donation" in f.message
               for f in _errors(fs, "hlo-budget-memory"))


def test_reshard_plan_tie_in():
    """Sync bytes wildly over the analytic grad_allreduce_bytes floor
    trip the reshard rule even when the op/axis pair is expected."""
    spec = _spec(plan_check=True)
    m = _metrics(bytes_={"all-reduce|data": 36528.0 * 8},
                 plan_bytes=36520.0)
    e = _entry(collective_bytes={"all-reduce|data": 36528.0 * 8})
    fs = run_budget_passes(specs={"cnn/dp": spec}, budget=[e],
                           matrix={"cnn/dp": m})
    errs = _errors(fs, "hlo-reshard")
    assert len(errs) == 1 and "analytic plan" in errs[0].message


def test_probe_build_failure_is_finding_not_crash():
    fs = run_budget_passes(
        specs={"cnn/dp": _spec()}, budget=[_entry()],
        matrix={"cnn/dp": {"probe": "cnn/dp", "error": "Boom: nope"}})
    errs = _errors(fs, "hlo-budget-bytes")
    assert len(errs) == 1 and "failed to lower" in errs[0].message


def test_budget_file_round_trip_and_malformed(tmp_path):
    p = str(tmp_path / "b.json")
    write_budget([_entry()], p)
    assert load_budget(p)[0]["probe"] == "cnn/dp"
    (tmp_path / "bad.json").write_text('{"version": 99, "entries": []}')
    with pytest.raises(ValueError):
        load_budget(str(tmp_path / "bad.json"))
    (tmp_path / "bad2.json").write_text(
        '{"version": 1, "entries": [{"probe": "x"}]}')
    with pytest.raises(ValueError):
        load_budget(str(tmp_path / "bad2.json"))


def test_update_budget_appends_empty_and_clears_on_drift(tmp_path,
                                                         monkeypatch):
    import bigdl_tpu.analysis.hlo_budget as hb
    p = str(tmp_path / "budget.json")
    specs = {"cnn/dp": _spec(),
             "cnn/new": _spec("cnn/new",
                              expected={"data": ("all-reduce",)})}
    matrix = {"cnn/dp": _metrics(),
              "cnn/new": _metrics("cnn/new")}
    monkeypatch.setattr(hb, "probe_matrix",
                        lambda *a, **kw: matrix)
    # seed: cnn/dp justified but with stale (doubled) bytes
    write_budget([_entry(collective_bytes={"all-reduce|data": 73056.0},
                         justification="was reviewed once")], p)
    path, added, refreshed = update_budget(budget_path=p, specs=specs)
    assert (added, refreshed) == (1, 1)
    entries = {e["probe"]: e for e in load_budget(p)}
    # the new probe landed with an EMPTY justification (gate stays red)
    assert entries["cnn/new"]["justification"] == ""
    # the drifted entry was refreshed AND its justification cleared
    assert entries["cnn/dp"]["collective_bytes"]["all-reduce|data"] \
        == 36528.0
    assert entries["cnn/dp"]["justification"] == ""
    # idempotent second run: nothing to add, nothing drifts... but the
    # empty justifications still gate
    path, added, refreshed = update_budget(budget_path=p, specs=specs)
    assert (added, refreshed) == (0, 0)
    fs = run_budget_passes(specs=specs, budget=load_budget(p),
                           matrix=matrix)
    assert len(_errors(fs, "budget-justification")) == 2


def test_probe_cache_round_trip(tmp_path, monkeypatch):
    """A cached metrics file short-circuits the compile; --no-cache
    recomputes; a corrupt cache entry recomputes instead of crashing."""
    monkeypatch.setenv("BIGDL_TPU_BUDGET_CACHE", str(tmp_path))
    calls = []

    def build():
        calls.append(1)
        raise RuntimeError("would compile here")

    spec = ProbeSpec("unit/p", "unit", "p", build,
                     expected={"data": ("all-reduce",)})
    cdir = tmp_path / "fp-unit"
    cdir.mkdir()
    (cdir / "unit__p.json").write_text(json.dumps(_metrics("unit/p")))
    m = probe_matrix({"unit/p": spec}, fingerprint="fp-unit")
    assert m["unit/p"]["collective_bytes"] == {"all-reduce|data": 36528.0}
    assert calls == []  # never built
    m = probe_matrix({"unit/p": spec}, fingerprint="fp-unit",
                     no_cache=True)
    assert "error" in m["unit/p"] and calls == [1]
    (cdir / "unit__p.json").write_text("{corrupt")
    m = probe_matrix({"unit/p": spec}, fingerprint="fp-unit")
    assert "error" in m["unit/p"] and calls == [1, 1]


def test_tree_fingerprint_tracks_sources():
    fp1 = tree_fingerprint()
    assert fp1 == tree_fingerprint()  # stable on an unchanged tree
    assert len(fp1) == 24


# ---------------------------------------------------------------------------
# real-compile legs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mlp_matrix():
    """Freshly lowered mlp probes (the PR-8 envelope family) — small
    enough to compile inside tier-1."""
    specs = PROBES()
    names = ("mlp/dp", "mlp/dcn_dp", "mlp/dcn_hier_fp32",
             "mlp/dcn_hier_int8")
    sub = {n: specs[n] for n in names}
    return sub, probe_matrix(sub)


def test_committed_budget_holds_for_mlp_probes(mlp_matrix):
    """The committed parallel_budget.json matches freshly lowered
    programs for the envelope family (full-matrix pin is the @slow
    leg + the lint.sh gate)."""
    specs, matrix = mlp_matrix
    budget = load_budget()
    fs = run_budget_passes(specs=specs, budget=[
        e for e in budget if e["probe"] in specs], matrix=matrix)
    assert _errors(fs) == [], render_human(fs)


def test_dcn_envelope_lives_in_budget_not_constants(mlp_matrix):
    """Acceptance: the PR-8 S=2 envelope (cross-slice 25% fp32 / 13%
    int8 of the flat fp32 baseline) is BUDGET DATA — recompute the
    ratios from the committed entries and check the measured programs
    against them."""
    specs, matrix = mlp_matrix
    entries = {e["probe"]: e for e in load_budget()}

    def dcn_bytes(name):
        return sum(v for k, v in entries[name]["collective_bytes"]
                   .items() if k.endswith("|dcn"))

    flat_dcn = dcn_bytes("mlp/dcn_dp")
    assert 0.22 <= dcn_bytes("mlp/dcn_hier_fp32") / flat_dcn <= 0.28, \
        "25.1% measured at S=2"
    assert 0.10 <= dcn_bytes("mlp/dcn_hier_int8") / flat_dcn <= 0.15, \
        "13.1% measured at S=2"
    # and the measured programs agree with the budget they are held to
    for name in ("mlp/dcn_dp", "mlp/dcn_hier_fp32",
                 "mlp/dcn_hier_int8"):
        measured = matrix[name]["collective_bytes"]
        for key, val in entries[name]["collective_bytes"].items():
            assert measured.get(key, 0.0) == pytest.approx(val), (
                name, key)


def test_misspec_rule_trips_reshard(monkeypatch):
    """Acceptance negative leg: a deliberately mis-specified sharding
    rule (params sharded over the batch axis, composition declaring
    pure dp) makes GSPMD insert a full-parameter all-gather — and
    hlo-reshard names it."""
    monkeypatch.setenv("BIGDL_TPU_BUDGET_MISSPEC", "1")
    specs = PROBES()
    assert "cnn/misspec_dp" in specs
    spec = specs["cnn/misspec_dp"]
    matrix = probe_matrix({"cnn/misspec_dp": spec})
    fs = run_budget_passes(specs={"cnn/misspec_dp": spec}, budget=[],
                           matrix=matrix)
    errs = _errors(fs, "hlo-reshard")
    assert errs, render_human(fs)
    assert any("all-gather" in f.message and "'data'" in f.message
               for f in errs)
    # negative probes are exempt from the budget-entry requirement
    assert _errors(fs, "hlo-budget-bytes") == []


@pytest.mark.slow
def test_full_matrix_zero_error_acceptance():
    """THE acceptance pin: the complete probe catalog vs the committed
    budget, zero errors, every entry justified (what `scripts/lint.sh
    --budget` gates on)."""
    fs = run_budget_passes()
    assert _errors(fs) == [], render_human(fs)
    assert all(str(e.get("justification", "")).strip()
               for e in load_budget())


def test_cli_lists_budget_rules():
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis", "--list"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout + out.stderr
    for rule in BUDGET_RULES:
        assert rule in out.stdout


def test_budget_covers_required_span():
    """>= 8 strategy compositions over >= 2 models, every entry
    justified — the coverage floor the ISSUE acceptance names."""
    entries = load_budget()
    comps = {e["probe"].split("/", 1)[1] for e in entries}
    models = {e["probe"].split("/", 1)[0] for e in entries}
    assert len(comps) >= 8, sorted(comps)
    assert len(models) >= 2, sorted(models)
    assert all(str(e.get("justification", "")).strip() for e in entries)
    # and the catalog itself stays in sync with the committed file
    assert {e["probe"] for e in entries} == set(PROBES())
