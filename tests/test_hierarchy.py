"""Hierarchical gradient sync + wire compression on the fake-DCN mesh.

Covers the two-tier story end to end on the 8-virtual-CPU backend as
2 slices × 4 devices: the codecs' error bounds, the
``hierarchical_grad_sync`` schedule's numerics, the Optimizer wiring
(``set_gradient_sync``) including fixed-seed loss equivalence vs the
flat XLA-inserted sync, and the acceptance byte counts read straight
out of the compiled HLO (cross-slice payload ≤ 55% of the flat fp32
baseline under bf16, ≤ 30% under int8; byte-identical HLO with sync
unset).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.parallel.compression import (
    Bf16Codec, Int8Codec, get_codec, wire_bytes, wire_itemsize,
)
from bigdl_tpu.parallel.hierarchy import (
    batch_axes_of, dcn_slice_map, fast_batch_axes_of,
    hierarchical_grad_sync, shard_map,
)
from bigdl_tpu.parallel.mesh import MeshConfig, batch_sharding, make_mesh
from bigdl_tpu.utils.xla_cost import cross_group_hlo_bytes


def _dcn_mesh():
    return make_mesh({"dcn": 2, "data": -1}, jax.devices()[:8])


# ---------------------------------------------------------------------------
# codecs
# ---------------------------------------------------------------------------

def test_bf16_codec_round_trip():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(513,)),
                    jnp.float32)
    c = Bf16Codec()
    out = c.decode(c.encode(x), x.shape[0])
    assert out.dtype == jnp.float32
    # bf16 has 8 mantissa bits: relative error bounded by 2^-8
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               rtol=2 ** -8, atol=1e-30)


def test_int8_codec_error_bound_deterministic():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1000,)) * 3.0, jnp.float32)
    c = Int8Codec(bucket_size=128, stochastic=False)
    out = np.asarray(c.decode(c.encode(x), x.shape[0]))
    assert out.shape == (1000,)
    # per-bucket bound: |err| <= max|bucket|/254 for round-to-nearest
    xs = np.asarray(x)
    pad = (-len(xs)) % 128
    xb = np.pad(xs, (0, pad)).reshape(-1, 128)
    bound = np.abs(xb).max(axis=1) / 254.0 + 1e-7
    err = np.abs(np.pad(out - xs, (0, pad)).reshape(-1, 128))
    assert (err <= bound[:, None]).all(), (err.max(), bound)


def test_int8_codec_stochastic_bound_and_unbiased():
    x = jnp.asarray(np.random.default_rng(2).normal(size=(256,)),
                    jnp.float32)
    c = Int8Codec(bucket_size=256, stochastic=True)
    scale = float(jnp.max(jnp.abs(x))) / 127.0
    outs = np.stack([
        np.asarray(c.decode(c.encode(x, key=jax.random.key(k)), 256))
        for k in range(64)])
    # stochastic floor(v+u): one full quantization step worst case
    assert np.abs(outs - np.asarray(x)).max() <= scale + 1e-7
    # unbiased: averaging across keys converges on the input
    mean_err = np.abs(outs.mean(axis=0) - np.asarray(x)).max()
    assert mean_err < 0.35 * scale, (mean_err, scale)


def test_int8_codec_zero_bucket_stays_zero():
    x = jnp.zeros((512,), jnp.float32)
    c = Int8Codec(bucket_size=64)
    out = np.asarray(c.decode(c.encode(x), 512))
    assert np.isfinite(out).all() and (out == 0).all()


def test_int8_codec_small_vector_clamps_bucket():
    """A shard SMALLER than bucket_size must not be zero-padded up to a
    full bucket — the wire would exceed flat fp32 (the whole point of
    the codec inverted).  The bucket clamps to the vector length."""
    x = jnp.asarray(np.random.default_rng(3).normal(size=(61,)),
                    jnp.float32)
    c = Int8Codec()  # default bucket_size=512 >> 61
    q, scale = c.encode(x)
    wire = q.size * q.dtype.itemsize + scale.size * scale.dtype.itemsize
    assert wire < 61 * 4, (wire, q.shape, scale.shape)
    out = np.asarray(c.decode((q, scale), 61))
    bound = float(jnp.max(jnp.abs(x))) / 254.0 + 1e-7
    assert out.shape == (61,)
    assert np.abs(out - np.asarray(x)).max() <= bound


def test_get_codec_resolution():
    assert get_codec(None) is None
    assert get_codec("fp32") is None
    assert isinstance(get_codec("bf16"), Bf16Codec)
    assert isinstance(get_codec(jnp.bfloat16), Bf16Codec)
    assert isinstance(get_codec("int8"), Int8Codec)
    custom = Int8Codec(bucket_size=64, stochastic=False)
    assert get_codec(custom) is custom
    with pytest.raises(ValueError):
        get_codec("fp8_someday")
    assert wire_itemsize(None) == 4.0
    assert wire_itemsize("bf16") == 2.0
    assert wire_itemsize("int8") == pytest.approx(1.0 + 4.0 / 512)


# ---------------------------------------------------------------------------
# dcn mesh construction + error paths (satellite)
# ---------------------------------------------------------------------------

def test_dcn_mesh_axes_and_batch_sharding():
    mesh = _dcn_mesh()
    assert mesh.axis_names == ("dcn", "data")
    assert mesh.shape["dcn"] == 2 and mesh.shape["data"] == 4
    assert batch_axes_of(mesh) == ("dcn", "data")
    assert fast_batch_axes_of(mesh) == ("data",)
    sh = batch_sharding(mesh)
    assert sh.spec == P(("dcn", "data"))
    sm = dcn_slice_map(mesh)
    assert sorted(sm) == list(range(8))
    assert sorted(set(sm.values())) == [0, 1]
    assert sum(1 for v in sm.values() if v == 0) == 4


def test_meshconfig_accepts_dcn():
    mesh = MeshConfig(dcn=2, data=-1).build()
    assert mesh.shape["dcn"] == 2
    assert mesh.shape["data"] == len(jax.devices()) // 2


def test_make_mesh_rejects_two_wildcards():
    with pytest.raises(ValueError, match="only one mesh axis may be -1"):
        make_mesh({"data": -1, "fsdp": -1})


def test_make_mesh_rejects_non_dividing_wildcard():
    # 8 devices, dcn=3 leaves no integer data extent for the -1
    with pytest.raises(ValueError, match="don't divide"):
        make_mesh({"dcn": 3, "data": -1}, jax.devices()[:8])


def test_make_mesh_rejects_oversized_product():
    with pytest.raises(ValueError, match="exceed device count"):
        make_mesh({"data": 16}, jax.devices()[:8])


def test_make_mesh_unknown_axes_order_after_known():
    """Unknown extra axes append AFTER the canonical AXES, in
    insertion order — the documented ordering contract."""
    mesh = make_mesh({"zeta": 2, "data": 2, "alpha": 2},
                     jax.devices()[:8])
    assert mesh.axis_names == ("data", "zeta", "alpha")


def test_make_mesh_truncation_warns_with_device_ids(caplog):
    import logging
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu.parallel"):
        mesh = make_mesh({"data": 4}, jax.devices()[:8])
    assert int(np.prod(mesh.devices.shape)) == 4
    dropped = [d.id for d in jax.devices()[4:8]]
    msgs = [r.getMessage() for r in caplog.records
            if "dropping device" in r.getMessage()]
    assert msgs, caplog.records
    for did in dropped:
        assert str(did) in msgs[0]


# ---------------------------------------------------------------------------
# hierarchical_grad_sync numerics
# ---------------------------------------------------------------------------

def _sync_stacked(mesh, wire=None, n=97):
    """Run the primitive via shard_map on stacked per-device local
    grads [8, n] (+ a second ragged leaf) and return the synced tree."""
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.normal(size=(8, n)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(8, 3, 5)), jnp.float32)

    def local(av, bv):
        grads = {"a": av.reshape(-1), "b": bv[0]}
        out = hierarchical_grad_sync(grads, mesh, wire_dtype=wire,
                                     rng=jax.random.key(0))
        return out["a"], out["b"]

    fn = jax.jit(shard_map(
        local, mesh,
        in_specs=(P(("dcn", "data")), P(("dcn", "data"))),
        out_specs=(P(), P())))
    oa, ob = fn(a, b)
    return (np.asarray(oa), np.asarray(ob),
            np.asarray(a).mean(axis=0), np.asarray(b).mean(axis=0))


def test_hier_sync_fp32_matches_mean():
    mesh = _dcn_mesh()
    oa, ob, ra, rb = _sync_stacked(mesh)
    np.testing.assert_allclose(oa, ra, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(ob, rb, rtol=1e-6, atol=1e-7)
    assert ob.shape == (3, 5)  # tree structure + shapes survive


def test_hier_sync_bf16_within_tolerance():
    oa, ob, ra, rb = _sync_stacked(_dcn_mesh(), wire="bf16")
    np.testing.assert_allclose(oa, ra, rtol=0, atol=2e-2)
    np.testing.assert_allclose(ob, rb, rtol=0, atol=2e-2)


def test_hier_sync_int8_within_tolerance():
    oa, ob, ra, rb = _sync_stacked(_dcn_mesh(), wire="int8")
    np.testing.assert_allclose(oa, ra, rtol=0, atol=5e-2)
    np.testing.assert_allclose(ob, rb, rtol=0, atol=5e-2)


def test_hier_sync_degenerates_without_dcn_axis():
    """On a dcn-less mesh the schedule collapses to rs+ag — an
    explicit flat mean, numerically exact."""
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    a = jnp.asarray(np.random.default_rng(4).normal(size=(8, 32)),
                    jnp.float32)

    fn = jax.jit(shard_map(
        lambda v: hierarchical_grad_sync({"g": v.reshape(-1)},
                                         mesh)["g"],
        mesh, in_specs=P("data"), out_specs=P()))
    np.testing.assert_allclose(np.asarray(fn(a)),
                               np.asarray(a).mean(axis=0),
                               rtol=1e-6, atol=1e-7)


def test_hier_sync_accounts_dcn_axis_bytes():
    """The dcn hop lands in collective_bytes_total{op, axis="dcn"} at
    trace time through the PR-7 wrappers."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.telemetry import families as tfam
    mesh = _dcn_mesh()
    telemetry.enable()
    telemetry.reset()
    try:
        a = jnp.ones((8, 64), jnp.float32)
        jax.jit(shard_map(
            lambda v: hierarchical_grad_sync(
                {"g": v.reshape(-1)}, mesh, wire_dtype="bf16")["g"],
            mesh, in_specs=P(("dcn", "data")), out_specs=P()),
        ).lower(a).compile()
        dcn_bytes = sum(
            v for (op, ax), v in
            tfam.collective_bytes_total().samples() if ax == "dcn")
        fast_bytes = sum(
            v for (op, ax), v in
            tfam.collective_bytes_total().samples() if ax == "data")
        # bf16 gather across 2 slices of the 16-elem shard: 2*16*2 B
        assert dcn_bytes == 2 * 16 * 2
        # rs (64*4/4) + ag (64*4) over the fast axis
        assert fast_bytes == 64 + 256
    finally:
        telemetry.reset()
        telemetry.disable()


def test_hier_sync_compressed_bytes_constant_in_slice_count():
    """The compressed dcn hop is a chunk-ownership all-reduce
    (all_to_all + all-gather): 2·shard·w bytes, CONSTANT in the slice
    count.  A gather-everything schedule would grow as S·shard·w and
    pessimize compression beyond 2 slices."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.telemetry import families as tfam
    mesh = make_mesh({"dcn": 4, "data": -1}, jax.devices()[:8])
    telemetry.enable()
    telemetry.reset()
    try:
        a = jnp.ones((8, 64), jnp.float32)
        jax.jit(shard_map(
            lambda v: hierarchical_grad_sync(
                {"g": v.reshape(-1)}, mesh, wire_dtype="bf16")["g"],
            mesh, in_specs=P(("dcn", "data")), out_specs=P()),
        ).lower(a).compile()
        dcn_bytes = sum(
            v for (op, ax), v in
            tfam.collective_bytes_total().samples() if ax == "dcn")
        # F=2 -> 32-elem shard; a2a (4 chunks x 8) bf16 = 64 B, gather
        # of the 8-elem reduced chunk = 8*2*4 = 64 B: 2*shard*2, NOT
        # S*shard*2 (=256)
        assert dcn_bytes == 2 * 32 * 2, dcn_bytes
    finally:
        telemetry.reset()
        telemetry.disable()


# ---------------------------------------------------------------------------
# Optimizer wiring: loss equivalence + compiled-HLO byte acceptance
# ---------------------------------------------------------------------------

_N_STEPS = 20


def _train(mesh_axes, hierarchical=False, wire=None):
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import DataSet, Sample
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils import set_seed
    set_seed(99)
    model = nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10), nn.LogSoftMax())
    rng = np.random.default_rng(5)
    samples = [Sample(rng.normal(size=(16,)).astype(np.float32),
                      int(rng.integers(1, 11))) for _ in range(64)]
    data = (DataSet.array(samples, shuffle=False)
            .transform(SampleToMiniBatch(16)))
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
           .set_end_when(Trigger.max_iteration(_N_STEPS))
           .set_log_interval(1)
           .set_mesh(MeshConfig(**mesh_axes)))
    if hierarchical:
        opt.set_gradient_sync(hierarchical=True, wire_dtype=wire)
    opt.optimize()
    leaves = [np.asarray(l) for l in
              jax.tree_util.tree_leaves(model.parameters())]
    return float(opt.state["loss"]), leaves


_FLAT_CACHE = {}


def _flat_run():
    if "flat" not in _FLAT_CACHE:
        _FLAT_CACHE["flat"] = _train({"data": 8})
    return _FLAT_CACHE["flat"]


def test_optimizer_flat_sync_ignores_dcn_mesh_shape():
    """A dcn×data mesh with the sync mode UNSET is still plain DP: the
    fixed-seed run matches the data-only mesh bit for bit."""
    l_flat, p_flat = _flat_run()
    l_dcn, p_dcn = _train({"dcn": 2, "data": -1})
    assert l_dcn == l_flat
    for a, b in zip(p_flat, p_dcn):
        np.testing.assert_array_equal(a, b)


def test_optimizer_hierarchical_fp32_matches_flat():
    l_flat, p_flat = _flat_run()
    l_h, p_h = _train({"dcn": 2, "data": -1}, hierarchical=True)
    np.testing.assert_allclose(l_h, l_flat, rtol=1e-5)
    for a, b in zip(p_flat, p_h):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_optimizer_hierarchical_bf16_loss_within_tolerance():
    """Acceptance: fixed-seed loss after 20 steps matches flat sync
    within 1e-2 relative under the bf16 wire."""
    l_flat, _ = _flat_run()
    l_b, _ = _train({"dcn": 2, "data": -1}, hierarchical=True,
                    wire="bf16")
    assert abs(l_b - l_flat) <= 1e-2 * abs(l_flat), (l_b, l_flat)


@pytest.mark.slow
def test_optimizer_hierarchical_int8_loss_within_tolerance():
    l_flat, _ = _flat_run()
    l_i, _ = _train({"dcn": 2, "data": -1}, hierarchical=True,
                    wire="int8")
    assert abs(l_i - l_flat) <= 2e-2 * abs(l_flat), (l_i, l_flat)


def _mini_batch():
    from bigdl_tpu.dataset.dataset import MiniBatch
    rng = np.random.default_rng(5)
    return MiniBatch(rng.normal(size=(16, 16)).astype(np.float32),
                     rng.integers(1, 11, size=(16,)).astype(np.int64))


def _compiled_step(hierarchical=False, wire=None):
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.utils import set_seed
    set_seed(99)
    model = nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10), nn.LogSoftMax())
    opt = (Optimizer(model, [Sample(np.zeros(16, np.float32), 1)],
                     nn.ClassNLLCriterion(), batch_size=16)
           .set_optim_method(SGD(0.1))
           .set_mesh(MeshConfig(dcn=2, data=-1)))
    if hierarchical:
        opt.set_gradient_sync(hierarchical=True, wire_dtype=wire)
    elif wire == "explicit-off":
        opt.set_gradient_sync(hierarchical=False)
    return opt.compile_step(_mini_batch())


def test_compiled_cross_slice_bytes_acceptance():
    """Acceptance: on the 8-fake-device 2-slice mesh, the compiled
    hierarchical step's cross-slice (dcn-axis) payload is ≤ 55% of the
    flat fp32 all-reduce baseline under bf16 and ≤ 30% under int8."""
    sm = dcn_slice_map(_dcn_mesh())
    base = cross_group_hlo_bytes(_compiled_step(), sm)
    assert base is not None and base["total"] > 0
    bf16 = cross_group_hlo_bytes(
        _compiled_step(hierarchical=True, wire="bf16"), sm)["total"]
    int8 = cross_group_hlo_bytes(
        _compiled_step(hierarchical=True, wire="int8"), sm)["total"]
    assert bf16 <= 0.55 * base["total"], (bf16, base)
    assert int8 <= 0.30 * base["total"], (int8, base)
    # and the hierarchy alone (fp32 wire) already beats flat: the
    # cross-slice hop carries 1/F of the gradient
    fp32 = cross_group_hlo_bytes(
        _compiled_step(hierarchical=True), sm)["total"]
    assert fp32 <= 0.30 * base["total"], (fp32, base)


def test_compiled_step_hlo_identical_when_sync_unset():
    """Acceptance: with the sync mode unset the step HLO is
    byte-identical to a build that never saw set_gradient_sync."""
    default = _compiled_step().as_text()
    explicit_off = _compiled_step(wire="explicit-off").as_text()
    assert default == explicit_off
    # and the hierarchical program is genuinely different
    assert _compiled_step(hierarchical=True).as_text() != default


def test_compile_step_restores_training_mode():
    """compile_step is a read-only introspection hook: lowering needs
    the training-mode program, but an eval_mode'd model must come back
    out in eval mode (dropout/BN-update must not silently re-arm)."""
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.utils import set_seed
    set_seed(99)
    model = nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10), nn.LogSoftMax())
    opt = (Optimizer(model, [Sample(np.zeros(16, np.float32), 1)],
                     nn.ClassNLLCriterion(), batch_size=16)
           .set_optim_method(SGD(0.1))
           .set_mesh(MeshConfig(dcn=2, data=-1)))
    model.eval_mode()
    opt.compile_step(_mini_batch())
    assert not model.is_training()
    assert not any(m.training for _, m in model.named_modules())


def test_compile_step_mirrors_watchdog_health_wiring():
    """A watchdog-armed optimize() dispatches the health=True step
    (in-graph grad-norm + guards) — compile_step must introspect THAT
    program, not the bare one."""
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.utils import set_seed

    def build(watchdog):
        set_seed(99)
        model = nn.Sequential(
            nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10),
            nn.LogSoftMax())
        opt = (Optimizer(model, [Sample(np.zeros(16, np.float32), 1)],
                         nn.ClassNLLCriterion(), batch_size=16)
               .set_optim_method(SGD(0.1))
               .set_mesh(MeshConfig(dcn=2, data=-1)))
        if watchdog:
            opt.set_health_watchdog()
        return opt

    bare = build(False).compile_step(_mini_batch())
    armed = build(True).compile_step(_mini_batch())
    # the armed program returns the extra grad-norm output
    n_out = lambda c: len(jax.tree_util.tree_leaves(  # noqa: E731
        c.output_shardings))
    assert n_out(armed) == n_out(bare) + 1


def test_compile_step_abstract_state_hlo_identical():
    """compile_step lowers the opt states from avals (no device
    allocation of momentum/variance buffers) — the program must be
    byte-identical to one lowered from the concrete init_state arrays,
    for a params-congruent state (SGD velocity, Adam m/v) AND a
    non-congruent one (LBFGS's flat history buffers)."""
    import jax
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD, Adam, LBFGS
    from bigdl_tpu.optim.optimizer import (
        _stage, batch_sharding, shard_model_params)
    from bigdl_tpu.utils import get_seed, set_seed

    def build(method, hierarchical):
        set_seed(99)
        model = nn.Sequential(
            nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10),
            nn.LogSoftMax())
        opt = (Optimizer(model, [Sample(np.zeros(16, np.float32), 1)],
                         nn.ClassNLLCriterion(), batch_size=16)
               .set_optim_method(method)
               .set_mesh(MeshConfig(dcn=2, data=-1)))
        if hierarchical:
            opt.set_gradient_sync(hierarchical=True, wire_dtype="bf16")
        return opt

    def concrete_compile(opt, batch):
        # compile_step's body with abstract_state=False
        mesh = opt.mesh_config.build()
        model = shard_model_params(opt.model.train_mode(), mesh,
                                   opt.sharding_rules)
        (pg, rest, names, _m, states, specs) = opt._setup_step_state(
            model, abstract_state=False)
        step = opt._build_step(mesh, names, specs, raw=True)
        xs = batch_sharding(mesh)
        with mesh:
            x = _stage(batch.get_input(), xs)
            y = _stage(batch.get_target(), xs)
            rng = jax.random.fold_in(jax.random.key(get_seed()), 0)
            return step.lower(pg, rest, states, x, y, rng, 1).compile()

    mb = _mini_batch()
    for method, hier in ((lambda: SGD(0.1, momentum=0.9), True),
                         (lambda: Adam(1e-3), True),
                         (lambda: SGD(0.1, momentum=0.9), False),
                         (lambda: LBFGS(), False)):
        abstract = build(method(), hier).compile_step(mb).as_text()
        concrete = concrete_compile(build(method(), hier), mb).as_text()
        assert abstract == concrete, (method(), hier)


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

def _opt_for_plan(**mesh_axes):
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer
    model = nn.Sequential(nn.Linear(4, 4))
    return (Optimizer(model, [Sample(np.zeros(4, np.float32), 1)],
                      nn.ClassNLLCriterion(), batch_size=1)
            .set_mesh(MeshConfig(**mesh_axes)))


def test_set_gradient_sync_rejects_unknown_wire():
    with pytest.raises(ValueError, match="wire dtype"):
        _opt_for_plan(data=8).set_gradient_sync(
            hierarchical=True, wire_dtype="fp4")


def test_grad_sync_plan_rejects_wire_without_hierarchical():
    # the setter itself rejects the pairing at configure time …
    with pytest.raises(ValueError, match="hierarchical=True"):
        _opt_for_plan(data=8).set_gradient_sync(
            hierarchical=False, wire_dtype="bf16")
    # … and plan resolution backstops a bypassed setter
    opt = _opt_for_plan(data=8)
    opt.grad_sync_wire_dtype = "bf16"  # bypass the setter's pairing
    with pytest.raises(ValueError, match="hierarchical=True"):
        opt._grad_sync_plan(opt.mesh_config.build())


def test_grad_sync_plan_rejects_model_axes():
    opt = _opt_for_plan(data=2, model=4).set_gradient_sync(
        hierarchical=True)
    with pytest.raises(ValueError, match="batch-parallel"):
        opt._grad_sync_plan(opt.mesh_config.build())


def test_grad_sync_plan_rejects_sum_reduction_criterion():
    """The hierarchical step averages per-shard losses/gradients —
    valid only for a mean-reduction criterion.  size_average=False
    would silently train at lr/n_devices, including one SMUGGLED
    inside a composite (MultiCriterion's crits / TimeDistributed's
    critrn), which the guard walks named_modules to find."""
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer

    def plan(crit):
        opt = (Optimizer(nn.Sequential(nn.Linear(4, 4)),
                         [Sample(np.zeros(4, np.float32), 1)],
                         crit, batch_size=1)
               .set_mesh(MeshConfig(data=8))
               .set_gradient_sync(hierarchical=True))
        return opt._grad_sync_plan(opt.mesh_config.build())

    for crit in (
            nn.ClassNLLCriterion(size_average=False),
            nn.CrossEntropyCriterion(size_average=False),
            nn.MultiCriterion().add(
                nn.ClassNLLCriterion(size_average=False)),
            nn.TimeDistributedCriterion(
                nn.ClassNLLCriterion(size_average=False),
                size_average=True),
            # batch-sum criteria WITHOUT a size_average flag — the
            # attribute probe can't see them, the class list must
            nn.KLDCriterion(),
            nn.MultiCriterion().add(nn.GaussianCriterion())):
        with pytest.raises(ValueError, match="mean-reduction"):
            plan(crit)
    # TimeDistributedCriterion's OWN size_average=False (the default)
    # normalizes over TIME, not batch — same extent on every shard, so
    # it must stay accepted
    assert plan(nn.TimeDistributedCriterion(
        nn.ClassNLLCriterion())) is not None


def test_grad_sync_plan_warns_on_weighted_criterion(caplog):
    """Class-weighted (or padding-masked) criteria divide by the LOCAL
    shard's weight sum, so the hierarchical pmean of local means
    differs from the flat step's global weighted mean when shards draw
    different class mixes — advisory, not rejection (uniform weights
    and no padding agree exactly).  Covers the bare criterion and the
    CrossEntropy wrapper's ``inner``."""
    import logging
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer
    for crit in (nn.ClassNLLCriterion(weights=[1.0, 2.0]),
                 nn.CrossEntropyCriterion(weights=[1.0, 2.0]),
                 # explicit paddingValue: the same local-denominator
                 # rescaling, detected without class weights
                 nn.ClassNLLCriterion(paddingValue=0),
                 # nested inside a composite: the walk must find it
                 nn.MultiCriterion().add(
                     nn.ClassNLLCriterion(weights=[1.0, 2.0]))):
        opt = (Optimizer(nn.Sequential(nn.Linear(4, 2)),
                         [Sample(np.zeros(4, np.float32), 1)],
                         crit, batch_size=1)
               .set_mesh(MeshConfig(dcn=2, data=-1))
               .set_gradient_sync(hierarchical=True))
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
            plan = opt._grad_sync_plan(opt.mesh_config.build())
        assert plan is not None
        assert any("weight sum" in r.getMessage()
                   for r in caplog.records), type(crit).__name__
    # unweighted criteria stay silent
    caplog.clear()
    opt2 = _opt_for_plan(dcn=2, data=-1).set_gradient_sync(
        hierarchical=True)
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
        assert opt2._grad_sync_plan(opt2.mesh_config.build()) is not None
    assert not any("weight sum" in r.getMessage()
                   for r in caplog.records)


def test_grad_sync_plan_rejects_sharding_rules():
    from bigdl_tpu.parallel import ShardingRules
    opt = _opt_for_plan(data=8)
    opt.set_mesh(MeshConfig(data=8), ShardingRules(fsdp=True))
    opt.set_gradient_sync(hierarchical=True)
    with pytest.raises(ValueError, match="replicated"):
        opt._grad_sync_plan(opt.mesh_config.build())


def test_grad_sync_plan_warns_wire_without_dcn(caplog):
    import logging
    opt = _opt_for_plan(data=8).set_gradient_sync(
        hierarchical=True, wire_dtype="bf16")
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
        plan = opt._grad_sync_plan(opt.mesh_config.build())
    assert plan is not None and plan["wire_dtype"] is None
    assert any("no slow hop" in r.getMessage() for r in caplog.records)


def test_grad_sync_plan_warns_on_batch_stat_modules(caplog):
    """BatchNorm under the hierarchical shard_map computes shard-local
    statistics (data-parallel BN), not the flat step's global-batch
    stats — the plan warns naming the module, and stays resolvable."""
    import logging
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer
    model = nn.Sequential(
        nn.Linear(4, 4), nn.BatchNormalization(4), nn.ReLU())
    opt = (Optimizer(model, [Sample(np.zeros(4, np.float32), 1)],
                     nn.ClassNLLCriterion(), batch_size=1)
           .set_mesh(MeshConfig(dcn=2, data=-1))
           .set_gradient_sync(hierarchical=True, wire_dtype="bf16"))
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
        plan = opt._grad_sync_plan(opt.mesh_config.build())
        # bench resolves the plan once for artifact stamping and the
        # step build resolves it again — the advisory fires once
        opt._grad_sync_plan(opt.mesh_config.build())
    assert plan is not None and plan["wire_dtype"] == "bf16"
    msgs = [r.getMessage() for r in caplog.records
            if "batch statistics" in r.getMessage()]
    assert len(msgs) == 1 and "BatchNormalization" in msgs[0], \
        caplog.records
    # BN-free models stay silent
    caplog.clear()
    opt2 = _opt_for_plan(dcn=2, data=-1).set_gradient_sync(
        hierarchical=True)
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
        assert opt2._grad_sync_plan(opt2.mesh_config.build()) is not None
    assert not [r for r in caplog.records
                if "batch statistics" in r.getMessage()]


# ---------------------------------------------------------------------------
# analytic floor + HLO classifier units + dcn roofline
# ---------------------------------------------------------------------------

def test_grad_allreduce_bytes_hierarchical_mode():
    from bigdl_tpu.parallel.sharding import grad_allreduce_bytes
    model = nn.Linear(12, 16)  # (16*12 + 16) * 4 = 832 B fp32
    mesh = _dcn_mesh()  # F=4, S=2
    flat = grad_allreduce_bytes(model, mesh)
    assert flat["bytes_per_step"] == 832.0  # unchanged default mode
    # the flat all-reduce crosses DCN at FULL width on a dcn>1 mesh —
    # the baseline needs its own dcn roofline floor
    assert flat["dcn_bytes_per_step"] == 832.0
    h = grad_allreduce_bytes(model, mesh, hierarchical=True)
    assert h["flat_fp32_bytes_per_step"] == 832.0
    assert h["intra_bytes_per_step"] == 832.0 / 4 + 832.0
    assert h["dcn_bytes_per_step"] == 832.0 / 4  # uncompressed psum
    hb = grad_allreduce_bytes(model, mesh, hierarchical=True,
                              wire_dtype="bf16")
    assert hb["dcn_bytes_per_step"] == 2 * (832.0 / 4) * 0.5
    assert hb["compression_ratio"] == pytest.approx(
        832.0 / (832.0 / 4 + 832.0 + 832.0 / 4))
    hi = grad_allreduce_bytes(model, mesh, hierarchical=True,
                              wire_dtype="int8")
    # 208 B shard = 52 elems in S=2 chunks of 26: the bucket clamps to
    # the 26-elem chunk, so each hop pays 52 int8 bytes + 2 fp32
    # scales — NOT the nominal 1+4/512 per-element asymptote
    assert hi["dcn_bytes_per_step"] == pytest.approx(2 * (52 + 2 * 4))
    assert hi["dcn_bytes_per_step"] == pytest.approx(
        2 * wire_bytes("int8", 52, n_chunks=2))
    assert hi["wire_dtype"] == "int8"
    # uncompressed SPELLINGS ("fp32"/"none") resolve to no codec at
    # runtime — the estimator must cost the single-hop psum, not the
    # two-hop codec schedule
    hf = grad_allreduce_bytes(model, mesh, hierarchical=True,
                              wire_dtype="fp32")
    assert hf["dcn_bytes_per_step"] == h["dcn_bytes_per_step"]
    assert hf["wire_dtype"] is None


def test_grad_allreduce_bytes_hierarchical_rejects_rules():
    """The hierarchical estimator models replicated params (the
    primitive's requirement); rules would silently understate the
    floor by the shard factor for a config optimize() rejects."""
    from bigdl_tpu.parallel import ShardingRules
    from bigdl_tpu.parallel.sharding import grad_allreduce_bytes
    with pytest.raises(ValueError, match="replicated"):
        grad_allreduce_bytes(nn.Linear(12, 16), _dcn_mesh(),
                             ShardingRules(fsdp=True),
                             hierarchical=True)


def test_cross_group_hlo_bytes_text_units():
    text = "\n".join([
        "ENTRY main {",
        # within-group: devices {0,1} and {2,3} are both group-pure
        "  %a = f32[8]{0} all-reduce(%p), replica_groups={{0,1},{2,3}}",
        # cross-group explicit: {0,2} spans groups
        "  %b = f32[4]{0} all-reduce(%q), replica_groups={{0,2},{1,3}}",
        # iota form [2,2]<=[4] -> groups {0,1},{2,3}: within
        "  %c = bf16[16]{0} all-gather(%r), replica_groups=[2,2]<=[4]",
        # iota with transpose [2,2]<=[2,2]T(1,0) -> {0,2},{1,3}: cross
        "  %d = s8[32]{0} all-gather(%s), "
        "replica_groups=[2,2]<=[2,2]T(1,0)",
        # async pair: groups on -start, payload at -done (cross)
        "  %e.s = (f32[4]{0}, f32[8]{0}) all-reduce-start(%t), "
        "replica_groups={{0,3}}",
        "  %e.d = f32[8]{0} all-reduce-done(%e.s)",
        # collective-permute prints source_target_pairs, not
        # replica_groups — a ring strictly inside each group must NOT
        # fall through to the "spans everything" default
        "  %f = f32[8]{0} collective-permute(%u), "
        "source_target_pairs={{0,1},{1,0},{2,3},{3,2}}",
        # one pair hops the group boundary: counts
        "  %g = f32[16]{0} collective-permute(%v), "
        "source_target_pairs={{1,2}}",
        "}",
    ])
    group_of = {0: 0, 1: 0, 2: 1, 3: 1}
    out = cross_group_hlo_bytes(text, group_of)
    assert out["all-reduce"] == 4 * 4 + 8 * 4  # %b + %e.d
    assert out["all-gather"] == 32  # %d only (s8)
    assert out["collective-permute"] == 64  # %g only
    assert out["total"] == 16 + 32 + 32 + 64
    # single-group world: nothing crosses
    assert cross_group_hlo_bytes(text, {i: 0 for i in range(4)})[
        "total"] == 0.0


def test_roofline_dcn_bound_verdict():
    from bigdl_tpu.telemetry import perf as tperf
    roof = tperf.roofline_verdict(
        1e12, 1e8, 1e15, 1e12,
        comm_bytes_per_step=1e9, ici_bytes_per_s=200e9,
        dcn_bytes_per_step=2e8, dcn_bytes_per_s=12.5e9)
    # dcn floor: 2e8/12.5e9 = 16 ms > comm 5 ms > compute 1 ms
    assert roof["verdict"] == "dcn_bound"
    assert roof["min_dcn_s"] == pytest.approx(16e-3)
    assert roof["attainable_step_s"] == pytest.approx(16e-3)
    # without a dcn budget the three-floor behavior is unchanged
    old = tperf.roofline_verdict(
        1e12, 1e8, 1e15, 1e12,
        comm_bytes_per_step=1e9, ici_bytes_per_s=200e9)
    assert old["verdict"] == "comm_bound"
    assert "min_dcn_s" not in old


def test_device_dcn_table_and_env_override(monkeypatch):
    from bigdl_tpu.telemetry import perf as tperf
    assert tperf.device_dcn_bytes_per_s("TPU v5e") == 12.5e9
    assert tperf.device_dcn_bytes_per_s("weird") is None
    monkeypatch.setenv("BIGDL_TPU_DCN_BYTES_PER_S", "1e6")
    assert tperf.device_dcn_bytes_per_s("TPU v5e") == 1e6
    assert tperf.device_dcn_bytes_per_s(None) == 1e6


def test_device_dcn_env_override_bad_value_warns(caplog, monkeypatch):
    """An unparsable override must not be silently discarded — the
    verdict would be computed from the spec table while the operator
    believes their measured number is in effect."""
    import logging
    from bigdl_tpu.telemetry import perf as tperf
    monkeypatch.setenv("BIGDL_TPU_DCN_BYTES_PER_S", "12.5GB")
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu.telemetry"):
        assert tperf.device_dcn_bytes_per_s("TPU v5e") == 12.5e9
    assert any("BIGDL_TPU_DCN_BYTES_PER_S" in r.getMessage()
               for r in caplog.records), caplog.records


def test_attribution_report_dcn_section():
    from bigdl_tpu.telemetry import perf as tperf
    records = [
        {"iterations": 1, "wall_s": 0.1, "data_wait_s": 0.01,
         "host_staging_s": 0.01, "device_compute_s": 0.07,
         "readback_s": 0.01}
        for _ in range(3)
    ]
    rep = tperf.attribution_report(
        records, flops_per_step=1e12, bytes_per_step=1e9,
        peak_spec_flops=197e12, hbm_bytes_per_s=819e9,
        comm_bytes_per_step=5e9, ici_bytes_per_s=200e9,
        dcn_bytes_per_step=1e9, dcn_bytes_per_s=12.5e9)
    assert rep["dcn"]["bytes_per_step"] == 1e9
    assert rep["dcn"]["min_dcn_s"] == pytest.approx(0.08)
    assert rep["roofline"]["verdict"] == "dcn_bound"
