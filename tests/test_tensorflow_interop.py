"""TF GraphDef interop tests: build GraphDef bytes with the wire
encoder, import, and check numerics against a torch oracle; export a
Sequential and re-import it (roundtrip).

Mirrors reference TensorflowLoaderSpec / TensorflowSaverSpec
(spark/dl/src/test/.../utils/tf/).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.protowire import (BYTES, FIXED32, VARINT,
                                         encode_message, varint)
from bigdl_tpu.interop.tensorflow import (load_tf_graph, parse_graphdef,
                                          save_tf_graph)
from bigdl_tpu.utils import set_seed


# ---- GraphDef construction helpers (test-side encoder) -------------------

def attr(key, fields):
    return encode_message([(1, BYTES, key.encode()),
                           (2, BYTES, encode_message(fields))])


def tensor_proto(arr):
    arr = np.asarray(arr)
    dt = {np.dtype(np.float32): 1, np.dtype(np.int32): 3,
          np.dtype(np.int64): 9}[arr.dtype]
    shape = encode_message([(2, BYTES, encode_message([(1, VARINT, d)]))
                            for d in arr.shape])
    return encode_message([(1, VARINT, dt), (2, BYTES, shape),
                           (4, BYTES, arr.tobytes())])


def node(name, op, inputs=(), attrs=()):
    fields = [(1, BYTES, name.encode()), (2, BYTES, op.encode())]
    for i in inputs:
        fields.append((3, BYTES, i.encode()))
    for a in attrs:
        fields.append((5, BYTES, a))
    return encode_message(fields)


def graphdef(*nodes):
    return encode_message([(1, BYTES, n) for n in nodes])


def const_node(name, arr):
    return node(name, "Const", (), [
        attr("dtype", [(6, VARINT, 1 if arr.dtype == np.float32 else 3)]),
        attr("value", [(8, BYTES, tensor_proto(arr))]),
    ])


def ints_list_attr(key, vals):
    packed = b"".join(varint(v) for v in vals)
    return attr(key, [(1, BYTES, encode_message([(3, BYTES, packed)]))])


def test_parse_graphdef():
    g = graphdef(
        node("x", "Placeholder"),
        node("y", "Relu", ["x"]),
        const_node("c", np.asarray([1.0, 2.0], np.float32)),
    )
    nodes = parse_graphdef(g)
    assert [n.op for n in nodes] == ["Placeholder", "Relu", "Const"]
    np.testing.assert_allclose(nodes[2].attrs["value"], [1.0, 2.0])


def test_import_mlp_matches_torch():
    set_seed(0)
    rng = np.random.RandomState(0)
    w1 = rng.randn(6, 8).astype(np.float32)   # TF layout (in, out)
    b1 = rng.randn(8).astype(np.float32)
    w2 = rng.randn(8, 3).astype(np.float32)
    b2 = rng.randn(3).astype(np.float32)
    g = graphdef(
        node("input", "Placeholder"),
        const_node("w1", w1), const_node("b1", b1),
        const_node("w2", w2), const_node("b2", b2),
        node("mm1", "MatMul", ["input", "w1"]),
        node("ba1", "BiasAdd", ["mm1", "b1"]),
        node("relu", "Relu", ["ba1"]),
        node("mm2", "MatMul", ["relu", "w2"]),
        node("ba2", "BiasAdd", ["mm2", "b2"]),
        node("prob", "Softmax", ["ba2"]),
    )
    model, layer_map = load_tf_graph(g, ["input"], ["prob"])
    # bias fused into the Linear layers
    assert isinstance(layer_map["mm1"], nn.Linear)
    x = rng.randn(4, 6).astype(np.float32)
    out = np.asarray(model(jnp.asarray(x)))
    tx = torch.tensor(x)
    want = F.softmax(
        F.relu(tx @ torch.tensor(w1) + torch.tensor(b1))
        @ torch.tensor(w2) + torch.tensor(b2), dim=-1).numpy()
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_import_conv_net_matches_torch():
    set_seed(1)
    rng = np.random.RandomState(1)
    w = rng.randn(3, 3, 2, 4).astype(np.float32)  # HWIO
    b = rng.randn(4).astype(np.float32)
    g = graphdef(
        node("input", "Placeholder"),
        const_node("w", w), const_node("b", b),
        node("conv", "Conv2D", ["input", "w"], [
            ints_list_attr("strides", [1, 1, 1, 1]),
            attr("padding", [(2, BYTES, b"SAME")]),
        ]),
        node("bias", "BiasAdd", ["conv", "b"]),
        node("relu", "Relu", ["bias"]),
        node("pool", "MaxPool", ["relu"], [
            ints_list_attr("ksize", [1, 2, 2, 1]),
            ints_list_attr("strides", [1, 2, 2, 1]),
            attr("padding", [(2, BYTES, b"VALID")]),
        ]),
    )
    model, _ = load_tf_graph(g, ["input"], ["pool"])
    x = rng.randn(1, 6, 6, 2).astype(np.float32)  # NHWC
    out = np.asarray(model(jnp.asarray(x)))
    # torch oracle (NCHW)
    tx = torch.tensor(np.transpose(x, (0, 3, 1, 2)))
    tw = torch.tensor(np.transpose(w, (3, 2, 0, 1)))
    y = F.conv2d(tx, tw, torch.tensor(b), padding=1)
    y = F.relu(y)
    y = F.max_pool2d(y, 2, 2)
    want = np.transpose(y.numpy(), (0, 2, 3, 1))
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_import_bn_and_eltwise():
    set_seed(2)
    rng = np.random.RandomState(2)
    gamma = rng.rand(3).astype(np.float32) + 0.5
    beta = rng.randn(3).astype(np.float32)
    mean = rng.randn(3).astype(np.float32)
    var = rng.rand(3).astype(np.float32) + 0.5
    g = graphdef(
        node("input", "Placeholder"),
        const_node("gamma", gamma), const_node("beta", beta),
        const_node("mean", mean), const_node("var", var),
        node("bn", "FusedBatchNormV3",
             ["input", "gamma", "beta", "mean", "var"]),
        node("out", "AddV2", ["bn", "bn"]),
    )
    model, _ = load_tf_graph(g, ["input"], ["out"])
    model.eval_mode()
    x = rng.randn(2, 4, 4, 3).astype(np.float32)
    out = np.asarray(model(jnp.asarray(x)))
    want = 2 * (gamma * (x - mean) / np.sqrt(var + 1e-3) + beta)
    np.testing.assert_allclose(out, want, rtol=1e-3, atol=1e-4)


def test_import_concat_mean_reshape():
    g = graphdef(
        node("input", "Placeholder"),
        const_node("axis", np.asarray(1, np.int32).reshape(())),
        node("cat", "ConcatV2", ["input", "input", "axis"]),
        const_node("mean_ax", np.asarray([1], np.int32)),
        node("mean", "Mean", ["cat", "mean_ax"]),
        const_node("shape", np.asarray([-1, 2], np.int32)),
        node("resh", "Reshape", ["mean", "shape"]),
    )
    model, _ = load_tf_graph(g, ["input"], ["resh"])
    x = jnp.asarray(np.arange(8, dtype=np.float32).reshape(2, 4))
    out = np.asarray(model(x))
    cat = np.concatenate([np.asarray(x)] * 2, axis=1)
    want = cat.mean(axis=1).reshape(-1, 2)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_export_import_roundtrip(tmp_path):
    set_seed(3)
    model = nn.Sequential(
        nn.Linear(5, 7).set_name("fc1"), nn.ReLU(),
        nn.Linear(7, 3).set_name("fc2"))
    p = str(tmp_path / "model.pb")
    names = save_tf_graph(model, p, input_name="input")
    assert names[0] == "input"
    back, _ = load_tf_graph(p, ["input"], [names[-1]])
    x = jnp.asarray(np.random.RandomState(4).randn(3, 5), jnp.float32)
    np.testing.assert_allclose(np.asarray(back(x)),
                               np.asarray(model(x)), rtol=1e-5,
                               atol=1e-6)


def test_unknown_op_errors():
    g = graphdef(node("input", "Placeholder"),
                 node("w", "WeirdCustomOp", ["input"]))
    with pytest.raises(ValueError, match="WeirdCustomOp"):
        load_tf_graph(g, ["input"], ["w"])


def test_onnx_shims():
    from bigdl_tpu.interop import Gemm, OnnxReshape, OnnxShape
    rng = np.random.RandomState(5)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4, 2).astype(np.float32)
    c = rng.randn(2).astype(np.float32)
    g = Gemm(alpha=2.0, beta=0.5)
    out = np.asarray(g((jnp.asarray(a), jnp.asarray(b), jnp.asarray(c))))
    np.testing.assert_allclose(out, 2.0 * a @ b + 0.5 * c, rtol=1e-5)
    r = OnnxReshape((0, 2, 2))
    assert r(jnp.ones((3, 4))).shape == (3, 2, 2)
    s = OnnxShape()
    np.testing.assert_array_equal(np.asarray(s(jnp.ones((2, 5)))), [2, 5])


def test_biasadd_not_fused_with_second_consumer():
    """A second consumer of the MatMul output must see PRE-bias values."""
    w = np.eye(2, dtype=np.float32)
    b = np.asarray([10.0, 10.0], np.float32)
    g = graphdef(
        node("input", "Placeholder"),
        const_node("w", w), const_node("b", b),
        node("mm", "MatMul", ["input", "w"]),
        node("ba", "BiasAdd", ["mm", "b"]),
        node("tap", "Identity", ["mm"]),   # pre-bias branch
        node("sum", "AddV2", ["ba", "tap"]),
    )
    model, _ = load_tf_graph(g, ["input"], ["sum"])
    x = jnp.asarray([[1.0, 2.0]])
    out = np.asarray(model(x))
    # sum = (x + 10) + x — bias applied exactly once
    np.testing.assert_allclose(out, [[12.0, 14.0]], rtol=1e-6)


def test_dilated_conv_import():
    rng = np.random.RandomState(6)
    w = rng.randn(3, 3, 1, 2).astype(np.float32)
    g = graphdef(
        node("input", "Placeholder"),
        const_node("w", w),
        node("conv", "Conv2D", ["input", "w"], [
            ints_list_attr("strides", [1, 1, 1, 1]),
            ints_list_attr("dilations", [1, 2, 2, 1]),
            attr("padding", [(2, BYTES, b"SAME")]),
        ]),
    )
    model, _ = load_tf_graph(g, ["input"], ["conv"])
    x = rng.randn(1, 8, 8, 1).astype(np.float32)
    out = np.asarray(model(jnp.asarray(x)))
    tx = torch.tensor(np.transpose(x, (0, 3, 1, 2)))
    tw = torch.tensor(np.transpose(w, (3, 2, 0, 1)))
    want = F.conv2d(tx, tw, padding=2, dilation=2)
    np.testing.assert_allclose(
        out, np.transpose(want.numpy(), (0, 2, 3, 1)),
        rtol=1e-4, atol=1e-5)


def test_export_flatten_roundtrip(tmp_path):
    set_seed(7)
    model = nn.Sequential(nn.Flatten(), nn.Linear(12, 3).set_name("fc"))
    p = str(tmp_path / "f.pb")
    names = save_tf_graph(model, p)
    back, _ = load_tf_graph(p, ["input"], [names[-1]])
    x = jnp.asarray(np.random.RandomState(8).randn(2, 3, 4), jnp.float32)
    np.testing.assert_allclose(np.asarray(back(x)),
                               np.asarray(model(x)), rtol=1e-5, atol=1e-6)


def test_caffe_missing_weights_clear_error(tmp_path):
    from bigdl_tpu.interop import load_caffe
    p = str(tmp_path / "only.prototxt")
    with open(p, "w") as f:
        f.write('input: "data"\n'
                'layer { name: "fc" type: "InnerProduct" bottom: "data" '
                'top: "fc" inner_product_param { num_output: 3 } }\n')
    with pytest.raises(ValueError, match="caffemodel"):
        load_caffe(p)


def test_avgpool_same_excludes_padding():
    g = graphdef(
        node("input", "Placeholder"),
        node("pool", "AvgPool", ["input"], [
            ints_list_attr("ksize", [1, 3, 3, 1]),
            ints_list_attr("strides", [1, 1, 1, 1]),
            attr("padding", [(2, BYTES, b"SAME")]),
        ]),
    )
    model, _ = load_tf_graph(g, ["input"], ["pool"])
    x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    out = np.asarray(model(jnp.asarray(x)))
    tx = torch.tensor(np.transpose(x, (0, 3, 1, 2)))
    want = F.avg_pool2d(tx, 3, 1, padding=1, count_include_pad=False)
    np.testing.assert_allclose(
        out, np.transpose(want.numpy(), (0, 2, 3, 1)), rtol=1e-5)


def test_export_repeated_unnamed_layers(tmp_path):
    set_seed(9)
    model = nn.Sequential(nn.Linear(4, 4), nn.ReLU(),
                          nn.Linear(4, 4), nn.ReLU())
    p = str(tmp_path / "dup.pb")
    names = save_tf_graph(model, p)
    assert len(set(names)) == len(names)  # no duplicate node names
    back, _ = load_tf_graph(p, ["input"], [names[-1]])
    x = jnp.asarray(np.random.RandomState(10).randn(2, 4), jnp.float32)
    np.testing.assert_allclose(np.asarray(back(x)),
                               np.asarray(model(x)), rtol=1e-5, atol=1e-6)


def test_pre_bias_output_not_fused():
    w = np.eye(2, dtype=np.float32)
    b = np.asarray([10.0, 10.0], np.float32)
    g = graphdef(
        node("input", "Placeholder"),
        const_node("w", w), const_node("b", b),
        node("mm", "MatMul", ["input", "w"]),
        node("ba", "BiasAdd", ["mm", "b"]),
    )
    model, _ = load_tf_graph(g, ["input"], ["mm", "ba"])
    x = jnp.asarray([[1.0, 2.0]])
    mm_out, ba_out = model(x)
    np.testing.assert_allclose(np.asarray(mm_out), [[1.0, 2.0]])
    np.testing.assert_allclose(np.asarray(ba_out), [[11.0, 12.0]])


def test_nchw_graph_rejected():
    w = np.zeros((3, 3, 1, 1), np.float32)
    g = graphdef(
        node("input", "Placeholder"),
        const_node("w", w),
        node("conv", "Conv2D", ["input", "w"], [
            ints_list_attr("strides", [1, 1, 1, 1]),
            attr("padding", [(2, BYTES, b"SAME")]),
            attr("data_format", [(2, BYTES, b"NCHW")]),
        ]),
    )
    with pytest.raises(ValueError, match="NCHW"):
        load_tf_graph(g, ["input"], ["conv"])


def test_varint_negative_terminates():
    from bigdl_tpu.interop.protowire import varint
    assert len(varint(-1)) == 10  # two's-complement 64-bit


# ---- extended op set + Session.train -------------------------------------

def test_extended_ops_numerics():
    """Reductions, argmax, slicing, transpose, pack, gather, one-hot."""
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    ax1 = np.asarray([1], np.int32)
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("axes", ax1),
        node("s", "Sum", ["x", "axes"]),
        const_node("perm", np.asarray([0, 2, 1], np.int32)),
        node("t", "Transpose", ["x", "perm"]),
        node("am", "ArgMax", ["x", "axes"]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["s", "t", "am"])
    s, t, am = model(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(s), x.sum(axis=1))
    np.testing.assert_allclose(np.asarray(t), x.transpose(0, 2, 1))
    np.testing.assert_array_equal(np.asarray(am), x.argmax(axis=1))


def test_strided_slice_and_split():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("b", np.asarray([1, 0], np.int32)),
        const_node("e", np.asarray([3, 4], np.int32)),
        const_node("st", np.asarray([1, 2], np.int32)),
        node("sl", "StridedSlice", ["x", "b", "e", "st"],
             [attr("begin_mask", [(3, VARINT, 0)]),
              attr("end_mask", [(3, VARINT, 0)])]),
        const_node("ax", np.asarray(1, np.int32)),
        node("sp", "Split", ["ax", "x"],
             [attr("num_split", [(3, VARINT, 2)])]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["sl", "sp", "sp:1"])
    sl, sp0, sp1 = model(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(sl), x[1:3, 0:4:2])
    # a bare 'sp' reference means output port 0, TF-style
    np.testing.assert_allclose(np.asarray(sp0), x[:, :3])
    np.testing.assert_allclose(np.asarray(sp1), x[:, 3:])


def test_leaky_relu_and_select():
    x = np.asarray([[-2.0, 3.0]], np.float32)
    gd = graphdef(
        node("x", "Placeholder"),
        node("l", "LeakyRelu", ["x"],
             [attr("alpha", [(4, FIXED32, 0.1)])]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["l"])
    out = np.asarray(model(jnp.asarray(x)))
    np.testing.assert_allclose(out, [[-0.2, 3.0]], rtol=1e-6)


def test_tf_session_train(tmp_path):
    """Session.train equivalence (utils/tf/Session.scala:43-132): an
    imported TF graph trains through the Optimizer — loss decreases and
    the imported MatMul weights move."""
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.interop.tensorflow import TFSession
    from bigdl_tpu.optim import SGD, Trigger

    set_seed(0)
    # author an MLP as a GraphDef via our own exporter
    src = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.LogSoftMax())
    p = str(tmp_path / "mlp.pb")
    save_tf_graph(src, p, input_name="input")

    sess = TFSession(p, ["input"], ["LogSoftMax_4/Log"])
    rng = np.random.default_rng(0)
    protos = rng.normal(size=(4, 8)).astype(np.float32)
    labels = rng.integers(0, 4, size=96)
    samples = [Sample((protos[l] + 0.2 * rng.normal(size=8))
                      .astype(np.float32), int(l) + 1)
               for l in labels]

    before = np.asarray(sess.layer_map["Linear_1/MatMul"].weight).copy()
    x_probe = jnp.asarray(protos)
    y_probe = jnp.asarray(labels[:0])  # unused
    crit = nn.ClassNLLCriterion()
    loss0 = None

    sess.train(samples, crit, optim_method=SGD(0.5),
               end_when=Trigger.max_epoch(6), batch_size=32)
    after = np.asarray(sess.layer_map["Linear_1/MatMul"].weight)
    assert not np.allclose(before, after), "imported weights never moved"
    # trained model separates the synthetic classes
    preds = np.asarray(sess.predict(x_probe)).argmax(axis=1)
    assert (preds == np.arange(4)).mean() >= 0.75


def test_split_output_port_consumption():
    """':N' refs into a tuple-producing op select that output."""
    x = np.arange(12, dtype=np.float32).reshape(2, 6)
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("ax", np.asarray(1, np.int32)),
        node("sp", "Split", ["ax", "x"],
             [attr("num_split", [(3, VARINT, 2)])]),
        node("r", "Relu", ["sp:1"]),
        node("a", "Add", ["sp", "sp:1"]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["r", "a"])
    r, a = model(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(r), np.maximum(x[:, 3:], 0))
    np.testing.assert_allclose(np.asarray(a), x[:, :3] + x[:, 3:])


def test_one_hot_axis_zero():
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("depth", np.asarray(3, np.int32)),
        const_node("on", np.asarray(1.0, np.float32)),
        const_node("off", np.asarray(0.0, np.float32)),
        node("oh", "OneHot", ["x", "depth", "on", "off"],
             [attr("axis", [(3, VARINT, 0)])]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["oh"])
    out = np.asarray(model(jnp.asarray([0, 2], np.int32)))
    assert out.shape == (3, 2)
    np.testing.assert_allclose(out, np.eye(3, dtype=np.float32)[:, [0, 2]])


@pytest.mark.parametrize("align_corners,half_pixel", [
    (True, False), (False, True), (False, False)])
def test_resize_bilinear_tf1_modes(align_corners, half_pixel):
    x = np.random.RandomState(0).rand(1, 4, 5, 2).astype(np.float32)
    attrs = [attr("align_corners", [(5, VARINT, int(align_corners))]),
             attr("half_pixel_centers", [(5, VARINT, int(half_pixel))])]
    gd = graphdef(
        node("x", "Placeholder"),
        const_node("size", np.asarray([8, 10], np.int32)),
        node("rz", "ResizeBilinear", ["x", "size"], attrs),
    )
    model, _ = load_tf_graph(gd, ["x"], ["rz"])
    got = np.asarray(model(jnp.asarray(x)))
    assert got.shape == (1, 8, 10, 2)
    tx = torch.tensor(x.transpose(0, 3, 1, 2))
    if align_corners:
        want = F.interpolate(tx, size=(8, 10), mode="bilinear",
                             align_corners=True)
    elif half_pixel:
        want = F.interpolate(tx, size=(8, 10), mode="bilinear",
                             align_corners=False)
    else:
        # asymmetric (TF1 default): src = dst * scale, clamped
        ys = np.minimum(np.arange(8) * 4 / 8, 3)
        xs = np.minimum(np.arange(10) * 5 / 10, 4)
        y0 = np.floor(ys).astype(int); y1 = np.minimum(y0 + 1, 3)
        x0 = np.floor(xs).astype(int); x1 = np.minimum(x0 + 1, 4)
        wy = (ys - y0)[None, :, None, None]
        wx = (xs - x0)[None, None, :, None]
        top = x[:, y0][:, :, x0] * (1 - wx) + x[:, y0][:, :, x1] * wx
        bot = x[:, y1][:, :, x0] * (1 - wx) + x[:, y1][:, :, x1] * wx
        want_np = top * (1 - wy) + bot * wy
        np.testing.assert_allclose(got, want_np, rtol=1e-5, atol=1e-6)
        return
    np.testing.assert_allclose(
        got, want.permute(0, 2, 3, 1).numpy(), rtol=1e-4, atol=1e-5)


def test_annotation_ops_pass_through():
    """StopGradient/CheckNumerics/PlaceholderWithDefault import as
    identity (StopGradient blocks gradients too)."""
    import jax
    from bigdl_tpu.interop.tensorflow import load_tf_graph
    gd = graphdef(
        node("x", "Placeholder"),
        node("sg", "StopGradient", ["x"]),
        node("cn", "CheckNumerics", ["sg"]),
        node("pd", "PlaceholderWithDefault", ["cn"]),
        node("out", "Neg", ["pd"]),
    )
    model, _ = load_tf_graph(gd, ["x"], ["out"])
    x = jnp.asarray([1.0, -2.0])
    np.testing.assert_allclose(np.asarray(model(x)), [-1.0, 2.0])
    g = jax.grad(lambda v: float(0) + model.forward(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g), [0.0, 0.0])  # StopGradient
