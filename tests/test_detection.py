"""Detection-stack tests: NMS/IoU vs numpy reference loops, RoiAlign vs a
naive bilinear implementation, anchors, box transforms, FPN/Pooler/heads
shape + semantics, SSD PriorBox/DetectionOutput.

Mirrors the reference spec strategy for nn/NmsSpec, RoiAlignSpec,
AnchorSpec, FPNSpec, PoolerSpec, BoxHeadSpec, MaskHeadSpec,
PriorBoxSpec, DetectionOutputSSDSpec (spark/dl/src/test/.../nn/).
"""

import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.detection import (
    Anchor, BoxHead, DetectionOutputSSD, FPN, MaskHead, Pooler, PriorBox,
    Proposal, RegionProposal, RoiAlign, RoiPooling, bbox_encode,
    bbox_transform_inv, box_iou, clip_boxes, nms,
)
from bigdl_tpu.utils import set_seed


def np_iou(a, b):
    x1 = max(a[0], b[0]); y1 = max(a[1], b[1])
    x2 = min(a[2], b[2]); y2 = min(a[3], b[3])
    inter = max(0.0, x2 - x1) * max(0.0, y2 - y1)
    aa = (a[2] - a[0]) * (a[3] - a[1])
    ab = (b[2] - b[0]) * (b[3] - b[1])
    return inter / (aa + ab - inter) if aa + ab - inter > 0 else 0.0


def np_nms(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        rest = [j for j in order[1:]
                if np_iou(boxes[i], boxes[j]) <= thresh]
        order = np.array(rest, int)
    return keep


def test_box_iou_matches_scalar():
    rng = np.random.RandomState(0)
    a = rng.rand(6, 4) * 50
    a[:, 2:] = a[:, :2] + rng.rand(6, 2) * 50 + 1
    b = rng.rand(4, 4) * 50
    b[:, 2:] = b[:, :2] + rng.rand(4, 2) * 50 + 1
    got = np.asarray(box_iou(a, b))
    for i in range(6):
        for j in range(4):
            assert abs(got[i, j] - np_iou(a[i], b[j])) < 1e-5


def test_nms_matches_numpy_reference():
    rng = np.random.RandomState(1)
    n = 40
    boxes = rng.rand(n, 4) * 80
    boxes[:, 2:] = boxes[:, :2] + rng.rand(n, 2) * 40 + 5
    scores = rng.rand(n).astype(np.float32)
    ref = np_nms(boxes, scores, 0.5)
    idx, valid = jax.jit(
        lambda b, s: nms(b, s, 0.5, n))(jnp.asarray(boxes),
                                        jnp.asarray(scores))
    got = [int(i) for i, v in zip(idx, valid) if v]
    assert got == ref


def test_nms_fixed_output_and_neg_inf_exclusion():
    boxes = jnp.asarray([[0, 0, 10, 10], [100, 100, 110, 110],
                         [0, 0, 10, 10]], jnp.float32)
    scores = jnp.asarray([0.9, -jnp.inf, 0.8])
    idx, valid = nms(boxes, scores, 0.5, 5)
    assert idx.shape == (5,)
    got = [int(i) for i, v in zip(idx, valid) if v]
    assert got == [0]  # box2 is -inf-masked, box3 suppressed by box1


def naive_roi_align(feat, roi, scale, sr, ph, pw, aligned=True):
    """Straight-from-the-paper per-sample loop (numpy)."""
    H, W, C = feat.shape
    off = 0.5 if aligned else 0.0
    x1, y1, x2, y2 = [r * scale - off for r in roi]
    rw, rh = x2 - x1, y2 - y1
    if not aligned:
        rw, rh = max(rw, 1.0), max(rh, 1.0)
    out = np.zeros((ph, pw, C), np.float32)
    for py in range(ph):
        for px in range(pw):
            acc = np.zeros(C, np.float32)
            for iy in range(sr):
                for ix in range(sr):
                    y = y1 + (py + (iy + .5) / sr) * rh / ph
                    x = x1 + (px + (ix + .5) / sr) * rw / pw
                    if y < -1 or y > H or x < -1 or x > W:
                        continue
                    y = min(max(y, 0), H - 1)
                    x = min(max(x, 0), W - 1)
                    y0, x0 = int(y), int(x)
                    y1c, x1c = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
                    ly, lx = y - y0, x - x0
                    acc += ((1 - ly) * (1 - lx) * feat[y0, x0]
                            + (1 - ly) * lx * feat[y0, x1c]
                            + ly * (1 - lx) * feat[y1c, x0]
                            + ly * lx * feat[y1c, x1c])
            out[py, px] = acc / (sr * sr)
    return out


def test_roi_align_matches_naive():
    rng = np.random.RandomState(2)
    feat = rng.randn(16, 20, 3).astype(np.float32)
    rois = np.array([[4.0, 4.0, 60.0, 50.0],
                     [0.0, 0.0, 16.0, 16.0]], np.float32)
    layer = RoiAlign(0.25, 2, 7, 7, aligned=True)
    got = np.asarray(layer((jnp.asarray(feat)[None], jnp.asarray(rois))))
    for i, roi in enumerate(rois):
        want = naive_roi_align(feat, roi, 0.25, 2, 7, 7)
        np.testing.assert_allclose(got[i], want, rtol=1e-4, atol=1e-5)


def test_roi_pooling_basic():
    feat = np.zeros((1, 8, 8, 1), np.float32)
    feat[0, 2, 3, 0] = 5.0
    rois = jnp.asarray([[0, 0, 0, 7, 7]], jnp.float32)
    layer = RoiPooling(2, 2, 1.0)
    out = np.asarray(layer((jnp.asarray(feat), rois)))
    assert out.shape == (1, 2, 2, 1)
    assert out.max() == pytest.approx(5.0)
    # the max lives in the top-left 4x4 bin
    assert out[0, 0, 0, 0] == pytest.approx(5.0)


def test_anchor_generation():
    a = Anchor(ratios=[0.5, 1.0, 2.0], scales=[8.0])
    assert a.anchor_num == 3
    base = a.base_anchors(16.0)
    # ratio=1 scale=8 on base 16 → 128x128 box centred at 7.5
    r1 = base[1]
    assert r1[2] - r1[0] + 1 == pytest.approx(128)
    assert (r1[0] + r1[2]) / 2 == pytest.approx(7.5)
    grid = np.asarray(a.generate(2, 3, 16.0))
    assert grid.shape == (2 * 3 * 3, 4)
    # shifting by one stride moves anchors by 16 in x
    np.testing.assert_allclose(grid[3] - grid[0], [16, 0, 16, 0])


def test_bbox_transform_roundtrip():
    rng = np.random.RandomState(3)
    ex = rng.rand(10, 4) * 50
    ex[:, 2:] = ex[:, :2] + rng.rand(10, 2) * 60 + 4
    gt = rng.rand(10, 4) * 50
    gt[:, 2:] = gt[:, :2] + rng.rand(10, 2) * 60 + 4
    deltas = bbox_encode(jnp.asarray(ex), jnp.asarray(gt))
    back = bbox_transform_inv(jnp.asarray(ex), deltas)
    np.testing.assert_allclose(np.asarray(back), gt, rtol=1e-4, atol=1e-3)


def test_clip_boxes():
    b = jnp.asarray([[-5.0, -5.0, 200.0, 90.0]])
    out = np.asarray(clip_boxes(b, 100, 150))
    np.testing.assert_allclose(out[0], [0, 0, 149, 90])


def test_fpn_shapes_and_topdown():
    set_seed(0)
    fpn = FPN([8, 16, 32], 4, top_blocks=1)
    feats = [jnp.ones((1, 16, 16, 8)), jnp.ones((1, 8, 8, 16)),
             jnp.ones((1, 4, 4, 32))]
    outs = fpn(feats)
    assert [tuple(o.shape) for o in outs] == [
        (1, 16, 16, 4), (1, 8, 8, 4), (1, 4, 4, 4), (1, 2, 2, 4)]


def test_pooler_level_assignment():
    set_seed(0)
    p = Pooler(3, [0.25, 0.125], 2)
    assert p.lvl_min == 2 and p.lvl_max == 3
    rois = jnp.asarray([[0, 0, 40, 40],        # tiny → lvl 2
                        [0, 0, 120, 120]],     # large → lvl 3
                       jnp.float32)
    lv = np.asarray(p.level_of(rois))
    assert lv[0] == 2 and lv[1] == 3
    feats = [jnp.ones((1, 32, 32, 4)), jnp.ones((1, 16, 16, 4))]
    out = p((feats, rois))
    assert out.shape == (2, 3, 3, 4)
    np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-5)


@pytest.mark.slow
def test_region_proposal_shapes():
    set_seed(1)
    rpn = RegionProposal(8, anchor_sizes=[32, 64], aspect_ratios=[1.0],
                         anchor_stride=[4, 8], pre_nms_topn_test=50,
                         post_nms_topn_test=20)
    rpn.eval_mode()
    feats = [jnp.asarray(np.random.RandomState(0).randn(1, 16, 16, 8),
                         jnp.float32),
             jnp.asarray(np.random.RandomState(1).randn(1, 8, 8, 8),
                         jnp.float32)]
    boxes, scores = rpn((feats, jnp.asarray([64.0, 64.0])))
    assert boxes.shape == (20, 4)
    assert scores.shape == (20,)
    b = np.asarray(boxes)
    assert (b[:, 2] >= b[:, 0] - 1).all() and (b[:, 3] >= b[:, 1] - 1).all()
    assert b.min() >= -1e-5 and b.max() <= 63.0 + 1e-4

@pytest.mark.slow
def test_proposal_shapes():
    set_seed(2)
    prop = Proposal(pre_nms_topn=60, post_nms_topn=10,
                    ratios=[0.5, 1.0, 2.0], scales=[8.0])
    prop.eval_mode()
    a = prop.anchor.anchor_num
    rng = np.random.RandomState(0)
    cls = jax.nn.softmax(
        jnp.asarray(rng.randn(1, 6, 6, 2 * a), jnp.float32), -1)
    bbox = jnp.asarray(rng.randn(1, 6, 6, 4 * a) * 0.1, jnp.float32)
    rois, scores = prop((cls, bbox, jnp.asarray([96.0, 96.0, 1.0, 1.0])))
    assert rois.shape == (10, 5)
    np.testing.assert_allclose(np.asarray(rois[:, 0]), 0.0)


@pytest.mark.slow
def test_box_head_end_to_end_shapes():
    set_seed(3)
    head = BoxHead(in_channels=4, resolution=3, scales=[0.25, 0.125],
                   sampling_ratio=2, score_thresh=0.0, nms_thresh=0.5,
                   max_per_image=8, output_size=16, num_classes=5)
    head.eval_mode()
    feats = [jnp.asarray(np.random.RandomState(1).randn(1, 32, 32, 4),
                         jnp.float32),
             jnp.asarray(np.random.RandomState(2).randn(1, 16, 16, 4),
                         jnp.float32)]
    props = jnp.asarray([[0, 0, 30, 30], [10, 10, 100, 100],
                         [5, 5, 64, 40]], jnp.float32)
    boxes, labels, scores, valid = head((feats, props,
                                         jnp.asarray([128.0, 128.0])))
    assert boxes.shape == (8, 4) and labels.shape == (8,)
    assert scores.shape == (8,) and valid.shape == (8,)
    lb = np.asarray(labels)[np.asarray(valid)]
    assert ((lb >= 1) & (lb < 5)).all()


@pytest.mark.slow
def test_mask_head_shapes():
    set_seed(4)
    mh = MaskHead(in_channels=4, resolution=4, scales=[0.25],
                  sampling_ratio=2, layers=[8, 8], dilation=1,
                  num_classes=3)
    feats = [jnp.asarray(np.random.RandomState(3).randn(1, 16, 16, 4),
                         jnp.float32)]
    boxes = jnp.asarray([[0, 0, 20, 20], [8, 8, 40, 40]], jnp.float32)
    labels = jnp.asarray([1, 2], jnp.int32)
    masks, logits = mh((feats, boxes, labels))
    assert masks.shape == (2, 8, 8)
    assert logits.shape == (2, 3, 8, 8)
    m = np.asarray(masks)
    assert (m >= 0).all() and (m <= 1).all()


def test_prior_box_values():
    pb = PriorBox(min_sizes=[30.0], max_sizes=[60.0],
                  aspect_ratios=[2.0], is_flip=True, is_clip=False,
                  variances=[0.1, 0.1, 0.2, 0.2], img_size=300,
                  step=100.0)
    # priors per location: 1 (min) + 1 (sqrt(min*max)) + 2 (ar 2, 1/2)
    assert pb.num_priors == 4
    feat = jnp.zeros((1, 3, 3, 2))
    out = np.asarray(pb(feat))
    assert out.shape == (2, 3 * 3 * 4 * 4)
    boxes = out[0].reshape(-1, 4)
    # first prior at cell (0,0): centred at 50,50, 30x30, normalized /300
    np.testing.assert_allclose(
        boxes[0], [(50 - 15) / 300, (50 - 15) / 300,
                   (50 + 15) / 300, (50 + 15) / 300], rtol=1e-5)
    var = out[1].reshape(-1, 4)
    np.testing.assert_allclose(var[0], [0.1, 0.1, 0.2, 0.2], rtol=1e-6)

@pytest.mark.slow
def test_detection_output_ssd():
    # 2 priors, 3 classes; zero loc deltas → boxes = priors
    priors = np.array([[0.1, 0.1, 0.3, 0.3], [0.6, 0.6, 0.9, 0.9]],
                      np.float32)
    var = np.full((2, 4), 0.1, np.float32)
    prior_t = jnp.asarray(np.stack([priors.ravel(), var.ravel()]))
    loc = jnp.zeros((1, 8))
    conf = jnp.asarray([[0.05, 0.9, 0.05,    # prior 1 → class 1
                         0.1, 0.1, 0.8]])    # prior 2 → class 2
    det = DetectionOutputSSD(n_classes=3, nms_thresh=0.45, keep_top_k=4,
                             conf_thresh=0.1)
    out = np.asarray(det((loc, conf, prior_t)))
    assert out.shape == (1, 4, 6)
    rows = out[0]
    # best two detections: class1@0.9 on prior1, class2@0.8 on prior2
    assert rows[0][0] == 1 and rows[0][1] == pytest.approx(0.9, abs=1e-5)
    np.testing.assert_allclose(rows[0][2:], priors[0], atol=1e-5)
    assert rows[1][0] == 2 and rows[1][1] == pytest.approx(0.8, abs=1e-5)
    np.testing.assert_allclose(rows[1][2:], priors[1], atol=1e-5)


def test_smooth_l1_with_weights():
    crit = nn.SmoothL1CriterionWithWeights(sigma=1.0, num=2)
    x = jnp.asarray([0.0, 2.0])
    t = jnp.asarray([0.25, 0.0])
    # d = [-0.25, 2]; loss = [0.5*0.0625, 1.5] = 0.03125 + 1.5
    got = float(crit(x, t))
    assert got == pytest.approx((0.03125 + 1.5) / 2)


def test_softmax_with_criterion():
    logits = jnp.asarray([[2.0, 1.0, 0.0], [0.0, 3.0, 0.0]])
    target = jnp.asarray([1.0, 2.0])
    crit = nn.SoftmaxWithCriterion()
    want = -float(jnp.mean(
        jax.nn.log_softmax(logits, -1)[jnp.arange(2),
                                       jnp.asarray([0, 1])]))
    assert float(crit(logits, target)) == pytest.approx(want, rel=1e-5)


def test_nms_jit_and_roi_align_jit():
    """The whole stack must be jittable (static shapes)."""
    layer = RoiAlign(0.5, 2, 2, 2)
    f = jax.jit(lambda feat, rois: layer((feat, rois)))
    out = f(jnp.ones((1, 8, 8, 2)), jnp.asarray([[0.0, 0.0, 8.0, 8.0]]))
    assert out.shape == (1, 2, 2, 2)


# ---------------- SSD-VGG16 (BASELINE config #5) ----------------

@pytest.mark.slow
def test_ssd_vgg16_300_architecture():
    """Canonical SSD-300: source maps 38/19/10/5/3/1 and 8,732 priors."""
    from bigdl_tpu.models import ssd_vgg16_300
    set_seed(0)
    m = ssd_vgg16_300(class_num=21).eval_mode()
    srcs = m.feature_maps(jnp.zeros((1, 300, 300, 3)))
    assert [tuple(s.shape[1:3]) for s in srcs] == [
        (38, 38), (19, 19), (10, 10), (5, 5), (3, 3), (1, 1)]
    total = sum(int(np.prod(p.forward(s).shape[1:])) // 4
                for p, s in zip(m.prior_layers, srcs))
    assert total == 8732
    out = m.forward(jnp.asarray(
        np.random.default_rng(0).normal(size=(1, 300, 300, 3)),
        jnp.float32))
    assert out.shape == (1, 200, 6)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_ssd_caffe_weight_import(tmp_path):
    """A caffemodel's blobs land in the same-named SSD layers (the
    reference's import-and-infer path, CaffeLoader.scala:57)."""
    from bigdl_tpu.interop.caffe import load_caffe_weights, save_caffemodel
    from bigdl_tpu.models import ssd_vgg16_300
    set_seed(0)
    m = ssd_vgg16_300(class_num=21)
    rng = np.random.RandomState(3)
    weights = {
        "conv1_1": {"type": "Convolution", "bottom": [], "top": [],
                    "blobs": [rng.randn(64, 3, 3, 3).astype(np.float32),
                              rng.randn(64).astype(np.float32)]},
        "conv6_1": {"type": "Convolution", "bottom": [], "top": [],
                    "blobs": [rng.randn(256, 1024, 1, 1).astype(np.float32),
                              rng.randn(256).astype(np.float32)]},
        "conv4_3_norm": {"type": "Normalize", "bottom": [], "top": [],
                         "blobs": [rng.randn(512).astype(np.float32)]},
    }
    p = str(tmp_path / "ssd.caffemodel")
    save_caffemodel(p, weights)
    _, copied = load_caffe_weights(m, None, p)
    assert set(copied) == {"conv1_1", "conv6_1", "conv4_3_norm"}
    named = {mod.get_name(): mod for _, mod in m.named_modules()}
    np.testing.assert_allclose(
        np.asarray(named["conv1_1"].weight),
        np.transpose(weights["conv1_1"]["blobs"][0], (2, 3, 1, 0)))
    np.testing.assert_allclose(np.asarray(named["conv4_3_norm"].weight),
                               weights["conv4_3_norm"]["blobs"][0])


def test_ssd_detection_output_map():
    """DetectionOutputSSD recovers planted boxes; VOC mAP == 1.0."""
    from bigdl_tpu.optim.validation import (
        MeanAveragePrecisionObjectDetection,
    )
    set_seed(0)
    # 4 priors spread out; loc = 0 so decoded boxes == priors
    priors = np.array([[0.05, 0.05, 0.2, 0.2], [0.3, 0.3, 0.5, 0.5],
                       [0.6, 0.6, 0.8, 0.8], [0.1, 0.6, 0.3, 0.9]],
                      np.float32)
    var = np.full_like(priors, 0.1)
    prior = jnp.asarray(np.stack([priors.reshape(-1), var.reshape(-1)]))
    loc = jnp.zeros((1, 16))
    conf = np.full((4, 3), 0.01, np.float32)
    conf[0, 1] = 0.95   # prior 0 → class 1
    conf[2, 2] = 0.9    # prior 2 → class 2
    det = DetectionOutputSSD(n_classes=3, keep_top_k=8, nms_topk=4,
                             conf_thresh=0.5)
    out = np.asarray(det((loc, jnp.asarray(conf.reshape(1, -1)), prior)))[0]
    kept = out[out[:, 1] > 0]
    assert kept.shape[0] == 2
    m = MeanAveragePrecisionObjectDetection(classes=2, iou_thresh=0.5)
    dets = [(kept[:, 0].astype(int), kept[:, 1], kept[:, 2:6])]
    gts = [(np.array([1, 2]), priors[[0, 2]])]
    assert m.evaluate(dets, gts) == 1.0

@pytest.mark.slow
def test_nms_pre_topk_matches_full():
    """Regression (round-1 advisor #2): pre-top-k capping must not
    change the result when the winners are inside the cap."""
    rng = np.random.RandomState(0)
    # 10 well-separated high-score boxes + 30 low-score jitters of them
    base = np.stack([np.linspace(0, 9, 10) * 30,
                     np.zeros(10),
                     np.linspace(0, 9, 10) * 30 + 20,
                     np.full(10, 20.0)], 1).astype(np.float32)
    jitter = np.repeat(base, 3, axis=0) + rng.rand(30, 4).astype(np.float32)
    boxes = jnp.asarray(np.concatenate([base, jitter]))
    scores = jnp.asarray(np.concatenate([
        0.9 + 0.01 * rng.rand(10), 0.1 * rng.rand(30)]).astype(np.float32))
    from bigdl_tpu.nn.detection import nms
    idx_full, val_full = nms(boxes, scores, 0.5, 10)
    idx_cap, val_cap = nms(boxes, scores, 0.5, 10, pre_topk=15)
    np.testing.assert_array_equal(np.asarray(val_full), np.asarray(val_cap))
    np.testing.assert_array_equal(np.asarray(idx_full)[np.asarray(val_full)],
                                  np.asarray(idx_cap)[np.asarray(val_cap)])


@pytest.mark.slow
def test_boxhead_masks_padded_proposals():
    """Regression (round-1 advisor #1): padded proposal slots must not
    produce detections when the validity mask is supplied."""
    set_seed(5)
    head = BoxHead(in_channels=4, resolution=3, scales=[0.25],
                   sampling_ratio=2, score_thresh=0.01, nms_thresh=0.99,
                   max_per_image=16, output_size=8, num_classes=2)
    feats = [jnp.asarray(np.random.RandomState(1).rand(1, 16, 16, 4),
                         jnp.float32)]
    # 4 real well-separated proposals + 4 padded zero slots
    real = np.array([[0, 0, 15, 15], [20, 0, 35, 15],
                     [40, 0, 55, 15], [0, 20, 15, 35]], np.float32)
    proposals = jnp.asarray(np.concatenate([real, np.zeros((4, 4))]),
                            jnp.float32)
    im_info = jnp.asarray([64.0, 64.0])
    pvalid = jnp.asarray([True] * 4 + [False] * 4)
    _, _, _, valid_masked = head((feats, proposals, im_info, pvalid))
    _, _, _, valid_unmasked = head((feats, proposals, im_info))
    assert int(valid_masked.sum()) <= 4
    assert int(valid_unmasked.sum()) > int(valid_masked.sum())


@pytest.mark.slow
def test_ssd_int8_quantized_inference():
    """BASELINE config #5: int8-quantized SSD inference runs and stays
    close to the fp32 detections (whitepaper fig10 recipe: <0.1%
    accuracy drop at up to 2x speedup)."""
    from bigdl_tpu.models import ssd_vgg16_300
    from bigdl_tpu.nn.quantized import Quantizer
    set_seed(0)
    m = ssd_vgg16_300(class_num=4, conf_thresh=0.05).eval_mode()
    q = Quantizer.quantize(m)
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(1, 300, 300, 3)), jnp.float32)
    out_f = np.asarray(m.forward(x))[0]
    out_q = np.asarray(q.forward(x))[0]
    assert out_q.shape == out_f.shape
    assert np.isfinite(out_q).all()
    # top detections must agree as a SET: near-tied scores reorder rows
    # between fp32 and int8, so match each fp32 detection to its nearest
    # int8 detection of the same label instead of comparing by rank
    for row in out_f[:5]:
        same = out_q[out_q[:, 0] == row[0]]
        assert same.shape[0] > 0, f"label {row[0]} lost under int8"
        d = np.abs(same[:, 2:] - row[2:]).max(axis=1)
        j = int(np.argmin(d))
        assert d[j] < 0.05, f"no int8 match for {row} (nearest {same[j]})"
        assert abs(same[j, 1] - row[1]) < 0.05
