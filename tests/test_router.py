"""Serving fabric tests (serving/router.py + serving/replica.py):
hash-ring determinism and rebalance-on-leave, bounded-load overflow,
SLO-aware shedding with a deliberately slowed replica, drain/deploy
with the zero-drop invariant asserted, stale/corrupt snapshots read as
unhealthy, /healthz drain consumption, single-flight prefill dedup,
and the disaggregated prefill→decode handoff — bit-identical to the
single-engine greedy rows and to solo ``generate()``.

The load-bearing assertions: (a) a deploy drops NOTHING it admitted —
``admitted_outstanding()`` reaches exactly 0 before the old replica is
removed and every pre-drain future resolves with a result; (b) under
overload the router answers with TYPED rejections, never timeouts;
(c) an 8-way identical cold-prompt burst runs exactly one prefill.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from bigdl_tpu.models import transformer_lm
from bigdl_tpu.serving import (
    DisaggregatedEngine, GenerationScheduler, ModelServer,
    NoReplicaAvailableError, Replica, RequestSheddedError, Router,
)
from bigdl_tpu.serving.replica import ReplicaRegistry, scrape_healthz
from bigdl_tpu.serving.router import HashRing, RouterRequest
from bigdl_tpu.telemetry import events
from bigdl_tpu.telemetry.fleet import write_host_snapshot
from bigdl_tpu.utils import set_seed


@pytest.fixture(scope="module")
def lm():
    set_seed(0)
    return transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                          num_heads=4, filter_size=64,
                          max_len=64).eval_mode()


def solo(model, prompt, max_new, eos_id=None):
    import jax.numpy as jnp
    return np.asarray(model.generate(
        jnp.asarray(prompt, jnp.int32)[None], int(max_new),
        eos_id=eos_id))[0]


def _replica(lm, rid, d, slots=2, interval=0.05, **server_kw):
    return Replica(rid, ModelServer(generator=lm, slots=slots,
                                    **server_kw),
                   snapshot_dir=d, publish_interval_s=interval)


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.perf_counter() + timeout
    while not cond():
        if time.perf_counter() > deadline:
            raise TimeoutError(f"{msg} not reached in {timeout}s")
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# hash ring
# ---------------------------------------------------------------------------

def test_hash_ring_deterministic_across_instances():
    a, b = HashRing(), HashRing()
    for ring in (a, b):
        for rid in (3, 1, 7):
            ring.add(rid)
    for key in ("user-1", "user-2", "s", "a-long-session-key", "42"):
        assert a.preference(key) == b.preference(key)
        assert sorted(a.preference(key)) == [1, 3, 7]


def test_hash_ring_balances_keys():
    ring = HashRing()
    for rid in range(4):
        ring.add(rid)
    homes = [ring.preference(f"k{i}")[0] for i in range(400)]
    counts = {rid: homes.count(rid) for rid in range(4)}
    # virtual nodes keep the split rough-uniform: nobody owns more
    # than half or less than a twentieth of the keyspace
    assert all(20 <= c <= 200 for c in counts.values()), counts


def test_hash_ring_rebalance_on_leave_moves_only_orphans():
    ring = HashRing()
    for rid in range(4):
        ring.add(rid)
    keys = [f"session-{i}" for i in range(300)]
    before = {k: ring.preference(k)[0] for k in keys}
    ring.remove(2)
    after = {k: ring.preference(k)[0] for k in keys}
    for k in keys:
        if before[k] != 2:
            # the consistent-hashing contract: a leave moves ONLY the
            # departed replica's keys — everyone else keeps their warm
            # prefix caches
            assert after[k] == before[k]
        else:
            assert after[k] != 2
    with pytest.raises(KeyError):
        ring.remove(2)
    with pytest.raises(ValueError):
        ring.add(3)


# ---------------------------------------------------------------------------
# registry: stale / corrupt / healthz
# ---------------------------------------------------------------------------

def test_registry_stale_snapshot_is_unhealthy(tmp_path):
    d = str(tmp_path)
    reg = ReplicaRegistry(d, max_age_s=0.2)
    from bigdl_tpu.serving.replica import replica_snapshot
    write_host_snapshot(d, replica_snapshot(0, None, name="fresh"))
    stale = replica_snapshot(1, None, name="stale")
    stale["time"] -= 10.0
    write_host_snapshot(d, stale)
    recs = reg.poll()
    assert recs[0]["healthy"] and recs[0]["reason"] is None
    assert not recs[1]["healthy"] and recs[1]["reason"] == "stale"


def test_registry_corrupt_snapshot_is_unhealthy(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "fleet_host_5.json"), "w") as f:
        f.write("{torn half-write")
    recs = ReplicaRegistry(d).poll()
    assert not recs[5]["healthy"] and recs[5]["reason"] == "corrupt"


def test_registry_consumes_healthz_503_as_draining(tmp_path):
    d = str(tmp_path)
    from bigdl_tpu.serving.replica import replica_snapshot
    write_host_snapshot(d, replica_snapshot(0, None))
    reg = ReplicaRegistry(d)
    assert not reg.poll()[0]["draining"]
    reg.observe_healthz(0, 503, {"status": "draining"})
    rec = reg.poll()[0]
    assert rec["draining"] and rec["healthy"]
    reg.observe_healthz(0, 200, {"status": "ok"})
    assert not reg.poll()[0]["draining"]
    reg.observe_healthz(0, 500, {})
    assert not reg.poll()[0]["healthy"]


def test_registry_scrapes_real_healthz_drain(tmp_path):
    """End-to-end against the real HTTP frontend: a draining
    examples/serve.py replica answers 503 and the registry consumes
    it into the record."""
    from bigdl_tpu.examples.serve import make_server
    server = make_server(object(), "127.0.0.1", 0)
    import threading
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        d = str(tmp_path)
        from bigdl_tpu.serving.replica import replica_snapshot
        write_host_snapshot(d, replica_snapshot(0, None))
        reg = ReplicaRegistry(d)
        port = server.server_port
        code, body = scrape_healthz("127.0.0.1", port)
        assert code == 200 and body["status"] == "ok"
        reg.observe_healthz(0, code, body)
        assert not reg.poll()[0]["draining"]
        server.health_state["draining"] = True
        code, body = scrape_healthz("127.0.0.1", port)
        assert code == 503 and body["status"] == "draining"
        reg.observe_healthz(0, code, body)
        assert reg.poll()[0]["draining"]
    finally:
        server.shutdown()
        server.server_close()
        t.join(5.0)


# ---------------------------------------------------------------------------
# routing: affinity, bounded load, SLO shed
# ---------------------------------------------------------------------------

def test_session_affinity_routes_to_ring_home(lm, tmp_path):
    d = str(tmp_path)
    reps = [_replica(lm, i, d) for i in range(3)]
    router = Router(replicas=reps, snapshot_dir=d, poll_interval_s=0.02)
    try:
        rng = np.random.default_rng(3)
        sessions = [f"user-{i}" for i in range(6)]
        # two waves of the same sessions: each wave's request for a
        # given key must land on the SAME replica
        for _wave in range(2):
            futs = [router.submit_generate_async(
                rng.integers(1, 50, 6).astype(np.int32), 4, session=s)
                for s in sessions]
            for f in futs:
                f.result(60)
        st = router.stats()
        assert st["outcomes"].get("ok") == 12
        assert st["affinity_hit_rate"] == 1.0     # nobody overflowed
    finally:
        router.shutdown()


def test_bounded_load_spills_hot_session_key(lm, tmp_path):
    """One hot session key must not wedge its home replica: once the
    home's in-flight count hits the bound, requests walk the ring."""
    d = str(tmp_path)
    reps = [_replica(lm, i, d, slots=1) for i in range(2)]
    router = Router(replicas=reps, snapshot_dir=d, poll_interval_s=0.01,
                    bounded_load_factor=1.0)
    try:
        rng = np.random.default_rng(4)
        # long decodes keep the home busy while the burst arrives
        futs = [router.submit_generate_async(
            rng.integers(1, 50, 4).astype(np.int32), 40,
            session="one-viral-session") for _ in range(6)]
        for f in futs:
            f.result(120)
        done = [r.stats().get("requests_done", 0) for r in reps]
        assert sum(done) == 6
        assert all(n > 0 for n in done), \
            f"hot key never spilled off its home replica: {done}"
        st = router.stats()
        assert st["affinity_hit_rate"] < 1.0
    finally:
        router.shutdown()


def _saturate_ttft(replica, lm, n=8, max_new=30):
    """Genuinely slow a 1-slot replica: queue enough long decodes that
    late requests' queue-to-first-token climbs, then wait for them so
    the p99 reservoir holds the breach."""
    rng = np.random.default_rng(5)
    futs = [replica.submit_generate_async(
        rng.integers(1, 50, 4).astype(np.int32), max_new)
        for _ in range(n)]
    for f in futs:
        f.result(120)


def test_slo_breached_replica_stops_receiving_non_affine_work(
        lm, tmp_path):
    d = str(tmp_path)
    slow = _replica(lm, 0, d, slots=1)
    fast = _replica(lm, 1, d, slots=2)
    _saturate_ttft(slow, lm)
    p99 = slow.stats()["queue_to_first_token_s_p99"]
    assert p99 > 0.0
    router = Router(replicas=[slow, fast], snapshot_dir=d,
                    poll_interval_s=0.01, slo_ttft_p99_s=p99 / 2)
    try:
        _wait(lambda: 0 in router.records()
              and router.records()[0].get("ttft_p99_s", 0) > p99 / 2,
              msg="registry sees the breach")
        before = [slow.stats()["requests_done"],
                  fast.stats()["requests_done"]]
        rng = np.random.default_rng(6)
        futs = [router.submit_generate_async(
            rng.integers(1, 50, 4).astype(np.int32), 4)
            for _ in range(5)]        # NON-affine: no session key
        for f in futs:
            f.result(60)
        after = [slow.stats()["requests_done"],
                 fast.stats()["requests_done"]]
        assert after[0] == before[0], \
            "SLO-breached replica still received non-affine work"
        assert after[1] == before[1] + 5
    finally:
        router.shutdown()


def test_all_replicas_breached_sheds_typed_not_timeout(lm, tmp_path):
    d = str(tmp_path)
    slow = _replica(lm, 0, d, slots=1)
    _saturate_ttft(slow, lm)
    p99 = slow.stats()["queue_to_first_token_s_p99"]
    router = Router(replicas=[slow], snapshot_dir=d,
                    poll_interval_s=0.01, slo_ttft_p99_s=p99 / 2,
                    shed_after_s=0.15)
    try:
        _wait(lambda: router.records().get(0, {}).get("ttft_p99_s", 0)
              > p99 / 2, msg="registry sees the breach")
        t0 = time.perf_counter()
        fut = router.submit_generate_async(
            np.asarray([3, 4, 5], np.int32), 4)   # non-affine
        with pytest.raises(RequestSheddedError):
            fut.result(30)
        waited = time.perf_counter() - t0
        assert waited < 5.0, "shed must be a fast typed no, not a " \
            "timeout"
        assert router.stats()["shed_reasons"].get("slo", 0) >= 1
        kinds = [e["kind"] for e in events.recent_events(50)]
        assert "router_shed" in kinds
        # affine work still reaches the breached replica (warm cache)
        before = slow.stats()["requests_done"]
        router.submit_generate(np.asarray([3, 4, 5], np.int32), 4,
                               session="sticky", timeout=60)
        assert slow.stats()["requests_done"] == before + 1
    finally:
        router.shutdown()


def test_admission_budget_sheds_with_budget_reason(lm, tmp_path):
    d = str(tmp_path)
    rep = _replica(lm, 0, d, slots=2)
    router = Router(replicas=[rep], snapshot_dir=d,
                    poll_interval_s=0.01, shed_after_s=0.1,
                    admission_budgets={"budgeted": 0})
    try:
        fut = router.submit_generate_async(
            np.asarray([3, 4, 5], np.int32), 4, model="budgeted")
        with pytest.raises(NoReplicaAvailableError):
            fut.result(30)
        assert router.stats()["shed_reasons"].get("budget", 0) >= 1
        # other models are untouched by that budget
        row = router.submit_generate(np.asarray([3, 4, 5], np.int32),
                                     4, timeout=60)
        assert len(row) == 7
    finally:
        router.shutdown()


def test_replica_without_snapshot_dir_adopted_stays_routable(lm):
    """The README construction path: Replicas built with NO
    snapshot_dir are adopted by the router — which must START their
    interval publishers, or the fleet silently goes stale-unroutable
    max_age_s after the single adoption-time publish."""
    reps = [Replica(i, ModelServer(generator=lm, slots=2))
            for i in range(2)]
    router = Router(replicas=reps, poll_interval_s=0.02,
                    registry_max_age_s=0.4)
    try:
        time.sleep(1.0)     # > 2x max_age: only live publishing keeps
        # the records fresh
        recs = router.records()
        assert recs and all(r["healthy"] for r in recs.values()), recs
        row = router.submit_generate(np.asarray([3, 4, 5], np.int32),
                                     4, timeout=60)
        assert len(row) == 7
    finally:
        router.shutdown()


def test_budget_blocked_model_does_not_starve_others(lm, tmp_path):
    """A budget-exhausted model's parked request must not
    head-of-line-block other models: model-B traffic keeps flowing
    while the model-A request waits out its shed deadline."""
    d = str(tmp_path)
    rep = _replica(lm, 0, d, slots=2)
    router = Router(replicas=[rep], snapshot_dir=d,
                    poll_interval_s=0.01, shed_after_s=3.0,
                    admission_budgets={"A": 0})
    try:
        futA = router.submit_generate_async(
            np.asarray([3, 4, 5], np.int32), 4, model="A")
        t0 = time.perf_counter()
        rowB = router.submit_generate(np.asarray([3, 4, 5], np.int32),
                                      4, model="B", timeout=60)
        b_wall = time.perf_counter() - t0
        assert len(rowB) == 7
        assert b_wall < 2.0, \
            f"model-B request waited {b_wall:.2f}s behind a " \
            f"budget-blocked model-A head"
        with pytest.raises(NoReplicaAvailableError):
            futA.result(30)
    finally:
        router.shutdown()


def test_no_replica_sheds_typed(tmp_path, lm):
    router = Router(replicas=[], snapshot_dir=str(tmp_path),
                    poll_interval_s=0.01, shed_after_s=0.1)
    try:
        fut = router.submit_generate_async(
            np.asarray([3, 4, 5], np.int32), 4)
        with pytest.raises(NoReplicaAvailableError):
            fut.result(30)
        assert router.stats()["shed_reasons"].get("no_replica", 0) >= 1
    finally:
        router.shutdown(close_replicas=False)


class _FakeTarget:
    """Minimal replica target for routing-logic tests: healthy
    snapshots, optional always-full admission."""

    def __init__(self, full: bool = False, slots: int = 2):
        self._full = full
        self._slots = slots

    def submit_generate_async(self, prompt, max_new_tokens,
                              eos_id=None, on_token=None, timeout=None):
        from concurrent.futures import Future

        from bigdl_tpu.serving import QueueFullError
        if self._full:
            raise QueueFullError("engine queue at capacity")
        f = Future()
        f.set_result(np.zeros(3, np.int32))
        return f

    def shutdown(self, drain=True, timeout=None):
        pass

    def admitted_outstanding(self):
        return 0

    def queue_depth(self):
        return 0

    def stats(self):
        return {"slots": self._slots}


def test_wedged_full_replica_still_sheds_at_deadline(tmp_path):
    """A replica that keeps answering queue-full (healthy snapshot,
    wedged engine) must not turn the typed-rejection contract into an
    indefinite hang: the shed deadline applies to the dispatch-failure
    park path too."""
    d = str(tmp_path)
    rep = Replica(0, _FakeTarget(full=True), snapshot_dir=d,
                  publish_interval_s=0.05)
    router = Router(replicas=[rep], snapshot_dir=d,
                    poll_interval_s=0.01, shed_after_s=0.25)
    try:
        t0 = time.perf_counter()
        fut = router.submit_generate_async(
            np.asarray([3, 4, 5], np.int32), 4)
        with pytest.raises(NoReplicaAvailableError):
            fut.result(30)
        assert time.perf_counter() - t0 < 5.0, \
            "shed took far longer than the deadline"
    finally:
        router.shutdown()


def test_affine_spill_respects_slo_gate(tmp_path):
    """Only the session's HOME replica keeps its SLO exemption (its
    warm cache is the justification); a bounded-load spill stop holds
    none of the session's cache and must pass the same SLO gate as
    non-affine work."""
    d = str(tmp_path)
    reps = [Replica(i, _FakeTarget(), snapshot_dir=d,
                    publish_interval_s=0.05) for i in (0, 1)]
    # factor 1.0 so the home can actually hit its bound with two
    # replicas (at c=2, n=2 the ceil(c*mean) cap exceeds any single
    # replica's possible share and never binds)
    router = Router(replicas=reps, snapshot_dir=d, start=False,
                    poll_interval_s=0.01, slo_ttft_p99_s=0.05,
                    bounded_load_factor=1.0)
    try:
        key = next(k for k in (f"s{i}" for i in range(50))
                   if router._ring.preference(k)[0] == 0)
        # breach replica 1's SLO in the records the pick routes on
        with router._lock:
            router._records[1]["ttft_p99_s"] = 1.0
        # home healthy within SLO: session routes home
        r = RouterRequest(np.asarray([3], np.int32), 1, session=key)
        assert router._pick(r) == (0, None)
        # home at bound: the spill stop is breached -> shed, not spill
        with router._lock:
            router._inflight[0] = 10 ** 6
        assert router._pick(r) == (None, "slo")
        # home itself breached but with room: sessions still ride it
        with router._lock:
            router._inflight[0] = 0
            router._records[0]["ttft_p99_s"] = 1.0
        assert router._pick(r) == (0, None)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# drain / deploy
# ---------------------------------------------------------------------------

def test_drain_reroutes_new_sessions_and_finishes_admitted(
        lm, tmp_path):
    d = str(tmp_path)
    reps = [_replica(lm, i, d) for i in range(2)]
    router = Router(replicas=reps, snapshot_dir=d, poll_interval_s=0.01)
    try:
        rng = np.random.default_rng(7)
        # find a session whose ring home is replica 0, pin some work
        key = next(k for k in (f"s{i}" for i in range(50))
                   if router._ring.preference(k)[0] == 0)
        futs = [router.submit_generate_async(
            rng.integers(1, 50, 4).astype(np.int32), 24, session=key)
            for _ in range(3)]
        _wait(lambda: reps[0].admitted_outstanding() > 0,
              msg="work admitted to replica 0")
        router.drain(0)
        assert router.records()[0]["draining"]
        # new work for the SAME session now lands on replica 1
        before = reps[1].stats()["requests_done"]
        router.submit_generate(rng.integers(1, 50, 4).astype(np.int32),
                               4, session=key, timeout=60)
        assert reps[1].stats()["requests_done"] == before + 1
        # the admitted work still finishes — nothing dropped
        for f in futs:
            assert len(f.result(120)) > 0
        assert reps[0].admitted_outstanding() == 0
        kinds = [e["kind"] for e in events.recent_events(50)]
        assert "replica_drain" in kinds and "replica_join" in kinds
    finally:
        router.shutdown()


def test_deploy_zero_drop_swap(lm, tmp_path):
    """The acceptance e2e: requests in flight on the old replica, a
    replacement deploys, and the router ASSERTS zero admitted drops via
    admitted_outstanding() before removal — every pre-drain future
    resolves with a real row."""
    d = str(tmp_path)
    reps = [_replica(lm, i, d, slots=2) for i in range(2)]
    router = Router(replicas=reps, snapshot_dir=d, poll_interval_s=0.01)
    try:
        rng = np.random.default_rng(8)
        futs = [router.submit_generate_async(
            rng.integers(1, 50, 6).astype(np.int32), 24,
            session=f"u{i}") for i in range(8)]
        _wait(lambda: sum(r.admitted_outstanding() for r in reps) > 0,
              msg="fleet has admitted work")
        new = _replica(lm, 9, d, slots=2)
        res = router.deploy(new, replaces=0, timeout=120)
        assert res["outstanding_at_removal"] == 0
        assert res["added"] == 9 and res["replaced"] == 0
        assert set(router.replica_ids()) == {1, 9}
        rows = [f.result(120) for f in futs]
        assert len(rows) == 8 and all(len(r) == 6 + 24 for r in rows)
        # no typed rejections, no drops: every outcome is ok
        st = router.stats()
        assert st["outcomes"].get("ok", 0) >= 8
        assert "shed" not in st["outcomes"]
        # the old replica's snapshot file is gone from the registry
        assert 0 not in router.registry.poll()
        # new sessions land on the survivor set only
        router.submit_generate(rng.integers(1, 50, 4).astype(np.int32),
                               4, session="post-deploy", timeout=60)
    finally:
        router.shutdown()


def test_deploy_drain_deadline_exceeded_mid_swap(lm, tmp_path):
    """deploy() whose old replica cannot drain in time: TimeoutError,
    the old replica stays REGISTERED and DRAINING (nothing dropped),
    the new replica is already serving, and the wedged request still
    finishes afterwards."""
    d = str(tmp_path)
    reps = [_replica(lm, i, d) for i in range(2)]
    router = Router(replicas=reps, snapshot_dir=d, poll_interval_s=0.01)
    try:
        key = next(k for k in (f"s{i}" for i in range(50))
                   if router._ring.preference(k)[0] == 0)
        paced = threading.Event()

        def pace(_tok):
            paced.set()
            time.sleep(0.05)    # ~40 paced tokens: >=2s of drain debt

        prompt = np.array([5, 6, 7], np.int32)
        fut = router.submit_generate_async(prompt, 40, session=key,
                                           on_token=pace)
        assert paced.wait(60.0), "paced stream never started"
        new = _replica(lm, 9, d)
        with pytest.raises(TimeoutError):
            router.deploy(new, replaces=0, timeout=0.3)
        # mid-swap state: old replica still held (draining), new one in
        assert set(router.replica_ids()) == {0, 1, 9}
        assert router.records()[0]["draining"]
        # the admitted request was NOT dropped by the failed swap
        row = fut.result(120)
        np.testing.assert_array_equal(row, solo(lm, prompt, 40))
        _wait(lambda: reps[0].admitted_outstanding() == 0,
              msg="old replica drained after all")
    finally:
        router.shutdown()


def test_remove_replica_no_drain_with_admitted_requests(lm, tmp_path):
    """remove_replica(drain=False) while requests are admitted: the
    rude removal must not strand a single future — every admitted
    request resolves bit-identical (served by the dying replica's
    last breaths or replayed onto the survivor)."""
    d = str(tmp_path)
    reps = [_replica(lm, i, d) for i in range(2)]
    router = Router(replicas=reps, snapshot_dir=d, poll_interval_s=0.01)
    try:
        key = next(k for k in (f"s{i}" for i in range(50))
                   if router._ring.preference(k)[0] == 0)
        prompts = [np.array([2 + i, 3 + i, 4 + i], np.int32)
                   for i in range(4)]
        futs = [router.submit_generate_async(p, 16, session=key)
                for p in prompts]
        _wait(lambda: reps[0].admitted_outstanding() > 0,
              msg="work admitted to replica 0")
        router.remove_replica(0, drain=False, timeout=5.0)
        assert set(router.replica_ids()) == {1}
        assert 0 not in router.registry.poll()
        rows = [f.result(120) for f in futs]
        for row, p in zip(rows, prompts):
            np.testing.assert_array_equal(row, solo(lm, p, 16))
    finally:
        router.shutdown()


def test_preference_exhaustion_all_replicas_draining(lm, tmp_path):
    """Every ring stop draining: the affine preference list exhausts,
    the non-affine fallback finds nothing either, and the request is
    rejected TYPED (NoReplicaAvailableError) at the shed deadline —
    never a hang."""
    d = str(tmp_path)
    reps = [_replica(lm, i, d) for i in range(2)]
    router = Router(replicas=reps, snapshot_dir=d,
                    poll_interval_s=0.01, shed_after_s=0.3)
    try:
        router.drain(0)
        router.drain(1)
        _wait(lambda: all(r["draining"]
                          for r in router.records().values()),
              msg="both replicas draining")
        t0 = time.perf_counter()
        with pytest.raises(NoReplicaAvailableError):
            router.submit_generate(np.array([1, 2, 3], np.int32), 4,
                                   session="sticky", timeout=30.0)
        assert time.perf_counter() - t0 < 10.0
        st = router.stats()
        assert st["shed_reasons"].get("no_replica", 0) >= 1
        assert st["outcomes"].get("rejected", 0) >= 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# admitted_outstanding (the satellite API)
# ---------------------------------------------------------------------------

def test_model_server_admitted_outstanding_both_planes(lm):
    server = ModelServer(generator=lm, slots=2)
    try:
        assert server.admitted_outstanding() == 0
        futs = [server.submit_generate_async(
            np.asarray([3, 4, 5], np.int32), 12) for _ in range(3)]
        assert server.admitted_outstanding() >= 1
        for f in futs:
            f.result(60)
        _wait(lambda: server.admitted_outstanding() == 0,
              msg="outstanding back to zero")
    finally:
        server.shutdown()


def test_generation_scheduler_outstanding_counts_failures(lm):
    eng = GenerationScheduler(lm, slots=2)
    try:
        with pytest.raises(ValueError):
            eng.submit_async(np.asarray([3], np.int32), 0)  # mixed: >=1
        assert eng.admitted_outstanding() == 0
        fut = eng.submit_async(np.asarray([3, 4], np.int32), 4)
        fut.result(60)
        _wait(lambda: eng.admitted_outstanding() == 0,
              msg="outstanding drained")
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# single-flight prefill dedup
# ---------------------------------------------------------------------------

def test_identical_cold_burst_prefills_once(lm):
    """8-way identical cold burst: one leader prefill pass, 7
    followers park on the in-flight claim, everyone's rows equal
    solo generate()."""
    rng = np.random.default_rng(11)
    # region = 16 tokens = exactly 2 granules: followers need zero
    # suffix prefill after the leader's insert lands
    p = rng.integers(1, 50, 17).astype(np.int32)
    eng = GenerationScheduler(lm, slots=8, prefix_cache_bytes=1 << 24,
                              prefix_granularity=8, prefill_chunk=8)
    try:
        futs = [eng.submit_async(p, 4) for _ in range(8)]
        rows = [f.result(60) for f in futs]
        st = eng.stats()
    finally:
        eng.shutdown()
    oracle = solo(lm, p, 4)
    assert all(np.array_equal(r, oracle) for r in rows)
    assert st["prefill_dedup_leaders"] == 1
    assert st["prefill_dedup_followers"] == 7
    # the leader's 16-token region at chunk width 8 = exactly 2
    # prefill program calls for the WHOLE burst
    assert st["prefill_calls"] == 2
    assert st["prefix_cache"]["inserts"] == 2
    assert st["prefix_cache"]["hits"] >= 7
    assert st["prefix_cache"]["inflight_prefills"] == 0


def test_dedup_shared_prefix_longer_follower(lm):
    """A longer prompt sharing the leader's prefix parks, then wakes
    and prefills ONLY its own suffix chunks."""
    rng = np.random.default_rng(12)
    prefix = rng.integers(1, 50, 17).astype(np.int32)   # 2 granules
    longer = np.concatenate(
        [prefix[:-1], rng.integers(1, 50, 17).astype(np.int32)])
    eng = GenerationScheduler(lm, slots=4, prefix_cache_bytes=1 << 24,
                              prefix_granularity=8, prefill_chunk=8)
    try:
        f1 = eng.submit_async(prefix, 4)
        f2 = eng.submit_async(longer, 4)
        r1, r2 = f1.result(60), f2.result(60)
        st = eng.stats()
    finally:
        eng.shutdown()
    assert np.array_equal(r1, solo(lm, prefix, 4))
    assert np.array_equal(r2, solo(lm, longer, 4))
    assert st["prefill_dedup_followers"] >= 1


def test_dedup_leader_failure_promotes_follower(lm, monkeypatch):
    """If the leader's prefill dispatch fails, its claims release and
    a parked follower re-claims — the burst still completes (minus the
    failed leader) instead of stalling forever."""
    eng = GenerationScheduler(lm, slots=4, prefix_cache_bytes=1 << 24,
                              prefix_granularity=8, prefill_chunk=8)
    rng = np.random.default_rng(13)
    p = rng.integers(1, 50, 17).astype(np.int32)
    fired = {"n": 0}
    orig = eng.pool.chunk_prefill_into

    def flaky(toks, slot, index):
        if fired["n"] == 0:
            fired["n"] += 1
            raise RuntimeError("injected prefill fault")
        return orig(toks, slot, index)

    monkeypatch.setattr(eng.pool, "chunk_prefill_into", flaky)
    try:
        futs = [eng.submit_async(p, 4) for _ in range(3)]
        results = []
        errors = 0
        for f in futs:
            try:
                results.append(f.result(60))
            except RuntimeError:
                errors += 1
        assert errors == 1, "exactly the leader fails"
        oracle = solo(lm, p, 4)
        assert len(results) == 2
        assert all(np.array_equal(r, oracle) for r in results)
    finally:
        eng.shutdown()


def test_dedup_family_recorded_when_enabled(lm):
    from bigdl_tpu import telemetry
    telemetry.enable()
    telemetry.reset()
    try:
        rng = np.random.default_rng(14)
        p = rng.integers(1, 50, 17).astype(np.int32)
        eng = GenerationScheduler(lm, slots=4,
                                  prefix_cache_bytes=1 << 24,
                                  prefix_granularity=8, prefill_chunk=8)
        try:
            futs = [eng.submit_async(p, 4) for _ in range(4)]
            for f in futs:
                f.result(60)
        finally:
            eng.shutdown()
        text = telemetry.prometheus_text()
        assert 'generation_prefill_dedup_total{result="leader"}' in text
        assert 'generation_prefill_dedup_total{result="follower"}' \
            in text
    finally:
        telemetry.reset()
        telemetry.disable()


# ---------------------------------------------------------------------------
# disaggregated prefill -> decode
# ---------------------------------------------------------------------------

def test_disaggregated_handoff_bit_identical(lm):
    """The acceptance pin: disaggregated-mode greedy rows are
    bit-identical to the single-engine engine's rows AND to solo
    generate(), across mixed lengths (including sub-granule prompts
    that skip the prefill tier)."""
    rng = np.random.default_rng(15)
    prompts = [rng.integers(1, 50, int(n)).astype(np.int32)
               for n in [3, 9, 17, 25, 33, 40, 17, 33]]
    budgets = [int(b) for b in rng.integers(2, 12, len(prompts))]
    de = DisaggregatedEngine(lm, decode_slots=4, prefill_slots=2,
                             prefix_granularity=8, prefill_chunk=8)
    try:
        futs = [de.submit_generate_async(p, m)
                for p, m in zip(prompts, budgets)]
        dis_rows = [f.result(120) for f in futs]
        st = de.stats()
    finally:
        de.shutdown()
    single = GenerationScheduler(lm, slots=4, prefill_chunk=8,
                                 prefix_cache_bytes=1 << 24,
                                 prefix_granularity=8)
    try:
        futs = [single.submit_async(p, m)
                for p, m in zip(prompts, budgets)]
        single_rows = [f.result(120) for f in futs]
    finally:
        single.shutdown()
    for p, m, dr, sr in zip(prompts, budgets, dis_rows, single_rows):
        assert np.array_equal(dr, sr), "disaggregated != single-engine"
        assert np.array_equal(dr, solo(lm, p, m)), \
            "disaggregated != solo generate()"
    # the split actually happened: the prefill tier served the
    # granule-sized prompts, and decode admits hit the shared cache
    assert st["prefill_engine"]["requests_done"] >= 6
    assert st["handoffs"] == len(prompts)
    assert st["prefix_cache"]["hits"] >= 6


def test_disaggregated_decode_admits_only_cache_resident(lm):
    """The admission gate: once the prefill tier published a prompt's
    chunks, the decode engine's admission match covers the whole
    granularity-aligned region — its chunk-prefill work is only ever
    the sub-granule tail."""
    rng = np.random.default_rng(16)
    p = rng.integers(1, 50, 33).astype(np.int32)   # region 32 = 4*8
    de = DisaggregatedEngine(lm, decode_slots=2, prefill_slots=2,
                             prefix_granularity=8, prefill_chunk=8)
    try:
        row = de.submit_generate_async(p, 4).result(120)
        st = de.stats()
    finally:
        de.shutdown()
    assert np.array_equal(row, solo(lm, p, 4))
    # region is granularity-aligned: decode prefilled NOTHING
    assert st["prefill_calls"] == 0, \
        "decode engine ran prefill work the prefill tier owned"
    assert st["prefix_chunks_copied"] == 4
    assert st["prefill_engine"]["prefill_calls"] > 0


def test_prefill_role_engine_requires_cache_and_accepts_zero_budget(lm):
    with pytest.raises(ValueError):
        GenerationScheduler(lm, slots=2, role="prefill")
    eng = GenerationScheduler(lm, slots=2, role="prefill",
                              prefix_cache_bytes=1 << 24,
                              prefix_granularity=8, prefill_chunk=8)
    try:
        rng = np.random.default_rng(17)
        p = rng.integers(1, 50, 17).astype(np.int32)
        row = eng.submit_async(p, 0).result(60)
        assert np.array_equal(row, p)      # prompt back, no decode
        st = eng.stats()
        assert st["role"] == "prefill"
        assert st["decode_steps"] == 0
        assert st["prefix_cache"]["inserts"] == 2
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# router CLI fabric
# ---------------------------------------------------------------------------

def test_cli_fabric_replicas(capsys):
    from bigdl_tpu.serving.__main__ import main
    rc = main(["--model", "transformer_lm_tiny", "--generate", "4",
               "--slots", "2", "--replicas", "2", "--synthetic", "5"])
    assert rc == 0
    out, err = capsys.readouterr()
    rows = [ln for ln in out.strip().splitlines() if ln]
    assert len(rows) == 5
    stats = json.loads(err.strip().splitlines()[-1])
    assert stats["router"]["replicas"] == 2
    assert stats["router"]["outcomes"].get("ok") == 5
    assert stats["fleet"]["processes"] == 2


def test_cli_replicas_without_generate_rejected(capsys):
    from bigdl_tpu.serving.__main__ import main
    rc = main(["--model", "lenet5", "--replicas", "2",
               "--synthetic", "1"])
    assert rc == 2


# ---------------------------------------------------------------------------
# soak (slow): sustained RPS over the fabric with the PR-7 forensics
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_sustained_rps_fleet_watched(lm, tmp_path):
    """Sustained sessioned traffic over a 3-replica fabric: every
    request completes or is shed TYPED (no timeouts), the PR-7 fleet
    table derives from the replica snapshots (straggler detection over
    the fleet), and the OOM forensics report is armed and readable."""
    d = str(tmp_path)
    reps = [_replica(lm, i, d, slots=4) for i in range(3)]
    router = Router(replicas=reps, snapshot_dir=d, poll_interval_s=0.02,
                    slo_ttft_p99_s=30.0, queue_capacity=64)
    rng = np.random.default_rng(18)
    futs = []
    try:
        t_end = time.perf_counter() + 8.0
        i = 0
        while time.perf_counter() < t_end:
            futs.append(router.submit_generate_async(
                rng.integers(1, 50, int(rng.integers(3, 30))).astype(
                    np.int32),
                int(rng.integers(2, 10)), session=f"user-{i % 16}"))
            i += 1
            time.sleep(0.01)      # ~100 rps offered
        ok = shed = 0
        for f in futs:
            try:
                f.result(120)
                ok += 1
            except (RequestSheddedError, NoReplicaAvailableError):
                shed += 1
        assert ok + shed == len(futs)
        assert ok > 0
        # straggler detection over the replica fleet: same files, same
        # derivation as the training fleet monitor
        fleet = router.registry.fleet()
        assert fleet is not None and fleet["processes"] == 3
        assert fleet["slowest_process"] in (0, 1, 2)
        assert fleet["skew"] >= 1.0
        # OOM forensics armed over the fleet host
        from bigdl_tpu.telemetry.runtime import (
            device_memory_snapshot, oom_forensics_report,
        )
        report = oom_forensics_report("RESOURCE_EXHAUSTED: probe", None)
        assert "devices" in report and "rss_bytes" in report
        assert device_memory_snapshot() is not None
    finally:
        router.shutdown()
