"""Fused conv+BN+ReLU Pallas kernels (ops/conv_bn_kernels.py) vs the
unfused XLA path: values, gradients, and running-stat updates must
match.  Runs the kernels in interpret mode on CPU (same code path the
TPU compiles).

Reference for WHAT must hold: the reference's fused mkl-dnn conv+BN
produces the same training math as its unfused nn/ layers
(nn/mkldnn/SpatialBatchNormalization.scala); here the oracle is our own
unfused module chain.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.ops.conv_bn_kernels import (
    fused_block_supported, fused_matmul_bn, fused_matmul_bn_reference,
)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape).astype(dtype)


class TestFusedOp:
    def test_plain_matmul_with_stats(self):
        x = _rand(0, (256, 64))
        w = _rand(1, (64, 128)) * 0.1
        k = _rand(2, (128,)) * 0.01
        y, s1, s2 = fused_matmul_bn(x, w, kshift=k, interpret=True)
        yr, r1, r2 = fused_matmul_bn_reference(x, w, kshift=k)
        np.testing.assert_allclose(y, yr, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(s1, r1, rtol=2e-4, atol=2e-3)
        np.testing.assert_allclose(s2, r2, rtol=2e-4, atol=2e-3)

    def test_input_fusion(self):
        x = _rand(0, (128, 32)) * 2 + 0.3
        w = _rand(1, (32, 64)) * 0.1
        norm = (_rand(2, (32,)) * 0.1, jnp.abs(_rand(3, (32,))) + 0.5,
                _rand(4, (32,)) * 0.2)
        k = jnp.zeros((64,))
        y, s1, s2 = fused_matmul_bn(x, w, norm=norm, kshift=k,
                                    interpret=True)
        yr, r1, r2 = fused_matmul_bn_reference(x, w, norm=norm, kshift=k)
        np.testing.assert_allclose(y, yr, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(s1, r1, rtol=2e-4, atol=2e-3)

    def test_no_stats(self):
        x = _rand(0, (128, 32))
        w = _rand(1, (32, 64))
        y = fused_matmul_bn(x, w, interpret=True)
        yr = fused_matmul_bn_reference(x, w)
        np.testing.assert_allclose(y, yr, rtol=2e-5, atol=2e-5)

    def test_gradients_match_reference(self):
        """Full vjp — including the gradient THROUGH the emitted batch
        statistics (the stats feed a downstream loss term here, exactly
        as the next layer's normalize would)."""
        x = _rand(0, (128, 32)) * 1.5
        w = _rand(1, (32, 64)) * 0.2
        norm = (_rand(2, (32,)) * 0.1, jnp.abs(_rand(3, (32,))) + 0.5,
                _rand(4, (32,)) * 0.2)
        k = _rand(5, (64,)) * 0.01

        def loss_fused(x, w, norm):
            y, s1, s2 = fused_matmul_bn(x, w, norm=norm, kshift=k,
                                        interpret=True)
            return (jnp.sum(y * y) + jnp.sum(jnp.sin(s1))
                    + jnp.sum(jnp.cos(s2) * 0.1))

        def loss_ref(x, w, norm):
            y, s1, s2 = fused_matmul_bn_reference(x, w, norm=norm,
                                                  kshift=k)
            return (jnp.sum(y * y) + jnp.sum(jnp.sin(s1))
                    + jnp.sum(jnp.cos(s2) * 0.1))

        gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, norm)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, norm)
        for a, b in zip(jax.tree_util.tree_leaves(gf),
                        jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(a, b, rtol=3e-4, atol=3e-3)

    def test_gradients_no_input_fusion(self):
        x = _rand(0, (128, 32))
        w = _rand(1, (32, 64)) * 0.2
        k = jnp.zeros((64,))

        def loss(op):
            def f(x, w):
                y, s1, s2 = op(x, w, kshift=k)
                return jnp.sum(y ** 2) + jnp.sum(s1 * 0.3) + jnp.sum(s2) * 0.1
            return f

        gf = jax.grad(loss(lambda *a, **kw: fused_matmul_bn(
            *a, interpret=True, **kw)), argnums=(0, 1))(x, w)
        gr = jax.grad(loss(fused_matmul_bn_reference), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gf[0], gr[0], rtol=3e-4, atol=3e-3)
        np.testing.assert_allclose(gf[1], gr[1], rtol=3e-4, atol=3e-3)

    def test_bf16_paths_agree(self):
        x = _rand(0, (256, 64), jnp.bfloat16)
        w = (_rand(1, (64, 128)) * 0.1).astype(jnp.bfloat16)
        k = jnp.zeros((128,))
        y, s1, s2 = fused_matmul_bn(x, w, kshift=k, interpret=True)
        yr, r1, r2 = fused_matmul_bn_reference(x, w, kshift=k)
        assert y.dtype == jnp.bfloat16
        np.testing.assert_allclose(y.astype(np.float32),
                                   yr.astype(np.float32),
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(s1, r1, rtol=2e-2, atol=0.5)

    def test_block_support_probe(self):
        assert fused_block_supported(256, 64, 128)
        assert not fused_block_supported(97, 64, 128)  # prime M
        # resident w + dW alone exceed the VMEM budget
        assert not fused_block_supported(4096, 2048, 2048)


class TestFusedBottleneck:
    def _make_pair(self, stride=1, cin=32, planes=8):
        """Two bottlenecks with identical params, one fused."""
        from bigdl_tpu.models.resnet import Bottleneck
        from bigdl_tpu.utils import set_seed
        set_seed(7)
        a = Bottleneck(cin, planes, stride=stride)
        set_seed(7)
        b = Bottleneck(cin, planes, stride=stride, fused="force")
        return a, b

    def test_forward_matches_unfused(self):
        a, b = self._make_pair()
        x = _rand(11, (4, 8, 8, 32))
        ya = a.train_mode()(x)
        yb = b.train_mode()(x)
        np.testing.assert_allclose(ya, yb, rtol=3e-5, atol=3e-5)

    @pytest.mark.slow
    def test_forward_matches_strided(self):
        a, b = self._make_pair(stride=2)
        x = _rand(12, (4, 8, 8, 32))
        np.testing.assert_allclose(a.train_mode()(x), b.train_mode()(x),
                                   rtol=3e-5, atol=3e-5)

    @pytest.mark.slow
    def test_running_stats_match(self):
        a, b = self._make_pair()
        x = _rand(13, (4, 8, 8, 32))
        a.train_mode()(x)
        b.train_mode()(x)
        for bn in ("bn1", "bn2", "bn3"):
            np.testing.assert_allclose(
                getattr(a, bn).running_mean, getattr(b, bn).running_mean,
                rtol=1e-4, atol=1e-5, err_msg=bn)
            np.testing.assert_allclose(
                getattr(a, bn).running_var, getattr(b, bn).running_var,
                rtol=1e-4, atol=1e-5, err_msg=bn)

    def test_gradients_match_unfused(self):
        from bigdl_tpu.core.module import partition, combine
        a, b = self._make_pair()
        x = _rand(14, (4, 8, 8, 32))

        def loss_of(mod):
            params, rest = partition(mod.train_mode())

            def loss(params, x):
                m = combine(params, rest)
                return jnp.sum(m(x) ** 2)
            return params, loss

        pa, la = loss_of(a)
        pb, lb = loss_of(b)
        ga = jax.grad(la)(pa, x)
        gb = jax.grad(lb)(pb, x)
        la_, lb_ = jax.tree_util.tree_leaves(ga), jax.tree_util.tree_leaves(gb)
        assert len(la_) == len(lb_)
        for u, v in zip(la_, lb_):
            np.testing.assert_allclose(u, v, rtol=5e-4, atol=5e-4)

    def test_eval_mode_ignores_fused(self):
        a, b = self._make_pair()
        x = _rand(15, (2, 8, 8, 32))
        np.testing.assert_allclose(a.eval_mode()(x), b.eval_mode()(x),
                                   rtol=1e-6, atol=1e-6)

    def test_env_kill_switch(self, monkeypatch):
        _, b = self._make_pair()
        monkeypatch.setenv("BIGDL_TPU_FUSED_CONVBN", "0")
        assert b.train_mode()._fused_selection() is None

    def test_env_subset(self, monkeypatch):
        _, b = self._make_pair()
        monkeypatch.setenv("BIGDL_TPU_FUSED_CONVBN", "conv3")
        assert b.train_mode()._fused_selection() == {"conv3"}


class TestFusedResNet50Slice:
    @pytest.mark.slow
    def test_resnet_fused_flag_trains(self):
        """A short jitted train step on a fused CIFAR-scale bottleneck
        stack — the integration path the perf harness uses."""
        from bigdl_tpu.models.resnet import ResNet, Bottleneck
        model = ResNet(Bottleneck, [1, 1], class_num=10, cifar=True,
                       fused="force")
        # cifar path uses BasicBlock normally; build directly with
        # Bottleneck to exercise the fused blocks
        x = _rand(20, (8, 8, 8, 3))
        out = model.train_mode()(x)
        assert out.shape == (8, 10)
        assert bool(jnp.isfinite(out).all())


class TestFusedConv3x3:
    def test_forward_and_stats(self):
        from bigdl_tpu.ops.conv_bn_kernels import (
            fused_conv3x3_bn, fused_conv3x3_bn_reference)
        x = _rand(40, (2, 8, 6, 4))
        w = _rand(41, (3, 3, 4, 8)) * 0.2
        norm = (_rand(42, (4,)) * 0.1, jnp.abs(_rand(43, (4,))) + 0.5,
                _rand(44, (4,)) * 0.2)
        k = _rand(45, (8,)) * 0.05
        y, s1, s2 = fused_conv3x3_bn(x, w, norm=norm, kshift=k,
                                     block_h=4, interpret=True)
        yr, r1, r2 = fused_conv3x3_bn_reference(x, w, norm=norm, kshift=k)
        np.testing.assert_allclose(y, yr, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(s1, r1, rtol=2e-4, atol=2e-3)
        np.testing.assert_allclose(s2, r2, rtol=2e-4, atol=2e-3)

    def test_gradients_incl_stats_path(self):
        from bigdl_tpu.ops.conv_bn_kernels import (
            fused_conv3x3_bn, fused_conv3x3_bn_reference)
        x = _rand(46, (2, 8, 6, 4))
        w = _rand(47, (3, 3, 4, 8)) * 0.2
        norm = (_rand(48, (4,)) * 0.1, jnp.abs(_rand(49, (4,))) + 0.5,
                _rand(50, (4,)) * 0.2)
        k = _rand(51, (8,)) * 0.05

        def loss(op):
            def f(x, w, norm):
                y, s1, s2 = op(x, w, norm=norm, kshift=k)
                return (jnp.sum(y ** 2) + jnp.sum(jnp.sin(s1))
                        + 0.1 * jnp.sum(jnp.cos(s2)))
            return f

        gf = jax.grad(loss(lambda *a, **kw: fused_conv3x3_bn(
            *a, block_h=4, interpret=True, **kw)),
            argnums=(0, 1, 2))(x, w, norm)
        gr = jax.grad(loss(fused_conv3x3_bn_reference),
                      argnums=(0, 1, 2))(x, w, norm)
        for a, b in zip(jax.tree_util.tree_leaves(gf),
                        jax.tree_util.tree_leaves(gr)):
            scale = max(float(jnp.max(jnp.abs(b))), 1.0)
            np.testing.assert_allclose(np.asarray(a) / scale,
                                       np.asarray(b) / scale,
                                       rtol=2e-4, atol=2e-5)

    def test_no_input_fusion_no_stats(self):
        from bigdl_tpu.ops.conv_bn_kernels import (
            fused_conv3x3_bn, fused_conv3x3_bn_reference)
        x = _rand(52, (1, 6, 6, 8))
        w = _rand(53, (3, 3, 8, 8)) * 0.2
        y = fused_conv3x3_bn(x, w, block_h=3, interpret=True)
        yr = fused_conv3x3_bn_reference(x, w)
        np.testing.assert_allclose(y, yr, rtol=2e-5, atol=2e-5)

    def test_block_with_conv2_fused_matches_unfused(self):
        """All three convs fused (the full tranche) vs the plain path."""
        from bigdl_tpu.models.resnet import Bottleneck
        from bigdl_tpu.utils import set_seed
        set_seed(7)
        a = Bottleneck(32, 8)
        set_seed(7)
        b = Bottleneck(32, 8, fused="force")
        x = _rand(54, (4, 8, 8, 32))
        np.testing.assert_allclose(a.train_mode()(x), b.train_mode()(x),
                                   rtol=3e-5, atol=3e-5)

    def test_block_grads_with_conv2_fused(self):
        from bigdl_tpu.core.module import partition, combine
        from bigdl_tpu.models.resnet import Bottleneck
        from bigdl_tpu.utils import set_seed
        set_seed(7)
        a = Bottleneck(32, 8)
        set_seed(7)
        b = Bottleneck(32, 8, fused="force")
        x = _rand(55, (4, 8, 8, 32))

        def grads(mod):
            params, rest = partition(mod.train_mode())

            def loss(params, x):
                return jnp.sum(combine(params, rest)(x) ** 2)
            return jax.grad(loss)(params, x)

        for u, v in zip(jax.tree_util.tree_leaves(grads(a)),
                        jax.tree_util.tree_leaves(grads(b))):
            np.testing.assert_allclose(u, v, rtol=8e-4, atol=8e-4)


class TestBlockPickers:
    """Sublane alignment of the VMEM block picks (ADVICE r05: bf16 tiles
    are (16, 128), f32 (8, 128); misaligned blocks lower via relayouts)."""

    def test_block_m_bf16_prefers_16_multiples(self):
        from bigdl_tpu.ops.conv_bn_kernels import _pick_block_m
        for m in (128, 256, 512, 1024, 3136):
            bm = _pick_block_m(m, 256, 256, itemsize=2)
            assert bm is not None and bm % 16 == 0

    def test_block_m_falls_back_when_no_aligned_divisor(self):
        from bigdl_tpu.ops.conv_bn_kernels import _pick_block_m
        # 24 has no 16-multiple divisor; the old 8-step pick must survive
        assert _pick_block_m(24, 256, 256, itemsize=2) == 24

    def test_block_m_f32_keeps_8_multiples(self):
        from bigdl_tpu.ops.conv_bn_kernels import _pick_block_m
        for m in (128, 24, 1024):
            bm = _pick_block_m(m, 256, 256, itemsize=4)
            assert bm is not None and bm % 8 == 0

    def test_block_h_aligns_flattened_rows_where_divisors_allow(self):
        from bigdl_tpu.ops.conv_bn_kernels import _pick_block_h
        for h, w, sub in ((56, 56, 16), (28, 28, 16), (32, 32, 16),
                          (56, 56, 8), (28, 28, 8)):
            itemsize = 2 if sub == 16 else 4
            bh = _pick_block_h(h, w, 64, 64, itemsize)
            assert bh is not None and (bh * w) % sub == 0

    def test_block_h_fallback_keeps_support(self):
        from bigdl_tpu.ops.conv_bn_kernels import (
            _pick_block_h, fused_conv3x3_supported,
        )
        # 7x7 (ResNet tail) has no aligned divisor: still supported
        assert fused_conv3x3_supported(7, 7, 64, 64, itemsize=2)
        assert _pick_block_h(7, 7, 64, 64, itemsize=2) is not None


# --------------------------------------------------------------------------
# On-TPU compiled smoke tests (ADVICE r05): everything above runs the
# kernels in interpret mode, which checks the math but never the
# Mosaic/TPU lowering (tiling, MXU dot placement, halo block specs).
# These run the COMPILED path and are skipped off-TPU; on a healthy
# hardware window run them with
#   BIGDL_TPU_TESTS_ON_TPU=1 pytest tests/test_fused_conv_bn.py -k tpu
# (the env var stops conftest.py from forcing the virtual-CPU mesh).
# --------------------------------------------------------------------------

def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@pytest.mark.skipif(not _on_tpu(), reason=(
    "compiled (non-interpret) Pallas path needs TPU hardware; run with "
    "BIGDL_TPU_TESTS_ON_TPU=1 on a chip"))
class TestCompiledOnTpu:
    """Numerics of the compiled kernels vs the XLA reference — shapes
    chosen lane-aligned (multiples of 128 channels) so they exercise
    the production ResNet tiles, not fallback paths."""

    def test_tpu_matmul_bn_forward(self):
        x = _rand(0, (256, 128), jnp.bfloat16) * 1.5
        w = _rand(1, (128, 256), jnp.bfloat16) * 0.1
        norm = (_rand(2, (128,)) * 0.1, jnp.abs(_rand(3, (128,))) + 0.5,
                _rand(4, (128,)) * 0.2)
        k = _rand(5, (256,)) * 0.01
        y, s1, s2 = fused_matmul_bn(x, w, norm=norm, kshift=k)
        yr, r1, r2 = fused_matmul_bn_reference(x, w, norm=norm, kshift=k)
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(yr, np.float32),
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(s1, r1, rtol=2e-2, atol=2.0)
        np.testing.assert_allclose(s2, r2, rtol=2e-2, atol=8.0)

    def test_tpu_matmul_bn_gradients(self):
        x = _rand(0, (256, 128)) * 1.5
        w = _rand(1, (128, 128)) * 0.2
        k = jnp.zeros((128,))

        def loss(op):
            def f(x, w):
                y, s1, s2 = op(x, w, kshift=k)
                return jnp.sum(y ** 2) + jnp.sum(s1) * 0.3 + jnp.sum(s2) * 0.1
            return f

        gf = jax.grad(loss(fused_matmul_bn), argnums=(0, 1))(x, w)
        gr = jax.grad(loss(fused_matmul_bn_reference), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gf[0], gr[0], rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(gf[1], gr[1], rtol=1e-3, atol=1e-2)

    def test_tpu_conv3x3_bn_forward(self):
        from bigdl_tpu.ops.conv_bn_kernels import (
            fused_conv3x3_bn, fused_conv3x3_bn_reference,
            fused_conv3x3_supported,
        )
        b, h, w_, c, co = 2, 16, 16, 128, 128
        assert fused_conv3x3_supported(h, w_, c, co, itemsize=4)
        x = _rand(0, (b, h, w_, c)) * 0.5
        w = _rand(1, (3, 3, c, co)) * 0.05
        norm = (_rand(2, (c,)) * 0.1, jnp.abs(_rand(3, (c,))) + 0.5,
                _rand(4, (c,)) * 0.2)
        k = _rand(5, (co,)) * 0.01
        y, s1, s2 = fused_conv3x3_bn(x, w, norm=norm, kshift=k)
        yr, r1, r2 = fused_conv3x3_bn_reference(x, w, norm=norm, kshift=k)
        np.testing.assert_allclose(y, yr, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(s1, r1, rtol=1e-3, atol=1e-1)
        np.testing.assert_allclose(s2, r2, rtol=1e-3, atol=1e-1)

    def test_tpu_conv3x3_bn_gradients(self):
        from bigdl_tpu.ops.conv_bn_kernels import (
            fused_conv3x3_bn, fused_conv3x3_bn_reference,
        )
        b, h, w_, c, co = 1, 8, 8, 128, 128
        x = _rand(0, (b, h, w_, c)) * 0.5
        w = _rand(1, (3, 3, c, co)) * 0.05

        def loss(op):
            def f(x, w):
                return jnp.sum(op(x, w) ** 2)
            return f

        gf = jax.grad(loss(fused_conv3x3_bn), argnums=(0, 1))(x, w)
        gr = jax.grad(loss(fused_conv3x3_bn_reference),
                      argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gf[0], gr[0], rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(gf[1], gr[1], rtol=1e-3, atol=1e-2)
