"""Model-tool CLIs: loadmodel (import + validate), quantize (int8),
serve (HTTP PredictionService) — reference example/loadmodel,
example/mkldnn int8, example/udfpredictor."""

import io
import threading

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.serializer import save_module


def _small_cnn(classes=3, size=16):
    from bigdl_tpu.utils import set_seed
    set_seed(7)
    return nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, -1, -1),
        nn.SpatialBatchNormalization(8),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape((8 * (size // 2) * (size // 2),)),
        nn.Linear(8 * (size // 2) * (size // 2), classes),
    )


@pytest.fixture()
def image_folder(tmp_path):
    from PIL import Image
    rng = np.random.default_rng(0)
    for cls in ("a", "b", "c"):
        d = tmp_path / "val" / cls
        d.mkdir(parents=True)
        for i in range(4):
            arr = rng.integers(0, 255, size=(20, 20, 3)).astype("uint8")
            Image.fromarray(arr).save(d / f"{i}.png")
    return tmp_path


def test_loadmodel_predict_and_evaluate(tmp_path, image_folder):
    model = _small_cnn()
    mpath = tmp_path / "m.bigdl"
    save_module(model, str(mpath))
    img = image_folder / "val" / "a" / "0.png"
    from bigdl_tpu.examples.loadmodel import main
    res = main(["--format", "bigdl", "--model", str(mpath),
                "--predict", str(img), "--image-size", "16", "-q"])
    pairs = res[str(img)]
    assert len(pairs) == 3  # 3-class model: top-5 clips to class count
    assert all(1 <= c <= 3 for c, _ in pairs)
    res = main(["--format", "bigdl", "--model", str(mpath),
                "--evaluate", str(image_folder / "val"),
                "--image-size", "16", "-b", "4", "-q"])
    assert 0.0 <= res["Top1Accuracy"] <= 1.0
    assert np.isfinite(res["Loss"])


def test_loadmodel_format_dispatch(tmp_path):
    """The --format switch must route to each interop loader."""
    from tests.test_t7_table_metrics import _write_torch_module
    from bigdl_tpu.examples.loadmodel import load_model
    wt = np.random.default_rng(1).normal(size=(2, 5)).astype(np.float32)
    b = np.zeros(2, np.float32)
    t7 = str(tmp_path / "lin.t7")
    _write_torch_module(t7, "nn.Linear", {"weight": wt, "bias": b})
    m = load_model("torch", t7)
    assert isinstance(m, nn.Linear)
    np.testing.assert_allclose(np.asarray(m.weight), wt)
    with pytest.raises(SystemExit, match="prototxt"):
        load_model("caffe", t7)


def test_quantize_cli(tmp_path, image_folder):
    model = _small_cnn()
    mpath, qpath = tmp_path / "m.bigdl", tmp_path / "q.bigdl"
    save_module(model, str(mpath))
    from bigdl_tpu.examples.quantize import main
    res = main(["--model", str(mpath), "--output", str(qpath),
                "--evaluate", str(image_folder / "val"),
                "--image-size", "16", "-b", "4", "-q"])
    assert qpath.exists()
    assert res["bytes_int8"] < res["bytes_fp32"]
    # int8 top-1 should track fp32 closely on this tiny set
    assert abs(res["top1_int8"] - res["top1_fp32"]) <= 0.5


def test_serve_http_roundtrip(tmp_path):
    from bigdl_tpu.examples.serve import make_server
    from bigdl_tpu.optim.predictor import PredictionService
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    service = PredictionService(model, concurrency=2)
    server = make_server(service, "127.0.0.1", 0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        import http.client
        port = server.server_port
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        assert conn.getresponse().read() == b'{"status": "ok"}'
        x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
        buf = io.BytesIO()
        np.save(buf, x, allow_pickle=False)
        conn.request("POST", "/predict", buf.getvalue())
        out = np.load(io.BytesIO(conn.getresponse().read()),
                      allow_pickle=False)
        assert out.shape == (5, 2)
        ref = np.asarray(model.clone().eval_mode().forward(x))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        # malformed payload -> 400 with an error body, server stays up
        conn.request("POST", "/predict", b"not-an-npy")
        r = conn.getresponse()
        assert r.status == 400 and b"error" in r.read()
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
    finally:
        server.shutdown()
        server.server_close()


def test_loadmodel_predict_batches_and_class_warning(tmp_path,
                                                     image_folder, caplog):
    import logging as _logging
    model = _small_cnn()
    mpath = tmp_path / "m.bigdl"
    save_module(model, str(mpath))
    imgs = [str(image_folder / "val" / c / "0.png") for c in ("a", "b", "c")]
    from bigdl_tpu.examples.loadmodel import main
    # batch_size 2 over 3 images: predict path must chunk, not stack all
    res = main(["--format", "bigdl", "--model", str(mpath),
                "--predict", *imgs, "--image-size", "16", "-b", "2", "-q"])
    assert set(res) == set(imgs)
    # 3-class model scored on a folder pruned to 2 classes -> warning
    import shutil
    shutil.rmtree(image_folder / "val" / "c")
    with caplog.at_level(_logging.WARNING):
        main(["--format", "bigdl", "--model", str(mpath),
              "--evaluate", str(image_folder / "val"),
              "--image-size", "16", "-b", "4", "-q"])
    assert any("class directories" in r.message for r in caplog.records)


@pytest.mark.slow
def test_perf_harness_cli():
    """DistriOptimizerPerf-analog: drives the real Optimizer loop and
    reports steady-state throughput."""
    from bigdl_tpu.examples.perf import main
    out = main(["--model", "lenet", "-b", "16", "--iterations", "3",
                "--epochs", "3"])
    assert out["records_per_sec"] > 0
    assert out["ms_per_iteration"] > 0
    # every flushed window after the compile-bearing first one is timed
    # (windows follow the drain's flush cadence, not epoch boundaries)
    assert out["windows_timed"] >= 1
    out = main(["--model", "transformer-lm", "-b", "8", "--seq-len", "16",
                "--vocab-size", "50", "--hidden-size", "16",
                "--num-layers", "1", "--num-heads", "2",
                "--iterations", "2", "--epochs", "2"])
    assert out["records_per_sec"] > 0


def test_perf_generate_mode():
    """--generate measures KV-cache greedy decode instead of training."""
    from bigdl_tpu.examples.perf import main
    out = main(["--model", "transformer-lm", "--generate", "8",
                "--seq-len", "16", "-b", "2", "--hidden-size", "32",
                "--num-layers", "1", "--num-heads", "2",
                "--vocab-size", "50"])
    assert out["mode"] == "generate"
    assert out["decode_tokens_per_sec"] > 0
    assert out["new_tokens"] == 8


def test_perf_int8_infer_mode():
    """--int8-infer reports fp32 vs quantized inference latency."""
    from bigdl_tpu.examples.perf import main
    out = main(["--model", "lenet", "--int8-infer", "-b", "8"])
    assert out["mode"] == "int8-infer"
    assert out["fp32_ms"] > 0 and out["int8_ms"] > 0
    assert out["int8_speedup"] > 0
