"""iterations-per-dispatch windowing: k train steps inside one compiled
dispatch (lax.scan) must be semantically identical to k single-step
dispatches — same trained weights, same per-iteration logging, and
triggers firing on the exact same iterations (the TPU analog of the
reference collapsing Spark task-scheduling overhead into one task per
node, docs/docs/whitepaper.md:171-177)."""

import glob
import os
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.optim import Optimizer, SGD, Trigger, Top1Accuracy
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.image import synthetic_mnist, GreyImgNormalizer
from bigdl_tpu.parallel import MeshConfig
from bigdl_tpu.utils import set_seed


def _pipeline(n=256, batch=32, seed=0):
    return DataSet.array(synthetic_mnist(n, seed=seed), shuffle=False) \
        .transform(GreyImgNormalizer(128.0, 128.0)) \
        .transform(SampleToMiniBatch(batch))


def _mlp():
    return nn.Sequential(
        nn.Flatten(), nn.Linear(784, 32), nn.Tanh(),
        nn.Linear(32, 10), nn.LogSoftMax())


def _train(k, epochs=2, **kw):
    set_seed(23)
    model = _mlp()
    opt = (Optimizer(model, _pipeline(), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
           .set_end_when(Trigger.max_epoch(epochs))
           .set_iterations_per_dispatch(k))
    for name, val in kw.items():
        getattr(opt, name)(*val)
    opt.optimize()
    return model, opt


def test_window_matches_single_step():
    """k=4 windows train to the SAME weights as k=1 (bit-level math is
    identical: scan runs the same step function over the same batches)."""
    m1, _ = _train(1)
    m4, _ = _train(4)
    p1 = m1.parameters()
    p4 = m4.parameters()
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_window_ragged_tail_and_counts():
    """8 batches/epoch with k=3: windows of 3+3 then 2 single-step
    dispatches; iteration count and records must match k=1 exactly."""
    _, opt3 = _train(3)
    _, opt1 = _train(1)
    assert opt3.state["neval"] == opt1.state["neval"]
    assert opt3.state["records"] == opt1.state["records"]


def test_window_checkpoint_trigger_alignment():
    """A several_iteration(3) checkpoint trigger with k=4 must fire on
    iterations 3, 6, 9, ... exactly as with k=1 (windows are trimmed so
    a trigger lands on a window boundary)."""
    nevals = {}
    for k in (1, 4):
        with tempfile.TemporaryDirectory() as d:
            set_seed(23)
            model = _mlp()
            opt = (Optimizer(model, _pipeline(), nn.ClassNLLCriterion())
                   .set_optim_method(SGD(0.1))
                   .set_end_when(Trigger.max_epoch(1))
                   .set_checkpoint(d, Trigger.several_iteration(3),
                                   is_overwrite=False)
                   .set_iterations_per_dispatch(k))
            opt.optimize()
            files = sorted(glob.glob(os.path.join(d, "checkpoint*.npz")))
            nevals[k] = [os.path.basename(f).split(".")[1] for f in files]
    assert nevals[1] == nevals[4]
    assert nevals[1]  # fired at least once


def test_window_validation_score_and_mesh():
    """Windowed dispatch composes with an 8-device data mesh and
    every-epoch validation; the model still learns."""
    set_seed(23)
    model = _mlp()
    opt = (Optimizer(model, _pipeline(512, 64), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(3))
           .set_mesh(MeshConfig(data=8))
           .set_validation(Trigger.every_epoch(),
                           _pipeline(256, 64, seed=7), [Top1Accuracy()])
           .set_iterations_per_dispatch(4))
    opt.optimize()
    assert opt.state["score"] > 0.8


def test_window_device_cached_reuse_and_shuffled_safety():
    """cache_on_device + windows: unshuffled datasets reuse the staged
    window across epochs; shuffled ones must not cache (fresh orders
    would pile stacked copies into device memory) yet still train to
    the same place as the unwindowed run."""
    for shuffle in (False, True):
        set_seed(23)
        model = _mlp()
        data = DataSet.array(synthetic_mnist(256, seed=0),
                             shuffle=shuffle) \
            .transform(GreyImgNormalizer(128.0, 128.0)) \
            .transform(SampleToMiniBatch(32)).cache_on_device()
        opt = (Optimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
               .set_end_when(Trigger.max_epoch(2))
               .set_iterations_per_dispatch(4))
        opt.optimize()
        assert opt.state["neval"] == 17  # 8 batches x 2 epochs + 1
    # unshuffled cached windows match the plain k=1 run exactly
    set_seed(23)
    m_cached = _mlp()
    data = DataSet.array(synthetic_mnist(256, seed=0), shuffle=False) \
        .transform(GreyImgNormalizer(128.0, 128.0)) \
        .transform(SampleToMiniBatch(32)).cache_on_device()
    (Optimizer(m_cached, data, nn.ClassNLLCriterion())
     .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
     .set_end_when(Trigger.max_epoch(2))
     .set_iterations_per_dispatch(4)).optimize()
    m_plain, _ = _train(1)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(m_cached.parameters()),
                    jax.tree_util.tree_leaves(m_plain.parameters())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_window_min_loss_trigger_forces_single_step():
    """A loss-reading end trigger (minLoss) cannot be windowed: loss
    changes mid-window.  The loop must fall back to k=1 dispatches and
    stop on the exact iteration the loss crosses the threshold."""
    set_seed(23)
    model = _mlp()
    opt = (Optimizer(model, _pipeline(), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.or_(Trigger.max_epoch(50),
                                     Trigger.min_loss(1.5)))
           .set_iterations_per_dispatch(4))
    opt.optimize()
    assert opt.state["loss"] < 1.5
    # stopped promptly after crossing, not at a window boundary past it
    assert opt.state["epoch"] <= 50


def test_ragged_batch_shapes_through_aot_cache():
    """The AOT executable cache (one compiled program per shape
    signature, dodging jit's layout-keyed recompile) must retrace for a
    ragged tail batch instead of rejecting it: 40 samples at batch 16
    -> one full window of k=2 at batch 16 plus a ragged size-8 batch
    down the single-step path, every epoch."""
    set_seed(3)
    model = _mlp()
    data = DataSet.array(synthetic_mnist(40, seed=0), shuffle=False) \
        .transform(GreyImgNormalizer(128.0, 128.0)) \
        .transform(SampleToMiniBatch(16, drop_last=False))
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
           .set_end_when(Trigger.max_epoch(3))
           .set_iterations_per_dispatch(2))
    opt.optimize()
    # 3 batches/epoch (16+16+8 samples) x 3 epochs + 1
    assert opt.state["neval"] == 10
    assert opt.state["loss"] < 2.5
