"""Torch7 .t7 codec, Table DSL, Metrics, and logger tests.

Mirrors reference TorchFileSpec (utils/), TableSpec, MetricsSpec.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.torch_file import (TorchObject, load_t7,
                                          load_torch_module, save_t7)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.utils.table import T, Table
from bigdl_tpu.utils import set_seed


# ---------------- t7 ----------------

def test_t7_scalar_string_table_roundtrip(tmp_path):
    p = str(tmp_path / "x.t7")
    save_t7(p, 42)
    assert load_t7(p) == 42
    save_t7(p, 3.5)
    assert load_t7(p) == 3.5
    save_t7(p, "hello")
    assert load_t7(p) == "hello"
    save_t7(p, True)
    assert load_t7(p) is True
    save_t7(p, {1: "a", 2: {1: 7}, "key": 9})
    back = load_t7(p)
    assert back[1] == "a" and back[2][1] == 7 and back["key"] == 9


def test_t7_tensor_roundtrip(tmp_path):
    p = str(tmp_path / "t.t7")
    for dt in (np.float32, np.float64, np.int64, np.int32):
        arr = (np.arange(24).reshape(2, 3, 4) * 1.5).astype(dt)
        save_t7(p, arr)
        back = load_t7(p)
        assert back.dtype == dt
        np.testing.assert_allclose(back, arr)


def test_t7_tensor_in_table(tmp_path):
    p = str(tmp_path / "tt.t7")
    w = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    save_t7(p, {"weight": w, "n": 5})
    back = load_t7(p)
    np.testing.assert_allclose(back["weight"], w)
    assert back["n"] == 5


def _write_torch_module(path, cls, payload, writer_cls=None):
    """Emit a TORCH record for an nn class wrapping a table payload."""
    from bigdl_tpu.interop.torch_file import _Writer
    with open(path, "wb") as f:
        w = _Writer(f)
        import struct
        f.write(struct.pack("<i", 4))          # TYPE_TORCH
        f.write(struct.pack("<i", w._idx()))   # index
        w._string("V 1")
        w._string(cls)
        w.write(payload)


def test_load_torch_module_linear(tmp_path):
    p = str(tmp_path / "lin.t7")
    wt = np.random.RandomState(1).randn(2, 5).astype(np.float32)
    b = np.random.RandomState(2).randn(2).astype(np.float32)
    _write_torch_module(p, "nn.Linear", {"weight": wt, "bias": b})
    m = load_torch_module(p)
    assert isinstance(m, nn.Linear)
    np.testing.assert_allclose(np.asarray(m.weight), wt)
    x = jnp.asarray(np.random.RandomState(3).randn(3, 5), jnp.float32)
    want = np.asarray(x) @ wt.T + b
    np.testing.assert_allclose(np.asarray(m(x)), want, rtol=1e-5)


def test_load_torch_module_unknown_class(tmp_path):
    p = str(tmp_path / "u.t7")
    _write_torch_module(p, "nn.ExoticLayer", {})
    obj = load_t7(p)
    assert isinstance(obj, TorchObject)
    with pytest.raises(ValueError, match="ExoticLayer"):
        load_torch_module(p)


# ---------------- Table ----------------

def test_table_basics():
    t = T(10, 20, name="x")
    assert t[1] == 10 and t[2] == 20 and t["name"] == "x"
    assert t.length() == 2 and len(t) == 2
    t.insert(30)
    assert t[3] == 30
    assert list(t) == [10, 20, 30]
    assert t.remove() == 30
    assert t.length() == 2
    assert T(1, 2) == T(1, 2)


def test_table_is_pytree():
    t = T(jnp.ones(3), jnp.zeros(2), tag=jnp.asarray(5.0))
    doubled = jax.tree_util.tree_map(lambda x: x * 2, t)
    assert isinstance(doubled, Table)
    np.testing.assert_allclose(np.asarray(doubled[1]), 2.0)
    np.testing.assert_allclose(np.asarray(doubled["tag"]), 10.0)

    @jax.jit
    def f(tbl):
        return tbl[1].sum() + tbl[2].sum() + tbl["tag"]

    assert float(f(t)) == pytest.approx(8.0)


def test_table_as_layer_input():
    """Table flows through table-op layers like a tuple."""
    add = nn.CAddTable()
    out = add(T(jnp.ones(4), jnp.full(4, 2.0)))
    np.testing.assert_allclose(np.asarray(out), 3.0)


# ---------------- Metrics ----------------

def test_metrics_accumulate_and_summary():
    m = Metrics()
    m.add("phase", 1.0)
    m.add("phase", 3.0)
    assert m.mean("phase") == pytest.approx(2.0)
    m.set("other", 10.0, parallelism=5)
    assert m.get("other") == (10.0, 5)
    s = m.summary()
    assert "phase" in s and "other" in s
    m.reset()
    assert m.get("phase") == (0.0, 0)


def test_metrics_time_context():
    import time
    m = Metrics()
    with m.time("sleep"):
        time.sleep(0.01)
    total, count = m.get("sleep")
    assert count == 1 and total >= 0.005


def test_optimizer_populates_metrics():
    from bigdl_tpu.dataset.dataset import LocalDataSet, Sample
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    set_seed(0)
    rng = np.random.RandomState(0)
    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(1).astype(np.float32)) for _ in range(16)]
    ds = LocalDataSet(samples).transform(SampleToMiniBatch(8))
    opt = (Optimizer(nn.Linear(4, 1), ds, nn.MSECriterion())
           .set_optim_method(SGD(0.01))
           .set_end_when(Trigger.max_epoch(2)))
    opt.optimize()
    assert opt.metrics.get("device step time")[1] >= 2
    assert opt.metrics.get("data load and transfer")[1] >= 2


# ---------------- logger ----------------

def test_logger_filter(tmp_path):
    from bigdl_tpu.utils.logger import disable, log_file, \
        redirect_noise_logs
    redirect_noise_logs(str(tmp_path / "noise.log"))
    logging.getLogger("jax._src.dispatch").info("to file only")
    assert (tmp_path / "noise.log").exists()
    disable()
    assert logging.getLogger("absl").level == logging.ERROR
    log_file(str(tmp_path / "app.log"))
    logging.getLogger("bigdl_tpu").warning("hello")
    assert "hello" in (tmp_path / "app.log").read_text()


def test_t7_cyclic_object_reference(tmp_path):
    """Regression (round-1 advisor #3): a torch object whose payload
    refers back to itself must resolve to the same instance."""
    import struct
    p = tmp_path / "cyclic.t7"
    with open(p, "wb") as f:
        def w_int(v): f.write(struct.pack("<i", v))
        def w_str(s):
            w_int(len(s)); f.write(s.encode())
        w_int(4); w_int(1)              # TYPE_TORCH, idx 1
        w_str("V 1"); w_str("nn.Weird")
        w_int(3); w_int(2)              # payload: TYPE_TABLE, idx 2
        w_int(1)                        # one entry
        w_int(2); w_str("self")         # key "self"
        w_int(4); w_int(1)              # value: TYPE_TORCH ref to idx 1
    obj = load_t7(str(p))
    assert isinstance(obj, TorchObject)
    assert obj.payload["self"] is obj


def test_t7_shared_table_roundtrip(tmp_path):
    """Writer memoizes shared tables so reader identity is preserved."""
    shared = {"v": 1.0}
    top = {"a": shared, "b": shared}
    p = str(tmp_path / "shared.t7")
    save_t7(p, top)
    back = load_t7(p)
    assert back["a"] is back["b"]
    d = {}
    d["self"] = d
    p2 = str(tmp_path / "cyclic_w.t7")
    save_t7(p2, d)
    back2 = load_t7(p2)
    assert back2["self"] is back2


def _strip_ours(*names):
    import logging
    for name in names:
        lg = logging.getLogger(name)
        for h in list(lg.handlers):
            if getattr(h, "_bigdl_tpu_handler", False):
                lg.removeHandler(h)


def test_logger_no_duplicate_handlers(tmp_path):
    """Regression (round-1 advisor #5): repeated setup calls must not
    stack FileHandlers (every log line would duplicate)."""
    import logging
    from bigdl_tpu.utils.logger import log_file, redirect_noise_logs
    _strip_ours("jax._src.dispatch", "absl", "bigdl_tpu")
    redirect_noise_logs(str(tmp_path / "noise.log"))
    redirect_noise_logs(str(tmp_path / "noise.log"))
    for name in ("jax._src.dispatch", "absl"):
        ours = [h for h in logging.getLogger(name).handlers
                if getattr(h, "_bigdl_tpu_handler", False)]
        assert len(ours) == 1, f"{name}: {len(ours)} handlers"
    log_file(str(tmp_path / "own.log"))
    log_file(str(tmp_path / "own.log"))
    ours = [h for h in logging.getLogger("bigdl_tpu").handlers
            if getattr(h, "_bigdl_tpu_handler", False)]
    assert len(ours) == 1


def test_t7_shared_tensor_memoized(tmp_path):
    """Shared numpy arrays serialize once and load as one object."""
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    p = str(tmp_path / "tied.t7")
    save_t7(p, {"w": arr, "tied": arr})
    back = load_t7(p)
    assert back["w"] is back["tied"]
    np.testing.assert_array_equal(back["w"], arr)


def test_logger_second_file_is_additive(tmp_path):
    """Dedup is keyed per target file: logging to a second file must not
    silently drop the first."""
    import logging
    from bigdl_tpu.utils.logger import log_file
    _strip_ours("bigdl_tpu")
    log_file(str(tmp_path / "one.log"))
    log_file(str(tmp_path / "two.log"))
    ours = [h for h in logging.getLogger("bigdl_tpu").handlers
            if getattr(h, "_bigdl_tpu_handler", False)]
    assert len(ours) == 2
    for h in ours:
        logging.getLogger("bigdl_tpu").removeHandler(h)


def test_table_operation_broadcasts_smaller_input():
    """nn/TableOperation.scala: expand the smaller tensor to the larger
    one's shape, then run the wrapped two-input table layer —
    whichever side is smaller."""
    t = nn.TableOperation(nn.CMulTable())
    a = jnp.arange(24, dtype=jnp.float32).reshape(2, 3, 4)
    b = jnp.full((1, 1, 4), 2.0)
    np.testing.assert_allclose(np.asarray(t.forward((a, b))),
                               np.asarray(a) * 2.0)
    np.testing.assert_allclose(np.asarray(t.forward((b, a))),
                               np.asarray(a) * 2.0)


def test_structural_aliases_exist():
    """BaseModule/DynamicContainer/DynamicGraph collapse into the static
    execution machinery under XLA (see containers.py rationale)."""
    assert nn.BaseModule is nn.Module
    assert nn.DynamicContainer is nn.Container
    assert nn.DynamicGraph is nn.Graph
