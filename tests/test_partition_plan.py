"""Declarative 3D-parallelism planner (ISSUE 20): one PartitionPlan
drives dp/fsdp/tp/sp/ep/pp through the Optimizer façade.

Three pin groups, all on the 8-fake-device CPU mesh so they live in
tier-1:

* conformance matrix — zoo models × strategy compositions train
  through ``set_partition_plan`` with fixed-seed per-iteration losses
  equal to the plain dp baseline (sharding annotations never change
  the math; GSPMD only inserts collectives),
* plan rejection — every unhonorable composition raises
  :class:`PlanError` NAMING the offending axis or parameter leaf (the
  actionable-error contract ``resolve`` documents), and
* plan-aware elastic resume — tp-sharded and pp-staged training state
  checkpoints under one plan and resumes under a DIFFERENT plan
  (mesh-shape change through the sharded-restore path) with the merged
  loss trajectory equal to the uninterrupted oracle, and the
  checkpoint manifest stamped with the writing plan's composition.
"""

import json
import os

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import Sample
from bigdl_tpu.models import zoo
from bigdl_tpu.nn.moe import MoE
from bigdl_tpu.optim import Optimizer, Trigger
from bigdl_tpu.optim.methods import SGD
from bigdl_tpu.parallel import (
    MeshConfig, PartitionPlan, Pipeline, PlanError, resolve,
)
from bigdl_tpu.parallel.plan import STRATEGIES
from bigdl_tpu.utils import set_seed
from bigdl_tpu.utils.file import CheckpointManager


def _has_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401
        return True
    except ImportError:
        return False


needs_orbax = pytest.mark.skipif(not _has_orbax(),
                                 reason="orbax-checkpoint not installed")

VOCAB, SEQ = 64, 32


class LossLog:
    def __init__(self):
        self.losses = {}

    def add_scalar(self, name, v, step):
        if name == "Loss":
            self.losses[step] = v

    def flush(self):
        pass


def make_lm():
    set_seed(5)
    return zoo("transformer_lm_tiny", vocab_size=VOCAB, hidden_size=32,
               num_layers=4, num_heads=4, filter_size=64, max_len=SEQ,
               padded_inputs=False)


def lm_samples(n=16):
    rng = np.random.default_rng(7)
    return [Sample(rng.integers(1, VOCAB, size=(SEQ,)).astype(np.int32),
                   rng.integers(1, VOCAB, size=(SEQ,)).astype(np.int32))
            for _ in range(n)]


def train_lm(plan, iters=6, n_samples=16, batch=8, end=None,
             ckdir=None, sharded=False, resume_from=None):
    set_seed(1234)
    data = (DataSet.array(lm_samples(n_samples), shuffle=False)
            .transform(SampleToMiniBatch(batch)))
    log = LossLog()
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
    opt = (Optimizer(make_lm(), data, crit)
           .set_optim_method(SGD(0.05))
           .set_end_when(end or Trigger.max_iteration(iters))
           .set_train_summary(log))
    if plan is not None:
        opt.set_partition_plan(plan)
    if ckdir is not None:
        opt.set_checkpoint(ckdir, Trigger.several_iteration(1),
                           sharded=sharded)
    if resume_from is not None:
        opt.resume(resume_from)
    opt.optimize()
    return opt, log.losses


def make_moe():
    set_seed(12)
    return MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(8)],
               top_k=2)


def train_moe(plan, iters=4):
    set_seed(1234)
    rng = np.random.default_rng(3)
    samples = [Sample(rng.standard_normal((8, 16)).astype(np.float32),
                      rng.standard_normal((8, 16)).astype(np.float32))
               for _ in range(16)]
    data = (DataSet.array(samples, shuffle=False)
            .transform(SampleToMiniBatch(8)))
    log = LossLog()
    opt = (Optimizer(make_moe(), data, nn.MSECriterion())
           .set_optim_method(SGD(0.05))
           .set_end_when(Trigger.max_iteration(iters))
           .set_train_summary(log))
    if plan is not None:
        opt.set_partition_plan(plan)
    opt.optimize()
    return log.losses


def _assert_close(losses, baseline, rtol=1e-4):
    assert set(losses) == set(baseline)
    for s, v in baseline.items():
        assert abs(losses[s] - v) <= rtol * max(abs(v), 1.0), \
            (s, v, losses[s])


# --------------------------------------------------------------------------
# Plan schema
# --------------------------------------------------------------------------

class TestPlanSchema:
    def test_strategies_cover_canonical_axes(self):
        from bigdl_tpu.parallel.mesh import AXES
        assert set(STRATEGIES.values()) == set(AXES)

    def test_degrees_reject_zero_and_double_wildcard(self):
        with pytest.raises(PlanError, match="dp=0"):
            PartitionPlan(dp=0).degrees()
        with pytest.raises(PlanError, match="only one strategy may be -1"):
            PartitionPlan(dp=-1, tp=-1).degrees()

    def test_mesh_axes_drop_degree_one(self):
        assert PartitionPlan(dp=2, tp=2).mesh_axes() == \
            {"data": 2, "model": 2}
        assert PartitionPlan().mesh_axes() == {"data": 1}

    def test_describe_names_active_strategies(self):
        d = PartitionPlan(dp=2, pp=4).describe()
        assert "dp=2" in d and "pp=4" in d and "tp" not in d

    def test_resolved_plan_describe_and_idempotent_apply(self):
        rp = resolve(PartitionPlan(dp=4, tp=2), make_lm())
        assert "dp4" in rp.describe() and "tp2" in rp.describe()
        calls = []
        rp.wirings = [("probe", lambda: calls.append(1))]
        rp.apply()
        rp.apply()
        assert calls == [1]
        assert rp.pp_schedule is None  # pp off -> no schedule


# --------------------------------------------------------------------------
# Rejection: PlanError names the offending axis/leaf
# --------------------------------------------------------------------------

class TestPlanRejections:
    def test_too_many_devices_requested(self):
        with pytest.raises(PlanError, match="dp=3"):
            resolve(PartitionPlan(dp=3, tp=3), make_lm())

    def test_explicit_mesh_missing_axis(self):
        mesh = MeshConfig(data=8).build()
        with pytest.raises(PlanError,
                           match=r"tp=2: axis 'model' is not on the mesh"):
            resolve(PartitionPlan(dp=8, tp=2), make_lm(), mesh)

    def test_explicit_mesh_degree_mismatch(self):
        mesh = MeshConfig(data=2, model=4).build()
        with pytest.raises(PlanError,
                           match=r"tp=2: mesh axis 'model' has size 4"):
            resolve(PartitionPlan(dp=2, tp=2), make_lm(), mesh)

    def test_tp_names_the_blocking_leaf(self):
        set_seed(0)
        model = nn.Sequential(nn.Linear(5, 3), nn.ReLU())
        with pytest.raises(PlanError) as ei:
            resolve(PartitionPlan(dp=4, tp=2), model)
        msg = str(ei.value)
        assert "axis 'model'" in msg
        assert "does not divide by 2" in msg
        assert "weight" in msg  # the leaf is named

    def test_pp_on_non_stageable_model(self):
        set_seed(0)
        model = nn.Sequential(nn.Linear(6, 4), nn.ReLU())
        with pytest.raises(PlanError,
                           match="not pipeline-stageable on axis 'pipe'"):
            resolve(PartitionPlan(dp=4, pp=2), model)

    def test_pp_blocks_not_divisible(self):
        with pytest.raises(PlanError,
                           match=r"pp=3: .* 4 blocks, not divisible"):
            resolve(PartitionPlan(pp=3), make_lm())

    def test_pp_cannot_compose_with_sp(self):
        with pytest.raises(PlanError, match="pp cannot compose"):
            resolve(PartitionPlan(pp=2, sp=4), make_lm())

    def test_1f1b_needs_a_pipeline_model(self):
        with pytest.raises(PlanError, match="pre/post-block stages"):
            resolve(PartitionPlan(dp=4, pp=2, pp_schedule="1f1b"),
                    make_lm())

    def test_1f1b_rejects_compute_dtype(self):
        set_seed(0)
        model = Pipeline([nn.Linear(4, 4) for _ in range(2)])
        with pytest.raises(PlanError, match="set_compute_dtype"):
            resolve(PartitionPlan(pp=2, pp_schedule="1f1b"), model,
                    compute_dtype="bfloat16")

    def test_sp_needs_an_attention_model(self):
        set_seed(0)
        model = nn.Sequential(nn.Linear(6, 4))
        with pytest.raises(PlanError,
                           match="no\\s+sequence-parallel path"):
            resolve(PartitionPlan(sp=8), model)

    def test_ep_needs_a_moe_layer(self):
        with pytest.raises(PlanError, match="no MoE layer"):
            resolve(PartitionPlan(ep=8), make_lm())

    def test_ep_expert_count_must_divide(self):
        model = make_moe()  # 8 experts
        with pytest.raises(PlanError,
                           match=r"ep=3: .* 8 experts, not divisible"):
            resolve(PartitionPlan(ep=3), model)

    def test_hierarchical_sync_rejects_non_batch_axes(self):
        with pytest.raises(PlanError,
                           match="hierarchical gradient sync"):
            resolve(PartitionPlan(dp=4, tp=2), make_lm(),
                    hierarchical=True)

    def test_sharded_tables_reject_model_axis(self):
        from bigdl_tpu.embedding.hybrid import HybridPlanError
        set_seed(0)
        wd = zoo("wide_and_deep")
        with pytest.raises(HybridPlanError,
                           match="batch-parallel meshes"):
            resolve(PartitionPlan(dp=4, tp=2), wd)
        # and a HybridPlanError IS a PlanError: one except clause
        # catches the whole planner surface
        assert issubclass(HybridPlanError, PlanError)

    def test_optimizer_facade_surfaces_plan_errors(self):
        set_seed(1234)
        data = (DataSet.array(lm_samples(8), shuffle=False)
                .transform(SampleToMiniBatch(8)))
        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        opt = Optimizer(make_lm(), data, crit)
        with pytest.raises(PlanError, match="no MoE layer"):
            opt.set_partition_plan(PartitionPlan(ep=8))

    def test_1f1b_requires_mean_reduction_criterion(self):
        set_seed(0)
        model = Pipeline([nn.Linear(4, 4) for _ in range(2)])
        samples = [Sample(np.zeros((4,), np.float32),
                          np.zeros((4,), np.float32))
                   for _ in range(8)]
        data = (DataSet.array(samples, shuffle=False)
                .transform(SampleToMiniBatch(4)))
        opt = Optimizer(model, data, nn.MSECriterion(size_average=False))
        with pytest.raises(PlanError, match="mean-reduction criterion"):
            opt.set_partition_plan(
                PartitionPlan(pp=2, pp_schedule="1f1b"))


# --------------------------------------------------------------------------
# Conformance matrix: compositions match the dp baseline
# --------------------------------------------------------------------------

_BASELINES = {}


def lm_baseline():
    if "lm" not in _BASELINES:
        _, losses = train_lm(PartitionPlan(dp=-1))
        _BASELINES["lm"] = losses
    return _BASELINES["lm"]


def moe_baseline():
    if "moe" not in _BASELINES:
        _BASELINES["moe"] = train_moe(PartitionPlan(dp=-1))
    return _BASELINES["moe"]


LM_COMPOSITIONS = [
    ("fsdp8", PartitionPlan(fsdp=-1), False),
    ("dp4_tp2", PartitionPlan(dp=4, tp=2), False),
    ("dp2_fsdp2_tp2", PartitionPlan(dp=2, fsdp=2, tp=2), True),
    ("dp4_pp2", PartitionPlan(dp=4, pp=2), False),
    ("dp2_tp2_pp2", PartitionPlan(dp=2, tp=2, pp=2), True),
    ("sp8", PartitionPlan(sp=-1), True),
]


class TestConformanceMatrix:
    @pytest.mark.parametrize(
        "name,plan",
        [pytest.param(n, p, id=n,
                      marks=[pytest.mark.slow] if slow else [])
         for n, p, slow in LM_COMPOSITIONS])
    def test_lm_composition_matches_dp(self, name, plan):
        _, losses = train_lm(plan)
        _assert_close(losses, lm_baseline())

    def test_moe_ep_matches_dp(self):
        # exact psum dispatch (no capacity factor): token routing and
        # the loss are bit-compatible with the dp run
        losses = train_moe(PartitionPlan(ep=-1))
        _assert_close(losses, moe_baseline())

    def test_clone_after_pipeline_plan(self):
        # the pp wiring leaves a Mesh in _static; clone() must share it
        # by reference instead of choking on its unpicklable Devices
        opt, _ = train_lm(PartitionPlan(dp=4, pp=2), iters=1)
        copy = opt.model.clone()
        assert copy.pipe_mesh is opt.model.pipe_mesh
        assert copy is not opt.model


# --------------------------------------------------------------------------
# Plan-aware elastic resume: checkpoint under plan A, resume under B
# --------------------------------------------------------------------------

def _manifest_plan(ckdir):
    # overwrite-mode checkpoints: one unnumbered manifest per directory
    with open(os.path.join(ckdir, "checkpoint.manifest.json")) as f:
        return json.load(f)["topology"].get("plan")


@needs_orbax
class TestPlanElasticResume:
    def test_tp_resharded_resume(self, tmp_path):
        """dp4×tp2 -> dp2×tp4: the tp-sharded parameter and optim
        leaves change their model-axis shard count through the sharded
        restore path; merged losses track the uninterrupted oracle
        (float tolerance: the dp all-reduce width changed)."""
        oracle, o_losses = train_lm(PartitionPlan(dp=4, tp=2),
                                    n_samples=32,
                                    end=Trigger.max_epoch(2))
        opt1, l1 = train_lm(PartitionPlan(dp=4, tp=2), n_samples=32,
                            end=Trigger.max_iteration(4),
                            ckdir=str(tmp_path), sharded=True)
        # the manifest stamps the writing plan's composition
        assert _manifest_plan(str(tmp_path)) == \
            {"degrees": {"dp": 4, "tp": 2}}
        good = CheckpointManager(str(tmp_path)).latest_good()
        opt2, l2 = train_lm(PartitionPlan(dp=2, tp=4), n_samples=32,
                            end=Trigger.max_epoch(2), resume_from=good)
        merged = dict(l1)
        merged.update(l2)
        _assert_close(merged, o_losses, rtol=2e-4)
        for key in ("epoch", "neval", "records"):
            assert opt2.state[key] == oracle.state[key]

    def test_pp_staged_resume_onto_tp(self, tmp_path):
        """dp2×pp2 (gpipe) -> dp4×tp2: pipeline-staged training state
        restores onto a mesh where the same leaves become tp-sharded —
        the reshard path re-lays out every matched weight."""
        oracle, o_losses = train_lm(PartitionPlan(dp=2, pp=2),
                                    n_samples=32,
                                    end=Trigger.max_epoch(2))
        opt1, l1 = train_lm(PartitionPlan(dp=2, pp=2), n_samples=32,
                            end=Trigger.max_iteration(4),
                            ckdir=str(tmp_path), sharded=True)
        plan_rec = _manifest_plan(str(tmp_path))
        assert plan_rec == {"degrees": {"dp": 2, "pp": 2},
                            "pp_schedule": "gpipe"}
        good = CheckpointManager(str(tmp_path)).latest_good()
        opt2, l2 = train_lm(PartitionPlan(dp=4, tp=2), n_samples=32,
                            end=Trigger.max_epoch(2), resume_from=good)
        merged = dict(l1)
        merged.update(l2)
        _assert_close(merged, o_losses, rtol=2e-4)
        for key in ("epoch", "neval", "records"):
            assert opt2.state[key] == oracle.state[key]

    def test_unplanned_checkpoint_has_no_plan_stamp(self, tmp_path):
        opt1, _ = train_lm(None, end=Trigger.max_iteration(1),
                           ckdir=str(tmp_path))
        assert _manifest_plan(str(tmp_path)) is None
