"""Caffe import tests: protobuf wire codec, prototxt parser, caffemodel
roundtrip, and a full deploy-net import checked numerically against a
torch-built oracle.

Mirrors reference CaffeLoaderSpec (spark/dl/src/test/.../utils/caffe/)
which feeds fixture prototxt+caffemodel files to the loader.
"""

import numpy as np
import jax.numpy as jnp
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu.interop.caffe import (load_caffe, load_caffe_weights,
                                     parse_prototxt, read_caffemodel,
                                     save_caffemodel)
from bigdl_tpu.interop.protowire import (BYTES, VARINT, as_floats,
                                         decode_message, encode_message,
                                         varint)
from bigdl_tpu.utils import set_seed


def test_wire_codec_roundtrip():
    inner = encode_message([(1, BYTES, b"hello"), (2, VARINT, 300)])
    msg = encode_message([(1, BYTES, inner), (3, VARINT, 7),
                          (3, VARINT, 9)])
    dec = decode_message(msg)
    assert dec[3] == [7, 9]
    sub = decode_message(dec[1][0])
    assert sub[1][0] == b"hello"
    assert sub[2][0] == 300


def test_packed_floats():
    arr = np.asarray([1.5, -2.0, 3.25], "<f4")
    msg = encode_message([(5, BYTES, arr.tobytes())])
    dec = decode_message(msg)
    np.testing.assert_allclose(as_floats(dec[5]), arr)


def test_parse_prototxt():
    txt = '''
    name: "TinyNet"  # a comment
    input: "data"
    layer {
      name: "conv1"
      type: "Convolution"
      bottom: "data"
      top: "conv1"
      convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1 }
    }
    layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
    '''
    net = parse_prototxt(txt)
    assert net["name"] == ["TinyNet"]
    assert net["input"] == ["data"]
    assert len(net["layer"]) == 2
    conv = net["layer"][0]
    assert conv["type"] == ["Convolution"]
    assert conv["convolution_param"][0]["num_output"] == [4]


def test_caffemodel_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    layers = {
        "conv1": {"type": "Convolution", "bottom": ["data"],
                  "top": ["conv1"],
                  "blobs": [rng.randn(4, 3, 3, 3).astype(np.float32),
                            rng.randn(4).astype(np.float32)]},
        "fc": {"type": "InnerProduct", "bottom": ["conv1"],
               "top": ["fc"],
               "blobs": [rng.randn(10, 64).astype(np.float32)]},
    }
    p = str(tmp_path / "net.caffemodel")
    save_caffemodel(p, layers)
    back = read_caffemodel(p)
    assert set(back) == {"conv1", "fc"}
    assert back["conv1"]["type"] == "Convolution"
    assert back["conv1"]["bottom"] == ["data"]
    np.testing.assert_allclose(back["conv1"]["blobs"][0],
                               layers["conv1"]["blobs"][0])
    np.testing.assert_allclose(back["fc"]["blobs"][0],
                               layers["fc"]["blobs"][0])


DEPLOY = '''
name: "TinyNet"
input: "data"
layer {
  name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1 }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer {
  name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "fc" type: "InnerProduct" bottom: "pool1" top: "fc"
  inner_product_param { num_output: 5 }
}
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
'''


def _tiny_weights(rng):
    return {
        "conv1": {"type": "Convolution", "bottom": ["data"],
                  "top": ["conv1"],
                  "blobs": [rng.randn(4, 2, 3, 3).astype(np.float32) * .5,
                            rng.randn(4).astype(np.float32) * .1]},
        "fc": {"type": "InnerProduct", "bottom": ["pool1"], "top": ["fc"],
               "blobs": [rng.randn(5, 4 * 3 * 3).astype(np.float32) * .2,
                         rng.randn(5).astype(np.float32) * .1]},
    }


def test_load_caffe_matches_torch_oracle(tmp_path):
    set_seed(0)
    rng = np.random.RandomState(1)
    weights = _tiny_weights(rng)
    proto_p = str(tmp_path / "deploy.prototxt")
    model_p = str(tmp_path / "net.caffemodel")
    with open(proto_p, "w") as f:
        f.write(DEPLOY)
    save_caffemodel(model_p, weights)

    model, layer_map = load_caffe(proto_p, model_p)
    model.eval_mode()
    assert set(layer_map) == {"conv1", "relu1", "pool1", "fc", "prob"}

    x = rng.randn(2, 2, 6, 6).astype(np.float32)  # NCHW like caffe
    out = np.asarray(model(jnp.asarray(x)))

    # torch oracle with the same caffe-layout weights
    tx = torch.tensor(x)
    w = torch.tensor(weights["conv1"]["blobs"][0])
    b = torch.tensor(weights["conv1"]["blobs"][1])
    y = F.conv2d(tx, w, b, stride=1, padding=1)
    y = F.relu(y)
    y = F.max_pool2d(y, 2, 2, ceil_mode=True)
    y = y.flatten(1)
    y = y @ torch.tensor(weights["fc"]["blobs"][0]).T \
        + torch.tensor(weights["fc"]["blobs"][1])
    want = F.softmax(y, dim=1).numpy()
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_load_caffe_weights_by_name(tmp_path):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.module import Parameter
    set_seed(2)
    rng = np.random.RandomState(3)
    weights = _tiny_weights(rng)
    model_p = str(tmp_path / "w.caffemodel")
    save_caffemodel(model_p, weights)

    conv = nn.SpatialConvolution(2, 4, 3, 3, 1, 1, 1, 1,
                                 data_format="NCHW").set_name("conv1")
    fc = nn.Linear(36, 5).set_name("fc")
    model = nn.Sequential(conv, nn.ReLU(), nn.Flatten(), fc)
    model2, copied = load_caffe_weights(model, "", model_p)
    assert set(copied) == {"conv1", "fc"}
    np.testing.assert_allclose(
        np.asarray(conv.weight),
        np.transpose(weights["conv1"]["blobs"][0], (2, 3, 1, 0)),
        rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fc.weight),
                               weights["fc"]["blobs"][0], rtol=1e-6)
    # unknown layer in file + match_all → error
    weights["ghost"] = {"type": "ReLU", "bottom": [], "top": [],
                       "blobs": [np.ones(3, np.float32)]}
    save_caffemodel(model_p, weights)
    with pytest.raises(ValueError, match="ghost"):
        load_caffe_weights(model, "", model_p, match_all=True)


def test_unknown_layer_type_errors(tmp_path):
    proto_p = str(tmp_path / "bad.prototxt")
    with open(proto_p, "w") as f:
        f.write('input: "data"\n'
                'layer { name: "x" type: "FancyOp" bottom: "data" '
                'top: "x" }\n')
    with pytest.raises(ValueError, match="FancyOp"):
        load_caffe(proto_p)


def test_inplace_final_layer_is_output(tmp_path):
    """Regression (round-1 advisor #4): an in-place layer (top == bottom)
    as the LAST layer must stay the graph output — consumption tracking
    by blob NAME dropped it."""
    proto = '''
    name: "InPlaceNet"
    input: "data"
    layer {
      name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
      convolution_param { num_output: 3 kernel_size: 1 stride: 1 }
    }
    layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
    '''
    rng = np.random.RandomState(7)
    weights = {"conv1": {
        "type": "Convolution", "bottom": ["data"], "top": ["conv1"],
        "blobs": [rng.randn(3, 2, 1, 1).astype(np.float32),
                  rng.randn(3).astype(np.float32)]}}
    proto_p = str(tmp_path / "deploy.prototxt")
    model_p = str(tmp_path / "net.caffemodel")
    with open(proto_p, "w") as f:
        f.write(proto)
    save_caffemodel(model_p, weights)
    model, layer_map = load_caffe(proto_p, model_p)
    model.eval_mode()
    x = rng.randn(2, 2, 4, 4).astype(np.float32)
    out = np.asarray(model(jnp.asarray(x)))
    assert out.shape == (2, 3, 4, 4)
    assert (out >= 0).all(), "ReLU (the in-place final layer) missing"
    assert (out == 0).any(), "output is pre-ReLU conv values"


def test_multi_top_partial_consumption(tmp_path):
    """A multi-top layer with only one top consumed must keep the other
    top as a graph output (consumption is per (node, blob-name) pair)."""
    proto = '''
    name: "MultiTop"
    input: "data"
    layer {
      name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
      convolution_param { num_output: 2 kernel_size: 1 stride: 1 }
    }
    layer { name: "split1" type: "ReLU" bottom: "conv1" top: "a" top: "b" }
    layer { name: "relu2" type: "ReLU" bottom: "a" top: "c" }
    '''
    rng = np.random.RandomState(0)
    weights = {"conv1": {
        "type": "Convolution", "bottom": ["data"], "top": ["conv1"],
        "blobs": [rng.randn(2, 2, 1, 1).astype(np.float32),
                  rng.randn(2).astype(np.float32)]}}
    proto_p = str(tmp_path / "deploy.prototxt")
    model_p = str(tmp_path / "net.caffemodel")
    with open(proto_p, "w") as f:
        f.write(proto)
    save_caffemodel(model_p, weights)
    model, _ = load_caffe(proto_p, model_p)
    model.eval_mode()
    out = model(jnp.asarray(rng.randn(1, 2, 3, 3).astype(np.float32)))
    assert isinstance(out, (tuple, list)) and len(out) == 2, \
        "partially-consumed multi-top output was dropped"
