"""Sequence/pipeline/expert parallelism tests on the 8-device CPU mesh.

These capabilities are NEW vs the reference (SURVEY §2.6/§5.7: no
TP/PP/SP/EP of any kind) — correctness oracle is single-device
execution of the same math.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.moe import MoE
from bigdl_tpu.ops.attention_kernels import xla_attention
from bigdl_tpu.parallel import Pipeline, ring_self_attention


def rnd(*shape, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), jnp.float32)


@pytest.fixture()
def seq_mesh():
    with Mesh(np.array(jax.devices()[:8]), ("seq",)) as m:
        yield m


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(seq_mesh, causal):
    q, k, v = rnd(2, 2, 64, 16, seed=1), rnd(2, 2, 64, 16, seed=2), \
        rnd(2, 2, 64, 16, seed=3)
    out = ring_self_attention(q, k, v, seq_mesh, causal=causal)
    ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_with_bias(seq_mesh):
    q, k, v = rnd(2, 2, 64, 16, seed=4), rnd(2, 2, 64, 16, seed=5), \
        rnd(2, 2, 64, 16, seed=6)
    bias = rnd(2, 1, 64, 64, seed=7)
    out = ring_self_attention(q, k, v, seq_mesh, bias=bias)
    ref = xla_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_kernel_matches_full(seq_mesh, causal):
    """The flash-partial ring path (Pallas kernel per visiting chunk,
    scalar-prefetched global offsets) must equal full attention."""
    q, k, v = rnd(1, 2, 128, 16, seed=31), rnd(1, 2, 128, 16, seed=32), \
        rnd(1, 2, 128, 16, seed=33)
    out = ring_self_attention(q, k, v, seq_mesh, causal=causal,
                              kernel="flash")
    ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_kernel_grads(seq_mesh, causal):
    """Grads through the flash ring's BLOCKWISE backward (dK/dV
    accumulators rotating with their chunks) must match full attention
    for q, k, AND v."""
    # T=192 on the 8-way mesh: tc=24, block 8 -> nk=3 blocks per
    # chunk, covering the partial kernels' cross-block accumulation
    q, k, v = rnd(1, 2, 192, 8, seed=34), rnd(1, 2, 192, 8, seed=35), \
        rnd(1, 2, 192, 8, seed=36)

    g_ring = jax.grad(
        lambda args: jnp.sum(ring_self_attention(
            *args, seq_mesh, causal=causal, kernel="flash") ** 2))(
        (q, k, v))
    g_full = jax.grad(
        lambda args: jnp.sum(xla_attention(
            *args, causal=causal) ** 2))((q, k, v))
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-3, atol=1e-4,
                                   err_msg=f"d{name}")


@pytest.mark.slow
def test_ring_attention_grads_match(seq_mesh):
    q, k, v = rnd(1, 2, 64, 8, seed=8), rnd(1, 2, 64, 8, seed=9), \
        rnd(1, 2, 64, 8, seed=10)

    g_ring = jax.grad(
        lambda q_: jnp.sum(ring_self_attention(
            q_, k, v, seq_mesh, causal=True) ** 2))(q)
    g_full = jax.grad(
        lambda q_: jnp.sum(xla_attention(q_, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_pipeline_matches_sequential():
    from bigdl_tpu.utils import set_seed
    set_seed(0)
    blocks = [nn.TransformerEncoderLayer(16, 2, 32) for _ in range(8)]
    pipe = Pipeline(blocks, num_microbatches=4).eval_mode()
    x = rnd(8, 6, 16, seed=11)
    ref = pipe.forward(x)
    for n_stage in (4, 8):
        with Mesh(np.array(jax.devices()[:n_stage]), ("pipe",)) as mesh:
            out = pipe.forward_on_mesh(x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

@pytest.mark.slow
def test_pipeline_per_device_memory_is_microbatch_ring():
    """VERDICT r03 #5: per-device pipeline buffers must be the SHARDED
    microbatch ring (M/S in + M/S out slots + ONE working activation),
    never the replicated full batch."""
    from bigdl_tpu.utils import set_seed
    set_seed(0)
    blocks = [nn.TransformerEncoderLayer(16, 2, 32) for _ in range(4)]
    M, S, mb = 8, 4, 2
    pipe = Pipeline(blocks, num_microbatches=M).eval_mode()
    x = rnd(M * mb, 6, 16, seed=15)
    with Mesh(np.array(jax.devices()[:S]), ("pipe",)) as mesh:
        pipe.forward_on_mesh(x, mesh)
    from bigdl_tpu.parallel.pipeline import LAST_PIPE_SHAPES as shapes
    assert shapes["x_loc"] == (M // S, mb, 6, 16), shapes
    assert shapes["out_loc"] == (M // S, mb, 6, 16), shapes
    assert shapes["carry"] == (mb, 6, 16), shapes


@pytest.mark.slow
def test_pipeline_heterogeneous_stages():
    """Stages with different structures (Linear vs parameterless blocks)
    run via the lax.switch path and match sequential execution, forward
    and backward.  (Stage boundaries must preserve the activation shape
    — the ppermute carry is one uniform buffer.)"""
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.utils import set_seed
    set_seed(3)
    blocks = [nn.Linear(16, 16), nn.ReLU(),
              nn.Linear(16, 16), nn.Tanh()]
    pipe = Pipeline(blocks, num_microbatches=2).eval_mode()
    x = rnd(4, 16, seed=16)
    ref = pipe.forward(x)
    with Mesh(np.array(jax.devices()[:4]), ("pipe",)) as mesh:
        out = pipe.forward_on_mesh(x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)

    params, rest = partition(pipe)

    def loss_seq(p):
        m = combine(p, rest)
        m.pipe_mesh = None
        return jnp.sum(m.forward(x) ** 2)

    def loss_pp(p):
        m = combine(p, rest)
        with Mesh(np.array(jax.devices()[:4]), ("pipe",)) as mesh:
            return jnp.sum(m.forward_on_mesh(x, mesh) ** 2)

    g_s = jax.grad(loss_seq)(params)
    g_p = jax.grad(loss_pp)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_s),
                    jax.tree_util.tree_leaves(g_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_renamed_blocks_stay_stacked():
    """Blocks differing only in display name (set_name for logging) are
    compute-identical and MUST take the sharded stacked path — the
    switch fallback replicates all stages' params on every device."""
    from bigdl_tpu.utils import set_seed
    set_seed(6)
    blocks = [nn.TransformerEncoderLayer(16, 2, 32) for _ in range(4)]
    for i, b in enumerate(blocks):
        b.name = f"stage{i}"
    pipe = Pipeline(blocks, num_microbatches=4).eval_mode()
    x = rnd(8, 6, 16, seed=18)
    ref = pipe.forward(x)
    with Mesh(np.array(jax.devices()[:4]), ("pipe",)) as mesh:
        out = pipe.forward_on_mesh(x, mesh)
    from bigdl_tpu.parallel.pipeline import LAST_PIPE_SHAPES
    assert LAST_PIPE_SHAPES["layout"] == "stacked"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_mixed_blocks_within_stage():
    """[Linear, ReLU] × S stages match each other but the BLOCKS differ,
    so per-block stacking is impossible — must route to the switch path
    and still match sequential."""
    from bigdl_tpu.utils import set_seed
    set_seed(5)
    blocks = [nn.Linear(16, 16), nn.ReLU(),
              nn.Linear(16, 16), nn.ReLU()]
    pipe = Pipeline(blocks, num_microbatches=2).eval_mode()
    x = rnd(4, 16, seed=17)
    ref = pipe.forward(x)
    with Mesh(np.array(jax.devices()[:2]), ("pipe",)) as mesh:
        out = pipe.forward_on_mesh(x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_moe_expert_parallel_matches_dense():
    from bigdl_tpu.utils import set_seed
    set_seed(1)
    moe = MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(8)],
              top_k=2).eval_mode()
    x = rnd(2, 6, 16, seed=12)
    ref = moe.forward(x)
    with Mesh(np.array(jax.devices()[:4]), ("expert",)) as mesh:
        out = moe.forward_on_mesh(x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    assert float(moe.aux_loss) > 0


def _train_mlp(mesh_cfg, rules, n_iter=4):
    """Train the same tiny MLP on the same data under a parallelism
    layout; returns (final loss, final params as numpy leaves)."""
    from bigdl_tpu.utils import set_seed
    from bigdl_tpu.dataset.dataset import Sample, DataSet
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    set_seed(99)
    model = nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10), nn.LogSoftMax())
    rng = np.random.default_rng(5)
    samples = [Sample(rng.normal(size=(16,)).astype(np.float32),
                      int(rng.integers(1, 11))) for _ in range(32)]
    data = (DataSet.array(samples, shuffle=False)
            .transform(SampleToMiniBatch(16)))
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
           .set_end_when(Trigger.max_iteration(n_iter))
           .set_log_interval(1)
           .set_mesh(mesh_cfg, rules))
    opt.optimize()
    leaves = [np.asarray(l) for l in
              jax.tree_util.tree_leaves(model.parameters())]
    return opt.state["loss"], leaves


def test_tensor_parallel_optimizer_equivalence():
    """Replicated-vs-TP numerical oracle (loss + trained params) through
    the full Optimizer loop on a 2x4 data×model mesh."""
    from bigdl_tpu.parallel import (
        MeshConfig, ShardingRules, tensor_parallel_rules,
    )
    loss_rep, params_rep = _train_mlp(MeshConfig(data=8), ShardingRules())
    rules = tensor_parallel_rules(column=[r"layers\[0\]"],
                                  row=[r"layers\[2\]"])
    loss_tp, params_tp = _train_mlp(MeshConfig(data=2, model=4), rules)
    np.testing.assert_allclose(loss_tp, loss_rep, rtol=1e-4)
    for a, b in zip(params_rep, params_tp):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


def test_fsdp_optimizer_equivalence():
    """FSDP-sharded training matches fully replicated training through
    Optimizer.set_mesh (ZeRO-style sharding must not change the math)."""
    from bigdl_tpu.parallel import MeshConfig, ShardingRules
    loss_rep, params_rep = _train_mlp(MeshConfig(data=8), ShardingRules())
    loss_f, params_f = _train_mlp(MeshConfig(data=2, fsdp=4),
                                  ShardingRules(fsdp=True))
    np.testing.assert_allclose(loss_f, loss_rep, rtol=1e-4)
    for a, b in zip(params_rep, params_f):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


def test_fsdp_spec_lands_on_model():
    """The fsdp rules must actually shard parameters of a real model."""
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.parallel import MeshConfig, ShardingRules
    from bigdl_tpu.parallel.sharding import model_shardings
    mesh = MeshConfig(data=2, fsdp=4).build()
    sh = model_shardings(LeNet5(), mesh, ShardingRules(fsdp=True))
    specs = [s.spec for s in jax.tree_util.tree_leaves(
        sh, is_leaf=lambda x: hasattr(x, "spec"))]
    n_sharded = sum(
        1 for s in specs
        if "fsdp" in jax.tree_util.tree_leaves(list(s)))
    assert n_sharded >= 4, f"fsdp landed on only {n_sharded} leaves"


@pytest.mark.slow
def test_pipeline_backward_matches_sequential():
    """Grads through the GPipe ppermute schedule == sequential grads."""
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.utils import set_seed
    set_seed(3)
    pipe = Pipeline([nn.TransformerEncoderLayer(16, 2, 32)
                     for _ in range(4)], num_microbatches=2).eval_mode()
    x = rnd(4, 6, 16, seed=20)
    params, rest = partition(pipe)

    def loss_seq(p):
        m = combine(p, rest)
        y = x
        for blk in m.blocks:
            y = blk(y)
        return jnp.sum(y ** 2)

    def loss_mesh(p):
        m = combine(p, rest)
        with Mesh(np.array(jax.devices()[:4]), ("pipe",)) as mesh:
            return jnp.sum(m.forward_on_mesh(x, mesh) ** 2)

    g_seq = jax.grad(loss_seq)(params)
    g_mesh = jax.grad(loss_mesh)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_seq),
                    jax.tree_util.tree_leaves(g_mesh)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


@pytest.mark.slow
def test_moe_backward_on_mesh_matches_dense():
    """Grads through the expert-parallel psum path == dense grads."""
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.utils import set_seed
    set_seed(4)
    moe = MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(8)],
              top_k=2).eval_mode()
    x = rnd(2, 6, 16, seed=21)
    params, rest = partition(moe)

    def loss_dense(p):
        return jnp.sum(combine(p, rest).forward(x) ** 2)

    def loss_mesh(p):
        m = combine(p, rest)
        with Mesh(np.array(jax.devices()[:4]), ("expert",)) as mesh:
            return jnp.sum(m.forward_on_mesh(x, mesh) ** 2)

    g_d = jax.grad(loss_dense)(params)
    g_m = jax.grad(loss_mesh)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_d),
                    jax.tree_util.tree_leaves(g_m)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


@pytest.mark.slow
def test_moe_a2a_matches_dense_at_ample_capacity():
    """The capacity-based all_to_all EP path (VERDICT r03 #4) must equal
    the dense path exactly when nothing overflows — forward and grads."""
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.utils import set_seed
    set_seed(4)
    moe = MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(8)],
              top_k=2).eval_mode()
    x = rnd(2, 6, 16, seed=21)   # B*T = 12 tokens, S = 3 per device
    params, rest = partition(moe)
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))

    def loss_dense(p):
        return jnp.sum(combine(p, rest).forward(x) ** 2)

    def loss_a2a(p):
        m = combine(p, rest).set_mesh(mesh, capacity_factor=4.0)
        with mesh:
            return jnp.sum(m.forward(x) ** 2)

    with mesh:
        out_a2a = combine(params, rest).set_mesh(
            mesh, capacity_factor=4.0).forward(x)
    out_dense = combine(params, rest).forward(x)
    np.testing.assert_allclose(np.asarray(out_a2a), np.asarray(out_dense),
                               rtol=1e-4, atol=1e-5)

    g_d = jax.grad(loss_dense)(params)
    g_m = jax.grad(loss_a2a)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_d),
                    jax.tree_util.tree_leaves(g_m)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)

@pytest.mark.slow
def test_moe_a2a_per_device_memory_is_tokens_over_n():
    """Per-device activation buffers on the a2a path are O(B·T/n) —
    dispatch [S, E, C] and expert buffers [E/n, n·C, H] with S=B·T/n —
    not the full replicated batch the psum fallback uses."""
    from bigdl_tpu.utils import set_seed
    set_seed(4)
    B, T, H, E, k, n = 2, 8, 16, 8, 2, 4
    moe = MoE(H, [nn.FeedForwardNetwork(H, 32) for _ in range(E)],
              top_k=k).eval_mode()
    x = rnd(B, T, H, seed=22)
    mesh = Mesh(np.array(jax.devices()[:n]), ("expert",))
    f = 2.0
    S = B * T // n
    C = max(1, round(f * k * S / E))
    with mesh:
        out = moe.set_mesh(mesh, capacity_factor=f).forward(x)
    assert out.shape == (B, T, H)
    from bigdl_tpu.nn.moe import LAST_A2A_SHAPES as shapes
    assert shapes["dispatch"] == (S, E, C), shapes
    assert shapes["expert_in"] == (E, C, H), shapes
    assert shapes["recv"] == (E // n, n * C, H), shapes


@pytest.mark.slow
def test_moe_a2a_capacity_overflow_drops_tokens():
    """With a starvation-level capacity the layer must stay finite and
    diverge from dense (dropped tokens contribute zero), locking the
    Switch overflow policy."""
    from bigdl_tpu.utils import set_seed
    set_seed(4)
    moe = MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(4)],
              top_k=2).eval_mode()
    x = rnd(2, 8, 16, seed=23)
    dense = np.asarray(moe.forward(x))
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    with mesh:
        tiny = np.asarray(
            moe.set_mesh(mesh, capacity_factor=0.25).forward(x))
    assert np.isfinite(tiny).all()
    assert not np.allclose(tiny, dense, atol=1e-4)


def _train_seq_model(build, mesh_cfg=None, n_iter=3):
    """Optimizer-driven training of a [B,T,H]->[B,T,H] model against an
    MSE target; returns final loss + trained params."""
    from bigdl_tpu.parallel import MeshConfig
    from bigdl_tpu.utils import set_seed
    from bigdl_tpu.dataset.dataset import Sample, DataSet
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    set_seed(42)
    model = build()
    rng = np.random.default_rng(9)
    samples = [Sample(rng.normal(size=(6, 16)).astype(np.float32),
                      rng.normal(size=(6, 16)).astype(np.float32))
               for _ in range(16)]
    data = (DataSet.array(samples, shuffle=False)
            .transform(SampleToMiniBatch(8)))
    opt = (Optimizer(model, data, nn.MSECriterion())
           .set_optim_method(SGD(0.05))
           .set_end_when(Trigger.max_iteration(n_iter))
           .set_log_interval(1)
           .set_mesh(mesh_cfg or MeshConfig(data=1)))
    opt.optimize()
    leaves = [np.asarray(l) for l in
              jax.tree_util.tree_leaves(model.parameters())]
    return opt.state["loss"], leaves


@pytest.mark.slow
def test_pipeline_optimizer_training_equivalence():
    """A Pipeline with set_mesh trains through the Optimizer and matches
    the sequential-path training run exactly."""
    def seq_build():
        return Pipeline([nn.TransformerEncoderLayer(16, 2, 32)
                         for _ in range(4)], num_microbatches=2)

    loss_seq, params_seq = _train_seq_model(seq_build)

    from bigdl_tpu.parallel import MeshConfig
    cfg = MeshConfig(pipe=4)
    mesh = cfg.build()

    def mesh_build():
        return Pipeline([nn.TransformerEncoderLayer(16, 2, 32)
                         for _ in range(4)],
                        num_microbatches=2).set_mesh(mesh)

    loss_pp, params_pp = _train_seq_model(mesh_build, mesh_cfg=cfg)
    np.testing.assert_allclose(loss_pp, loss_seq, rtol=1e-4)
    for a, b in zip(params_seq, params_pp):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


@pytest.mark.slow
def test_moe_optimizer_training_equivalence():
    """A MoE layer with set_mesh trains through the Optimizer and
    matches dense-path training (EP backward + update end to end)."""
    def dense_build():
        return MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(4)],
                   top_k=2)

    loss_d, params_d = _train_seq_model(dense_build)

    from bigdl_tpu.parallel import MeshConfig
    cfg = MeshConfig(expert=4)
    mesh = cfg.build()

    def mesh_build():
        return MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(4)],
                   top_k=2).set_mesh(mesh)

    loss_m, params_m = _train_seq_model(mesh_build, mesh_cfg=cfg)
    np.testing.assert_allclose(loss_m, loss_d, rtol=1e-4)
    for a, b in zip(params_d, params_m):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)

    def a2a_build():
        return MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(4)],
                   top_k=2).set_mesh(mesh, capacity_factor=2.0)

    loss_a, params_a = _train_seq_model(a2a_build, mesh_cfg=cfg)
    np.testing.assert_allclose(loss_a, loss_d, rtol=1e-4)
    for a, b in zip(params_d, params_a):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


@pytest.mark.slow
def test_moe_trains():
    """Gradient flows through routing + experts; aux loss finite."""
    from bigdl_tpu.utils import set_seed
    from bigdl_tpu.core.module import partition, combine
    set_seed(2)
    moe = MoE(8, [nn.FeedForwardNetwork(8, 16) for _ in range(4)], top_k=2)
    x = rnd(2, 5, 8, seed=13)
    params, rest = partition(moe)

    def loss_fn(p):
        m = combine(p, rest)
        y = m.forward(x)
        return jnp.mean(y ** 2) + 0.01 * m.aux_loss

    loss, grads = jax.value_and_grad(loss_fn)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # gate must receive gradient (routing is differentiable via weights)
    gate_grad = grads.gate._params["weight"]
    assert float(jnp.abs(gate_grad).max()) > 0


@pytest.mark.slow
def test_tp_sp_composition_matches_dense():
    """TP (Megatron head-sharded projections, model axis) composes with
    SP (ring attention, seq axis) on ONE mesh: head_axis keeps the TP
    sharding THROUGH the ring's shard_map (no forced head all-gather),
    and loss + all grads match the dense model."""
    from bigdl_tpu.core.module import combine, partition
    from bigdl_tpu.models import transformer_lm
    from bigdl_tpu.parallel import tensor_parallel_rules
    from bigdl_tpu.parallel.sharding import shard_model_params
    from bigdl_tpu.utils import set_seed

    set_seed(0)
    lm = transformer_lm(vocab_size=30, hidden_size=16, num_layers=2,
                        num_heads=2, filter_size=32,
                        max_len=64).eval_mode()
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(1, 31, (2, 16)), jnp.int32)
    targets = jnp.asarray(rng.integers(1, 31, (2, 16)), jnp.int32)
    crit = nn.CrossEntropyCriterion()

    def loss_grads(model):
        params, rest = partition(model)

        def f(p):
            out = combine(p, rest).forward(toks).reshape(-1, 31)
            return crit(out, targets.reshape(-1))

        l, g = jax.value_and_grad(f)(params)
        return float(l), {jax.tree_util.keystr(kp): np.asarray(v)
                          for kp, v in
                          jax.tree_util.tree_leaves_with_path(g)}

    l_dense, g_dense = loss_grads(lm)
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("model", "seq"))
    rules = tensor_parallel_rules(
        column=[r".*q_layer.*", r".*k_layer.*", r".*v_layer.*",
                r".*filter_layer.*"],
        row=[r".*output_layer.*"])
    with mesh:
        lm2 = shard_model_params(lm, mesh, rules)
        lm2.set_sequence_parallel(mesh, "seq", head_axis="model")
        l_both, g_both = loss_grads(lm2)
    np.testing.assert_allclose(l_both, l_dense, rtol=1e-4)
    assert set(g_both) == set(g_dense)
    for kp in g_dense:
        np.testing.assert_allclose(g_both[kp], g_dense[kp],
                                   rtol=3e-3, atol=3e-4, err_msg=kp)


@pytest.mark.slow
def test_dp_pp_composition_training_equivalence():
    """DP (batch over data axis) composes with PP (GPipe microbatch ring
    over the pipe axis) on one mesh through the full Optimizer loop:
    loss and trained params match the single-device sequential run."""
    from bigdl_tpu.parallel import MeshConfig

    def build(mesh=None):
        pipe = Pipeline([nn.TransformerEncoderLayer(16, 2, 32)
                         for _ in range(4)], num_microbatches=2)
        return pipe.set_mesh(mesh) if mesh is not None else pipe

    l_ref, p_ref = _train_seq_model(build, n_iter=4)
    cfg = MeshConfig(data=2, pipe=4)
    mesh = cfg.build()
    l_both, p_both = _train_seq_model(lambda: build(mesh), mesh_cfg=cfg,
                                      n_iter=4)
    np.testing.assert_allclose(l_both, l_ref, rtol=1e-4)
    for a, b in zip(p_ref, p_both):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


@pytest.mark.slow
def test_dp_sp_composition_training_equivalence():
    """DP (batch over data axis) composes with SP (ring attention over
    the seq axis) through the full Optimizer loop on a TransformerLM:
    loss and trained params match the dense single-device run."""
    from bigdl_tpu.models import transformer_lm
    from bigdl_tpu.parallel import MeshConfig
    from bigdl_tpu.dataset.dataset import Sample, DataSet
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils import set_seed

    def train(mesh_cfg, sp_mesh=None):
        set_seed(5)
        lm = transformer_lm(vocab_size=30, hidden_size=16, num_layers=2,
                            num_heads=2, filter_size=32, max_len=32)
        if sp_mesh is not None:
            lm.set_sequence_parallel(sp_mesh, "seq")
        rng = np.random.default_rng(7)
        samples = [Sample(rng.integers(1, 31, size=(32,)).astype(np.int32),
                          rng.integers(1, 31, size=(32,)).astype(np.int32))
                   for _ in range(8)]
        data = (DataSet.array(samples, shuffle=False)
                .transform(SampleToMiniBatch(4)))
        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        opt = (Optimizer(lm, data, crit)
               .set_optim_method(SGD(0.05))
               .set_end_when(Trigger.max_iteration(4))
               .set_mesh(mesh_cfg))
        opt.optimize()
        return float(opt.state["loss"]), [
            np.asarray(l) for l in
            jax.tree_util.tree_leaves(lm.parameters())]

    l_ref, p_ref = train(MeshConfig(data=1))
    cfg = MeshConfig(data=2, seq=4)
    l_both, p_both = train(cfg, cfg.build())
    np.testing.assert_allclose(l_both, l_ref, rtol=1e-4)
    for a, b in zip(p_ref, p_both):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=2e-5)


@pytest.mark.slow
def test_dp_ep_composition_training_equivalence():
    """DP (batch over data axis) composes with EP (a2a token dispatch
    over the expert axis) on one mesh, through the full Optimizer loop:
    loss and trained params match the dense single-device run."""
    from bigdl_tpu.parallel import MeshConfig
    from bigdl_tpu.dataset.dataset import Sample, DataSet
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils import set_seed

    def train(mesh_cfg, moe_mesh=None):
        set_seed(42)
        moe = MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(4)],
                  top_k=2)
        if moe_mesh is not None:
            moe.set_mesh(moe_mesh, "expert", capacity_factor=2.0)
        rng = np.random.default_rng(9)
        samples = [Sample(rng.normal(size=(6, 16)).astype(np.float32),
                          rng.normal(size=(6, 16)).astype(np.float32))
                   for _ in range(16)]
        data = (DataSet.array(samples, shuffle=False)
                .transform(SampleToMiniBatch(8)))
        opt = (Optimizer(moe, data, nn.MSECriterion())
               .set_optim_method(SGD(0.05))
               .set_end_when(Trigger.max_iteration(4))
               .set_mesh(mesh_cfg))
        opt.optimize()
        return float(opt.state["loss"]), [
            np.asarray(l) for l in
            jax.tree_util.tree_leaves(moe.parameters())]

    l_ref, p_ref = train(MeshConfig(data=1))
    cfg = MeshConfig(data=2, expert=4)
    l_both, p_both = train(cfg, cfg.build())
    np.testing.assert_allclose(l_both, l_ref, rtol=1e-4)
    for a, b in zip(p_ref, p_both):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


# ---------------------------------------------------------------------------
# 1F1B pipelined training step
# ---------------------------------------------------------------------------

def _mse(out, tgt):
    return jnp.mean((out - tgt) ** 2)


def _ref_1f1b(pipe, x, tgt, s, m):
    """Sequential oracle for the 1F1B step: mean-over-microbatches loss
    through the same stacked parameter layout."""
    per_stage = len(pipe.blocks) // s
    stacked = jax.tree_util.tree_map(
        lambda l: l.reshape((s, per_stage) + l.shape[1:]),
        pipe._stacked())
    x_mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    t_mb = tgt.reshape((m, tgt.shape[0] // m) + tgt.shape[1:])

    def loss_of(stacked_p, x):
        tot = 0.0
        for i in range(m):
            h = x[i]
            for si in range(s):
                stage = jax.tree_util.tree_map(lambda l: l[si], stacked_p)
                for bi in range(per_stage):
                    blk = jax.tree_util.tree_map(lambda l: l[bi], stage)
                    h = blk(h)
            tot = tot + _mse(h.astype(jnp.float32), t_mb[i])
        return tot / m

    loss, (grads, dx) = jax.value_and_grad(loss_of, argnums=(0, 1))(
        stacked, x_mb)
    return loss, grads, dx.reshape(x.shape)


@pytest.mark.parametrize("s,m", [(2, 4)])
def test_1f1b_matches_sequential(s, m):
    from bigdl_tpu.utils import set_seed
    set_seed(0)
    blocks = [nn.TransformerEncoderLayer(16, 2, 32)
              for _ in range(s * 2)]
    pipe = Pipeline(blocks, num_microbatches=m).eval_mode()
    x = rnd(m * 2, 6, 16, seed=31)
    tgt = rnd(m * 2, 6, 16, seed=32)
    with Mesh(np.array(jax.devices()[:s]), ("pipe",)) as mesh:
        loss, grads, dx = pipe.train_step_on_mesh(x, tgt, _mse, mesh)
    ref_loss, ref_grads, ref_dx = _ref_1f1b(pipe, x, tgt, s, m)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(ref_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=1e-3, atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("s,m", [(4, 8), (2, 8), (4, 6)])  # 6: padded
def test_1f1b_matches_sequential_full(s, m):
    test_1f1b_matches_sequential(s, m)


@pytest.mark.slow
def test_1f1b_matches_gpipe_loss():
    """1F1B and GPipe-forward+loss agree (same math, different
    schedule)."""
    from bigdl_tpu.utils import set_seed
    set_seed(0)
    s, m = 4, 8
    blocks = [nn.TransformerEncoderLayer(16, 2, 32) for _ in range(4)]
    pipe = Pipeline(blocks, num_microbatches=m).eval_mode()
    x = rnd(16, 6, 16, seed=33)
    tgt = rnd(16, 6, 16, seed=34)
    with Mesh(np.array(jax.devices()[:s]), ("pipe",)) as mesh:
        loss, _, _ = pipe.train_step_on_mesh(x, tgt, _mse, mesh)
        y = pipe.forward_on_mesh(x, mesh)
    mbs = x.shape[0] // m
    ref = jnp.mean(jnp.stack([
        _mse(y[i * mbs:(i + 1) * mbs].astype(jnp.float32),
             tgt[i * mbs:(i + 1) * mbs]) for i in range(m)]))
    np.testing.assert_allclose(float(loss), float(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_1f1b_ring_memory_and_bubble():
    """The 1F1B residual ring is 2S-1 slots — INDEPENDENT of M (GPipe
    under autodiff stashes O(M) tick residuals) — and the schedule
    drains in M + 2S - 2 ticks (same bubble FRACTION as GPipe; the win
    is memory)."""
    from bigdl_tpu.parallel.pipeline import LAST_PIPE_SHAPES as shapes
    from bigdl_tpu.utils import set_seed
    set_seed(0)
    s, m, mb = 2, 8, 2
    blocks = [nn.TransformerEncoderLayer(16, 2, 32) for _ in range(2)]
    pipe = Pipeline(blocks, num_microbatches=m).eval_mode()
    x = rnd(m * mb, 6, 16, seed=35)
    tgt = rnd(m * mb, 6, 16, seed=36)
    with Mesh(np.array(jax.devices()[:s]), ("pipe",)) as mesh:
        pipe.train_step_on_mesh(x, tgt, _mse, mesh)
    assert shapes["ring"] == (2 * s - 1, mb, 6, 16), shapes
    assert shapes["ring"][0] < m  # smaller than the microbatch count
    assert shapes["ticks_1f1b"] == m + 2 * s - 2, shapes


def test_1f1b_rejects_heterogeneous():
    pipe = Pipeline([nn.Linear(8, 8), nn.ReLU()]).eval_mode()
    with Mesh(np.array(jax.devices()[:2]), ("pipe",)) as mesh:
        with pytest.raises(NotImplementedError):
            pipe.train_step_on_mesh(rnd(4, 8, seed=37),
                                    rnd(4, 8, seed=38), _mse, mesh)


# ---------------------------------------------------------------------------
# EP under realistic capacity (VERDICT r04 weak #4)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_moe_trains_at_realistic_capacity():
    """Training quality under capacity_factor 1.25 — the regime real
    Switch/GShard deployments run in: the task loss must converge to
    within tolerance of the DENSE run of the same schedule, and the aux
    loss must keep the overflow-drop rate bounded (drop telemetry
    exposed via MoE.drop_rate)."""
    from bigdl_tpu.core.module import partition, combine
    from bigdl_tpu.utils import set_seed

    def build():
        set_seed(5)
        return MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(8)],
                   top_k=2).eval_mode()

    rng = np.random.default_rng(17)
    x = jnp.asarray(rng.normal(size=(4, 16, 16)), jnp.float32)
    teacher = jnp.asarray(rng.normal(size=(16, 16)), jnp.float32)
    target = jnp.tanh(x @ teacher)

    def train(use_mesh, steps=200, aux_w=0.02):
        moe = build()
        mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
        if use_mesh:
            moe.set_mesh(mesh, capacity_factor=1.25)
        params, rest = partition(moe)

        def loss_fn(p):
            m = combine(p, rest)
            with mesh:
                y = m.forward(x)
            task = jnp.mean((y - target) ** 2)
            return task + aux_w * m.aux_loss, (task, m.drop_rate)

        @jax.jit
        def step(p):
            (_, (task, drop)), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p)
            p = jax.tree_util.tree_map(lambda a, b: a - 0.3 * b, p, g)
            return p, task, drop

        task = drop = None
        first_task = None
        for i in range(steps):
            params, task, drop = step(params)
            if first_task is None:
                first_task = float(task)
        return first_task, float(task), float(drop)

    first_ep, ep_loss, ep_drop = train(True)
    _, dense_loss, dense_drop = train(False)

    # it trains: the EP task loss must drop substantially
    assert ep_loss < 0.5 * first_ep, (first_ep, ep_loss)
    # convergence within tolerance of dense (dropped-token noise only)
    assert ep_loss < dense_loss + 0.25 * abs(dense_loss) + 0.02, (
        ep_loss, dense_loss)
    # the aux loss keeps overflow bounded at capacity_factor 1.25
    assert ep_drop < 0.25, ep_drop
    assert dense_drop == 0.0  # dense path never drops
