"""Sequence/pipeline/expert parallelism tests on the 8-device CPU mesh.

These capabilities are NEW vs the reference (SURVEY §2.6/§5.7: no
TP/PP/SP/EP of any kind) — correctness oracle is single-device
execution of the same math.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.moe import MoE
from bigdl_tpu.ops.attention_kernels import xla_attention
from bigdl_tpu.parallel import Pipeline, ring_self_attention


def rnd(*shape, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), jnp.float32)


@pytest.fixture()
def seq_mesh():
    with Mesh(np.array(jax.devices()[:8]), ("seq",)) as m:
        yield m


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(seq_mesh, causal):
    q, k, v = rnd(2, 2, 64, 16, seed=1), rnd(2, 2, 64, 16, seed=2), \
        rnd(2, 2, 64, 16, seed=3)
    out = ring_self_attention(q, k, v, seq_mesh, causal=causal)
    ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_with_bias(seq_mesh):
    q, k, v = rnd(2, 2, 64, 16, seed=4), rnd(2, 2, 64, 16, seed=5), \
        rnd(2, 2, 64, 16, seed=6)
    bias = rnd(2, 1, 64, 64, seed=7)
    out = ring_self_attention(q, k, v, seq_mesh, bias=bias)
    ref = xla_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_grads_match(seq_mesh):
    q, k, v = rnd(1, 2, 64, 8, seed=8), rnd(1, 2, 64, 8, seed=9), \
        rnd(1, 2, 64, 8, seed=10)

    g_ring = jax.grad(
        lambda q_: jnp.sum(ring_self_attention(
            q_, k, v, seq_mesh, causal=True) ** 2))(q)
    g_full = jax.grad(
        lambda q_: jnp.sum(xla_attention(q_, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=1e-3, atol=1e-4)


def test_pipeline_matches_sequential():
    from bigdl_tpu.utils import set_seed
    set_seed(0)
    blocks = [nn.TransformerEncoderLayer(16, 2, 32) for _ in range(8)]
    pipe = Pipeline(blocks, num_microbatches=4).eval_mode()
    x = rnd(8, 6, 16, seed=11)
    ref = pipe.forward(x)
    for n_stage in (4, 8):
        with Mesh(np.array(jax.devices()[:n_stage]), ("pipe",)) as mesh:
            out = pipe.forward_on_mesh(x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)


def test_moe_expert_parallel_matches_dense():
    from bigdl_tpu.utils import set_seed
    set_seed(1)
    moe = MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(8)],
              top_k=2).eval_mode()
    x = rnd(2, 6, 16, seed=12)
    ref = moe.forward(x)
    with Mesh(np.array(jax.devices()[:4]), ("expert",)) as mesh:
        out = moe.forward_on_mesh(x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    assert float(moe.aux_loss) > 0


def test_moe_trains():
    """Gradient flows through routing + experts; aux loss finite."""
    from bigdl_tpu.utils import set_seed
    from bigdl_tpu.core.module import partition, combine
    set_seed(2)
    moe = MoE(8, [nn.FeedForwardNetwork(8, 16) for _ in range(4)], top_k=2)
    x = rnd(2, 5, 8, seed=13)
    params, rest = partition(moe)

    def loss_fn(p):
        m = combine(p, rest)
        y = m.forward(x)
        return jnp.mean(y ** 2) + 0.01 * m.aux_loss

    loss, grads = jax.value_and_grad(loss_fn)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # gate must receive gradient (routing is differentiable via weights)
    gate_grad = grads.gate._params["weight"]
    assert float(jnp.abs(gate_grad).max()) > 0
