"""dlframes (pandas/sklearn pipeline integration) + Engine runtime tests.

Mirrors reference DLEstimatorSpec/DLClassifierSpec
(spark/dl/src/test/.../dlframes/) and utils/EngineSpec.
"""

import os

import numpy as np
import pandas as pd
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dlframes import (DLClassifier, DLEstimator, DLImageReader,
                                DLImageTransformer, DLModel)
from bigdl_tpu.utils import Engine, ThreadPool, get_property, set_seed


def _toy_df(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32) + 1  # classes 1/2
    return pd.DataFrame({"features": list(x), "label": list(y)}), x, y


def test_dl_classifier_fit_transform():
    set_seed(0)
    df, x, y = _toy_df()
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2),
                          nn.LogSoftMax())
    clf = DLClassifier(model, feature_size=(4,),
                       batch_size=16, max_epoch=30, learning_rate=0.5)
    fitted = clf.fit(df)
    out = fitted.transform(df)
    assert "prediction" in out.columns
    acc = (out["prediction"].to_numpy() == y).mean()
    assert acc >= 0.9, acc


def test_dl_estimator_regression():
    set_seed(1)
    rng = np.random.RandomState(2)
    x = rng.randn(48, 3).astype(np.float32)
    w = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
    y = x @ w
    df = pd.DataFrame({"features": list(x), "label": list(y)})
    est = DLEstimator(nn.Linear(3, 1), nn.MSECriterion(),
                      feature_size=(3,), label_size=(1,),
                      batch_size=16, max_epoch=40, learning_rate=0.1)
    fitted = est.fit(df)
    out = fitted.transform(df)
    preds = np.stack(out["prediction"].to_numpy())
    assert np.abs(preds - y).mean() < 0.1


@pytest.mark.slow
def test_sklearn_pipeline_compat():
    """DLEstimator must compose in sklearn Pipelines (the analog of the
    reference's Spark ML pipeline integration)."""
    from sklearn.pipeline import Pipeline
    set_seed(2)
    df, x, y = _toy_df(seed=3)
    model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    clf = DLClassifier(model, feature_size=(4,), batch_size=16,
                       max_epoch=20, learning_rate=0.5)
    pipe = Pipeline([("clf", clf)])
    fitted = pipe.fit(df)
    out = fitted.named_steps["clf"].fit(df).transform(df)
    assert "prediction" in out.columns


def test_dl_image_reader_and_transformer(tmp_path):
    from PIL import Image
    from bigdl_tpu.transform.vision import ChannelNormalize, Resize
    d = tmp_path / "cls" / "a"
    d.mkdir(parents=True)
    for i in range(3):
        Image.fromarray(
            np.full((8, 8, 3), i * 40, np.uint8)).save(d / f"{i}.png")
    df = DLImageReader.read_images(str(tmp_path / "cls"),
                                   with_label_from_dirs=True)
    assert len(df) == 3 and "image" in df.columns
    tr = DLImageTransformer(Resize(4, 4) >> ChannelNormalize(0, 0, 0,
                                                             255, 255, 255))
    out = tr.transform(df)
    assert out["features"][0].shape == (4, 4, 3)
    assert out["features"][2].max() <= 1.0


def test_engine_topology_and_pools():
    Engine.reset()
    Engine.init()
    assert Engine.node_number() >= 1
    assert Engine.core_number() >= 1
    assert Engine.check_singleton()
    pool = Engine.default_pool()
    results = pool.invoke_and_wait([lambda i=i: i * i for i in range(5)])
    assert sorted(results) == [0, 1, 4, 9, 16]
    done, pending = pool.invoke_and_wait2(
        [lambda: 1, lambda: 2], timeout=10)
    assert len(done) == 2 and not pending


def test_engine_properties(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_CORENUMBER", "3")
    assert get_property("bigdl.coreNumber") == "3"
    Engine.reset()
    Engine.init()
    assert Engine.core_number() == 3
    Engine.reset()
    Engine.init(node_number=2, core_number=8)
    assert Engine.node_number() == 2
    assert Engine.core_number() == 8
    Engine.reset()


def test_optimizer_version_switch():
    Engine.set_optimizer_version("optimizerV2")
    assert Engine.get_optimizer_version() == "optimizerV2"
    Engine.set_optimizer_version("optimizerV1")
    with pytest.raises(AssertionError):
        Engine.set_optimizer_version("bogus")


def test_init_distributed_single_process_and_idempotent(monkeypatch):
    """num_processes==1 (explicit or via the env tier) must skip the
    DCN coordinator entirely and later calls must be no-ops — library
    code calls this defensively."""
    Engine.reset()
    monkeypatch.setenv("BIGDL_TPU_NUM_PROCESSES", "1")
    Engine.init_distributed()
    assert getattr(Engine._state, "dist_inited", False)
    # second call (different args) is a no-op, not a re-init attempt
    Engine.init_distributed(coordinator_address="bogus:1234",
                            num_processes=8, process_id=0)
    assert Engine.node_number() >= 1
    Engine.reset()
