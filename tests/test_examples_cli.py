"""End-user entry points: dataset file readers + runnable mains
(reference models/lenet/Train.scala, models/resnet/Train.scala,
example/languagemodel/PTBWordLM.scala)."""

import gzip
import struct

import numpy as np
import pytest


def _write_idx(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">i", 0x800 + arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">i", d))
        f.write(arr.astype(np.uint8).tobytes())


@pytest.fixture()
def mnist_dir(tmp_path):
    rng = np.random.default_rng(0)
    for prefix, n in (("train", 32), ("t10k", 16)):
        _write_idx(tmp_path / f"{prefix}-images-idx3-ubyte",
                   rng.integers(0, 256, size=(n, 28, 28)))
        _write_idx(tmp_path / f"{prefix}-labels-idx1-ubyte",
                   rng.integers(0, 10, size=(n,)))
    return str(tmp_path)


def test_mnist_reader(mnist_dir):
    from bigdl_tpu.dataset.mnist import load_mnist, mnist_samples
    images, labels = load_mnist(mnist_dir, train=True)
    assert images.shape == (32, 28, 28) and labels.shape == (32,)
    samples = mnist_samples(mnist_dir, train=False)
    assert len(samples) == 16
    assert all(1 <= s.label <= 10 for s in samples)
    assert abs(float(np.mean([s.feature.mean() for s in samples]))) < 3.0


def test_mnist_reader_gz(tmp_path):
    from bigdl_tpu.dataset.mnist import load_mnist
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, size=(4, 28, 28)).astype(np.uint8)
    lbls = rng.integers(0, 10, size=(4,)).astype(np.uint8)
    for name, arr in (("train-images-idx3-ubyte", imgs),
                      ("train-labels-idx1-ubyte", lbls)):
        raw = struct.pack(">i", 0x800 + arr.ndim)
        for d in arr.shape:
            raw += struct.pack(">i", d)
        raw += arr.tobytes()
        with gzip.open(tmp_path / (name + ".gz"), "wb") as f:
            f.write(raw)
    images, labels = load_mnist(str(tmp_path), train=True)
    np.testing.assert_array_equal(images, imgs)
    np.testing.assert_array_equal(labels, lbls)


def test_cifar_reader(tmp_path):
    from bigdl_tpu.dataset.cifar import cifar10_samples, load_cifar10
    rng = np.random.default_rng(0)
    for i in range(1, 6):
        rec = rng.integers(0, 256, size=(8, 3073)).astype(np.uint8)
        rec[:, 0] = rng.integers(0, 10, size=8)
        rec.tofile(tmp_path / f"data_batch_{i}.bin")
    rec.tofile(tmp_path / "test_batch.bin")
    images, labels = load_cifar10(str(tmp_path), train=True)
    assert images.shape == (40, 32, 32, 3) and labels.shape == (40,)
    samples = cifar10_samples(str(tmp_path), train=False)
    assert len(samples) == 8 and samples[0].feature.shape == (32, 32, 3)


def test_ptb_corpus(tmp_path):
    from bigdl_tpu.dataset.text import load_ptb_corpus, ptb_batches
    text = "the cat sat on the mat\nthe dog ran\n"
    for split in ("train", "valid", "test"):
        (tmp_path / f"ptb.{split}.txt").write_text(text * 20)
    train, valid, test, d = load_ptb_corpus(str(tmp_path), vocab_size=50)
    assert d.index("the") >= 1 and d.index("<eos>") >= 1
    assert train.dtype == np.int32 and len(train) == 11 * 20
    batches = ptb_batches(train, batch_size=4, num_steps=5)
    x, y = batches[0]
    assert x.shape == (4, 5)
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])


def test_ptb_corpus_missing(tmp_path):
    from bigdl_tpu.dataset.text import load_ptb_corpus
    with pytest.raises(FileNotFoundError):
        load_ptb_corpus(str(tmp_path))


@pytest.mark.slow
def test_lenet_main_synthetic(tmp_path):
    from bigdl_tpu.examples.lenet import main
    model = main(["--synthetic", "64", "-e", "1", "-b", "32", "-q",
                  "--checkpoint", str(tmp_path / "ckpt")])
    assert (tmp_path / "ckpt" / "checkpoint.npz").exists()
    assert model is not None


@pytest.mark.slow
def test_lenet_main_real_files(mnist_dir):
    from bigdl_tpu.examples.lenet import main
    model = main(["-f", mnist_dir, "-e", "1", "-b", "16", "-q"])
    assert model is not None

@pytest.mark.slow
def test_ptb_main_synthetic():
    from bigdl_tpu.examples.ptb_lm import main
    model = main(["--synthetic", "2000", "-e", "1", "-q", "-b", "8",
                  "--hidden-size", "16", "--num-steps", "8",
                  "--vocab-size", "50"])
    assert model is not None


def test_cache_on_device_distinct_batches():
    """Regression: id()-recycling of freed batch arrays must not alias
    distinct batches to one cached transfer."""
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(4,)).astype(np.float32), i + 1)
               for i in range(32)]
    data = (DataSet.array(samples, shuffle=False)
            .transform(SampleToMiniBatch(8)).cache_on_device())
    first = [np.asarray(b.get_input()) for b in data.data(train=False)]
    assert len(first) == 4
    for i in range(len(first)):
        for j in range(i + 1, len(first)):
            assert not np.array_equal(first[i], first[j])
    again = [np.asarray(b.get_input()) for b in data.data(train=False)]
    for a, b in zip(first, again):
        np.testing.assert_array_equal(a, b)


def test_cache_on_device_respects_shuffle_flag():
    from bigdl_tpu.dataset import DataSet, MiniBatch
    batches = [MiniBatch(np.full((2, 3), i, np.float32),
                         np.ones(2, np.int32)) for i in range(6)]
    data = DataSet.array(batches, shuffle=False).cache_on_device()
    vals = [float(np.asarray(b.get_input())[0, 0])
            for b in data.data(train=True)]
    assert vals == sorted(vals)


def test_main_requires_data_source():
    from bigdl_tpu.examples.lenet import main
    with pytest.raises(SystemExit):
        main(["-e", "1"])


@pytest.mark.slow
def test_ptb_main_real_files(tmp_path):
    """PTB LM trains end-to-end from ptb.*.txt files on disk."""
    from bigdl_tpu.examples.ptb_lm import main
    text = ("the quick brown fox jumps over the lazy dog\n"
            "a stitch in time saves nine\n") * 120
    for split in ("train", "valid", "test"):
        (tmp_path / f"ptb.{split}.txt").write_text(text)
    model = main(["-f", str(tmp_path), "-e", "1", "-q", "-b", "8",
                  "--hidden-size", "16", "--num-steps", "8",
                  "--vocab-size", "30"])
    assert model is not None


@pytest.mark.slow
def test_textclassifier_synthetic():
    from bigdl_tpu.examples.text_classifier import main
    model = main(["--synthetic", "256", "-e", "2", "-q", "-b", "32",
                  "--seq-len", "32"])
    assert model is not None


def test_textclassifier_folder(tmp_path):
    """Class-per-subdirectory corpus (the reference's 20news layout)."""
    from bigdl_tpu.examples.text_classifier import main
    texts = {"sport": "the game was won by the home team in overtime",
             "tech": "the compiler fuses the kernel into the graph"}
    for cls, line in texts.items():
        d = tmp_path / cls
        d.mkdir()
        for i in range(24):
            (d / f"doc{i}.txt").write_text(line + f" sample {i}")
    model = main(["-f", str(tmp_path), "-e", "1", "-q", "-b", "8",
                  "--seq-len", "16", "--vocab-size", "100"])
    assert model is not None


@pytest.mark.slow
def test_imagenet_main_synthetic():
    from bigdl_tpu.examples.imagenet import main
    model = main(["--synthetic", "32", "--model", "resnet50", "-e", "1",
                  "-b", "16", "-q", "--image-size", "32",
                  "--classes", "4"])
    assert model is not None


@pytest.mark.slow
def test_imagenet_main_folder(tmp_path):
    """Real image-folder path through the vision augmentation pipeline."""
    PIL = pytest.importorskip("PIL")
    from PIL import Image
    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        for cls in ("cat", "dog"):
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            for i in range(8):
                arr = rng.integers(0, 255, size=(40, 40, 3)).astype("uint8")
                Image.fromarray(arr).save(d / f"{i}.png")
    from bigdl_tpu.examples.imagenet import main
    model = main(["-f", str(tmp_path), "--model", "inception-v1",
                  "-e", "1", "-b", "8", "-q", "--classes", "2"])
    assert model is not None


@pytest.mark.slow
def test_inception_v2_forward_grad_and_blocks():
    """BN-Inception (reference Inception_v2.scala): channel progression
    through all ten blocks matches the reference configs exactly, the
    head is trainable, and grid-reduction blocks halve the grid (64px
    input — the global-mean head is resolution-agnostic — but ten
    BN-conv blocks still compile slowly on the 1-core box: slow
    suite)."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.core.module import combine, partition
    from bigdl_tpu.models import Inception_v2
    from bigdl_tpu.utils import set_seed

    set_seed(0)
    m = Inception_v2(class_num=5).eval_mode()
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 64, 64, 3)), np.float32)
    y = m.stem(x)
    widths = []
    for b in m.blocks:
        y = b(y)
        widths.append(y.shape[-1])
    assert widths == [256, 320, 576, 576, 576, 576, 576, 1024, 1024,
                      1024], widths
    assert y.shape[1] == 2  # 64px -> /8 stem -> /2 (3c) -> /2 (4e)

    out = m.forward(x)
    assert out.shape == (2, 5)
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0,
                               rtol=1e-5)  # log-probs tail
    params, rest = partition(m)
    g = jax.grad(lambda p: jnp.sum(
        combine(p, rest).forward(x) ** 2))(params)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(g))


def test_imagenet_warmup_schedule_ramps_to_peak():
    """Warmup must ramp ~0 -> peak lr, then Poly decays FROM the peak
    (regression: the ramp used to start at the peak and reach 2x it,
    and warmup_epochs == max_epoch produced a 0/0 NaN lr)."""
    from bigdl_tpu.optim.methods import Poly, SequentialSchedule, Warmup
    peak, iters_per_epoch, max_epoch, warm_epochs = 0.4, 10, 9, 3
    total = max_epoch * iters_per_epoch
    warm = warm_epochs * iters_per_epoch
    start = peak / warm
    sched = (SequentialSchedule(iters_per_epoch)
             .add(Warmup((peak - start) / warm), warm)
             .add(Poly(0.5, total - warm), total - warm))
    lr0 = float(sched(start, 0, 0))
    lr_end_warm = float(sched(start, warm, 0))
    lr_mid = float(sched(start, (warm + total) // 2, 0))
    lr_last = float(sched(start, total - 1, 0))
    assert abs(lr0 - start) < 1e-6
    assert abs(lr_end_warm - peak) < 1e-6
    assert 0.0 < lr_mid < peak
    assert 0.0 <= lr_last < lr_mid
    import math
    for s in range(0, total + 5):
        assert math.isfinite(float(sched(start, s, 0)))


@pytest.mark.slow
def test_imagenet_main_rejects_warmup_ge_epochs():
    import pytest as _pytest
    from bigdl_tpu.examples.imagenet import main
    with _pytest.raises(SystemExit):
        main(["--synthetic", "32", "-e", "1", "--warmup-epochs", "1",
              "-b", "16", "-q", "--image-size", "32", "--classes", "4"])


def test_image_folder_listing_filters_and_shares_class_map(tmp_path):
    from PIL import Image
    rng = np.random.default_rng(0)
    for cls in ("a", "b", "c"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        arr = rng.integers(0, 255, size=(8, 8, 3)).astype("uint8")
        Image.fromarray(arr).save(d / "x.png")
    # stray non-image files must be ignored, not decoded
    (tmp_path / "train" / "a" / "README.txt").write_text("notes")
    (tmp_path / "train" / "b" / ".DS_Store").write_bytes(b"\x00junk")
    # val/ is missing class "b": labels must come from the TRAIN mapping
    for cls in ("a", "c"):
        d = tmp_path / "val" / cls
        d.mkdir(parents=True)
        arr = rng.integers(0, 255, size=(8, 8, 3)).astype("uint8")
        Image.fromarray(arr).save(d / "y.jpg")
    from bigdl_tpu.examples.imagenet import _list_image_folder
    train_items, classes, cmap = _list_image_folder(str(tmp_path / "train"))
    assert classes == 3 and len(train_items) == 3
    assert all(p.lower().endswith((".png", ".jpg")) for p, _ in train_items)
    val_items, _, _ = _list_image_folder(str(tmp_path / "val"), cmap)
    labels = {p.split("/")[-2]: l for p, l in val_items}
    assert labels == {"a": cmap["a"], "c": cmap["c"]}
    # a val class unknown to train fails loudly, not silently
    d = tmp_path / "val" / "zzz"
    d.mkdir()
    Image.fromarray(rng.integers(0, 255, size=(8, 8, 3)).astype("uint8")
                    ).save(d / "z.png")
    with pytest.raises(SystemExit):
        _list_image_folder(str(tmp_path / "val"), cmap)


def test_augment_preserves_aspect_ratio():
    """Eval recipe = short-side scale + center crop (not a distorting
    square resize): the scale stage must keep the image's geometry."""
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.examples.imagenet import _Augment
    from bigdl_tpu.transform.vision import ImageFeature
    img = np.zeros((300, 600, 3), np.float32)
    aug = _Augment(train=False, size=64)
    scaled = aug.stages[0](ImageFeature(img)).image
    # short side -> r = max(64*256//224, 64) = 73; ratio preserved
    assert scaled.shape[0] == 73
    assert abs(scaled.shape[1] - 146) <= 1
    # an extreme panorama must still yield a full-size crop (an
    # aspect cap that shrinks the short side would crash batching)
    pano = np.zeros((200, 3000, 3), np.float32)
    out = list(_Augment(train=False, size=224)([Sample(pano, 1)]))
    assert out[0].feature.shape == (224, 224, 3)
    # end-to-end shape on the normal image too
    out = list(aug([Sample(img, 1)]))
    assert out[0].feature.shape == (64, 64, 3)


def test_decode_augment_uses_per_thread_rngs():
    """RandomState is not thread-safe: each ParallelMap worker must get
    its own _Augment (own RandomCrop/RandomTransformer RNG streams)."""
    import threading
    from bigdl_tpu.examples.imagenet import _DecodeAugment
    da = _DecodeAugment(train=True, size=32)
    augs = {}

    def grab(name):
        augs[name] = da._aug()
        assert da._aug() is augs[name]  # cached within the thread

    threads = [threading.Thread(target=grab, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len({id(a) for a in augs.values()}) == 3
    rngs = [a.stages[1].rng for a in augs.values()]  # RandomCrop rng
    assert len({id(r) for r in rngs}) == 3


def test_treelstm_sexpr_parser():
    from bigdl_tpu.examples.treelstm_sentiment import parse_sexpr
    label, tokens, nodes = parse_sexpr(
        "(3 (2 It) (4 (2 's) (4 good)))")
    assert label == 3
    assert tokens == ["It", "'s", "good"]
    # post-order: leaf It, leaf 's, leaf good, ('s+good), root
    assert nodes == [(-1, -1, 0), (-1, -1, 1), (-1, -1, 2),
                     (1, 2, -1), (0, 3, -1)]


@pytest.mark.slow
def test_treelstm_main_synthetic():
    from bigdl_tpu.examples.treelstm_sentiment import main
    model = main(["--synthetic", "96", "-e", "1", "-q", "-b", "16",
                  "--embedding-dim", "16", "--hidden-size", "16",
                  "--max-nodes", "24", "--max-tokens", "16",
                  "--vocab-size", "100"])
    assert model is not None


@pytest.mark.slow
def test_treelstm_main_sst_files(tmp_path):
    from bigdl_tpu.examples.treelstm_sentiment import main
    lines = ["(3 (2 it) (4 (2 's) (4 good)))",
             "(1 (2 it) (0 (2 's) (0 bad)))",
             "(2 (2 a) (2 film))"] * 8
    (tmp_path / "train.txt").write_text("\n".join(lines))
    (tmp_path / "dev.txt").write_text("\n".join(lines[:6]))
    model = main(["-f", str(tmp_path), "-e", "1", "-q", "-b", "8",
                  "--embedding-dim", "8", "--hidden-size", "8",
                  "--max-nodes", "8", "--max-tokens", "8"])
    assert model is not None


@pytest.mark.slow
def test_ptb_main_transformer():
    from bigdl_tpu.examples.ptb_lm import main
    model = main(["--synthetic", "2000", "-e", "1", "-q", "-b", "8",
                  "--model", "transformer", "--remat",
                  "--hidden-size", "16", "--num-steps", "8",
                  "--num-heads", "2", "--vocab-size", "50"])
    assert model is not None


@pytest.mark.slow
def test_autoencoder_main_synthetic():
    """bigdl-tpu-autoencoder (reference models/autoencoder/Train.scala):
    reconstruction targets are the inputs; trains with MSE + Adagrad."""
    import numpy as np
    import jax.numpy as jnp
    from bigdl_tpu.examples.autoencoder import (
        main, synthetic_split, to_reconstruction_samples,
    )

    m = main(["--synthetic", "256", "-e", "5", "-b", "32", "-q"])
    assert m is not None
    # reconstruction must beat predicting the mean target; evaluate on
    # the SAME generation main() trained on (synthetic_mnist prototypes
    # depend on both seed and count — synthetic_split owns that math)
    train_s, _ = synthetic_split(256, 32)
    recon = to_reconstruction_samples(train_s[:64])
    x = np.stack([np.asarray(s.feature) for s in recon])
    t = np.stack([np.asarray(s.label) for s in recon])
    out = np.asarray(m.eval_mode().forward(jnp.asarray(x)))
    mse = float(((out - t) ** 2).mean())
    base = float(((t.mean() - t) ** 2).mean())
    assert mse < base, (mse, base)


def test_movielens_reader(tmp_path):
    """ratings.dat parsing (reference pyspark/bigdl/dataset/
    movielens.py:26-52): ml-1m layout and flat layout, id projections."""
    from bigdl_tpu.dataset.movielens import (
        get_id_pairs, get_id_ratings, read_data_sets,
    )
    rows = "1::31::5::978300019\n2::12::3::978300020\n1::7::4::978300021\n"
    d = tmp_path / "ml-1m"
    d.mkdir()
    (d / "ratings.dat").write_text(rows)
    data = read_data_sets(str(tmp_path))
    assert data.shape == (3, 4) and data[0, 1] == 31
    assert get_id_pairs(str(tmp_path)).shape == (3, 2)
    assert get_id_ratings(str(tmp_path))[1].tolist() == [2, 12, 3]
    with pytest.raises(FileNotFoundError):
        read_data_sets(str(tmp_path / "nowhere"))


def test_ncf_model_shapes_and_leave_one_out():
    """NeuralCF scores [B,2] training pairs and [B,1+neg,2] HitRatio
    rows with one forward; leave-one-out holds out exactly one item per
    user and samples negatives from the user's unseen items."""
    import jax.numpy as jnp
    from bigdl_tpu.dataset.movielens import synthetic_ratings
    from bigdl_tpu.examples.ncf import leave_one_out
    from bigdl_tpu.models.ncf import NeuralCF

    ratings = synthetic_ratings(n_users=12, n_items=20, per_user=5)
    pairs, labels, eval_rows = leave_one_out(ratings, neg_train=3,
                                             neg_eval=10)
    assert pairs.shape == (12 * 4 * (1 + 3), 2)
    assert labels.mean() == pytest.approx(0.25)
    assert eval_rows.shape == (12, 11, 2)
    for rows in eval_rows:
        u = rows[0, 0]
        seen = set(ratings[ratings[:, 0] == u][:, 1].tolist())
        assert int(rows[0, 1]) in seen           # held-out positive
        assert not (set(rows[1:, 1].tolist()) & seen)  # negatives unseen

    m = NeuralCF(12, 20, embed_dim=4).eval_mode()
    s1 = m.forward(jnp.asarray(pairs[:6]))
    s2 = m.forward(jnp.asarray(eval_rows[:3]))
    assert s1.shape == (6,) and s2.shape == (3, 11)
    assert float(s1.min()) >= 0.0 and float(s1.max()) <= 1.0


@pytest.mark.slow
def test_ncf_main_learns_above_chance():
    """bigdl-tpu-ncf end to end on the latent-structured synthetic set:
    HitRatio@10 over 40-row eval lists (chance = 0.25) must end well
    above chance after training."""
    from bigdl_tpu.examples.ncf import main

    m = main(["--synthetic", "640", "-e", "10", "-b", "32", "-r", "0.005",
              "--embed-dim", "8", "-q"])
    assert m is not None
    # the final validation score rides on the model's optimizer; re-run
    # evaluation directly for the assertion
    import numpy as np
    import jax.numpy as jnp
    from bigdl_tpu.dataset.movielens import synthetic_ratings
    from bigdl_tpu.examples.ncf import leave_one_out

    ratings = synthetic_ratings(n_users=80, n_items=40, per_user=8)
    _, _, eval_rows = leave_one_out(ratings, 4, 39)
    out = np.asarray(m.eval_mode().forward(jnp.asarray(eval_rows)))
    rank = (out > out[:, :1]).sum(axis=1) + 1
    hr = float((rank <= 10).mean())
    assert hr > 0.40, f"HitRatio@10 {hr} not above chance (0.25)"


@pytest.mark.slow
def test_perf_ptb_lstm_training():
    """bigdl-tpu-perf --model ptb-lstm: the BASELINE PTB-LSTM config's
    perf path (embedding -> stacked LSTM scan -> TimeDistributed
    decoder) through the Optimizer loop."""
    from bigdl_tpu.examples.perf import main
    out = main(["--model", "ptb-lstm", "-b", "8", "--seq-len", "8",
                "--vocab-size", "50", "--hidden-size", "16",
                "--num-layers", "2", "--iterations", "2",
                "--epochs", "3"], emit=False)
    assert out["records_per_sec"] > 0
    assert out["windows_timed"] >= 1


def test_perf_input_pipeline_synthetic():
    """HOST jpeg->batch throughput mode (VERDICT r03 weak #7: no
    input-pipeline number existed anywhere).  Small and unmarked: the
    only default-run coverage of train_pipeline/bench_input_pipeline."""
    pytest.importorskip("PIL")
    from bigdl_tpu.examples.perf import main
    out = main(["--input-pipeline", "synthetic", "--synthetic-images",
                "32", "-b", "8", "--workers", "4", "--image-size", "64"])
    assert out["input_pipeline_img_per_sec"] > 0
    assert out["images"] == 32


@pytest.mark.slow
def test_perf_real_jpeg_training():
    """--real-jpeg-train: REAL jpeg files through the production
    imagenet decode/augment pipeline feeding the live Optimizer loop
    (VERDICT r04 missing #4); the artifact carries the end-to-end step
    rate next to the host-only pipeline rate."""
    from bigdl_tpu.examples.perf import main
    out = main(["--model", "resnet50", "-b", "8", "--image-size", "64",
                "--real-jpeg-train", "32", "--workers", "2",
                "--epochs", "2", "--classes", "2"], emit=False)
    assert out["mode"] == "real-jpeg-train"
    assert out["records_per_sec"] > 0
    assert out["host_pipeline_img_per_sec"] > 0
    assert out["real_images"] == 32
