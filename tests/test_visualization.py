"""Tests for TensorBoard-compatible event files (reference
visualization/ + tensorboard/ writers/readers)."""

import os
import struct

import numpy as np
import pytest

from bigdl_tpu.visualization import (
    FileReader, FileWriter, TrainSummary, ValidationSummary,
    Event, ScalarValue, make_histogram,
)
from bigdl_tpu.visualization.crc32c import crc32c, masked_crc32c, \
    unmask_crc32c
from bigdl_tpu.visualization.proto import encode_event, decode_event


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43


def test_masked_crc_roundtrip():
    for payload in (b"", b"abc", b"x" * 1000):
        assert unmask_crc32c(masked_crc32c(payload)) == crc32c(payload)


def test_event_proto_roundtrip_scalar():
    ev = Event(wall_time=123.5, step=7,
               scalars=[ScalarValue("Loss", 0.25),
                        ScalarValue("Throughput", 1000.0)])
    dec = decode_event(encode_event(ev))
    assert dec.wall_time == 123.5
    assert dec.step == 7
    assert [(s.tag, s.value) for s in dec.scalars] == [
        ("Loss", 0.25), ("Throughput", 1000.0)]


def test_event_proto_roundtrip_histogram():
    vals = np.concatenate([np.linspace(-2, 2, 101), [0.0]])
    h = make_histogram(vals)
    ev = Event(step=3, histograms=[("weights", h)])
    dec = decode_event(encode_event(ev))
    tag, h2 = dec.histograms[0]
    assert tag == "weights"
    assert h2.num == vals.size
    assert h2.min == pytest.approx(-2.0)
    assert h2.max == pytest.approx(2.0)
    assert h2.sum == pytest.approx(vals.sum())
    assert sum(h2.bucket) == vals.size
    assert len(h2.bucket_limit) == len(h2.bucket)


def test_file_writer_reader_roundtrip(tmp_path):
    w = FileWriter(str(tmp_path))
    for i in range(5):
        w.add_event(Event(step=i, scalars=[ScalarValue("Loss", i * 0.5)]))
    w.close()
    r = FileReader(w.path)
    events = r.events()
    assert events[0].file_version == "brain.Event:2"
    assert r.scalars("Loss") == [(i, i * 0.5) for i in range(5)]


def test_record_framing_is_tfrecord(tmp_path):
    w = FileWriter(str(tmp_path))
    w.close()
    with open(w.path, "rb") as f:
        data = f.read()
    (length,) = struct.unpack("<Q", data[:8])
    (hcrc,) = struct.unpack("<I", data[8:12])
    assert hcrc == masked_crc32c(data[:8])
    payload = data[12:12 + length]
    (pcrc,) = struct.unpack("<I", data[12 + length:16 + length])
    assert pcrc == masked_crc32c(payload)


def test_train_summary_scalars_and_read_back(tmp_path):
    s = TrainSummary(str(tmp_path), "app1")
    s.add_scalar("Loss", 1.0, 1).add_scalar("Loss", 0.5, 2)
    s.add_scalar("Throughput", 100.0, 1)
    got = s.read_scalar("Loss")
    s.close()
    assert got == [(1, 1.0), (2, 0.5)]


def test_train_summary_parameter_trigger(tmp_path):
    from bigdl_tpu.optim import Trigger
    import bigdl_tpu.nn as nn
    s = TrainSummary(str(tmp_path), "app2")
    s.set_summary_trigger("Parameters", Trigger.several_iteration(1))
    model = nn.Sequential(nn.Linear(4, 2))  # nested: flat paths required
    s.save_parameters(model, 1)
    s.flush()
    d = os.path.join(str(tmp_path), "app2", "train")
    fname = os.path.join(d, sorted(os.listdir(d))[0])
    hists = {t for ev in FileReader(fname).events()
             for t, _ in ev.histograms}
    s.close()
    assert any("weight" in t for t in hists)
    assert any("bias" in t for t in hists)


def test_optimizer_writes_summaries(tmp_path):
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(4,)).astype(np.float32),
                      rng.normal(size=(2,)).astype(np.float32))
               for _ in range(16)]
    model = nn.Linear(4, 2)
    train_sum = TrainSummary(str(tmp_path), "opt")
    val_sum = ValidationSummary(str(tmp_path), "opt")
    from bigdl_tpu.optim.validation import Loss
    opt = (Optimizer(model, samples, nn.MSECriterion(), batch_size=8)
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(2))
           .set_train_summary(train_sum)
           .set_val_summary(val_sum)
           .set_validation(Trigger.every_epoch(), samples,
                           [Loss(nn.MSECriterion())], batch_size=8))
    opt.optimize()
    losses = train_sum.read_scalar("Loss")
    assert len(losses) == 4  # 2 epochs × 2 iterations
    val = val_sum.read_scalar("Loss")
    assert len(val) == 2
    train_sum.close()
    val_sum.close()


def test_optimizer_flushes_summaries_at_end(tmp_path):
    """Regression: the async FileWriter drains when optimize() returns,
    so scalars are READABLE immediately — without waiting for the
    writer thread's next flush cadence (short runs used to lose every
    scalar if the process exited first)."""
    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils import set_seed
    import glob

    set_seed(0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(1, 5, size=(32,)).astype(np.int32)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                      nn.LogSoftMax())
    summary = TrainSummary(str(tmp_path), "app")
    (Optimizer(m, DataSet.array(
        [MiniBatch(x[i:i + 16], y[i:i + 16]) for i in (0, 16)]),
        nn.ClassNLLCriterion())
     .set_optim_method(SGD(0.1))
     .set_end_when(Trigger.max_epoch(3))
     .set_train_summary(summary)
     .optimize())
    summary.close()
    f = glob.glob(str(tmp_path / "app" / "train" / "*tfevents*"))[0]
    rd = FileReader(f)
    tags = sorted({s.tag for ev in rd.events() for s in ev.scalars})
    assert "Loss" in tags and "Throughput" in tags, tags
    assert len(rd.scalars("Loss")) >= 2
