"""Vision transform pipeline + COCO/RLE segmentation tests.

Mirrors reference specs under transform/vision (BrightnessSpec,
ChannelNormalizeSpec, CropSpec, ExpandSpec, HFlipSpec, ResizeSpec, …)
and dataset/segmentation (COCODatasetSpec, MaskUtilsSpec).
"""

import json
import os

import numpy as np
import pytest

from bigdl_tpu.dataset.segmentation import (
    COCODataset, PolyMasks, RLEMasks, mask_area, merge_rles, poly_to_mask,
    rle_decode, rle_encode, rle_from_string, rle_to_string,
)
from bigdl_tpu.transform.vision import (
    AspectScale, Brightness, CenterCrop, ChannelNormalize, ChannelOrder,
    ColorJitter, Contrast, Expand, Filler, FixedCrop, HFlip, Hue,
    ImageFeature, ImageFrame, ImageFrameToSample, LocalImageFrame,
    MatToTensor, PixelNormalizer, RandomAlterAspect, RandomCrop,
    RandomCropper, RandomResize, RandomTransformer, Resize, RoiHFlip,
    RoiNormalize, RoiResize, Saturation,
)


def img(h=6, w=8, c=3, seed=0):
    return np.random.RandomState(seed).rand(h, w, c).astype(
        np.float32) * 255


def test_brightness_contrast_deterministic():
    rng = np.random.RandomState(0)
    f = ImageFeature(img())
    base = f.image.copy()
    out = Brightness(10, 10, rng=rng)(ImageFeature(base)).image
    np.testing.assert_allclose(out, base + 10, rtol=1e-6)
    out = Contrast(2, 2, rng=rng)(ImageFeature(base)).image
    np.testing.assert_allclose(out, base * 2, rtol=1e-6)


def test_channel_normalize_and_order():
    f = ImageFeature(img())
    base = f.image.copy()
    out = ChannelNormalize(1, 2, 3, 2, 2, 2)(ImageFeature(base)).image
    want = (base - np.array([1, 2, 3], np.float32)) / 2
    np.testing.assert_allclose(out, want, rtol=1e-6)
    out = ChannelOrder()(ImageFeature(base)).image
    np.testing.assert_allclose(out, base[:, :, ::-1])


def test_pixel_normalizer():
    base = img()
    means = np.ones_like(base) * 5
    out = PixelNormalizer(means)(ImageFeature(base)).image
    np.testing.assert_allclose(out, base - 5, rtol=1e-6)


def test_hue_saturation_roundtrip_range():
    base = img()
    out = Saturation(1.0, 1.0)(ImageFeature(base)).image
    # unit saturation change ≈ identity
    np.testing.assert_allclose(out, base, atol=1.0)
    out = Hue(0.0, 0.0)(ImageFeature(base)).image
    np.testing.assert_allclose(out, base, atol=1.0)


def test_crops_and_resize():
    base = img(10, 12)
    out = CenterCrop(6, 4)(ImageFeature(base)).image
    assert out.shape == (4, 6, 3)
    np.testing.assert_allclose(out, base[3:7, 3:9])
    out = RandomCrop(6, 4, rng=np.random.RandomState(0))(
        ImageFeature(base)).image
    assert out.shape == (4, 6, 3)
    out = FixedCrop(0.25, 0.0, 0.75, 1.0, normalized=True)(
        ImageFeature(base)).image
    assert out.shape == (10, 6, 3)
    out = Resize(5, 7)(ImageFeature(base)).image
    assert out.shape == (5, 7, 3)


def test_aspect_scale_records_scale():
    f = ImageFeature(img(10, 20))
    out = AspectScale(5, max_size=8)(f)
    # long side capped at 8: scale = 8/20
    assert out.image.shape[1] == 8
    sy, sx = out["scale"]
    assert sx == pytest.approx(8 / 20)


def test_expand_and_filler():
    base = img(4, 4)
    f = Expand(1, 2, 3, 2.0, 2.0, rng=np.random.RandomState(0))(
        ImageFeature(base))
    assert f.image.shape == (8, 8, 3)
    y0, x0 = f["expand_offset"]
    np.testing.assert_allclose(f.image[y0:y0 + 4, x0:x0 + 4], base)
    base2 = img(4, 4).copy()
    out = Filler(0.0, 0.0, 0.5, 0.5, value=9.0)(ImageFeature(base2)).image
    np.testing.assert_allclose(out[:2, :2], 9.0)


def test_hflip_and_random_transformer():
    base = img()
    out = HFlip()(ImageFeature(base)).image
    np.testing.assert_allclose(out, base[:, ::-1])
    rt = RandomTransformer(HFlip(), 0.0, rng=np.random.RandomState(0))
    np.testing.assert_allclose(rt(ImageFeature(base)).image, base)


def test_color_jitter_and_random_shapes():
    base = img()
    out = ColorJitter(rng=np.random.RandomState(1))(
        ImageFeature(base)).image
    assert out.shape == base.shape
    assert out.min() >= 0 and out.max() <= 255
    out = RandomResize(4, 6, rng=np.random.RandomState(2))(
        ImageFeature(base)).image
    assert 4 <= out.shape[0] <= 6 and out.shape[0] == out.shape[1]
    out = RandomAlterAspect(crop_length=5, rng=np.random.RandomState(3))(
        ImageFeature(base)).image
    assert out.shape == (5, 5, 3)
    out = RandomCropper(4, 4, rng=np.random.RandomState(4))(
        ImageFeature(base)).image
    assert out.shape == (4, 4, 3)


def test_roi_transforms():
    f = ImageFeature(img(10, 20))
    f[ImageFeature.bounding_box] = np.asarray(
        [[2.0, 1.0, 10.0, 9.0]], np.float32)
    f = RoiNormalize()(f)
    np.testing.assert_allclose(f[ImageFeature.bounding_box],
                               [[0.1, 0.1, 0.5, 0.9]], rtol=1e-6)
    f = RoiHFlip(normalized=True)(f)
    np.testing.assert_allclose(f[ImageFeature.bounding_box],
                               [[0.5, 0.1, 0.9, 0.9]], rtol=1e-6)
    f2 = ImageFeature(img(10, 20))
    f2[ImageFeature.bounding_box] = np.asarray(
        [[2.0, 1.0, 10.0, 9.0]], np.float32)
    f2["scale"] = (0.5, 2.0)
    f2 = RoiResize()(f2)
    np.testing.assert_allclose(f2[ImageFeature.bounding_box],
                               [[4.0, 0.5, 20.0, 4.5]], rtol=1e-6)


def test_image_frame_pipeline_to_samples():
    frame = ImageFrame.from_arrays([img(8, 8, seed=i) for i in range(3)],
                                   labels=[1.0, 2.0, 3.0])
    pipeline = Resize(4, 4) >> MatToTensor(scale=1 / 255.0)
    out = frame.transform(pipeline)
    samples = list(ImageFrameToSample()(iter(out.features)))
    assert len(samples) == 3
    assert samples[0].feature.shape == (4, 4, 3)
    assert samples[0].feature.max() <= 1.0
    assert samples[2].label == 3.0


# ---------------- RLE / COCO ----------------

def test_rle_roundtrip():
    rng = np.random.RandomState(0)
    mask = (rng.rand(13, 7) > 0.6).astype(np.uint8)
    counts = rle_encode(mask)
    back = rle_decode(counts, 13, 7)
    np.testing.assert_array_equal(back, mask)
    assert sum(counts) == mask.size


def test_rle_string_codec_pycoco_compat():
    # hand-checked vector: 3x3 mask with first column set
    mask = np.zeros((3, 3), np.uint8)
    mask[:, 0] = 1
    counts = rle_encode(mask)
    assert counts == [0, 3, 6]
    s = rle_to_string(counts)
    assert rle_from_string(s) == counts
    # negative-delta path
    counts2 = [10, 2, 3, 50, 1]
    assert rle_from_string(rle_to_string(counts2)) == counts2


def test_poly_to_mask_square():
    mask = poly_to_mask([[1, 1, 5, 1, 5, 5, 1, 5]], 8, 8)
    assert mask.shape == (8, 8)
    assert mask[3, 3] == 1 and mask[0, 0] == 0
    assert mask_area(mask) >= 16


def test_merge_rles():
    a = np.zeros((4, 4), np.uint8)
    a[0, :] = 1
    b = np.zeros((4, 4), np.uint8)
    b[3, :] = 1
    merged = merge_rles([rle_encode(a), rle_encode(b)], 4, 4)
    np.testing.assert_array_equal(rle_decode(merged, 4, 4), a | b)


def test_coco_dataset_load(tmp_path):
    ann = {
        "images": [
            {"id": 1, "file_name": "a.jpg", "height": 10, "width": 20},
            {"id": 2, "file_name": "b.jpg", "height": 8, "width": 8},
        ],
        "categories": [{"id": 7, "name": "cat"},
                       {"id": 3, "name": "dog"}],
        "annotations": [
            {"id": 100, "image_id": 1, "category_id": 7,
             "bbox": [2, 3, 4, 5], "area": 20, "iscrowd": 0,
             "segmentation": [[2, 3, 6, 3, 6, 8, 2, 8]]},
            {"id": 101, "image_id": 2, "category_id": 3,
             "bbox": [0, 0, 4, 4], "area": 16, "iscrowd": 1,
             "segmentation": {"size": [8, 8],
                              "counts": rle_to_string([0, 8, 56])}},
        ],
    }
    p = tmp_path / "ann.json"
    p.write_text(json.dumps(ann))
    ds = COCODataset.load(str(p), image_root="/imgs")
    assert len(ds.images) == 2
    assert ds.categories == {7: "cat", 3: "dog"}
    assert ds.cat_to_label == {3: 1, 7: 2}
    img1 = [i for i in ds.images if i.id == 1][0]
    assert img1.file_name == "/imgs/a.jpg"
    a = img1.annotations[0]
    assert a.bbox_xyxy() == (2, 3, 6, 8)
    assert isinstance(a.segmentation, PolyMasks)
    assert a.segmentation.to_mask().shape == (10, 20)
    img2 = [i for i in ds.images if i.id == 2][0]
    seg2 = img2.annotations[0].segmentation
    assert isinstance(seg2, RLEMasks)
    assert seg2.to_mask()[:, 0].sum() == 8
    recs = ds.to_detection_samples()
    assert len(recs) == 2
    fn, boxes, labels, crowd = recs[0]
    np.testing.assert_allclose(boxes, [[2, 3, 6, 8]])
    assert labels[0] == 2  # category 7 → contiguous label 2
