"""Request-reliability layer tests (serving/reliability.py wired
through serving/router.py): deadline propagation with stage-stamped
typed rejection, per-replica circuit breakers (failure AND staleness
channels, half-open probe recovery), bounded retry with the PR-2
backoff shape, hedged dispatch with first-completion-wins, and
mid-stream generation failover.

The load-bearing assertions: (a) a replica hard-killed mid-decode
loses NOTHING — the failed-over stream's final row is bit-identical to
an uninterrupted solo ``generate()`` and every streamed token is
delivered exactly once; (b) a flaked submit retries on a DIFFERENT
replica and the answer is still bit-identical; (c) the breaker opens
on submit failures in milliseconds — strictly inside the fleet
controller's ``dead_after_polls`` registry window; (d) a caller that
abandons a request frees its engine slot (no slot leak)."""

import ast
import os
import threading
import time

import numpy as np
import pytest

from bigdl_tpu.models import transformer_lm
from bigdl_tpu.serving import (
    CircuitBreaker, Deadline, DeadlineExceededError, HedgePolicy,
    ModelServer, ReliabilityPolicy, Replica, RetryPolicy, Router,
)
from bigdl_tpu.telemetry import events
from bigdl_tpu.utils import chaos, set_seed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_chaos():
    yield
    chaos.reset()


@pytest.fixture(scope="module")
def lm():
    set_seed(0)
    return transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                          num_heads=4, filter_size=64,
                          max_len=64).eval_mode()


def solo(model, prompt, max_new, eos_id=None):
    import jax.numpy as jnp
    return np.asarray(model.generate(
        jnp.asarray(prompt, jnp.int32)[None], int(max_new),
        eos_id=eos_id))[0]


def _replica(lm, rid, d, slots=2, interval=0.05, **server_kw):
    return Replica(rid, ModelServer(generator=lm, slots=slots,
                                    **server_kw),
                   snapshot_dir=d, publish_interval_s=interval)


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.perf_counter() + timeout
    while not cond():
        if time.perf_counter() > deadline:
            raise TimeoutError(f"{msg} not reached in {timeout}s")
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# deadlines (pure, injected time)
# ---------------------------------------------------------------------------

def test_deadline_expiry_against_injected_time():
    d = Deadline(0.5, now=100.0)
    assert not d.expired(now=100.4)
    assert d.remaining(now=100.4) == pytest.approx(0.1)
    assert d.expired(now=100.5)
    assert d.expired(now=101.0)
    err = d.error("decode", now=100.7)
    assert isinstance(err, DeadlineExceededError)
    assert err.stage == "decode"
    assert "decode" in str(err)


def test_deadline_rejects_nonpositive_budget():
    with pytest.raises(ValueError):
        Deadline(0.0)
    with pytest.raises(ValueError):
        Deadline(-1.0)


# ---------------------------------------------------------------------------
# retry / hedge policy (pure)
# ---------------------------------------------------------------------------

def test_retry_policy_pr2_backoff_shape():
    # jitter=0 makes the schedule exact: interval + backoff doubling,
    # capped — the set_failure_retry knob shape
    p = RetryPolicy(times=3, interval_s=0.1, backoff_s=0.05,
                    backoff_cap_s=0.15, jitter=0.0)
    assert p.delay_s(1) == pytest.approx(0.15)   # 0.1 + 0.05
    assert p.delay_s(2) == pytest.approx(0.20)   # 0.1 + 0.10
    assert p.delay_s(3) == pytest.approx(0.25)   # 0.1 + cap(0.20)=0.15
    assert p.delay_s(9) == pytest.approx(0.25)   # stays capped


def test_retry_policy_jitter_bounds_and_validation():
    p = RetryPolicy(times=2, interval_s=0.0, backoff_s=0.1,
                    backoff_cap_s=1.0, jitter=0.5, seed=7)
    for attempt in (1, 2, 3):
        base = min(0.1 * 2 ** (attempt - 1), 1.0)
        for _ in range(20):
            d = p.delay_s(attempt)
            assert base * 0.5 - 1e-9 <= d <= base * 1.5 + 1e-9
    with pytest.raises(ValueError):
        RetryPolicy(times=-1)


def test_hedge_policy_delay_derivation():
    assert HedgePolicy(after_s=0.25).delay_for(10.0) == 0.25
    h = HedgePolicy(p99_factor=2.0, floor_s=0.05)
    assert h.delay_for(0.3) == pytest.approx(0.6)
    # a cold replica (p99==0) must not hedge instantly
    assert h.delay_for(0.0) == pytest.approx(0.05)


# ---------------------------------------------------------------------------
# circuit breaker (pure, injected time)
# ---------------------------------------------------------------------------

def test_breaker_opens_on_consecutive_failures_and_probes_back():
    cb = CircuitBreaker(failure_threshold=3, open_s=1.0,
                        probe_budget=1)
    cb.record_failure(0, "submit", now=0.0)
    cb.record_failure(0, "submit", now=0.1)
    assert cb.state(0) == "closed" and cb.routable(0, now=0.2)
    cb.record_failure(0, "submit", now=0.2)
    assert cb.state(0) == "open"
    assert not cb.routable(0, now=0.5)
    # open_s elapsed: the first routing decision flips to half-open
    assert cb.routable(0, now=1.3)
    assert cb.state(0) == "half_open"
    cb.on_dispatch(0)               # the probe is in flight
    assert not cb.routable(0, now=1.4)  # budget spent: hold the rest
    cb.record_success(0, now=1.5)
    assert cb.state(0) == "closed"
    assert cb.routable(0, now=1.6)
    tc = cb.transition_counts()
    assert tc.get("open") == 1 and tc.get("half_open") == 1 \
        and tc.get("closed") == 1


def test_breaker_success_resets_failure_streak():
    cb = CircuitBreaker(failure_threshold=3)
    cb.record_failure(1, now=0.0)
    cb.record_failure(1, now=0.1)
    cb.record_success(1, now=0.2)
    cb.record_failure(1, now=0.3)
    cb.record_failure(1, now=0.4)
    assert cb.state(1) == "closed"  # CONSECUTIVE failures, not total


def test_breaker_half_open_probe_failure_reopens():
    cb = CircuitBreaker(failure_threshold=1, open_s=0.5)
    cb.record_failure(0, now=0.0)
    assert cb.state(0) == "open"
    assert cb.routable(0, now=1.0)          # half-open
    cb.on_dispatch(0)
    cb.record_failure(0, "probe", now=1.1)
    assert cb.state(0) == "open"
    assert not cb.routable(0, now=1.2)      # new open_s window

def test_breaker_staleness_channel_and_healthy_retraction():
    cb = CircuitBreaker(failure_threshold=3, stale_threshold=2,
                        open_s=60.0)
    cb.note_unhealthy(0, now=0.0)
    assert cb.state(0) == "closed"
    cb.note_unhealthy(0, now=0.1)
    assert cb.state(0) == "open"
    # the health plane retracting its own verdict needs no probe
    cb.note_healthy(0, now=0.2)
    assert cb.state(0) == "closed" and cb.routable(0, now=0.3)
    # but a FAILURE-opened breaker is not closed by healthy snapshots:
    # a replica can publish healthy while flaking every submit
    for i in range(3):
        cb.record_failure(0, now=0.4 + i * 0.01)
    assert cb.state(0) == "open"
    cb.note_healthy(0, now=0.5)
    assert cb.state(0) == "open"


def test_breaker_forget_and_snapshot():
    cb = CircuitBreaker(failure_threshold=1)
    cb.record_failure(3, now=0.0)
    assert cb.open_count() == 1
    snap = cb.snapshot()
    assert snap[3]["state"] == "open" and snap[3]["failures"] == 1
    cb.forget(3)
    assert cb.open_count() == 0 and cb.state(3) == "closed"


def test_breaker_transitions_land_in_flight_recorder():
    events.reset_events()
    cb = CircuitBreaker(failure_threshold=1, open_s=0.1)
    cb.record_failure(7, "submit", now=0.0)
    assert cb.routable(7, now=1.0)
    cb.on_dispatch(7)
    cb.record_success(7, now=1.1)
    recs = [e for e in events.recent_events()
            if e["kind"] == "breaker_transition"]
    assert [r["to"] for r in recs] == ["open", "half_open", "closed"]
    assert all(r["replica"] == 7 for r in recs)


def test_breaker_opens_inside_controller_dead_window():
    """The breaker's whole point: it must fire BEFORE the fleet
    controller's dead-replica sweep.  Submit failures open it at
    failure_threshold dispatches (milliseconds); staleness opens it at
    stale_threshold registry polls — structurally <= the controller's
    dead_after_polls default, so the router stops routing to a corpse
    while the controller is still confirming the death."""
    from bigdl_tpu.fleet.policy import PoolSpec
    pol = ReliabilityPolicy()
    assert pol.stale_threshold <= PoolSpec().dead_after_polls


def test_reliability_policy_budget_per_model():
    pol = ReliabilityPolicy(deadline_budget_s=2.0,
                            deadline_budgets={"fast": 0.5})
    assert pol.budget_for("fast") == 0.5
    assert pol.budget_for("default") == 2.0
    assert ReliabilityPolicy().budget_for("default") is None


# ---------------------------------------------------------------------------
# emission-site discipline (AST)
# ---------------------------------------------------------------------------

def _record_event_literals():
    sites = {}
    for root, _dirs, files in os.walk(os.path.join(REPO, "bigdl_tpu")):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            if "record_event" not in src:
                continue
            for node in ast.walk(ast.parse(src)):
                if isinstance(node, ast.Call) \
                        and getattr(node.func, "attr",
                                    getattr(node.func, "id", None)) \
                        == "record_event" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    sites.setdefault(node.args[0].value, []).append(
                        os.path.relpath(path, REPO))
    return sites


def test_reliability_kinds_have_exactly_one_emission_site():
    sites = _record_event_literals()
    for kind in ("request_retry", "request_hedge",
                 "breaker_transition", "generation_failover"):
        assert kind in events.EVENT_KINDS
        assert len(sites.get(kind, [])) == 1, \
            f"{kind} must have exactly one emission site, " \
            f"got {sites.get(kind)}"


# ---------------------------------------------------------------------------
# integration: retries, breakers, deadlines through the fabric
# ---------------------------------------------------------------------------

def test_flaky_submit_retries_on_other_replica(lm, tmp_path):
    """chaos.flaky_submit_p on replica 0: the transport error never
    reaches the engine, the retry lands on replica 1, the answer is
    bit-identical, and the campaign records ONE chaos event."""
    d = str(tmp_path)
    events.reset_events()
    chaos.install(flaky_submit_p=1.0, flaky_replica_id=0)
    prompt = np.array([5, 9, 2, 7], np.int32)
    rel = ReliabilityPolicy(
        retry=RetryPolicy(times=3, backoff_s=0.01, backoff_cap_s=0.05,
                          jitter=0.0))
    with Router([_replica(lm, 0, d), _replica(lm, 1, d)],
                snapshot_dir=d, registry_max_age_s=5.0, shed_after_s=20.0,
                reliability=rel) as router:
        _wait(lambda: sum(
            1 for r in router.records().values() if r["healthy"]) == 2,
            msg="both replicas healthy")
        out = router.submit_generate(prompt, 8, timeout=60.0)
        np.testing.assert_array_equal(out, solo(lm, prompt, 8))
        st = router.stats()
        assert st["retries"] >= 1
        assert st["outcomes"].get("ok", 0) == 1
    kinds = events.event_counts()
    assert kinds.get("request_retry", 0) >= 1
    ctl = chaos.active()
    assert ctl.flaked_submits >= 1
    assert sum("flaking submits" in e for e in ctl.events) == 1


def test_flaky_submit_opens_breaker_then_half_open_recovery(lm, tmp_path):
    """A single-replica fabric whose submits flake exactly twice:
    failure_threshold=2 opens the breaker (traffic holds), open_s
    later the half-open probe goes through (the flake budget is
    spent), succeeds, and closes the breaker — the full state-machine
    loop against real dispatch."""
    d = str(tmp_path)
    chaos.install(flaky_submit_p=1.0, flaky_replica_id=0,
                  flaky_submit_count=2)
    prompt = np.array([3, 1, 4], np.int32)
    rel = ReliabilityPolicy(
        retry=RetryPolicy(times=6, backoff_s=0.01, backoff_cap_s=0.05,
                          jitter=0.0),
        failure_threshold=2, open_s=0.3)
    with Router([_replica(lm, 0, d)], snapshot_dir=d, registry_max_age_s=5.0,
                shed_after_s=30.0, reliability=rel) as router:
        _wait(lambda: any(
            r["healthy"] for r in router.records().values()),
            msg="replica healthy")
        out = router.submit_generate(prompt, 6, timeout=60.0)
        np.testing.assert_array_equal(out, solo(lm, prompt, 6))
        st = router.stats()
        assert st["retries"] >= 2
        tc = st["breaker_transitions"]
        assert tc.get("open", 0) >= 1, tc
        assert tc.get("half_open", 0) >= 1, tc
        assert tc.get("closed", 0) >= 1, tc
        assert st["breakers"][0]["state"] == "closed"
        assert st["breakers_open"] == 0


def test_deadline_expires_in_queue_typed_and_staged(lm, tmp_path):
    """No routable replica + a 50ms budget: the request is rejected
    with the stage-stamped typed error, not a generic shed and not a
    hang."""
    d = str(tmp_path)
    with Router([], snapshot_dir=d, shed_after_s=30.0) as router:
        fut = router.submit_generate_async(
            np.array([1, 2, 3], np.int32), 4, deadline_s=0.05)
        with pytest.raises(DeadlineExceededError) as ei:
            fut.result(timeout=10.0)
        assert ei.value.stage == "queue"
        st = router.stats()
        assert st["shed_reasons"].get("deadline", 0) == 1
        assert st["outcomes"].get("shed", 0) == 1


def test_deadline_expires_mid_generation_and_frees_slot(lm):
    """A budget that expires after decode begins: the engine sweep
    evicts the request with stage prefill/decode (not queue) and the
    slot is reusable immediately after."""
    server = ModelServer(generator=lm, slots=1)
    try:
        prompt = np.array([2, 4, 6, 8], np.int32)
        started = threading.Event()

        def slow_stream(_tok):
            # pace the decode loop so the 0.25s budget reliably dies
            # mid-decode instead of racing a fast machine to the end
            started.set()
            time.sleep(0.05)

        fut = server.submit_generate_async(
            prompt, 50, on_token=slow_stream, deadline=Deadline(0.25))
        started.wait(20.0)
        with pytest.raises(DeadlineExceededError) as ei:
            fut.result(timeout=30.0)
        assert ei.value.stage in ("prefill", "decode")
        # the evicted request's slot must be free for the next one
        out = server.submit_generate(prompt, 6, timeout=30.0)
        np.testing.assert_array_equal(out, solo(lm, prompt, 6))
    finally:
        server.shutdown(drain=False, timeout=10.0)


def test_abandoned_request_frees_slot(lm):
    """The slot-leak regression: a caller whose submit_generate times
    out walks away — the timeout must propagate into an engine cancel
    so the slot frees within a few iterations, instead of decoding to
    completion for nobody.

    A filler stream paces the engine loop at >=50ms per iteration (its
    on_token sleeps on the engine thread), so the abandoned 50-token
    victim would hold its slot >=2.5s if leaked.  With slots=2 (filler
    + victim own both), a third request admits quickly ONLY if the
    victim's slot actually freed — the timing assertion detects the
    leak with seconds of margin."""
    from concurrent.futures import TimeoutError as FuturesTimeout
    server = ModelServer(generator=lm, slots=2)
    try:
        filler_started = threading.Event()

        def pace(_tok):
            filler_started.set()
            time.sleep(0.05)

        filler = server.submit_generate_async(
            np.array([9, 9, 9], np.int32), 60, on_token=pace)
        assert filler_started.wait(30.0)
        prompt = np.array([7, 3, 1, 9], np.int32)
        with pytest.raises(FuturesTimeout):
            server.submit_generate(prompt, 50, timeout=0.3)
        t0 = time.perf_counter()
        out = server.submit_generate(prompt, 2, timeout=30.0)
        elapsed = time.perf_counter() - t0
        np.testing.assert_array_equal(out, solo(lm, prompt, 2))
        # leaked: the victim keeps its slot for the remaining ~45 paced
        # iterations (>2s) and the third request queues behind it
        assert elapsed < 1.5, \
            f"slot not reused promptly ({elapsed:.2f}s): leak"
        server.cancel_generate(filler)
    finally:
        server.shutdown(drain=False, timeout=10.0)


def test_router_client_timeout_cancels_through_fabric(lm, tmp_path):
    """Router.submit_generate(timeout=...) abandonment reaches the
    engine: the inner request is cancelled (slot freed), and the
    fabric still serves the next request promptly."""
    from concurrent.futures import TimeoutError as FuturesTimeout
    d = str(tmp_path)
    prompt = np.array([1, 5, 9], np.int32)
    with Router([_replica(lm, 0, d, slots=1)], snapshot_dir=d, registry_max_age_s=5.0,
                shed_after_s=20.0) as router:
        _wait(lambda: any(
            r["healthy"] for r in router.records().values()),
            msg="replica healthy")
        with pytest.raises(FuturesTimeout):
            router.submit_generate(prompt, 50, timeout=0.05)
        out = router.submit_generate(prompt, 5, timeout=60.0)
        np.testing.assert_array_equal(out, solo(lm, prompt, 5))


# ---------------------------------------------------------------------------
# integration: mid-stream failover + hedging
# ---------------------------------------------------------------------------

def test_midstream_failover_bit_identical(lm, tmp_path):
    """THE failover contract: a replica hard-killed mid-decode loses
    nothing — the router replays prompt+emitted onto the survivor, the
    final row is bit-identical to an uninterrupted solo generate, and
    the streamed tokens arrive exactly once each."""
    d = str(tmp_path)
    events.reset_events()
    prompt = np.array([4, 8, 15, 16, 23], np.int32)
    max_new = 20
    expect = solo(lm, prompt, max_new)
    got = []
    seen3 = threading.Event()

    def on_token(t):
        got.append(int(t))
        if len(got) >= 3:
            seen3.set()

    rel = ReliabilityPolicy(
        retry=RetryPolicy(times=2, backoff_s=0.01, backoff_cap_s=0.05,
                          jitter=0.0))
    with Router([_replica(lm, 0, d), _replica(lm, 1, d)],
                snapshot_dir=d, registry_max_age_s=5.0, shed_after_s=30.0,
                reliability=rel) as router:
        _wait(lambda: sum(
            1 for r in router.records().values() if r["healthy"]) == 2,
            msg="both replicas healthy")
        fut = router.submit_generate_async(prompt, max_new,
                                           on_token=on_token)
        assert seen3.wait(60.0), "stream never started"
        # find where it landed and kill that replica HARD (no drain:
        # slot-resident requests fail typed)
        inflight = router.stats()["inflight"]
        primary = next(rid for rid, n in inflight.items() if n > 0)
        router.replica(primary).kill()
        row = fut.result(timeout=120.0)
        np.testing.assert_array_equal(row, expect)
        st = router.stats()
        assert st["failovers"] >= 1
        assert st["outcomes"].get("ok", 0) == 1
    # the stitched stream: every generated token exactly once, in order
    assert got == list(expect[len(prompt):])
    assert events.event_counts().get("generation_failover", 0) >= 1


def test_hedged_dispatch_first_completion_wins(lm, tmp_path):
    """Primary lands on a replica whose slots are wedged behind long
    decodes; after the hedge delay the twin goes to the idle replica
    and the first completion resolves the caller — bit-identical
    either way, exactly one hedge counted."""
    d = str(tmp_path)
    events.reset_events()
    srv0 = ModelServer(generator=lm, slots=2)
    r0 = Replica(0, srv0, snapshot_dir=d, publish_interval_s=0.05)
    r1 = _replica(lm, 1, d)
    prompt = np.array([6, 2, 9], np.int32)
    rel = ReliabilityPolicy(
        retry=RetryPolicy(times=2, backoff_s=0.01, jitter=0.0),
        hedge=HedgePolicy(enabled=True, after_s=0.1))
    with Router([r0, r1], snapshot_dir=d, registry_max_age_s=5.0, shed_after_s=30.0,
                reliability=rel) as router:
        _wait(lambda: sum(
            1 for r in router.records().values() if r["healthy"]) == 2,
            msg="both replicas healthy")
        # pick a session whose ring home is replica 0, then wedge 0
        session = next(s for s in (f"s{i}" for i in range(64))
                       if router._ring.preference(s)[0] == 0)
        fillers = [srv0.submit_generate_async(
            np.array([1, 1, 1, i], np.int32), 45) for i in range(2)]
        fut = router.submit_generate_async(prompt, 8, session=session)
        row = fut.result(timeout=120.0)
        np.testing.assert_array_equal(row, solo(lm, prompt, 8))
        _wait(lambda: router.stats()["hedges"] >= 1, timeout=60.0,
              msg="hedge resolution")
        st = router.stats()
        assert st["hedges"] == 1
        for f in fillers:
            f.result(timeout=120.0)
    recs = [e for e in events.recent_events()
            if e["kind"] == "request_hedge"]
    assert len(recs) == 1
    assert recs[0]["outcome"] in ("primary_won", "hedge_won")


def test_slow_replica_chaos_fires_one_event(lm, tmp_path):
    """chaos.slow_replica_s stalls every submit by the given delay and
    records ONE flight-recorder event for the whole campaign."""
    d = str(tmp_path)
    chaos.install(slow_replica_s=0.05)
    prompt = np.array([2, 7], np.int32)
    with Router([_replica(lm, 0, d)], snapshot_dir=d, registry_max_age_s=5.0,
                shed_after_s=20.0) as router:
        _wait(lambda: any(
            r["healthy"] for r in router.records().values()),
            msg="replica healthy")
        for _ in range(3):
            out = router.submit_generate(prompt, 4, timeout=60.0)
            np.testing.assert_array_equal(out, solo(lm, prompt, 4))
    ctl = chaos.active()
    assert ctl.slowed_submits >= 3
    assert sum("slowing submits" in e for e in ctl.events) == 1


def test_chaos_env_seams_for_reliability_faults(monkeypatch):
    """The BIGDL_TPU_CHAOS_* env seams parse value[:replica] for the
    new faults."""
    monkeypatch.setenv("BIGDL_TPU_CHAOS_SLOW_REPLICA", "0.25:3")
    monkeypatch.setenv("BIGDL_TPU_CHAOS_FLAKY_SUBMIT", "0.5")
    monkeypatch.setenv("BIGDL_TPU_CHAOS_FLAKY_SUBMIT_COUNT", "4")
    chaos.reset()
    ctl = chaos._from_env()
    assert ctl is not None
    assert ctl.slow_replica_s == 0.25 and ctl.slow_replica_id == 3
    assert ctl.flaky_submit_p == 0.5 and ctl.flaky_replica_id is None
    assert ctl.flaky_submit_count == 4


# ---------------------------------------------------------------------------
# slow: chaos soak
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_no_admitted_request_lost(lm, tmp_path):
    """Sustained load over a 3-replica fabric while chaos flakes
    submits and a replica is hard-killed mid-stream: every admitted
    request resolves (bit-identical for the streaming cohort), zero
    admitted-request failures, and the breaker's verdicts land in the
    flight recorder."""
    d = str(tmp_path)
    events.reset_events()
    chaos.install(flaky_submit_p=0.2, flaky_submit_count=8, seed=3)
    rel = ReliabilityPolicy(
        retry=RetryPolicy(times=5, backoff_s=0.01, backoff_cap_s=0.1,
                          jitter=0.0),
        failure_threshold=3, open_s=0.3)
    prompts = [np.array([1 + i, 2 + i, 3 + i], np.int32)
               for i in range(12)]
    budgets = [6 + (i % 5) for i in range(12)]
    expected = [solo(lm, p, m) for p, m in zip(prompts, budgets)]
    streams = {i: [] for i in range(12)}
    with Router([_replica(lm, r, d, slots=2) for r in range(3)],
                snapshot_dir=d, registry_max_age_s=5.0, shed_after_s=60.0,
                reliability=rel) as router:
        _wait(lambda: sum(
            1 for r in router.records().values() if r["healthy"]) == 3,
            msg="all replicas healthy")
        futs = []
        for i, (p, m) in enumerate(zip(prompts, budgets)):
            cb = ((lambda t, i=i: streams[i].append(int(t)))
                  if i % 2 == 0 else None)
            futs.append(router.submit_generate_async(p, m, on_token=cb))
        # once some streams are moving, hard-kill a busy replica
        _wait(lambda: any(len(s) >= 2 for s in streams.values()),
              timeout=120.0, msg="streams started")
        inflight = router.stats()["inflight"]
        victim = max(inflight, key=lambda r: inflight[r])
        router.replica(victim).kill()
        rows = [f.result(timeout=300.0) for f in futs]
        for row, exp in zip(rows, expected):
            np.testing.assert_array_equal(row, exp)
        st = router.stats()
        assert st["outcomes"].get("ok", 0) == 12
        assert st["outcomes"].get("failed", 0) == 0
    for i, (p, m, exp) in enumerate(zip(prompts, budgets, expected)):
        if i % 2 == 0:
            assert streams[i] == list(exp[len(p):]), f"stream {i}"
    counts = events.event_counts()
    assert counts.get("request_retry", 0) + \
        counts.get("generation_failover", 0) >= 1
