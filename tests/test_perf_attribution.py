"""Perf-attribution layer (telemetry.perf): step-time decomposition
(phases + residual summing to wall), MFU/roofline accounting, the
RoundArtifact durable-evidence schema (confirmed vs carried-forward,
chip-session promotion), the xla_cost cost_breakdown satellite, and the
optimizer's window-record capture end-to-end — including the
stalled-pipeline chaos run attributing the gap to data-wait.
"""

import json
import os
import time

import numpy as np
import pytest

from bigdl_tpu import nn, telemetry
from bigdl_tpu.telemetry import families, perf
from bigdl_tpu.utils.xla_cost import (
    compiled_bytes, compiled_flops, cost_breakdown,
)


@pytest.fixture(autouse=True)
def _telemetry_clean():
    """Leave the process in the repo-wide default (disabled, zeroed)."""
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


def _rec(iters=1, wall=1.0, fetch=0.1, stage=0.2, block=0.5, rb=0.1,
         sync=True):
    return {"iterations": iters, "wall_s": wall, "data_wait_s": fetch,
            "host_staging_s": stage, "device_compute_s": block,
            "readback_s": rb, "t_ready": 0.0, "sync": sync}


# --------------------------------------------------------------------------
# attribution math on synthetic streams with known phase durations
# --------------------------------------------------------------------------

class TestAttributionMath:
    def test_decomposition_sums_to_wall(self):
        # compile window (skipped) + 4 steady windows of known phases
        recs = [_rec(wall=9.0)] + [_rec() for _ in range(4)]
        rep = perf.attribute_windows(recs)
        assert rep["windows"] == 4 and rep["iterations"] == 4
        assert not rep["includes_compile_window"]
        assert rep["wall_step_s"] == pytest.approx(1.0)
        # phases land exactly where the synthetic stream put them
        assert rep["phases_s"]["data_wait"] == pytest.approx(0.1)
        assert rep["phases_s"]["host_staging"] == pytest.approx(0.2)
        assert rep["phases_s"]["device_compute"] == pytest.approx(0.5)
        assert rep["phases_s"]["readback"] == pytest.approx(0.1)
        # the residual is explicit, non-negative, and closes the sum
        assert rep["residual_s"] == pytest.approx(0.1)
        assert rep["residual_s"] >= 0.0
        total = (sum(rep["phases_s"].values()) + rep["residual_s"]
                 - rep["overlap_s"])
        assert total == pytest.approx(rep["wall_step_s"], rel=1e-9)
        assert rep["dominant_phase"] == "device_compute"
        assert rep["unattributed_fraction"] == pytest.approx(0.1)

    def test_multi_iteration_windows_amortize(self):
        # 2 windows x 5 iterations: per-step values divide by 10
        recs = [_rec()] + [_rec(iters=5, wall=5.0, fetch=1.0, stage=0.5,
                                block=3.0, rb=0.25) for _ in range(2)]
        rep = perf.attribute_windows(recs)
        assert rep["iterations"] == 10
        assert rep["wall_step_s"] == pytest.approx(1.0)
        assert rep["phases_s"]["data_wait"] == pytest.approx(0.2)
        assert rep["phases_s"]["device_compute"] == pytest.approx(0.6)
        assert rep["residual_s"] == pytest.approx(0.05)

    def test_overlap_is_reported_not_rescaled(self):
        # async drain: measured phases over-sum the completion-to-
        # completion wall — residual clamps at 0, the excess is named
        recs = [_rec()] + [_rec(wall=1.0, fetch=0.5, stage=0.5,
                                block=0.4, rb=0.1, sync=False)]
        rep = perf.attribute_windows(recs)
        assert rep["residual_s"] == 0.0
        assert rep["overlap_s"] == pytest.approx(0.5)
        total = (sum(rep["phases_s"].values()) + rep["residual_s"]
                 - rep["overlap_s"])
        assert total == pytest.approx(rep["wall_step_s"], rel=1e-9)

    def test_empty_and_compile_only_streams(self):
        assert perf.attribute_windows([]) is None
        assert perf.attribute_windows(None) is None
        # one window: nothing steady to skip into — used whole, flagged
        rep = perf.attribute_windows([_rec()])
        assert rep["includes_compile_window"]
        assert rep["windows"] == 1

    def test_negative_clock_skew_clamped(self):
        recs = [_rec()] + [_rec(fetch=-0.5)]
        rep = perf.attribute_windows(recs)
        assert rep["phases_s"]["data_wait"] == 0.0
        assert rep["residual_s"] >= 0.0

    def test_fractions_sum_to_one_minus_overlap(self):
        recs = [_rec()] + [_rec() for _ in range(3)]
        rep = perf.attribute_windows(recs)
        assert sum(rep["fractions"].values()) == pytest.approx(1.0)

    def test_dominant_residual_when_unattributed_dwarfs_phases(self):
        # the pre-fix XLA:CPU regime: phases are slivers, residual is
        # the story — the diagnosis must say so, not name a sliver
        recs = [_rec()] + [_rec(wall=1.0, fetch=0.01, stage=0.02,
                                block=0.03, rb=0.01) for _ in range(2)]
        rep = perf.attribute_windows(recs)
        assert rep["dominant_phase"] == "residual"
        assert rep["unattributed_fraction"] == pytest.approx(0.93)

    def test_accepts_deque_input(self):
        from collections import deque
        recs = deque([_rec(), _rec(), _rec()], maxlen=8)
        rep = perf.attribute_windows(recs)
        assert rep["windows"] == 2  # compile window skipped


class TestRoofline:
    def test_hbm_bound_verdict(self):
        # 1 TFLOP over 10 GB on a 100 TF/s / 100 GB/s device:
        # compute floor 0.01 s, memory floor 0.1 s -> HBM bound
        v = perf.roofline_verdict(1e12, 10e9, 100e12, 100e9)
        assert v["verdict"] == "hbm_bound"
        assert v["min_compute_s"] == pytest.approx(0.01)
        assert v["min_hbm_s"] == pytest.approx(0.1)
        assert v["attainable_step_s"] == pytest.approx(0.1)
        assert v["arithmetic_intensity_flops_per_byte"] == pytest.approx(100)
        assert v["machine_balance_flops_per_byte"] == pytest.approx(1000)

    def test_compute_bound_verdict(self):
        # compute floor 10 s dwarfs the 0.01 s memory floor
        v = perf.roofline_verdict(1e15, 1e9, 100e12, 100e9)
        assert v["verdict"] == "compute_bound"
        assert v["attainable_step_s"] == pytest.approx(10.0)

    def test_partial_inputs(self):
        assert perf.roofline_verdict(None, None, 1e12, 1e9) is None
        v = perf.roofline_verdict(1e12, None, 100e12, 100e9)
        assert v["verdict"] is None  # one floor only: no comparison
        assert v["attainable_step_s"] == pytest.approx(0.01)

    def test_device_capability_tables(self):
        assert perf.device_peak_flops("TPU v5 lite") == pytest.approx(
            197e12)
        assert perf.device_peak_flops("TPU v4") == pytest.approx(275e12)
        assert perf.device_peak_flops("cpu") is None
        assert perf.device_peak_flops(None) is None
        assert perf.device_hbm_bytes_per_s("TPU v5 lite") == \
            pytest.approx(819e9)
        assert perf.device_hbm_bytes_per_s("weird-chip") is None


class TestAttributionReport:
    def test_mfu_overall_vs_device(self):
        # wall 1.0 s/step with 0.5 s device-compute; 50 TFLOP/step on a
        # 100 TF/s spec part: overall MFU 0.5, device-busy MFU 1.0
        recs = [_rec()] + [_rec() for _ in range(2)]
        rep = perf.attribution_report(
            recs, flops_per_step=50e12, bytes_per_step=100e9,
            peak_spec_flops=100e12, peak_measured_flops=80e12,
            hbm_bytes_per_s=100e9)
        assert rep["mfu"]["vs_spec"] == pytest.approx(0.5)
        assert rep["mfu"]["device_vs_spec"] == pytest.approx(1.0)
        assert rep["mfu"]["vs_measured"] == pytest.approx(50 / 80)
        # memory floor 1.0 s vs compute floor 0.625 s (vs the measured
        # peak): HBM bound
        assert rep["roofline"]["verdict"] == "hbm_bound"
        assert rep["flops_per_step"] == 50e12

    def test_peaks_default_from_device_kind(self):
        recs = [_rec(), _rec()]
        rep = perf.attribution_report(
            recs, flops_per_step=197e12, bytes_per_step=819e9,
            device_kind="TPU v5 lite")
        assert rep["mfu"]["vs_spec"] == pytest.approx(1.0)
        # bytes floor == compute floor here is 1s vs 1s -> compute wins
        # the tie (strictly-greater test), so just assert a verdict
        assert rep["roofline"]["verdict"] in ("hbm_bound",
                                              "compute_bound")
        assert rep["device_kind"] == "TPU v5 lite"

    def test_report_publishes_mfu_gauge_only(self):
        telemetry.enable()
        telemetry.reset()
        recs = [_rec(), _rec()]
        rep = perf.attribution_report(
            recs, flops_per_step=40e12, peak_measured_flops=80e12)
        assert rep["mfu"]["vs_measured"] == pytest.approx(0.5)
        assert families.step_mfu_vs_measured().value() == \
            pytest.approx(0.5)
        # the residual gauge has exactly ONE writer (the drain worker,
        # per window) — a report must not overwrite it with the run
        # aggregate, or a scrape's value depends on who ran last
        assert families.step_unattributed_fraction().value() == 0.0

    def test_report_without_cost_model(self):
        rep = perf.attribution_report([_rec(), _rec()])
        assert "mfu" not in rep and "roofline" not in rep
        assert rep["residual_s"] >= 0.0


# --------------------------------------------------------------------------
# xla_cost.cost_breakdown: missing-key vs legitimate-zero, one pass
# --------------------------------------------------------------------------

class _FakeCompiled:
    def __init__(self, analysis, wrap_list=False, raise_=False):
        self.analysis = analysis
        self.wrap_list = wrap_list
        self.raise_ = raise_
        self.calls = 0

    def cost_analysis(self):
        self.calls += 1
        if self.raise_:
            raise RuntimeError("no analysis on this backend")
        return [self.analysis] if self.wrap_list else self.analysis


class TestCostBreakdown:
    def test_all_present(self):
        c = _FakeCompiled({"flops": 100.0, "bytes accessed": 50.0,
                           "transcendentals": 7.0})
        # comm_bytes: None — the fake has no HLO text to read
        assert cost_breakdown(c) == {"flops": 100.0, "bytes": 50.0,
                                     "transcendentals": 7.0,
                                     "comm_bytes": None}

    def test_zero_is_legitimate_not_missing(self):
        c = _FakeCompiled({"flops": 0.0, "bytes accessed": 0,
                           "transcendentals": 0.0})
        out = cost_breakdown(c)
        assert out["flops"] == 0.0 and out["flops"] is not None
        assert out["bytes"] == 0.0
        assert out["transcendentals"] == 0.0

    def test_missing_keys_are_none(self):
        c = _FakeCompiled({"flops": 10.0})
        out = cost_breakdown(c)
        assert out["flops"] == 10.0
        assert out["bytes"] is None
        assert out["transcendentals"] is None

    def test_negative_sentinel_and_non_numeric_are_none(self):
        c = _FakeCompiled({"flops": -1.0, "bytes accessed": "n/a",
                           "transcendentals": 3.0})
        out = cost_breakdown(c)
        assert out["flops"] is None
        assert out["bytes"] is None
        assert out["transcendentals"] == 3.0

    def test_list_wrapped_and_raising_analyses(self):
        c = _FakeCompiled({"flops": 5.0, "bytes accessed": 6.0,
                           "transcendentals": 0.0}, wrap_list=True)
        assert cost_breakdown(c)["bytes"] == 6.0
        bad = _FakeCompiled({}, raise_=True)
        assert cost_breakdown(bad) == {"flops": None, "bytes": None,
                                       "transcendentals": None,
                                       "comm_bytes": None}

    def test_single_pass(self):
        c = _FakeCompiled({"flops": 1.0, "bytes accessed": 2.0,
                           "transcendentals": 3.0})
        cost_breakdown(c)
        assert c.calls == 1

    def test_existing_helpers_agree(self):
        c = _FakeCompiled({"flops": 9.0, "bytes accessed": 0.0})
        assert compiled_flops(c) == 9.0
        assert compiled_bytes(c) == 0.0  # zero, not None (PR-4 fix)


# --------------------------------------------------------------------------
# RoundArtifact: versioned durable evidence
# --------------------------------------------------------------------------

class TestRoundArtifact:
    def test_round_trip_and_caller_timestamp(self, tmp_path):
        payload = {"metric": "m", "value": 123.4, "platform": "tpu",
                   "device_kind": "TPU v5 lite"}
        art = perf.make_round_artifact(
            payload, kind="bench", timestamp=1234.5,
            confirmed_on_device=True, source="test", git_rev="abc123")
        assert art["schema"] == perf.ROUND_SCHEMA
        assert art["schema_version"] == perf.ROUND_ARTIFACT_VERSION
        assert art["timestamp"] == 1234.5  # caller's clock, verbatim
        assert art["device_kind"] == "TPU v5 lite"  # from payload
        assert art["platform"] == "tpu"
        path = str(tmp_path / "BENCH_measured_x.json")
        perf.write_round_artifact(path, art)
        loaded = perf.load_round_artifact(path)
        assert loaded == json.loads(json.dumps(art))
        assert perf.artifact_payload(loaded)["value"] == 123.4
        assert perf.artifact_timestamp(loaded) == 1234.5

    def test_is_confirmed_rules(self):
        # new schema: confirmed flag, not carried forward, nonzero value
        good = perf.make_round_artifact(
            {"value": 1.0}, kind="bench", timestamp=1.0,
            confirmed_on_device=True)
        assert perf.is_confirmed(good)
        cf = perf.make_round_artifact(
            {"value": 1.0}, kind="bench", timestamp=1.0,
            confirmed_on_device=True, carried_forward=True)
        assert not perf.is_confirmed(cf)  # stale evidence can't launder
        zero = perf.make_round_artifact(
            {"value": 0.0}, kind="bench", timestamp=1.0,
            confirmed_on_device=True)
        assert not perf.is_confirmed(zero)
        unconfirmed = perf.make_round_artifact(
            {"value": 5.0}, kind="bench", timestamp=1.0)
        assert not perf.is_confirmed(unconfirmed)
        # legacy flat files: complete real-chip run only
        assert perf.is_confirmed({"platform": "tpu", "value": 2221.4})
        assert not perf.is_confirmed({"platform": "tpu", "value": 2221.4,
                                      "partial": "watchdog"})
        assert not perf.is_confirmed({"platform": "cpu", "value": 99.0})
        assert not perf.is_confirmed({"platform": "tpu", "value": 0.0})
        assert not perf.is_confirmed({"platform": "tpu", "value": 10.0,
                                      "carried_forward": True})
        assert not perf.is_confirmed(None)

    def test_latest_confirmed_ordering_and_skips(self, tmp_path):
        d = str(tmp_path)
        # legacy confirmed file (timestampless: ordered by mtime)
        legacy = {"metric": "m", "value": 100.0, "platform": "tpu"}
        with open(os.path.join(d, "BENCH_measured_2026-01-01.json"),
                  "w") as f:
            json.dump(legacy, f)
        old = time.time() - 3600
        os.utime(os.path.join(d, "BENCH_measured_2026-01-01.json"),
                 (old, old))
        # newer envelope artifact wins by its own timestamp
        art = perf.make_round_artifact(
            {"metric": "m", "value": 200.0, "platform": "tpu"},
            kind="bench", timestamp=time.time(), confirmed_on_device=True)
        perf.write_round_artifact(
            os.path.join(d, "BENCH_measured_2026-02-02.json"), art)
        # distractors: a corrupt file, a driver round wrapper, a
        # carried-forward copy — all skipped
        with open(os.path.join(d, "BENCH_corrupt.json"), "w") as f:
            f.write("{not json")
        with open(os.path.join(d, "BENCH_r05.json"), "w") as f:
            json.dump({"n": 5, "cmd": "python bench.py", "rc": 0,
                       "tail": "..."}, f)
        cf = perf.make_round_artifact(
            {"value": 999.0, "platform": "tpu"}, kind="bench",
            timestamp=time.time() + 999, confirmed_on_device=True,
            carried_forward=True)
        perf.write_round_artifact(
            os.path.join(d, "BENCH_measured_2026-03-03.json"), cf)

        path, doc = perf.latest_confirmed(d)
        assert os.path.basename(path) == "BENCH_measured_2026-02-02.json"
        assert perf.artifact_payload(doc)["value"] == 200.0
        # with the envelope gone, the legacy file is still usable
        os.remove(path)
        path2, doc2 = perf.latest_confirmed(d)
        assert os.path.basename(path2) == "BENCH_measured_2026-01-01.json"
        assert perf.artifact_payload(doc2)["value"] == 100.0

    def test_latest_confirmed_empty_dir(self, tmp_path):
        assert perf.latest_confirmed(str(tmp_path)) is None

    def test_carried_forward_result(self, tmp_path):
        art = perf.make_round_artifact(
            {"metric": "resnet", "value": 2221.4, "platform": "tpu",
             "mfu_vs_measured": 0.34},
            kind="bench", timestamp=777.0, confirmed_on_device=True)
        path = str(tmp_path / "BENCH_measured_prior.json")
        perf.write_round_artifact(path, art)
        out = perf.carried_forward_result(art, path, note="wedged")
        assert out["carried_forward"] is True
        assert out["carried_forward_from"] == "BENCH_measured_prior.json"
        assert out["original_timestamp"] == 777.0  # the MEASUREMENT time
        assert out["value"] == 2221.4  # never a 0.0 round
        assert out["carried_forward_note"] == "wedged"
        assert out["schema_version"] == perf.ROUND_ARTIFACT_VERSION
        # and the copy itself can never become a confirmed source
        assert not perf.is_confirmed(out)

    def test_promote_chip_session(self, tmp_path):
        session = {
            "date": "2026-08-03",
            "bench": {"metric": "resnet", "value": 2300.0,
                      "platform": "tpu", "device_kind": "TPU v5 lite"},
            "real_jpeg_train": {"records_per_sec": 1890.0,
                                "mode": "real-jpeg-train"},
            "int8_infer": {"error": "timeout 420s"},  # errors stay out
        }
        path = perf.promote_chip_session(
            session, timestamp=555.0, out_dir=str(tmp_path),
            git_rev="deadbee")
        assert os.path.basename(path) == "BENCH_measured_2026-08-03.json"
        doc = perf.load_round_artifact(path)
        assert perf.is_confirmed(doc)
        assert doc["timestamp"] == 555.0 and doc["git_rev"] == "deadbee"
        payload = perf.artifact_payload(doc)
        # real-JPEG device training landed IN the round record
        assert payload["real_jpeg_train"]["records_per_sec"] == 1890.0
        assert "int8_infer" not in payload
        # and bench.py's degradation path would find it
        found = perf.latest_confirmed(str(tmp_path))
        assert found is not None and found[0] == path

    def test_promote_refuses_unconfirmed_sessions(self, tmp_path):
        # CPU smoke run / partial / absent bench: nothing to promote
        for bench in (None, {"error": "timeout"},
                      {"value": 50.0, "platform": "cpu"},
                      {"value": 100.0, "platform": "tpu",
                       "partial": "watchdog"}):
            session = {"date": "d", "bench": bench}
            assert perf.promote_chip_session(
                session, timestamp=1.0, out_dir=str(tmp_path)) is None
        assert perf.latest_confirmed(str(tmp_path)) is None


# --------------------------------------------------------------------------
# end-to-end: the optimizer's window records drive real attribution
# --------------------------------------------------------------------------

def _mini_dataset(n=32, feature=6, classes=4, seed=0):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(seed)
    samples = [Sample(rng.normal(size=(feature,)).astype(np.float32),
                      int(rng.integers(1, classes + 1)))
               for _ in range(n)]
    return DataSet.array(samples).transform(SampleToMiniBatch(16))


def _mini_model():
    return nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 4),
                         nn.LogSoftMax())


class TestOptimizerCaptureE2E:
    def test_window_records_statusz_and_families(self):
        from bigdl_tpu.optim import Optimizer, Trigger
        telemetry.enable()
        telemetry.reset()
        opt = (Optimizer(_mini_model(), _mini_dataset(),
                         nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(4)))
        opt.optimize()

        recs = opt.window_records
        assert recs, "optimizer recorded no windows"
        for r in recs:
            assert r["iterations"] >= 1 and r["wall_s"] >= 0.0
            for key in ("data_wait_s", "host_staging_s",
                        "device_compute_s", "readback_s"):
                assert r[key] >= 0.0
        # the real stream obeys the published invariant
        rep = perf.attribute_windows(recs)
        total = (sum(rep["phases_s"].values()) + rep["residual_s"]
                 - rep["overlap_s"])
        assert total == pytest.approx(rep["wall_step_s"], rel=1e-6)
        assert rep["residual_s"] >= 0.0

        # /statusz surfaces the same attribution live
        st = opt.statusz()
        assert st["perf"] is not None
        assert st["perf"]["attribution"]["wall_step_s"] == \
            pytest.approx(rep["wall_step_s"])
        assert set(st["perf"]["last_window"]) >= {
            "iterations", "wall_s", "data_wait_s", "host_staging_s",
            "device_compute_s", "readback_s"}

        # preregistered families got real observations
        h = families.step_phase_seconds()
        for phase in perf.PHASES:
            snap = h.labels(phase).snapshot()
            assert snap["count"] == len(recs), phase
        # residual gauge was set from the final window
        assert 0.0 <= families.step_unattributed_fraction().value() <= 1.0

    def test_window_records_are_bounded(self, monkeypatch):
        # a multi-million-iteration run must not grow host memory one
        # dict per window forever: the record stream is a deque capped
        # by BIGDL_TPU_WINDOW_RECORDS_CAP
        from bigdl_tpu.optim import Optimizer, Trigger
        monkeypatch.setenv("BIGDL_TPU_WINDOW_RECORDS_CAP", "3")
        opt = (Optimizer(_mini_model(), _mini_dataset(),
                         nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(6)))
        opt.optimize()
        assert len(opt.window_records) == 3  # 6 windows flushed, 3 kept
        assert perf.attribute_windows(opt.window_records) is not None

    def test_off_by_default_records_still_exist(self):
        # telemetry disabled: the phase stream (plain floats, no
        # metrics) still exists so harnesses can attribute without
        # flipping the global switch
        from bigdl_tpu.optim import Optimizer, Trigger
        assert not telemetry.enabled()
        opt = (Optimizer(_mini_model(), _mini_dataset(),
                         nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(2)))
        opt.optimize()
        assert opt.window_records
        assert families.step_phase_seconds().labels(
            "data_wait").snapshot()["count"] == 0

    def test_stalled_pipeline_attributes_to_data_wait(self):
        # chaos delays every batch fetch; the attribution must point at
        # data_wait — the question ROADMAP item 1 wants answered per
        # phase, demonstrated end-to-end
        from bigdl_tpu.optim import Optimizer, Trigger
        from bigdl_tpu.utils import chaos
        telemetry.enable()
        telemetry.reset()
        chaos.reset()
        chaos.install(stall_pipeline_s=0.05)
        try:
            opt = (Optimizer(_mini_model(), _mini_dataset(),
                             nn.ClassNLLCriterion())
                   .set_end_when(Trigger.max_epoch(4)))
            opt.optimize()
        finally:
            chaos.reset()
        rep = perf.attribute_windows(opt.window_records)
        assert rep["dominant_phase"] == "data_wait", rep
        assert rep["fractions"]["data_wait"] > 0.3, rep
        assert rep["residual_s"] >= 0.0

    def test_statusz_perf_none_before_any_window(self):
        from bigdl_tpu.optim import Optimizer, Trigger
        opt = (Optimizer(_mini_model(), _mini_dataset(),
                         nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(1)))
        st = opt.statusz()  # before optimize(): no records yet
        assert st["perf"] is None
