"""Fault-tolerance layer: atomic/verifiable checkpoints
(utils/file.CheckpointManager), the retry loop's exception
classification + backoff + preemption handling (optim/optimizer.py),
and the chaos hooks driving it all (utils/chaos.py).

The headline test is the acceptance scenario: kill training
mid-checkpoint-write so the NEWEST checkpoint is torn, prove
``latest_good()`` walks back to the previous good generation, and prove
``optimize()`` resumes from it and completes with the same final driver
state as an uninterrupted run — the exact crash the reference's retry
loop (DistriOptimizer.scala:901-983) existed for but could not survive
with mtime-newest checkpoint selection.
"""

import json
import os
import signal

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.optim import SGD, Optimizer, Trigger
from bigdl_tpu.utils import chaos, set_seed
from bigdl_tpu.utils.file import (
    CheckpointManager, load_checkpoint, load_pytree, save_checkpoint,
    save_pytree,
)


@pytest.fixture(autouse=True)
def _chaos_reset():
    chaos.reset()
    yield
    chaos.reset()


def _samples(n=32, dim=6, classes=4, seed=0):
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(seed)
    return [Sample(rng.normal(size=(dim,)).astype(np.float32),
                   int(rng.integers(1, classes + 1))) for _ in range(n)]


def _model(dim=6, classes=4):
    return nn.Sequential(nn.Linear(dim, 8), nn.ReLU(),
                         nn.Linear(8, classes), nn.LogSoftMax())


def _dataset(samples, batch=16):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    return DataSet.array(samples).transform(SampleToMiniBatch(batch))


def _fast_retry(opt, times=2):
    return opt.set_failure_retry(times, interval_s=300,
                                 backoff_s=0.01, backoff_cap_s=0.05)


# --------------------------------------------------------------------------
# atomic writes
# --------------------------------------------------------------------------

class TestAtomicWrites:
    def test_save_leaves_no_tmp_file(self, tmp_path):
        p = str(tmp_path / "t.npz")
        crc, size = save_pytree({"w": np.arange(8, dtype=np.float32)}, p)
        assert size == os.path.getsize(p) and crc
        assert [f for f in os.listdir(tmp_path) if ".tmp-" in f] == []

    def test_failed_write_leaves_previous_file_intact(self, tmp_path):
        p = str(tmp_path / "t.npz")
        save_pytree({"w": np.arange(8, dtype=np.float32)}, p)
        chaos.install(io_fail_p=1.0)
        with pytest.raises(OSError, match="injected IO failure"):
            save_pytree({"w": np.zeros(8, np.float32)}, p)
        chaos.reset()
        # the OLD payload is still complete and loadable
        np.testing.assert_array_equal(load_pytree(p)["w"],
                                      np.arange(8, dtype=np.float32))

    def test_crc_matches_payload_bytes(self, tmp_path):
        import zlib
        p = str(tmp_path / "t.npz")
        crc, size = save_pytree({"a": np.ones((3, 3), np.float32)}, p)
        data = open(p, "rb").read()
        assert (zlib.crc32(data) & 0xFFFFFFFF, len(data)) == (crc, size)


# --------------------------------------------------------------------------
# CheckpointManager
# --------------------------------------------------------------------------

def _ckpt_state(v: float):
    model = {"params": {"w": np.full((4,), v, np.float32)}, "buffers": {}}
    return model, [{"t": np.asarray(1)}], {"epoch": 1, "neval": int(v)}


class TestCheckpointManager:
    def test_save_and_latest_good_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        path = mgr.save(*_ckpt_state(3.0), generation=3)
        assert mgr.latest_good() == path
        model, _opt, driver = load_checkpoint(path)
        assert driver["neval"] == 3

    def test_overwrite_mode_records_true_generation(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(*_ckpt_state(3.0), generation=3, overwrite=True)
        path = mgr.save(*_ckpt_state(5.0), generation=5, overwrite=True)
        assert os.path.basename(path) == "checkpoint.npz"
        man = json.loads(
            (tmp_path / "checkpoint.manifest.json").read_text())
        assert man["generation"] == 5 and man["crc32"]

    def test_latest_good_skips_truncated_generation(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        good = mgr.save(*_ckpt_state(3.0), generation=3)
        torn = mgr.save(*_ckpt_state(5.0), generation=5)
        with open(torn, "r+b") as f:
            f.truncate(64)  # torn write: manifest committed, payload torn
        assert mgr.latest_good() == good

    def test_latest_good_skips_uncommitted_generation(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        good = mgr.save(*_ckpt_state(3.0), generation=3)
        # crash between payload and manifest: payload alone, truncated
        # (a committed-looking full payload without a manifest is still
        # usable via the legacy probe — this one is not loadable)
        (tmp_path / "checkpoint.9.npz").write_bytes(b"PK\x03\x04 torn")
        assert mgr.latest_good() == good

    def test_latest_good_walks_back_multiple_generations(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        good = mgr.save(*_ckpt_state(1.0), generation=1)
        for g in (2, 3):
            p = mgr.save(*_ckpt_state(float(g)), generation=g)
            with open(p, "r+b") as f:
                f.truncate(32)
        assert mgr.latest_good() == good

    def test_latest_good_none_when_everything_corrupt(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        p = mgr.save(*_ckpt_state(1.0), generation=1)
        with open(p, "r+b") as f:
            f.truncate(16)
        assert mgr.latest_good() is None

    def test_legacy_unmanifested_checkpoint_still_found(self, tmp_path):
        # files written by save_checkpoint directly (older sessions)
        save_checkpoint(str(tmp_path / "checkpoint.7.npz"),
                        *_ckpt_state(7.0))
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.latest_good() == str(tmp_path / "checkpoint.7.npz")

    def test_gc_keeps_exactly_keep_n_good_generations(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_n=2)
        for g in range(1, 6):
            mgr.save(*_ckpt_state(float(g)), generation=g)
        kept = sorted(f for f in os.listdir(tmp_path)
                      if f.endswith(".npz"))
        assert kept == ["checkpoint.4.npz", "checkpoint.5.npz"]
        assert sorted(mgr.generations()) == [4, 5]

    def test_gc_does_not_count_torn_generation_toward_keep_n(
            self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_n=2)
        mgr.save(*_ckpt_state(1.0), generation=1)
        mgr.save(*_ckpt_state(2.0), generation=2)
        # fresh controller: its write counter starts at the 3rd save
        c = chaos.install(truncate_checkpoint=1, truncate_keep_bytes=16)
        mgr.save(*_ckpt_state(3.0), generation=3)
        assert any("truncated" in e for e in c.events)
        chaos.reset()
        # gen 3 is torn, so gens 1 and 2 are the two good ones — 1 must
        # survive GC or a walkback past gen 3 then 2 would find nothing
        good = [f for f in sorted(os.listdir(tmp_path))
                if f.endswith(".npz")]
        assert "checkpoint.1.npz" in good and "checkpoint.2.npz" in good

    def test_gc_sweeps_stale_tmp_files(self, tmp_path):
        stale = tmp_path / ".checkpoint.3.npz.tmp-123-dead"
        stale.write_bytes(b"partial")
        os.utime(stale, (0, 0))  # ancient: no writer can still own it
        mgr = CheckpointManager(str(tmp_path), keep_n=1)
        mgr.save(*_ckpt_state(1.0), generation=1)
        assert not stale.exists()

    def test_remote_manifest_commit_marker(self):
        """On fsspec paths (no atomic rename) the manifest IS the commit
        marker; a payload without one is not served unless loadable."""
        pytest.importorskip("fsspec")
        mgr = CheckpointManager("memory://bigdl_ft_test/ckpts")
        p = mgr.save(*_ckpt_state(2.0), generation=2)
        assert mgr.latest_good() == p
        _model_s, _opt_s, driver = load_checkpoint(mgr.latest_good())
        assert driver["neval"] == 2


# --------------------------------------------------------------------------
# chaos hooks
# --------------------------------------------------------------------------

class TestChaos:
    def test_on_step_fires_once(self):
        c = chaos.install(fail_at_step=3)
        chaos.on_step(2)
        with pytest.raises(chaos.FaultInjected):
            chaos.on_step(3)
        chaos.on_step(3)  # one-shot: the retry must get through
        assert c.events

    def test_env_driven_install(self, monkeypatch):
        chaos.reset()
        monkeypatch.setenv("BIGDL_TPU_CHAOS_FAIL_STEP", "5")
        with pytest.raises(chaos.FaultInjected):
            chaos.on_step(5)
        chaos.reset()

    def test_io_fail_probability_seeded(self):
        chaos.install(io_fail_p=1.0, seed=7)
        with pytest.raises(OSError):
            chaos.on_io_write("/x")
        chaos.reset()
        chaos.install(io_fail_p=0.0)
        chaos.on_io_write("/x")  # never fires

    def test_inactive_hooks_are_noops(self):
        chaos.reset()
        chaos.on_step(123)
        chaos.on_io_write("/x")
        chaos.on_checkpoint_payload("/x")


# --------------------------------------------------------------------------
# retry loop: classification + backoff
# --------------------------------------------------------------------------

class TestRetryPolicy:
    def test_programming_error_not_retried(self, tmp_path):
        """A ValueError must re-raise immediately even with retries and
        a perfectly good checkpoint available."""
        class Bad:
            def __init__(self, inner):
                self.inner = inner
                self.calls = 0

            def data(self, train=True):
                self.calls += 1
                if self.calls >= 2:
                    raise ValueError("bug in user code")
                return self.inner.data(train)

            def size(self):
                return self.inner.size()

        set_seed(31)
        data = Bad(_dataset(_samples(seed=3)))
        opt = _fast_retry(
            Optimizer(_model(), data, nn.ClassNLLCriterion())
            .set_optim_method(SGD(0.1))
            .set_end_when(Trigger.max_epoch(3))
            .set_checkpoint(str(tmp_path), Trigger.every_epoch()), 5)
        with pytest.raises(ValueError, match="bug in user code"):
            opt.optimize()
        assert data.calls == 2, "ValueError was retried"

    def test_backoff_grows_exponentially_and_caps(self):
        opt = Optimizer(_model(), _dataset(_samples()),
                        nn.ClassNLLCriterion())
        opt.set_failure_retry(5, backoff_s=1.0, backoff_cap_s=8.0,
                              jitter=0.0)
        delays = [opt._backoff_delay(a) for a in range(6)]
        assert delays == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]

    def test_backoff_jitter_bounded(self):
        opt = Optimizer(_model(), _dataset(_samples()),
                        nn.ClassNLLCriterion())
        opt.set_failure_retry(5, backoff_s=2.0, jitter=0.25)
        for _ in range(50):
            assert 1.5 <= opt._backoff_delay(0) <= 2.5

    def test_transient_classification(self):
        from bigdl_tpu.optim.optimizer import _is_transient
        assert _is_transient(RuntimeError("x"))
        assert _is_transient(OSError("x"))
        assert _is_transient(ConnectionError("x"))
        assert _is_transient(chaos.FaultInjected("x"))
        assert not _is_transient(ValueError("x"))
        assert not _is_transient(TypeError("x"))
        assert not _is_transient(KeyError("x"))
        assert not _is_transient(AssertionError("x"))


# --------------------------------------------------------------------------
# end-to-end: crash mid-checkpoint → walkback → resume → same final state
# --------------------------------------------------------------------------

def _run_training(tmp_path=None, keep_n=None, fail_at_step=None,
                  truncate_ckpt=None, seed=41, epochs=3):
    set_seed(seed)
    opt = (Optimizer(_model(), _dataset(_samples(seed=5)),
                     nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(epochs)))
    if tmp_path is not None:
        opt.set_checkpoint(str(tmp_path), Trigger.every_epoch(),
                           keep_n=keep_n)
        _fast_retry(opt, 3)
    if fail_at_step or truncate_ckpt:
        chaos.install(fail_at_step=fail_at_step,
                      truncate_checkpoint=truncate_ckpt,
                      truncate_keep_bytes=64)
    opt.optimize()
    return opt


class TestCrashResumeEndToEnd:
    def test_crash_mid_checkpoint_resumes_from_previous_good(
            self, tmp_path):
        """The acceptance scenario.  32 samples / batch 16 → 2
        iterations per epoch, checkpoints at epoch ends (generations
        3 and 5).  The 2nd checkpoint write is torn mid-write AND
        training is killed at iteration 6 (epoch 3) — resume must skip
        torn generation 5, restart from generation 3, and finish with
        the driver state an uninterrupted run produces."""
        clean = _run_training(None)  # uninterrupted oracle

        faulty = _run_training(tmp_path, keep_n=2, fail_at_step=6,
                               truncate_ckpt=2)
        events = chaos.active().events
        assert any("truncated" in e for e in events)
        assert any("injected failure at iteration 6" in e
                   for e in events)

        # same terminal driver state as the uninterrupted run
        for key in ("epoch", "neval", "records"):
            assert faulty.state[key] == clean.state[key], key
        assert np.isfinite(faulty.state["loss"])

        # retention: exactly keep_n good generations survive, and the
        # latest one loads with the final iteration's driver state
        mgr = CheckpointManager(str(tmp_path), keep_n=2)
        last = mgr.latest_good()
        assert last is not None
        _model_s, _opt_s, driver = load_checkpoint(last)
        assert driver["neval"] == faulty.state["neval"]
        good = [g for g in mgr.generations()
                if mgr.validate(next(m for m in mgr._manifests()
                                     if m["generation"] == g))]
        assert len(good) == 2

    def test_resume_replays_interrupted_epoch(self, tmp_path):
        """The checkpoint at an epoch boundary stores the NEXT epoch
        number; a failure mid-epoch must replay that epoch from its
        start, not skip the remaining batches."""
        opt = _run_training(tmp_path, keep_n=None, fail_at_step=4)
        # epoch 2 was interrupted at iteration 4 and replayed
        assert opt.state["epoch"] == 4
        assert opt.state["neval"] == 7

    def test_retry_exhaustion_still_raises(self, tmp_path):
        class AlwaysFails:
            def __init__(self, inner):
                self.inner = inner
                self.calls = 0

            def data(self, train=True):
                self.calls += 1
                if self.calls >= 2:
                    raise RuntimeError("persistent failure")
                return self.inner.data(train)

            def size(self):
                return self.inner.size()

        set_seed(43)
        data = AlwaysFails(_dataset(_samples(seed=7)))
        opt = _fast_retry(
            Optimizer(_model(), data, nn.ClassNLLCriterion())
            .set_optim_method(SGD(0.1))
            .set_end_when(Trigger.max_epoch(3))
            .set_checkpoint(str(tmp_path), Trigger.every_epoch()), 2)
        with pytest.raises(RuntimeError, match="persistent failure"):
            opt.optimize()
        assert data.calls == 4  # initial + 2 retries + final raise


# --------------------------------------------------------------------------
# preemption (SIGTERM)
# --------------------------------------------------------------------------

class TestPreemption:
    def test_sigterm_checkpoints_and_exits_cleanly(self, tmp_path):
        """SIGTERM mid-epoch-2 → a final checkpoint at the next step
        boundary, clean return (no exception), epoch counter NOT
        advanced past the unfinished epoch — and a fresh optimizer can
        resume from that checkpoint and complete the run."""
        class KillsItself:
            def __init__(self, inner):
                self.inner = inner
                self.epochs = 0

            def data(self, train=True):
                self.epochs += 1
                it = self.inner.data(train)
                if self.epochs == 2:
                    def gen():
                        yield next(it)
                        os.kill(os.getpid(), signal.SIGTERM)
                        yield next(it)
                    return gen()
                return it

            def size(self):
                return self.inner.size()

        set_seed(47)
        data = KillsItself(_dataset(_samples(seed=9)))
        opt = (Optimizer(_model(), data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(0.1))
               .set_end_when(Trigger.max_epoch(3))
               .set_checkpoint(str(tmp_path), Trigger.every_epoch()))
        model = opt.optimize()  # returns, does not die
        assert model is not None
        assert opt.preempted
        assert opt.state["epoch"] == 2, "unfinished epoch must not advance"

        # default SIGTERM disposition restored after optimize()
        assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL

        ckpt = CheckpointManager(str(tmp_path)).latest_good()
        assert ckpt is not None
        _m, _o, driver = load_checkpoint(ckpt)
        assert driver["epoch"] == 2

        set_seed(47)
        opt2 = (Optimizer(_model(), _dataset(_samples(seed=9)),
                          nn.ClassNLLCriterion())
                .set_optim_method(SGD(0.1))
                .set_end_when(Trigger.max_epoch(3))
                .resume(ckpt))
        opt2.optimize()
        assert opt2.state["epoch"] == 4 and not opt2.preempted

    def test_sigterm_without_checkpoint_path_still_clean(self):
        class KillsItself:
            def __init__(self, inner):
                self.inner = inner

            def data(self, train=True):
                it = self.inner.data(train)

                def gen():
                    yield next(it)
                    os.kill(os.getpid(), signal.SIGTERM)
                    yield next(it)
                return gen()

            def size(self):
                return self.inner.size()

        set_seed(53)
        opt = (Optimizer(_model(), KillsItself(_dataset(_samples())),
                         nn.ClassNLLCriterion())
               .set_optim_method(SGD(0.1))
               .set_end_when(Trigger.max_epoch(2)))
        opt.optimize()
        assert opt.preempted


class TestReviewRegressions:
    def test_stale_manifest_overwrite_mode_still_resumes(self, tmp_path):
        """Overwrite mode: a crash between the payload rename and the
        manifest write leaves a STALE manifest beside a complete
        payload — latest_good must trust the load probe, not the stale
        CRC, or a perfectly good checkpoint bricks every retry."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(*_ckpt_state(2.0), generation=2, overwrite=True)
        # crash before the gen-4 manifest: payload committed, manifest
        # still describes gen 2
        chaos.install(crash_checkpoint=1)
        with pytest.raises(chaos.FaultInjected):
            mgr.save(*_ckpt_state(4.0), generation=4, overwrite=True)
        chaos.reset()
        p = mgr.latest_good()
        assert p == str(tmp_path / "checkpoint.npz")
        _m, _o, driver = load_checkpoint(p)
        assert driver["neval"] == 4  # the NEW payload, stale manifest

    def test_gc_does_not_count_unmarked_orbax_dir_as_good(self, tmp_path):
        """A present-but-unmarked orbax directory is a torn two-phase
        commit; counting it toward keep_n would let GC delete the last
        restorable generation."""
        mgr = CheckpointManager(str(tmp_path), keep_n=1)
        good = tmp_path / "checkpoint.1.orbax" / "tree"
        good.mkdir(parents=True)
        (good / "_CHECKPOINT_METADATA").write_text("{}")
        mgr._write_manifest("checkpoint.1.orbax", 1, None, None, True)
        torn = tmp_path / "checkpoint.2.orbax" / "tree"
        torn.mkdir(parents=True)  # no commit markers
        mgr._write_manifest("checkpoint.2.orbax", 2, None, None, True)
        mgr.gc()
        assert (tmp_path / "checkpoint.1.orbax").exists(), \
            "GC deleted the only committed generation"

    def test_preempted_flag_resets_on_next_optimize(self, tmp_path):
        """optimize() after a preemption must not report the stale
        preempted=True when the second run completes normally."""
        opt = _run_training(tmp_path)
        opt.preempted = True  # as a prior preempted run would leave it
        opt.set_end_when(Trigger.max_epoch(4))
        opt.optimize()
        assert not opt.preempted
