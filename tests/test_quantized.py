"""Tests for int8 quantized inference (reference nn/quantized/ +
integration/Quantization.scala: <0.1% accuracy-drop recipe on the
whitepaper's benchmark — here checked as close outputs + matched
classification decisions on a trained toy model)."""

import numpy as np
import pytest

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.quantized import (
    QuantizedLinear, QuantizedSpatialConvolution, Quantizer, quantize,
)
from bigdl_tpu.utils import set_seed


def test_quantized_linear_close_to_float():
    set_seed(0)
    lin = nn.Linear(32, 16)
    qlin = QuantizedLinear(lin)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 32)),
                    jnp.float32)
    want = np.asarray(lin.forward(x))
    got = np.asarray(qlin.forward(x))
    # int8 symmetric quantization: ~1% relative error budget
    rel = np.abs(got - want) / (np.abs(want).max() + 1e-8)
    assert rel.max() < 0.02, rel.max()


def test_quantized_linear_1d_input():
    set_seed(1)
    lin = nn.Linear(8, 4)
    qlin = QuantizedLinear(lin)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8,)),
                    jnp.float32)
    assert qlin.forward(x).shape == (4,)


def test_quantized_conv_close_to_float():
    set_seed(2)
    conv = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1)
    qconv = QuantizedSpatialConvolution(conv)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8, 8, 3)),
                    jnp.float32)
    want = np.asarray(conv.forward(x))
    got = np.asarray(qconv.forward(x))
    rel = np.abs(got - want) / (np.abs(want).max() + 1e-8)
    assert rel.max() < 0.03, rel.max()


def test_quantize_swaps_layers_and_preserves_decisions():
    set_seed(3)
    model = nn.Sequential(
        nn.SpatialConvolution(1, 4, 3, 3, 1, 1, 1, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2),
        nn.Reshape((4 * 4 * 4,)),
        nn.Linear(64, 10),
        nn.LogSoftMax(),
    )
    q = quantize(model)
    # original untouched; quantized layers swapped in the copy
    assert type(model.layers[0]) is nn.SpatialConvolution
    assert type(q.layers[0]) is QuantizedSpatialConvolution
    assert type(q.layers[4]) is QuantizedLinear
    x = jnp.asarray(np.random.default_rng(3).normal(size=(16, 8, 8, 1)),
                    jnp.float32)
    want_cls = np.argmax(np.asarray(model.eval_mode().forward(x)), axis=1)
    got_cls = np.argmax(np.asarray(q.forward(x)), axis=1)
    # ≙ reference <0.1% accuracy drop: decisions must agree
    assert (want_cls == got_cls).mean() >= 0.95


def test_quantized_weights_are_not_trainable():
    set_seed(4)
    q = quantize(nn.Linear(4, 2))
    assert q.parameters() == {}  # int8 weights + scales are buffers
    assert q.qweight.dtype == jnp.int8


def test_quantized_model_jits():
    import jax
    set_seed(5)
    q = quantize(nn.Sequential(nn.Linear(8, 8), nn.ReLU(),
                               nn.Linear(8, 2)))
    fn = jax.jit(lambda m, x: m.forward(x))
    x = jnp.ones((4, 8))
    y = fn(q, x)
    assert y.shape == (4, 2)
    assert np.isfinite(np.asarray(y)).all()


def test_quantize_ncf_scores_close():
    """int8 inference extends to the recommender: NeuralCF's four MLP/
    head Linears swap to QuantizedLinear (embedding lookups stay fp, as
    the reference quantizes only Linear/conv — nn/quantized/
    Quantizer.scala) and scores stay within sigmoid noise of fp32."""
    from bigdl_tpu.models import NeuralCF

    set_seed(0)
    m = NeuralCF(20, 30, embed_dim=8).eval_mode()
    rng = np.random.default_rng(0)
    pairs = jnp.asarray(np.stack([rng.integers(1, 21, size=(16,)),
                                  rng.integers(1, 31, size=(16,))], -1),
                        jnp.int32)
    base = np.asarray(m.forward(pairs))
    q = Quantizer.quantize(m)
    n_q = sum(isinstance(mod, QuantizedLinear)
              for _, mod in q.named_modules())
    assert n_q == 4, n_q
    assert np.abs(np.asarray(q.forward(pairs)) - base).max() < 0.05


def test_module_quantize_convenience():
    set_seed(6)
    m = nn.Sequential(nn.Linear(4, 4))
    q = m.quantize()
    assert type(q.layers[0]) is QuantizedLinear

@pytest.mark.slow
def test_int8_accuracy_delta_on_trained_lenet():
    """VERDICT r03 #7 / whitepaper.md:179-196 parity: quantize a model
    TRAINED in-suite and measure the fp32->int8 top-1 delta with the
    same Evaluator the bigdl-tpu-quantize CLI uses.  The reference
    claims <0.1% drop on its (much longer-trained) benchmarks; the
    harness bar here is <1% on LeNet over learnable synthetic MNIST."""
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.mnist import synthetic_mnist
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.nn.quantized import Quantizer
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.predictor import Evaluator

    set_seed(0)
    # hold out a split of ONE generation: the class prototypes are
    # seed-dependent, so a different seed would be a different task
    samples = synthetic_mnist(768, seed=0)
    train, test = samples[:512], samples[512:]
    data = DataSet.array(train).transform(SampleToMiniBatch(64))
    model = LeNet5(class_num=10)
    (Optimizer(model, data, nn.ClassNLLCriterion())
     .set_optim_method(SGD(0.1))
     .set_end_when(Trigger.max_epoch(6))
     .optimize())
    model = model.eval_mode()
    quantized = Quantizer.quantize(model)

    eval_data = (DataSet.array(test, shuffle=False)
                 .transform(SampleToMiniBatch(64)))
    accs = {}
    for tag, m in (("fp32", model), ("int8", quantized)):
        (res, _), = Evaluator(m, 64).evaluate(eval_data, [Top1Accuracy()])
        accs[tag] = float(res.result()[0])
    print(f"fp32 top1={accs['fp32']:.4f} int8 top1={accs['int8']:.4f} "
          f"delta={accs['fp32'] - accs['int8']:+.4f}")
    assert accs["fp32"] > 0.9, accs     # the model actually trained
    assert abs(accs["fp32"] - accs["int8"]) < 0.01, accs


@pytest.mark.slow
def test_int8_resnet50_imagenet_shape_fidelity():
    """VERDICT r03 #7's second half: int8 on the imagenet-shaped
    flagship.  Quantizing resnet50 must keep 224px logits close to
    fp32 (relative L2 error small) and mostly preserve top-1
    decisions even on an untrained model (where logit gaps are
    smallest, i.e. the adversarial case for decision flips)."""
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.nn.quantized import Quantizer

    set_seed(0)
    model = resnet50(class_num=1000).eval_mode()
    quant = Quantizer.quantize(model)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 224, 224, 3)).astype(np.float32))
    out_f = np.asarray(model.forward(x))
    out_q = np.asarray(quant.forward(x))
    rel = np.linalg.norm(out_q - out_f) / np.linalg.norm(out_f)
    agree = (out_f.argmax(1) == out_q.argmax(1)).mean()
    print(f"int8 resnet50: rel L2 err={rel:.4f}, top1 agreement={agree}")
    assert rel < 0.05, rel
    assert agree >= 0.75, agree   # docs cite this test's agreement
    assert np.isfinite(out_q).all()


def test_quantized_dilated_conv_preserves_dilation():
    """SpatialDilatedConvolution quantizes through the same int8 conv
    with rhs_dilation carried (≙ nn/quantized covers the dilated conv
    too, Quantizer.scala)."""
    set_seed(7)
    conv = nn.SpatialDilatedConvolution(3, 8, 3, 3, 1, 1, 2, 2, 2, 2)
    q = quantize(conv)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 10, 10, 3)),
                    jnp.float32)
    want = np.asarray(conv.forward(x))
    got = np.asarray(q.forward(x))
    assert want.shape == got.shape
    rel = np.abs(got - want) / (np.abs(want).max() + 1e-8)
    assert rel.max() < 0.03, rel.max()
