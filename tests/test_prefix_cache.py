"""Prefix KV-cache reuse + chunked prefill (serving/prefix_cache.py,
serving/generation.py): LRU byte budgeting, hit/miss/eviction
semantics, chunked-prefill equivalence across chunk boundaries, the
decode-must-not-disturb-inactive-rows pin, cadence/TTFT reservoirs, and
the acceptance harnesses at smoke scale.

The load-bearing assertion throughout: greedy rows stay BIT-IDENTICAL
to solo ``model.generate()`` — cache hit or miss, chunked or bucketed
prefill, across evictions — and cache disabled reproduces the PR-10
engine's rows exactly.
"""

import numpy as np
import pytest

from bigdl_tpu.models import transformer_lm
from bigdl_tpu.serving.generation import (
    GenerationScheduler, SlotPool, run_cadence_probe,
    run_shared_prefix_workload,
)
from bigdl_tpu.serving.prefix_cache import PrefixKVCache
from bigdl_tpu.utils import set_seed


@pytest.fixture(scope="module")
def lm():
    set_seed(0)
    return transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                          num_heads=4, filter_size=64,
                          max_len=64).eval_mode()


_SOLO_CACHE = {}


def solo(model, prompt, max_new, eos_id=None):
    import jax.numpy as jnp
    key = (id(model), prompt.tobytes(), int(max_new), eos_id)
    if key not in _SOLO_CACHE:
        _SOLO_CACHE[key] = np.asarray(model.generate(
            jnp.asarray(prompt, jnp.int32)[None], int(max_new),
            eos_id=eos_id))[0]
    return _SOLO_CACHE[key]


# ---------------------------------------------------------------------------
# PrefixKVCache unit semantics
# ---------------------------------------------------------------------------

def _fake_chunk_arrays(g=8, h=2, d=4):
    return ([{"k": np.zeros((h, g, d), np.float32),
              "v": np.zeros((h, g, d), np.float32)}],
            np.zeros((g,), bool))


def test_prefix_cache_match_insert_and_lru_eviction():
    layers, pad = _fake_chunk_arrays()
    nbytes = 2 * layers[0]["k"].nbytes + pad.size   # one entry's cost
    cache = PrefixKVCache(byte_budget=2 * nbytes, granularity=8)
    toks = np.arange(1, 25, dtype=np.int32)         # 3 granules
    assert cache.match(toks) == []                  # miss counted
    assert cache.missing_boundaries(toks) == [1, 2, 3]
    cache.insert(toks, 1, *_fake_chunk_arrays())
    cache.insert(toks, 2, *_fake_chunk_arrays())
    chain = cache.match(toks)
    assert [c.index for c in chain] == [0, 8]
    # a DIFFERENT second granule shares granule 1 only
    other = toks.copy()
    other[10] += 1
    assert len(cache.match(other)) == 1
    # third insert exceeds the 2-entry budget: LRU (granule-1 entry was
    # most recently touched by the matches) evicts the granule-2 entry
    cache.insert(toks, 3, *_fake_chunk_arrays())
    st = cache.stats()
    assert st["evictions"] == 1
    assert st["resident_bytes"] <= cache.byte_budget
    assert len(cache.match(toks)) >= 1              # granule 1 survived
    # prompts shorter than one granule are neither hit nor miss
    before = cache.stats()["lookups"]
    assert cache.match(np.arange(1, 5, dtype=np.int32)) == []
    assert cache.stats()["lookups"] == before


def test_prefix_cache_validation():
    with pytest.raises(ValueError, match="byte_budget"):
        PrefixKVCache(0, 8)
    with pytest.raises(ValueError, match="power of two"):
        PrefixKVCache(1 << 20, 12)
    # an entry larger than the whole budget is refused, not thrashed
    cache = PrefixKVCache(8, 8)
    layers, pad = _fake_chunk_arrays()
    assert cache.insert(np.arange(1, 9, dtype=np.int32), 1,
                        layers, pad) is None
    assert len(cache) == 0


# ---------------------------------------------------------------------------
# chunked prefill: equivalence across chunk boundaries
# ---------------------------------------------------------------------------

def test_chunked_prefill_equivalence_straddling_boundaries(lm):
    """Prompts whose prefill region lands exactly on, one short of, and
    past chunk boundaries (including the suffix-aligned overlapping
    final chunk) must stay bit-identical to solo generate()."""
    chunk = 8
    eng = GenerationScheduler(lm, slots=2, prefill_chunk=chunk,
                              prefill_chunk_budget=1)
    rng = np.random.default_rng(3)
    try:
        for tp in (chunk - 1, chunk, chunk + 1, 2 * chunk,
                   2 * chunk + 3, 6 * chunk + 5):
            prompt = rng.integers(1, 51, tp).astype(np.int32)
            row = eng.submit(prompt, 4, timeout=120)
            np.testing.assert_array_equal(row, solo(lm, prompt, 4),
                                          err_msg=f"Tp={tp}")
        counts = eng.pool.trace_counts
        assert counts["chunk_prefill"], "chunk path never exercised"
        assert all(n == 1 for n in counts["chunk_prefill"].values()), \
            counts
    finally:
        eng.shutdown()


def test_prefix_hit_longer_than_suffix_bucket(lm):
    """A cached prefix longer than the remaining suffix's bucket: the
    copy path must seed positions beyond where the suffix prefill
    writes, and the row stays bit-identical."""
    rng = np.random.default_rng(4)
    prefix = rng.integers(1, 51, 32).astype(np.int32)
    eng = GenerationScheduler(lm, slots=2, prefill_chunk=16,
                              prefix_cache_bytes=1 << 24,
                              prefix_granularity=8)
    try:
        p1 = np.concatenate([prefix, rng.integers(1, 51, 2)
                             .astype(np.int32)])
        p2 = np.concatenate([prefix, rng.integers(1, 51, 3)
                             .astype(np.int32)])
        np.testing.assert_array_equal(eng.submit(p1, 4, timeout=120),
                                      solo(lm, p1, 4))
        np.testing.assert_array_equal(eng.submit(p2, 4, timeout=120),
                                      solo(lm, p2, 4))
        st = eng.stats()
        # p2 hit the full 32-token prefix: 4 chunks of 8 copied, and
        # its suffix bucket (<= 4) is far shorter than the hit
        assert st["prefix_cache"]["hits"] == 1
        assert st["prefix_chunks_copied"] == 4
    finally:
        eng.shutdown()


def test_cache_disabled_byte_identical_to_baseline_engine(lm):
    """prefix_cache_bytes=None must reproduce the PR-10 behavior: same
    rows, no cache programs ever traced, bucketed prefill only (these
    prompts fit one chunk)."""
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 51, int(rng.integers(2, 20)))
               .astype(np.int32) for _ in range(8)]
    eng = GenerationScheduler(lm, slots=3)     # defaults: cache off
    try:
        rows = [f.result(120) for f in
                [eng.submit_async(p, 5) for p in prompts]]
        counts = eng.pool.trace_counts
    finally:
        eng.shutdown()
    for p, row in zip(prompts, rows):
        np.testing.assert_array_equal(row, solo(lm, p, 5))
    assert counts["kv_copy"] == {} and counts["kv_extract"] == {}
    assert counts["chunk_prefill"] == {}
    assert counts["prefill"], "bucketed prefill path was not used"
    # the cache-enabled engine emits the same bytes
    eng2 = GenerationScheduler(lm, slots=3, prefix_cache_bytes=1 << 24,
                               prefix_granularity=8)
    try:
        rows2 = [f.result(120) for f in
                 [eng2.submit_async(p, 5) for p in prompts]]
    finally:
        eng2.shutdown()
    for a, b in zip(rows, rows2):
        np.testing.assert_array_equal(a, b)


def test_eviction_under_byte_pressure_mid_stream(lm):
    """A byte budget that cannot hold every prefix forces evictions
    while requests are decoding; rows stay correct and the cache stays
    within budget (matched chains keep their arrays alive by
    reference, so eviction cannot corrupt an admitted request)."""
    rng = np.random.default_rng(6)
    pool = SlotPool(lm, slots=1)
    one_chunk = sum(
        2 * c["self"]["k"][0, :, :8, :].nbytes
        for c in pool.caches["layers"]) + 8
    eng = GenerationScheduler(lm, slots=3,
                              prefill_chunk=8,
                              prefix_cache_bytes=3 * one_chunk,
                              prefix_granularity=8)
    prompts = [rng.integers(1, 51, int(rng.integers(17, 40)))
               .astype(np.int32) for _ in range(10)]
    try:
        rows = [f.result(180) for f in
                [eng.submit_async(p, 4) for p in prompts]]
        st = eng.stats()["prefix_cache"]
    finally:
        eng.shutdown()
    for p, row in zip(prompts, rows):
        np.testing.assert_array_equal(row, solo(lm, p, 4))
    assert st["evictions"] > 0
    assert st["resident_bytes"] <= st["byte_budget"]


# ---------------------------------------------------------------------------
# the slot-isolation pin behind chunked prefill
# ---------------------------------------------------------------------------

def test_decode_does_not_disturb_inactive_rows(lm):
    """Pooled decode steps must not write into an INACTIVE slot's
    freshly prefilled region — every lane burns a write (S is
    shape-stable), so inactive lanes are steered to the always-masked,
    always-rewritten-before-read position max_len-1.  A stale index
    would silently clobber a co-scheduled chunked prefill (this is a
    byte-level pin; greedy-row tests can miss an ulp-scale poisoning
    that does not flip an argmax)."""
    rng = np.random.default_rng(7)
    pool = SlotPool(lm, slots=2)
    pool.chunk_prefill_into(rng.integers(1, 51, 8).astype(np.int32),
                            0, 0)
    k_before = [np.asarray(c["self"]["k"])[0, :, :8, :].copy()
                for c in pool.caches["layers"]]
    pad_before = np.asarray(pool.caches["pad"])[0, :8].copy()
    pool.activate(1, 5, 20)
    for _ in range(3):
        pool.decode()
    for i, c in enumerate(pool.caches["layers"]):
        np.testing.assert_array_equal(
            np.asarray(c["self"]["k"])[0, :, :8, :], k_before[i])
    np.testing.assert_array_equal(np.asarray(pool.caches["pad"])[0, :8],
                                  pad_before)


# ---------------------------------------------------------------------------
# observability: reservoirs, stats, telemetry families, trace counts
# ---------------------------------------------------------------------------

def test_ttft_and_inter_token_reservoir_quantiles_in_stats(lm):
    rng = np.random.default_rng(8)
    prompts = [rng.integers(1, 51, int(rng.integers(2, 16)))
               .astype(np.int32) for _ in range(5)]
    eng = GenerationScheduler(lm, slots=2)
    try:
        [f.result(120) for f in
         [eng.submit_async(p, 6) for p in prompts]]
        st = eng.stats()
    finally:
        eng.shutdown()
    assert st["queue_to_first_token_s_p50"] > 0
    assert st["queue_to_first_token_s_p99"] >= \
        st["queue_to_first_token_s_p50"]
    assert st["inter_token_s_p50"] > 0
    assert st["inter_token_s_p99"] >= st["inter_token_s_p50"]
    assert st["prefix_cache"] is None      # off by default


def test_prefix_and_cadence_families_recorded_when_enabled(lm):
    from bigdl_tpu import telemetry
    telemetry.enable()
    telemetry.reset()
    try:
        rng = np.random.default_rng(9)
        prefix = rng.integers(1, 51, 16).astype(np.int32)
        eng = GenerationScheduler(lm, slots=2, prefill_chunk=8,
                                  prefix_cache_bytes=1 << 24,
                                  prefix_granularity=8)
        try:
            for _ in range(3):
                tail = rng.integers(1, 51, 3).astype(np.int32)
                eng.submit(np.concatenate([prefix, tail]), 4,
                           timeout=120)
        finally:
            eng.shutdown()
        text = telemetry.prometheus_text()
        assert 'generation_prefix_cache_events_total{result="miss"}' \
            in text
        assert 'generation_prefix_cache_events_total{result="hit"}' \
            in text
        assert "generation_prefix_cache_bytes_reused_total" in text
        assert "generation_prefix_cache_resident_bytes" in text
        assert "generation_inter_token_seconds_count" in text
    finally:
        telemetry.reset()
        telemetry.disable()


def test_cache_and_seed_programs_compile_once(lm):
    """The new programs keep the O(1) budget: chunk prefill once per
    width, kv copy/extract once per granularity, the membership seed
    once total — across many requests joining and leaving."""
    rng = np.random.default_rng(10)
    eng = GenerationScheduler(lm, slots=2, prefill_chunk=8,
                              prefix_cache_bytes=1 << 24,
                              prefix_granularity=8)
    prompts = [rng.integers(1, 51, int(rng.integers(10, 40)))
               .astype(np.int32) for _ in range(8)]
    try:
        [f.result(180) for f in
         [eng.submit_async(p, 4) for p in prompts]]
        [f.result(180) for f in
         [eng.submit_async(p, 4) for p in prompts]]
        counts = {k: (dict(v) if isinstance(v, dict) else v)
                  for k, v in eng.pool.trace_counts.items()}
    finally:
        eng.shutdown()
    assert counts["decode"] == 1
    assert counts["seed"] == 1
    assert counts["kv_copy"] == {8: 1}
    assert counts["kv_extract"] == {8: 1}
    assert counts["chunk_prefill"] and \
        all(n == 1 for n in counts["chunk_prefill"].values()), counts


# ---------------------------------------------------------------------------
# acceptance harnesses at smoke scale
# ---------------------------------------------------------------------------

def test_shared_prefix_workload_harness(lm):
    out = run_shared_prefix_workload(
        lm, n_requests=6, prefix_len=24, tail=(2, 7), max_new=3,
        slots=2, prefix_cache_bytes=1 << 24, prefix_granularity=8,
        prefill_chunk=8, oracle_sample=1)
    assert out["rows_equal_cache_vs_nocache"]
    assert out["greedy_equal_checked"]
    assert out["cache"]["prefix_cache"]["hits"] > 0
    assert out["ttft_p50_speedup"] > 0
    assert out["shared_fraction"] > 0.5


def test_cadence_probe_harness(lm):
    out = run_cadence_probe(lm, slots=2, steady_requests=1,
                            warm_tokens=4, steady_budget=30,
                            long_prompt_len=40, long_max_new=2,
                            long_arrivals=1, prefill_chunk=8)
    assert out["bounded"] and out["prefill_chunk"] == 8
    assert out["gaps_before"] > 0 and out["gaps_during"] > 0
    assert out["steady_gap_p50_s"] > 0
    assert out["mixed_gap_p99_s"] > 0
