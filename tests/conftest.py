"""Test configuration: run on XLA CPU with 8 virtual devices so the
multi-chip sharding paths are exercised without a pod — the equivalent of
the reference's `new SparkContext("local[1]", ...)` trick
(reference: optim/DistriOptimizerSpec.scala:139).

NOTE: the axon sitecustomize forces jax_platforms="axon,cpu" via
jax.config.update at interpreter start, overriding the JAX_PLATFORMS env
var — so we must win the override war with our own config.update AFTER
importing jax, BEFORE any backend is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.utils import set_seed
    set_seed(4357)
    yield


@pytest.fixture()
def mesh8():
    """An 8-device CPU mesh shaped (data=2, model=2, pipe=2)."""
    import numpy as np
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    with Mesh(devs, ("data", "model", "pipe")) as m:
        yield m
