"""Test configuration: run on XLA CPU with 8 virtual devices so the
multi-chip sharding paths are exercised without a pod — the equivalent of
the reference's `new SparkContext("local[1]", ...)` trick
(reference: optim/DistriOptimizerSpec.scala:139)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.utils import set_seed
    set_seed(4357)  # the reference's default RandomGenerator seed semantics
    yield
