"""Test configuration: run on XLA CPU with 8 virtual devices so the
multi-chip sharding paths are exercised without a pod — the equivalent of
the reference's `new SparkContext("local[1]", ...)` trick
(reference: optim/DistriOptimizerSpec.scala:139).

NOTE: the axon sitecustomize forces jax_platforms="axon,cpu" via
jax.config.update at interpreter start, overriding the JAX_PLATFORMS env
var — so we must win the override war with our own config.update AFTER
importing jax, BEFORE any backend is initialized.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _ensure_devices  # noqa: E402

# BIGDL_TPU_TESTS_ON_TPU=1 keeps the real accelerator visible so the
# on-TPU smoke tests (compiled, non-interpret Pallas numerics in
# test_fused_conv_bn.py) can run during a healthy hardware window:
#   BIGDL_TPU_TESTS_ON_TPU=1 pytest tests/test_fused_conv_bn.py -k tpu
# Everything else assumes the 8-virtual-CPU mesh and should not be run
# in that mode.
_ON_TPU = os.environ.get("BIGDL_TPU_TESTS_ON_TPU") == "1"
jax = _ensure_devices(1 if _ON_TPU else 8, force_cpu=not _ON_TPU)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.utils import set_seed
    set_seed(4357)
    yield


@pytest.fixture()
def mesh8():
    """An 8-device CPU mesh shaped (data=2, model=2, pipe=2)."""
    import numpy as np
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    with Mesh(devs, ("data", "model", "pipe")) as m:
        yield m
