"""MaskRCNN model forward, autoencoder, and ranking/detection metrics.

Mirrors reference specs: models/maskrcnn/MaskRCNNSpec, autoencoder
specs, optim/ValidationSpec (MAP + object-detection mAP cases).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.models import Autoencoder, MaskRCNN, MaskRCNNParams
from bigdl_tpu.optim import (MeanAveragePrecision,
                             MeanAveragePrecisionObjectDetection,
                             PrecisionRecallAUC, TreeNNAccuracy)
from bigdl_tpu.utils import set_seed


def test_autoencoder_shapes_and_range():
    set_seed(0)
    model = Autoencoder(32)
    x = jnp.asarray(np.random.RandomState(0).rand(4, 28, 28),
                    jnp.float32)
    out = model(x)
    assert out.shape == (4, 784)
    o = np.asarray(out)
    assert (o >= 0).all() and (o <= 1).all()


@pytest.mark.slow
def test_maskrcnn_forward_shapes():
    set_seed(1)
    cfg = MaskRCNNParams(
        anchor_sizes=(16, 32, 64, 128, 256),
        pre_nms_topn_test=50, post_nms_topn_test=16,
        max_per_image=8, output_size=32, layers=(8, 8),
        box_score_thresh=0.0)
    model = MaskRCNN(num_classes=5, config=cfg).eval_mode()
    img = jnp.asarray(np.random.RandomState(0).randn(1, 64, 64, 3),
                      jnp.float32)
    info = jnp.asarray([64.0, 64.0, 64.0, 64.0])
    boxes, labels, scores, valid, masks = model((img, info))
    assert boxes.shape == (8, 4)
    assert labels.shape == (8,) and scores.shape == (8,)
    assert masks.shape == (8, 28, 28)
    m = np.asarray(masks)
    assert (m >= 0).all() and (m <= 1).all()


def test_map_classification_perfect_and_half():
    m = MeanAveragePrecision(classes=2)
    # two classes, predictions perfectly ranked
    scores = jnp.asarray([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9], [0.2, 0.8]])
    target = jnp.asarray([1.0, 1.0, 2.0, 2.0])
    res = m(scores, target)
    val, _ = res.result()
    assert val == pytest.approx(1.0)
    # merge two batches: still perfect
    res2 = res + m(scores, target)
    assert res2.result()[0] == pytest.approx(1.0)


def test_map_classification_known_value():
    m = MeanAveragePrecision(classes=1)
    # ranked: pos, neg, pos → AP = (1/1 + 2/3)/2 = 0.8333
    scores = jnp.asarray([[0.9], [0.8], [0.7]])
    target = jnp.asarray([1.0, 2.0, 1.0])
    val, _ = m(scores, target).result()
    assert val == pytest.approx((1.0 + 2.0 / 3.0) / 2.0, rel=1e-6)


def test_precision_recall_auc_perfect():
    m = PrecisionRecallAUC()
    scores = jnp.asarray([0.9, 0.8, 0.2, 0.1])
    labels = jnp.asarray([1.0, 1.0, 0.0, 0.0])
    val, n = m(scores, labels).result()
    assert n == 4
    assert val == pytest.approx(1.0, abs=1e-6)


def test_tree_nn_accuracy():
    m = TreeNNAccuracy()
    out = jnp.asarray([[[0.1, 0.9], [0.5, 0.5]],
                       [[0.8, 0.2], [0.5, 0.5]]])  # (B, nodes, C)
    tgt = jnp.asarray([[2.0, 1.0], [1.0, 1.0]])
    res = m(out, tgt)
    val, _ = res.result()
    assert val == pytest.approx(1.0)


def test_detection_map_voc():
    m = MeanAveragePrecisionObjectDetection(classes=2, iou_thresh=0.5)
    gts = [
        (np.array([1, 2]), np.array([[0, 0, 10, 10], [20, 20, 30, 30]],
                                    np.float32)),
    ]
    # perfect detections
    dets = [
        (np.array([1, 2]), np.array([0.9, 0.8]),
         np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)),
    ]
    assert m.evaluate(dets, gts) == pytest.approx(1.0)
    # class-2 detection misses (IoU < .5) → its AP = 0, mAP = 0.5
    dets_half = [
        (np.array([1, 2]), np.array([0.9, 0.8]),
         np.array([[0, 0, 10, 10], [25, 25, 40, 40]], np.float32)),
    ]
    assert m.evaluate(dets_half, gts) == pytest.approx(0.5)


def test_detection_map_voc07_and_coco_styles():
    gts = [(np.array([1]), np.array([[0, 0, 10, 10]], np.float32))]
    dets = [(np.array([1]), np.array([0.9]),
             np.array([[0, 0, 10, 10]], np.float32))]
    for style in ("VOC07", "COCO"):
        m = MeanAveragePrecisionObjectDetection(classes=1, style=style)
        assert m.evaluate(dets, gts) == pytest.approx(1.0)
    # duplicate detection of the same gt counts as FP under VOC
    dets_dup = [(np.array([1, 1]), np.array([0.9, 0.8]),
                 np.array([[0, 0, 10, 10], [0, 0, 10, 10]], np.float32))]
    m = MeanAveragePrecisionObjectDetection(classes=1)
    assert m.evaluate(dets_dup, gts) == pytest.approx(1.0)  # recall hit at rank 1
