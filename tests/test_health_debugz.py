"""Health watchdog, flight recorder, and debugz introspection
endpoints — plus the satellite fixes that ride along (draining
/healthz, profile_trace reentrancy, xla_cost zero-vs-missing).

The tentpole acceptance scenario lives in
``TestWatchdogEndToEnd.test_nonfinite_halt_writes_good_checkpoint...``:
a poisoned NaN batch under ``checkpoint_and_halt`` stops the run
within one step of detection, leaves a good checkpoint plus a
flight-recorder dump whose tail carries the verdict, and
``latest_good()`` resume completes cleanly.
"""

import http.client
import io
import json
import math
import os
import threading
import time

import numpy as np
import pytest

import jax

from bigdl_tpu import nn, telemetry
from bigdl_tpu.telemetry import events, families, tracing
from bigdl_tpu.telemetry.debugz import (
    Debugz, DebugzServer, ProfileBusyError,
)
from bigdl_tpu.telemetry.health import HealthWatchdog


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Each test starts enabled with zeroed metrics/spans/events and
    leaves the process disabled (the repo-wide default)."""
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


def _samples(n=32, dim=6, classes=4, seed=0):
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(seed)
    return [Sample(rng.normal(size=(dim,)).astype(np.float32),
                   int(rng.integers(1, classes + 1))) for _ in range(n)]


def _poison(samples, i=-1, dim=6):
    from bigdl_tpu.dataset.dataset import Sample
    out = list(samples)
    out[i] = Sample(np.full((dim,), np.nan, np.float32), 1)
    return out


def _model(dim=6, classes=4):
    return nn.Sequential(nn.Linear(dim, 8), nn.ReLU(),
                         nn.Linear(8, classes), nn.LogSoftMax())


def _dataset(samples, batch=16):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    return DataSet.array(samples).transform(SampleToMiniBatch(batch))


def _params_finite(tree) -> bool:
    return all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(tree))


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

class TestFlightRecorder:
    def test_always_on_and_ordered(self):
        # recording does NOT require telemetry.enabled(): the black box
        # must exist for the run where nobody enabled anything
        telemetry.disable()
        events.record_event("retry", error="boom", retries_left=2)
        events.record_event("checkpoint_commit", generation=3)
        recent = events.recent_events()
        assert [e["kind"] for e in recent] == ["retry",
                                               "checkpoint_commit"]
        assert recent[0]["error"] == "boom"
        assert recent[0]["time"] <= recent[1]["time"]
        assert events.event_counts() == {"retry": 1,
                                         "checkpoint_commit": 1}

    def test_ring_bounded_with_drop_counter_keeps_newest(self):
        events.set_event_capacity(8)
        try:
            for i in range(20):
                events.record_event("tick", i=i)
            recent = events.recent_events()
            assert len(recent) == 8
            assert [e["i"] for e in recent] == list(range(12, 20))
            assert events.dropped_events() == 12
        finally:
            events.reset_events()
            events.set_event_capacity(2048)

    def test_zero_n_means_none_not_all(self):
        for i in range(5):
            events.record_event("tick", i=i)
        assert events.recent_events(0) == []
        assert len(events.recent_events(2)) == 2
        assert len(events.recent_events(99)) == 5

    def test_nonfinite_fields_stay_strict_json(self):
        # a NaN value recorded during the incident must not poison the
        # dump/statusz with a bare NaN token (jq/JSON.parse reject it)
        events.record_event("watchdog", value=float("nan"),
                            limit=float("inf"))
        data = json.loads(events.dumps_events())  # round-trips
        json.dumps(data, allow_nan=False)         # and is STRICT json
        assert data["events"][-1]["value"] == "nan"
        assert data["events"][-1]["limit"] == "inf"

    def test_dump_survives_unserializable_fields(self, tmp_path):
        events.record_event("crash", error=RuntimeError("kaput"))
        path = events.dump_events(str(tmp_path / "fr.json"))
        data = json.loads(open(path).read())
        assert data["dropped"] == 0
        assert data["events"][-1]["kind"] == "crash"
        assert "kaput" in data["events"][-1]["error"]
        assert data["counts"] == {"crash": 1}


# --------------------------------------------------------------------------
# watchdog unit behavior
# --------------------------------------------------------------------------

class TestWatchdogUnit:
    def test_policy_validation(self):
        with pytest.raises(ValueError, match="unknown watchdog policy"):
            HealthWatchdog(nonfinite="explode")
        with pytest.raises(ValueError, match="skip_step"):
            HealthWatchdog(loss_spike="skip_step")
        assert HealthWatchdog(nonfinite="skip_step").guard_updates
        assert not HealthWatchdog(nonfinite="warn").guard_updates

    def test_set_health_watchdog_rejects_instance_plus_kwargs(self):
        from bigdl_tpu.optim import Optimizer
        opt = Optimizer(_model(), _dataset(_samples()),
                        nn.ClassNLLCriterion())
        with pytest.raises(ValueError, match="not both"):
            opt.set_health_watchdog(HealthWatchdog(),
                                    nonfinite="skip_step")
        # either alone is fine
        opt.set_health_watchdog(HealthWatchdog(nonfinite="warn"))
        assert opt.watchdog.policies["nonfinite"] == "warn"
        opt.set_health_watchdog(nonfinite="skip_step")
        assert opt.watchdog.policies["nonfinite"] == "skip_step"

    def test_nonfinite_verdicts_counters_and_halt(self):
        wd = HealthWatchdog(nonfinite="checkpoint_and_halt")
        vs = wd.observe_step(7, float("nan"), float("inf"))
        assert [v.kind for v in vs] == ["nonfinite_loss",
                                       "nonfinite_grad"]
        assert all(v.action == "checkpoint_and_halt" for v in vs)
        assert wd.halt_requested
        assert families.training_nonfinite_total().value() == 2
        assert families.training_anomalies_total().labels(
            "nonfinite_loss").value() == 1
        kinds = [e["anomaly"] for e in events.recent_events()
                 if e["kind"] == "watchdog"]
        assert kinds == ["nonfinite_loss", "nonfinite_grad"]
        # verdict history + events serialize to STRICT json even
        # though the offending values are NaN/Inf
        json.dumps(wd.state(), allow_nan=False)
        json.dumps(events.recent_events(), allow_nan=False)
        assert wd.state()["recent_verdicts"][0]["value"] == "nan"

    def test_warn_policy_does_not_halt(self):
        wd = HealthWatchdog(nonfinite="warn")
        vs = wd.observe_step(1, float("nan"))
        assert vs and vs[0].action == "warn"
        assert not wd.halt_requested

    def test_loss_spike_ewma(self):
        wd = HealthWatchdog(loss_spike="checkpoint_and_halt",
                            spike_factor=10.0, spike_grace_steps=10)
        rng = np.random.default_rng(0)
        for i in range(30):
            assert wd.observe_step(i, 1.0 + 0.01 * rng.normal()) == []
        vs = wd.observe_step(30, 100.0)
        assert [v.kind for v in vs] == ["loss_spike"]
        assert wd.halt_requested
        # nan must not poison the EWMA baseline
        wd2 = HealthWatchdog(nonfinite="warn")
        wd2.observe_step(0, 1.0)
        wd2.observe_step(1, float("nan"))
        assert math.isfinite(wd2.state()["loss_ewma"])

    def test_step_time_outlier(self):
        wd = HealthWatchdog(step_time_outlier="checkpoint_and_halt",
                            step_time_factor=10.0,
                            step_time_grace_windows=5)
        for _ in range(10):
            assert wd.observe_window(0.01, 0.0, 1) == []
        vs = wd.observe_window(5.0, 0.0, 1)
        assert [v.kind for v in vs] == ["step_time_outlier"]
        assert wd.halt_requested

    def test_data_starvation_rolling_window(self):
        wd = HealthWatchdog(starvation_fraction=0.5,
                            starvation_windows=4)
        verdicts = []
        for _ in range(4):
            verdicts += wd.observe_window(1.0, 0.9, 1)
        assert [v.kind for v in verdicts] == ["data_starvation"]
        assert verdicts[0].action == "warn"
        # the window cleared after the verdict: no immediate re-fire
        assert wd.observe_window(1.0, 0.9, 1) == []

    def test_state_is_jsonable_and_bounded(self):
        wd = HealthWatchdog(nonfinite="warn", max_history=3)
        for i in range(10):
            wd.observe_step(i, float("nan"))
        st = json.loads(json.dumps(wd.state()))
        assert len(st["recent_verdicts"]) == 3
        assert st["anomaly_counts"]["nonfinite_loss"] == 10
        assert st["recent_verdicts"][-1]["step"] == 9


# --------------------------------------------------------------------------
# watchdog end-to-end through the optimizer
# --------------------------------------------------------------------------

class TestWatchdogEndToEnd:
    def test_nonfinite_halt_writes_good_checkpoint_dump_and_resumes(
            self, tmp_path):
        """The acceptance scenario: poisoned NaN batch under
        checkpoint_and_halt -> stop within one step of detection, good
        final checkpoint, flight-recorder dump whose tail holds the
        verdict, latest_good() resume completes cleanly."""
        from bigdl_tpu.optim import Optimizer, Trigger
        from bigdl_tpu.utils.file import CheckpointManager, load_checkpoint
        ck = str(tmp_path / "ck")
        samples = _poison(_samples())
        model = _model()
        opt = (Optimizer(model, _dataset(samples), nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(6))
               .set_checkpoint(ck, Trigger.several_iteration(1))
               .set_health_watchdog())  # nonfinite -> checkpoint_and_halt
        opt.optimize()
        assert opt.watchdog_halted and not opt.preempted
        # stopped within one step of the verdict
        verdicts = [v for v in opt.watchdog.history
                    if v.kind.startswith("nonfinite")]
        assert verdicts
        assert opt.state["neval"] <= verdicts[0].step + 1
        assert families.training_nonfinite_total().value() >= 1
        # the final checkpoint is GOOD: the in-graph guard discarded
        # the poisoned update before it reached the params
        good = CheckpointManager(ck).latest_good()
        assert good is not None
        ms, _opt_state, driver = load_checkpoint(good)
        assert _params_finite(ms["params"])
        # flight recorder dumped next to the checkpoint, verdict in tail
        fr = json.loads(open(os.path.join(ck, "flight_recorder.json"))
                        .read())
        tail_kinds = [e["kind"] for e in fr["events"]][-6:]
        assert "watchdog_halt" in tail_kinds
        assert any(e["kind"] == "watchdog"
                   and e["anomaly"].startswith("nonfinite")
                   for e in fr["events"])
        # resume from the halt checkpoint (clean data) completes
        clean = _dataset(_samples(seed=1))
        resumed = (Optimizer(model, clean, nn.ClassNLLCriterion())
                   .set_end_when(Trigger.max_epoch(6))
                   .resume(good))
        resumed.optimize()
        assert not resumed.preempted and not resumed.watchdog_halted
        assert _params_finite(model.parameters())

    def test_skip_step_discards_update_and_training_continues(
            self, tmp_path):
        from bigdl_tpu.optim import Optimizer, Trigger
        samples = _poison(_samples())
        model = _model()
        opt = (Optimizer(model, _dataset(samples), nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(3))
               .set_gradient_clipping_by_l2_norm(5.0)  # norm reuse path
               .set_health_watchdog(nonfinite="skip_step"))
        opt.optimize()
        assert not opt.watchdog_halted
        # every epoch hit the poisoned batch; all updates were
        # discarded in-graph, so params never went NaN
        assert _params_finite(model.parameters())
        assert opt.watchdog.counts.get("nonfinite_loss", 0) >= 3
        assert opt.state["epoch"] == 4  # ran to completion

    def test_watchdog_off_pays_zero_extra_transfers(self, monkeypatch,
                                                    tmp_path):
        """The acceptance overhead clause: with the watchdog off the
        loop performs zero additional per-step host transfers — the
        single site that does them is never called, and the grad-norm
        family records nothing."""
        from bigdl_tpu.optim import Optimizer, Trigger
        from bigdl_tpu.optim.optimizer import Optimizer as OptClass
        calls = []
        orig = OptClass._watchdog_step_check

        def spy(self, *a, **k):
            calls.append(1)
            return orig(self, *a, **k)

        monkeypatch.setattr(OptClass, "_watchdog_step_check", spy)
        opt = (Optimizer(_model(), _dataset(_samples()),
                         nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_epoch(2)))
        opt.optimize()
        assert calls == []
        assert families.grad_norm().snapshot()["count"] == 0
        # zero verdicts (label children from other tests survive
        # reset() by design — zeroed in place, handles stay valid)
        assert all(v == 0 for _k, v in
                   families.training_anomalies_total().samples())

    def test_crash_out_of_retries_dumps_flight_recorder(self, tmp_path):
        from bigdl_tpu.optim import Optimizer, Trigger
        from bigdl_tpu.utils import chaos
        from bigdl_tpu.utils.chaos import FaultInjected
        ck = str(tmp_path / "ck")
        chaos.reset()
        chaos.install(fail_at_step=2)
        try:
            opt = (Optimizer(_model(), _dataset(_samples()),
                             nn.ClassNLLCriterion())
                   .set_end_when(Trigger.max_epoch(2))
                   .set_checkpoint(ck, Trigger.every_epoch())
                   .set_failure_retry(0))
            with pytest.raises(FaultInjected):
                opt.optimize()
        finally:
            chaos.reset()
        fr = json.loads(open(os.path.join(ck, "flight_recorder.json"))
                        .read())
        kinds = [e["kind"] for e in fr["events"]]
        assert "chaos_fault" in kinds
        dump = [e for e in fr["events"]
                if e["kind"] == "flight_recorder_dump"]
        assert dump and dump[-1]["reason"] == "crash"
        assert "FaultInjected" in dump[-1]["error"]


# --------------------------------------------------------------------------
# live /statusz on a running trainer (sidecar)
# --------------------------------------------------------------------------

class _SlowBatches:
    """Dataset transform pacing the loop so a scrape lands mid-run."""

    def __call__(self, it):
        for b in it:
            time.sleep(0.02)
            yield b


def test_statusz_live_scrape_during_optimize(tmp_path):
    from bigdl_tpu.optim import Optimizer, Trigger
    samples = _poison(_samples())  # warn-policy NaNs -> anomaly history
    ds = _dataset(samples).transform(_SlowBatches())
    opt = (Optimizer(_model(), ds, nn.ClassNLLCriterion())
           .set_end_when(Trigger.max_epoch(60))
           .set_checkpoint(str(tmp_path / "ck"), Trigger.every_epoch())
           .set_health_watchdog(nonfinite="warn")
           .set_debug_server(0))
    done = []
    t = threading.Thread(target=lambda: done.append(opt.optimize()))
    t.start()
    scraped = None
    try:
        deadline = time.time() + 120
        while time.time() < deadline and t.is_alive():
            srv = opt.debug_server
            if srv is not None:
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", srv.port, timeout=10)
                    conn.request("GET", "/statusz")
                    j = json.loads(conn.getresponse().read())
                    conn.close()
                    if (j["checkpoint"]["last_generation"] is not None
                            and j["watchdog"]["recent_verdicts"]):
                        scraped = j
                        break
                except (OSError, http.client.HTTPException):
                    pass
            time.sleep(0.05)
    finally:
        t.join(180)
    assert not t.is_alive()
    assert scraped is not None, "statusz never showed ckpt + verdicts"
    # current step, last checkpoint generation, anomaly history — the
    # acceptance triple — all in one live scrape
    assert scraped["role"] == "trainer"
    assert scraped["iteration"] >= 1
    assert scraped["checkpoint"]["last_generation"] >= 1
    assert scraped["watchdog"]["recent_verdicts"][0]["kind"] \
        == "nonfinite_loss"
    assert scraped["watchdog"]["policies"]["nonfinite"] == "warn"
    # the page is strict JSON even with a NaN loss (stringified)
    assert not isinstance(scraped["loss"], float) \
        or math.isfinite(scraped["loss"])
    # sidecar is torn down with the run
    assert opt.debug_server is None


# --------------------------------------------------------------------------
# debugz endpoints over HTTP (serve.py server + unit logic)
# --------------------------------------------------------------------------

def _http(port, method, path, body=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path, body)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


@pytest.fixture()
def serve_httpd():
    from bigdl_tpu.examples.serve import make_server
    from bigdl_tpu.optim.predictor import PredictionService
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    service = PredictionService(model, concurrency=2)
    server = make_server(service, "127.0.0.1", 0,
                         statusz_fn=lambda: {"role": "server",
                                             "model": "m.bigdl"})
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server
    server.shutdown()
    server.server_close()


class TestDebugzHttp:
    def test_healthz_reports_draining_non_200(self, serve_httpd):
        port = serve_httpd.server_port
        status, body = _http(port, "GET", "/healthz")
        assert status == 200 and json.loads(body) == {"status": "ok"}
        serve_httpd.health_state["draining"] = True
        status, body = _http(port, "GET", "/healthz")
        assert status == 503
        assert json.loads(body) == {"status": "draining"}
        # and back: the flag, not a latch, drives the answer
        serve_httpd.health_state["draining"] = False
        status, _ = _http(port, "GET", "/healthz")
        assert status == 200

    def test_statusz_and_tracez_on_serve_server(self, serve_httpd):
        port = serve_httpd.server_port
        with tracing.span("serving/enqueue"):
            pass
        status, body = _http(port, "GET", "/statusz")
        j = json.loads(body)
        assert status == 200
        assert j["role"] == "server" and j["model"] == "m.bigdl"
        assert j["telemetry_enabled"] is True
        assert "events" in j and "uptime_s" in j
        status, body = _http(port, "GET", "/tracez?limit=5")
        j = json.loads(body)
        assert status == 200 and j["limit"] == 5
        assert any(s["name"] == "serving/enqueue" for s in j["spans"])
        status, _ = _http(port, "GET", "/tracez?limit=bogus")
        assert status == 400
        # limit=0 means "counters only", not "the whole ring"
        status, body = _http(port, "GET", "/tracez?limit=0")
        j = json.loads(body)
        assert status == 200 and j["spans"] == [] and j["buffered"] >= 1

    def test_profilez_returns_nonempty_logdir(self, serve_httpd,
                                              tmp_path):
        port = serve_httpd.server_port
        body = json.dumps({"duration_s": 0.05,
                           "logdir": str(tmp_path / "prof")}).encode()
        status, data = _http(port, "POST", "/profilez", body)
        assert status == 200, data
        j = json.loads(data)
        assert j["logdir"] == str(tmp_path / "prof")
        assert j["files"] >= 1
        n_files = sum(len(fs) for _r, _d, fs in os.walk(j["logdir"]))
        assert n_files >= 1
        # a second capture works (start/stop correctly paired)
        status, data = _http(port, "POST", "/profilez",
                             json.dumps({"duration_s": 0.05}).encode())
        assert status == 200, data

    def test_profilez_rejects_bad_body(self, serve_httpd):
        port = serve_httpd.server_port
        status, data = _http(port, "POST", "/profilez", b"not json")
        assert status == 400 and b"error" in data
        status, data = _http(port, "POST", "/profilez", b"[1, 2]")
        assert status == 400

    def test_profilez_concurrent_capture_busy(self):
        dz = Debugz()
        started = threading.Event()
        result = {}

        def long_capture():
            started.set()
            result["r"] = dz.profilez(duration_s=1.0)

        t = threading.Thread(target=long_capture)
        t.start()
        started.wait(5)
        time.sleep(0.2)  # let the lock be taken
        with pytest.raises(ProfileBusyError):
            dz.profilez(duration_s=0.05)
        t.join(30)
        assert result["r"]["files"] >= 1

    def test_sidecar_server_serves_metrics_and_statusz(self):
        srv = DebugzServer(Debugz(
            statusz_fn=lambda: {"role": "trainer", "iteration": 7}))
        srv.start()
        try:
            status, body = _http(srv.port, "GET", "/statusz")
            j = json.loads(body)
            assert status == 200 and j["iteration"] == 7
            status, body = _http(srv.port, "GET", "/metrics")
            assert status == 200
            assert b"# TYPE training_nonfinite_total counter" in body
            status, _ = _http(srv.port, "GET", "/healthz")
            assert status == 200
            status, _ = _http(srv.port, "GET", "/nope")
            assert status == 404
        finally:
            srv.stop()

    def test_broken_statusz_provider_degrades_not_500(self):
        def boom():
            raise RuntimeError("provider died")
        dz = Debugz(statusz_fn=boom)
        page = dz.statusz()
        assert "provider died" in page["statusz_error"]


# --------------------------------------------------------------------------
# satellites: profile_trace reentrancy, xla_cost zero-vs-missing
# --------------------------------------------------------------------------

class TestProfileTraceReentrancy:
    def test_recovers_from_orphaned_trace(self, tmp_path):
        from bigdl_tpu.optim.profiling import profile_trace
        # simulate a crashed capture that never stopped
        jax.profiler.start_trace(str(tmp_path / "orphan"))
        with profile_trace(str(tmp_path / "a")):
            pass  # must not raise "already started"
        # profiler is free again: a plain start/stop pair works
        jax.profiler.start_trace(str(tmp_path / "b"))
        jax.profiler.stop_trace()

    def test_always_pairs_stop_on_body_exception(self, tmp_path):
        from bigdl_tpu.optim.profiling import profile_trace
        with pytest.raises(RuntimeError, match="body blew up"):
            with profile_trace(str(tmp_path / "c")):
                raise RuntimeError("body blew up")
        # the trace was stopped despite the exception
        with profile_trace(str(tmp_path / "d")):
            pass

    def test_repeated_captures(self, tmp_path):
        from bigdl_tpu.optim.profiling import profile_trace
        for i in range(3):
            with profile_trace(str(tmp_path / f"r{i}")):
                jax.block_until_ready(jax.numpy.zeros((1,)))


class _FakeCompiled:
    def __init__(self, cost):
        self._cost = cost

    def cost_analysis(self):
        return self._cost


class TestXlaCostZeroVsMissing:
    def test_zero_is_reported_not_none(self):
        from bigdl_tpu.utils.xla_cost import compiled_bytes, compiled_flops
        c = _FakeCompiled({"flops": 0.0, "bytes accessed": 0})
        assert compiled_flops(c) == 0.0
        assert compiled_bytes(c) == 0.0

    def test_missing_key_is_none(self):
        from bigdl_tpu.utils.xla_cost import compiled_bytes, compiled_flops
        c = _FakeCompiled({"something else": 5.0})
        assert compiled_flops(c) is None
        assert compiled_bytes(c) is None

    def test_negative_sentinel_and_junk_are_none(self):
        from bigdl_tpu.utils.xla_cost import compiled_flops
        assert compiled_flops(_FakeCompiled({"flops": -1.0})) is None
        assert compiled_flops(_FakeCompiled({"flops": "n/a"})) is None

    def test_list_wrapped_and_raising_analysis(self):
        from bigdl_tpu.utils.xla_cost import compiled_flops
        assert compiled_flops(_FakeCompiled([{"flops": 3.0}])) == 3.0

        class Raising:
            def cost_analysis(self):
                raise RuntimeError("unavailable on this backend")
        assert compiled_flops(Raising()) is None
        assert compiled_flops(_FakeCompiled([])) is None


# --------------------------------------------------------------------------
# serving-layer events + snapshot embedding
# --------------------------------------------------------------------------

def test_admission_shed_lands_in_flight_recorder():
    from bigdl_tpu.serving.admission import (
        BoundedRequestQueue, Request, RequestSheddedError,
    )
    q = BoundedRequestQueue(1, policy="shed_oldest")
    first = Request(np.zeros(2, np.float32))
    q.put(first)
    q.put(Request(np.ones(2, np.float32)))  # sheds `first`
    with pytest.raises(RequestSheddedError):
        first.future.result(1)
    shed = [e for e in events.recent_events()
            if e["kind"] == "admission_shed"]
    assert shed and shed[0]["capacity"] == 1


def test_json_snapshot_embeds_event_summary():
    from bigdl_tpu.telemetry.export import json_snapshot
    events.record_event("retry", error="x")
    events.record_event("retry", error="y")
    events.record_event("preemption", epoch=1)
    snap = json.loads(json.dumps(json_snapshot(), default=str))
    assert snap["events"]["by_kind"] == {"retry": 2, "preemption": 1}
    assert snap["events"]["buffered"] == 3
    assert snap["events"]["recent"][-1]["kind"] == "preemption"
