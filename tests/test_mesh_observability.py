"""Mesh observability: collective-comm accounting, fleet telemetry +
straggler detection, HBM watermarks and OOM forensics.

Covers PR 7's three tentpoles end to end on the 8-virtual-device CPU
mesh: exact trace-time byte totals per {op, axis} with the HLO
cross-check, the fleet table (allgather and file-merge transports)
feeding the watchdog's ``straggler`` class, and the
RESOURCE_EXHAUSTED → forensics-artifact pipeline through the chaos
seam — plus the off-by-default discipline every telemetry PR asserts.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import collectives as tcoll
from bigdl_tpu.telemetry import families as tfam
from bigdl_tpu.telemetry import fleet as tfleet
from bigdl_tpu.telemetry import runtime as truntime
from bigdl_tpu.telemetry import perf as tperf
from bigdl_tpu.telemetry.health import HealthWatchdog
from bigdl_tpu.utils import chaos
from bigdl_tpu.utils.xla_cost import (
    collective_hlo_bytes, comm_bytes_from_hlo_text, cost_breakdown,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.enable()
    telemetry.reset()
    truntime.reset_hbm_peaks()
    chaos.reset()
    yield
    chaos.reset()
    telemetry.reset()
    telemetry.disable()


def _shard_map(f, mesh, in_specs, out_specs):
    """Version-compat shard_map (this env's jax predates
    ``jax.shard_map``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def _mesh1d(axis="x"):
    return Mesh(np.array(jax.devices()[:8]), (axis,))


def _bytes_of(op, axis="x"):
    return tfam.collective_bytes_total().labels(op, axis).value()


def _calls_of(op, axis="x"):
    return tfam.collective_calls_total().labels(op, axis).value()


# ---------------------------------------------------------------------------
# collective accounting
# ---------------------------------------------------------------------------

def _collective_zoo(a):
    """One of each wrapped collective over a local [1, 64] f32 shard
    (256 bytes)."""
    s = tcoll.psum(a, "x")
    g = tcoll.all_gather(a, "x", tiled=True)
    p = tcoll.ppermute(a, "x", [(i, (i + 1) % 8) for i in range(8)])
    rs = tcoll.psum_scatter(jnp.broadcast_to(a[0], (8, 64)), "x",
                            tiled=True)
    return s.sum() + g.sum() + p.sum() + rs.sum()


def test_collective_bytes_exact_per_op_axis():
    """Trace-time accounting: exact per-device OUTPUT payload bytes
    per {op, axis}, one call count per site per trace."""
    mesh = _mesh1d()
    fn = jax.jit(_shard_map(_collective_zoo, mesh, P("x"), P()))
    fn.lower(jnp.ones((8, 64), jnp.float32)).compile()
    # local shard [1,64] f32 = 256 B
    assert _bytes_of("psum") == 256.0
    assert _bytes_of("all_gather") == 8 * 256.0
    assert _bytes_of("ppermute") == 256.0
    assert _bytes_of("reduce_scatter") == 8 * 256.0 / 8
    for op in ("psum", "all_gather", "ppermute", "reduce_scatter"):
        assert _calls_of(op) == 1.0, op


def test_collective_all_to_all_and_pmean_bytes():
    mesh = _mesh1d()

    def f(a):
        # local [8, 16] f32 = 512 B
        t = tcoll.all_to_all(a, "x", split_axis=0, concat_axis=1,
                             tiled=True)
        m = tcoll.pmean(a, "x")
        return t.sum() + m.sum()

    fn = jax.jit(_shard_map(f, mesh, P(None, "x"), P()))
    fn.lower(jnp.ones((8, 128), jnp.float32)).compile()
    assert _bytes_of("all_to_all") == 512.0
    assert _bytes_of("pmean") == 512.0


def test_collective_wrappers_off_by_default():
    """Disabled telemetry: the wrapper IS the bare collective — no
    bytes, no calls recorded, identical numerics."""
    mesh = _mesh1d()
    x = jnp.arange(8 * 64, dtype=jnp.float32).reshape(8, 64)
    fn = jax.jit(_shard_map(_collective_zoo, mesh, P("x"), P()))
    telemetry.disable()
    try:
        out = fn(x)
    finally:
        telemetry.enable()
    bare = jax.jit(_shard_map(
        lambda a: (jax.lax.psum(a, "x").sum()
                   + jax.lax.all_gather(a, "x", tiled=True).sum()
                   + jax.lax.ppermute(
                       a, "x", [(i, (i + 1) % 8) for i in range(8)]).sum()
                   + jax.lax.psum_scatter(
                       jnp.broadcast_to(a[0], (8, 64)), "x",
                       tiled=True).sum()),
        mesh, P("x"), P()))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(bare),
                               rtol=1e-6)
    for op in ("psum", "all_gather", "ppermute", "reduce_scatter"):
        assert _bytes_of(op) == 0.0, op
        assert _calls_of(op) == 0.0, op


def test_collective_accounting_matches_hlo_cross_check():
    """Wrapper totals vs the compiled module's collective output
    payloads: the two sides of the same budget must agree within 10%
    on a program whose collectives are all explicit."""
    mesh = _mesh1d()
    fn = jax.jit(_shard_map(_collective_zoo, mesh, P("x"), P()))
    compiled = fn.lower(jnp.ones((8, 64), jnp.float32)).compile()
    wrapper_total = sum(
        v for _k, v in tfam.collective_bytes_total().samples())
    hlo = collective_hlo_bytes(compiled)
    assert hlo is not None and hlo["total"] > 0
    assert abs(wrapper_total - hlo["total"]) <= 0.10 * hlo["total"], (
        wrapper_total, hlo)


def test_ring_attention_sp_step_cross_check():
    """Satellite: the HLO cross-check within tolerance on a compiled
    sp step (ring attention) — the wrappers see every ppermute the
    ring issues, and so does the compiled module."""
    from bigdl_tpu.parallel import ring_self_attention
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    q = jnp.ones((2, 2, 64, 16), jnp.float32)

    fn = jax.jit(lambda q, k, v: ring_self_attention(
        q, k, v, mesh, causal=False))
    compiled = fn.lower(q, q, q).compile()
    wrapper_total = sum(
        v for (op, _ax), v in tfam.collective_bytes_total().samples()
        if op == "ppermute")
    hlo = collective_hlo_bytes(compiled)
    assert wrapper_total > 0
    assert hlo is not None
    permute = hlo.get("collective-permute", 0.0)
    assert abs(wrapper_total - permute) <= 0.10 * max(permute, 1.0), (
        wrapper_total, hlo)


def test_comm_bytes_from_hlo_text_units():
    text = "\n".join([
        "ENTRY main {",
        "  %p = f32[8,16]{1,0} parameter(0)",
        "  %ar = f32[8,16]{1,0} all-reduce(%p), to_apply=%add",
        "  %ag.s = (f32[8]{0}, f32[64]{0}) all-gather-start(%q)",
        "  %ag.d = f32[64]{0} all-gather-done(%ag.s)",
        "  %tup = (bf16[4]{0}, bf16[4]{0}) collective-permute(%a, %b)",
        "  %weird = zz99q[8] all-to-all(%p)",
        "  %use = f32[8,16]{1,0} add(%ar, %p)",
        "}",
    ])
    out = comm_bytes_from_hlo_text(text)
    assert out["all-reduce"] == 8 * 16 * 4
    assert out["all-gather"] == 64 * 4          # the -done, not -start
    assert out["collective-permute"] == 2 * 4 * 2
    assert out["total"] == (8 * 16 * 4) + (64 * 4) + (2 * 4 * 2)
    assert comm_bytes_from_hlo_text("x = f32[8] add(a, b)") == {
        "total": 0.0}


def test_cost_breakdown_reports_comm_bytes():
    # no collectives: comm_bytes is a legitimate 0.0, not None
    c = jax.jit(lambda x: x * 2).lower(jnp.ones((4,))).compile()
    assert cost_breakdown(c)["comm_bytes"] == 0.0
    mesh = _mesh1d()
    fn = jax.jit(_shard_map(lambda a: tcoll.psum(a, "x").sum(),
                            mesh, P("x"), P()))
    c2 = fn.lower(jnp.ones((8, 64), jnp.float32)).compile()
    assert cost_breakdown(c2)["comm_bytes"] > 0


def test_grad_allreduce_bytes_estimator():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.parallel.sharding import (
        ShardingRules, grad_allreduce_bytes,
    )
    model = nn.Linear(12, 16)  # weight [16,12] + bias [16], f32
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    est = grad_allreduce_bytes(model, mesh)
    assert est["bytes_per_step"] == (16 * 12 + 16) * 4
    assert est["param_leaves"] == 2
    fmesh = Mesh(np.array(jax.devices()[:8]), ("fsdp",))
    est2 = grad_allreduce_bytes(model, fmesh, ShardingRules(fsdp=True))
    # both leaves shard their 16-dim over 8 devices -> bytes / 8
    assert est2["bytes_per_step"] == (16 * 12 + 16) * 4 / 8


# ---------------------------------------------------------------------------
# fleet telemetry + straggler detection
# ---------------------------------------------------------------------------

def _host(process, wall, wait, **kw):
    row = {"process": process, "time": 0.0, "step_wall_s": wall,
           "data_wait_s": wait, "iterations": 1.0,
           "rss_bytes": 1.0, "hbm_bytes_in_use": 0.0}
    row.update(kw)
    return row


def test_fleet_table_single_host_is_balanced():
    t = tfleet.fleet_table([_host(0, 0.2, 0.01)])
    assert t["processes"] == 1
    assert t["skew"] == pytest.approx(1.0)
    assert t["slowest_process"] == 0


def test_fleet_table_names_the_lockstep_straggler():
    """SPMD lockstep: every host's wall is identical; the straggler is
    the one whose wall is data-wait while the others wait in the
    collective."""
    rows = [_host(0, 0.26, 0.002), _host(1, 0.26, 0.25),
            _host(2, 0.26, 0.003), _host(3, 0.26, 0.001)]
    t = tfleet.fleet_table(rows)
    assert t["slowest_process"] == 1
    assert t["wait_skew"] > 2.0
    assert t["skew"] == t["wait_skew"]
    assert t["step_skew"] == pytest.approx(1.0)


def test_fleet_table_no_false_positive_on_uniform_tiny_waits():
    rows = [_host(i, 0.2, 0.001 + 0.0002 * i) for i in range(4)]
    t = tfleet.fleet_table(rows)
    # waits are noise (under the 5%-of-wall floor): skew must stay low
    assert t["skew"] < 2.0


def test_fleet_table_async_straggler_by_wall():
    rows = [_host(0, 0.2, 0.0), _host(1, 0.9, 0.0), _host(2, 0.21, 0.0)]
    t = tfleet.fleet_table(rows)
    assert t["slowest_process"] == 1
    assert t["step_skew"] > 2.0


def test_host_snapshot_write_and_merge(tmp_path):
    d = str(tmp_path)
    tfleet.write_host_snapshot(d, _host(0, 0.25, 0.002, time=1e9))
    tfleet.write_host_snapshot(d, _host(1, 0.25, 0.22, time=1e9))
    # corrupt file and a stale host must both be ignored
    with open(os.path.join(d, "fleet_host_9.json"), "w") as f:
        f.write("{not json")
    tfleet.write_host_snapshot(d, _host(2, 9.9, 9.9, time=1.0))
    merged = tfleet.merge_host_snapshots(d, max_age_s=10**9)
    assert merged is not None
    assert merged["processes"] == 2
    assert merged["slowest_process"] == 1
    assert merged["skew"] > 2.0
    assert tfleet.merge_host_snapshots(str(tmp_path / "empty")) is None


def test_watchdog_straggler_verdict():
    wd = HealthWatchdog(straggler="warn", straggler_ratio=2.0)
    assert wd.observe_fleet(7, 1.4, 0) == []
    v = wd.observe_fleet(9, 3.5, 2, "3 host(s)")
    assert len(v) == 1 and v[0].kind == "straggler"
    assert wd.counts["straggler"] == 1
    assert not wd.halt_requested  # warn policy keeps training
    from bigdl_tpu.telemetry import events as tev
    recent = [e for e in tev.recent_events()
              if e["kind"] == "watchdog"
              and e.get("anomaly") == "straggler"]
    assert recent and "process 2" in recent[-1]["message"]
    assert tfam.training_anomalies_total().labels(
        "straggler").value() == 1.0


def test_watchdog_straggler_halt_policy():
    wd = HealthWatchdog(straggler="checkpoint_and_halt",
                        straggler_ratio=2.0)
    wd.observe_fleet(3, 9.0, 1)
    assert wd.halt_requested


def test_fleet_monitor_rate_limit_and_status():
    fm = tfleet.FleetMonitor(every_n_windows=2)
    assert fm.status()["samples"] == 0
    assert fm.contribute(0.2, 0.01, 1) is None      # window 1: skipped
    table = fm.contribute(0.2, 0.01, 1)             # window 2: sampled
    assert table is not None and table["processes"] == 1
    st = fm.status()
    assert st["samples"] == 1 and st["windows_seen"] == 2
    assert st["hosts"][0]["process"] == 0
    assert tfam.fleet_step_skew().value() == pytest.approx(1.0)
    json.dumps(st)  # /statusz must be able to serialize it


def test_fleet_monitor_snapshot_dir_and_watchdog(tmp_path):
    wd = HealthWatchdog(straggler="warn", straggler_ratio=1.0)
    fm = tfleet.FleetMonitor(snapshot_dir=str(tmp_path))
    fm.contribute(0.2, 0.01, 1, step=4, watchdog=wd)
    # skew 1.0 >= ratio 1.0: verdict fired with the monitor's numbers
    assert wd.counts.get("straggler") == 1
    files = [f for f in os.listdir(str(tmp_path))
             if f.startswith("fleet_host_")]
    assert files == ["fleet_host_0.json"]
    merged = tfleet.merge_host_snapshots(str(tmp_path))
    assert merged["processes"] == 1


def _mini_dataset(n=32, batch=16):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(6,)).astype(np.float32),
                      int(rng.integers(1, 5))) for _ in range(n)]
    return DataSet.array(samples).transform(SampleToMiniBatch(batch))


def _mini_model():
    import bigdl_tpu.nn as nn
    return nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 4),
                         nn.LogSoftMax())


def test_optimizer_fleet_statusz_e2e():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import Optimizer, Trigger
    opt = (Optimizer(_mini_model(), _mini_dataset(),
                     nn.ClassNLLCriterion())
           .set_end_when(Trigger.max_epoch(2))
           .set_fleet_monitor())
    opt.optimize()
    st = opt.statusz()
    fleet = st["fleet"]
    assert fleet["processes"] == 1
    assert fleet["samples"] >= 1
    host = fleet["hosts"][0]
    assert host["step_wall_s"] > 0
    assert "skew" in fleet and "slowest_process" in fleet
    json.dumps(st, default=str)


def test_set_fleet_monitor_rejects_instance_plus_kwargs():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import Optimizer
    opt = Optimizer(_mini_model(), _mini_dataset(),
                    nn.ClassNLLCriterion())
    with pytest.raises(ValueError):
        opt.set_fleet_monitor(tfleet.FleetMonitor(), every_n_windows=2)


# ---------------------------------------------------------------------------
# HBM watermarks + OOM forensics
# ---------------------------------------------------------------------------

class _FakeDevice:
    platform = "tpu"
    id = 0

    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_hbm_peak_sampled_watermark(monkeypatch):
    dev = _FakeDevice({"bytes_in_use": 100, "bytes_limit": 1000})
    monkeypatch.setattr(jax, "local_devices", lambda: [dev])
    truntime.sample_runtime()
    peak = tfam.hbm_bytes_peak()
    assert peak.labels("tpu:0").value() == 100.0
    dev._stats = {"bytes_in_use": 40}
    truntime.sample_runtime()
    assert peak.labels("tpu:0").value() == 100.0  # high-water holds
    dev._stats = {"bytes_in_use": 250}
    truntime.sample_runtime()
    assert peak.labels("tpu:0").value() == 250.0
    assert truntime.hbm_peaks()["tpu:0"] == 250.0
    truntime.reset_hbm_peaks()
    assert truntime.hbm_peaks() == {}


def test_hbm_peak_prefers_backend_peak(monkeypatch):
    """Satellite: when memory_stats() carries peak_bytes_in_use the
    backend's own (exact) watermark wins over the sampled one."""
    dev = _FakeDevice({"bytes_in_use": 100, "peak_bytes_in_use": 700})
    monkeypatch.setattr(jax, "local_devices", lambda: [dev])
    truntime.sample_runtime()
    assert tfam.hbm_bytes_peak().labels("tpu:0").value() == 700.0
    # a later (smaller) backend peak is authoritative too: the backend
    # may reset its watermark; we mirror, not max over, exact sources
    dev._stats = {"bytes_in_use": 10, "peak_bytes_in_use": 650}
    truntime.sample_runtime()
    assert tfam.hbm_bytes_peak().labels("tpu:0").value() == 650.0


def test_hbm_sampling_skips_missing_keys(monkeypatch):
    dev = _FakeDevice({"unrelated": 1})
    monkeypatch.setattr(jax, "local_devices", lambda: [dev])
    truntime.sample_runtime()  # must not raise, must not invent a peak
    assert truntime.hbm_peaks() == {}


def test_oom_forensics_report_shape():
    rep = truntime.oom_forensics_report(
        error="RESOURCE_EXHAUSTED: boom",
        last_window={"iterations": 2, "wall_s": 0.5})
    for key in ("kind", "time", "pid", "error", "devices",
                "hbm_bytes_peak", "live_arrays", "last_window"):
        assert key in rep, key
    assert rep["kind"] == "oom_forensics"
    census = rep["live_arrays"]
    if census.get("available"):
        assert census["arrays"] >= 0
        for g in census["top_groups"]:
            assert set(g) == {"dtype", "shape", "count", "bytes"}
    json.dumps(rep, default=str)


def test_chaos_oom_seam_env(monkeypatch):
    from bigdl_tpu.optim.optimizer import _is_oom
    monkeypatch.setenv("BIGDL_TPU_CHAOS_OOM", "1")
    chaos.reset()
    with pytest.raises(chaos.FaultInjected) as ei:
        chaos.on_step(1)
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    assert _is_oom(ei.value)
    chaos.reset()
    monkeypatch.delenv("BIGDL_TPU_CHAOS_OOM")
    assert not _is_oom(ValueError("no groups cover parameter"))


def test_optimizer_oom_forensics_e2e(tmp_path):
    """Chaos-injected RESOURCE_EXHAUSTED at step 3: the run retries
    through it AND leaves the oom event + forensics artifact beside
    the flight recorder."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.telemetry import events as tev
    ckdir = str(tmp_path / "ck")
    chaos.install(oom_at_step=3)
    opt = (Optimizer(_mini_model(), _mini_dataset(),
                     nn.ClassNLLCriterion())
           .set_end_when(Trigger.max_epoch(3))
           .set_checkpoint(ckdir, Trigger.several_iteration(1))
           .set_failure_retry(3, interval_s=300, backoff_s=0.01,
                              backoff_cap_s=0.02))
    opt.optimize()
    chaos.reset()
    assert tev.event_counts().get("oom", 0) == 1
    path = os.path.join(ckdir, "oom_forensics.json")
    assert os.path.isfile(path)
    with open(path) as f:
        rep = json.load(f)
    assert rep["kind"] == "oom_forensics"
    assert "RESOURCE_EXHAUSTED" in rep["error"]
    assert "live_arrays" in rep and "devices" in rep
    oom_events = [e for e in tev.recent_events() if e["kind"] == "oom"]
    assert oom_events and "RESOURCE_EXHAUSTED" in oom_events[0]["error"]


def test_real_oom_error_string_detected():
    from bigdl_tpu.optim.optimizer import _is_oom
    assert _is_oom(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "17179869184 bytes."))
    assert _is_oom(RuntimeError("XLA:TPU Out of memory allocating"))
    assert not _is_oom(RuntimeError("connection reset by peer"))


# ---------------------------------------------------------------------------
# statusz events counters + comm roofline
# ---------------------------------------------------------------------------

def test_statusz_events_expose_ring_counters():
    from bigdl_tpu.telemetry import events as tev
    from bigdl_tpu.telemetry.debugz import Debugz
    tev.record_event("retry", error="x")
    ev = Debugz().statusz()["events"]
    for key in ("buffered", "capacity", "dropped", "counts", "recent"):
        assert key in ev, key
    assert ev["capacity"] == tev.event_capacity() > 0
    assert ev["buffered"] >= 1
    assert ev["counts"].get("retry") == 1


def test_roofline_comm_bound_verdict():
    # comm floor dominates: 1 GB over 200 GB/s = 5 ms vs 1 ms compute
    roof = tperf.roofline_verdict(
        1e12, 1e8, 1e15, 1e12,
        comm_bytes_per_step=1e9, ici_bytes_per_s=200e9)
    assert roof["verdict"] == "comm_bound"
    assert roof["min_comm_s"] == pytest.approx(5e-3)
    assert roof["attainable_step_s"] == pytest.approx(5e-3)
    # without comm the two-floor behavior is unchanged
    old = tperf.roofline_verdict(1e12, 1e8, 1e15, 1e12)
    assert old["verdict"] == "compute_bound"
    assert "min_comm_s" not in old


def test_attribution_report_comm_contributor():
    records = [
        {"iterations": 1, "wall_s": 0.1, "data_wait_s": 0.01,
         "host_staging_s": 0.01, "device_compute_s": 0.07,
         "readback_s": 0.01}
        for _ in range(3)
    ]
    rep = tperf.attribution_report(
        records, flops_per_step=1e12, bytes_per_step=1e9,
        peak_spec_flops=197e12, hbm_bytes_per_s=819e9,
        comm_bytes_per_step=5e9, ici_bytes_per_s=200e9)
    assert rep["comm"]["bytes_per_step"] == 5e9
    assert rep["comm"]["min_comm_s"] == pytest.approx(25e-3)
    assert 0 < rep["comm"]["fraction_of_device_compute"] <= 1.0
    assert rep["roofline"]["verdict"] == "comm_bound"


def test_device_ici_table():
    assert tperf.device_ici_bytes_per_s("TPU v5e") == 200e9
    assert tperf.device_ici_bytes_per_s("weird accelerator") is None
