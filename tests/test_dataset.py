"""Data pipeline tests (reference dataset/ specs, SURVEY §2.7)."""

import numpy as np
import pytest

from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import DistributedDataSet


def _samples(n):
    return [Sample(np.full((2,), i, np.float32), i) for i in range(n)]


def test_distributed_transform_preserves_shard():
    """Regression: .transform() must not re-shard an already-sharded
    DistributedDataSet (it used to re-run __init__ on the shard)."""
    ds = DistributedDataSet(_samples(16), shuffle=False,
                            process_index=1, process_count=4)
    shard_before = [s.feature[0] for s in ds.data(train=False)]
    out = ds.transform(SampleToMiniBatch(2))
    assert out.size() == 16  # global size preserved
    assert out.process_index == 1 and out.process_count == 4
    batches = list(out.data(train=False))
    got = np.concatenate([np.asarray(b.input)[:, 0] for b in batches])
    np.testing.assert_array_equal(sorted(got), sorted(shard_before))


def test_round_robin_sharding_partitions_data():
    all_feats = []
    for p in range(3):
        ds = DistributedDataSet(_samples(10), shuffle=False,
                                process_index=p, process_count=3)
        all_feats.extend(s.feature[0] for s in ds.data(train=False))
    np.testing.assert_array_equal(sorted(all_feats), np.arange(10))


def test_local_dataset_chained_transforms():
    ds = DataSet.array(_samples(8), shuffle=False) \
        .transform(SampleToMiniBatch(4))
    batches = list(ds.data(train=False))
    assert len(batches) == 2 and batches[0].input.shape == (4, 2)


def test_row_transformer_modes():
    """Tabular rows -> tensors (reference datamining/RowTransformer:
    atomic, numeric, and grouped modes) over dicts, structured arrays,
    and namedtuples."""
    from collections import namedtuple
    from bigdl_tpu.dataset.datamining import RowToSample, RowTransformer

    rows = [{"age": 30, "scores": [1.0, 2.0], "income": 5.5, "y": 2},
            {"age": 40, "scores": [3.0, 4.0], "income": 6.5, "y": 1}]
    atomic = RowTransformer.atomic(["age", "scores"])
    out = list(atomic(iter(rows)))
    np.testing.assert_allclose(out[0]["age"], [30.0])
    np.testing.assert_allclose(out[1]["scores"], [3.0, 4.0])

    grouped = RowTransformer({"num": ["age", "income"], "s": ["scores"]})
    g = list(grouped(iter(rows)))[0]
    np.testing.assert_allclose(g["num"], [30.0, 5.5])
    np.testing.assert_allclose(g["s"], [1.0, 2.0])

    samples = list(RowToSample(["age", "scores", "income"], "y")(
        iter(rows)))
    np.testing.assert_allclose(samples[0].feature, [30.0, 1.0, 2.0, 5.5])
    assert samples[0].label == 2 and samples[1].label == 1

    # numpy structured arrays
    arr = np.array([(1.5, 2.5, 3)], dtype=[("a", "f4"), ("b", "f4"),
                                           ("y", "i4")])
    s, = RowToSample(["a", "b"], "y")(iter(arr))
    np.testing.assert_allclose(s.feature, [1.5, 2.5])
    assert s.label == 3

    # namedtuples (attribute access fallback)
    Row = namedtuple("Row", ["a", "b"])
    out, = RowTransformer.numeric(["a", "b"])(iter([Row(7.0, 8.0)]))
    np.testing.assert_allclose(out["all"], [7.0, 8.0])


def test_row_transformer_missing_field_raises():
    """A missing field must fail loudly, not silently resolve to an
    unrelated attribute of the row object (regression: pandas
    Series.size was returned for a missing 'size' column)."""
    from bigdl_tpu.dataset.datamining import RowTransformer
    t = RowTransformer.numeric(["age", "size"])
    with pytest.raises((KeyError, AttributeError)):
        t.transform_row({"age": 30.0, "income": 5.5})
