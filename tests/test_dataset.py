"""Data pipeline tests (reference dataset/ specs, SURVEY §2.7)."""

import numpy as np

from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import DistributedDataSet


def _samples(n):
    return [Sample(np.full((2,), i, np.float32), i) for i in range(n)]


def test_distributed_transform_preserves_shard():
    """Regression: .transform() must not re-shard an already-sharded
    DistributedDataSet (it used to re-run __init__ on the shard)."""
    ds = DistributedDataSet(_samples(16), shuffle=False,
                            process_index=1, process_count=4)
    shard_before = [s.feature[0] for s in ds.data(train=False)]
    out = ds.transform(SampleToMiniBatch(2))
    assert out.size() == 16  # global size preserved
    assert out.process_index == 1 and out.process_count == 4
    batches = list(out.data(train=False))
    got = np.concatenate([np.asarray(b.input)[:, 0] for b in batches])
    np.testing.assert_array_equal(sorted(got), sorted(shard_before))


def test_round_robin_sharding_partitions_data():
    all_feats = []
    for p in range(3):
        ds = DistributedDataSet(_samples(10), shuffle=False,
                                process_index=p, process_count=3)
        all_feats.extend(s.feature[0] for s in ds.data(train=False))
    np.testing.assert_array_equal(sorted(all_feats), np.arange(10))


def test_local_dataset_chained_transforms():
    ds = DataSet.array(_samples(8), shuffle=False) \
        .transform(SampleToMiniBatch(4))
    batches = list(ds.data(train=False))
    assert len(batches) == 2 and batches[0].input.shape == (4, 2)
