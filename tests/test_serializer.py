"""Tests for model persistence + torch import (reference
utils/serializer round-trip specs + TorchFile/Caffe loader specs)."""

import numpy as np
import pytest

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module
from bigdl_tpu.utils import set_seed
from bigdl_tpu.utils.serializer import (
    save_module, load_module, save_weights, load_weights,
)
from bigdl_tpu.interop import load_torch_state_dict


def _cnn():
    set_seed(5)
    return nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(8),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2),
        nn.Reshape((8 * 4 * 4,)),
        nn.Linear(8 * 4 * 4, 10),
        nn.LogSoftMax(),
    )


def test_save_load_module_roundtrip(tmp_path):
    m = _cnn().eval_mode()
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 8, 8, 3)), jnp.float32)
    want = np.asarray(m.forward(x))
    p = str(tmp_path / "model.bigdl")
    m.save(p)
    m2 = Module.load(p)
    assert type(m2) is type(m)
    got = np.asarray(m2.eval_mode().forward(x))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def _rewrite_manifest(path, mutate):
    import json
    with np.load(path, allow_pickle=False) as z:
        manifest = json.loads(z["__manifest__"].tobytes().decode())
        arrays = {k: z[k] for k in z.files if k != "__manifest__"}
    mutate(manifest)
    with open(path, "wb") as f:
        np.savez(f, __manifest__=np.frombuffer(
            json.dumps(manifest).encode(), np.uint8), **arrays)


def test_load_module_rejects_bad_version(tmp_path):
    p = str(tmp_path / "bad.bigdl")
    nn.Linear(2, 2).save(p)
    _rewrite_manifest(p, lambda m: m.update(manifest_version=99))
    with pytest.raises(ValueError, match="manifest version"):
        Module.load(p)


def test_load_module_migration_hook(tmp_path):
    """Old-version manifests upgrade through registered migrations
    (≙ the reference serializer's version converters,
    ModuleSerializer.scala:36-223)."""
    from bigdl_tpu.utils import serializer as S
    p = str(tmp_path / "old.bigdl")
    m = nn.Linear(3, 2)
    m.save(p)

    def downgrade(man):
        man["manifest_version"] = 0
        man["module"]["cls"] = man["module"].pop("class")

    _rewrite_manifest(p, downgrade)
    with pytest.raises(ValueError, match="no migration"):
        Module.load(p)

    def migrate_0_to_1(man):
        man = dict(man)
        man["module"] = dict(man["module"])
        man["module"]["class"] = man["module"].pop("cls")
        man["manifest_version"] = 1
        return man

    S.register_migration(0, migrate_0_to_1)
    try:
        m2 = Module.load(p)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 3)),
                        jnp.float32)
        np.testing.assert_allclose(np.asarray(m2.forward(x)),
                                   np.asarray(m.forward(x)))
    finally:
        S._MIGRATIONS.pop(0, None)


def test_load_module_refuses_foreign_class(tmp_path):
    """Class resolution must not import arbitrary modules (code exec)."""
    p = str(tmp_path / "evil.bigdl")
    nn.Linear(2, 2).save(p)
    _rewrite_manifest(
        p, lambda m: m["module"].update({"class": "os:system"}))
    with pytest.raises(ValueError, match="refusing"):
        Module.load(p)


def test_load_module_refuses_legacy_pickle(tmp_path):
    p = str(tmp_path / "legacy.bigdl")
    with open(p, "wb") as f:
        np.savez(f, __treedef__=np.zeros(4, np.uint8))
    with pytest.raises(ValueError, match="pickle"):
        Module.load(p)


def test_no_pickle_in_persistence_code():
    import inspect
    from bigdl_tpu.utils import file as file_mod
    from bigdl_tpu.utils import serializer as ser_mod
    for mod in (file_mod, ser_mod):
        src = inspect.getsource(mod)
        assert "import pickle" not in src, mod.__name__
        assert "pickle.load" not in src and "pickle.dump" not in src
        assert "allow_pickle=True" not in src


@pytest.mark.parametrize("family", ["graph", "rnn", "transformer", "moe",
                                    "ncf", "autoencoder"])
def test_roundtrip_layer_families(tmp_path, family):
    from bigdl_tpu.models import (
        Autoencoder, NeuralCF, PTBModel, lenet5_graph,
    )
    from bigdl_tpu.nn.moe import MoE
    set_seed(3)
    rng = np.random.default_rng(0)
    if family == "graph":
        m = lenet5_graph()
        x = jnp.asarray(rng.normal(size=(2, 28, 28)), jnp.float32)
    elif family == "rnn":
        m = PTBModel(50, 16)
        x = jnp.asarray(rng.integers(1, 50, size=(2, 7)))
    elif family == "transformer":
        m = nn.Sequential(nn.TransformerEncoderLayer(16, 2, 32))
        x = jnp.asarray(rng.normal(size=(2, 5, 16)), jnp.float32)
    elif family == "ncf":
        m = NeuralCF(12, 20, embed_dim=4)
        x = jnp.asarray(
            np.stack([rng.integers(1, 13, size=(5,)),
                      rng.integers(1, 21, size=(5,))], axis=-1),
            jnp.int32)
    elif family == "autoencoder":
        m = Autoencoder(class_num=8)
        x = jnp.asarray(rng.normal(size=(2, 28, 28)), jnp.float32)
    else:
        m = MoE(8, [nn.FeedForwardNetwork(8, 16) for _ in range(4)],
                top_k=2)
        x = jnp.asarray(rng.normal(size=(2, 3, 8)), jnp.float32)
    p = str(tmp_path / "m.bigdl")
    m.save(p)
    m2 = Module.load(p)
    np.testing.assert_array_equal(
        np.asarray(m.eval_mode().forward(x)),
        np.asarray(m2.eval_mode().forward(x)))


def test_checkpoint_pytree_roundtrip(tmp_path):
    """The checkpoint codec preserves nested structure without pickle,
    including tuples, int dict keys, and scalar dtypes."""
    from bigdl_tpu.utils.file import load_pytree, save_pytree
    tree = {"a": [np.arange(3), (np.float32(2.5), None)],
            1: {"nested": np.ones((2, 2), np.float32)},
            "s": "text", "flag": True}
    p = str(tmp_path / "t.npz")
    save_pytree(tree, p)
    back = load_pytree(p)
    assert back["s"] == "text" and back["flag"] is True
    assert isinstance(back["a"][1], tuple)
    np.testing.assert_array_equal(back["a"][0], np.arange(3))
    np.testing.assert_array_equal(back[1]["nested"], tree[1]["nested"])


def test_save_load_weights_roundtrip(tmp_path):
    m = _cnn()
    p = str(tmp_path / "weights.npz")
    m.save_weights(p)
    set_seed(99)  # different init
    m2 = _cnn.__wrapped__() if hasattr(_cnn, "__wrapped__") else _cnn()
    # force-different init: reinit under another seed
    m2.load_weights(p)
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(2, 8, 8, 3)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(m2.eval_mode().forward(x)),
        np.asarray(m.eval_mode().forward(x)), rtol=1e-6)


def test_load_weights_strict_mismatch(tmp_path):
    m = nn.Linear(4, 2)
    p = str(tmp_path / "w.npz")
    m.save_weights(p)
    other = nn.Linear(4, 3)
    with pytest.raises(Exception):
        other.load_weights(p)


def test_torch_import_linear_mlp():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(
        torch.nn.Linear(6, 16), torch.nn.ReLU(), torch.nn.Linear(16, 3))
    set_seed(0)
    ours = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 3))
    load_torch_state_dict(ours, tm.state_dict())
    x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
    want = tm(torch.tensor(x)).detach().numpy()
    got = np.asarray(ours.eval_mode().forward(jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_torch_import_cnn_with_bn():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(
        torch.nn.Conv2d(3, 8, 3, padding=1),
        torch.nn.BatchNorm2d(8),
        torch.nn.ReLU(),
    ).eval()
    # make BN stats non-trivial
    with torch.no_grad():
        tm[1].running_mean.uniform_(-1, 1)
        tm[1].running_var.uniform_(0.5, 2)
        tm[1].weight.uniform_(0.5, 1.5)
        tm[1].bias.uniform_(-0.2, 0.2)
    set_seed(1)
    ours = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(8),
        nn.ReLU(),
    ).eval_mode()
    load_torch_state_dict(ours, tm.state_dict())
    x = np.random.default_rng(2).normal(size=(2, 5, 5, 3)) \
        .astype(np.float32)
    want = tm(torch.tensor(x.transpose(0, 3, 1, 2))).detach().numpy()
    got = np.asarray(ours.forward(jnp.asarray(x))).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_torch_import_structure_mismatch_raises():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 4),
                             torch.nn.Linear(4, 4))
    ours = nn.Sequential(nn.Linear(4, 4))
    with pytest.raises(ValueError, match="structure mismatch"):
        load_torch_state_dict(ours, tm.state_dict())


def test_torch_import_with_path_map():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 4), torch.nn.ReLU(),
                             torch.nn.Linear(4, 2))
    ours = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    load_torch_state_dict(
        ours, tm.state_dict(),
        path_map={"layers[0]": "0", "layers[2]": "2"})
    x = np.random.default_rng(3).normal(size=(2, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ours.eval_mode().forward(jnp.asarray(x))),
        tm(torch.tensor(x)).detach().numpy(), rtol=1e-4, atol=1e-5)


def test_module_save_load_remote_and_file_scheme(tmp_path):
    pytest.importorskip("fsspec")
    m = nn.Linear(3, 2)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 3)),
                    jnp.float32)
    want = np.asarray(m.forward(x))
    m.save("memory://bigdl_tpu_test/model.bigdl")
    m2 = Module.load("memory://bigdl_tpu_test/model.bigdl")
    np.testing.assert_allclose(np.asarray(m2.forward(x)), want)
    # file:// URIs are local paths, not literal directories
    p = f"file://{tmp_path}/m.bigdl"
    m.save(p)
    assert (tmp_path / "m.bigdl").exists()
    m3 = Module.load(p)
    np.testing.assert_allclose(np.asarray(m3.forward(x)), want)
