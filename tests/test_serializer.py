"""Tests for model persistence + torch import (reference
utils/serializer round-trip specs + TorchFile/Caffe loader specs)."""

import numpy as np
import pytest

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module
from bigdl_tpu.utils import set_seed
from bigdl_tpu.utils.serializer import (
    save_module, load_module, save_weights, load_weights,
)
from bigdl_tpu.interop import load_torch_state_dict


def _cnn():
    set_seed(5)
    return nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(8),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2),
        nn.Reshape((8 * 4 * 4,)),
        nn.Linear(8 * 4 * 4, 10),
        nn.LogSoftMax(),
    )


def test_save_load_module_roundtrip(tmp_path):
    m = _cnn().eval_mode()
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 8, 8, 3)), jnp.float32)
    want = np.asarray(m.forward(x))
    p = str(tmp_path / "model.bigdl")
    m.save(p)
    m2 = Module.load(p)
    assert type(m2) is type(m)
    got = np.asarray(m2.eval_mode().forward(x))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_load_module_rejects_bad_version(tmp_path):
    from bigdl_tpu.utils.file import save_pytree
    p = str(tmp_path / "bad.bigdl")
    save_pytree({"__bigdl_tpu_version__": np.int64(99),
                 "module": nn.Linear(2, 2)}, p)
    with pytest.raises(ValueError, match="version"):
        Module.load(p)


def test_save_load_weights_roundtrip(tmp_path):
    m = _cnn()
    p = str(tmp_path / "weights.npz")
    m.save_weights(p)
    set_seed(99)  # different init
    m2 = _cnn.__wrapped__() if hasattr(_cnn, "__wrapped__") else _cnn()
    # force-different init: reinit under another seed
    m2.load_weights(p)
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(2, 8, 8, 3)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(m2.eval_mode().forward(x)),
        np.asarray(m.eval_mode().forward(x)), rtol=1e-6)


def test_load_weights_strict_mismatch(tmp_path):
    m = nn.Linear(4, 2)
    p = str(tmp_path / "w.npz")
    m.save_weights(p)
    other = nn.Linear(4, 3)
    with pytest.raises(Exception):
        other.load_weights(p)


def test_torch_import_linear_mlp():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(
        torch.nn.Linear(6, 16), torch.nn.ReLU(), torch.nn.Linear(16, 3))
    set_seed(0)
    ours = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 3))
    load_torch_state_dict(ours, tm.state_dict())
    x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
    want = tm(torch.tensor(x)).detach().numpy()
    got = np.asarray(ours.eval_mode().forward(jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_torch_import_cnn_with_bn():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(
        torch.nn.Conv2d(3, 8, 3, padding=1),
        torch.nn.BatchNorm2d(8),
        torch.nn.ReLU(),
    ).eval()
    # make BN stats non-trivial
    with torch.no_grad():
        tm[1].running_mean.uniform_(-1, 1)
        tm[1].running_var.uniform_(0.5, 2)
        tm[1].weight.uniform_(0.5, 1.5)
        tm[1].bias.uniform_(-0.2, 0.2)
    set_seed(1)
    ours = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(8),
        nn.ReLU(),
    ).eval_mode()
    load_torch_state_dict(ours, tm.state_dict())
    x = np.random.default_rng(2).normal(size=(2, 5, 5, 3)) \
        .astype(np.float32)
    want = tm(torch.tensor(x.transpose(0, 3, 1, 2))).detach().numpy()
    got = np.asarray(ours.forward(jnp.asarray(x))).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_torch_import_structure_mismatch_raises():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 4),
                             torch.nn.Linear(4, 4))
    ours = nn.Sequential(nn.Linear(4, 4))
    with pytest.raises(ValueError, match="structure mismatch"):
        load_torch_state_dict(ours, tm.state_dict())


def test_torch_import_with_path_map():
    torch = pytest.importorskip("torch")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 4), torch.nn.ReLU(),
                             torch.nn.Linear(4, 2))
    ours = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    load_torch_state_dict(
        ours, tm.state_dict(),
        path_map={"layers[0]": "0", "layers[2]": "2"})
    x = np.random.default_rng(3).normal(size=(2, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ours.eval_mode().forward(jnp.asarray(x))),
        tm(torch.tensor(x)).detach().numpy(), rtol=1e-4, atol=1e-5)
